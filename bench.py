"""Headline benchmark: ops verified/sec on a single-register history.

North star (BASELINE.json): verify a 10k-op single-register r/w/cas history
where the reference's CPU knossos search times out at 1 h — i.e. a baseline
of 10_000 ops / 3600 s ≈ 2.78 ops/s. We run the WGL-style
just-in-time-linearization scan (jepsen_tpu.ops.jitlin) on whatever
accelerator is attached (real TPU chip under the driver; CPU otherwise),
timing the verification after one warm-up compile at the same shapes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time

N_OPS = 10_000
N_PROCS = 5
CAPACITY = 256
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # reference CPU knossos: 1 h timeout


def main() -> None:
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops, pad_streams
    from jepsen_tpu.ops.jitlin import JitLinKernel, _bucket, verdict

    import jax

    history = _register_history(N_OPS, n_procs=N_PROCS, seed=42)
    stream = encode_register_ops(history)
    batch = pad_streams([stream], length=_bucket(len(stream)))
    S = max(1, batch["n_slots"])
    # production kernel selection: the exact dense-table scan when the
    # 2^S x V configuration space is small, else the capacity-K frontier
    run = JitLinKernel()._get(S, CAPACITY, batched=False,
                              num_states=len(stream.intern))
    args = tuple(jax.numpy.asarray(batch[k][0])
                 for k in ("kind", "slot", "f", "a", "b"))

    # Warm-up: compile at these shapes (cached thereafter, as in production
    # where shape bucketing keeps the jit cache hot).
    out = run(*args)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    alive, died, ovf, peak = run(*args)
    jax.block_until_ready((alive, died, ovf, peak))
    dt = time.perf_counter() - t0

    assert verdict(bool(alive), bool(ovf)) is True, (
        f"10k-op valid history must verify (died at event {int(died)}, "
        f"overflow={bool(ovf)})")

    ops_per_sec = N_OPS / dt
    print(json.dumps({
        "metric": "single_register_ops_verified_per_sec_10k",
        "value": round(ops_per_sec, 2),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / BASELINE_OPS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
