"""Benchmark matrix: every BASELINE.json config, one JSON line each.

BASELINE.json publishes five configs plus a scaling metric ("max history
length checked <300s"); the reference's only hard in-repo perf anchor is
the >20k ops/sec generator-scheduling figure
(jepsen/src/jepsen/generator.clj:67-70).  Each config below prints one
compact JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.
All lines are buffered and emitted together at the very end, with the
round-1 headline metric LAST (the driver parses the final line):

  1. cpu_ref_200op          — 200-op single-register history, CPU oracle
                              (the knossos :linear analog; the anchor the
                              device configs are measured against).
  2. interpreter_sched      — pure generator+interpreter scheduling loop,
                              vs the reference's >20k ops/s anchor.
  3. multikey_64x1k         — 64 independent keys x 1k ops, vmapped
                              per-key on device (BASELINE config 3).
  4. set_full_matrix        — set-full membership-matrix kernel vs the
                              CPU per-element walk (BASELINE config 4).
  5. elle_50k_txns          — 50k-txn list-append dependency check, device
                              SCC trim vs CPU trim (BASELINE config 5).
  6. matrix_kernel_128k     — block-composed transfer-matrix kernel on a
                              small-value-domain 128k-event history vs the
                              event-by-event dense scan on device.
  7. max_history_len_300s   — largest single history verified on device
                              within the 300 s budget (north-star scaling
                              metric; run length capped by
                              BENCH_SCALE_TARGET_S, default 240).
  8. single_register_ops_verified_per_sec_10k — the round-1 headline:
                              10k-op history vs the reference's 1 h CPU
                              knossos timeout (BASELINE config 2).

Environment knobs: BENCH_SCALE_TARGET_S (seconds of device time the
scaling run aims to fill; 0 skips config 7), BENCH_SKIP (comma-separated
stage keys to skip: cpu_ref, interpreter_sched, multikey, set_full,
elle_50k, matrix_kernel, headline, scale).
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

N_OPS = 10_000
N_PROCS = 5
CAPACITY = 256
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # reference CPU knossos: 1 h timeout
GEN_SCHED_BASELINE = 20_000.0          # generator.clj:67-70

_RESULTS: list[dict] = []


def emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(float(value), 2), "unit": unit,
            "vs_baseline": round(float(vs_baseline), 2)}
    line.update(extra)
    _RESULTS.append(line)
    print(f"[bench] {metric}: {line['value']} {unit} "
          f"(vs_baseline {line['vs_baseline']})", file=sys.stderr, flush=True)


def _block_stream(n_blocks: int, n_procs: int = N_PROCS, n_values: int = 100):
    """Vectorized valid single-register event stream: block t = P invokes
    (proc 0 writes w_t = t mod V; procs 1..P-1 read w_{t-1}) then P
    returns. Reads linearize before the concurrent write, so the history
    is linearizable by construction. O(E) numpy, no Python per-op loop —
    this is what makes multi-million-event scaling runs generatable."""
    from jepsen_tpu.checker.linear_encode import EventStream
    from jepsen_tpu.history import Intern
    from jepsen_tpu.models import CAS_F_READ, CAS_F_WRITE

    P, V = n_procs, n_values
    intern = Intern()
    for v in range(V):
        intern.id(v)  # ids 1..V

    t = np.arange(n_blocks, dtype=np.int64)
    w_id = (t % V).astype(np.int32) + 1              # this block's write
    r_id = np.where(t > 0, ((t - 1) % V).astype(np.int32) + 1, 0)  # read

    kind = np.tile(np.concatenate([np.zeros(P, np.int8), np.ones(P, np.int8)]),
                   n_blocks)
    slot = np.tile(np.concatenate([np.arange(P), np.arange(P)]).astype(np.int32),
                   n_blocks)
    f = np.zeros((n_blocks, 2 * P), np.int32)
    f[:, 0] = CAS_F_WRITE
    f[:, 1:P] = CAS_F_READ
    a = np.zeros((n_blocks, 2 * P), np.int32)
    a[:, 0] = w_id
    a[:, 1:P] = r_id[:, None]
    E = n_blocks * 2 * P
    return EventStream(
        kind=kind, slot=slot, f=f.reshape(-1), a=a.reshape(-1),
        b=np.zeros(E, np.int32), op_index=np.arange(E, dtype=np.int32),
        n_slots=P, n_ops=n_blocks * P, intern=intern)


def _prefix(stream, n_events: int):
    """Stream prefix: a truncated history is still a history (the cut-off
    pending invokes simply never return)."""
    from dataclasses import replace
    return replace(stream, kind=stream.kind[:n_events],
                   slot=stream.slot[:n_events], f=stream.f[:n_events],
                   a=stream.a[:n_events], b=stream.b[:n_events],
                   op_index=stream.op_index[:n_events])


def _device_args(batch):
    import jax
    return tuple(jax.numpy.asarray(batch[k][0])
                 for k in ("kind", "slot", "f", "a", "b"))


def _force(*xs):
    """Forces completion by reading results back to host. Timings must
    end with this, NOT jax.block_until_ready: on out-of-process backends
    (the tunneled TPU) block_until_ready can return before execution
    finishes, silently turning a compute measurement into a dispatch
    measurement."""
    return [np.asarray(x) for x in xs]


def _best_of(fn, n: int = 2):
    """(result, best dt) over n runs — the shared host is noisy, so all
    quick configs take the minimum for BOTH sides of any comparison."""
    dt = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        dt = min(dt, time.perf_counter() - t0)
    return out, dt


def cfg_cpu_ref_200() -> float:
    """BASELINE config 1: the CPU oracle (knossos :linear analog)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops

    history = _register_history(200, n_procs=N_PROCS, seed=1)
    stream = encode_register_ops(history)
    check_stream(stream)  # warm interpreter caches
    res, dt = _best_of(lambda: check_stream(stream))
    assert res.valid is True
    rate = 200 / dt
    # this IS the CPU reference anchor the device configs compare against
    emit("cpu_ref_200op_ops_per_sec", rate, "ops/s", 1.0)
    return rate


def cfg_interpreter_sched():
    """Reference anchor: >20k ops/sec pure-generator scheduling
    (generator.clj:67-70)."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.generator.simulate import quick

    n = 50_000
    test = {"concurrency": 5}
    history, dt = _best_of(lambda: quick(
        test, gen.limit(n, gen.Fn(lambda: {"f": "write", "value": 1}))))
    n_inv = sum(1 for op in history if op["type"] == "invoke")
    assert n_inv == n, n_inv
    rate = n / dt
    emit("interpreter_sched_ops_per_sec", rate, "ops/s",
         rate / GEN_SCHED_BASELINE)


def cfg_multikey():
    """BASELINE config 3: 64 keys x 1k ops, vmapped per-key. Values are
    drawn from a 5-value domain like the reference's linearizable-register
    workload (``(rand-int 5)``); the measured baseline is the CPU oracle
    checking the same 64 keys sequentially (the host execution model)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.parallel import batch_check

    streams = [encode_register_ops(
        _register_history(1000, n_procs=N_PROCS, seed=1000 + k, n_values=5))
        for k in range(64)]
    batch_check(streams, capacity=CAPACITY)  # warm-up compile
    results, dt = _best_of(lambda: batch_check(streams, capacity=CAPACITY))
    assert all(r[0] and not r[2] for r in results)

    def cpu_all():
        for s in streams:
            assert check_stream(s).valid is True
    _, dt_cpu = _best_of(cpu_all)
    rate = 64_000 / dt
    emit("multikey_64x1k_ops_per_sec", rate, "ops/s", dt_cpu / dt,
         cpu_sequential_ops_per_sec=round(64_000 / dt_cpu, 2))


def cfg_set_full():
    """BASELINE config 4: membership-matrix kernel vs CPU walk."""
    from jepsen_tpu.checker import SetFullChecker

    n_els, read_every = 20_000, 50
    history, present = [], []
    t = 0
    for v in range(n_els):
        history.append({"type": "invoke", "process": v % 5, "f": "add",
                        "value": v, "time": t})
        history.append({"type": "ok", "process": v % 5, "f": "add",
                       "value": v, "time": t + 1})
        present.append(v)
        t += 2
        if (v + 1) % read_every == 0:
            history.append({"type": "invoke", "process": 5, "f": "read",
                            "value": None, "time": t})
            history.append({"type": "ok", "process": 5, "f": "read",
                            "value": list(present), "time": t + 1})
            t += 2
    test, opts = {}, {}
    dev = SetFullChecker(accelerator="tpu")
    cpu = SetFullChecker(accelerator="cpu")
    dev.check(test, history, opts)  # warm-up compile
    r_dev, dt_dev = _best_of(lambda: dev.check(test, history, opts))
    r_cpu, dt_cpu = _best_of(lambda: cpu.check(test, history, opts))
    assert r_dev["valid?"] and r_cpu["valid?"]
    assert r_dev["stable-count"] == r_cpu["stable-count"]
    emit("set_full_elements_per_sec", n_els / dt_dev, "elements/s",
         dt_cpu / dt_dev, cpu_elements_per_sec=round(n_els / dt_cpu, 2))


def _elle_history(n_txns: int, n_keys: int = 100, crossed_pairs: int = 0):
    """Serializable list-append history; ``crossed_pairs`` appends pairs
    of mutually-observing txns (wr edges both ways → G1c 2-cycles), which
    defeats the acyclicity screen and forces the trim + cycle search."""
    history = []
    t = 0

    def txn(proc, mops_inv, mops_ok):
        nonlocal t
        history.append({"type": "invoke", "process": proc,
                        "value": mops_inv, "time": t})
        history.append({"type": "ok", "process": proc,
                        "value": mops_ok, "time": t + 1})
        t += 2

    for i in range(n_txns):
        k = i % n_keys
        seen = list(range(k, i + 1, n_keys))  # every append to k so far
        txn(i % 10, [["append", k, i], ["r", k, None]],
            [["append", k, i], ["r", k, seen]])
    for p in range(crossed_pairs):
        ka, kb = 10_000 + 2 * p, 10_001 + 2 * p
        va, vb = 2_000_000 + 2 * p, 2_000_001 + 2 * p
        # A observes B's append before B commits; B observes A's: a wr
        # cycle between the two on fresh keys
        txn(10, [["append", ka, va], ["r", kb, None]],
            [["append", ka, va], ["r", kb, [vb]]])
        txn(11, [["append", kb, vb], ["r", ka, None]],
            [["append", kb, vb], ["r", ka, [va]]])
    return history


def cfg_elle_50k():
    """BASELINE config 5: 50k-txn list-append check. Two regimes: a
    serializable history (settled by the vectorized acyclicity screen —
    the production fast path) and an anomalous one with 50 injected wr
    cycles (forces the SCC trim + exact cycle search on both backends)."""
    from jepsen_tpu.elle import list_append

    n_txns = 50_000
    history = _elle_history(n_txns)
    # warm caches on a tail WITH the same anomaly count so the φ-cluster
    # screen kernel compiles at the anomalous run's exact bucket shapes
    # (the valid tail alone never reaches it: no back edges, no clusters)
    warm = _elle_history(2_000, crossed_pairs=50)
    list_append.check(warm, accelerator="tpu")
    t0 = time.perf_counter()
    r_cpu = list_append.check(history, accelerator="cpu")
    dt_cpu = time.perf_counter() - t0
    t0 = time.perf_counter()
    r_dev = list_append.check(history, accelerator="tpu")
    dt_dev = time.perf_counter() - t0
    assert r_dev["valid?"] is True and r_cpu["valid?"] is True
    emit("elle_50k_txns_per_sec", n_txns / dt_dev, "txns/s",
         dt_cpu / dt_dev, cpu_txns_per_sec=round(n_txns / dt_cpu, 2))

    bad = _elle_history(n_txns, crossed_pairs=50)
    n_bad = n_txns + 100
    t0 = time.perf_counter()
    r_cpu = list_append.check(bad, accelerator="cpu")
    dt_cpu = time.perf_counter() - t0
    t0 = time.perf_counter()
    r_dev = list_append.check(bad, accelerator="tpu")
    dt_dev = time.perf_counter() - t0
    assert r_dev["valid?"] is False and r_cpu["valid?"] is False
    assert "G1c" in r_dev["anomaly-types"], r_dev.get("anomaly-types")
    emit("elle_50k_anomalous_txns_per_sec", n_bad / dt_dev, "txns/s",
         dt_cpu / dt_dev, cpu_txns_per_sec=round(n_bad / dt_cpu, 2))


def cfg_matrix_kernel():
    """Block-composed transfer-matrix kernel on its home regime — long
    history, small value domain — vs the event-by-event dense scan."""
    import jax
    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import (
        JitLinKernel, _bucket, matrix_check, matrix_ok)

    stream = _block_stream(12_800, n_values=4)   # 128k events, V=5
    E = len(stream)
    S, V = stream.n_slots, len(stream.intern)
    n_returns = int((np.asarray(stream.kind) == 1).sum())
    assert matrix_ok(S, V, n_returns), "bench config must be in-regime"

    m = matrix_check(stream)                      # warm-up compile
    assert m is not None and m[0] and not m[2], m
    t0 = time.perf_counter()
    m = matrix_check(stream)
    dt_matrix = time.perf_counter() - t0

    batch = pad_streams([stream], length=_bucket(E))
    run = JitLinKernel()._get(S, CAPACITY, batched=False, num_states=V)
    args = _device_args(batch)
    _force(*run(*args))                           # warm-up compile
    t0 = time.perf_counter()
    alive, _, ovf, _ = _force(*run(*args))
    dt_scan = time.perf_counter() - t0
    assert bool(alive) and not bool(ovf)
    assert bool(m[0]) == bool(alive), "matrix and scan verdicts must agree"
    extra = {"scan_events_per_sec": round(E / dt_scan, 2)}

    # failing-history double run: a not-alive matrix verdict falls back to
    # the event scan for diagnostics — measure that total so the cost of
    # the two-pass failure path is on record (VERDICT r1 weak #7). Run
    # guarded AFTER the primary measurement exists, so a failure here
    # can't discard it.
    try:
        from dataclasses import replace
        t = (E // (2 * N_PROCS)) // 2
        a_bad = stream.a.copy()
        e_corrupt = t * 2 * N_PROCS + 1     # block t, proc 1's read invoke
        a_bad[e_corrupt] = (t + 1) % 4 + 1  # neither w_{t-1} nor w_t
        bad = replace(stream, a=a_bad)
        t0 = time.perf_counter()
        mb = matrix_check(bad)
        assert mb is not None and not mb[0]
        batch_bad = pad_streams([bad], length=_bucket(E))
        alive_b, _, _, _ = _force(*run(*_device_args(batch_bad)))
        dt_fail = time.perf_counter() - t0
        assert not bool(alive_b)
        extra["failing_double_run_seconds"] = round(dt_fail, 3)
    except Exception:
        print("[bench] failing-path add-on failed:", file=sys.stderr)
        traceback.print_exc()
    emit("matrix_kernel_128k_events_per_sec", E / dt_matrix, "events/s",
         dt_scan / dt_matrix, **extra)


def cfg_scale(device_rate: float):
    """North-star scaling metric: the largest single history verified on
    device inside the 300 s budget. Predicts a length that fills
    BENCH_SCALE_TARGET_S seconds at the measured headline rate, AOT-
    compiles (no throwaway warm-up execution at this size), runs once, and
    reports the verified length. Halves once if the run overshoots 300 s."""
    import jax
    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import JitLinKernel, _bucket

    target_s = float(os.environ.get("BENCH_SCALE_TARGET_S", "240"))
    if target_s <= 0:
        return
    # hard cap: 8M+-event scans have crashed the tunneled TPU worker
    # process ("TPU worker process crashed or restarted"); 4.19M is the
    # largest size proven stable on this backend
    E_CAP = 4_200_000
    e_target = min(device_rate * target_s, E_CAP)
    E = _bucket(int(e_target)) // 2 or 64          # largest bucket <= target
    n_values = 100
    stream = _block_stream(E // (2 * N_PROCS), n_values=n_values)
    E = len(stream)

    def run_once(stream):
        batch = pad_streams([stream], length=_bucket(len(stream)))
        run = JitLinKernel()._get(stream.n_slots, CAPACITY, batched=False,
                                  num_states=n_values + 1)
        args = _device_args(batch)
        compiled = run.lower(*args).compile()      # AOT: compile w/o running
        t0 = time.perf_counter()
        alive, _, ovf, _ = _force(*compiled(*args))
        dt = time.perf_counter() - t0
        assert bool(alive) and not bool(ovf)
        return dt

    dt = run_once(stream)
    if dt >= 300.0:
        E //= 2
        stream = _prefix(stream, E)
        dt = run_once(stream)
    # the headline rate underestimates long-run throughput (fixed
    # overheads amortize), so grow while a doubling is predicted to fit
    # the budget with margin; always keep the best verified result, even
    # if a larger attempt dies
    best = (E, dt) if dt < 300.0 else None
    try:
        while dt < 100.0 and 2 * E <= E_CAP:
            E *= 2
            stream = _block_stream(E // (2 * N_PROCS), n_values=n_values)
            E = len(stream)
            dt = run_once(stream)
            if dt < 300.0:
                best = (E, dt)
    except Exception:
        print(f"[bench] scale doubling failed at E={E}; keeping best",
              file=sys.stderr)
        traceback.print_exc()
    if best is not None:
        emit("max_history_len_checked_300s", best[0], "events",
             best[0] / N_OPS, measured_seconds=round(best[1], 1),
             note="largest length run; rate extrapolates higher")
    else:
        print(f"[bench] scale run over budget at E={E}: {dt:.0f}s",
              file=sys.stderr)


def cfg_headline() -> float:
    """Round-1 headline, printed last: 10k-op single-register history on
    device vs the reference's 1 h CPU knossos timeout. Returns the
    measured device event rate (drives the scale config)."""
    import jax
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops, pad_streams
    from jepsen_tpu.ops.jitlin import JitLinKernel, _bucket, verdict

    history = _register_history(N_OPS, n_procs=N_PROCS, seed=42)
    stream = encode_register_ops(history)
    batch = pad_streams([stream], length=_bucket(len(stream)))
    S = max(1, batch["n_slots"])
    run = JitLinKernel()._get(S, CAPACITY, batched=False,
                              num_states=len(stream.intern))
    args = _device_args(batch)
    _force(*run(*args))                           # warm-up compile

    t0 = time.perf_counter()
    alive, died, ovf, peak = _force(*run(*args))
    dt = time.perf_counter() - t0
    assert verdict(bool(alive), bool(ovf)) is True, (
        f"10k-op valid history must verify (died at event {int(died)}, "
        f"overflow={bool(ovf)})")
    ops_per_sec = N_OPS / dt
    emit("single_register_ops_verified_per_sec_10k", ops_per_sec, "ops/s",
         ops_per_sec / BASELINE_OPS_PER_SEC)
    return len(stream) / dt


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    skip = set(filter(None, os.environ.get("BENCH_SKIP", "").split(",")))
    device_rate = 50_000.0  # headline's event rate sizes the scaling run

    def guard(name, fn):
        if name in skip:
            return None
        try:
            return fn()
        except Exception:
            print(f"[bench] {name} FAILED:", file=sys.stderr)
            traceback.print_exc()
            return None

    guard("cpu_ref", cfg_cpu_ref_200)
    guard("interpreter_sched", cfg_interpreter_sched)
    guard("multikey", cfg_multikey)
    guard("set_full", cfg_set_full)
    guard("elle_50k", cfg_elle_50k)
    guard("matrix_kernel", cfg_matrix_kernel)
    device_rate = guard("headline", cfg_headline) or device_rate
    guard("scale", lambda: cfg_scale(device_rate))

    # all lines together at the end (driver tails stdout); headline last
    headline = "single_register_ops_verified_per_sec_10k"
    for line in ([r for r in _RESULTS if r["metric"] != headline]
                 + [r for r in _RESULTS if r["metric"] == headline]):
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
