"""Benchmark matrix: every BASELINE.json config, one JSON line each.

BASELINE.json publishes five configs plus a scaling metric ("max history
length checked <300s"); the reference's only hard in-repo perf anchor is
the >20k ops/sec generator-scheduling figure
(jepsen/src/jepsen/generator.clj:67-70).  Each config below prints one
compact JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.
All lines are buffered and emitted together at the very end, with the
round-1 headline metric LAST (the driver parses the final line) and a
compact ``bench_summary`` line (every metric's value+ratio) right
before it, so the driver's 2000-char stdout tail always recovers every
metric:

  1. cpu_ref_200op          — 200-op single-register history, CPU oracle
                              (the knossos :linear analog; the anchor the
                              device configs are measured against).
  2. interpreter_sched      — pure generator+interpreter scheduling loop,
                              vs the reference's >20k ops/s anchor.
  3. multikey_64x1k         — 64 independent keys x 1k ops, vmapped
                              per-key on device (BASELINE config 3).
  4. set_full_matrix        — set-full membership-matrix kernel vs the
                              CPU per-element walk (BASELINE config 4).
  5. elle_50k_txns          — 50k-txn list-append dependency check, device
                              SCC trim vs CPU trim (BASELINE config 5).
  6. matrix_kernel_128k     — block-composed transfer-matrix kernel on a
                              small-value-domain 128k-event history vs the
                              event-by-event dense scan on device; carries
                              per-phase attribution (phase_*_s measured
                              host/device split + modeled_*_frac analytic
                              FLOP shares — doc/performance.md).
  7. max_history_len_300s   — largest single history verified on device
                              within the 300 s budget (north-star scaling
                              metric; run length capped by
                              BENCH_SCALE_TARGET_S, default 240).
  8. single_register_ops_verified_per_sec_10k — the round-1 headline:
                              10k-op history vs the reference's 1 h CPU
                              knossos timeout (BASELINE config 2).

Environment knobs: BENCH_SCALE_TARGET_S (seconds of device time the
scaling run aims to fill; 0 skips config 7), BENCH_SKIP (comma-separated
stage keys to skip: cpu_ref, interpreter_sched, wal_ingest, multikey,
set_full, elle_50k, ir_amortization, online_lag, matrix_kernel, explain,
multichip, ckpt, trace, fleet, headline, scale, telemetry — the last
opts out of the per-stage telemetry block in bench_summary).
``fleet`` measures the fleet plane end to end (fleet_runs_sustained:
100 concurrent runs shipped over loopback HTTP into one pool daemon,
one mesh shrink + regrow cycle injected, verdicts checked bit-identical
to local analyze — doc/observability.md "Fleet plane"). ``trace`` measures
the causal-trace cost (trace_overhead_frac: fully-traced vs untraced
interpreter wall, bar <= 5%, with the always-on flight-recorder
configuration <= 1% — doc/observability.md "Causal trace").
``ckpt`` measures the
resumable-check cost/benefit (ckpt_overhead_frac bar <= 5%, plus
resume_savings_frac at a 50% cut — doc/robustness.md "Resumable checks
and the elastic mesh"). ``ir_amortization``
measures the history-IR encode-once contract: a two-checker run over
one 50k-op history reports the first encode's wall vs the second
checker's encode phase (target ~= 0 — views are memoized on the shared
IR; doc/performance.md "History IR"). ``explain`` tracks anomaly-forensics cost
(explain_latency_128k: localize + shrink a planted anomaly; the bar is
< 2× the plain check wall — doc/observability.md "Anomaly forensics").
"""
from __future__ import annotations

import json
import os
import re
import sys
import time
import traceback

import numpy as np

from jepsen_tpu import telemetry

N_OPS = 10_000
N_PROCS = 5
CAPACITY = 256
BASELINE_OPS_PER_SEC = N_OPS / 3600.0  # reference CPU knossos: 1 h timeout
GEN_SCHED_BASELINE = 20_000.0          # generator.clj:67-70

_RESULTS: list[dict] = []

# Per-stage telemetry folded into the bench_summary line (BENCH_SKIP key
# "telemetry" opts out): compile_s (the timed warm-up call — JIT compile
# plus one execute), wall_s (whole stage), device_peak_mb (allocator
# high-water AFTER the stage; monotone across stages, so per-stage
# high-water reads as the running max). The execute side of the
# compile/execute split is each metric's median trial time, already in
# the metric lines.
_STAGE_TELEMETRY: dict = {}
_TELEMETRY_ON = True


def _stage_note(stage: str, **kv):
    if _TELEMETRY_ON:
        _STAGE_TELEMETRY.setdefault(stage, {}).update(kv)


def _warm_timed(stage: str, fn):
    """Runs a warm-up (compile) call, recording its wall time as the
    stage's compile_s via the telemetry block."""
    t0 = time.perf_counter()
    out = fn()
    _stage_note(stage, compile_s=round(time.perf_counter() - t0, 3))
    return out


def emit(metric: str, value: float, unit: str, vs_baseline: float, **extra):
    line = {"metric": metric, "value": round(float(value), 2), "unit": unit,
            "vs_baseline": round(float(vs_baseline), 2)}
    line.update(extra)
    _RESULTS.append(line)
    print(f"[bench] {metric}: {line['value']} {unit} "
          f"(vs_baseline {line['vs_baseline']})", file=sys.stderr, flush=True)


def _block_stream(n_blocks: int, n_procs: int = N_PROCS, n_values: int = 100,
                  start_block: int = 0):
    """Vectorized valid single-register event stream: block t = P invokes
    (proc 0 writes w_t = t mod V; procs 1..P-1 read w_{t-1}) then P
    returns. Reads linearize before the concurrent write, so the history
    is linearizable by construction. O(E) numpy, no Python per-op loop —
    this is what makes multi-million-event scaling runs generatable.

    ``start_block`` continues a longer logical history: block numbering
    (and so the read/write value sequence) picks up at that offset, so
    consecutive segments chain correctly through the carried frontier."""
    from jepsen_tpu.checker.linear_encode import EventStream
    from jepsen_tpu.history import Intern
    from jepsen_tpu.models import CAS_F_READ, CAS_F_WRITE

    P, V = n_procs, n_values
    intern = Intern()
    for v in range(V):
        intern.id(v)  # ids 1..V

    t = np.arange(start_block, start_block + n_blocks, dtype=np.int64)
    w_id = (t % V).astype(np.int32) + 1              # this block's write
    r_id = np.where(t > 0, ((t - 1) % V).astype(np.int32) + 1, 0)  # read

    kind = np.tile(np.concatenate([np.zeros(P, np.int8), np.ones(P, np.int8)]),
                   n_blocks)
    slot = np.tile(np.concatenate([np.arange(P), np.arange(P)]).astype(np.int32),
                   n_blocks)
    f = np.zeros((n_blocks, 2 * P), np.int32)
    f[:, 0] = CAS_F_WRITE
    f[:, 1:P] = CAS_F_READ
    a = np.zeros((n_blocks, 2 * P), np.int32)
    a[:, 0] = w_id
    a[:, 1:P] = r_id[:, None]
    E = n_blocks * 2 * P
    return EventStream(
        kind=kind, slot=slot, f=f.reshape(-1), a=a.reshape(-1),
        b=np.zeros(E, np.int32), op_index=np.arange(E, dtype=np.int32),
        n_slots=P, n_ops=n_blocks * P, intern=intern)


def _prefix(stream, n_events: int):
    """Stream prefix: a truncated history is still a history (the cut-off
    pending invokes simply never return)."""
    from dataclasses import replace
    return replace(stream, kind=stream.kind[:n_events],
                   slot=stream.slot[:n_events], f=stream.f[:n_events],
                   a=stream.a[:n_events], b=stream.b[:n_events],
                   op_index=stream.op_index[:n_events])


def _device_args(batch):
    import jax
    return tuple(jax.numpy.asarray(batch[k][0])
                 for k in ("kind", "slot", "f", "a", "b"))


def _force(*xs):
    """Forces completion by reading results back to host in ONE batched
    transfer. Timings must end with this, NOT jax.block_until_ready: on
    out-of-process backends (the tunneled TPU) block_until_ready can
    return before execution finishes, silently turning a compute
    measurement into a dispatch measurement. One call, not one per
    array — each host readback is a full tunnel round-trip (~100 ms)."""
    import jax

    return list(jax.device_get(xs))


def _best_of(fn, n: int = 2):
    """(result, best dt) over n runs — the shared host is noisy, so all
    quick configs take the minimum for BOTH sides of any comparison."""
    out, times = _trials(fn, n)
    return out, min(times)


def _trials(fn, n: int = 5):
    """(result, [dt...]) over n runs. Metrics report the MEDIAN with
    min/max spread (VERDICT r2: single-shot numbers made regressions and
    measurement fixes indistinguishable on this noisy shared host; r4
    widened 3 -> 5 trials after clean-run medians still swung 40%)."""
    times = []
    out = None
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        times.append(time.perf_counter() - t0)
    return out, times


_ROOFLINE: dict = {}


def device_roofline() -> dict:
    """Measured single-chip peaks used as denominators for the
    hardware-efficiency fractions (VERDICT r4 #7: every ratio was
    vs-CPU; nothing said what fraction of the chip the kernels use).
    Empirical, not datasheet: best-of-3 large square matmuls (f32 and
    bf16) and a large elementwise add for HBM read+write bandwidth."""
    if _ROOFLINE:
        return _ROOFLINE
    import jax
    import jax.numpy as jnp
    from jax import lax

    # chain enough work inside ONE dispatch that the tunnel's ~100 ms
    # round-trip amortizes away — a single 4096 matmul finishes in
    # microseconds of device time and would measure the tunnel instead
    measured: dict = {}   # publish all-or-nothing: a partial cache
    #                       would silently drop fractions forever
    n, reps = 4096, 32
    for dt, key in ((jnp.float32, "f32_matmul_flops"),
                    (jnp.bfloat16, "bf16_matmul_flops")):
        a = jnp.eye(n, dtype=dt) * 0.5

        @jax.jit
        def chain(x, a=a):
            return lax.fori_loop(0, reps, lambda i, y: y @ a, x)

        chain(a).block_until_ready()
        _, ts = _trials(lambda: chain(a).block_until_ready(), 3)
        measured[key] = reps * 2.0 * n ** 3 / min(ts)
    # publish the measured peak so runtime roofline gauges (checker
    # telemetry) share bench's denominator
    telemetry.set_device_peak_flops(measured["f32_matmul_flops"])
    big = jnp.ones((64 * 1024 * 1024,), jnp.float32)   # 256 MB
    bw_reps = 64

    @jax.jit
    def adds(x):
        return lax.fori_loop(0, bw_reps, lambda i, y: y + 1.0, x)

    adds(big).block_until_ready()
    _, ts = _trials(lambda: adds(big).block_until_ready(), 3)
    measured["hbm_bytes_per_sec"] = bw_reps * 2.0 * big.size * 4 / min(ts)
    _ROOFLINE.update(measured)
    return _ROOFLINE


def matrix_roofline_extras(n_returns: int, S: int, V: int,
                           seconds: float) -> dict:
    """Roofline accounting for the transfer-matrix kernels: each return
    composes one [MV, MV] operator via ~(ceil(log2 S) + 2) dense f32
    matmuls (closure squarings + K-apply + P-update; the elementwise L
    build is excluded, so this is a LOWER bound on issued FLOPs).
    ``roofline_frac`` = modeled achieved FLOP/s over the measured f32
    matmul peak — small matrices (MV ~ 2^S·V) under-tile the MXU, which
    is exactly what this fraction is here to make visible."""
    flops_per_return = telemetry.matrix_modeled_flops(1, S, V)
    achieved = telemetry.matrix_modeled_flops(n_returns, S, V) / seconds
    peak = device_roofline()["f32_matmul_flops"]
    return {
        "modeled_flops_per_return": round(flops_per_return),
        "achieved_matmul_flops": round(achieved),
        "device_f32_matmul_peak_flops": round(peak),
        "roofline_frac": round(achieved / peak, 4),
    }


def _median(ts):
    """Upper median — the one idiom shared by every bench reporter."""
    s = sorted(ts)
    return s[len(s) // 2]


def _combine_reduction(keys, chunks, mv, fused) -> float:
    """tree/fused modeled-HBM-byte ratio of the chunk combine — the
    fused combine's designed win (1.0 when the tree ran: the regression
    signal). Shared by matrix_kernel_128k and the segmented scale
    metric so the two can't silently diverge."""
    if not fused:
        return 1.0
    return round(
        telemetry.combine_modeled_hbm_bytes(keys, chunks, mv, False)
        / max(telemetry.combine_modeled_hbm_bytes(keys, chunks, mv, True),
              1), 2)


def _spread(times, scale: float):
    """Spread extras for emit(): rates at the median/min/max timings."""
    ts = sorted(times)
    med = _median(ts)
    return med, {"trials": len(ts),
                 "value_min": round(scale / ts[-1], 2),
                 "value_max": round(scale / ts[0], 2)}


def cfg_cpu_ref_200() -> float:
    """BASELINE config 1: the CPU oracle (knossos :linear analog)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops

    history = _register_history(200, n_procs=N_PROCS, seed=1)
    stream = encode_register_ops(history)
    check_stream(stream)  # warm interpreter caches
    res, times = _trials(lambda: check_stream(stream), 5)
    assert res.valid is True
    med, extras = _spread(times, 200)
    rate = 200 / med
    # this IS the CPU reference anchor the device configs compare against
    emit("cpu_ref_200op_ops_per_sec", rate, "ops/s", 1.0, **extras)
    return rate


def cfg_interpreter_sched():
    """Reference anchor: >20k ops/sec pure-generator scheduling
    (generator.clj:67-70). The simulated loop rides the native
    scheduler lane (columnar_ext.c sim_lane) when probed; the
    ``sched_batch_*`` extras measure the THREADED interpreter's chunked
    completion bus (``sched_batch_ops``) against its per-op fallback —
    Tentpole B of the host ingest spine (doc/performance.md)."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.generator.interpreter import (
        DEFAULT_SCHED_BATCH_OPS, run as interp_run,
    )
    from jepsen_tpu.generator.simulate import quick

    n = 50_000
    test = {"concurrency": 5}
    history, times = _trials(lambda: quick(
        test, gen.limit(n, gen.Fn(lambda: {"f": "write", "value": 1}))), 3)
    n_inv = sum(1 for op in history if op["type"] == "invoke")
    assert n_inv == n, n_inv
    med, extras = _spread(times, n)

    class _Echo:
        def open(self, test, node):
            return self

        def setup(self, test):
            pass

        def invoke(self, test, op):
            return {**op, "type": "ok"}

        def teardown(self, test):
            pass

        def close(self, test):
            pass

    m = 10_000

    def threaded(batch):
        t = {"concurrency": 8, "client": _Echo(), "nodes": ["n1"],
             "name": "bench-sched", "sched_batch_ops": batch,
             "generator": gen.clients(gen.limit(
                 m, gen.Fn(lambda: {"f": "write", "value": 1})))}
        h = interp_run(t)
        assert sum(1 for op in h if op["type"] == "invoke") == m
        return h

    _, t_batched = _trials(lambda: threaded(DEFAULT_SCHED_BATCH_OPS), 3)
    _, t_per_op = _trials(lambda: threaded(0), 3)
    batched_rate = m / _median(t_batched)
    per_op_rate = m / _median(t_per_op)
    emit("interpreter_sched_ops_per_sec", n / med, "ops/s",
         (n / med) / GEN_SCHED_BASELINE,
         sched_batch_default=DEFAULT_SCHED_BATCH_OPS,
         sched_batch_ops_per_sec=round(batched_rate, 1),
         sched_batch_per_op_ops_per_sec=round(per_op_rate, 1),
         sched_batch_vs_per_op=round(batched_rate / per_op_rate, 3),
         **extras)


def cfg_wal_ingest():
    """wal_ingest_native: the raw WAL chunk scan+parse rate, native
    (columnar_ext.c ingest_chunk) vs the pure-Python twin over the same
    bytes — the tail side of the 1M ops/s ingest bar, isolated from
    encode+frontier (those ride online_lag)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py
    from jepsen_tpu.store import _serializable

    history = _register_history(100_000, n_procs=5, seed=3, n_values=5)
    n = len(history)  # invokes + completions
    chunk = "".join(json.dumps(_serializable(op)) + "\n"
                    for op in history).encode()

    def native():
        m = ingest.native_mod()
        assert m is not None, "native ingest unavailable"
        with ingest.ingest_burst():
            ops, consumed, torn, _tr = m.ingest_chunk(
                chunk, True, ingest._line_fallback,
                ingest._SKIP, ingest._TORN)
        assert len(ops) == n and torn == 0 and consumed == len(chunk)

    def python():
        with ingest.ingest_burst():
            ops, consumed, torn, _tr = parse_wal_chunk_py(chunk,
                                                          final=True)
        assert len(ops) == n and torn == 0 and consumed == len(chunk)

    _, t_nat = _trials(native, 5)
    _, t_py = _trials(python, 3)
    med, extras = _spread(t_nat, n)
    rate = n / med
    emit("wal_ingest_native_ops_per_sec", rate, "ops/s",
         rate / (n / _median(t_py)),  # vs_baseline IS the ratio
         python_ops_per_sec=round(n / _median(t_py), 1),
         chunk_mb=round(len(chunk) / 2 ** 20, 1), **extras)


def cfg_multikey():
    """BASELINE config 3: independent per-key registers, 1k ops each,
    batched on device. Values are drawn from a 5-value domain like the
    reference's linearizable-register workload (``(rand-int 5)``); the
    measured baseline is the CPU oracle checking the same keys
    sequentially (the host execution model).

    Emits the 64-key config (r1/r2 comparability) AND the batch-scaling
    curve at 256/1024 keys — the matrix path splits big batches into
    pipelined ≤256-key sub-dispatches, so the win opens with batch size
    (VERDICT r2 item 2). The CPU side is measured DIRECTLY at every
    batch size (r3 weak #3 closed: no linear extrapolation; big sizes
    take fewer trials to bound the added wall time)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.parallel import batch_check

    all_streams = [encode_register_ops(
        _register_history(1000, n_procs=N_PROCS, seed=1000 + k, n_values=5))
        for k in range(1024)]

    def cpu_n(n):
        for s in all_streams[:n]:
            assert check_stream(s).valid is True

    from jepsen_tpu.parallel import pipeline

    for nk, main, cpu_trials in ((64, True, 3), (256, False, 2),
                                 (1024, False, 2)):
        streams = all_streams[:nk]
        _, cpu_times = _trials(lambda: cpu_n(nk), cpu_trials)
        dt_cpu = min(cpu_times)  # noisy host: best run is the fair anchor
        _warm_timed(f"multikey_{nk}x1k",            # warm-up compile
                    lambda: batch_check(streams, capacity=CAPACITY))
        results, times = _trials(
            lambda: batch_check(streams, capacity=CAPACITY), 3)
        assert all(r[0] and not r[2] for r in results)
        med, extras = _spread(times, nk * 1000)
        name = ("multikey_64x1k_ops_per_sec" if main
                else f"multikey_{nk}x1k_ops_per_sec")
        try:
            n_rets = sum(int((np.asarray(s.kind) == 1).sum())
                         for s in streams)
            extras.update(matrix_roofline_extras(
                n_rets, streams[0].n_slots, len(streams[0].intern), med))
        except Exception:
            print("[bench] roofline add-on failed:", file=sys.stderr)
            traceback.print_exc()
        # dispatch-pipeline occupancy (the overlap evidence for the
        # small-batch fix): stats of the last trial's pipeline
        ps = pipeline.last_stats()
        if ps.get("queue") == "matrix":
            extras.update(
                pipeline_batches=ps["batches"],
                pipeline_inflight_peak=ps["inflight_peak"],
                pipeline_overlap_frac=ps["overlap_frac"],
                pipeline_stall_s=ps["stall_s"])
            # ... and into the bench_summary telemetry block, so the
            # occupancy evidence survives the driver's stdout tail
            _stage_note(f"multikey_{nk}x1k",
                        pipeline={k: ps[k] for k in
                                  ("batches", "inflight_peak",
                                   "overlap_frac", "stall_s", "sync_s")})
        emit(name, nk * 1000 / med, "ops/s", dt_cpu / med,
             cpu_sequential_ops_per_sec=round(nk * 1000 / dt_cpu, 2),
             cpu_trials=cpu_trials, **extras)


def cfg_set_full():
    """BASELINE config 4: membership-matrix kernel vs CPU walk."""
    from jepsen_tpu.checker import SetFullChecker

    n_els, read_every = 20_000, 50
    history, present = [], []
    t = 0
    for v in range(n_els):
        history.append({"type": "invoke", "process": v % 5, "f": "add",
                        "value": v, "time": t})
        history.append({"type": "ok", "process": v % 5, "f": "add",
                       "value": v, "time": t + 1})
        present.append(v)
        t += 2
        if (v + 1) % read_every == 0:
            history.append({"type": "invoke", "process": 5, "f": "read",
                            "value": None, "time": t})
            history.append({"type": "ok", "process": 5, "f": "read",
                            "value": list(present), "time": t + 1})
            t += 2
    test, opts = {}, {}
    dev = SetFullChecker(accelerator="tpu")
    cpu = SetFullChecker(accelerator="cpu")
    _warm_timed("set_full", lambda: dev.check(test, history, opts))
    # per-trial kernel-only time (setscan.last_kernel_seconds): the
    # hbm_frac roofline divides bytes moved by DEVICE time, not the
    # whole stage (which is mostly host history parse)
    from jepsen_tpu.ops import setscan
    kernel_times: list[float] = []

    def dev_phased():
        out = dev.check(test, history, opts)
        kernel_times.append(setscan.last_kernel_seconds())
        return out

    r_dev, t_dev = _trials(dev_phased, 5)
    r_cpu, t_cpu = _trials(lambda: cpu.check(test, history, opts), 5)
    assert r_dev["valid?"] and r_cpu["valid?"]
    assert r_dev["stable-count"] == r_cpu["stable-count"]
    med, extras = _spread(t_dev, n_els)
    cpu_med, _ = _spread(t_cpu, n_els)
    try:
        n_reads = n_els // read_every
        mb = setscan.modeled_bytes(n_reads, n_els)
        k_med = _median(kernel_times)
        bw = device_roofline()["hbm_bytes_per_sec"]
        extras.update(
            modeled_hbm_bytes=mb,
            kernel_seconds=round(k_med, 4),
            hbm_frac=round((mb / max(k_med, 1e-9)) / bw, 4))
    except Exception:
        print("[bench] set-full roofline add-on failed:", file=sys.stderr)
        traceback.print_exc()
    emit("set_full_elements_per_sec", n_els / med, "elements/s",
         cpu_med / med, cpu_elements_per_sec=round(n_els / cpu_med, 2),
         **extras)


def _elle_history(n_txns: int, n_keys: int = 100, crossed_pairs: int = 0):
    """Serializable list-append history; ``crossed_pairs`` appends pairs
    of mutually-observing txns (wr edges both ways → G1c 2-cycles), which
    defeats the acyclicity screen and forces the trim + cycle search."""
    history = []
    t = 0

    def txn(proc, mops_inv, mops_ok):
        nonlocal t
        history.append({"type": "invoke", "process": proc,
                        "value": mops_inv, "time": t})
        history.append({"type": "ok", "process": proc,
                        "value": mops_ok, "time": t + 1})
        t += 2

    for i in range(n_txns):
        k = i % n_keys
        seen = list(range(k, i + 1, n_keys))  # every append to k so far
        txn(i % 10, [["append", k, i], ["r", k, None]],
            [["append", k, i], ["r", k, seen]])
    for p in range(crossed_pairs):
        ka, kb = 10_000 + 2 * p, 10_001 + 2 * p
        va, vb = 2_000_000 + 2 * p, 2_000_001 + 2 * p
        # A observes B's append before B commits; B observes A's: a wr
        # cycle between the two on fresh keys
        txn(10, [["append", ka, va], ["r", kb, None]],
            [["append", ka, va], ["r", kb, [vb]]])
        txn(11, [["append", kb, vb], ["r", ka, None]],
            [["append", kb, vb], ["r", ka, [va]]])
    return history


def cfg_elle_50k():
    """BASELINE config 5: 50k-txn list-append check. Two regimes: a
    serializable history (settled by the vectorized acyclicity screen —
    the production fast path) and an anomalous one with 50 injected wr
    cycles (forces the SCC trim + exact cycle search on both backends)."""
    from jepsen_tpu.elle import list_append

    n_txns = 50_000
    history = _elle_history(n_txns)
    # warm caches on a tail WITH the same anomaly count so the φ-cluster
    # screen kernel compiles at the anomalous run's exact bucket shapes
    # (the valid tail alone never reaches it: no back edges, no clusters)
    warm = _elle_history(2_000, crossed_pairs=50)
    _warm_timed("elle_50k", lambda: list_append.check(warm, accelerator="tpu"))
    # 5 trials: the build is host-bound (C parser + numpy tail) and this
    # shared VM's ambient noise swung 3-trial medians by 40%+ between
    # clean runs. Per-trial phase split on BOTH regimes (r4 weak #1: the
    # clean-path regression was unattributable without it) — build is
    # the host-side history parse, cycles is the device screen + search.
    from jepsen_tpu.elle import columnar
    from jepsen_tpu.native import columnar_c

    def phased(h, phases):
        def run():
            out = list_append.check(h, accelerator="tpu")
            phases.append(dict(columnar.LAST_PHASE_SECONDS))
            return out
        return run

    r_cpu, t_cpu = _trials(
        lambda: list_append.check(history, accelerator="cpu"), 5)
    clean_phases: list[dict] = []
    r_dev, t_dev = _trials(phased(history, clean_phases), 5)
    assert r_dev["valid?"] is True and r_cpu["valid?"] is True
    med, extras = _spread(t_dev, n_txns)
    cpu_med, _ = _spread(t_cpu, n_txns)
    emit("elle_50k_txns_per_sec", n_txns / med, "txns/s",
         cpu_med / med, cpu_txns_per_sec=round(n_txns / cpu_med, 2),
         trial_seconds=[round(t, 2) for t in t_dev],
         phase_build_s=[p.get("build") for p in clean_phases],
         phase_cycles_s=[p.get("cycles") for p in clean_phases],
         c_parser=columnar_c.available(),
         **extras)

    # stored-column re-check: the same verdict straight off the
    # history.npz elle_* sidecar — no jsonl, no PyObject parse (the
    # analyze/re-check path for saved runs, SURVEY §7's
    # struct-of-arrays stance carried to its conclusion)
    cols = columnar.parse_columns(history)
    if cols is not None:
        r_cols = columnar.check_columns(cols, accelerator="tpu")  # warm
        assert r_cols["valid?"] is True
        stored_phases: list[dict] = []

        def stored_run():
            out = columnar.check_columns(cols, accelerator="tpu")
            stored_phases.append(dict(columnar.LAST_PHASE_SECONDS))
            return out

        _, t_cols = _trials(stored_run, 5)
        med_c, extras_c = _spread(t_cols, n_txns)
        # phase_build_s reduction: the object path's host build vs the
        # stored/IR array path's — the 7:1 build-dominance trend
        # (BENCH_r04) tracked release over release
        build_obj = _median(sorted(p.get("build") or 0.0
                                   for p in clean_phases))
        build_arr = _median(sorted(p.get("build") or 0.0
                                   for p in stored_phases))
        emit("elle_50k_stored_columns_txns_per_sec", n_txns / med_c,
             "txns/s", cpu_med / med_c,
             object_path_txns_per_sec=round(n_txns / med, 2),
             phase_build_s=[p.get("build") for p in stored_phases],
             phase_build_reduction=round(build_obj / max(build_arr, 1e-4),
                                         2),
             **extras_c)

    bad = _elle_history(n_txns, crossed_pairs=50)
    n_bad = n_txns + 100
    r_cpu, t_cpu = _trials(
        lambda: list_append.check(bad, accelerator="cpu"), 5)
    # the 2k-txn warm above covers the clean path only: the anomalous
    # 50k run compiles the cluster screen/search at ITS bucket shapes,
    # and that one-time ~16 s compile was landing inside trial 0 (r5
    # measured phase_cycles_s[0]=15.9 vs 0.13 steady) — warm it out
    _warm_timed("elle_50k_anomalous",
                lambda: list_append.check(bad, accelerator="tpu"))
    phases: list[dict] = []
    r_dev, t_dev = _trials(phased(bad, phases), 5)
    assert r_dev["valid?"] is False and r_cpu["valid?"] is False
    assert "G1c" in r_dev["anomaly-types"], r_dev.get("anomaly-types")
    med, extras = _spread(t_dev, n_bad)
    cpu_med, _ = _spread(t_cpu, n_bad)
    emit("elle_50k_anomalous_txns_per_sec", n_bad / med, "txns/s",
         cpu_med / med, cpu_txns_per_sec=round(n_bad / cpu_med, 2),
         trial_seconds=[round(t, 2) for t in t_dev],
         phase_build_s=[p.get("build") for p in phases],
         phase_cycles_s=[p.get("cycles") for p in phases],
         **extras)


def cfg_ir_amortization():
    """The history-IR encode-once contract: two checkers over the SAME
    50k-op register history through one shared IR. first_encode_s is
    the IR build + the first checker's view derivation; the second
    checker's encode phase is a memo hit and must be ~zero (the
    acceptance bar for ROADMAP item 3 / ISSUE 11). Both checkers then
    actually run (Compose-style shared test map) so the sharing is the
    production code path, not a synthetic probe."""
    from __graft_entry__ import _register_history
    from jepsen_tpu import history_ir
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.history_ir import views

    n = 50_000
    history = _register_history(n, n_procs=N_PROCS, seed=11)
    test = {"name": "bench-ir"}

    t0 = time.perf_counter()
    ir = history_ir.of(test, history)
    stream = views.register_stream(ir)      # first checker's encode
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    again = views.register_stream(ir)       # second checker's encode
    second_s = time.perf_counter() - t0
    assert again is stream, "second checker re-encoded: memo broken"

    # the real two-checker path: both checks share the test map's IR
    c1 = LinearizableChecker(accelerator="cpu")
    c2 = LinearizableChecker(accelerator="cpu")
    t0 = time.perf_counter()
    r1 = c1.check(test, history, {})
    wall_1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    r2 = c2.check(test, history, {})
    wall_2 = time.perf_counter() - t0
    assert r1["valid?"] is True and r2["valid?"] is True
    assert test.get("_history_ir") is ir, "checkers didn't share the IR"

    emit("ir_encode_amortization", second_s * 1000.0, "ms",
         first_s / max(second_s, 1e-9),
         first_encode_s=round(first_s, 4),
         second_encode_s=round(second_s, 6),
         checker_wall_first_s=round(wall_1, 3),
         checker_wall_second_s=round(wall_2, 3),
         ops=n)


def cfg_matrix_kernel():
    """Block-composed transfer-matrix kernel on its home regime — long
    history, small value domain — vs the event-by-event dense scan."""
    import jax
    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import (
        JitLinKernel, _bucket, matrix_check, matrix_ok)

    stream = _block_stream(12_800, n_values=4)   # 128k events, V=5
    E = len(stream)
    S, V = stream.n_slots, len(stream.intern)
    n_returns = int((np.asarray(stream.kind) == 1).sum())
    assert matrix_ok(S, V, n_returns), "bench config must be in-regime"

    m = _warm_timed("matrix_kernel",              # warm-up compile
                    lambda: matrix_check(stream))
    assert m is not None and m[0] and not m[2], m
    # per-trial host/device phase split (r5 weak #1: the 17.6%-of-peak
    # single-dispatch fraction was unattributable): prepass/grids are
    # host encode, dispatch is the async kernel call, fetch is the
    # device compute + readback wait
    from jepsen_tpu.ops import jitlin as jitlin_mod
    phase_trials: list[dict] = []

    def matrix_phased():
        out = matrix_check(stream)
        phase_trials.append(jitlin_mod.last_phase_seconds())
        return out

    m, t_matrix = _trials(matrix_phased, 5)
    dt_matrix, extras = _spread(t_matrix, E)
    try:
        from jepsen_tpu.ops.jitlin import _matrix_plan, last_dispatch_info
        Vb = _bucket(V, 8)
        C_plan, _T = _matrix_plan(1, S, n_returns, Vb, None)
        extras.update(telemetry.matrix_phase_model(
            n_returns, S, Vb, C_plan, 1))
        for ph in ("prepass", "grids", "dispatch", "fetch"):
            vals = sorted(p.get(ph, 0.0) for p in phase_trials)
            extras[f"phase_{ph}_s"] = vals[len(vals) // 2]
        # combine-stage HBM share + routing labels: which kernel
        # representation and combine path the dispatch actually ran
        # (probe-selected — "scan"/"tree" on backends without pallas),
        # and the modeled combine traffic over wall time and measured
        # bandwidth. The tree/fused byte ratio is the fused combine's
        # designed win; both are on record so a routing regression is
        # visible in one diff.
        info = last_dispatch_info()
        MV = (1 << S) * Vb
        fused = info.get("combine") == "fused"
        bw = device_roofline()["hbm_bytes_per_sec"]
        cb = telemetry.combine_modeled_hbm_bytes(1, C_plan, MV, fused)
        extras.update(
            matrix_variant=info.get("variant", "unknown"),
            combine_path=info.get("combine", "unknown"),
            combine_modeled_hbm_bytes=cb,
            combine_hbm_frac=round((cb / dt_matrix) / bw, 6),
            combine_fused_reduction=_combine_reduction(
                1, C_plan, MV, fused))
        from jepsen_tpu.ops import pallas_matrix
        extras["pallas_probe_seconds"] = round(
            pallas_matrix.probe_seconds(), 4)
    except Exception:
        print("[bench] phase attribution failed:", file=sys.stderr)
        traceback.print_exc()

    # per-variant attribution (ISSUE 12): each representation measured
    # through the SAME production dispatch with the variant pinned —
    # probe-gated, so on a backend where a variant can't run the
    # `*_ran` label records what actually executed instead of lying
    # with a zero
    try:
        from jepsen_tpu.ops import pallas_matrix
        from jepsen_tpu.ops.jitlin import last_dispatch_info
        for v in pallas_matrix.VARIANTS:
            _, t_v = _trials(lambda v=v: matrix_check(stream, variant=v), 2)
            dt_v = min(t_v)
            ran = last_dispatch_info().get("variant", "unknown")
            extras[f"events_per_sec_{v}"] = round(E / dt_v, 2)
            extras[f"roofline_frac_{v}"] = matrix_roofline_extras(
                n_returns, S, V, dt_v)["roofline_frac"]
            extras[f"variant_ran_{v}"] = ran
    except Exception:
        print("[bench] per-variant attribution failed:", file=sys.stderr)
        traceback.print_exc()

    batch = pad_streams([stream], length=_bucket(E))
    run = JitLinKernel()._get(S, CAPACITY, batched=False, num_states=V)
    args = _device_args(batch)
    _warm_timed("matrix_kernel_scan",             # warm-up compile
                lambda: _force(*run(*args)))
    out, t_scan = _trials(lambda: _force(*run(*args)), 5)
    alive, _, ovf, _ = out
    dt_scan, _ = _spread(t_scan, E)
    assert bool(alive) and not bool(ovf)
    assert bool(m[0]) == bool(alive), "matrix and scan verdicts must agree"
    extra = {"scan_events_per_sec": round(E / dt_scan, 2), **extras}
    try:
        extra.update(matrix_roofline_extras(n_returns, S, V, dt_matrix))
        # the scan path is event-sequential and bandwidth-bound: bound
        # it against measured HBM read+write of its P state per event
        bw = device_roofline()["hbm_bytes_per_sec"]
        MV = (1 << S) * V
        scan_bytes = 2.0 * MV * MV * 4          # P read + write, f32
        extra["scan_hbm_frac"] = round(
            (E / dt_scan) * scan_bytes / bw, 4)
    except Exception:
        print("[bench] roofline add-on failed:", file=sys.stderr)
        traceback.print_exc()

    # failing-history double run: a not-alive matrix verdict falls back to
    # the event scan for diagnostics — measure that total so the cost of
    # the two-pass failure path is on record (VERDICT r1 weak #7). Run
    # guarded AFTER the primary measurement exists, so a failure here
    # can't discard it.
    try:
        from dataclasses import replace
        t = (E // (2 * N_PROCS)) // 2
        a_bad = stream.a.copy()
        e_corrupt = t * 2 * N_PROCS + 1     # block t, proc 1's read invoke
        a_bad[e_corrupt] = (t + 1) % 4 + 1  # neither w_{t-1} nor w_t
        bad = replace(stream, a=a_bad)
        t0 = time.perf_counter()
        mb = matrix_check(bad)
        assert mb is not None and not mb[0]
        batch_bad = pad_streams([bad], length=_bucket(E))
        alive_b, _, _, _ = _force(*run(*_device_args(batch_bad)))
        dt_fail = time.perf_counter() - t0
        assert not bool(alive_b)
        extra["failing_double_run_seconds"] = round(dt_fail, 3)
    except Exception:
        print("[bench] failing-path add-on failed:", file=sys.stderr)
        traceback.print_exc()
    emit("matrix_kernel_128k_events_per_sec", E / dt_matrix, "events/s",
         dt_scan / dt_matrix, **extra)


def cfg_explain():
    """explain_latency_128k: anomaly forensics (device localization +
    witness shrink, checker/explain.py) on a planted-anomaly 128k-event
    history. The bar is < 2× the PLAIN matrix check's wall time —
    forensics must stay in the same cost class as the verdict they
    explain, or nobody runs them (vs_baseline = 2×check / explain; ≥ 1
    is under the bar). Steady-state like every quick config: the one
    warm-up explain compiles the forensics kernels (products + prefix
    scan + the ddmin candidate buckets its deterministic round sequence
    touches)."""
    from dataclasses import replace

    from jepsen_tpu.checker.explain import explain_stream
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.ops.jitlin import matrix_check

    stream = _block_stream(12_800, n_values=4)   # 128k events, V=5
    E = len(stream)
    # plant the anomaly the way cfg_matrix_kernel's failing path does:
    # one read observes a value that is neither w_{t-1} nor w_t
    t = (E // (2 * N_PROCS)) // 2
    a_bad = stream.a.copy()
    a_bad[t * 2 * N_PROCS + 1] = (t + 1) % 4 + 1
    bad = replace(stream, a=a_bad)

    m = _warm_timed("explain_check", lambda: matrix_check(bad))
    assert m is not None and not m[0] and not m[2], m
    _, t_check = _trials(lambda: matrix_check(bad), 3)
    check_med = _median(t_check)

    f = _warm_timed("explain", lambda: explain_stream(bad))
    assert f is not None, "planted anomaly must localize"
    # differential anchor: the device bisection must land on the exact
    # CPU frontier rejection (one CPU pass, outside the trials)
    cpu = check_stream(bad)
    assert f["first_anomaly"]["event"] == cpu.failed_event, (
        f["first_anomaly"], cpu.failed_event)
    results, t_explain = _trials(lambda: explain_stream(bad), 3)
    explain_med = _median(t_explain)
    emit("explain_latency_128k", explain_med, "s",
         (2.0 * check_med) / max(explain_med, 1e-9),
         check_seconds=round(check_med, 4),
         first_anomaly_op=results["first_anomaly"]["op_index"],
         witness_ops=len(results["witness"]["op_indices"]),
         bisect_steps=results["bisect_steps"],
         shrink_candidates=results["witness"]["candidates"],
         trials=len(t_explain))


def cfg_scale(device_rate: float):
    """North-star scaling metric: the largest single logical history
    verified on device inside the 300 s budget.

    Runs as a CHAIN of ~1M-event segments through the transfer-matrix
    kernel with the composed operator product carried on device between
    them (jitlin.matrix_check_resume): each segment is generated fresh
    with a continuing block offset, its returns compose as [MV, MV] MXU
    matmuls, and the product chains — one contiguous valid history on the
    faithful rand-int-5 domain, verified end to end, ~300k events/s per
    segment. Segmentation is what lets the run spend the WHOLE budget:
    monolithic 8M+-event dispatches crash the tunneled TPU worker
    ("TPU worker process crashed or restarted"), so r2 stopped at a 4.19M
    stability cap; bounded dispatches sidestep that entirely. (Large
    domains out of the matrix regime take the same segment chain through
    the event-scan kernels' frontier carry — jitlin.segmented_check.) A
    segment failure is caught and named, and the total verified so far (a
    sound prefix verdict) is still reported."""
    from jepsen_tpu.ops.jitlin import matrix_check_resume

    target_s = float(os.environ.get("BENCH_SCALE_TARGET_S", "280"))
    if target_s <= 0:
        return
    SEG_E = 1 << 20                      # ~1M events: well under the
    #                                      monolithic-dispatch crash size,
    #                                      fine-grained enough to respect
    #                                      the budget within one segment
    # faithful small domain (the register workload's rand-int 5 → values
    # 0..4): each return composes one [MV, MV] operator on the MXU, and
    # the segment carry is the composed product — the matrix kernel's
    # home regime
    n_values = 5
    seg_blocks = SEG_E // (2 * N_PROCS)
    seg_events = seg_blocks * 2 * N_PROCS

    def seg_stream(k):
        return _block_stream(seg_blocks, n_values=n_values,
                             start_block=k * seg_blocks)

    def dispatch(k, tot):
        return matrix_check_resume(seg_stream(k), tot, n_slots=N_PROCS,
                                   num_states=n_values + 1)

    # compile + warm outside the budget at both carry shapes (the first
    # call carries the identity, later calls the previous device total)
    a0, ix0, warm_tot = dispatch(0, None)
    a1, ix1, _ = dispatch(1, warm_tot)
    a1, ix1 = _force(a1, ix1)
    assert bool(np.asarray(a1).all()) and not bool(np.asarray(ix1).any())

    # one-deep pipeline: dispatch segment k (async), THEN sync segment
    # k-1 — so segment k's host generation + prepass + grid transfer
    # overlap segment k-1's device compute. The tot carry chains as a
    # lazy device array, no sync needed between dispatches.
    # budget discipline (r3 weak #1): a segment COUNTS only if its sync
    # completed with elapsed <= target_s. A sync that straggles past the
    # budget (the tunnel-stall signature r3 caught: one 262 s sync after
    # ~2 s steady state) is reported separately, never counted.
    total_events = 0
    segments = 0
    failure = None
    tot = None
    pending = None
    seg_times: list = []
    counted_at = 0.0          # elapsed when the last counted sync landed
    overflow = None           # the uncounted straggler, if any
    t_start = time.perf_counter()

    def sync_counts(p):
        """Forces p; returns True iff it verified AND landed in budget."""
        nonlocal total_events, segments, counted_at, overflow
        pa, pix = _force(*p)
        assert bool(np.asarray(pa).all())
        assert not bool(np.asarray(pix).any())
        elapsed = time.perf_counter() - t_start
        if elapsed <= target_s:
            total_events += seg_events
            segments += 1
            counted_at = elapsed
            return True
        overflow = {"events": seg_events,
                    "synced_at_seconds": round(elapsed, 1)}
        return False

    k = 0
    while True:
        elapsed = time.perf_counter() - t_start
        # next-segment estimate: MEDIAN of recent segments, not max — a
        # single tunnel stall (r4 observed 112 s against a 1.2 s steady
        # state) would otherwise poison the estimate and abandon the
        # rest of the budget after the stall clears; straddling syncs
        # never count anyway, so optimism here is budget-safe
        recent = seg_times[-5:]
        est = _median(recent) if recent else 0.0
        if elapsed >= target_s or elapsed + est >= target_s:
            break
        try:
            t0 = time.perf_counter()
            alive, inexact, tot = dispatch(k, tot)
            k += 1
            if pending is not None and not sync_counts(pending):
                pending = None
                break  # budget blown mid-sync: stop dispatching
            pending = (alive, inexact)
            seg_times.append(round(time.perf_counter() - t0, 1))
        except Exception as e:  # noqa: BLE001 — name the failure, keep prefix
            failure = f"{type(e).__name__}: {e}"
            print(f"[bench] scale segment {segments} failed: {failure}",
                  file=sys.stderr)
            traceback.print_exc()
            pending = None
            break
    if pending is not None:
        try:
            sync_counts(pending)
        except Exception as e:  # noqa: BLE001
            failure = f"{type(e).__name__}: {e}"
    wall = time.perf_counter() - t_start
    if total_events:
        ts = sorted(seg_times)
        med_seg = _median(ts) if ts else 0.0
        extra = {"measured_seconds": round(counted_at, 1),
                 "wall_seconds": round(wall, 1), "segments": segments,
                 "segment_events": seg_events,
                 "segment_seconds_median": med_seg,
                 "segment_seconds_max": max(ts) if ts else 0.0,
                 "value_domain": n_values,
                 "path": "matrix-segmented",
                 "events_per_sec": round(total_events / max(counted_at, 1e-9),
                                         1)}
        if ts and max(ts) > 5 * max(med_seg, 0.1):
            extra["stall"] = (f"tunnel stall: worst segment "
                              f"{max(ts)}s vs median {med_seg}s")
        try:
            # fused-combine attribution for the segmented path: the
            # routing the chain's dispatches actually took, and the
            # modeled tree/fused HBM-byte ratio the fusion delivers
            # (1.0 = tree combine ran — the regression signal)
            from jepsen_tpu.ops.jitlin import (
                _bucket as _bk, _matrix_plan as _mp, last_dispatch_info)
            info = last_dispatch_info()
            Vb = _bk(n_values + 1, 8)
            MVs = (1 << N_PROCS) * Vb
            Cs, _Ts = _mp(1, N_PROCS, seg_events // 2, Vb, None)
            fused = info.get("combine") == "fused"
            extra["combine_path"] = info.get("combine", "unknown")
            extra["matrix_variant"] = info.get("variant", "unknown")
            extra["combine_fused_reduction"] = _combine_reduction(
                1, Cs, MVs, fused)
        except Exception:
            print("[bench] combine attribution failed:", file=sys.stderr)
            traceback.print_exc()
        if overflow:
            extra["uncounted_overflow_segment"] = overflow
        if failure:
            extra["failure"] = failure
        try:
            # returns = half the events (invoke/return block pairs)
            extra.update(matrix_roofline_extras(
                total_events // 2, N_PROCS, n_values + 1, counted_at))
        except Exception:
            print("[bench] roofline add-on failed:", file=sys.stderr)
            traceback.print_exc()
        # full per-segment timings to stderr only (they once pushed the
        # metric lines out of the driver's 2000-char stdout tail)
        print(f"[bench] scale segment_seconds={seg_times}", file=sys.stderr)
        emit("max_history_len_checked_300s", total_events, "events",
             total_events / N_OPS, **extra)
    else:
        # nothing counted — name WHY (a first-segment tunnel stall is
        # sync work that verified late, not a silent no-op)
        print(f"[bench] scale run counted nothing: failure={failure} "
              f"overflow={overflow} wall={round(wall, 1)}s",
              file=sys.stderr)


def _multichip_measure(counts=(1, 2, 4, 8)) -> dict:
    """In-process multichip measurement: events/s of the segmented
    transfer-matrix path (matrix_check_resume chain) at each mesh width,
    plus the host's independent-dispatch ceiling at the widest. Small
    faithful shapes (3-way concurrency, rand-int-5 domain → MV = 64) so
    the CPU mesh finishes inside a bench stage; the mechanism, padding,
    collectives, and per-device staging are exactly the production
    path's."""
    import jax

    from jepsen_tpu.ops import jitlin
    from jepsen_tpu.parallel import get_mesh

    n_procs, n_values = 3, 5
    V = n_values + 1
    seg_events = int(os.environ.get("BENCH_MULTICHIP_SEG_EVENTS",
                                    str(1 << 15)))
    n_segs = int(os.environ.get("BENCH_MULTICHIP_SEGMENTS", "3"))
    seg_blocks = max(1, seg_events // (2 * n_procs))
    streams = [_block_stream(seg_blocks, n_procs=n_procs,
                             n_values=n_values, start_block=k * seg_blocks)
               for k in range(n_segs)]
    E = sum(len(s.kind) for s in streams)
    n_dev = len(jax.devices())
    counts = [c for c in counts if c <= n_dev]
    rates: dict[int, float] = {}
    for nd in counts:
        mesh = get_mesh(nd) if nd > 1 else None

        def chain():
            tot = None
            for s in streams:
                a, ix, tot = jitlin.matrix_check_resume(
                    s, tot, n_slots=n_procs, num_states=V, mesh=mesh)
            assert bool(np.asarray(a).all()), f"nd={nd}: chain not alive"
            assert not bool(np.asarray(ix).any()), f"nd={nd}: inexact"

        _warm_timed(f"multichip_{nd}dev", chain)   # compile + one execute
        t0 = time.perf_counter()
        chain()
        rates[nd] = E / (time.perf_counter() - t0)
        print(f"[bench] multichip nd={nd}: {rates[nd]:,.0f} events/s",
              file=sys.stderr, flush=True)
    top = max(rates)
    ceiling = _independent_dispatch_ceiling(n_procs, n_values, top)
    speedup = rates[top] / rates[min(rates)]
    # efficiency vs what the host can actually deliver: ideal scaling is
    # min(N, the measured embarrassingly-parallel ceiling) — on real
    # N-device hardware the ceiling is ~N and this degrades to the
    # classic speedup/N; on a virtual CPU mesh (one shared host, XLA
    # serializing cross-device executions) raw /N would only measure the
    # container's core count, not the sharding mechanism
    # (doc/performance.md "Multi-device sharding").
    eff = speedup / max(1.0, min(float(top), ceiling))
    return {"events_per_sec": {str(k): round(v, 1)
                               for k, v in rates.items()},
            "speedup_top": round(speedup, 3),
            "top_devices": top,
            "host_parallel_ceiling": round(ceiling, 3),
            "scaling_efficiency_8dev": round(eff, 3),
            "segments": n_segs, "segment_events": seg_blocks * 2 * n_procs,
            "platform": jax.default_backend()}


def _independent_dispatch_ceiling(n_procs: int, n_values: int,
                                  nd: int) -> float:
    """Measured embarrassingly-parallel ceiling: aggregate speedup of
    ``nd`` INDEPENDENT single-device dispatches of the same compiled
    matrix kernel (one per device, zero collectives) over one. This is
    the upper bound ANY sharding of this workload can reach on this
    host, so it is the honest denominator for scaling efficiency."""
    import jax

    from jepsen_tpu.ops import jitlin

    V = n_values + 1
    blocks = max(1, int(os.environ.get("BENCH_MULTICHIP_CEIL_EVENTS",
                                       str(1 << 13))) // (2 * n_procs))
    s = _block_stream(blocks, n_procs=n_procs, n_values=n_values)
    prep = jitlin._returns_prepass(
        np.asarray(s.kind), np.asarray(s.slot), np.asarray(s.f),
        np.asarray(s.a), np.asarray(s.b))
    S = max(n_procs, prep[3])
    R = prep[0].shape[0]
    Vb = jitlin._bucket(V, floor=8)
    C, T = jitlin._matrix_plan(1, S, R, Vb, None)
    grids, uops = jitlin._matrix_grids([prep], S, Vb, 1, C, T, None)
    run = jitlin._matrix_cache(S, Vb, jitlin._default_step_ids(), 0, T, C)
    devs = jax.devices()[:nd]
    args = [[jax.device_put(g, d) for g in grids]
            + [jax.device_put(uops, d)] for d in devs]
    for ar in args:  # compile once, then one warm execute per device
        jax.block_until_ready(run(ar[0], ar[1], ar[4], ar[2], ar[3]))

    def once(n: int) -> float:
        t0 = time.perf_counter()
        outs = [run(ar[0], ar[1], ar[4], ar[2], ar[3]) for ar in args[:n]]
        jax.block_until_ready(outs)
        return time.perf_counter() - t0

    t1 = min(once(1) for _ in range(3))
    tn = min(once(len(devs)) for _ in range(2))
    return len(devs) * t1 / max(tn, 1e-9)


def cfg_multichip_scaling():
    """multichip_scaling: events/s of the segmented path at 1/2/4/8
    devices, plus scaling_efficiency_8dev — the regression guard for the
    multi-device data plane (ROADMAP item 1). Self-provisions an
    8-virtual-CPU-device subprocess when this process cannot supply 8
    devices (the dryrun_multichip recipe: env BEFORE jax import)."""
    in_proc = False
    if "jax" in sys.modules:
        import jax
        try:
            in_proc = len(jax.devices()) >= 8
        except Exception:  # noqa: BLE001 — backend unreachable: child
            in_proc = False
    if in_proc:
        data = _multichip_measure()
    else:
        import subprocess
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        # replace (not just append) any pre-existing forced count — a
        # site XLA_FLAGS pinning =4 would otherwise shrink the mesh and
        # the metric would be an 8dev label over a 4-device measurement
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--multichip-child"],
            capture_output=True, text=True, timeout=480, env=env)
        if out.returncode != 0:
            raise RuntimeError(
                f"multichip child failed (rc {out.returncode}):\n"
                f"{out.stderr[-2000:]}")
        data = json.loads(out.stdout.strip().splitlines()[-1])
    rates = {int(k): v for k, v in data["events_per_sec"].items()}
    top = data["top_devices"]
    eff = data["scaling_efficiency_8dev"]
    emit("multichip_scaling", rates[top], "events/s",
         data["speedup_top"],
         events_per_sec_by_devices=data["events_per_sec"],
         host_parallel_ceiling=data["host_parallel_ceiling"],
         segments=data["segments"],
         segment_events=data["segment_events"],
         value_domain=5, n_procs=3, platform=data["platform"],
         path="matrix-segmented-sharded",
         in_process=in_proc)
    emit("scaling_efficiency_8dev", eff, "frac", eff,
         top_devices=top,
         host_parallel_ceiling=data["host_parallel_ceiling"],
         methodology="speedup vs max(1, min(N, measured independent-"
                     "dispatch ceiling)); classic speedup/N on real "
                     "N-device hardware")


def cfg_online_lag():
    """online_checker_lag: sustained ingest rate of the live checking
    path (doc/observability.md "Live checking") — WAL tail (offset
    reader + JSON parse) -> incremental register encode -> resumable
    frontier — with a verdict poll after every chunk, and the worst
    verdict lag observed at any poll. The target shape is the
    acceptance bar: >= 1M ops/s sustained at bounded lag (raised from
    100k by the host ingest spine — native tail+parse, chunked
    ``add_many`` encode, GC deferred per burst)."""
    import tempfile
    from pathlib import Path

    from __graft_entry__ import _register_history
    from jepsen_tpu.history_ir import ingest as ingest_mod
    from jepsen_tpu.journal import Journal, WalTailer
    from jepsen_tpu.live.sessions import LinearLiveSession

    n = 100_000
    chunk = 20_000  # one verdict poll per chunk bounds the lag
    # 3-way concurrency: the live path's steady-state shape (a serving
    # fleet's per-key streams are narrow; wide frontiers are the batch
    # checker's province — and the budget/admission machinery's, not
    # this throughput bar's)
    history = _register_history(n, n_procs=3, seed=7, n_values=5)
    with tempfile.TemporaryDirectory() as tmp:
        wal = Path(tmp) / "history.wal.jsonl"
        j = Journal(wal, fsync_interval_s=-1)
        for op in history:
            j.append(op)
        j.close()

        def consume():
            tailer = WalTailer(wal)
            session = LinearLiveSession(accelerator="cpu")
            lag_max = 0
            with ingest_mod.ingest_burst():
                ops = tailer.poll()
            assert len(ops) == len(history), len(ops)
            for i in range(0, len(ops), chunk):
                with ingest_mod.ingest_burst():
                    session.add_many(ops[i:i + chunk])
                v = session.verdict()
                assert v["valid_so_far"] is True, v
                lag_max = max(lag_max,
                              session.ops_absorbed - v["checked_ops"])
            session.finalize()
            return lag_max

        lag_max, times = _trials(consume, 5)

        # checker-side sustained rate (pre-parsed ops): isolates the
        # incremental encode+frontier from the JSON tail
        parsed = WalTailer(wal).poll()

        def check_only():
            session = LinearLiveSession(accelerator="cpu")
            for i in range(0, len(parsed), chunk):
                with ingest_mod.ingest_burst():
                    session.add_many(parsed[i:i + chunk])
                session.verdict()
            session.finalize()

        _, check_times = _trials(check_only, 3)
    med, extras = _spread(times, len(history))
    rate = len(history) / med
    emit("online_checker_lag", rate, "ops/s", rate / 1_000_000.0,
         lag_ops_max=int(lag_max), chunk_ops=chunk, n_ops=n,
         path="tail+encode+frontier",
         native_ingest=ingest_mod.enabled(),
         check_ops_per_sec=round(len(history) / min(check_times), 1),
         **extras)


def _fleet_measure():
    """100 concurrent synthetic runs shipped over loopback HTTP into
    one FleetDaemon, with one mesh shrink + one regrow cycle injected
    mid-flight. Returns the raw measurement dict (also the
    --fleet-child stdout payload)."""
    import shutil
    import tempfile
    import threading
    from pathlib import Path

    import jax

    from __graft_entry__ import _register_history
    from jepsen_tpu import parallel
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.fleet.scheduler import FleetDaemon
    from jepsen_tpu.fleet.ship import Shipper
    from jepsen_tpu.journal import Journal
    from jepsen_tpu.live.daemon import load_live_status

    n_runs = 100
    ops_per_run = 120
    histories = {f"r{i:03d}": _register_history(
        ops_per_run, n_procs=3, seed=i, n_values=5)
        for i in range(n_runs)}
    reg = telemetry.Registry()
    # regrow_mesh/shrink_mesh count on the process-global registry
    prev = telemetry.install(reg)
    tmp = tempfile.mkdtemp(prefix="fleet-bench-")
    worst_lag = 0.0
    try:
        src = Path(tmp) / "src"
        store = Path(tmp) / "fleet"
        fd = FleetDaemon(store, port=0, poll_s=0.05,
                         ingest_budget_s=0.5, max_runs=n_runs + 8,
                         accelerator="cpu", registry=reg,
                         regrow_backoff_s=0.05)
        fd.start()
        t0 = time.perf_counter()

        def one(ts, h):
            # ship WHILE producing — the live-shipping shape; a run
            # landing already complete is post-hoc territory
            rd = src / "bench" / ts
            rd.mkdir(parents=True)
            j = Journal(rd / "history.wal.jsonl", fsync_interval_s=-1)
            j.append(h[0])
            sh = Shipper(rd, f"http://127.0.0.1:{fd.port}",
                         poll_s=0.02)
            shipped = []
            st = threading.Thread(
                target=lambda: shipped.append(sh.run(timeout_s=240)),
                daemon=True)
            st.start()
            born = time.monotonic()
            for op in h[1:]:
                j.append(op)
                time.sleep(0.0005)
            j.close()
            # keep the run live for a few discovery polls before the
            # final lands — a run that completes inside one poll is
            # (correctly) post-hoc territory, not the pool's; polls
            # stretch toward ingest_budget_s with 100 runs tracked
            time.sleep(max(0.0, 2.0 - (time.monotonic() - born)))
            with open(rd / "history.jsonl", "w") as f:
                for op in h:
                    f.write(json.dumps(op) + "\n")
            st.join(240)
            if shipped != [True]:
                raise RuntimeError(f"run {ts} never finalized")

        threads = [threading.Thread(target=one, args=(ts, h),
                                    daemon=True)
                   for ts, h in histories.items()]
        for t in threads:
            t.start()

        # one shrink + one regrow cycle mid-flight: fail a device the
        # way a collective error would, then let the fleet daemon's
        # heal probe regrow the mesh
        time.sleep(0.3)
        devs = jax.devices()
        mesh = parallel.auto_mesh() if len(devs) >= 2 else None
        if mesh is not None and int(mesh.devices.size) >= 2:
            casualty = list(mesh.devices.flat)[-1].id
            parallel.shrink_mesh(mesh, RuntimeError(
                f"UNAVAILABLE: device {casualty} lost mid collective"))

        def lag_gauge():
            return reg.gauge("fleet_worst_lag_ops",
                             "largest per-run checker lag across "
                             "the pool").value()

        for t in threads:
            while t.is_alive():
                t.join(0.1)
                worst_lag = max(worst_lag, lag_gauge())
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and fd.daemon.trackers:
            worst_lag = max(worst_lag, lag_gauge())
            time.sleep(0.05)
        elapsed = time.perf_counter() - t0
        if fd.daemon.trackers:
            raise RuntimeError(
                f"pool never settled {len(fd.daemon.trackers)} runs")
        fd.stop()

        snap = reg.snapshot()

        def ctr(name):
            return sum(r["value"] for r in snap if r["name"] == name)

        # fleet verdicts must be bit-identical to local analyze over
        # the same histories
        mismatches = 0
        invalid = 0
        for ts, h in histories.items():
            status = load_live_status(store / "bench" / ts)
            if status is None or status.get("state") != "final":
                raise RuntimeError(f"run {ts} has no final status")
            local = LinearizableChecker(
                accelerator="cpu").check({}, h, {})
            mismatches += status["valid_so_far"] is not local["valid?"]
            invalid += status["valid_so_far"] is False
        if mismatches:
            raise RuntimeError(
                f"{mismatches} fleet verdicts diverged from local "
                "analyze")
        total_ops = n_runs * ops_per_run
        return {"runs": n_runs, "ops_total": total_ops,
                "ops_per_sec": round(total_ops / elapsed, 1),
                "wall_s": round(elapsed, 2),
                "worst_lag_ops": int(worst_lag),
                "shrinks": int(ctr("mesh_shrink_total")),
                "regrows": int(ctr("mesh_regrow_total")),
                "ingest_bytes": int(ctr("fleet_ingest_bytes_total")),
                "ingest_rejected": int(
                    ctr("fleet_ingest_rejected_total")),
                "invalid_runs": invalid,
                "n_devices": len(devs)}
    finally:
        telemetry.install(prev)
        with parallel._HEALTH_LOCK:
            parallel._FAILED_DEVICES.clear()
        shutil.rmtree(tmp, ignore_errors=True)


def cfg_fleet_runs_sustained():
    """fleet_runs_sustained: sustained ops/s through the full fleet
    plane — 100 concurrent synthetic runs shipping WALs over loopback
    HTTP into one ingest receiver while the pool daemon live-checks
    them all — with one mesh shrink + one regrow cycle injected
    mid-flight (doc/observability.md "Fleet plane"). Guards bounded
    worst live_checker_lag_ops, verdict parity against local analyze
    on the same WALs, and zero ingest rejections on the happy path.
    Self-provisions an 8-virtual-CPU-device subprocess when this
    process cannot supply >= 2 devices (the shrink/regrow leg needs a
    mesh that can narrow and widen)."""
    in_proc = False
    if "jax" in sys.modules:
        import jax
        try:
            in_proc = len(jax.devices()) >= 2
        except Exception:  # noqa: BLE001 — backend unreachable: child
            in_proc = False
    if in_proc:
        data = _fleet_measure()
    else:
        import subprocess
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--fleet-child"],
            capture_output=True, text=True, timeout=480, env=env)
        if out.returncode != 0:
            raise RuntimeError(
                f"fleet child failed (rc {out.returncode}):\n"
                f"{out.stderr[-2000:]}")
        data = json.loads(out.stdout.strip().splitlines()[-1])
    rate = data["ops_per_sec"]
    # the bar: >= 2k ops/s sustained over network ingest with lag
    # bounded by the admission budget's working set
    emit("fleet_runs_sustained", rate, "ops/s", rate / 2_000.0,
         runs=data["runs"], ops_total=data["ops_total"],
         wall_s=data["wall_s"], worst_lag_ops=data["worst_lag_ops"],
         mesh_shrinks=data["shrinks"], mesh_regrows=data["regrows"],
         ingest_bytes=data["ingest_bytes"],
         ingest_rejected=data["ingest_rejected"],
         invalid_runs=data["invalid_runs"],
         n_devices=data["n_devices"], in_process=in_proc,
         verdict_parity="bit-identical to local analyze")


def cfg_fleet_failover():
    """fleet_failover: kill the ACTIVE pool host under live shipped
    load and measure what HA actually costs (doc/robustness.md "Fleet
    HA"). Real OS processes — the receiver and both leased pool hosts
    are the fleet-chaos harness's child roles — with pool0 holding
    every lease when it is SIGKILLed:

    * ``fleet_failover_adoption_s`` — wall from the kill to the
      standby holding a lease on EVERY in-flight run. Bar: <= 2x the
      lease TTL (one TTL for the lease to expire, one for the
      standby's discovery/claim cadence).
    * ``fleet_failover_recheck_frac`` — fraction of the runs already
      settled before the kill that any host finalized AGAIN
      afterwards. Bar: <= 0.1 (the design says 0: a final verdict is
      durable and discovery skips it; the 10% headroom is for a
      verdict racing the kill itself).
    """
    import shutil
    import tempfile
    import threading
    from pathlib import Path

    from __graft_entry__ import _register_history
    from jepsen_tpu.fleet.chaos import _Child, _free_port
    from jepsen_tpu.fleet.ship import Shipper
    from jepsen_tpu.journal import WAL_NAME, Journal, read_jsonl_tolerant
    from jepsen_tpu.live.daemon import load_live_status

    ttl = float(os.environ.get("BENCH_FAILOVER_TTL_S", "1.0"))
    n_pre = int(os.environ.get("BENCH_FAILOVER_PRE_RUNS", "6"))
    n_live = int(os.environ.get("BENCH_FAILOVER_LIVE_RUNS", "6"))
    ops_per_run = 120
    deadline_s = 120.0
    reg = telemetry.Registry()
    tmp = tempfile.mkdtemp(prefix="fleet-failover-")
    root = Path(tmp)
    fleet = root / "fleet"
    src = root / "src"
    fleet.mkdir()
    src.mkdir()
    port = _free_port()
    receiver = _Child(fleet, "receiver",
                      ["--store", str(fleet), "--port", str(port)],
                      "failover-receiver.log")
    pool0 = _Child(fleet, "pool",
                   ["--store", str(fleet), "--host-id", "pool0",
                    "--ttl", str(ttl)], "failover-pool0.log")
    pool1 = _Child(fleet, "pool",
                   ["--store", str(fleet), "--host-id", "pool1",
                    "--ttl", str(ttl)], "failover-pool1.log")
    release_finals = threading.Event()
    threads: list[threading.Thread] = []

    def lease_host(key):
        try:
            with open(fleet / key / "check.lease",
                      encoding="utf-8") as f:
                return json.load(f).get("host")
        except (OSError, ValueError):
            return None

    def start_run(key, history, hold_final):
        """Producer + shipper for one run; ``hold_final`` gates the
        history.jsonl write on release_finals so the run stays live
        (tailing) until the conductor has measured adoption."""
        rd = src / key
        rd.mkdir(parents=True)

        def produce():
            j = Journal(rd / WAL_NAME, fsync_interval_s=-1)
            for op in history:
                j.append(op)
            j.close()
            if hold_final:
                release_finals.wait(deadline_s)
            else:
                # hold the final until the pool LEASED the run: a
                # history.jsonl landing before the pool's first poll
                # makes it post-hoc territory (discovery skips it) and
                # there'd be no settled verdict to survive the kill
                end = time.monotonic() + deadline_s
                while time.monotonic() < end and lease_host(key) is None:
                    time.sleep(0.02)
            with open(rd / "history.jsonl", "w", encoding="utf-8") as f:
                for op in history:
                    f.write(json.dumps(op) + "\n")

        sh = Shipper(rd, f"http://127.0.0.1:{port}", poll_s=0.02,
                     registry=reg)
        tp = threading.Thread(target=produce, daemon=True)
        ts = threading.Thread(
            target=lambda: sh.run(timeout_s=deadline_s), daemon=True)
        tp.start()
        ts.start()
        threads.extend([tp, ts])

    def await_final(keys, budget):
        end = time.monotonic() + budget
        pending = set(keys)
        while pending and time.monotonic() < end:
            for key in sorted(pending):
                st = load_live_status(fleet / key)
                if st is not None and st.get("state") == "final":
                    pending.discard(key)
            time.sleep(0.05)
        if pending:
            raise RuntimeError(f"failover runs never settled: "
                               f"{sorted(pending)}")

    pre_keys = [f"fob/p{i:02d}" for i in range(n_pre)]
    live_keys = [f"fob/l{i:02d}" for i in range(n_live)]
    try:
        receiver.spawn()
        pool0.spawn()
        # phase A: settle a population under pool0 — the runs whose
        # verdicts must SURVIVE the kill un-rechecked
        for i, key in enumerate(pre_keys):
            start_run(key, _register_history(ops_per_run, n_procs=3,
                                             seed=i, n_values=5),
                      hold_final=False)
        await_final(pre_keys, deadline_s)
        # phase B: live runs; pool0 must hold every lease before the
        # kill so the kill provably hits the ACTIVE host
        for i, key in enumerate(live_keys):
            start_run(key, _register_history(ops_per_run, n_procs=3,
                                             seed=100 + i, n_values=5),
                      hold_final=True)
        end = time.monotonic() + deadline_s
        while time.monotonic() < end and any(
                lease_host(k) != "pool0" for k in live_keys):
            time.sleep(0.05)
        assert all(lease_host(k) == "pool0" for k in live_keys)
        pool1.spawn()  # standby: sees pool0's live leases, claims none
        time.sleep(max(2 * 0.05, ttl / 4))

        t_kill = time.monotonic()
        pool0.kill()
        adopted: set = set()
        adoption_s = None
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            for k in live_keys:
                if k not in adopted and lease_host(k) == "pool1":
                    adopted.add(k)
            if len(adopted) == len(live_keys):
                adoption_s = time.monotonic() - t_kill
                break
            time.sleep(0.02)
        if adoption_s is None:
            raise RuntimeError(
                f"standby adopted {len(adopted)}/{n_live} runs within "
                f"{deadline_s}s")
        release_finals.set()
        for t in threads:
            t.join(deadline_s)
        await_final(live_keys, deadline_s)
    finally:
        release_finals.set()
        for child in (receiver, pool0, pool1):
            child.kill()

    rechecked = set()
    for f in sorted(fleet.glob("finals-*.jsonl")):
        rows, _ = read_jsonl_tolerant(f)
        for row in rows:
            key = str(row.get("key"))
            if key in pre_keys and row.get("host") == "pool1":
                rechecked.add(key)
    recheck_frac = len(rechecked) / max(n_pre, 1)
    snap = reg.snapshot()
    resyncs = {r["labels"].get("reason"): int(r["value"])
               for r in snap if r["name"] == "fleet_ship_resyncs_total"}
    shutil.rmtree(tmp, ignore_errors=True)

    emit("fleet_failover_adoption_s", adoption_s, "s",
         (2.0 * ttl) / max(adoption_s, 1e-6),
         lease_ttl_s=ttl, live_runs=n_live, settled_pre=n_pre,
         ship_resyncs=resyncs, killed_host="pool0",
         adopter="pool1")
    emit("fleet_failover_recheck_frac", recheck_frac, "frac",
         0.1 / max(recheck_frac, 1e-6),
         rechecked=sorted(rechecked), settled_pre=n_pre,
         lease_ttl_s=ttl)


def cfg_membership_resolve():
    """membership_resolve_latency: full reconfiguration cycles per
    second through the membership scenario machinery — durable registry
    record (fsynced, pre-op member set + heal spec), State invoke
    (fsynced members file), and the locked resolve fixed point with its
    heal-mark. This is the per-op overhead a membership nemesis adds to
    a run; the bar is 150 cycles/s (~6.7 ms/cycle — three fsyncs per
    cycle dominate on the container's disk, and one reconfig per ~10 s
    of test time needs ~0.07% of a worker)."""
    import tempfile
    from pathlib import Path

    from jepsen_tpu.fakes import FakeClusterState
    from jepsen_tpu.nemesis import membership
    from jepsen_tpu.nemesis.faults import FaultRegistry

    nodes = [f"n{i}" for i in range(1, 6)]
    n_cycles = 200

    def cycle_all():
        with tempfile.TemporaryDirectory() as tmp:
            st = FakeClusterState(Path(tmp) / "members.json", nodes=nodes,
                                  settle_s=0.0)
            nem = membership.MembershipNemesis(st, poll_interval=3600)
            registry = FaultRegistry(Path(tmp) / "faults.jsonl")
            test = {"nodes": nodes, "_faults": registry}
            for i in range(n_cycles):
                f = "shrink" if i % 2 == 0 else "grow"
                nem.invoke(test, {"type": "info", "f": f, "value": "n5"})
            assert nem.pending_count() == 0
            assert registry.unhealed() == []
            registry.close()

    cycle_all()  # warm imports/allocators
    _, times = _trials(cycle_all, 3)
    med, extras = _spread(times, n_cycles)
    rate = n_cycles / med
    emit("membership_resolve_latency", rate, "cycles/s", rate / 150.0,
         cycle="record+invoke+resolve+heal", n_cycles=n_cycles,
         per_cycle_ms=round(1000.0 * med / n_cycles, 3), **extras)


def cfg_ckpt():
    """Resumable-check cost/benefit (doc/robustness.md "Resumable
    checks and the elastic mesh"), riding the segmented 300s metric's
    path at a bench-friendly scale:

    * ``ckpt_overhead_frac`` — segmented matrix chain with a durable
      checkpoint persisted after EVERY segment (interval 0: the
      worst-case write cadence; production's default is one write per
      5 s) vs the plain chain. Bar: <= 5% overhead.
    * ``resume_savings_frac`` — the same chain resumed from a
      checkpoint at the 50% cut vs checked from zero. The checkpoint
      is authored through the same carry/fingerprint machinery the
      checker uses, so the resumed run exercises real validation
      (hash + config match), not a mock.
    """
    import tempfile
    from pathlib import Path

    from jepsen_tpu.checker.checkpoint import (
        CheckpointStore, encode_array, stream_prefix_hash,
    )
    from jepsen_tpu.ops.jitlin import (
        _bucket, _slice_stream, matrix_check_segmented,
        matrix_segmented_config,
    )

    # multichip-bench shapes (3-way concurrency, rand-int-5 domain →
    # MV = 64): big enough to segment, small enough that the CPU
    # container's matrix kernel finishes the trial matrix promptly
    n_procs, n_values = 3, 5
    seg_events = int(os.environ.get("BENCH_CKPT_SEG_EVENTS",
                                    str(1 << 13)))
    n_segs = int(os.environ.get("BENCH_CKPT_SEGMENTS", "6"))
    seg_blocks = seg_events // (2 * n_procs)
    seg_events = seg_blocks * 2 * n_procs
    stream = _block_stream(seg_blocks * n_segs, n_procs=n_procs,
                           n_values=n_values)
    kw = dict(num_states=n_values + 1, n_slots=n_procs,
              max_segment=seg_events)

    def plain():
        a, _, ix, _ = matrix_check_segmented(stream, **kw)
        assert a and not ix

    _warm_timed("ckpt", plain)
    _, t_plain = _trials(plain, 3)
    wall_plain = _median(t_plain)

    with tempfile.TemporaryDirectory() as tmp:
        def with_ckpt():
            store = CheckpointStore(Path(tmp) / "check.ckpt",
                                    interval_s=0.0, resume=False)
            a, _, ix, _ = matrix_check_segmented(stream, ckpt=store,
                                                 **kw)
            assert a and not ix
            assert store.writes >= n_segs - 1, store.writes

        _, t_ckpt = _trials(with_ckpt, 3)
        wall_ckpt = _median(t_ckpt)

        # author a 50%-cut checkpoint through the real carry machinery
        half = seg_blocks * (n_segs // 2) * 2 * n_procs
        carries = []
        a, _, ix, _ = matrix_check_segmented(
            _slice_stream(stream, 0, half), carry_sink=carries.append,
            **kw)
        assert a and not ix and carries
        S, V = n_procs, _bucket(n_values + 1, floor=8)
        resume_path = Path(tmp) / "resume.ckpt"
        CheckpointStore(resume_path, resume=True).save({
            "kind": "matrix",
            "config": matrix_segmented_config(S, V, 0, n_values + 1,
                                              seg_events, None, None),
            "events_done": half, "segment": n_segs // 2,
            "prefix_hash": stream_prefix_hash(stream, half),
            "carry": {"tot0": encode_array(np.asarray(
                carries[-1]["tot0"]))},
        })

        def resumed():
            store = CheckpointStore(resume_path, interval_s=None,
                                    resume=True)
            a2, _, ix2, _ = matrix_check_segmented(stream, ckpt=store,
                                                   **kw)
            assert a2 and not ix2

        _warm_timed("ckpt_resume", resumed)
        _, t_res = _trials(resumed, 3)
        wall_res = _median(t_res)

    overhead = max(0.0, wall_ckpt / max(wall_plain, 1e-9) - 1.0)
    savings = max(0.0, 1.0 - wall_res / max(wall_plain, 1e-9))
    emit("ckpt_overhead_frac", overhead, "frac",
         0.05 / max(overhead, 1e-6),
         plain_wall_s=round(wall_plain, 4),
         ckpt_wall_s=round(wall_ckpt, 4), segments=n_segs,
         segment_events=seg_events, write_cadence="every-segment",
         path="matrix-segmented")
    emit("resume_savings_frac", savings, "frac", savings / 0.33,
         full_wall_s=round(wall_plain, 4),
         resumed_wall_s=round(wall_res, 4), resume_cut_frac=0.5,
         path="matrix-segmented")


def cfg_trace():
    """trace_overhead_frac: the causal trace's cost on the hot path
    (doc/observability.md "Causal trace") — the REAL generator
    interpreter (threads, queues, deadlines) over the standard register
    workload, measured three ways:

    * untraced — NULL tracer (the default run minus the flight
      recorder): the anchor;
    * flight-recorder only — the always-on default configuration; bar
      <= 1% over the anchor;
    * causal trace — streaming Perfetto trace.json sink + flight
      recorder (the run-wide span stream this subsystem adds); bar
      <= 5%.

    The pre-existing per-client span log (tracing.py's trace.jsonl +
    TracedClient, which ``--trace`` also turns on) is measured
    separately as ``client_span_overhead_frac`` — it predates the
    causal trace and its cost must not hide inside (or be blamed on)
    the new stream's number.

    Best-of-N trials on both sides: the interpreter's wall is
    thread-scheduling noisy, and the overhead question is about the
    added per-op work, which the best runs isolate."""
    import tempfile
    from pathlib import Path

    import jepsen_tpu.generator as gen
    from jepsen_tpu import trace as trace_mod
    from jepsen_tpu import tracing
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.generator import interpreter

    n = int(os.environ.get("BENCH_TRACE_OPS", "4000"))
    trials = 5

    def build(wrap=None):
        db = AtomDB()
        client = AtomClient(db)
        if wrap is not None:
            client = wrap(client)
        return noop_test(
            name="bench-trace", db=db, client=client, concurrency=5,
            checker=None,
            generator=gen.clients(gen.limit(n, gen.mix([
                gen.repeat({"f": "read"}),
                lambda test, ctx: {"f": "write",
                                   "value": ctx.rng.randrange(5)},
            ]))))

    def measure(make_tracer, wrap=None) -> float:
        best = float("inf")
        for _ in range(trials):
            test = build(wrap)
            tracer = make_tracer()
            with trace_mod.use(tracer):
                t0 = time.perf_counter()
                history = interpreter.run(test)
                dt = time.perf_counter() - t0
            tracer.close()
            n_inv = sum(1 for op in history if op["type"] == "invoke")
            assert n_inv == n, n_inv
            best = min(best, dt)
        return best

    with tempfile.TemporaryDirectory() as tmp:
        t_plain = measure(lambda: trace_mod.NULL_TRACER)
        t_flight = measure(lambda: trace_mod.RunTracer(
            flight=trace_mod.FlightRecorder(
                trace_mod.DEFAULT_FLIGHT_EVENTS)))
        runs = [0]

        def traced_tracer():
            runs[0] += 1
            return trace_mod.RunTracer(
                perfetto=trace_mod.PerfettoSink(
                    Path(tmp) / f"trace-{runs[0]}.json"),
                flight=trace_mod.FlightRecorder(
                    trace_mod.DEFAULT_FLIGHT_EVENTS))

        t_traced = measure(traced_tracer)

        legacy = tracing.Tracer(str(Path(tmp) / "trace.jsonl"))
        t_client = measure(lambda: trace_mod.NULL_TRACER,
                           wrap=lambda c: tracing.TracedClient(c, legacy))
        legacy.close()

    overhead = max(0.0, t_traced / max(t_plain, 1e-9) - 1.0)
    flight_overhead = max(0.0, t_flight / max(t_plain, 1e-9) - 1.0)
    client_overhead = max(0.0, t_client / max(t_plain, 1e-9) - 1.0)
    emit("trace_overhead_frac", overhead, "frac",
         0.05 / max(overhead, 1e-6),
         flight_overhead_frac=round(flight_overhead, 4),
         client_span_overhead_frac=round(client_overhead, 4),
         untraced_wall_s=round(t_plain, 4),
         flight_wall_s=round(t_flight, 4),
         traced_wall_s=round(t_traced, 4),
         client_span_wall_s=round(t_client, 4),
         ops=n, trials=trials,
         untraced_ops_per_sec=round(n / t_plain, 1),
         traced_ops_per_sec=round(n / t_traced, 1))


def cfg_lint():
    """lint_wall_s: full-tree static-analysis wall clock — the cost of
    the tier-1 self-lint gate (tests/test_lint_clean.py) with every
    rule enabled, including the interprocedural thread-edge call graph,
    lock-order deadlock detection, and durability-protocol passes. The
    bar: < 60 s cold (fresh AST cache), < 30 s warm (the steady-state
    cost every tier-1 run actually pays). A regression here silently
    eats the tier-1 budget, so it gets a metric line like any kernel.
    ``vs_baseline`` is bar/actual for the warm number (>1 = under
    bar)."""
    from pathlib import Path

    from jepsen_tpu.analysis import lint as lint_mod
    from jepsen_tpu.analysis.lint import astcache, csrc

    root = Path(__file__).resolve().parent
    pkg = root / "jepsen_tpu"

    def run():
        rep = lint_mod.lint_paths([str(pkg)],
                                  baseline=str(root / "lint-baseline.txt"),
                                  root=str(root))
        assert rep.findings == [], [f.render() for f in rep.findings]
        return rep

    astcache._CACHE.clear()
    csrc._CACHE.clear()
    t0 = time.perf_counter()
    rep = run()
    cold_s = time.perf_counter() - t0
    _, times = _trials(run, 3)
    warm_s = _median(times)
    assert cold_s < 60.0, f"cold full-tree lint took {cold_s:.1f}s"
    assert warm_s < 30.0, f"warm full-tree lint took {warm_s:.1f}s"
    emit("lint_wall_s", warm_s, "s", 30.0 / max(warm_s, 1e-9),
         cold_s=round(cold_s, 2), files=rep.files,
         rules=len(lint_mod.RULE_NAMES), trials=len(times))

    # the JTN family alone over the shipped C sources — the acceptance
    # bar is < 10 s warm for the native rule pass
    def run_native():
        rep = lint_mod.lint_paths([str(pkg / "native")], baseline=False,
                                  root=str(root), rules=["jtn-*"])
        assert rep.findings == [], [f.render() for f in rep.findings]
        return rep

    csrc._CACHE.clear()
    t0 = time.perf_counter()
    nrep = run_native()
    n_cold_s = time.perf_counter() - t0
    _, ntimes = _trials(run_native, 3)
    n_warm_s = _median(ntimes)
    assert n_warm_s < 10.0, f"warm native lint took {n_warm_s:.1f}s"
    emit("lint_native_wall_s", n_warm_s, "s", 10.0 / max(n_warm_s, 1e-9),
         cold_s=round(n_cold_s, 3), files=nrep.files,
         rules=len(lint_mod.C_RULES), trials=len(ntimes))


def cfg_fuzz():
    """fuzz_trials_per_sec + fuzz_coverage_edges_per_1k_trials: the
    schedule fuzzer's throughput and its guidance signal. Two hunts at
    an identical 300-trial budget over a bug-free target (inline pool,
    no early stop): one coverage-guided, one blind-random. Throughput
    is the guided hunt's trials/wall. The guidance bar rides the DEEP
    edges — fault×op interleavings whose active mask composes >= 3
    fault kinds, the class the corpus splicer exists to reach (blind
    triple-overlaps are rare by construction): guided must find >= 2x
    the blind count at equal trials. ``vs_baseline`` on the edges
    metric is ratio/2 (>1 = over bar). Fully deterministic given the
    seed, so the ratio is a regression pin, not a flake."""
    import shutil
    import tempfile

    from jepsen_tpu.fuzz.hunt import Hunter

    trials, seed = 300, 1

    def deep(edges):
        # "op:<kind+kind+...>:<f>" edges with a 3-way composed mask
        return sum(1 for e in edges
                   if e.startswith("op:")
                   and len(e.split(":")[1].split("+")) >= 3)

    tmp = tempfile.mkdtemp(prefix="jepsen-bench-fuzz-")
    try:
        res = {}
        for mode in ("guided", "blind"):
            h = Hunter(os.path.join(tmp, mode), trials=trials,
                       pool_workers=0, trial_ops=120, seed=seed,
                       guided=(mode == "guided"), bug_spec=None,
                       batch_size=25, stop_on_first=False)
            t0 = time.perf_counter()
            summary = h.run()
            wall = time.perf_counter() - t0
            assert summary["trials"] == trials, summary
            assert summary["outcomes"].get("error", 0) == 0, (
                f"{mode} hunt hit errored trials: {summary['outcomes']}")
            res[mode] = {"wall": wall, "edges": set(h.covmap.edges)}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    g, b = res["guided"], res["blind"]
    g_deep, b_deep = deep(g["edges"]), deep(b["edges"])
    ratio = g_deep / max(b_deep, 1)
    assert ratio >= 2.0, (
        f"guided found {g_deep} deep edges vs blind {b_deep} at "
        f"{trials} trials — guidance bar is >= 2x")
    trials_per_sec = trials / g["wall"]
    emit("fuzz_trials_per_sec", trials_per_sec, "trials/s",
         trials_per_sec / 20.0, trials=trials, seed=seed,
         guided_wall_s=round(g["wall"], 2),
         blind_wall_s=round(b["wall"], 2))
    emit("fuzz_coverage_edges_per_1k_trials",
         len(g["edges"]) * 1000.0 / trials, "edges/1k",
         ratio / 2.0, deep_edges_guided=g_deep, deep_edges_blind=b_deep,
         edges_guided=len(g["edges"]), edges_blind=len(b["edges"]),
         guided_vs_blind_deep_ratio=round(ratio, 2))


def cfg_fuzz_native():
    """fuzz_native_execs_per_sec: the differential WAL-parser fuzz
    harness's throughput against the plain -O3 build (the san build's
    ~2-5x tax is the lane's, not the harness's), plus corpus coverage —
    every checked-in seed and every mutation operator must have fired
    within the budget (a silently dead operator means a coverage hole,
    not a perf win). Zero divergences is an assertion, not a metric:
    a C-vs-Python disagreement fails the bench like any broken kernel.
    Deterministic under the fixed seed."""
    import shutil
    import tempfile

    from jepsen_tpu.fuzz import native as fuzz_native

    execs, seed = 4000, 1
    tmp = tempfile.mkdtemp(prefix="jepsen-bench-fuzz-native-")
    try:
        res = fuzz_native.run_fuzz(execs, seed=seed, san=False,
                                   store_dir=tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if res["status"] == "no-native":
        print("[bench] fuzz_native skipped: no native build", flush=True)
        return
    assert res["divergences"] == 0, res["artifacts"]
    seeds_hit = len(res["seed_coverage"])
    ops_hit = len(res["operator_coverage"])
    assert seeds_hit == len(fuzz_native.SEEDS), res["seed_coverage"]
    assert ops_hit == len(fuzz_native.OPERATORS), res["operator_coverage"]
    rate = res["execs_per_s"]
    emit("fuzz_native_execs_per_sec", rate, "execs/s", rate / 1000.0,
         execs=res["execs"], seed=seed,
         corpus_seeds_covered=seeds_hit,
         operators_covered=ops_hit,
         ops_parsed=res["ops_parsed"], torn_lines=res["torn_lines"],
         wall_s=round(res["elapsed_s"], 2))


def cfg_headline() -> float:
    """The headline, printed last: a 10k-op single-register history on
    device vs the reference's 1 h CPU knossos timeout.

    The history uses the reference workload's value domain —
    linearizable_register.clj writes ``(rand-int 5)`` — and the
    measurement takes the PRODUCTION dispatch (checker/linearizable.py
    device path): the block-composed transfer-matrix kernel settles the
    small-domain verdict exactly, with the event scan kept as the
    diagnostics path. r1-r2 measured the event scan over an unfaithful
    100-value domain; the scan number stays in the extras for
    continuity. Returns the measured device event rate (drives the scale
    config default)."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops, pad_streams
    from jepsen_tpu.ops.jitlin import (JitLinKernel, _bucket, matrix_check,
                                       verdict)

    history = _register_history(N_OPS, n_procs=N_PROCS, seed=42, n_values=5)
    stream = encode_register_ops(history)

    m = _warm_timed("headline",                   # warm-up compile
                    lambda: matrix_check(stream))
    assert m is not None and m[0] and not m[2], (
        "10k-op valid small-domain history must verify on the matrix path")
    _, times = _trials(lambda: matrix_check(stream), 5)
    dt, extras = _spread(times, N_OPS)

    # continuity extra: the event-scan path on the same history
    batch = pad_streams([stream], length=_bucket(len(stream)))
    S = max(1, batch["n_slots"])
    run = JitLinKernel()._get(S, CAPACITY, batched=False,
                              num_states=len(stream.intern))
    args = _device_args(batch)
    _warm_timed("headline_scan", lambda: _force(*run(*args)))
    out, scan_times = _trials(lambda: _force(*run(*args)), 5)
    alive, died, ovf, peak = out
    assert verdict(bool(alive), bool(ovf)) is True, (
        f"10k-op valid history must verify (died at event {int(died)}, "
        f"overflow={bool(ovf)})")
    scan_dt, _ = _spread(scan_times, N_OPS)

    ops_per_sec = N_OPS / dt
    emit("single_register_ops_verified_per_sec_10k", ops_per_sec, "ops/s",
         ops_per_sec / BASELINE_OPS_PER_SEC, value_domain=5,
         algorithm="jitlin-tpu-matrix",
         scan_ops_per_sec=round(N_OPS / scan_dt, 2), **extras)
    return len(stream) / dt


def main() -> None:
    global _TELEMETRY_ON
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    skip = set(filter(None, os.environ.get("BENCH_SKIP", "").split(",")))
    # stage telemetry (compile_s/wall_s/device_peak_mb) uses module
    # helpers only — no registry: bench stages call the kernels directly,
    # below the instrumented checker/interpreter dispatch layers
    _TELEMETRY_ON = "telemetry" not in skip
    device_rate = 50_000.0  # headline's event rate sizes the scaling run

    def guard(name, fn):
        if name in skip:
            return None
        t0 = time.perf_counter()
        try:
            return fn()
        except Exception:
            print(f"[bench] {name} FAILED:", file=sys.stderr)
            traceback.print_exc()
            return None
        finally:
            if _TELEMETRY_ON:
                _stage_note(name, wall_s=round(time.perf_counter() - t0, 2))
                peak = telemetry.device_memory_peak_bytes()
                if peak is not None:
                    _stage_note(name,
                                device_peak_mb=round(peak / 2 ** 20, 1))

    guard("cpu_ref", cfg_cpu_ref_200)
    guard("interpreter_sched", cfg_interpreter_sched)
    guard("wal_ingest", cfg_wal_ingest)
    guard("multikey", cfg_multikey)
    guard("set_full", cfg_set_full)
    guard("elle_50k", cfg_elle_50k)
    guard("ir_amortization", cfg_ir_amortization)
    guard("online_lag", cfg_online_lag)
    guard("membership_resolve", cfg_membership_resolve)
    guard("matrix_kernel", cfg_matrix_kernel)
    guard("explain", cfg_explain)
    guard("multichip", cfg_multichip_scaling)
    guard("ckpt", cfg_ckpt)
    guard("trace", cfg_trace)
    guard("fleet", cfg_fleet_runs_sustained)
    guard("fleet_failover", cfg_fleet_failover)
    guard("lint", cfg_lint)
    guard("fuzz", cfg_fuzz)
    guard("fuzz_native", cfg_fuzz_native)
    device_rate = guard("headline", cfg_headline) or device_rate
    guard("scale", lambda: cfg_scale(device_rate))

    # all lines together at the end (driver tails stdout ~2000 chars);
    # headline last (the driver parses the final line), and a compact
    # every-metric summary right before it so even a short tail
    # recovers every value+ratio (r3 weak #5: verbose extras once
    # pushed 5 of 11 metrics out of the tail)
    headline = "single_register_ops_verified_per_sec_10k"
    summary = {"metric": "bench_summary",
               "all": {r["metric"]: [r["value"], r["vs_baseline"]]
                       for r in _RESULTS}}
    if _STAGE_TELEMETRY:
        summary["telemetry"] = _STAGE_TELEMETRY
    for line in [r for r in _RESULTS if r["metric"] != headline]:
        print(json.dumps(line), flush=True)
    print(json.dumps(summary), flush=True)
    for line in [r for r in _RESULTS if r["metric"] == headline]:
        print(json.dumps(line), flush=True)


def _multichip_child() -> None:
    """Child-process entry for cfg_multichip_scaling: the parent set
    JAX_PLATFORMS=cpu + the forced-device-count flag BEFORE this
    interpreter started; override any site-level platform pinning the
    same way conftest does, measure, print ONE json line."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — env var alone may suffice
        pass
    print(json.dumps(_multichip_measure()), flush=True)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if "--multichip-child" in sys.argv:
        _multichip_child()
    elif "--fleet-child" in sys.argv:
        print(json.dumps(_fleet_measure()), flush=True)
    else:
        main()
