"""Worker script for the two-process jax.distributed test: joined by
tests/test_distributed.py as two real OS processes, each with 4 virtual
CPU devices, forming one 8-device global mesh spanning processes.

Runs the sharded trim across the process-spanning mesh on a graph whose
edges are split between the processes, and checks the replicated result
against the known answer. Prints DIST-OK on success (the parent asserts
it). Run directly:

    python tests/distributed_worker.py <process_id> <num_processes> <port>
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

proc_id, n_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the image's sitecustomize can pre-import jax and pin the platform list;
# force cpu before the distributed runtime initializes (conftest pattern)
try:
    import jax as _jax_pre

    _jax_pre.config.update("jax_platforms", "cpu")
except Exception:
    pass

from jepsen_tpu.parallel import distributed as dist  # noqa: E402

dist.initialize(f"127.0.0.1:{port}", n_procs, proc_id, local_devices=4)

import jax  # noqa: E402

assert jax.process_count() == n_procs, jax.process_count()
assert jax.device_count() == 4 * n_procs, jax.device_count()
assert jax.local_device_count() == 4, jax.local_device_count()

mesh = dist.global_mesh()

# global graph over 8 nodes: 0->1->2->0 (cycle) plus chains 3->4->5, 6->7.
# process 0 holds the cycle's edges, process 1 the acyclic tails — the
# verdict needs BOTH shards' degrees, so a psum that failed to cross
# processes would get it wrong.
if proc_id == 0:
    local_src = [0, 1, 2, 3]
    local_dst = [1, 2, 0, 4]
else:
    local_src = [4, 6, 7]
    local_dst = [5, 7, 6]

mask = dist.trim_to_cycles_distributed(8, local_src, local_dst, mesh)
expected = [True, True, True, False, False, False, True, True]
assert mask.tolist() == expected, mask.tolist()

# batch_check across processes: keys split between hosts, verdicts
# allgathered — every process must see the full result list, including
# the one injected invalid key
from jepsen_tpu.checker.linear_encode import encode_register_ops  # noqa: E402


def _reg_history(writes, bad_read=None):
    h = []
    for i, v in enumerate(writes):
        h.append({"type": "invoke", "process": 0, "f": "write", "value": v})
        h.append({"type": "ok", "process": 0, "f": "write", "value": v})
    if bad_read is not None:
        h.append({"type": "invoke", "process": 1, "f": "read", "value": None})
        h.append({"type": "ok", "process": 1, "f": "read", "value": bad_read})
    return h


streams = [encode_register_ops(_reg_history([1, 2, 3])) for _ in range(7)]
streams.append(encode_register_ops(_reg_history([1, 2, 3], bad_read=99)))
results = dist.batch_check_distributed(streams)
assert len(results) == 8
assert all(r[0] for r in results[:7]), results
assert results[7][0] is False, results[7]

print(f"DIST-OK {proc_id}", flush=True)
