"""History-IR differential tier (``-m ir``): one device-resident
columnar IR, encoded once, every checker a zero-copy view.

Pins ISSUE 11's acceptance bars:

* IR-derived views == legacy encoder outputs **bit-identically** —
  register EventStream (batch view vs the live incremental encoder),
  Elle builder columns, the independent per-key split, the set-full
  membership encode — on register / list-append / wr / independent
  histories including planted anomalies;
* the WAL-streamed incremental build is bit-identical to the batch
  build, survives torn-WAL resume, and REJECTS a diverged stream;
* a multi-checker run encodes exactly once (the memoized-view
  identity);
* the ``history.npz`` sidecar round-trips the IR (canonical columns +
  codec-encoded intern table) and a corrupt sidecar falls back to the
  jsonl visibly (``store_sidecar_load_failures_total``);
* the new knobs preflight-validate and the ``no-host-roundtrip`` lint
  rule fires/waives.
"""
import json
import random

import numpy as np
import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.history import ColumnarHistory, Intern
from jepsen_tpu.history_ir import (
    DeviceHistory, IncrementalHistoryBuilder, WalStreamer, of,
)
from jepsen_tpu.history_ir import sidecar, views
from jepsen_tpu.history_ir.builder import LiveRegisterEncoder

pytestmark = pytest.mark.ir

CANONICAL = ("types", "processes", "fs", "times", "indices",
             "completion_of", "invocation_of")
STREAM_COLS = ("kind", "slot", "f", "a", "b", "op_index")


def _register_history(n, seed=7, planted_at=None, n_procs=4):
    from __graft_entry__ import _register_history as gen
    h = gen(n, n_procs=n_procs, seed=seed, n_values=5)
    if planted_at is not None:
        for i, op in enumerate(h):
            if i >= planted_at and op.get("type") == "ok" \
                    and op.get("f") == "read" \
                    and op.get("value") is not None:
                op["value"] = op["value"] + 10_000
                return h, i
        raise AssertionError("no read to corrupt")
    return h, None


def _messy_register_history(n=120, seed=3):
    """Fuzzed register history with fails, infos, crashed reads,
    nemesis ops, and an open tail — every drop rule the encoder has."""
    rng = random.Random(seed)
    h = []
    open_p = {}
    for i in range(n):
        p = rng.randrange(5)
        if p in open_p:
            f, v = open_p.pop(p)
            typ = rng.choice(["ok", "ok", "ok", "fail", "info"])
            val = (rng.randrange(5) if typ == "ok" and f == "read"
                   else v)
            h.append({"type": typ, "process": p, "f": f, "value": val,
                      "time": i})
        elif rng.random() < 0.1:
            h.append({"type": "info", "process": "nemesis", "f": "kill",
                      "value": None, "time": i})
        else:
            f = rng.choice(["read", "write", "cas"])
            v = (None if f == "read" else rng.randrange(5) if f == "write"
                 else [rng.randrange(5), rng.randrange(5)])
            open_p[p] = (f, v)
            h.append({"type": "invoke", "process": p, "f": f, "value": v,
                      "time": i})
    return h  # some invokes stay open: the crashed-tail rules apply


def _elle_history(n_txns=60, anomalous=False):
    h, t = [], 0
    for i in range(n_txns):
        k = i % 3
        seen = list(range(k, i + 1, 3))
        h.append({"type": "invoke", "process": i % 4,
                  "value": [["append", k, i], ["r", k, None]], "time": t})
        h.append({"type": "ok", "process": i % 4,
                  "value": [["append", k, i], ["r", k, seen]], "time": t + 1})
        t += 2
    if anomalous:
        # a wr 2-cycle on fresh keys (G1c)
        for (p, ka, kb, va, vb) in [(8, 100, 101, 9000, 9001)]:
            h.append({"type": "invoke", "process": p,
                      "value": [["append", ka, va], ["r", kb, None]],
                      "time": t})
            h.append({"type": "ok", "process": p,
                      "value": [["append", ka, va], ["r", kb, [vb]]],
                      "time": t + 1})
            h.append({"type": "invoke", "process": p + 1,
                      "value": [["append", kb, vb], ["r", ka, None]],
                      "time": t + 2})
            h.append({"type": "ok", "process": p + 1,
                      "value": [["append", kb, vb], ["r", ka, [va]]],
                      "time": t + 3})
    return h


@pytest.fixture
def registry():
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


# ---------------------------------------------------------------------------
# IR core: promotion + incremental build
# ---------------------------------------------------------------------------

def test_device_history_promotes_columnar():
    h = _messy_register_history()
    dh = DeviceHistory.from_ops(h)
    base = ColumnarHistory.from_ops(h)
    assert isinstance(dh, ColumnarHistory)
    for name in CANONICAL:
        assert np.array_equal(getattr(dh, name), getattr(base, name)), name
    # value ids round-trip through the intern table
    assert dh.value_ids is not None and len(dh.value_ids) == len(h)
    for op, vid in zip(h, dh.value_ids.tolist()):
        assert dh.intern.value(vid) == op.get("value")


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_incremental_builder_bit_identical(seed):
    h = _messy_register_history(seed=seed)
    b = IncrementalHistoryBuilder()
    b.extend(h)
    inc, ref = b.snapshot(), DeviceHistory.from_ops(h)
    for name in CANONICAL + ("value_ids",):
        assert np.array_equal(getattr(inc, name), getattr(ref, name)), name
    assert inc.f_table == ref.f_table
    assert inc.intern.table == ref.intern.table


def test_wal_streamed_builder_torn_resume(tmp_path):
    """Chunked WAL writes with an in-progress (unterminated) line midway:
    the tailer resumes past it once completed, and the streamed IR is
    bit-identical to the batch build."""
    h = _messy_register_history(n=80, seed=9)
    wal = tmp_path / "history.wal.jsonl"
    s = WalStreamer(wal, poll_interval_s=0.01)
    # drive the tailer by hand (deterministic: no thread timing)
    lines = [json.dumps(op) for op in h]
    with open(wal, "w") as f:
        f.write("\n".join(lines[:30]) + "\n")
        f.flush()
        s.builder.absorb_wal(s.tailer)
        assert len(s.builder) == 30
        f.write(lines[30][:10])       # torn in-progress line
        f.flush()
        s.builder.absorb_wal(s.tailer)
        assert len(s.builder) == 30   # offset must NOT advance past it
        f.write(lines[30][10:] + "\n")
        f.write("\n".join(lines[31:]) + "\n")
        f.flush()
        s.builder.absorb_wal(s.tailer, final=True)
    assert len(s.builder) == len(h)
    s._stop.set()
    dh = s.snapshot_for(h)
    assert dh is not None
    ref = DeviceHistory.from_ops(h)
    for name in CANONICAL + ("value_ids",):
        assert np.array_equal(getattr(dh, name), getattr(ref, name)), name
    # a diverged history is rejected, never adopted
    bad = [dict(op) for op in h]
    bad[5]["value"] = "not-what-ran"
    assert s.snapshot_for(bad) is None


def test_ir_stream_from_wal_end_to_end(tmp_path, caplog):
    """core.run with ir_stream_from_wal: the analyze-time IR is adopted
    from the stream (log line), the verdict is unchanged."""
    import logging

    from jepsen_tpu import core
    from test_core import cas_test
    test, _ = cas_test(str(tmp_path), n_ops=120, concurrency=4)
    test["ir_stream_from_wal"] = True
    with caplog.at_level(logging.INFO, logger="jepsen.history_ir"):
        result = core.run(test)
    assert result["results"]["linear"]["valid?"] is True
    assert any("adopted WAL-streamed history IR" in r.message
               for r in caplog.records), \
        "analyze did not adopt the streamed IR"


# ---------------------------------------------------------------------------
# views == legacy encoders, bit-identically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [1, 4])
@pytest.mark.parametrize("init_value", [None, 0])
def test_register_stream_view_bit_identical(seed, init_value):
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    h = _messy_register_history(seed=seed)
    intern = Intern()
    if init_value is not None:
        intern.id(init_value)
    legacy = encode_register_ops(h, intern=intern)
    view = views.register_stream(DeviceHistory.from_ops(h),
                                 init_value=init_value)
    for name in STREAM_COLS:
        assert np.array_equal(getattr(legacy, name), getattr(view, name)), name
    assert legacy.n_slots == view.n_slots
    assert legacy.n_ops == view.n_ops
    assert legacy.intern.table == view.intern.table


@pytest.mark.parametrize("seed", [2, 5, 8])
def test_register_view_vs_live_incremental_encoder(seed):
    """The batch view vs the genuinely separate incremental state
    machine the live sessions use — two implementations, one event
    sequence."""
    h = _messy_register_history(seed=seed)
    enc = LiveRegisterEncoder(Intern())
    for op in h:
        enc.add(op)
    enc.finalize()
    live = enc.stream.to_event_stream()
    view = views.register_stream(DeviceHistory.from_ops(h))
    for name in STREAM_COLS:
        assert np.array_equal(getattr(live, name), getattr(view, name)), name
    assert live.n_slots == view.n_slots
    assert live.intern.table == view.intern.table


@pytest.mark.parametrize("anomalous", [False, True])
def test_elle_view_matches_legacy_and_oracle(anomalous):
    from jepsen_tpu.elle import list_append
    h = _elle_history(anomalous=anomalous)
    test = {"name": "elle-ir"}
    with_ir = list_append.check(h, accelerator="auto", ir=of(test, h))
    legacy = list_append.check(h, accelerator="auto")
    oracle = list_append.check(h, accelerator="cpu")
    assert with_ir["valid?"] == legacy["valid?"] == oracle["valid?"] \
        == (not anomalous)
    assert (sorted(with_ir.get("anomaly-types") or [])
            == sorted(legacy.get("anomaly-types") or [])
            == sorted(oracle.get("anomaly-types") or []))
    if anomalous:
        assert "G1c" in with_ir["anomaly-types"]


def test_wr_checker_ir_on_off_identical():
    from jepsen_tpu.workloads import wr as wr_mod
    rng = random.Random(1)
    h, t = [], 0
    for i in range(40):
        k = i % 3
        mops = [["w", k, i], ["r", k, i]]
        h.append({"type": "invoke", "process": i % 4, "f": "txn",
                  "value": [["w", k, None], ["r", k, None]], "time": t})
        h.append({"type": "ok", "process": i % 4, "f": "txn",
                  "value": mops, "time": t + 1})
        t += 2
    chk = wr_mod.checker(accelerator="cpu")
    r_ir = chk.check({"name": "wr-ir"}, h, {})
    r_off = chk.check({"name": "wr-off", "ir_enabled": False}, h, {})
    assert r_ir["valid?"] == r_off["valid?"]
    assert (r_ir.get("anomaly-types") or []) == \
        (r_off.get("anomaly-types") or [])


def test_linearizable_checker_ir_on_off_identical():
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    h, planted = _register_history(300, seed=5, planted_at=150)
    chk = LinearizableChecker(accelerator="cpu")
    on = chk.check({"name": "ir-on"}, h, {})
    off = chk.check({"name": "ir-off", "ir_enabled": False}, h, {})
    assert on["valid?"] is False and off["valid?"] is False
    assert on["failed-op"] == off["failed-op"]
    assert on["algorithm"] == off["algorithm"]


def test_independent_ir_on_off_identical():
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.independent import checker as lift, tuple_value
    rng = random.Random(2)
    h, t = [], 0
    for i in range(200):
        k, p = rng.randrange(4), rng.randrange(6)
        f = rng.choice(["read", "write"])
        v = None if f == "read" else rng.randrange(5)
        h.append({"type": "invoke", "process": p, "f": f,
                  "value": tuple_value(k, v), "time": t})
        h.append({"type": "ok", "process": p, "f": f,
                  "value": tuple_value(k, v if v is not None else 0),
                  "time": t + 1})
        t += 2
    chk = lift(linearizable(accelerator="cpu"))
    on = chk.check({"name": "ind-on"}, h, {})
    off = chk.check({"name": "ind-off", "ir_enabled": False}, h, {})
    assert on["valid?"] == off["valid?"]
    assert on["count"] == off["count"] == 4
    assert sorted(on["results"]) == sorted(off["results"])


def test_set_full_view_matches_cpu_oracle():
    from jepsen_tpu.checker import SetFullChecker
    rng = random.Random(3)
    h, t, added = [], 0, []
    for i in range(60):
        if rng.random() < 0.7 or not added:
            h.append({"type": "invoke", "process": i % 3, "f": "add",
                      "value": i, "time": t})
            h.append({"type": "ok", "process": i % 3, "f": "add",
                      "value": i, "time": t + 1})
            added.append(i)
        else:
            seen = [x for x in added if rng.random() < 0.9]
            h.append({"type": "invoke", "process": i % 3, "f": "read",
                      "value": None, "time": t})
            h.append({"type": "ok", "process": i % 3, "f": "read",
                      "value": seen, "time": t + 1})
        t += 2
    h.append({"type": "invoke", "process": 0, "f": "read", "value": None,
              "time": t})
    h.append({"type": "ok", "process": 0, "f": "read", "value": added,
              "time": t + 1})
    test = {"name": "set-ir"}
    dev = SetFullChecker(accelerator="auto").check(test, h, {})
    cpu = SetFullChecker(accelerator="cpu").check({"name": "s2"}, h, {})
    for key in ("valid?", "attempt-count", "stable-count", "lost-count",
                "never-read-count", "stale-count"):
        assert dev[key] == cpu[key], key
    # the encode was memoized as an IR view on the shared test map
    assert ("set-full",) in test["_history_ir"].view_keys()


def test_multi_checker_run_encodes_once():
    from jepsen_tpu.checker import compose
    from jepsen_tpu.checker.linearizable import linearizable
    h, _ = _register_history(400, seed=6)
    test = {"name": "compose-ir"}
    chk = compose({"a": linearizable(accelerator="cpu"),
                   "b": linearizable(accelerator="cpu")})
    out = chk.check(test, h, {})
    assert out["a"]["valid?"] is True and out["b"]["valid?"] is True
    ir = test["_history_ir"]
    keys = [k for k in ir.view_keys() if k[0] == "register-stream"]
    assert len(keys) == 1, f"two checkers built {len(keys)} streams"
    # and the view object is shared: a third ask is the same stream
    s1 = views.register_stream(ir)
    assert views.register_stream(ir) is s1


# ---------------------------------------------------------------------------
# sidecar + codec round-trip
# ---------------------------------------------------------------------------

def test_sidecar_roundtrip(tmp_path):
    h = _messy_register_history(n=60, seed=11)
    dh = DeviceHistory.from_ops(h)
    p = tmp_path / "history.npz"
    sidecar.save(p, dh)
    back = sidecar.load(p)
    for name in CANONICAL + ("value_ids",):
        assert np.array_equal(getattr(back, name), getattr(dh, name)), name
    assert back.f_table == dh.f_table
    assert back.intern.table == dh.intern.table  # codec round-trip
    # register shape: the lin_* stream columns rode along
    with np.load(p, allow_pickle=True) as z:
        assert "lin_n_slots" in z.files
        assert "val_table" in z.files


def test_store_write_load_columnar_is_ir(tmp_path):
    from jepsen_tpu import store
    h = _messy_register_history(n=40, seed=12)
    test = {"name": "sc", "start_time": "20260804T000000.000",
            "store_dir": str(tmp_path), "history": h}
    store.write_columnar(test)
    back = store.load_columnar("sc", "20260804T000000.000", str(tmp_path))
    assert isinstance(back, DeviceHistory)
    ref = DeviceHistory.from_ops(h)
    for name in CANONICAL:
        assert np.array_equal(getattr(back, name), getattr(ref, name)), name
    # the run's shared IR was attached (write reused/of built it)
    assert isinstance(test["_history_ir"], DeviceHistory)


def test_codec_intern_roundtrip():
    from jepsen_tpu.history_ir.ir import ValueIntern
    intern = ValueIntern()
    for v in (1, "s", [1, 2], {"a": 1}, None, 2.5, [["append", 3, 4]]):
        intern.id(v)
    rows = sidecar.intern_to_rows(intern)
    assert rows is not None
    back = sidecar.intern_from_rows(rows)
    assert back.table == intern.table
    # non-JSON values: table not serializable, sidecar omits values
    intern.id(object())
    assert sidecar.intern_to_rows(intern) is None


def test_corrupt_sidecar_falls_back_visibly(tmp_path, registry):
    """check_stored over a corrupt history.npz: verdict still produced
    from the jsonl, and store_sidecar_load_failures_total counts it."""
    from jepsen_tpu.checker.linearizable import check_stored
    h, _ = _register_history(80, seed=13)
    d = tmp_path / "runf" / "20260804T000000.000"
    d.mkdir(parents=True)
    with open(d / "history.jsonl", "w") as f:
        for op in h:
            f.write(json.dumps(op) + "\n")
    (d / "history.npz").write_bytes(b"this is not a zip archive")
    out = check_stored("runf", "20260804T000000.000", str(tmp_path),
                       accelerator="cpu")
    assert out["valid?"] is True
    assert "store_sidecar_load_failures_total" in registry.render_prom(), \
        "sidecar failure not counted"


# ---------------------------------------------------------------------------
# knobs + lint
# ---------------------------------------------------------------------------

def test_preflight_ir_knobs():
    from jepsen_tpu.analysis.preflight import _check_knobs
    errs = _check_knobs({"ir_enabled": "banana"})
    assert any(d.code == "KNB001" and d.path == "ir_enabled"
               for d in errs)
    warns = _check_knobs({"ir_stream_from_wal": "true"})
    assert any(d.code == "KNB006" and d.path == "ir_stream_from_wal"
               for d in warns)
    assert not _check_knobs({"ir_enabled": True,
                             "ir_stream_from_wal": False})


def test_lint_no_host_roundtrip(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(
        "import numpy as np\n\n\n"
        "def bad(dh):\n"
        "    cols, n = dh.device_columns()\n"
        "    kind = cols['kind']\n"
        "    return np.asarray(kind)\n\n\n"
        "def waived(dh):\n"
        "    cols, n = dh.device_columns()\n"
        "    return cols['kind'].tolist()  "
        "# lint: ignore[no-host-roundtrip]\n\n\n"
        "def clean(dh):\n"
        "    cols = {'kind': [1]}\n"
        "    return np.asarray(cols['kind'])\n\n\n"
        "def rebound(dh, host):\n"
        "    cols, n = dh.device_columns()\n"
        "    cols = host['summary']\n"
        "    return np.asarray(cols)\n")
    from jepsen_tpu.analysis.lint import lint_paths
    rep = lint_paths([str(mod)], baseline=None)
    hits = [f for f in rep.findings if f.rule == "no-host-roundtrip"]
    assert len(hits) == 1 and hits[0].qualname == "bad", hits


@pytest.mark.mesh
def test_ir_streams_mesh_vs_single_device():
    """IR-derived per-key streams through the sharded batch dispatch:
    mesh and single-device verdicts are bit-identical (the IR feeds the
    `sharded-matrix`/key-sharded lanes without changing results)."""
    import jax

    from jepsen_tpu.parallel import batch_check, get_mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest-forced 8-device virtual mesh")
    streams = []
    for k in range(16):
        h = _messy_register_history(n=60, seed=100 + k)
        streams.append(views.register_stream(DeviceHistory.from_ops(h)))
    single = batch_check(streams, mesh=False)
    mesh = batch_check(streams, mesh=get_mesh(8))
    assert [r[0] for r in single] == [r[0] for r in mesh]
    assert [r[1] for r in single] == [r[1] for r in mesh]


def test_device_columns_placement_and_memo():
    """The canonical-column device placement: whole-array single-device
    staging, mesh padding to a device multiple with inert pad rows, and
    per-mesh memoization."""
    import jax

    h = _messy_register_history(n=30, seed=21)
    dh = DeviceHistory.from_ops(h)
    cols, n = dh.device_columns()
    assert n == len(h)
    assert np.array_equal(np.asarray(cols["types"]), dh.types)
    assert dh.device_columns()[0] is cols  # memoized
    if len(jax.devices()) >= 8:
        from jepsen_tpu.parallel import get_mesh
        mesh = get_mesh(8)
        mcols, mn = dh.device_columns(mesh)
        assert mn == len(h)
        B = np.asarray(mcols["types"]).shape[0]
        assert B % 8 == 0 and B >= len(h)
        assert np.array_equal(np.asarray(mcols["types"])[:mn], dh.types)
        # pad rows are inert: no process, no pairing
        assert (np.asarray(mcols["processes"])[mn:] == -1).all()
        assert (np.asarray(mcols["completion_of"])[mn:] == -1).all()
        assert dh.device_columns(mesh)[0] is mcols


def test_sidecar_intern_positional_on_json_collision(tmp_path):
    """Two distinct intern ids whose canonical-JSON rows collide (tuple
    vs list with equal contents) must keep their positional ids on
    reload — never deduplicate (value_ids would misalign)."""
    h = [
        {"type": "invoke", "process": 0, "f": "w", "value": (1, 2),
         "time": 0},
        {"type": "ok", "process": 0, "f": "w", "value": [1, 2], "time": 1},
        {"type": "invoke", "process": 1, "f": "w", "value": "tail",
         "time": 2},
        {"type": "ok", "process": 1, "f": "w", "value": "tail", "time": 3},
    ]
    dh = DeviceHistory.from_ops(h)
    assert len(dh.intern.table) == 4  # None, (1,2), [1,2], 'tail'
    p = tmp_path / "history.npz"
    sidecar.save(p, dh)
    back = sidecar.load(p)
    assert len(back.intern.table) == len(dh.intern.table)
    assert np.array_equal(back.value_ids, dh.value_ids)
    # every id still resolves to (the JSON image of) its own value
    assert back.intern.value(int(dh.value_ids[2])) == "tail"
    assert back.intern.value(int(dh.value_ids[1])) == [1, 2]


def test_independent_per_key_checks_do_not_evict_run_ir():
    """The lifted checker's per-key sub-checks must not thrash the
    run-level _history_ir slot (they see ir_enabled: False)."""
    from jepsen_tpu.checker import compose
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.independent import checker as lift, tuple_value
    h, t = [], 0
    for i in range(40):
        k, p = i % 3, i % 5
        h.append({"type": "invoke", "process": p, "f": "write",
                  "value": tuple_value(k, i), "time": t})
        h.append({"type": "ok", "process": p, "f": "write",
                  "value": tuple_value(k, i), "time": t + 1})
        t += 2
    test = {"name": "ind-evict"}
    # a Compose of two linearizables defeats _try_batched -> per-key lane
    chk = lift(compose({"a": linearizable(accelerator="cpu"),
                        "b": linearizable(accelerator="cpu")}))
    out = chk.check(test, h, {})
    assert out["valid?"] is True
    ir = test.get("_history_ir")
    assert ir is not None and ir.ops is h, \
        "per-key sub-checks evicted the run-level IR"
    assert ("subhistories",) in ir.view_keys()


def test_malformed_history_falls_back_soft():
    """A history the column encoder can't pack (foreign/hand-edited
    jsonl: non-numeric time, unhashable process) must not crash the
    checkers — of() returns None and the legacy encodes serve."""
    from jepsen_tpu.elle import list_append
    h = [{"type": "info", "process": ["weird"], "time": "bogus"}]
    t = {"name": "malformed"}
    assert of(t, h) is None
    assert "_history_ir" not in t
    assert list_append.check(h, accelerator="auto", ir=None)["valid?"] \
        is True


# -- host ingest spine: native vs Python differentials ------------------
#
# The native WAL tail→parse→IR path (native/columnar_ext.c via
# history_ir.ingest) must be bit-identical to the Python twins over
# every torn-tail shape the tolerant reader defines. Each case runs the
# SAME bytes through m.ingest_chunk and journal.parse_wal_chunk_py and
# compares the full (ops, consumed, torn, truncated) tuple with
# type-exact deep equality (int-vs-float, -0.0, key sets).

def _native_ingest():
    from jepsen_tpu.history_ir import ingest
    m = ingest.native_mod()
    if m is None:
        pytest.skip("native ingest extension unavailable")
    return m, ingest


def _chunk_both(m, ingest, chunk: bytes, final: bool):
    from jepsen_tpu.journal import parse_wal_chunk_py
    got = m.ingest_chunk(chunk, final, ingest._line_fallback,
                         ingest._SKIP, ingest._TORN)
    want = parse_wal_chunk_py(chunk, final=final)
    assert ingest._deep_eq(list(got[0]), list(want[0])), \
        f"ops diverged (final={final})"
    assert got[1] == want[1], "consumed diverged"
    assert got[2] == want[2], "torn count diverged"
    assert bool(got[3]) == bool(want[3]), "truncated flag diverged"
    return want


_L = b'{"type":"ok","f":"write","value":%d,"process":0,"time":%d}\n'


@pytest.mark.parametrize("final", [False, True])
def test_ingest_chunk_torn_final_line(final):
    m, ingest = _native_ingest()
    chunk = (_L % (1, 10)) + (_L % (2, 11)) + b'{"type":"ok","f":"wr'
    ops, consumed, torn, truncated = _chunk_both(m, ingest, chunk, final)
    assert len(ops) == 2
    if final:
        assert truncated and torn == 1 and consumed == len(chunk)
    else:
        # cursor parks at the tear; the next poll resumes there
        assert not truncated and torn == 0
        assert consumed == len(chunk) - len(b'{"type":"ok","f":"wr')


@pytest.mark.parametrize("final", [False, True])
def test_ingest_chunk_torn_interior_line(final):
    m, ingest = _native_ingest()
    chunk = (_L % (1, 10)) + b'{"torn": tru\n' + (_L % (2, 11))
    ops, consumed, torn, truncated = _chunk_both(m, ingest, chunk, final)
    # one tear costs one op, never the lines after it
    assert [o["value"] for o in ops] == [1, 2]
    assert torn == 1 and not truncated and consumed == len(chunk)


def test_ingest_chunk_unicode_and_large_values():
    m, ingest = _native_ingest()
    chunk = (
        b'{"u":"\\ud83d\\ude00 caf\\u00e9","lone":"\\ud800tail"}\n'
        b'{"big":123456789012345678901234567890,"neg":-0,'
        b'"f":1.5e-300,"ninf":-Infinity,"nan":NaN}\n'
        + ('{"raw":"' + "\u00e9\u6f22\U0001f600" + '"}\n').encode()
        + b'{"deep":[[[[[1]]]]],"v":' + str(2**70).encode() + b'}\n')
    ops, consumed, torn, truncated = _chunk_both(m, ingest, chunk, True)
    assert len(ops) == 4 and torn == 0 and not truncated
    assert ops[3]["v"] == 2**70  # arbitrary-precision ints survive


def test_ingest_chunk_whitespace_and_empty_lines():
    m, ingest = _native_ingest()
    chunk = b"\n   \n" + (_L % (5, 20)) + b"\t\n" + (_L % (6, 21))
    ops, consumed, torn, truncated = _chunk_both(m, ingest, chunk, True)
    assert [o["value"] for o in ops] == [5, 6]
    assert torn == 0  # whitespace-only lines skip silently, never count


def test_ingest_chunk_raw_surrogate_bytes_get_replaced():
    """fuzz-native finding (seed 0, exec 271): raw lone-surrogate BYTES
    (CESU-8 \\xed\\xa0\\x80) parsed differently depending on the
    neighbors — the fast whole-array path fed raw bytes to json.loads,
    whose internal decode is surrogatepass, while the tolerant per-line
    path (and WalTailer/read_jsonl_tolerant) decode with replacement.
    Pinned: replacement always, regardless of surrounding lines."""
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py
    line = b'{"f":"\xed\xa0\x80w"}\n'
    want = {"f": "���w"}
    solo = parse_wal_chunk_py(line, final=True)
    noisy = parse_wal_chunk_py(b'{"torn": tr\n' + line, final=True)
    assert solo[0] == [want], "fast path must not surrogatepass"
    assert noisy[0] == [want]
    m = ingest.native_mod()
    if m is not None:
        _chunk_both(m, ingest, line, True)


def test_ingest_chunk_unbalanced_quote_cannot_weld_lines():
    """fuzz-native finding (seed 0, exec 2712): a torn line with an
    unbalanced quote in key position swallowed the fast path's bare
    "," separators into its string literal and welded itself plus the
    following lines into ONE syntactically valid document — so the op
    list depended on where the chunk boundary fell. Pinned: the torn
    lines stay torn, the valid neighbors parse, nothing welds."""
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py
    chunk = b'{"ok":1}\n{"a":1,"k:1}\n\n\nb":2}\n{"ok":2}\n'
    ops, consumed, torn, truncated = parse_wal_chunk_py(chunk, final=True)
    assert ops == [{"ok": 1}, {"ok": 2}]
    assert torn == 2 and consumed == len(chunk) and not truncated
    m = ingest.native_mod()
    if m is not None:
        _chunk_both(m, ingest, chunk, True)


def test_ingest_chunk_array_tear_cannot_weld_structurally():
    """fuzz-native finding (seed 0, exec 90681): a line torn INSIDE a
    numeric array welds through a *structural* position — ",\\n"
    between "...,1" and "37,...]" is legal JSON whitespace, so the
    fast path parsed two torn halves as one valid document while the
    per-line contract (and the C scanner) counts two torn lines.
    Pinned: element-count-vs-line-count mismatch drops to the
    tolerant path; the halves stay torn."""
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py
    chunk = b'{"f":"txn","value":[0,1\n37,2],"time":9}\n'
    ops, consumed, torn, truncated = parse_wal_chunk_py(chunk, final=True)
    assert ops == [], "array-context weld must not produce an op"
    assert torn == 2 and consumed == len(chunk) and not truncated
    m = ingest.native_mod()
    if m is not None:
        _chunk_both(m, ingest, chunk, True)


def test_ingest_chunk_multi_document_line_is_torn():
    """The dual of the weld class: ONE line holding two documents
    ("{...},{...}", a mid-line splice shape) parsed as two array
    elements on the fast path, where the per-line contract says one
    torn line (json.loads: Extra data). Same count-mismatch guard."""
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py
    chunk = b'{"ok":1}\n{"a":1},{"b":2}\n{"ok":2}\n'
    ops, consumed, torn, truncated = parse_wal_chunk_py(chunk, final=True)
    assert ops == [{"ok": 1}, {"ok": 2}]
    assert torn == 1 and consumed == len(chunk) and not truncated
    m = ingest.native_mod()
    if m is not None:
        _chunk_both(m, ingest, chunk, True)


def test_wal_tailer_resume_from_offset_prefix_sha(tmp_path):
    """WalTailer.seek's (offset, prefix_sha256) resume token advances
    identically whether the polls ran native or pure-Python — a
    receiver that restarts onto the other path resumes at the same op."""
    import hashlib
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "history.wal.jsonl"
    body = b"".join(_L % (i, 100 + i) for i in range(50))
    p.write_bytes(body[: len(body) - 7])  # mid-line tear at the tail

    ingest.reset()
    tailers = {}
    for mode, env in (("native", "1"), ("python", "0")):
        import os as _os
        old = _os.environ.get("JEPSEN_TPU_INGEST_NATIVE")
        _os.environ["JEPSEN_TPU_INGEST_NATIVE"] = env
        try:
            ingest.reset()
            t = WalTailer(p)
            ops = t.poll()
            tailers[mode] = (len(ops), t.offset, t.prefix_sha())
        finally:
            if old is None:
                _os.environ.pop("JEPSEN_TPU_INGEST_NATIVE", None)
            else:
                _os.environ["JEPSEN_TPU_INGEST_NATIVE"] = old
            ingest.reset()
    assert tailers["native"] == tailers["python"]
    n_ops, off, sha = tailers["native"]
    assert n_ops == 49  # the torn tail op is parked, not delivered
    assert sha == hashlib.sha256(body[:off]).hexdigest()
    # resume a FRESH tailer from the recorded token: identical pickup
    t2 = WalTailer(p)
    t2.seek(off, lines_read=n_ops)
    p.write_bytes(body)  # writer completes the torn line
    more = t2.poll()
    assert [o["value"] for o in more] == [49]


def test_fleet_ingest_feeds_native_parse(tmp_path):
    """The fleet receiver hands verified chunk bytes straight to the
    native parse while they're in memory: the feed consumer sees every
    op exactly once and in order even when a chunk boundary splits a
    line, and the receiver's parse counters match."""
    import hashlib
    from jepsen_tpu.fleet.ingest import IngestServer
    got = []
    srv = IngestServer(tmp_path, registry=telemetry.Registry(),
                       feed=lambda key, ops: got.extend(
                           (key, o["value"]) for o in ops))
    body = b"".join(_L % (i, 100 + i) for i in range(10))
    cut = body.index(b"\n", len(body) // 2) + 30  # mid-line split
    sha = hashlib.sha256()
    off = 0
    for part in (body[:cut], body[cut:]):
        prefix = sha.hexdigest()
        sha.update(part)
        assert srv.append_chunk("run/ts1", off, prefix,
                                sha.hexdigest(), part) is None
        off += len(part)
    assert [v for _, v in got] == list(range(10))
    assert all(k == "run/ts1" for k, _ in got)
    st = srv.parse_stats()["run/ts1"]
    assert st["ops"] == 10 and st["torn"] == 0
