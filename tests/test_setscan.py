"""Differential tests for the set-full membership-matrix device kernel
(jepsen_tpu.ops.setscan, BASELINE config 4) against the pure-Python
per-element walk — the CPU-as-oracle strategy (SURVEY.md §4)."""
import random

from jepsen_tpu import checker as chk


def gen_set_history(rng: random.Random, n_adds=60, n_reads=8,
                    lose=0, stale=0, crash=0):
    """A set history with optional injected loss (acked adds that never
    appear) and staleness (elements that vanish from one mid read)."""
    t = [0]

    def tick():
        t[0] += 1
        return t[0]

    history = []
    acked, lost_els, stale_els, crashed = [], [], [], []
    for v in range(n_adds):
        history.append({"type": "invoke", "process": v % 5, "f": "add",
                        "value": v, "time": tick()})
        r = rng.random()
        if crash and len(crashed) < crash and r < 0.15:
            history.append({"type": "info", "process": v % 5, "f": "add",
                            "value": v, "time": tick()})
            crashed.append(v)
        else:
            history.append({"type": "ok", "process": v % 5, "f": "add",
                            "value": v, "time": tick()})
            if lose and len(lost_els) < lose and r > 0.8:
                lost_els.append(v)
            else:
                acked.append(v)
                if stale and len(stale_els) < stale and 0.4 < r < 0.6:
                    stale_els.append(v)

    visible = set(acked) | set(x for x in crashed if rng.random() < 0.5)
    for i in range(n_reads):
        t0 = tick()
        vs = set(visible)
        if 0 < i < n_reads - 1:
            # a mid-run read that misses the stale elements
            vs -= set(stale_els)
        history.append({"type": "invoke", "process": 7, "f": "read",
                        "value": None, "time": t0})
        history.append({"type": "ok", "process": 7, "f": "read",
                        "value": sorted(vs), "time": tick()})
    return history, lost_els, stale_els


def normalize(r):
    return {k: r[k] for k in ("valid?", "attempt-count", "stable-count",
                              "lost-count", "lost", "never-read-count",
                              "never-read", "stale-count", "stale")}


def test_device_matches_cpu_random():
    rng = random.Random(5)
    for trial in range(12):
        h, lost, stale = gen_set_history(
            rng, n_adds=50, n_reads=6,
            lose=trial % 3, stale=trial % 2, crash=trial % 4)
        for linearizable in (False, True):
            cpu = chk.SetFullChecker(linearizable=linearizable,
                                     accelerator="cpu").check({}, h, {})
            dev = chk.SetFullChecker(linearizable=linearizable,
                                     accelerator="auto").check({}, h, {})
            assert normalize(cpu) == normalize(dev), (
                f"trial {trial} linearizable={linearizable}:\n"
                f"cpu={normalize(cpu)}\ndev={normalize(dev)}")
            if lost:
                assert cpu["valid?"] is False


def test_device_latency_quantiles_close():
    rng = random.Random(11)
    h, _, _ = gen_set_history(rng, n_adds=40, n_reads=5)
    cpu = chk.SetFullChecker(accelerator="cpu").check({}, h, {})
    dev = chk.SetFullChecker(accelerator="auto").check({}, h, {})
    for q, v in cpu["stable-latencies"].items():
        assert abs(dev["stable-latencies"][q] - v) < 1e-3


def test_device_no_reads_unknown():
    h = [{"type": "invoke", "process": 0, "f": "add", "value": 1, "time": 1},
         {"type": "ok", "process": 0, "f": "add", "value": 1, "time": 2}]
    r = chk.SetFullChecker(accelerator="auto").check({}, h, {})
    assert r["valid?"] == "unknown"


def test_device_member_build_rejects_coercible_payloads():
    """The columnar member-matrix fast path must not coerce float/string
    read elements into ints (np.asarray would turn 2.5 into 2, making a
    lost element look present). Device and CPU paths must agree."""
    from jepsen_tpu.checker import SetFullChecker

    history = []
    for v in range(4):
        history.append({"type": "invoke", "process": 0, "f": "add",
                        "value": v, "time": 2 * v})
        history.append({"type": "ok", "process": 0, "f": "add",
                        "value": v, "time": 2 * v + 1})
    # element 2 vanishes from the final read, which instead carries 2.5
    history.append({"type": "invoke", "process": 1, "f": "read",
                    "value": None, "time": 100})
    history.append({"type": "ok", "process": 1, "f": "read",
                    "value": [0, 1, 2.5, 3], "time": 101})
    dev = SetFullChecker(accelerator="tpu").check({}, history, {})
    cpu = SetFullChecker(accelerator="cpu").check({}, history, {})
    assert dev["valid?"] is False and cpu["valid?"] is False
    assert dev["lost"] == cpu["lost"] == [2]


def test_set_full_device_fallback_is_counted(monkeypatch):
    """An auto-mode device failure must fall back loudly: CPU result plus
    a device-fallback marker (a silent fallback hides perf regressions)."""
    from jepsen_tpu.checker import SetFullChecker

    chk = SetFullChecker(accelerator="auto")
    monkeypatch.setattr(SetFullChecker, "_check_device",
                        lambda self, *a: (_ for _ in ()).throw(RuntimeError))
    history = [
        {"type": "invoke", "process": 0, "f": "add", "value": 1, "time": 0},
        {"type": "ok", "process": 0, "f": "add", "value": 1, "time": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": None, "time": 2},
        {"type": "ok", "process": 1, "f": "read", "value": [1], "time": 3},
    ]
    out = chk.check({}, history, {})
    assert out["valid?"] is True
    assert out["device-fallback"] is True
