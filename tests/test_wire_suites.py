"""Wire-protocol tests for the rabbitmq (AMQP 0-9-1), rethinkdb (ReQL),
and aerospike suites: each client is exercised against a scripted
stub server speaking the real framing, plus digest/codec unit tests
and fake-mode lifecycle runs."""
import json
import socket
import struct
import threading

import pytest

from jepsen_tpu.suites import aerospike, rabbitmq, rethinkdb
from jepsen_tpu.suites import _amqp, _reql
from jepsen_tpu.suites._aerospike import key_digest, ripemd160

from conftest import run_fake  # noqa: E402


def serve_once(handler, want_thread=False):
    """Starts a one-connection stub server; returns its port (and the
    server thread when want_thread, so tests can join before asserting
    on state the handler writes after the client's last await)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def go():
        conn, _ = srv.accept()
        try:
            handler(conn)
        finally:
            # graceful close: send FIN, then drain until the client
            # closes. An abrupt close() with unread client bytes still
            # in our receive buffer makes the kernel RST the connection,
            # racing the client's reads of our final responses (seen as
            # a rare ConnectionResetError under load). Real brokers
            # close gracefully; so do we.
            try:
                conn.shutdown(socket.SHUT_WR)
                conn.settimeout(5)
                while conn.recv(65536):
                    pass
            except OSError:
                pass
            conn.close()
            srv.close()

    thread = threading.Thread(target=go, daemon=True)
    thread.start()
    return (port, thread) if want_thread else port


# ---------------------------------------------------------------------------
# AMQP 0-9-1
# ---------------------------------------------------------------------------

def amqp_frame(ftype, channel, payload):
    return (struct.pack(">BHI", ftype, channel, len(payload)) + payload
            + b"\xce")


def amqp_method(channel, cm, args=b""):
    return amqp_frame(1, channel, struct.pack(">HH", *cm) + args)


def read_amqp_frame(f):
    ftype, channel, size = struct.unpack(">BHI", f.read(7))
    payload = f.read(size)
    assert f.read(1) == b"\xce"
    return ftype, channel, payload


def test_amqp_connect_publish_confirm_get():
    """Full AMQP conversation: negotiate, declare, publish-with-confirm,
    get + ack, against a scripted broker."""
    received = {}

    def broker(conn):
        f = conn.makefile("rb")
        assert f.read(8) == b"AMQP\x00\x00\x09\x01"
        conn.sendall(amqp_method(0, _amqp.CONN_START,
                                 bytes([0, 9]) + b"\x00\x00\x00\x00"
                                 + _amqp.longstr(b"PLAIN")
                                 + _amqp.longstr(b"en_US")))
        _, _, payload = read_amqp_frame(f)          # start-ok
        assert payload[:4] == struct.pack(">HH", *_amqp.CONN_START_OK)
        received["auth"] = payload
        conn.sendall(amqp_method(0, _amqp.CONN_TUNE,
                                 struct.pack(">HIH", 2047, 131072, 60)))
        read_amqp_frame(f)                          # tune-ok
        read_amqp_frame(f)                          # connection.open
        conn.sendall(amqp_method(0, _amqp.CONN_OPEN_OK, _amqp.shortstr("")))
        read_amqp_frame(f)                          # channel.open
        conn.sendall(amqp_method(1, _amqp.CHAN_OPEN_OK,
                                 _amqp.longstr(b"")))
        # queue.declare
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.QUEUE_DECLARE_OK,
                                 _amqp.shortstr("jepsen.queue")
                                 + struct.pack(">II", 0, 0)))
        # confirm.select
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.CONFIRM_SELECT_OK))
        # basic.publish + header + body → confirm with basic.ack
        read_amqp_frame(f)                          # publish method
        _, _, header = read_amqp_frame(f)           # content header
        body_size = struct.unpack(">Q", header[4:12])[0]
        _, _, body = read_amqp_frame(f)             # body
        received["body"] = body
        assert len(body) == body_size
        conn.sendall(amqp_method(1, _amqp.BASIC_ACK,
                                 struct.pack(">QB", 1, 0)))
        # basic.get → get-ok + header + body; then client basic.ack
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.BASIC_GET_OK,
                                 struct.pack(">Q", 7) + b"\x00"
                                 + _amqp.shortstr("")
                                 + _amqp.shortstr("jepsen.queue")
                                 + struct.pack(">I", 0)))
        conn.sendall(amqp_frame(2, 1, struct.pack(">HHQH", 60, 0, 2, 0)))
        conn.sendall(amqp_frame(3, 1, b"42"))
        _, _, ack = read_amqp_frame(f)
        received["ack_tag"] = struct.unpack(
            ">Q", ack[4:12])[0]

    port, thread = serve_once(broker, want_thread=True)
    c = _amqp.AmqpConnection("127.0.0.1", port)
    assert b"PLAIN" in received["auth"]
    assert b"\x00guest\x00guest" in received["auth"]
    c.queue_declare("jepsen.queue")
    c.confirm_select()
    assert c.publish("jepsen.queue", b"42") is True
    got = c.get("jepsen.queue")
    assert got is not None
    tag, body = got
    assert tag == 7 and body == b"42"
    c.ack(tag)
    thread.join(timeout=10)  # ack is fire-and-forget; let the broker read it
    c.close()
    assert received["body"] == b"42"
    assert received["ack_tag"] == 7


def test_amqp_channel_close_raises():
    def broker(conn):
        f = conn.makefile("rb")
        f.read(8)
        conn.sendall(amqp_method(0, _amqp.CONN_START,
                                 bytes([0, 9]) + b"\x00\x00\x00\x00"
                                 + _amqp.longstr(b"PLAIN")
                                 + _amqp.longstr(b"en_US")))
        read_amqp_frame(f)
        conn.sendall(amqp_method(0, _amqp.CONN_TUNE,
                                 struct.pack(">HIH", 0, 131072, 0)))
        read_amqp_frame(f)
        read_amqp_frame(f)
        conn.sendall(amqp_method(0, _amqp.CONN_OPEN_OK, _amqp.shortstr("")))
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.CHAN_OPEN_OK, _amqp.longstr(b"")))
        # respond to queue.declare with channel.close 404
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.CHAN_CLOSE,
                                 struct.pack(">H", 404)
                                 + _amqp.shortstr("NOT_FOUND")
                                 + struct.pack(">HH", 50, 10)))
        read_amqp_frame(f)  # client's close-ok

    port = serve_once(broker)
    c = _amqp.AmqpConnection("127.0.0.1", port)
    import pytest
    with pytest.raises(_amqp.AmqpError) as ei:
        c.queue_declare("nope")
    assert ei.value.code == 404
    c.close()


@pytest.mark.slow
def test_rabbitmq_fake_queue_run():
    result = run_fake(rabbitmq.rabbitmq_test)
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# ReQL
# ---------------------------------------------------------------------------

def test_reql_handshake_and_query():
    received = {}

    def server(conn):
        f = conn.makefile("rb")
        magic = struct.unpack("<I", f.read(4))[0]
        assert magic == _reql.V0_4
        key_len = struct.unpack("<I", f.read(4))[0]
        f.read(key_len)
        proto = struct.unpack("<I", f.read(4))[0]
        assert proto == _reql.PROTOCOL_JSON
        conn.sendall(b"SUCCESS\x00")
        token, size = struct.unpack("<QI", f.read(12))
        received["query"] = json.loads(f.read(size).decode())
        resp = json.dumps({"t": _reql.SUCCESS_ATOM, "r": [4]}).encode()
        conn.sendall(struct.pack("<QI", token, len(resp)) + resp)

    port = serve_once(server)
    c = _reql.ReqlConnection("127.0.0.1", port)
    term = _reql.default(
        _reql.get_field(
            _reql.get(_reql.table(_reql.db("jepsen"), "cas",
                                  read_mode="majority"), 5), "val"), None)
    out = c.run(term)
    assert out == 4
    c.close()
    qtype, qterm, _opts = received["query"]
    assert qtype == _reql.START
    # DEFAULT(GET_FIELD(GET(TABLE(DB(jepsen), cas, read_mode), 5), val))
    assert qterm[0] == _reql.DEFAULT
    assert qterm[1][0][0] == _reql.GET_FIELD
    table_term = qterm[1][0][1][0][1][0]
    assert table_term[0] == _reql.TABLE
    assert table_term[2] == {"read_mode": "majority"}


def test_reql_runtime_error_raises():
    def server(conn):
        f = conn.makefile("rb")
        f.read(4)
        key_len = struct.unpack("<I", f.read(4))[0]
        f.read(key_len)
        f.read(4)
        conn.sendall(b"SUCCESS\x00")
        token, size = struct.unpack("<QI", f.read(12))
        f.read(size)
        resp = json.dumps({"t": _reql.RUNTIME_ERROR,
                           "r": ["abort"]}).encode()
        conn.sendall(struct.pack("<QI", token, len(resp)) + resp)

    port = serve_once(server)
    c = _reql.ReqlConnection("127.0.0.1", port)
    import pytest
    with pytest.raises(_reql.ReqlError):
        c.run(_reql.db("x"))
    c.close()


def test_rethinkdb_cas_term_shape():
    """The CAS update lambda must be branch(eq(row.val, old), {...},
    error) wrapped in func (document_cas.clj:95-105)."""
    sent = []

    class FakeConn:
        def run(self, term):
            sent.append(term)
            return {"errors": 0, "replaced": 1}

    c = rethinkdb.RethinkDBClient(node="n1")
    c.conn = FakeConn()
    out = c.invoke({}, {"f": "cas", "type": "invoke", "value": [1, (4, 5)]})
    assert out["type"] == "ok"
    update_term = sent[0]
    assert update_term[0] == _reql.UPDATE
    func_term = update_term[1][1]
    assert func_term[0] == _reql.FUNC
    branch_term = func_term[1][1]
    assert branch_term[0] == _reql.BRANCH
    assert branch_term[1][0][0] == _reql.EQ          # eq(row.val, 4)
    assert branch_term[1][1] == {"val": 5}
    assert branch_term[1][2][0] == _reql.ERROR


def test_rethinkdb_cas_not_replaced_is_fail():
    class FakeConn:
        def run(self, term):
            return {"errors": 1, "replaced": 0,
                    "first_error": "abort"}

    c = rethinkdb.RethinkDBClient(node="n1")
    c.conn = FakeConn()
    out = c.invoke({}, {"f": "cas", "type": "invoke", "value": [1, (4, 5)]})
    assert out["type"] == "fail"


@pytest.mark.slow
def test_rethinkdb_fake_register_run():
    result = run_fake(rethinkdb.rethinkdb_test)
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# Aerospike
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ripemd160_vectors():
    """Published RIPEMD-160 test vectors (Dobbertin et al.)."""
    assert ripemd160(b"").hex() == \
        "9c1185a5c5e9fc54612808977ee8f548b2258d31"
    assert ripemd160(b"abc").hex() == \
        "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
    assert ripemd160(b"message digest").hex() == \
        "5d0689ef49d2fae572b881b123a85ffa21595f36"
    assert ripemd160(b"a" * 1000000).hex() == \
        "52783243c1697bdbe16d37f97f68f08325dc1528"


def test_aerospike_key_digest_deterministic():
    d1 = key_digest("registers", 5)
    assert len(d1) == 20
    assert d1 == key_digest("registers", 5)
    assert d1 != key_digest("registers", 6)
    assert d1 != key_digest("other", 5)


def test_aerospike_message_roundtrip():
    """get/put against a scripted server speaking the message framing."""
    received = []

    def server(conn):
        for reply_payload in (
                # put reply: header-only message, rc=0
                struct.pack(">BBBBBBIIIHH", 22, 0, 0, 0, 0, 0, 3, 0, 0,
                            0, 0),
                # get reply: rc=0, generation=3, one op with int value 9
                struct.pack(">BBBBBBIIIHH", 22, 0, 0, 0, 0, 0, 3, 0, 0,
                            0, 1)
                + struct.pack(">IBBBB", 4 + 5 + 8, 1, 1, 0, 5) + b"value"
                + struct.pack(">q", 9)):
            header = conn.recv(8)
            size = struct.unpack(">Q", header)[0] & 0xFFFFFFFFFFFF
            buf = b""
            while len(buf) < size:
                buf += conn.recv(size - len(buf))
            received.append(buf)
            out = struct.pack(">Q", (2 << 56) | (3 << 48)
                              | len(reply_payload)) + reply_payload
            conn.sendall(out)

    port = serve_once(server)
    c = aerospike.AerospikeConnection(
        "127.0.0.1", port, namespace="jepsen", set_name="registers")
    assert c.put(5, 7) is True
    value, gen = c.get(5)
    assert value == 9 and gen == 3
    c.close()
    # the put message carried namespace/set/digest fields + one write op
    put_msg = received[0]
    assert b"jepsen" in put_msg and b"registers" in put_msg
    assert key_digest("registers", 5) in put_msg
    assert b"value" in put_msg


def test_aerospike_gen_cas_fail():
    """A GENERATION_ERROR result maps to an unapplied CAS."""
    def server(conn):
        while True:
            header = conn.recv(8)
            if not header:
                return
            size = struct.unpack(">Q", header)[0] & 0xFFFFFFFFFFFF
            buf = b""
            while len(buf) < size:
                buf += conn.recv(size - len(buf))
            payload = struct.pack(">BBBBBBIIIHH", 22, 0, 0, 0, 0,
                                  3,  # rc=3: GENERATION_ERROR
                                  0, 0, 0, 0, 0)
            conn.sendall(struct.pack(">Q", (2 << 56) | (3 << 48)
                                     | len(payload)) + payload)

    port = serve_once(server)
    c = aerospike.AerospikeConnection("127.0.0.1", port)
    assert c.put(1, 2, generation=5) is False    # generation mismatch
    c.close()


@pytest.mark.slow
def test_aerospike_fake_register_run():
    result = run_fake(aerospike.aerospike_test)
    assert result["results"]["valid?"] is True, result["results"]


def test_registry_covers_all_reference_suites():
    from jepsen_tpu.suites import suite_registry
    assert {"rabbitmq", "rethinkdb", "aerospike"} <= set(suite_registry())


def test_aerospike_info_protocol():
    def server(conn):
        header = conn.recv(8)
        size = struct.unpack(">Q", header)[0] & 0xFFFFFFFFFFFF
        req = b""
        while len(req) < size:
            req += conn.recv(size - len(req))
        assert req == b"roster:namespace=jepsen\n"
        reply = (b"roster:namespace=jepsen\t"
                 b"roster=null:observed_nodes=BB9,BB8\n")
        conn.sendall(struct.pack(">Q", (2 << 56) | (1 << 48) | len(reply))
                     + reply)

    port = serve_once(server)
    c = aerospike.AerospikeConnection("127.0.0.1", port)
    out = c.info("roster:namespace=jepsen")
    assert out["roster:namespace=jepsen"].endswith("observed_nodes=BB9,BB8")
    c.close()


def test_amqp_empty_body_basic_return_keeps_sync():
    """A mandatory-unroutable publish with an EMPTY body sends a return
    + header with body-size 0 and NO body frame; the confirm loop must
    not consume the following basic.ack as if it were the body."""
    def broker(conn):
        f = conn.makefile("rb")
        f.read(8)
        conn.sendall(amqp_method(0, _amqp.CONN_START,
                                 bytes([0, 9]) + b"\x00\x00\x00\x00"
                                 + _amqp.longstr(b"PLAIN")
                                 + _amqp.longstr(b"en_US")))
        read_amqp_frame(f)
        conn.sendall(amqp_method(0, _amqp.CONN_TUNE,
                                 struct.pack(">HIH", 0, 131072, 0)))
        read_amqp_frame(f)
        read_amqp_frame(f)
        conn.sendall(amqp_method(0, _amqp.CONN_OPEN_OK, _amqp.shortstr("")))
        read_amqp_frame(f)
        conn.sendall(amqp_method(1, _amqp.CHAN_OPEN_OK, _amqp.longstr(b"")))
        read_amqp_frame(f)                         # publish
        read_amqp_frame(f)                         # header
        # empty body → no body frame from client either; now return it:
        conn.sendall(amqp_method(1, _amqp.BASIC_RETURN,
                                 struct.pack(">H", 312)
                                 + _amqp.shortstr("NO_ROUTE")
                                 + _amqp.shortstr("")
                                 + _amqp.shortstr("jepsen.queue")))
        conn.sendall(amqp_frame(2, 1, struct.pack(">HHQH", 60, 0, 0, 0)))
        # no body frame — straight to the confirm ack
        conn.sendall(amqp_method(1, _amqp.BASIC_ACK,
                                 struct.pack(">QB", 1, 0)))

    port = serve_once(broker)
    c = _amqp.AmqpConnection("127.0.0.1", port)
    # returned (unroutable) → publish reports False, and the connection
    # stays frame-aligned (no hang, no misparse)
    assert c.publish("jepsen.queue", b"") is False
    c.close()


# ---------------------------------------------------------------------------
# mutex workload (rabbitmq semaphore)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_rabbitmq_fake_mutex_run():
    """The semaphore workload checks linearizable mutual exclusion
    against the knossos mutex model."""
    result = run_fake(rabbitmq.rabbitmq_test, workload="mutex",
                      concurrency=4)
    assert result["results"]["valid?"] is True, result["results"]
    oks = [op for op in result["history"]
           if op.get("type") == "ok" and op.get("f") in ("acquire",
                                                         "release")]
    assert oks, "some acquires must have succeeded"


def test_semaphore_client_state_machine():
    """Client-side held-tag discipline (rabbitmq.clj:196-231): double
    acquire fails locally, release without hold fails locally, release
    rejects the held delivery with requeue."""
    calls = []

    class FakeConn:
        def get(self, queue, no_ack=False):
            calls.append(("get", no_ack))
            return (9, b"")

        def reject(self, tag, requeue=True):
            calls.append(("reject", tag, requeue))

    c = rabbitmq.SemaphoreClient()
    c.conn = FakeConn()
    out = c.invoke({}, {"f": "release", "type": "invoke"})
    assert out["type"] == "fail" and out["error"] == ["not-held"]
    out = c.invoke({}, {"f": "acquire", "type": "invoke"})
    assert out["type"] == "ok" and c.tag == 9
    assert calls[-1] == ("get", False)       # unacked hold, not auto-ack
    out = c.invoke({}, {"f": "acquire", "type": "invoke"})
    assert out["type"] == "fail" and out["error"] == ["already-held"]
    out = c.invoke({}, {"f": "release", "type": "invoke"})
    assert out["type"] == "ok" and c.tag is None
    assert calls[-1] == ("reject", 9, True)  # requeue the token


@pytest.mark.slow
def test_aerospike_fake_counter_run():
    result = run_fake(aerospike.aerospike_test, workload="counter")
    assert result["results"]["valid?"] is True, result["results"]
    reads = [op for op in result["history"]
             if op.get("f") == "read" and op.get("type") == "ok"]
    assert reads and isinstance(reads[-1]["value"], int)


def test_counter_checker_bounds():
    from jepsen_tpu import checker as chk
    history = [
        {"type": "invoke", "f": "add", "value": 2, "process": 0},
        {"type": "ok", "f": "add", "value": 2, "process": 0},
        {"type": "invoke", "f": "read", "value": None, "process": 1},
        {"type": "ok", "f": "read", "value": 2, "process": 1},
        # read outside [acknowledged, attempted] window
        {"type": "invoke", "f": "read", "value": None, "process": 1},
        {"type": "ok", "f": "read", "value": 7, "process": 1},
    ]
    out = chk.counter().check({}, history, {})
    assert out["valid?"] is False
    assert out["reads-checked"] == 2


def test_aerospike_append_and_string_read():
    """The set workload's wire ops: atomic string append + string get
    (aerospike/set.clj CAS-op set shape)."""
    received = []

    def server(conn):
        raw = " 3 5".encode()
        for reply_payload in (
                # append reply: header-only, rc=0
                struct.pack(">BBBBBBIIIHH", 22, 0, 0, 0, 0, 0, 1, 0, 0,
                            0, 0),
                # string get reply: one op with string particle
                struct.pack(">BBBBBBIIIHH", 22, 0, 0, 0, 0, 0, 1, 0, 0,
                            0, 1)
                + struct.pack(">IBBBB", 4 + 5 + len(raw), 1, 3, 0, 5)
                + b"value" + raw):
            header = conn.recv(8)
            size = struct.unpack(">Q", header)[0] & 0xFFFFFFFFFFFF
            buf = b""
            while len(buf) < size:
                buf += conn.recv(size - len(buf))
            received.append(buf)
            out = struct.pack(">Q", (2 << 56) | (3 << 48)
                              | len(reply_payload)) + reply_payload
            conn.sendall(out)

    port = serve_once(server)
    c = aerospike.AerospikeConnection(
        "127.0.0.1", port, namespace="jepsen", set_name="elements")
    c.append(0, " 5")
    assert c.get_string(0) == " 3 5"
    c.close()
    # the append op rode the wire with the string particle payload
    assert b" 5" in received[0]


@pytest.mark.slow
def test_aerospike_fake_set_run():
    from conftest import run_fake
    from jepsen_tpu.suites.aerospike import aerospike_test

    result = run_fake(aerospike_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]
