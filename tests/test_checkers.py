"""Built-in checker tests on literal histories (mirrors
jepsen/test/jepsen/checker_test.clj's strategy)."""
from jepsen_tpu import checker as c
from jepsen_tpu.models import UnorderedQueue


def op(typ, process, f, value=None, **kw):
    return {"type": typ, "process": process, "f": f, "value": value, **kw}


def test_stats():
    h = [
        op("invoke", 0, "read"), op("ok", 0, "read", 5),
        op("invoke", 1, "write", 3), op("fail", 1, "write", 3),
        op("invoke", 0, "read"), op("info", 0, "read"),
    ]
    r = c.stats().check({}, h, {})
    assert r["count"] == 3
    assert r["ok-count"] == 1
    assert r["by-f"]["read"]["ok-count"] == 1
    assert r["by-f"]["write"]["valid?"] is False
    assert r["valid?"] is False


def test_stats_valid_when_every_f_has_ok():
    h = [op("invoke", 0, "read"), op("ok", 0, "read", 1)]
    assert c.stats().check({}, h, {})["valid?"] is True


def test_set_checker_happy():
    h = [
        op("invoke", 0, "add", 1), op("ok", 0, "add", 1),
        op("invoke", 1, "add", 2), op("ok", 1, "add", 2),
        op("invoke", 0, "read"), op("ok", 0, "read", [1, 2]),
    ]
    r = c.set_checker().check({}, h, {})
    assert r["valid?"] is True
    assert r["ok-count"] == 2


def test_set_checker_lost_and_unexpected():
    h = [
        op("invoke", 0, "add", 1), op("ok", 0, "add", 1),
        op("invoke", 1, "add", 2), op("info", 1, "add", 2),   # indeterminate
        op("invoke", 0, "read"), op("ok", 0, "read", [2, 99]),
    ]
    r = c.set_checker().check({}, h, {})
    assert r["valid?"] is False
    assert r["lost"] == [1]
    assert r["unexpected"] == [99]
    assert r["recovered"] == [2]


def test_set_checker_never_read():
    r = c.set_checker().check({}, [op("invoke", 0, "add", 1), op("ok", 0, "add", 1)], {})
    assert r["valid?"] == "unknown"


def test_set_full_stable():
    h = [
        op("invoke", 0, "add", 1, time=0), op("ok", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [1], time=30),
        op("invoke", 1, "read", None, time=40), op("ok", 1, "read", [1], time=50),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is True
    assert r["stable-count"] == 1
    assert r["lost-count"] == 0


def test_set_full_lost():
    h = [
        op("invoke", 0, "add", 1, time=0), op("ok", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [1], time=30),
        op("invoke", 1, "read", None, time=40), op("ok", 1, "read", [], time=50),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is False
    assert r["lost"] == [1]


def test_set_full_never_read():
    h = [
        op("invoke", 0, "add", 1, time=0), op("info", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [], time=30),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is True
    assert r["never-read-count"] == 1


def test_counter_in_bounds():
    h = [
        op("invoke", 0, "add", 5), op("ok", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 5),
        op("invoke", 0, "add", 3), op("info", 0, "add", 3),  # maybe applied
        op("invoke", 1, "read"), op("ok", 1, "read", 8),
        op("invoke", 1, "read"), op("ok", 1, "read", 5),
    ]
    r = c.counter().check({}, h, {})
    assert r["valid?"] is True
    assert r["reads-checked"] == 3


def test_counter_out_of_bounds():
    h = [
        op("invoke", 0, "add", 5), op("ok", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 17),
    ]
    r = c.counter().check({}, h, {})
    assert r["valid?"] is False
    assert r["errors"][0]["expected"] == [5, 5]


def test_counter_failed_add_rolled_back():
    h = [
        op("invoke", 0, "add", 5), op("fail", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 0),
    ]
    assert c.counter().check({}, h, {})["valid?"] is True


def test_total_queue():
    h = [
        op("invoke", 0, "enqueue", "a"), op("ok", 0, "enqueue", "a"),
        op("invoke", 1, "enqueue", "b"), op("info", 1, "enqueue", "b"),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "b"),
    ]
    r = c.total_queue().check({}, h, {})
    assert r["valid?"] is False           # 'a' was acknowledged, never seen
    assert r["lost"] == ["a"]
    assert r["recovered-count"] == 1      # 'b' wasn't acked but came out


def test_total_queue_unexpected():
    h = [op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "x")]
    r = c.total_queue().check({}, h, {})
    assert r["valid?"] is False
    assert r["unexpected"] == ["x"]


def test_queue_model_checker():
    h = [
        op("invoke", 0, "enqueue", "a"), op("ok", 0, "enqueue", "a"),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "a"),
    ]
    assert c.queue(UnorderedQueue()).check({}, h, {})["valid?"] is True
    bad = [op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "ghost")]
    assert c.queue(UnorderedQueue()).check({}, bad, {})["valid?"] is False


def test_unique_ids():
    h = [
        op("invoke", 0, "generate"), op("ok", 0, "generate", 1),
        op("invoke", 0, "generate"), op("ok", 0, "generate", 2),
    ]
    assert c.unique_ids().check({}, h, {})["valid?"] is True
    h += [op("invoke", 0, "generate"), op("ok", 0, "generate", 2)]
    r = c.unique_ids().check({}, h, {})
    assert r["valid?"] is False
    assert r["duplicated"] == {2: 2}


def test_unhandled_exceptions():
    h = [
        op("info", 0, "read", None, error=["timeout"]),
        op("info", 1, "read", None, error=["timeout"]),
        op("fail", 0, "write", 1, error=["conflict"]),
    ]
    r = c.unhandled_exceptions().check({}, h, {})
    assert r["valid?"] is True
    assert r["exceptions"][0]["count"] == 2


def test_compose_merges_validity():
    comp = c.compose({"s": c.stats(), "n": c.noop()})
    h = [op("invoke", 0, "read"), op("fail", 0, "read")]
    r = comp.check({}, h, {})
    assert r["valid?"] is False
    assert r["n"]["valid?"] is True
    assert r["s"]["valid?"] is False


def test_check_safe_degrades_to_unknown():
    class Boom(c.Checker):
        def check(self, test, history, opts):
            raise RuntimeError("boom")

    r = c.check_safe(Boom(), {}, [], {})
    assert r["valid?"] == "unknown"


def test_merge_valid_priorities():
    assert c.merge_valid([True, "unknown", False]) is False
    assert c.merge_valid([True, "unknown"]) == "unknown"
    assert c.merge_valid([True, True]) is True
    assert c.merge_valid([]) is True
