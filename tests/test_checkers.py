"""Built-in checker tests on literal histories (mirrors
jepsen/test/jepsen/checker_test.clj's strategy)."""
from jepsen_tpu import checker as c
from jepsen_tpu.models import UnorderedQueue

import pytest


def op(typ, process, f, value=None, **kw):
    return {"type": typ, "process": process, "f": f, "value": value, **kw}


def test_stats():
    h = [
        op("invoke", 0, "read"), op("ok", 0, "read", 5),
        op("invoke", 1, "write", 3), op("fail", 1, "write", 3),
        op("invoke", 0, "read"), op("info", 0, "read"),
    ]
    r = c.stats().check({}, h, {})
    assert r["count"] == 3
    assert r["ok-count"] == 1
    assert r["by-f"]["read"]["ok-count"] == 1
    assert r["by-f"]["write"]["valid?"] is False
    assert r["valid?"] is False


def test_stats_valid_when_every_f_has_ok():
    h = [op("invoke", 0, "read"), op("ok", 0, "read", 1)]
    assert c.stats().check({}, h, {})["valid?"] is True


def test_set_checker_happy():
    h = [
        op("invoke", 0, "add", 1), op("ok", 0, "add", 1),
        op("invoke", 1, "add", 2), op("ok", 1, "add", 2),
        op("invoke", 0, "read"), op("ok", 0, "read", [1, 2]),
    ]
    r = c.set_checker().check({}, h, {})
    assert r["valid?"] is True
    assert r["ok-count"] == 2


def test_set_checker_lost_and_unexpected():
    h = [
        op("invoke", 0, "add", 1), op("ok", 0, "add", 1),
        op("invoke", 1, "add", 2), op("info", 1, "add", 2),   # indeterminate
        op("invoke", 0, "read"), op("ok", 0, "read", [2, 99]),
    ]
    r = c.set_checker().check({}, h, {})
    assert r["valid?"] is False
    assert r["lost"] == [1]
    assert r["unexpected"] == [99]
    assert r["recovered"] == [2]


def test_set_checker_never_read():
    r = c.set_checker().check({}, [op("invoke", 0, "add", 1), op("ok", 0, "add", 1)], {})
    assert r["valid?"] == "unknown"


def test_set_full_stable():
    h = [
        op("invoke", 0, "add", 1, time=0), op("ok", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [1], time=30),
        op("invoke", 1, "read", None, time=40), op("ok", 1, "read", [1], time=50),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is True
    assert r["stable-count"] == 1
    assert r["lost-count"] == 0


def test_set_full_lost():
    h = [
        op("invoke", 0, "add", 1, time=0), op("ok", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [1], time=30),
        op("invoke", 1, "read", None, time=40), op("ok", 1, "read", [], time=50),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is False
    assert r["lost"] == [1]


def test_set_full_never_read():
    h = [
        op("invoke", 0, "add", 1, time=0), op("info", 0, "add", 1, time=10),
        op("invoke", 1, "read", None, time=20), op("ok", 1, "read", [], time=30),
    ]
    r = c.set_full().check({}, h, {})
    assert r["valid?"] is True
    assert r["never-read-count"] == 1


def test_counter_in_bounds():
    h = [
        op("invoke", 0, "add", 5), op("ok", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 5),
        op("invoke", 0, "add", 3), op("info", 0, "add", 3),  # maybe applied
        op("invoke", 1, "read"), op("ok", 1, "read", 8),
        op("invoke", 1, "read"), op("ok", 1, "read", 5),
    ]
    r = c.counter().check({}, h, {})
    assert r["valid?"] is True
    assert r["reads-checked"] == 3


def test_counter_out_of_bounds():
    h = [
        op("invoke", 0, "add", 5), op("ok", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 17),
    ]
    r = c.counter().check({}, h, {})
    assert r["valid?"] is False
    assert r["errors"][0]["expected"] == [5, 5]


def test_counter_failed_add_rolled_back():
    h = [
        op("invoke", 0, "add", 5), op("fail", 0, "add", 5),
        op("invoke", 1, "read"), op("ok", 1, "read", 0),
    ]
    assert c.counter().check({}, h, {})["valid?"] is True


def test_total_queue():
    h = [
        op("invoke", 0, "enqueue", "a"), op("ok", 0, "enqueue", "a"),
        op("invoke", 1, "enqueue", "b"), op("info", 1, "enqueue", "b"),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "b"),
    ]
    r = c.total_queue().check({}, h, {})
    assert r["valid?"] is False           # 'a' was acknowledged, never seen
    assert r["lost"] == ["a"]
    assert r["recovered-count"] == 1      # 'b' wasn't acked but came out


def test_total_queue_unexpected():
    h = [op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "x")]
    r = c.total_queue().check({}, h, {})
    assert r["valid?"] is False
    assert r["unexpected"] == ["x"]


def test_queue_model_checker():
    h = [
        op("invoke", 0, "enqueue", "a"), op("ok", 0, "enqueue", "a"),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "a"),
    ]
    assert c.queue(UnorderedQueue()).check({}, h, {})["valid?"] is True
    bad = [op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", "ghost")]
    assert c.queue(UnorderedQueue()).check({}, bad, {})["valid?"] is False


def test_unique_ids():
    h = [
        op("invoke", 0, "generate"), op("ok", 0, "generate", 1),
        op("invoke", 0, "generate"), op("ok", 0, "generate", 2),
    ]
    assert c.unique_ids().check({}, h, {})["valid?"] is True
    h += [op("invoke", 0, "generate"), op("ok", 0, "generate", 2)]
    r = c.unique_ids().check({}, h, {})
    assert r["valid?"] is False
    assert r["duplicated"] == {2: 2}


def test_unhandled_exceptions():
    h = [
        op("info", 0, "read", None, error=["timeout"]),
        op("info", 1, "read", None, error=["timeout"]),
        op("fail", 0, "write", 1, error=["conflict"]),
    ]
    r = c.unhandled_exceptions().check({}, h, {})
    assert r["valid?"] is True
    assert r["exceptions"][0]["count"] == 2


def test_compose_merges_validity():
    comp = c.compose({"s": c.stats(), "n": c.noop()})
    h = [op("invoke", 0, "read"), op("fail", 0, "read")]
    r = comp.check({}, h, {})
    assert r["valid?"] is False
    assert r["n"]["valid?"] is True
    assert r["s"]["valid?"] is False


def test_check_safe_degrades_to_unknown():
    class Boom(c.Checker):
        def check(self, test, history, opts):
            raise RuntimeError("boom")

    r = c.check_safe(Boom(), {}, [], {})
    assert r["valid?"] == "unknown"


def test_merge_valid_priorities():
    assert c.merge_valid([True, "unknown", False]) is False
    assert c.merge_valid([True, "unknown"]) == "unknown"
    assert c.merge_valid([True, True]) is True
    assert c.merge_valid([]) is True


# ---------------------------------------------------------------------------
# perf / timeline / clock renderers
# ---------------------------------------------------------------------------

def _plot_history():
    ns = 1_000_000_000
    h = []
    t = 0
    for i in range(40):
        t += ns // 4
        p = i % 3
        h.append({"type": "invoke", "process": p, "f": "read", "value": None,
                  "time": t})
        h.append({"type": ["ok", "fail", "info"][i % 3], "process": p,
                  "f": "read", "value": i, "time": t + ns // 10})
    h.insert(10, {"type": "info", "process": "nemesis", "f": "start",
                  "value": None, "time": 2 * ns})
    h.insert(30, {"type": "info", "process": "nemesis", "f": "stop",
                  "value": {"clock-offsets": {"n1": 50, "n2": -20}},
                  "time": 6 * ns})
    return h


@pytest.mark.slow
def test_perf_timeline_clock_render(tmp_path):
    from jepsen_tpu import checker as chk
    test = {"name": "plotty", "start_time": "20260729T000000",
            "store_dir": str(tmp_path)}
    h = _plot_history()
    r = chk.perf().check(test, h, {})
    assert r["valid?"] is True
    r2 = chk.timeline_html().check(test, h, {})
    assert r2["valid?"] is True
    r3 = chk.clock_plot().check(test, h, {})
    assert r3["valid?"] is True
    base = tmp_path / "plotty" / "20260729T000000"
    for f in ("latency-raw.png", "latency-quantiles.png", "rate.png",
              "timeline.html", "clock-skew.png"):
        assert (base / f).stat().st_size > 0, f
    html = (base / "timeline.html").read_text()
    assert "process 0" in html and "read" in html


def test_point_graph_downsamples_large_histories(tmp_path):
    """r2 weak #5 / r3 item 8: the raw-latency scatter must cap its
    point count so a huge run renders in seconds, not choke
    matplotlib."""
    import time

    from jepsen_tpu.checker.perf_plots import POINT_LIMIT, point_graph

    ns = 1_000_000_000
    h = []
    for i in range(60_000):
        h.append({"type": "invoke", "process": i % 5, "f": "w",
                  "value": None, "time": i * ns // 1000})
        h.append({"type": "ok", "process": i % 5, "f": "w",
                  "value": i, "time": i * ns // 1000 + ns // 10_000})
    out = tmp_path / "raw.png"
    t0 = time.perf_counter()
    point_graph({"name": "big"}, h, out)
    dt = time.perf_counter() - t0
    assert out.stat().st_size > 0
    assert dt < 30, f"downsampled render took {dt:.1f}s"
    assert POINT_LIMIT == 10_000


def test_latencies_to_quantiles():
    import numpy as np
    from jepsen_tpu.checker.perf_plots import latencies_to_quantiles
    times = np.asarray([0.0, 1.0, 2.0, 11.0, 12.0])
    lats = np.asarray([1.0, 2.0, 3.0, 10.0, 20.0])
    q = latencies_to_quantiles(times, lats, dt=10.0, qs=(0.5, 1.0))
    assert q[1.0][0] == (5.0, 3.0)
    assert q[1.0][1] == (15.0, 20.0)
    assert q[0.5][0][1] == 2.0


def test_nemesis_activity_regions():
    from jepsen_tpu.checker.perf_plots import nemesis_activity
    h = _plot_history()
    regions = nemesis_activity(h)
    assert len(regions) == 1
    t0, t1 = regions[0]
    assert t0 == 2.0 and t1 == 6.0
