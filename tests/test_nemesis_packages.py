"""Nemesis-package tests over the dummy remote (reference tier-2 style):
combined kill/pause/partition/clock packages, clock nemesis command shapes,
daemon helpers, membership nemesis with an in-memory State, faketime
script generation, and host-side compilation of the C clock utilities."""
import random
import subprocess
import sys

import pytest

from jepsen_tpu import control
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control.dummy import DummyRemote
from jepsen_tpu.db import NoopDB, Pause, Process
from jepsen_tpu.generator.simulate import default_context
from jepsen_tpu.nemesis import combined, membership
from jepsen_tpu.nemesis import time as ntime

NODES = ["n1", "n2", "n3", "n4", "n5"]


def dummy_test(**over):
    t = {"nodes": list(NODES), "ssh": {"dummy": True}, "concurrency": 2}
    t.update(over)
    return t


@pytest.fixture()
def dummy():
    t = dummy_test()
    remote = control.default_remote(t)  # the shared-log dummy transport
    yield t, remote
    control.disconnect_all(t)


class KillableDB(NoopDB, Process, Pause):
    def __init__(self):
        self.events = []

    def start(self, test, node):
        self.events.append(("start", node))

    def kill(self, test, node):
        self.events.append(("kill", node))

    def pause(self, test, node):
        self.events.append(("pause", node))

    def resume(self, test, node):
        self.events.append(("resume", node))


# ---------------------------------------------------------------------------
# node specs
# ---------------------------------------------------------------------------

def test_db_nodes_specs():
    t = dummy_test()
    db = KillableDB()
    rng = random.Random(0)
    assert len(combined.db_nodes(t, db, "one", rng)) == 1
    assert len(combined.db_nodes(t, db, "minority", rng)) == 2
    assert len(combined.db_nodes(t, db, "majority", rng)) == 3
    assert len(combined.db_nodes(t, db, "minority-third", rng)) == 1
    assert combined.db_nodes(t, db, "all", rng) == NODES
    assert set(combined.db_nodes(t, db, None, rng)) <= set(NODES)
    assert combined.db_nodes(t, db, ["n2"], rng) == ["n2"]


def test_db_package_kill_pause(dummy):
    t, remote = dummy
    db = KillableDB()
    pkg = combined.db_package({"db": db, "faults": {"kill", "pause"},
                               "interval": 1.0})
    assert pkg["perf"]["fs"] == {"start", "kill", "pause", "resume"}
    n = pkg["nemesis"]
    out = n.invoke(t, {"type": "info", "f": "kill", "value": "all"})
    assert out["type"] == "info"
    assert {e for e, _ in db.events} == {"kill"}
    assert len(db.events) == 5
    db.events.clear()
    n.invoke(t, {"type": "info", "f": "start", "value": None})
    assert {node for _, node in db.events} == set(NODES)


def test_partition_package_applies_grudge(dummy):
    t, remote = dummy

    class RecordingNet:
        def __init__(self):
            self.calls = []

        def drop_all(self, test, grudge):
            self.calls.append(("drop_all", grudge))

        def heal(self, test):
            self.calls.append(("heal",))

    net = RecordingNet()
    t["net"] = net
    pkg = combined.partition_package({"db": None, "faults": {"partition"}})
    n = pkg["nemesis"].setup(t)
    out = n.invoke(t, {"type": "info", "f": "start-partition",
                       "value": "majority"})
    assert out["value"][0] == "isolated"
    grudge = out["value"][1]
    assert set(grudge) == set(NODES)
    n.invoke(t, {"type": "info", "f": "stop-partition", "value": None})
    kinds = [c[0] for c in net.calls]
    assert kinds == ["heal", "drop_all", "heal"]


def test_nemesis_package_composes(dummy):
    t, _ = dummy
    db = KillableDB()
    pkg = combined.nemesis_package({
        "db": db, "faults": {"kill", "partition"}, "interval": 0.5})
    assert pkg["nemesis"] is not None
    assert pkg["generator"] is not None
    assert pkg["final_generator"] is not None
    fs = pkg["nemesis"].fs()
    assert {"kill", "start", "start-partition", "stop-partition"} <= fs


def test_clock_nemesis_dummy_commands(dummy):
    t, remote = dummy
    n = ntime.clock_nemesis()
    n.setup(t)
    joined = " ".join(str(x) for x in remote.log)
    # dummy remote reports the binaries already present, so setup checks
    # but does not recompile; a forced compile uploads + runs gcc
    assert "test -e /opt/jepsen/bump-time" in joined
    control.on("n1", t, lambda: ntime.compile_resource("bump-time", force=True))
    joined = " ".join(str(x) for x in remote.log)
    assert "gcc" in joined and "upload" in joined
    out = n.invoke(t, {"type": "info", "f": "bump",
                       "value": {"n1": 4000, "n2": -4000}})
    joined = " ".join(str(x) for x in remote.log)
    assert "bump-time" in joined
    assert out["value"]["f"] == "bump"
    assert "clock-offsets" in out["value"]


def test_clock_gens():
    ctx = default_context({"concurrency": 2, "nodes": NODES}, seed=3)
    t = {"nodes": NODES}
    op = ntime.bump_gen(t, ctx)
    assert op["f"] == "bump"
    for node, delta in op["value"].items():
        assert node in NODES and abs(delta) >= 4
    op2 = ntime.strobe_gen(t, ctx)
    for node, spec in op2["value"].items():
        assert {"delta", "period", "duration"} <= set(spec)


def test_c_sources_compile(tmp_path):
    import shutil
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    for src in ("bump-time", "strobe-time"):
        out = tmp_path / src
        r = subprocess.run(["gcc", "-O2", "-o", str(out),
                            f"jepsen_tpu/resources/{src}.c"],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        usage = subprocess.run([str(out)], capture_output=True, text=True)
        assert usage.returncode == 1
        assert "usage" in usage.stderr


# ---------------------------------------------------------------------------
# control.util daemon helpers
# ---------------------------------------------------------------------------

def test_daemon_helpers_dummy(dummy):
    t, remote = dummy
    from jepsen_tpu.control import util as cutil

    def run():
        cutil.start_daemon({"pidfile": "/run/x.pid", "logfile": "/var/log/x",
                            "chdir": "/opt"}, "/opt/bin/x", "--flag", 1)
        cutil.grepkill("myproc")
        cutil.stop_daemon("/opt/bin/x", "/run/x.pid")

    control.on("n1", t, run)
    joined = " ".join(str(x) for x in remote.log)
    assert "setsid nohup" in joined
    assert "pkill" in joined
    assert "/run/x.pid" in joined


# ---------------------------------------------------------------------------
# membership
# ---------------------------------------------------------------------------

class FakeState(membership.State):
    """In-memory membership over a set of nodes."""

    def __init__(self, nodes):
        self.members = set(nodes)
        self.views = {}
        self.done = []

    def node_view(self, test, node):
        return sorted(self.members)

    def merge_views(self, test, views):
        self.views = views
        return self

    def fs(self):
        return {"grow", "shrink"}

    def op(self, test):
        if len(self.members) > 3:
            gone = sorted(self.members)[-1]
            return {"type": "info", "f": "shrink", "value": gone}
        return "pending"

    def invoke(self, test, op):
        if op["f"] == "shrink":
            self.members.discard(op["value"])
            return ["removed", op["value"]]
        return ["noop"]

    def resolve_op(self, test, pair):
        op, value = pair
        self.done.append(op["f"])
        return self

    def teardown(self, test):
        self.done.append("teardown")


def test_membership_nemesis(dummy):
    t, _ = dummy
    state = FakeState(NODES)
    pkg = membership.package(state, interval=0.1, poll_interval=0.05)
    n = pkg["nemesis"].setup(t)
    import time as _t
    _t.sleep(0.15)  # let view threads poll
    gen_fn = membership.membership_gen(n)
    op = gen_fn(t, default_context({"concurrency": 2}))
    assert op["f"] == "shrink"
    out = n.invoke(t, op)
    assert out["value"][0] == "removed"
    assert state.views  # views were polled and merged
    n.teardown(t)
    assert "teardown" in state.done
    assert "shrink" in state.done


# ---------------------------------------------------------------------------
# faketime
# ---------------------------------------------------------------------------

def test_faketime_script():
    from jepsen_tpu import faketime
    s = faketime.script("/usr/lib/faketime/libfaketime.so.1", 1.0123)
    assert "LD_PRELOAD=/usr/lib/faketime/libfaketime.so.1" in s
    assert "x1.0123" in s
    assert s.startswith("#!/bin/bash")
    r = faketime.rand_factor(random.Random(1))
    assert 0.9 < r < 1.1


# ---------------------------------------------------------------------------
# DB-specific fault vocabularies (cockroach skews, yugabyte roles)
# ---------------------------------------------------------------------------

def test_cockroach_skew_package_restarts_on_stop(dummy):
    """critical-skews: start bumps clocks on ~half the nodes, stop resets
    and restarts the DB everywhere (cockroach/nemesis.clj restarting)."""
    from jepsen_tpu.nemesis.db_specific import cockroach_fault_packages

    t, remote = dummy
    db = KillableDB()
    pkg = cockroach_fault_packages()["skew-critical"](
        {"db": db, "faults": {"skew-critical"}, "interval": 1.0})
    n = pkg["nemesis"]
    n.setup(t)
    out = n.invoke(t, {"type": "info", "f": "start", "value": None})
    assert out["type"] == "info"
    out = n.invoke(t, {"type": "info", "f": "stop", "value": None})
    # restarting wrapper: value is [inner-value, {node: started}]
    assert isinstance(out["value"], list) and len(out["value"]) == 2
    assert set(out["value"][1]) == set(NODES)
    assert {node for f, node in db.events if f == "start"} == set(NODES)
    n.teardown(t)


def test_cockroach_strobe_and_slowing_packages(dummy):
    from jepsen_tpu.nemesis.db_specific import cockroach_fault_packages

    t, remote = dummy
    db = KillableDB()
    for fault in ("skew-strobe", "skew-big"):
        pkg = cockroach_fault_packages()[fault]({"db": db, "interval": 1.0})
        n = pkg["nemesis"].setup(t)
        n.invoke(t, {"type": "info", "f": "start", "value": None})
        n.invoke(t, {"type": "info", "f": "stop", "value": None})
        n.teardown(t)
        assert pkg["perf"]["fs"] == {"start", "stop"}


def test_cockroach_startkill_package(dummy):
    from jepsen_tpu.nemesis.db_specific import cockroach_fault_packages

    t, remote = dummy
    db = KillableDB()
    pkg = cockroach_fault_packages()["startkill"]({"db": db})
    n = pkg["nemesis"]
    n.invoke(t, {"type": "info", "f": "start", "value": None})
    kills = [node for f, node in db.events if f == "kill"]
    assert len(kills) == 1  # startkill(1): exactly one shuffled node
    n.invoke(t, {"type": "info", "f": "stop", "value": None})
    assert ("start", kills[0]) in db.events


class RoleDB(KillableDB):
    """Master role on the first three nodes, like yugabyte."""

    def role_nodes(self, test, role):
        nodes = list(test.get("nodes") or [])
        return nodes[:3] if role == "master" else nodes

    def kill_master(self, test, node):
        self.events.append(("kill-master", node))

    def start_master(self, test, node):
        self.events.append(("start-master", node))

    def pause_tserver(self, test, node):
        self.events.append(("pause-tserver", node))

    def resume_tserver(self, test, node):
        self.events.append(("resume-tserver", node))


def test_role_process_targets_right_roles(dummy):
    from jepsen_tpu.nemesis.db_specific import RoleProcess

    t, remote = dummy
    db = RoleDB()
    n = RoleProcess(db, rng=random.Random(5))
    masters = {"n1", "n2", "n3"}
    for _ in range(8):
        out = n.invoke(t, {"type": "info", "f": "kill-master", "value": None})
        assert set(out["value"]["kill"]) <= masters
    killed = {node for f, node in db.events if f == "kill-master"}
    assert killed <= masters and killed
    out = n.invoke(t, {"type": "info", "f": "start-master", "value": None})
    assert set(out["value"]["start"]) == masters  # heal goes to ALL masters
    out = n.invoke(t, {"type": "info", "f": "pause-tserver", "value": None})
    assert set(out["value"]["pause"]) <= set(NODES)
    assert n.fs() >= {"kill-master", "start-master", "pause-tserver",
                      "resume-tserver"}


@pytest.mark.slow
def test_yugabyte_fake_mode_kill_master_end_to_end():
    """--fault kill-master runs the full fake lifecycle and the kill ops
    reach only master nodes (VERDICT r2 item 4)."""
    from jepsen_tpu.suites.yugabyte import yugabyte_test
    from tests.conftest import run_fake

    res = run_fake(yugabyte_test, faults={"kill-master"},
                   nemesis_interval=0.2)
    t = res["test"] if isinstance(res, dict) and "test" in res else res
    db = t["db"]
    kills = [node for ev, node in db.log if ev == "db-kill-master"]
    starts = [node for ev, node in db.log if ev == "db-start-master"]
    masters = {"n1", "n2", "n3"}
    assert kills, "nemesis must have fired within the time limit"
    assert set(kills) <= masters
    assert set(starts) <= masters


@pytest.mark.slow
def test_cockroach_fake_mode_skew_critical_end_to_end():
    """--fault skew-critical runs the full fake lifecycle
    (VERDICT r2 item 4)."""
    from jepsen_tpu.suites.cockroachdb import cockroachdb_test
    from tests.conftest import run_fake

    res = run_fake(cockroachdb_test, faults={"skew-critical"},
                   nemesis_interval=0.2)
    t = res["test"] if isinstance(res, dict) and "test" in res else res
    hist = t.get("history") or []
    fs = {op.get("f") for op in hist
          if op.get("process") == "nemesis"}
    assert "start" in fs and "stop" in fs
