"""Deep-suite workload stragglers (VERDICT r3 item 3): tidb
monotonic/sequential, dgraph delete/sequential, stolon ledger, mongodb
transfer — checker soundness on known-bad histories, client op bodies
over scripted transports, and fake-mode lifecycles."""
import random

import pytest

from jepsen_tpu.suites import dgraph, mongodb, stolon, tidb
from jepsen_tpu.workloads import (delete_workload, dgraph_sequential,
                                  ledger, monotonic_key, transfer)

from conftest import run_fake  # noqa: E402


# ---------------------------------------------------------------------------
# tidb monotonic (monotonic-key cycle workload)
# ---------------------------------------------------------------------------

def _ok(f, value, process=0, index=None):
    return {"type": "ok", "f": f, "value": value, "process": process,
            "index": index}


def test_monotonic_key_graph_edges():
    history = [_ok("inc", {0: 1}), _ok("read", {0: 1, 1: 2}),
               _ok("inc", {0: 2})]
    g, txns = monotonic_key.monotonic_key_graph(history)
    assert len(txns) == 3
    # value order on key 0: {0:1} ops (0,1) -> {0:2} op (2)
    assert (0, 2, "ww") in [(s, d, t) for s, d, t in g.edges] \
        or any(s in (0, 1) and d == 2 for s, d, _ in g.edges)


def test_monotonic_key_checker_catches_observed_regression():
    """One read sees x advance while another (realtime-later) sees it
    retreat → cycle through the realtime edge."""
    history = [
        {"type": "invoke", "f": "inc", "value": 0, "process": 0, "time": 0},
        {"type": "ok", "f": "inc", "value": {0: 1}, "process": 0, "time": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 1,
         "time": 2},
        {"type": "ok", "f": "read", "value": {0: 0, 1: 5}, "process": 1,
         "time": 3},
        {"type": "invoke", "f": "read", "value": None, "process": 2,
         "time": 4},
        {"type": "ok", "f": "read", "value": {0: 1, 1: 4}, "process": 2,
         "time": 5},
    ]
    out = monotonic_key.checker().check({"accelerator": "cpu"}, history, {})
    # key 0 orders read1 < read2 (0<1); key 1 orders read2 < read1 (4<5)
    assert out["valid?"] is False, out


def test_monotonic_key_checker_valid_on_consistent():
    history = [
        {"type": "invoke", "f": "inc", "value": 0, "process": 0, "time": 0},
        {"type": "ok", "f": "inc", "value": {0: 1}, "process": 0, "time": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 1,
         "time": 2},
        {"type": "ok", "f": "read", "value": {0: 1, 1: -1}, "process": 1,
         "time": 3},
    ]
    out = monotonic_key.checker().check({"accelerator": "cpu"}, history, {})
    assert out["valid?"] is True, out


@pytest.mark.slow
def test_tidb_fake_monotonic_and_sequential_runs():
    result = run_fake(tidb.tidb_test, workload="monotonic")
    assert result["results"]["valid?"] is True, result["results"]
    result = run_fake(tidb.tidb_test, workload="sequential")
    assert result["results"]["valid?"] is True, result["results"]


class ScriptedSQL:
    """Captures SQL; returns scripted results per matching substring."""

    def __init__(self, script=None):
        self.script = script or {}
        self.sql = []

    def query(self, sql):
        self.sql.append(sql)
        for pat, out in self.script.items():
            if pat in sql:
                return out
        return (0, b"")


def test_mysql_mono_key_inc_sql():
    from jepsen_tpu.suites._mysql_client import MySQLSuiteClient
    c = MySQLSuiteClient.__new__(MySQLSuiteClient)
    c.conn = ScriptedSQL({"SELECT val": [[4]]})
    c._broken = False
    out = c._mono_key_inc({"f": "inc", "type": "invoke", "value": 3})
    assert out["type"] == "ok" and out["value"] == {3: 5}
    assert any("UPDATE cycle SET val = 5 WHERE pk = 3" in s
               for s in c.conn.sql)
    # absent key: insert 0
    c.conn = ScriptedSQL({"SELECT val": []})
    out = c._mono_key_inc({"f": "inc", "type": "invoke", "value": 7})
    assert out["value"] == {7: 0}
    assert any("INSERT INTO cycle (pk, sk, val) VALUES (7, 7, 0)" in s
               for s in c.conn.sql)


def test_mysql_seq_bodies():
    from jepsen_tpu.suites._mysql_client import MySQLSuiteClient
    c = MySQLSuiteClient.__new__(MySQLSuiteClient)
    c.conn = ScriptedSQL()
    c._broken = False
    out = c._seq_write({"key-count": 3}, {"f": "write", "type": "invoke",
                                          "value": 9})
    assert out["type"] == "ok"
    inserts = [s for s in c.conn.sql if "INSERT IGNORE" in s]
    assert len(inserts) == 3 and "'9_0'" in inserts[0]
    c.conn = ScriptedSQL({"SELECT k": []})
    out = c._seq_read({"key-count": 3}, {"f": "read", "type": "invoke",
                                         "value": 9})
    assert out["type"] == "ok" and out["value"] == [9, [None, None, None]]


# ---------------------------------------------------------------------------
# stolon ledger
# ---------------------------------------------------------------------------

def test_ledger_checker_catches_double_spend():
    history = [
        {"type": "ok", "f": "transfer", "value": [0, 10, 0]},
        {"type": "ok", "f": "transfer", "value": [0, -9, 1]},
        {"type": "ok", "f": "transfer", "value": [0, -9, 2]},  # double spend
    ]
    out = ledger.LedgerChecker().check({}, history, {})
    assert out["valid?"] is False
    assert out["errors"] == [{"account": 0, "balance": -8}]


def test_ledger_checker_charitable_interpretation():
    history = [
        {"type": "info", "f": "transfer", "value": [1, 10, 0]},  # counts
        {"type": "info", "f": "transfer", "value": [1, -9, 1]},  # doesn't
        {"type": "ok", "f": "transfer", "value": [1, -9, 2]},
        {"type": "fail", "f": "transfer", "value": [1, -9, 3]},  # ignored
    ]
    out = ledger.LedgerChecker().check({}, history, {})
    assert out["valid?"] is True, out


def test_pg_ledger_transfer_sql():
    from jepsen_tpu.suites._pg_client import PGSuiteClient

    class ScriptedPG:
        def __init__(self, sum_value):
            self.sum_value = sum_value
            self.sql = []

        def query(self, sql):
            self.sql.append(sql)
            if "SUM" in sql:
                return [[self.sum_value]], b""
            return [], b""

    c = PGSuiteClient.__new__(PGSuiteClient)
    c.isolation = "serializable"
    c._broken = False
    c.conn = ScriptedPG(9)
    out = c._ledger_transfer({}, {"f": "transfer", "type": "invoke",
                                  "value": [2, -9, 17]})
    assert out["type"] == "ok"
    guard = [s for s in c.conn.sql if "SUM" in s][0]
    assert "account = 2" in guard and "id != 17" in guard
    assert any("VALUES (17, 2, -9)" in s for s in c.conn.sql)
    # insufficient balance refuses before inserting
    c.conn = ScriptedPG(8)
    out = c._ledger_transfer({}, {"f": "transfer", "type": "invoke",
                                  "value": [2, -9, 18]})
    assert out["type"] == "fail" and out["error"][0] == "insufficient"
    assert not any("INSERT" in s for s in c.conn.sql)


@pytest.mark.slow
def test_stolon_fake_ledger_run():
    result = run_fake(stolon.stolon_test, workload="ledger")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# mongodb transfer
# ---------------------------------------------------------------------------

def test_accounts_model_steps():
    m = transfer.Accounts({0: 10, 1: 10})
    m2 = m.step({"f": "transfer", "value": {"from": 0, "to": 1, "amount": 3}})
    assert m2.balances == {0: 7, 1: 13}
    from jepsen_tpu.models import is_inconsistent
    ok = m2.step({"f": "read", "value": {0: 7, 1: 13}})
    assert ok is m2
    assert is_inconsistent(m2.step({"f": "read", "value": {0: 10, 1: 10}}))
    partial_ok = m2.step({"f": "partial-read", "value": {1: 13}})
    assert partial_ok is m2
    assert is_inconsistent(
        m2.step({"f": "partial-read", "value": {1: 10}}))


def test_transfer_checker_catches_torn_read():
    history = [
        {"type": "invoke", "f": "transfer",
         "value": {"from": 0, "to": 1, "amount": 3}, "process": 0,
         "time": 0, "index": 0},
        {"type": "ok", "f": "transfer",
         "value": {"from": 0, "to": 1, "amount": 3}, "process": 0,
         "time": 1, "index": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 1,
         "time": 2, "index": 2},
        # torn: from debited, to not credited — never a model state
        {"type": "ok", "f": "read", "value": {0: 7, 1: 10}, "process": 1,
         "time": 3, "index": 3},
    ]
    chk = transfer.TransferChecker([0, 1], 10)
    out = chk.check({}, history, {})
    assert out["valid?"] is False, out


@pytest.mark.slow
def test_mongodb_fake_transfer_run():
    result = run_fake(mongodb.mongodb_test, workload="transfer")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# dgraph delete + sequential
# ---------------------------------------------------------------------------

def test_delete_bad_read_classification():
    assert delete_workload.bad_read(1, {"value": [1, []]}) is None
    assert delete_workload.bad_read(
        1, {"value": [1, [{"uid": "0x1", "key": 1}]]}) is None
    assert delete_workload.bad_read(
        1, {"value": [1, [{"uid": "0x1", "key": 1},
                          {"uid": "0x2", "key": 1}]]}) == "multiple-records"
    assert delete_workload.bad_read(
        1, {"value": [1, [{"uid": "0x1"}]]}) == "malformed-record"
    assert delete_workload.bad_read(
        1, {"value": [1, [{"uid": "0x1", "key": 2}]]}) == "wrong-key"


def test_delete_checker_flags_bad_reads():
    history = [{"type": "ok", "f": "read",
                "value": [3, [{"uid": "0x1", "key": 3},
                              {"uid": "0x2", "key": 3}]]}]
    out = delete_workload.DeleteChecker().check(
        {}, history, {"history-key": 3})
    assert out["valid?"] is False and out["bad-read-count"] == 1


def test_dgraph_sequential_checker():
    history = [_ok("inc", [0, 2], process=0), _ok("read", [0, 1], process=0)]
    out = dgraph_sequential.SequentialChecker().check({}, history, {})
    assert out["valid?"] is False and out["non-monotonic-count"] == 1
    ok_hist = [_ok("inc", [0, 1], process=0), _ok("read", [0, 2], process=0),
               _ok("read", [0, 1], process=1)]  # other process: fine
    out = dgraph_sequential.SequentialChecker().check({}, ok_hist, {})
    assert out["valid?"] is True


class ScriptedDgraph(dgraph.DgraphClient):
    def __init__(self, queries=None, txn=None, mutate_uids=None):
        super().__init__(node="n1")
        self.queries = queries or {}
        self.txn = txn or {}
        self.mutate_uids = mutate_uids
        self.calls = []

    def _query(self, q):
        self.calls.append(("query", q))
        return self.queries

    def _txn_query(self, q):
        self.calls.append(("txn_query", q))
        return self.txn, 42

    def _txn_mutate(self, ts, body):
        self.calls.append(("txn_mutate", ts, body))
        return {"keys": [], "preds": []}

    def _txn_commit(self, ts, txn):
        self.calls.append(("txn_commit", ts))

    def _mutate(self, body):
        self.calls.append(("mutate", body))
        return {"data": {"uids": self.mutate_uids or {}}}


def test_dgraph_delete_client_bodies():
    t = {"delete-workload": True}
    c = ScriptedDgraph(mutate_uids={"u": "0x9"})
    out = c.invoke(t, {"f": "upsert", "type": "invoke", "value": [5, None]})
    assert out["type"] == "ok"
    cond = c.calls[0][1]
    assert cond["cond"] == "@if(eq(len(u), 0))" and cond["set"] == [{"key": 5}]
    c = ScriptedDgraph(mutate_uids={})
    out = c.invoke(t, {"f": "upsert", "type": "invoke", "value": [5, None]})
    assert out["type"] == "fail" and out["error"] == ["present"]
    c = ScriptedDgraph(txn={"q": [{"uid": "0x9"}]})
    out = c.invoke(t, {"f": "delete", "type": "invoke", "value": [5, None]})
    assert out["type"] == "ok"
    assert ("txn_mutate", 42, {"delete": [{"uid": "0x9"}]}) in c.calls
    c = ScriptedDgraph(txn={"q": []})
    out = c.invoke(t, {"f": "delete", "type": "invoke", "value": [5, None]})
    assert out["type"] == "fail" and out["error"] == ["not-found"]


def test_dgraph_sequential_client_bodies():
    t = {"dgraph-sequential": True}
    c = ScriptedDgraph(txn={"q": [{"uid": "0x3", "value": 4}]})
    out = c.invoke(t, {"f": "inc", "type": "invoke", "value": [2, None]})
    assert out["type"] == "ok" and out["value"] == [2, 5]
    assert ("txn_mutate", 42,
            {"set": [{"uid": "0x3", "value": 5}]}) in c.calls
    c = ScriptedDgraph(txn={"q": []})
    out = c.invoke(t, {"f": "inc", "type": "invoke", "value": [2, None]})
    assert out["value"] == [2, 1]
    assert ("txn_mutate", 42, {"set": [{"key": 2, "value": 1}]}) in c.calls


@pytest.mark.slow
def test_dgraph_fake_delete_and_sequential_runs():
    result = run_fake(dgraph.dgraph_test, workload="delete")
    assert result["results"]["valid?"] is True, result["results"]
    fs = {op.get("f") for op in result["history"]
          if op.get("type") == "ok"}
    # a generator misconfiguration that emits nothing would be
    # trivially valid — require the op vocabulary actually ran
    assert {"read", "upsert", "delete"} <= fs, fs
    result = run_fake(dgraph.dgraph_test, workload="sequential")
    assert result["results"]["valid?"] is True, result["results"]
    fs = {op.get("f") for op in result["history"]
          if op.get("type") == "ok"}
    assert {"inc", "read"} <= fs, fs
