"""Driver entry-point contract tests.

The driver compile-checks ``entry()`` on a single chip and executes
``dryrun_multichip(n)`` in a process whose default platform is the real
(1-chip) TPU plugin; these tests pin both contracts. The round-1 failure
mode was exactly this: the dryrun body worked under the test env's
virtual 8-device CPU mesh but the entry point did not provision that env
for itself (VERDICT round 1, weak #1).
"""
import jax

import __graft_entry__ as ge
import pytest


def test_entry_returns_jittable_fn_and_args():
    fn, args = ge.entry()
    alive, _died, ovf, _peak = jax.jit(fn)(*args)
    assert bool(alive) and not bool(ovf)


@pytest.mark.slow
def test_dryrun_multichip_in_process():
    # Test env: 8 virtual CPU devices, backends initialized -> fast path.
    assert len(jax.devices()) >= 8
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_self_provisions_when_short_of_devices():
    # 16 > the 8 devices this process owns: must re-exec with a
    # self-provisioned 16-device virtual mesh and still pass.
    ge.dryrun_multichip(16)
