"""Full-lifecycle integration tests with the dummy remote + in-memory
doubles (reference: jepsen/test/jepsen/core_test.clj basic-cas-test,
worker-recovery-test; SURVEY.md §4 tier 2)."""
import tempfile

import pytest

import jepsen_tpu.generator as gen
from jepsen_tpu import checker, core, nemesis as nemesis_mod, store
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.fakes import AtomClient, AtomDB, CrashingClient, noop_test


def cas_test(tmp, n_ops=200, concurrency=5):
    db = AtomDB()
    return noop_test(
        name="cas-register",
        db=db,
        client=AtomClient(db),
        concurrency=concurrency,
        store_dir=tmp,
        generator=gen.clients(gen.limit(n_ops, gen.mix([
            gen.repeat({"f": "read"}),
            lambda test, ctx: {"f": "write", "value": ctx.rng.randrange(5)},
            lambda test, ctx: {"f": "cas",
                               "value": [ctx.rng.randrange(5), ctx.rng.randrange(5)]},
        ]))),
        checker=checker.compose({
            "linear": linearizable(accelerator="cpu"),
            "stats": checker.stats(),
        }),
    ), db


def test_basic_cas_run():
    with tempfile.TemporaryDirectory() as tmp:
        test, db = cas_test(tmp, n_ops=200, concurrency=5)
        result = core.run(test)
        history = result["history"]
        # every op indexed, invoke/completion paired
        assert all("index" in op for op in history)
        invokes = [op for op in history if op["type"] == "invoke"]
        completions = [op for op in history if op["type"] in ("ok", "fail", "info")]
        assert len(invokes) == 200
        assert len(completions) == 200
        # the atom register is linearizable by construction
        assert result["results"]["valid?"] is True, result["results"]
        assert result["results"]["linear"]["valid?"] is True
        # client lifecycle: one open+setup per node at minimum, all closed
        opens = [e for e in db.log if e[0] == "client-open"]
        closes = [e for e in db.log if e[0] == "client-close"]
        assert len(opens) >= len(test["nodes"])
        assert len(closes) == len(opens)
        setups = [e for e in db.log if e[0] == "db-setup"]
        assert len(setups) == len(test["nodes"])


def test_store_persistence_round_trip():
    with tempfile.TemporaryDirectory() as tmp:
        test, _ = cas_test(tmp, n_ops=50)
        result = core.run(test)
        name, ts = result["name"], result["start_time"]
        loaded = store.load_test(name, ts, tmp)
        assert len(loaded["history"]) == len(result["history"])
        # persistence, not validity, is under test: with few ops the stats
        # checker may legitimately flag an all-fail :cas (no successful
        # compare-and-set in 50 tries) — what matters is that the stored
        # verdict round-trips exactly
        assert loaded["results"]["valid?"] == result["results"]["valid?"]
        assert loaded["results"]["linear"]["valid?"] is True
        # columnar sidecar exists
        assert (store.test_dir(result) / "history.npz").exists()
        # latest symlink resolves
        assert (store.base_dir(result) / name / "latest").exists()


def test_worker_recovery_crashing_client():
    """A client that always throws: every op becomes :info, processes are
    renumbered, and the run completes (core_test.clj:179-198)."""
    with tempfile.TemporaryDirectory() as tmp:
        client = CrashingClient()
        test = noop_test(
            name="crash", client=client, concurrency=2, store_dir=tmp,
            generator=gen.clients(gen.limit(10, gen.repeat({"f": "read"}))),
            checker=checker.unbridled_optimism(),
        )
        result = core.run(test)
        infos = [op for op in result["history"] if op["type"] == "info"]
        assert len(infos) == 10
        assert client.invocations == 10
        procs = {op["process"] for op in result["history"] if op["type"] == "invoke"}
        assert len(procs) == 10  # every crash burns a process


def test_nemesis_ops_flow_through():
    with tempfile.TemporaryDirectory() as tmp:
        db = AtomDB()
        test = noop_test(
            name="nemesis-flow", db=db, client=AtomClient(db), concurrency=2,
            store_dir=tmp,
            nemesis=nemesis_mod.partition_random_halves(),
            generator=gen.phases(
                gen.nemesis_gen(gen.once(gen.repeat({"f": "start-partition", "value": "majority"}))),
                gen.clients(gen.limit(10, gen.repeat({"f": "read"}))),
                gen.nemesis_gen(gen.once(gen.repeat({"f": "stop-partition"}))),
            ),
            checker=checker.unbridled_optimism(),
        )
        result = core.run(test)
        nem_ops = [op for op in result["history"] if op["process"] == "nemesis"]
        assert any(op["f"] == "start-partition" and op["type"] == "info"
                   and op["value"][0] == "isolated" for op in nem_ops)
        # the noop net recorded a drop-all and heals (prepare_test copies
        # the test map, so inspect the returned copy)
        assert any(e[0] == "drop-all" for e in result.get("_net_log", []))
        assert any(e[0] == "heal" for e in result.get("_net_log", []))


def test_generator_exception_shuts_down_cleanly():
    """Generator throws mid-run: run raises, workers die, clients close
    (core_test.clj generator-recovery-test)."""
    with tempfile.TemporaryDirectory() as tmp:
        db = AtomDB()

        def boom(test, ctx):
            raise RuntimeError("generator exploded")

        test = noop_test(
            name="gen-crash", db=db, client=AtomClient(db), concurrency=2,
            store_dir=tmp,
            generator=gen.clients([gen.limit(4, gen.repeat({"f": "read"})), boom]),
            checker=checker.unbridled_optimism(),
        )
        with pytest.raises(RuntimeError):
            core.run(test)
        opens = [e for e in db.log if e[0] == "client-open"]
        closes = [e for e in db.log if e[0] == "client-close"]
        assert len(closes) >= len(opens) - len(test["nodes"])  # workers' clients closed


def test_time_limit_wall_clock():
    """time_limit bounds the run in real time."""
    import time
    with tempfile.TemporaryDirectory() as tmp:
        db = AtomDB()
        test = noop_test(
            name="timed", db=db, client=AtomClient(db), concurrency=2,
            store_dir=tmp,
            generator=gen.time_limit(1.0, gen.clients(
                gen.stagger(0.05, gen.repeat({"f": "read"})))),
            checker=checker.stats(),
        )
        t0 = time.monotonic()
        result = core.run(test)
        dt = time.monotonic() - t0
        assert dt < 15
        assert result["results"]["valid?"] is True
        n = result["results"]["count"]
        assert 5 <= n <= 40  # ~20 ops in 1s at 50ms stagger


@pytest.mark.slow
def test_high_concurrency_soak():
    """50 workers x ~4 s of mixed register traffic with a fast nemesis:
    shakes out interpreter races; asserts the structural invariants the
    reference's interpreter tests check (every invoke completed by the
    same process, types legal, crashed processes renumbered)."""
    from conftest import run_fake
    from jepsen_tpu.suites import etcd

    result = run_fake(etcd.etcd_test, time_limit=4.0, concurrency=50,
                      faults={"partition"}, nemesis_interval=0.1)
    history = result["history"]
    assert len(history) > 200
    # pair invokes with their completions per process
    open_ops: dict = {}
    for op in history:
        p = op.get("process")
        if p == "nemesis":
            continue
        if op.get("type") == "invoke":
            assert p not in open_ops, f"process {p} double-invoked"
            open_ops[p] = op
        elif op.get("type") in ("ok", "fail", "info"):
            inv = open_ops.pop(p, None)
            assert inv is not None, f"completion without invoke: {op}"
            assert inv.get("f") == op.get("f")
        else:
            raise AssertionError(f"illegal type: {op}")
    # anything left open must have crashed (type info would have closed it)
    assert not open_ops, f"unclosed invokes: {list(open_ops)[:5]}"
    assert result["results"]["valid?"] is True, result["results"]
