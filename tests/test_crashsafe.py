"""Crash-safety: WAL journal + recovery, degradation ladder, fault
registry heal, and the capped-exponential-jitter backoff schedule
(doc/robustness.md).

The kill/recover tests carry the ``chaos`` marker (run just them with
``-m chaos``); they stay fast enough for the quick lane too.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jepsen_tpu import telemetry


@pytest.fixture
def metrics_registry():
    """A live telemetry registry installed for the test's duration."""
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


# ---------------------------------------------------------------------------
# WAL + tolerant readers
# ---------------------------------------------------------------------------

def test_journal_appends_and_torn_tail(tmp_path):
    from jepsen_tpu.journal import Journal, read_wal

    p = tmp_path / "history.wal.jsonl"
    j = Journal(p, fsync_interval_s=0)
    for i in range(5):
        j.append({"type": "invoke", "f": "write", "value": i, "process": 0})
    j.close()
    ops, truncated = read_wal(p)
    assert [op["value"] for op in ops] == [0, 1, 2, 3, 4]
    assert truncated is False
    # tear the final line mid-document, as a crash would
    raw = p.read_text()
    p.write_text(raw[: len(raw) - 17])
    ops, truncated = read_wal(p)
    assert [op["value"] for op in ops] == [0, 1, 2, 3]
    assert truncated is True


def test_journal_discard(tmp_path):
    from jepsen_tpu.journal import Journal

    p = tmp_path / "w.jsonl"
    j = Journal(p)
    j.append({"a": 1})
    j.close(discard=True)
    assert not p.exists()
    j.close()  # double close is a no-op


def test_load_history_tolerates_truncated_tail(tmp_path):
    from jepsen_tpu import store

    d = tmp_path / "t" / "ts"
    d.mkdir(parents=True)
    good = json.dumps({"type": "invoke", "f": "read", "value": None})
    (d / "history.jsonl").write_text(
        good + "\n" + good + "\n" + '{"type": "ok", "f": "re')
    ops = store.load_history("t", "ts", str(tmp_path))
    assert len(ops) == 2  # torn tail dropped, no JSONDecodeError
    assert store.read_history is store.load_history


# ---------------------------------------------------------------------------
# Backoff schedule
# ---------------------------------------------------------------------------

def test_backoff_schedule_deterministic_and_capped():
    import random

    from jepsen_tpu.utils import backoff_delay

    a = [backoff_delay(n, base_s=0.1, cap_s=2.0, rng=random.Random(7))
         for n in range(8)]
    b = [backoff_delay(n, base_s=0.1, cap_s=2.0, rng=random.Random(7))
         for n in range(8)]
    assert a == b  # seeded rng -> deterministic schedule
    for n, d in enumerate(a):
        assert 0.0 <= d <= min(2.0, 0.1 * 2 ** n)
    # the ceiling grows exponentially then saturates at the cap
    rng = random.Random(0)
    big = [backoff_delay(n, base_s=0.1, cap_s=2.0, rng=rng)
           for n in range(100)]
    assert max(big) <= 2.0


def test_retry_with_backoff_retries_then_raises():
    import random

    from jepsen_tpu.utils import retry_with_backoff

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("flake")
        return "ok"

    assert retry_with_backoff(flaky, tries=5, base_s=0.001, cap_s=0.002,
                              rng=random.Random(1)) == "ok"
    assert len(calls) == 3

    def always():
        raise RuntimeError("always")

    with pytest.raises(RuntimeError, match="always"):
        retry_with_backoff(always, tries=2, base_s=0.001, cap_s=0.002,
                           rng=random.Random(1))


def test_retry_remote_backoff_deterministic(monkeypatch):
    """RetryRemote sleeps on the capped-exponential full-jitter
    schedule, deterministic under a seeded RNG."""
    import random

    from jepsen_tpu.control import retry as retry_mod

    def run_once(seed):
        sleeps: list[float] = []
        monkeypatch.setattr(retry_mod.time, "sleep", sleeps.append)

        class Dying:
            def connect(self, spec):
                raise OSError("transport down")

        rr = retry_mod.RetryRemote(Dying(), rng=random.Random(seed))
        with pytest.raises(OSError):
            rr.connect({"host": "n1"})
        return sleeps

    a, b = run_once(42), run_once(42)
    assert a == b  # same seed -> identical schedule
    assert len(a) == retry_mod.TRIES - 1  # no sleep after the give-up try
    for n, s in enumerate(a):
        # each delay within [0, min(cap, base * 2**n)]
        assert 0.0 <= s <= min(retry_mod.BACKOFF_CAP_S,
                               retry_mod.BACKOFF_BASE_S * 2 ** n)
    assert a != run_once(7)  # different seed, different jitter


# ---------------------------------------------------------------------------
# BackendLadder
# ---------------------------------------------------------------------------

def _counter_value(reg, name, **labels):
    return reg.counter(name, labels=tuple(labels)).value(**labels)


def test_ladder_resource_exhausted_shrinks_then_demotes(metrics_registry):
    from jepsen_tpu.checker.ladder import Backend, BackendLadder

    calls = {"a": 0, "b": 0, "shrink": 0}

    def a_fn(ctx):
        calls["a"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: out of device memory")

    def a_shrink(ctx):
        calls["shrink"] += 1
        ctx["tile"] //= 2
        return True

    def b_fn(ctx):
        calls["b"] += 1
        return "b-result"

    ladder = BackendLadder([
        Backend("a", a_fn, shrink=a_shrink),
        Backend("b", b_fn),
    ], watchdog_s=0)
    ctx = {"tile": 128}
    res, backend = ladder.run(ctx)
    assert (res, backend) == ("b-result", "b")
    # demotion order: a tried, shrunk-retried once, then demoted to b
    assert calls == {"a": 2, "b": 1, "shrink": 1}
    assert ctx["tile"] == 64
    assert ctx["_attempted"] == ["a"]
    reg = metrics_registry
    assert _counter_value(reg, "checker_backend_demotions_total",
                          backend="a", reason="resource-exhausted") == 1
    assert _counter_value(reg, "checker_backend_shrink_retries_total",
                          backend="a") == 1


def test_ladder_circuit_breaker_trips(metrics_registry):
    from jepsen_tpu.checker.ladder import Backend, BackendLadder

    calls = {"a": 0}

    def a_fn(ctx):
        calls["a"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: oom")

    ladder = BackendLadder([
        Backend("a", a_fn),
        Backend("b", lambda ctx: "b"),
    ], watchdog_s=0, breaker_threshold=2)
    for _ in range(2):
        res, backend = ladder.run({})
        assert backend == "b"
    assert ladder.broken() == {"a"}
    # breaker open: a's fn is no longer invoked at all
    res, backend = ladder.run({})
    assert backend == "b"
    assert calls["a"] == 2
    reg = metrics_registry
    assert _counter_value(reg, "checker_backend_demotions_total",
                          backend="a", reason="circuit-open") == 1
    assert reg.gauge("checker_circuit_open",
                     labels=("backend",)).value(backend="a") == 1.0
    ladder.reset()
    assert ladder.broken() == set()
    ladder.run({})
    assert calls["a"] == 3  # closed again


def test_ladder_watchdog_timeout_demotes(metrics_registry):
    from jepsen_tpu.checker.ladder import Backend, BackendLadder

    def hung(ctx):
        time.sleep(5.0)
        return "never"

    ladder = BackendLadder([
        Backend("dev", hung, device=True),
        Backend("cpu", lambda ctx: "cpu"),
    ], watchdog_s=0.05)
    t0 = time.monotonic()
    res, backend = ladder.run({})
    assert (res, backend) == ("cpu", "cpu")
    assert time.monotonic() - t0 < 2.0  # demoted, not hung
    reg = metrics_registry
    assert _counter_value(reg, "checker_watchdog_timeouts_total",
                          backend="dev") == 1
    assert _counter_value(reg, "checker_backend_demotions_total",
                          backend="dev", reason="watchdog-timeout") == 1


def test_ladder_terminal_rung_raises_and_is_breaker_exempt(
        metrics_registry):
    """A hard failure in the terminal rung propagates (check_safe wants
    the real traceback), and the terminal rung is never circuit-broken
    — a wedged breaker on the rung with no fallback would poison every
    later dispatch."""
    from jepsen_tpu.checker.ladder import Backend, BackendLadder

    calls = {"cpu": 0}

    def cpu_fn(ctx):
        calls["cpu"] += 1
        if ctx.get("explode"):
            raise ValueError("model stepped into a wall")
        return "ok"

    ladder = BackendLadder([Backend("cpu", cpu_fn)], watchdog_s=0,
                           breaker_threshold=1)
    with pytest.raises(ValueError, match="stepped into a wall"):
        ladder.run({"explode": True})
    # even after a failure past the threshold, the terminal rung still
    # runs — healthy dispatches keep settling
    res, backend = ladder.run({})
    assert (res, backend) == ("ok", "cpu")
    assert calls["cpu"] == 2


def test_ladder_decline_and_unavailable(metrics_registry):
    from jepsen_tpu.checker.ladder import (
        Backend, BackendLadder, LadderExhausted, Unavailable,
    )

    ladder = BackendLadder([
        Backend("skip", lambda ctx: None),
        Backend("unavail", lambda ctx: (_ for _ in ()).throw(Unavailable())),
        Backend("ok", lambda ctx: 42),
    ], watchdog_s=0)
    res, backend = ladder.run({})
    assert (res, backend) == (42, "ok")
    # declines never count toward the breaker
    assert ladder.broken() == set()
    with pytest.raises(LadderExhausted):
        BackendLadder([Backend("skip", lambda ctx: None)]).run({})


def _register_history(n_pairs):
    """A trivially-linearizable register history: sequential writes."""
    h = []
    for i in range(n_pairs):
        h.append({"type": "invoke", "f": "write", "value": i, "process": 0,
                  "time": 2 * i})
        h.append({"type": "ok", "f": "write", "value": i, "process": 0,
                  "time": 2 * i + 1})
    return h


def test_linearizable_forced_oom_demotes_to_cpu(metrics_registry,
                                                monkeypatch):
    """A device frontier kernel dying of RESOURCE_EXHAUSTED demotes
    (after one halved-capacity retry) to the exact CPU twin — the run
    degrades instead of crashing, with the demotion on the books."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops.jitlin import JitLinKernel

    def oom(self, stream, capacity=256):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                           "allocating frontier")

    monkeypatch.setattr(JitLinKernel, "check", oom)
    checker = LinearizableChecker(accelerator="tpu", watchdog_s=0)
    out = checker.check({}, _register_history(300), {})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-cpu(fallback)"
    reg = metrics_registry
    assert _counter_value(reg, "checker_backend_demotions_total",
                          backend="jitlin-device",
                          reason="resource-exhausted") == 1
    assert _counter_value(reg, "checker_backend_shrink_retries_total",
                          backend="jitlin-device") == 1


def test_linearizable_ladder_bit_identical_host_path():
    """The ladder refactor must not change host-regime dispatch: the
    native/python rungs produce the same verdicts and labels as the
    direct calls."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    h = _register_history(20)
    out = LinearizableChecker(accelerator="cpu").check({}, h, {})
    assert out["valid?"] is True
    assert out["algorithm"] in ("jitlin-native", "jitlin-cpu")


# ---------------------------------------------------------------------------
# Fault registry
# ---------------------------------------------------------------------------

def test_fault_classify():
    from jepsen_tpu.nemesis.faults import classify

    assert classify("start-partition") == ("begin", "net")
    assert classify("stop-partition") == ("end", "net")
    assert classify("start_partition") == ("begin", "net")
    assert classify("kill") == ("begin", "process")
    # bare start/stop are ambiguous (kill-heal vs raw-partitioner
    # open/close) and deliberately unclassified
    assert classify("start") == (None, None)
    assert classify("stop") == (None, None)
    assert classify("pause") == ("begin", "pause")
    assert classify("resume") == ("end", "pause")
    assert classify("bump") == ("begin", "clock")
    assert classify("reset") == ("end", "clock")
    assert classify("truncate-file") == ("begin", "file")
    # prefix fallback maps only to kinds we can actually heal: a
    # partition-flavored suffix is net; an unknown suffix (yugabyte's
    # stop-master is an INJECTION, not a heal) stays unclassified
    assert classify("start-partition-replica") == ("begin", "net")
    assert classify("stop-partition-replica") == ("end", "net")
    assert classify("stop-master") == (None, None)
    assert classify("read") == (None, None)
    assert classify(None) == (None, None)
    # membership reconfigurations: one-shot "begin" transitions, healed
    # by State resolution (nemesis/membership.py), never by a close op
    for f in ("grow", "shrink", "join", "leave", "add-node",
              "remove-node", "rolling-restart", "reconfigure"):
        assert classify(f) == ("begin", "membership"), f
    assert classify("rolling_restart") == ("begin", "membership")
    # libfaketime clock-rate windows are a proper begin/end pair
    assert classify("start-clock-rate") == ("begin", "clock-rate")
    assert classify("stop-clock-rate") == ("end", "clock-rate")


def test_teardown_heals_and_unhealable_table_rows():
    """The PR-9 table extensions: clock-rate is restored by a clean
    nemesis teardown (unwrap); membership is NOT — State.teardown does
    not restore the member set, so unresolved reconfigs must stay on
    the books for replay — and neither is unhealable evidence."""
    from jepsen_tpu.nemesis.faults import (
        KINDS, ROW_HEALERS, TEARDOWN_HEALS, UNHEALABLE_KINDS,
    )
    assert "membership" in KINDS and "clock-rate" in KINDS
    assert "clock-rate" in TEARDOWN_HEALS
    assert "membership" not in TEARDOWN_HEALS
    assert "membership" not in UNHEALABLE_KINDS
    assert "clock-rate" not in UNHEALABLE_KINDS
    # both heal from WHAT was recorded (pre-op set / binary path), not
    # from a kind-wide cluster action
    assert set(ROW_HEALERS) == {"membership", "clock-rate"}


def test_teardown_marker_skips_membership(tmp_path):
    """core's teardown heal marker must leave membership entries
    unhealed: the fake State teardown can't re-join a removed node."""
    from jepsen_tpu.nemesis.faults import TEARDOWN_HEALS, FaultRegistry

    reg = FaultRegistry(tmp_path / "faults.jsonl")
    a = reg.record("net", f="start-partition")
    b = reg.record("membership", f="shrink",
                   value={"pre_members": ["n1", "n2"]})
    assert reg.mark_healed(kinds=TEARDOWN_HEALS, via="teardown") == [a]
    assert [r["id"] for r in reg.unhealed()] == [b]
    reg.close()


def test_fault_registry_roundtrip_and_reopen(tmp_path):
    from jepsen_tpu.nemesis.faults import FaultRegistry

    p = tmp_path / "faults.jsonl"
    reg = FaultRegistry(p)
    a = reg.record("net", f="start-partition", value="majority")
    b = reg.record("clock", f="bump", value={"n1": 100})
    assert [r["id"] for r in reg.unhealed()] == [a, b]
    assert reg.mark_healed(kind="net", via="nemesis") == [a]
    assert [r["id"] for r in reg.unhealed()] == [b]
    reg.close()
    # reopen: the durable log reconstructs the same state
    reg2 = FaultRegistry(p)
    assert [r["id"] for r in reg2.unhealed()] == [b]
    # ids keep monotonically increasing after reopen
    c = reg2.record("net", f="start-partition")
    assert c > b
    # healing twice marks once
    assert reg2.mark_healed(fault_id=b) == [b]
    assert reg2.mark_healed(fault_id=b) == []
    # the teardown marker never claims file damage healed
    d = reg2.record("file", f="truncate-file")
    from jepsen_tpu.nemesis.faults import TEARDOWN_HEALS
    assert reg2.mark_healed(kinds=TEARDOWN_HEALS, via="teardown") == [c]
    assert [r["id"] for r in reg2.unhealed()] == [d]
    reg2.close()


def test_replay_unhealed_heals_exactly_once(tmp_path):
    from jepsen_tpu.net import NoopNet
    from jepsen_tpu.nemesis.faults import FaultRegistry, replay_unhealed

    p = tmp_path / "faults.jsonl"
    reg = FaultRegistry(p)
    reg.record("net", f="start-partition")
    reg.record("net", f="start-partition")
    reg.record("file", f="truncate-file")
    test = {"nodes": ["n1", "n2"], "ssh": {"dummy": True},
            "net": NoopNet()}
    out = replay_unhealed(test, reg)
    assert len(out["healed"]) == 2      # both net faults, one heal action
    assert len(out["unhealable"]) == 1  # file damage has no inverse
    assert test["_net_log"] == [("heal",)]  # exactly one net.heal
    # second replay: net entries are marked healed; nothing re-applied
    out2 = replay_unhealed(test, reg)
    assert out2["healed"] == []
    assert test["_net_log"] == [("heal",)]
    reg.close()


def test_heal_clock_raises_when_no_mechanism_works(monkeypatch):
    """A clock heal that can't verify any reset mechanism worked must
    raise — the registry marks healed only on clean return, and a false
    success would durably destroy the only record that the clocks are
    still scrambled."""
    from jepsen_tpu import control
    from jepsen_tpu.control.core import RemoteError
    from jepsen_tpu.nemesis import faults as fm

    monkeypatch.setattr(control, "on", lambda node, test, fn: fn())

    def bad_exec(*a, **k):
        raise RemoteError("command not found")

    monkeypatch.setattr(control, "exec_", bad_exec)
    with pytest.raises(RuntimeError, match="clock-reset"):
        fm._heal_clock({"nodes": ["n1"]})


def test_recover_prefers_longer_wal_over_torn_history(tmp_path):
    """A crash DURING save_1 leaves a torn history.jsonl next to the
    complete journal; --recover must use the journal, not silently
    analyze the truncated history as if the run were complete."""
    from jepsen_tpu import store
    from jepsen_tpu.journal import Journal

    run_dir = tmp_path / "noop" / "20260101T000000.000"
    run_dir.mkdir(parents=True)
    ops = []
    for i in range(6):
        ops.append({"type": "invoke", "f": "write", "value": i,
                    "process": 0, "time": 2 * i, "index": 2 * i})
        ops.append({"type": "ok", "f": "write", "value": i,
                    "process": 0, "time": 2 * i + 1, "index": 2 * i + 1})
    j = Journal(run_dir / "history.wal.jsonl", fsync_interval_s=0)
    for op in ops:
        j.append(op)
    j.close()
    # torn mid-save: only the first 3 ops landed, last one torn
    with open(run_dir / "history.jsonl", "w") as f:
        for op in ops[:3]:
            f.write(json.dumps(op) + "\n")
        f.write('{"type": "inv')
    (run_dir / "test.json").write_text(json.dumps(
        {"name": "noop", "start_time": "20260101T000000.000",
         "nodes": ["n1"], "ssh": {"dummy": True}}))
    main = _cli_main()
    rc = main(["analyze", "--recover", "--store-dir", str(tmp_path),
               "--test-name", "noop", "--no-ssh", "--accelerator", "cpu"])
    assert rc == 0
    recovered = store.load_history("noop", "20260101T000000.000",
                                   str(tmp_path))
    assert len(recovered) == len(ops)  # journal won over the torn file
    results = json.loads((run_dir / "results.json").read_text())
    assert results["incomplete"] is True


def test_heal_refuses_to_heal_blind(tmp_path):
    """cli heal with faults on the books but no readable node list must
    NOT mark them healed — that would destroy the only record that
    healing is still needed."""
    import argparse

    from jepsen_tpu import cli
    from jepsen_tpu.nemesis.faults import FaultRegistry

    run_dir = tmp_path / "t" / "ts"
    run_dir.mkdir(parents=True)
    reg = FaultRegistry(run_dir / "faults.jsonl")
    reg.record("net", f="start-partition")
    reg.close()
    # no test.json at all
    opts = argparse.Namespace(dir=str(run_dir), test_name=None,
                              timestamp=None, store_dir=str(tmp_path))
    assert cli.heal_cmd(opts) == cli.EXIT_UNKNOWN
    reg = FaultRegistry(run_dir / "faults.jsonl")
    assert len(reg.unhealed()) == 1  # registry untouched
    reg.close()


# ---------------------------------------------------------------------------
# Chaos: SIGKILL mid-run -> analyze --recover -> cli heal
# ---------------------------------------------------------------------------

def _cli_main():
    from jepsen_tpu import cli
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.fakes import noop_test

    def build(opts):
        return cli.test_opts_to_test(
            opts, noop_test(checker=linearizable(accelerator="cpu")))

    return cli.single_test_cmd(build)


@pytest.mark.chaos
def test_sigkill_midrun_recover_and_heal(tmp_path):
    """The acceptance scenario end to end: a fake-mode run SIGKILLed
    mid-case leaves a replayable WAL and an unhealed-fault registry;
    ``analyze --recover`` produces a valid-but-incomplete verdict over
    the partial history; ``cli heal`` restores net state and a second
    heal is a no-op."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "crashsafe_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, worker, str(tmp_path)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    # wait for the WAL to accumulate ops, then kill mid-case
    wal = None
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            wals = list(tmp_path.glob("noop/*/history.wal.jsonl"))
            if wals and wals[0].read_text().count("\n") >= 40:
                wal = wals[0]
                break
            if proc.poll() is not None:
                out = proc.stdout.read()
                pytest.fail(f"worker exited early ({proc.returncode}):\n"
                            f"{out[-4000:]}")
            time.sleep(0.05)
        assert wal is not None, "WAL never appeared"
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)

    run_dir = wal.parent
    # the crash left: a journal, an early test.json, an unhealed fault —
    # and NO saved history/results
    assert not (run_dir / "history.jsonl").exists()
    assert not (run_dir / "results.json").exists()
    assert (run_dir / "test.json").exists()
    from jepsen_tpu.nemesis.faults import FaultRegistry
    freg = FaultRegistry(run_dir / "faults.jsonl")
    unhealed = freg.unhealed()
    freg.close()
    assert [r["kind"] for r in unhealed] == ["net"]

    # analyze --recover: a valid verdict over the partial history,
    # badged incomplete; the run becomes re-analyzable normally
    main = _cli_main()
    rc = main(["analyze", "--recover", "--store-dir", str(tmp_path),
               "--no-ssh", "--accelerator", "cpu"])
    assert rc == 0
    results = json.loads((run_dir / "results.json").read_text())
    assert results["valid?"] is True
    assert results["incomplete"] is True
    assert (run_dir / "history.jsonl").exists()
    ops = [json.loads(line) for line in
           (run_dir / "history.jsonl").read_text().splitlines()]
    assert len(ops) >= 40
    test_json = json.loads((run_dir / "test.json").read_text())
    assert test_json.get("wal_recovered") is True

    # cli heal: replays the unhealed partition heal (dummy transport ->
    # NoopNet), marks it healed; the second heal is a no-op
    rc = main(["heal", str(tmp_path)])
    assert rc == 0
    freg = FaultRegistry(run_dir / "faults.jsonl")
    assert freg.unhealed() == []
    freg.close()
    rc = main(["heal", str(tmp_path)])
    assert rc == 0


@pytest.mark.chaos
def test_failed_teardown_triggers_crash_path_replay(tmp_path):
    """A nemesis whose teardown keeps dying (after the backoff retries)
    leaves its partition unmarked — core.run's crash-path finally
    replays the heal, so the run still ends with a clean cluster and a
    fully-healed registry."""
    from jepsen_tpu import core
    from jepsen_tpu import generator as gen
    from jepsen_tpu import nemesis as nem
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.nemesis.faults import FaultRegistry

    class TeardownDies(nem.Nemesis):
        def __init__(self, inner):
            self.inner = inner

        def setup(self, test):
            return TeardownDies(self.inner.setup(test))

        def fs(self):
            return self.inner.fs()

        def invoke(self, test, op):
            return self.inner.invoke(test, op)

        def teardown(self, test):
            raise RuntimeError("teardown dies every time")

    db = AtomDB()
    # a partition that the generator never stops: only teardown (which
    # dies) or the crash-path replay can heal it
    g = gen.Seq([
        gen.nemesis_gen(gen.Seq([
            {"type": "info", "f": "start-partition", "value": None}])),
        gen.clients(gen.limit(4, gen.cycle(gen.Seq(
            [{"type": "invoke", "f": "write", "value": 1}])))),
    ])
    t = noop_test(db=db, client=AtomClient(db),
                  nemesis=TeardownDies(nem.partitioner()),
                  generator=g, store_dir=str(tmp_path), time_limit=30.0)
    result = core.run(t)
    runs = list(tmp_path.glob("noop/*/faults.jsonl"))
    assert runs, "fault registry missing"
    freg = FaultRegistry(runs[0])
    assert freg.unhealed() == []  # crash-path replay healed the partition
    freg.close()
    rows = [json.loads(line) for line in runs[0].read_text().splitlines()]
    heals = [r for r in rows if r["op"] == "heal"]
    assert heals and heals[-1]["via"] == "replay"
    # the replay really drove the net layer: the last action on the
    # (NoopNet) log is the heal
    assert result["_net_log"][-1] == ("heal",)
