"""The stdlib PostgreSQL wire client against a scripted in-process server.

Covers the protocol surface the postgres-family suites depend on
(startup + trust/md5/SCRAM-SHA-256 auth, simple-query resultsets,
SQLSTATE error surfacing, int[] parsing), the way the reference
unit-tests its transports against local endpoints (control_test.clj
pattern, SURVEY.md §4)."""
from __future__ import annotations

import base64
import hashlib
import hmac
import socket
import struct
import threading

import pytest

from jepsen_tpu.suites._postgres import (PGConnection, PgError,
                                         parse_int_array)

PASSWORD = "jepsenpw"
USER = "jepsen"
SALT = b"0123456789abcdef"
ITERS = 4096


def _msg(mtype: bytes, payload: bytes) -> bytes:
    return mtype + struct.pack("!I", len(payload) + 4) + payload


def _ready() -> bytes:
    return _msg(b"Z", b"I")


def _row_description(names) -> bytes:
    body = struct.pack("!H", len(names))
    for n in names:
        body += n.encode() + b"\x00" + struct.pack("!IHIHIH", 0, 0, 23, 4,
                                                   0, 0)
    return _msg(b"T", body)


def _data_row(cells) -> bytes:
    body = struct.pack("!H", len(cells))
    for c in cells:
        if c is None:
            body += struct.pack("!i", -1)
        else:
            raw = str(c).encode()
            body += struct.pack("!i", len(raw)) + raw
    return _msg(b"D", body)


def _error(sqlstate: str, message: str) -> bytes:
    body = (b"SERROR\x00" + b"C" + sqlstate.encode() + b"\x00"
            + b"M" + message.encode() + b"\x00\x00")
    return _msg(b"E", body)


class FakeServer:
    """Accepts one connection, runs the chosen auth flow, answers
    scripted queries."""

    def __init__(self, auth: str = "trust"):
        self.auth = auth
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.errors: list[str] = []
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _recv_startup(self, conn) -> bytes:
        n = struct.unpack("!I", self._exact(conn, 4))[0]
        return self._exact(conn, n - 4)

    def _recv_msg(self, conn) -> tuple[bytes, bytes]:
        head = self._exact(conn, 5)
        n = struct.unpack("!I", head[1:])[0]
        return head[:1], self._exact(conn, n - 4)

    @staticmethod
    def _exact(conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client gone")
            buf += chunk
        return buf

    def _do_auth(self, conn) -> None:
        if self.auth == "trust":
            conn.sendall(_msg(b"R", struct.pack("!I", 0)))
        elif self.auth == "md5":
            salt = b"ab12"
            conn.sendall(_msg(b"R", struct.pack("!I", 5) + salt))
            mtype, body = self._recv_msg(conn)
            inner = hashlib.md5(PASSWORD.encode() + USER.encode()).hexdigest()
            expect = b"md5" + hashlib.md5(
                inner.encode() + salt).hexdigest().encode() + b"\x00"
            if mtype != b"p" or body != expect:
                self.errors.append(f"bad md5 response {body!r}")
            conn.sendall(_msg(b"R", struct.pack("!I", 0)))
        elif self.auth == "scram":
            conn.sendall(_msg(b"R", struct.pack("!I", 10)
                              + b"SCRAM-SHA-256\x00\x00"))
            mtype, body = self._recv_msg(conn)
            mech, rest = body.split(b"\x00", 1)
            if mech != b"SCRAM-SHA-256":
                self.errors.append(f"bad mechanism {mech!r}")
            n = struct.unpack("!I", rest[:4])[0]
            client_first = rest[4:4 + n].decode()
            bare = client_first[3:]  # strip "n,,"
            client_nonce = dict(kv.split("=", 1) for kv in
                                bare.split(","))["r"]
            server_nonce = client_nonce + "SRVNONCE"
            server_first = (f"r={server_nonce},"
                            f"s={base64.b64encode(SALT).decode()},i={ITERS}")
            conn.sendall(_msg(b"R", struct.pack("!I", 11)
                              + server_first.encode()))
            _, final = self._recv_msg(conn)
            final = final.decode()
            without_proof, proof_b64 = final.rsplit(",p=", 1)
            salted = hashlib.pbkdf2_hmac("sha256", PASSWORD.encode(), SALT,
                                         ITERS)
            ckey = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
            skey = hashlib.sha256(ckey).digest()
            auth_msg = ",".join([bare, server_first, without_proof]).encode()
            sig = hmac.new(skey, auth_msg, hashlib.sha256).digest()
            expect = bytes(a ^ b for a, b in zip(ckey, sig))
            if base64.b64decode(proof_b64) != expect:
                self.errors.append("bad scram proof")
            server_key = hmac.new(salted, b"Server Key",
                                  hashlib.sha256).digest()
            server_sig = hmac.new(server_key, auth_msg,
                                  hashlib.sha256).digest()
            conn.sendall(_msg(b"R", struct.pack("!I", 12) + b"v="
                              + base64.b64encode(server_sig)))
            conn.sendall(_msg(b"R", struct.pack("!I", 0)))

    def _serve(self):
        conn, _ = self.sock.accept()
        try:
            startup = self._recv_startup(conn)
            proto = struct.unpack("!I", startup[:4])[0]
            if proto != 196608:
                self.errors.append(f"bad protocol {proto}")
            kv = startup[4:].rstrip(b"\x00").split(b"\x00")
            params = dict(zip(kv[::2], kv[1::2]))
            if params.get(b"user") != USER.encode():
                self.errors.append(f"bad user {params.get(b'user')!r}")
            self._do_auth(conn)
            conn.sendall(_msg(b"S", b"server_version\x0015.fake\x00"))
            conn.sendall(_msg(b"K", struct.pack("!II", 1, 2)))
            conn.sendall(_ready())
            while True:
                mtype, body = self._recv_msg(conn)
                if mtype == b"X":
                    return
                sql = body.rstrip(b"\x00").decode()
                if sql.startswith("SELECT"):
                    conn.sendall(_row_description(["k", "elems"]))
                    conn.sendall(_data_row([5, "{1,2,3}"]))
                    conn.sendall(_data_row([None, "{}"]))
                    conn.sendall(_msg(b"C", b"SELECT 2\x00"))
                elif sql.startswith("BOOM"):
                    conn.sendall(_error("40001", "serialization failure"))
                else:
                    conn.sendall(_msg(b"C", b"UPDATE 1\x00"))
                conn.sendall(_ready())
        except ConnectionError:
            pass
        finally:
            conn.close()
            self.sock.close()


@pytest.mark.parametrize("auth", ["trust", "md5", "scram"])
def test_auth_and_query_roundtrip(auth):
    srv = FakeServer(auth=auth)
    conn = PGConnection("127.0.0.1", srv.port, user=USER, password=PASSWORD,
                        timeout_s=5)
    assert conn.parameters["server_version"] == "15.fake"
    rows, tag = conn.query("SELECT k, elems FROM lists")
    assert rows == [("5", "{1,2,3}"), (None, "{}")]
    assert tag == "SELECT 2"
    rows, tag = conn.query("UPDATE registers SET v = 1")
    assert rows == [] and conn.rowcount(tag) == 1
    conn.close()
    srv.thread.join(timeout=5)
    assert srv.errors == []


def test_error_surfacing_keeps_connection_usable():
    srv = FakeServer()
    conn = PGConnection("127.0.0.1", srv.port, user=USER, timeout_s=5)
    with pytest.raises(PgError) as err:
        conn.query("BOOM")
    assert err.value.sqlstate == "40001"
    # connection resynced on ReadyForQuery: further queries work
    assert conn.query("UPDATE t SET x=1")[1] == "UPDATE 1"
    conn.close()
    srv.thread.join(timeout=5)
    assert srv.errors == []


def test_parse_int_array():
    assert parse_int_array("{1,2,3}") == [1, 2, 3]
    assert parse_int_array("{}") == []
    assert parse_int_array(None) == []
    assert parse_int_array("{-4}") == [-4]


def test_client_reconnects_after_net_error():
    """After an OSError the client marks the socket desynced and the next
    invoke reconnects instead of reusing it (the interpreter only reopens
    clients on "info" completions, so read "fail"s would otherwise keep a
    poisoned connection)."""
    from jepsen_tpu.suites.postgres import PostgresClient

    srv1, srv2 = FakeServer(), FakeServer()
    ports = iter([srv1.port, srv2.port])

    class TClient(PostgresClient):
        DB_NAME, DB_USER, DB_PASS = "postgres", USER, PASSWORD

        def endpoint(self, test, node):
            return "127.0.0.1", next(ports)

    c = TClient(timeout_s=5).open({"nodes": ["n1"]}, "n1")
    assert c.conn.query("UPDATE t SET x=1")[1] == "UPDATE 1"
    # sever the socket under the client: next op fails with OSError
    c.conn.sock.close()
    done = c.invoke({}, {"f": "read", "value": [1, None]})
    assert done["type"] == "fail" and done["error"][0] == "net"
    assert c._broken
    # next invoke transparently reconnects (to srv2) and succeeds
    done = c.invoke({}, {"f": "write", "value": [1, 5]})
    assert done["type"] == "ok" and not c._broken
    c.close({})
