"""Soundness fuzz for the round-4 checkers (SURVEY §4's
differential-oracle strategy): valid-by-construction histories must
NEVER be convicted, and planted anomalies must always be caught.

Covered: the fauna multimonotonic read-skew SCC checker (vs an O(n²)
pairwise incomparability oracle), the ts-order state machine, the
monotonic-key cycle checker (tidb), and the ledger double-spend
checker."""
import random

from jepsen_tpu.workloads import fauna_multimonotonic, ledger, monotonic_key


def _simulate_multi_reads(rng, n_keys=4, n_ops=60):
    """Sequential execution of per-key increments with interleaved
    snapshot reads — every read sees a true moment-in-time state, so
    both checkers must pass."""
    state = {k: 0 for k in range(n_keys)}
    ts = 0
    reads = []
    for i in range(n_ops):
        if rng.random() < 0.5:
            k = rng.randrange(n_keys)
            state[k] += 1
            ts += 1
        else:
            ts += 1
            ks = rng.sample(range(n_keys), rng.randint(1, n_keys))
            reads.append({
                "type": "ok", "f": "read", "index": i,
                "value": {"ts": ts,
                          "registers": {k: {"value": state[k], "ts": ts}
                                        for k in ks}}})
    return reads


def _pairwise_skew_oracle(reads):
    """O(n²) oracle for the 2-cycle case: a pair of reads where one key
    increases and another decreases (multimonotonic.clj's map-compare
    incomparability)."""
    states = [fauna_multimonotonic.read_state(op) for op in reads]
    for i in range(len(states)):
        for j in range(i + 1, len(states)):
            common = set(states[i]) & set(states[j])
            signs = {(states[i][k] > states[j][k]) - (states[i][k] <
                                                      states[j][k])
                     for k in common}
            if 1 in signs and -1 in signs:
                return True
    return False


def test_read_skew_fuzz_no_false_convictions():
    for seed in range(30):
        rng = random.Random(seed)
        reads = _simulate_multi_reads(rng)
        out = fauna_multimonotonic.ReadSkewChecker().check({}, reads, {})
        assert out["valid?"] is True, (seed, out)
        assert _pairwise_skew_oracle(reads) is False
        out = fauna_multimonotonic.TsOrderChecker().check({}, reads, {})
        assert out["valid?"] is True, (seed, out)


def test_read_skew_fuzz_agrees_with_pairwise_oracle_on_mutations():
    """Mutate a valid history; wherever the pairwise oracle sees a
    2-cycle, the SCC checker must convict too (SCC also catches longer
    cycles, so only oracle→checker is implied)."""
    caught = 0
    for seed in range(40):
        rng = random.Random(1000 + seed)
        reads = _simulate_multi_reads(rng, n_ops=40)
        if len(reads) < 3:
            continue
        # swap two observed values of one key between two reads
        victims = [op for op in reads
                   if len(fauna_multimonotonic.read_state(op)) >= 2]
        if len(victims) < 2:
            continue
        a, b = rng.sample(victims, 2)
        ks = list(set(fauna_multimonotonic.read_state(a))
                  & set(fauna_multimonotonic.read_state(b)))
        if len(ks) < 2:
            continue
        k1, k2 = rng.sample(ks, 2)
        ra, rb = a["value"]["registers"], b["value"]["registers"]
        # force a: k1 low, k2 high; b: k1 high, k2 low
        ra[k1]["value"], rb[k1]["value"] = 0, 10
        ra[k2]["value"], rb[k2]["value"] = 10, 0
        oracle = _pairwise_skew_oracle(reads)
        out = fauna_multimonotonic.ReadSkewChecker().check({}, reads, {})
        if oracle:
            caught += 1
            assert out["valid?"] is False, seed
    assert caught >= 10, f"mutation fuzz only produced {caught} skews"


def _simulate_mono_key(rng, n_keys=4, n_ops=50):
    """Sequential per-key increments + whole-pool reads with realtime
    metadata — valid by construction."""
    state = {k: -1 for k in range(n_keys)}
    history = []
    t = 0
    for i in range(n_ops):
        p = i % 3
        if rng.random() < 0.5:
            k = rng.randrange(n_keys)
            state[k] += 1
            history.append({"type": "invoke", "f": "inc", "value": k,
                            "process": p, "time": t})
            history.append({"type": "ok", "f": "inc",
                            "value": {k: state[k]}, "process": p,
                            "time": t + 1})
        else:
            history.append({"type": "invoke", "f": "read", "value": None,
                            "process": p, "time": t})
            history.append({"type": "ok", "f": "read",
                            "value": dict(state), "process": p,
                            "time": t + 1})
        t += 2
    return history


def test_monotonic_key_fuzz_no_false_convictions():
    for seed in range(25):
        rng = random.Random(seed)
        history = _simulate_mono_key(rng)
        out = monotonic_key.checker().check({"accelerator": "cpu"},
                                            history, {})
        assert out["valid?"] is True, (seed, out)


def _simulate_ledger(rng, n_accounts=3, n_ops=60):
    """Guarded sequential ledger — never double-spends."""
    balances = {a: 0 for a in range(n_accounts)}
    history = []
    for i in range(n_ops):
        a = rng.randrange(n_accounts)
        amount = rng.randint(-3, 3)
        if amount >= 0 or balances[a] + amount >= 0:
            if amount != 0:
                balances[a] += amount
                history.append({"type": "ok", "f": "transfer",
                                "value": [a, amount, i]})
        else:
            history.append({"type": "fail", "f": "transfer",
                            "value": [a, amount, i]})
        if rng.random() < 0.1:  # indeterminate deposit: counts
            balances[a] += 2
            history.append({"type": "info", "f": "transfer",
                            "value": [a, 2, 1000 + i]})
    return history


def test_ledger_fuzz_no_false_convictions():
    for seed in range(40):
        rng = random.Random(seed)
        history = _simulate_ledger(rng)
        out = ledger.LedgerChecker().check({}, history, {})
        assert out["valid?"] is True, (seed, out)


def test_ledger_fuzz_catches_planted_double_spends():
    for seed in range(20):
        rng = random.Random(seed)
        history = _simulate_ledger(rng)
        # plant: one acknowledged withdrawal that overdraws account 0
        balance = sum(v[1] for op in history
                      for v in [op["value"]]
                      if v[0] == 0 and (op["type"] == "ok"
                                        or (op["type"] == "info"
                                            and v[1] > 0)))
        history.append({"type": "ok", "f": "transfer",
                        "value": [0, -(balance + 1), 9999]})
        out = ledger.LedgerChecker().check({}, history, {})
        assert out["valid?"] is False, seed
        assert any(e["account"] == 0 for e in out["errors"])
