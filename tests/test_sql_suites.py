"""MySQL-protocol suite family tests: galera, percona, mysql-cluster,
tidb — test-map shapes, DB automation command shapes over the dummy
remote, fake-mode lifecycle runs for the new bank/dirty-reads fake
paths, and the shared SQL client's workload bodies against a stub
connection."""
from jepsen_tpu import control
from jepsen_tpu.suites import galera, mysql_cluster, percona, tidb
from jepsen_tpu.suites._mysql_client import MySQLSuiteClient, parse_int_list
from jepsen_tpu.workloads import dirty_reads

NODES = ["n1", "n2", "n3", "n4", "n5"]


from conftest import run_fake  # noqa: E402
import pytest


# ---------------------------------------------------------------------------
# config generation
# ---------------------------------------------------------------------------

def test_galera_wsrep_config():
    cfg = galera.wsrep_config({"nodes": NODES})
    assert "wsrep_cluster_address = gcomm://n1,n2,n3,n4,n5" in cfg
    assert "wsrep_on = ON" in cfg
    assert "binlog_format = ROW" in cfg


def test_mysql_cluster_config_ini_roles():
    t = {"nodes": NODES}
    ini = mysql_cluster.config_ini(t)
    # mgmd on every node (ids 1..5), ndbd on first four (ids 11..14),
    # mysqld everywhere (ids 21..25) — mysql_cluster.clj:54-118
    assert "NodeId=1" in ini and "NodeId=5" in ini
    assert "NodeId=11" in ini and "NodeId=14" in ini
    assert "NodeId=15" not in ini.split("[mysqld]")[0]
    assert "NodeId=21" in ini and "NodeId=25" in ini
    cnf = mysql_cluster.my_cnf(t, "n3")
    assert "ndbcluster" in cnf
    assert "ndb-connectstring=n1,n2,n3,n4,n5" in cnf
    assert "ndb-nodeid=23" in cnf


def test_tidb_cluster_strings():
    t = {"nodes": NODES}
    assert tidb.initial_cluster(t).startswith("pd1=http://n1:2380,")
    assert tidb.pd_endpoints(t) == ("n1:2379,n2:2379,n3:2379,"
                                    "n4:2379,n5:2379")


def test_tidb_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = tidb.TiDBDB()
    try:
        control.on("n2", t, lambda: db.start_pd(t, "n2"))
        control.on("n2", t, lambda: db.start_kv(t, "n2"))
        control.on("n2", t, lambda: db.start_db(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--name pd2" in joined
        assert "--initial-cluster" in joined
        assert "--store tikv" in joined
        assert "--advertise-addr n2:20160" in joined
    finally:
        control.disconnect_all(t)


# ---------------------------------------------------------------------------
# fake-mode lifecycle: bank, dirty-reads, append
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_galera_fake_bank_run():
    result = run_fake(galera.galera_test, workload="bank")
    assert result["results"]["valid?"] is True, result["results"]
    # bank reads must be balance dicts summing to the invariant total
    reads = [op for op in result["history"]
             if op.get("f") == "read" and op.get("type") == "ok"]
    assert reads and all(sum(op["value"].values()) == 80 for op in reads)


@pytest.mark.slow
def test_galera_fake_dirty_reads_run():
    result = run_fake(galera.galera_test, workload="dirty-reads")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_percona_fake_bank_run():
    result = run_fake(percona.percona_test, workload="bank")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_tidb_fake_append_run():
    result = run_fake(tidb.tidb_test, workload="append")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_tidb_fake_long_fork_run():
    result = run_fake(tidb.tidb_test, workload="long-fork")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_mysql_cluster_fake_register_run():
    result = run_fake(mysql_cluster.mysql_cluster_test, workload="register")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# dirty-reads checker semantics
# ---------------------------------------------------------------------------

def test_dirty_reads_checker_flags_failed_write_values():
    chk = dirty_reads.checker()
    history = [
        {"type": "invoke", "f": "write", "value": 7, "process": 0},
        {"type": "fail", "f": "write", "value": 7, "process": 0},
        {"type": "invoke", "f": "read", "value": None, "process": 1},
        {"type": "ok", "f": "read", "value": [7, 7, 7, 7], "process": 1},
    ]
    out = chk.check({}, history, {})
    assert out["valid?"] is False
    assert out["dirty-count"] == 1


def test_dirty_reads_checker_reports_inconsistent_reads():
    chk = dirty_reads.checker()
    history = [
        {"type": "invoke", "f": "write", "value": 3, "process": 0},
        {"type": "ok", "f": "write", "value": 3, "process": 0},
        {"type": "ok", "f": "read", "value": [3, 3, -1, -1], "process": 1},
    ]
    out = chk.check({}, history, {})
    assert out["valid?"] is True            # only dirty reads invalidate
    assert out["inconsistent-count"] == 1


# ---------------------------------------------------------------------------
# the shared SQL client against a stub connection
# ---------------------------------------------------------------------------

class StubConn:
    """Collects queries; returns canned rows per matching prefix."""

    def __init__(self, replies=()):
        self.queries: list[str] = []
        self.replies = dict(replies)

    def query(self, sql):
        self.queries.append(sql)
        for prefix, rows in self.replies.items():
            if sql.startswith(prefix):
                return rows
        return (0, 0)

    def close(self):
        pass


def test_sql_client_transfer_refuses_overdraft():
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT balance": [("3",)]})
    out = c.invoke({"accounts": [0, 1]},
                   {"f": "transfer", "type": "invoke",
                    "value": {"from": 0, "to": 1, "amount": 5}})
    assert out["type"] == "fail" and out["error"][0] == "negative"
    assert any(q == "ROLLBACK" for q in c.conn.queries)
    assert not any(q.startswith("UPDATE") for q in c.conn.queries)


def test_sql_client_transfer_commits():
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT balance": [("10",)]})
    out = c.invoke({}, {"f": "transfer", "type": "invoke",
                        "value": {"from": 0, "to": 1, "amount": 5}})
    assert out["type"] == "ok"
    updates = [q for q in c.conn.queries if q.startswith("UPDATE")]
    assert len(updates) == 2 and c.conn.queries[-1] == "COMMIT"


def test_sql_client_txn_append_and_read():
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT elems": [("1,2,3",)]})
    out = c.invoke({}, {"f": "txn", "type": "invoke",
                        "value": [["r", 5, None], ["append", 5, 4]]})
    assert out["type"] == "ok"
    assert out["value"][0] == ["r", 5, [1, 2, 3]]
    assert out["value"][1] == ["append", 5, 4]
    assert any("CONCAT" in q for q in c.conn.queries)
    assert c.conn.queries[-1] == "COMMIT"


def test_sql_client_wr_txn_reads_registers():
    c = MySQLSuiteClient(txn_style="wr")
    c.conn = StubConn({"SELECT v FROM registers": [("9",)]})
    out = c.invoke({}, {"f": "txn", "type": "invoke",
                        "value": [["r", 1, None], ["w", 1, 2]]})
    assert out["type"] == "ok"
    assert out["value"][0] == ["r", 1, 9]
    assert out["value"][1] == ["w", 1, 2]


def test_sql_client_whole_read_dispatch():
    # bank-style test map → balances dict
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT id, balance": [("0", "10"), ("1", "13")]})
    out = c.invoke({"accounts": [0, 1]},
                   {"f": "read", "type": "invoke", "value": None})
    assert out["value"] == {0: 10, 1: 13}
    # dirty-reads test map → row list
    c.conn = StubConn({"SELECT x FROM dirty": [("5",), ("5",)]})
    out = c.invoke({"dirty-rows": 2},
                   {"f": "read", "type": "invoke", "value": None})
    assert out["value"] == [5, 5]
    # plain → whole set
    c.conn = StubConn({"SELECT elem": [("1",), ("2",)]})
    out = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
    assert out["value"] == [1, 2]


def test_parse_int_list():
    assert parse_int_list(None) == []
    assert parse_int_list("") == []
    assert parse_int_list("1") == [1]
    assert parse_int_list("1,2,3") == [1, 2, 3]


def test_tidb_set_cas_client_body():
    """tidb/sets.clj CasSetClient: the set is one text row appended under
    a txn; reads split it."""
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT value FROM sets_cas": [("3,5",)]})
    out = c.invoke({"set-cas": True}, {"f": "add", "type": "invoke",
                                       "value": 9})
    assert out["type"] == "ok"
    assert any("CONCAT(value, ',9')" in q for q in c.conn.queries)
    assert c.conn.queries[-1] == "COMMIT"
    out = c.invoke({"set-cas": True}, {"f": "read", "type": "invoke",
                                       "value": None})
    assert out["type"] == "ok" and out["value"] == [3, 5]

    # empty set: first add inserts
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT value FROM sets_cas": []})
    c.invoke({"set-cas": True}, {"f": "add", "type": "invoke", "value": 1})
    assert any(q.startswith("INSERT INTO sets_cas") for q in c.conn.queries)


def test_tidb_multitable_bank_client_body():
    """tidb/bank.clj MultiBankClient: balances live in per-account
    tables; transfers keep the overdraft discipline."""
    c = MySQLSuiteClient()
    c.conn = StubConn({"SELECT balance FROM accounts0": [("3",)],
                       "SELECT balance FROM accounts1": [("7",)]})
    out = c.invoke({"bank-multitable": True, "accounts": [0, 1]},
                   {"f": "transfer", "type": "invoke",
                    "value": {"from": 0, "to": 1, "amount": 5}})
    assert out["type"] == "fail" and out["error"][0] == "negative"
    out = c.invoke({"bank-multitable": True, "accounts": [0, 1]},
                   {"f": "transfer", "type": "invoke",
                    "value": {"from": 1, "to": 0, "amount": 5}})
    assert out["type"] == "ok"
    assert any("UPDATE accounts1" in q for q in c.conn.queries)
    assert any("UPDATE accounts0" in q for q in c.conn.queries)
    out = c.invoke({"bank-multitable": True, "accounts": [0, 1]},
                   {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "ok" and out["value"] == {0: 3, 1: 7}


@pytest.mark.slow
def test_tidb_fake_set_cas_and_multitable_runs():
    from jepsen_tpu.suites import tidb

    for wl in ("set-cas", "bank-multitable"):
        result = run_fake(tidb.tidb_test, workload=wl)
        assert result["results"]["valid?"] is True, (wl, result["results"])


def test_tidb_table_workload_client_body():
    """Real-client half of the table probe: create-table issues DDL,
    inserts map 'doesn't exist' to the checker's doesnt-exist error."""
    from jepsen_tpu.suites._mysql import MySQLError

    c = MySQLSuiteClient()
    c.conn = StubConn()
    out = c.invoke({"table-workload": True},
                   {"f": "create-table", "type": "invoke", "value": 3})
    assert out["type"] == "ok"
    assert any(q.startswith("CREATE TABLE IF NOT EXISTS t3") 
               for q in c.conn.queries)
    out = c.invoke({"table-workload": True},
                   {"f": "insert", "type": "invoke", "value": [3, 0]})
    assert out["type"] == "ok"

    class MissingTableConn(StubConn):
        def query(self, sql):
            if sql.startswith("INSERT INTO t"):
                raise MySQLError(1146, "42S02",
                                 "Table 'jepsen.t4' doesn't exist")
            return super().query(sql)

    c = MySQLSuiteClient()
    c.conn = MissingTableConn()
    out = c.invoke({"table-workload": True},
                   {"f": "insert", "type": "invoke", "value": [4, 0]})
    assert out["type"] == "fail" and out["error"][0] == "doesnt-exist"
