"""Tier-1 gate: ``jepsen_tpu/`` lints at ZERO non-baselined findings
with EVERY rule enabled — including the interprocedural families this
tier added (thread-spawn edges, lock-order, cond-wait,
durability-protocol, telemetry-name).

This is the machine that turns a future regression of any encoded
invariant class — a lock taken in the wrong order, a durable artifact
overwritten in place, a naked ``wait()``, a silent metric rename — into
a red build instead of a review catch. The wall-clock assertions mirror
the ``lint_wall_s`` bench bars (< 60 s cold, < 30 s warm) so analysis
cost regressions fail here before they silently eat the tier-1 budget.
"""
from __future__ import annotations

import time
from pathlib import Path

import pytest

from jepsen_tpu.analysis import lint as lint_mod

pytestmark = pytest.mark.lint

ROOT = Path(__file__).resolve().parent.parent


def _lint():
    return lint_mod.lint_paths([str(ROOT / "jepsen_tpu")],
                               baseline=str(ROOT / "lint-baseline.txt"),
                               root=str(ROOT))


def test_all_rules_enabled_and_clean():
    # every registered rule runs (no silent subset): the default
    # selection IS the full set
    t0 = time.monotonic()
    rep = _lint()
    cold_s = time.monotonic() - t0
    assert set(lint_mod.RULE_NAMES) >= {
        "thread-owner", "no-unbounded-block", "lock-order", "cond-wait",
        "durability-protocol", "telemetry-name", "lock-guard",
        "fsync-pairing"}
    assert rep.findings == [], (
        "non-baselined lint findings in jepsen_tpu/ — fix them or add a "
        "documented waiver to lint-baseline.txt:\n"
        + "\n".join(f.render() for f in rep.findings))
    assert rep.stale_waivers == [], (
        "stale lint-baseline.txt entries: " + str(rep.stale_waivers))
    assert cold_s < 60.0, f"cold full-tree lint took {cold_s:.1f}s"


def test_warm_lint_within_budget():
    _lint()  # ensure the AST cache is populated
    t0 = time.monotonic()
    rep = _lint()
    warm_s = time.monotonic() - t0
    assert rep.findings == []
    assert warm_s < 30.0, f"warm full-tree lint took {warm_s:.1f}s"
