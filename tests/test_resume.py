"""Crash-safe resumable checking + the elastic mesh (ISSUE 13).

Pins the acceptance contract of doc/robustness.md "Resumable checks and
the elastic mesh":

* durable checker checkpoints (`check.ckpt`) — interval-gated persists
  of the segmented matrix/frontier carries and the exact CPU frontier's
  session, auto-resumed by the next check BIT-IDENTICALLY while
  re-running only the segments after the last persist;
* validity rules — a hash-mismatched or knob-drifted checkpoint is
  discarded (with the file cleared), never trusted;
* carry threading — a watchdog-demoted matrix rung's completed
  segments seed the demoted rung (down to the exact CPU frontier)
  instead of being discarded;
* the elastic mesh — an injected per-device failure shrinks the
  sharded rung's mesh 8→4 (`mesh_shrink_total`) and the check completes
  sharded, never collapsing to single-device;
* the restartable live daemon — kill/restart resumes tailing at the
  snapshot's WAL offset with divergence-checked adoption.

SIGKILL tests carry the ``chaos`` marker, mesh tests ``mesh`` (the
conftest-forced 8-virtual-CPU-device mesh), daemon tests ``live``.
"""
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from jepsen_tpu import telemetry

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resume_worker import N_PROCS, N_VALUES, block_history  # noqa: E402


@pytest.fixture
def metrics_registry():
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


@pytest.fixture
def healthy_devices():
    """Device-health isolation: elastic-mesh tests mark devices failed;
    nothing may leak into later tests' meshes."""
    from jepsen_tpu import parallel
    parallel.reset_device_health()
    try:
        yield
    finally:
        parallel.reset_device_health()


def _stream(n_blocks, seed=11, plant_anomaly_at=None):
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    return encode_register_ops(
        block_history(n_blocks, seed=seed,
                      plant_anomaly_at=plant_anomaly_at))


def _resume_count(reg, source):
    return reg.counter("checker_resume_total",
                       labels=("source",)).value(source=source)


# ---------------------------------------------------------------------------
# FrontierSession snapshots
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [None, 90])
def test_frontier_snapshot_roundtrip_bit_identical(plant):
    """snapshot() at an arbitrary (mid-operation) cut, restore, absorb
    the rest → the same verdict/failed_event as one uninterrupted
    absorb."""
    from jepsen_tpu.checker.linear_cpu import FrontierSession, check_stream
    s = _stream(120, plant_anomaly_at=plant)
    full = check_stream(s)
    fs = FrontierSession()
    cut = len(s.kind) // 2 + 1  # odd cut: open ops cross it
    fs.absorb(s, end=cut)
    snap = fs.snapshot()
    assert snap is not None
    restored = FrontierSession.restore(snap)
    assert restored is not None
    res = restored.absorb(s, start=restored.events_absorbed)
    assert res.valid == full.valid
    assert res.failed_event == full.failed_event
    assert res.failed_op_index == full.failed_op_index


def test_frontier_snapshot_latches_failure():
    from jepsen_tpu.checker.linear_cpu import FrontierSession
    s = _stream(60, plant_anomaly_at=20)
    fs = FrontierSession()
    res = fs.absorb(s)
    assert res.valid is False
    restored = FrontierSession.restore(fs.snapshot())
    assert restored.result().valid is False
    assert restored.result().failed_event == res.failed_event


# ---------------------------------------------------------------------------
# Segmented matrix chain: differential + durable resume
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [None, 500])
def test_matrix_segmented_matches_oneshot(plant):
    from jepsen_tpu.ops.jitlin import matrix_check, matrix_check_segmented
    s = _stream(600, plant_anomaly_at=plant)
    one = matrix_check(s, force=True)
    seg = matrix_check_segmented(s, max_segment=512)
    assert seg[0] == one[0]
    assert bool(seg[2]) == bool(one[2])


def _count_segments(monkeypatch):
    """Counts matrix_check_resume dispatches (one per segment)."""
    from jepsen_tpu.ops import jitlin
    calls = []
    real = jitlin.matrix_check_resume

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(jitlin, "matrix_check_resume", counting)
    return calls


@pytest.mark.parametrize("plant", [None, 560])
def test_matrix_segmented_ckpt_resume_bit_identical(tmp_path, monkeypatch,
                                                    metrics_registry,
                                                    plant):
    """A chain checkpointed every segment, then re-run against the
    surviving check.ckpt: only the segments after the last persist
    re-run, and the verdict is bit-identical (valid and planted-anomaly
    variants)."""
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops.jitlin import matrix_check_segmented, quiescent_cuts
    s = _stream(600, plant_anomaly_at=plant)
    n_cuts = len(quiescent_cuts(np.asarray(s.kind), 512))
    path = tmp_path / "check.ckpt"
    full = matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))
    assert path.exists()

    calls = _count_segments(monkeypatch)
    resumed = matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=None, resume=True))
    assert resumed == full
    # the last persist covers everything up to the final (or failing)
    # segment: the resumed run re-ran strictly fewer segments
    assert 1 <= len(calls) < n_cuts
    assert _resume_count(metrics_registry, "ckpt") == 1


def test_matrix_ckpt_hash_mismatch_discarded(tmp_path, monkeypatch,
                                             metrics_registry):
    """A checkpoint written for a DIFFERENT history (same shapes) is
    discarded, not trusted: every segment re-runs, the verdict is the
    other history's own, and the stale file is cleared."""
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops.jitlin import matrix_check_segmented, quiescent_cuts
    a = _stream(600, seed=11)
    b = _stream(600, seed=12)
    path = tmp_path / "check.ckpt"
    matrix_check_segmented(
        a, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))
    before = path.read_bytes()

    calls = _count_segments(monkeypatch)
    out = matrix_check_segmented(
        b, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=None, resume=True))
    assert out[0] is True and not out[2]
    assert len(calls) == len(quiescent_cuts(np.asarray(b.kind), 512))
    assert _resume_count(metrics_registry, "ckpt") == 0
    # discarded AND cleared — a stale carry must not survive to mislead
    # the next analyze
    assert not path.exists() or path.read_bytes() != before


def test_matrix_ckpt_knob_drift_discarded(tmp_path, monkeypatch,
                                          metrics_registry):
    """The same history under a different segment-size knob: the
    fingerprint differs, so the checkpoint is discarded with a full
    re-run (a carry is only meaningful under the writer's exact
    config)."""
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops.jitlin import matrix_check_segmented, quiescent_cuts
    s = _stream(600)
    path = tmp_path / "check.ckpt"
    matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))

    calls = _count_segments(monkeypatch)
    out = matrix_check_segmented(
        s, max_segment=1024,
        ckpt=CheckpointStore(path, interval_s=None, resume=True))
    assert out[0] is True
    assert len(calls) == len(quiescent_cuts(np.asarray(s.kind), 1024))
    assert _resume_count(metrics_registry, "ckpt") == 0


def test_matrix_ckpt_model_drift_discarded(tmp_path, monkeypatch,
                                           metrics_registry):
    """The config fingerprint stamps the model step's identity: the
    prefix hash covers only the encoded columns (model-independent),
    so a carry written under a different model must discard on the
    config instead of composing over the wrong operators."""
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops.jitlin import matrix_check_segmented, quiescent_cuts
    s = _stream(600)
    path = tmp_path / "check.ckpt"
    matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))
    doc = json.loads(path.read_text())
    assert doc["config"]["step"]  # the identity is recorded
    doc["config"]["step"] = "some.other.model.step_ids"
    path.write_text(json.dumps(doc))

    calls = _count_segments(monkeypatch)
    out = matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=None, resume=True))
    assert out[0] is True
    assert len(calls) == len(quiescent_cuts(np.asarray(s.kind), 512))
    assert _resume_count(metrics_registry, "ckpt") == 0


def test_resume_check_false_ignores_ckpt(tmp_path, monkeypatch,
                                         metrics_registry):
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops.jitlin import matrix_check_segmented, quiescent_cuts
    s = _stream(600)
    path = tmp_path / "check.ckpt"
    matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))
    calls = _count_segments(monkeypatch)
    matrix_check_segmented(
        s, max_segment=512,
        ckpt=CheckpointStore(path, interval_s=None, resume=False))
    assert len(calls) == len(quiescent_cuts(np.asarray(s.kind), 512))
    assert _resume_count(metrics_registry, "ckpt") == 0


# ---------------------------------------------------------------------------
# Segmented event-scan chain (frontier carry)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [None, 110])
def test_segmented_check_ckpt_resume_bit_identical(tmp_path, monkeypatch,
                                                   metrics_registry,
                                                   plant):
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    from jepsen_tpu.ops import jitlin
    s = _stream(128, plant_anomaly_at=plant)
    path = tmp_path / "check.ckpt"
    full = jitlin.segmented_check(
        s, max_segment=128,
        ckpt=CheckpointStore(path, interval_s=0.0, resume=False))

    sliced = []
    real = jitlin._slice_stream

    def counting(stream, lo, hi):
        sliced.append((lo, hi))
        return real(stream, lo, hi)

    monkeypatch.setattr(jitlin, "_slice_stream", counting)
    resumed = jitlin.segmented_check(
        s, max_segment=128,
        ckpt=CheckpointStore(path, interval_s=None, resume=True))
    assert resumed == full
    assert sliced and sliced[0][0] > 0, \
        "resume must skip the checkpointed prefix"
    assert _resume_count(metrics_registry, "ckpt") == 1


# ---------------------------------------------------------------------------
# Matrix-carry -> CPU-frontier handoff
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [None, 560])
def test_matrix_carry_seeds_frontier_bit_identical(plant):
    """A segmented matrix carry at a quiescent cut seeds the exact CPU
    frontier: absorbing the remainder lands on the same verdict and the
    same failed_event as a full CPU pass — the cross-representation
    handoff the demotion path relies on."""
    from jepsen_tpu.checker.checkpoint import frontier_from_matrix_carry
    from jepsen_tpu.checker.linear_cpu import (
        cas_register_step_py, check_stream,
    )
    from jepsen_tpu.ops.jitlin import _slice_stream, matrix_check_segmented
    s = _stream(600, plant_anomaly_at=plant)
    cut = len(s.kind) // 2
    cut -= cut % 4  # block-aligned → quiescent
    carries = []
    a, _, ix, _ = matrix_check_segmented(
        _slice_stream(s, 0, cut), max_segment=512,
        carry_sink=carries.append)
    assert a and not ix and carries
    carry = carries[-1]
    assert carry["events_done"] == cut
    fs = frontier_from_matrix_carry(carry, step=cas_register_step_py,
                                    init_state=0)
    assert fs is not None
    res = fs.absorb(s, start=cut)
    full = check_stream(s)
    assert res.valid == full.valid
    assert res.failed_event == full.failed_event


def test_dead_or_nonquiescent_carry_declined():
    from jepsen_tpu.checker.checkpoint import frontier_from_matrix_carry
    from jepsen_tpu.checker.linear_cpu import cas_register_step_py
    V = 8
    # dead carry: no live column entries
    dead = {"tot0": np.zeros((1, 2 * V, 2 * V), np.float32),
            "events_done": 4, "S": 1, "V": V, "init_state": 0}
    assert frontier_from_matrix_carry(dead, cas_register_step_py, 0) is None
    # non-quiescent: a live row with a non-zero mask
    t = np.zeros((1, 2 * V, 2 * V), np.float32)
    t[0, V + 3, 0] = 1.0  # mask bit 0 set
    bad = {"tot0": t, "events_done": 4, "S": 1, "V": V, "init_state": 0}
    assert frontier_from_matrix_carry(bad, cas_register_step_py, 0) is None


# ---------------------------------------------------------------------------
# Carry threading across ladder demotions (the watchdog satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [None, 900])
def test_watchdog_demotion_resumes_from_carry(monkeypatch,
                                              metrics_registry, plant):
    """A matrix rung that completes half its segments and then hangs:
    the watchdog abandons it, and the demoted CPU rung RESUMES from the
    threaded carry instead of restarting — counted in
    checker_resume_total{source="carry"}, verdict bit-identical."""
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin

    history = block_history(1100, plant_anomaly_at=plant)
    stream = _stream(1100, plant_anomaly_at=plant)
    full = check_stream(stream)

    monkeypatch.setattr(jitlin, "MATRIX_SEGMENT_EVENTS", 1024)
    real = jitlin.matrix_check_segmented
    cut = (len(stream.kind) // 2) - ((len(stream.kind) // 2) % 4)
    # warm the slice's kernel shapes OUTSIDE the watchdog: the hang must
    # land after the prefix's carries are threaded, not mid-compile
    real(jitlin._slice_stream(stream, 0, cut), max_segment=1024)

    def half_then_hang(s, **kw):
        real(jitlin._slice_stream(s, 0, cut), **kw)
        time.sleep(30)  # the watchdog abandons this thread
        return None

    monkeypatch.setattr(jitlin, "matrix_check_segmented", half_then_hang)

    def no_frontier_kernel(self, *a, **kw):
        raise RuntimeError("injected frontier-kernel failure")

    monkeypatch.setattr(jitlin.JitLinKernel, "check", no_frontier_kernel)

    chk = LinearizableChecker(accelerator="tpu", watchdog_s=3.0)
    out = chk.check({}, history, {"checker_sharded": False})
    assert out["valid?"] == full.valid
    assert out["algorithm"] == "jitlin-cpu(fallback)"
    if plant is not None:
        assert (out["failed-op"] ==
                history[full.failed_op_index])
    assert _resume_count(metrics_registry, "carry") >= 1
    wd = metrics_registry.counter("checker_watchdog_timeouts_total",
                                  labels=("backend",)
                                  ).value(backend="pallas-matrix")
    assert wd == 1


# ---------------------------------------------------------------------------
# The elastic mesh
# ---------------------------------------------------------------------------

@pytest.mark.mesh
def test_shrink_mesh_unit(metrics_registry, healthy_devices):
    import jax

    from jepsen_tpu import parallel
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest-forced 8-device mesh")
    mesh = parallel.auto_mesh(8)
    assert int(mesh.devices.size) == 8

    # attributed failure: the named device is excluded and the width
    # drops to the covering power of two
    err = RuntimeError("UNAVAILABLE: device 7 lost mid collective")
    new = parallel.shrink_mesh(mesh, exc=err)
    assert int(new.devices.size) == 4
    assert 7 in parallel.failed_device_ids()
    assert all(d.id != 7 for d in new.devices.flat)
    # auto_mesh now excludes the casualty everywhere
    assert all(d.id != 7 for d in parallel.auto_mesh(8).devices.flat)
    shrunk = metrics_registry.counter(
        "mesh_shrink_total", labels=("from", "to")).value(
        **{"from": "8", "to": "4"})
    assert shrunk == 1

    # unattributable failure: halve conservatively
    new2 = parallel.shrink_mesh(new, exc=RuntimeError("collective op "
                                                      "failed"))
    assert int(new2.devices.size) == 2
    # the floor bottoms out → None (the ladder then demotes)
    assert parallel.shrink_mesh(new2, exc=err) is None


@pytest.mark.mesh
def test_regrow_mesh_unit(metrics_registry, healthy_devices):
    """The heal path: a probe-passing failed device rejoins and the
    mesh regrows to the next power-of-two width, counted in
    mesh_regrow_total{from,to} (doc/robustness.md "The elastic
    mesh")."""
    import jax

    from jepsen_tpu import parallel
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest-forced 8-device mesh")
    mesh = parallel.auto_mesh(8)
    err = RuntimeError("UNAVAILABLE: device 7 lost mid collective")
    assert int(parallel.shrink_mesh(mesh, exc=err).devices.size) == 4

    # healthy pool, nothing failed after heal -> regrow 4 -> 8
    new = parallel.regrow_mesh()
    assert new is not None and int(new.devices.size) == 8
    assert parallel.failed_device_ids() == set()
    assert all(
        any(d.id == 7 for d in new.devices.flat) for _ in (0,))
    regrown = metrics_registry.counter(
        "mesh_regrow_total", labels=("from", "to")).value(
        **{"from": "4", "to": "8"})
    assert regrown == 1

    # nothing failed: regrow is a no-op
    assert parallel.regrow_mesh() is None

    # a device that FAILS its probe stays excluded: no regrow
    parallel.mark_device_failed(7)
    assert parallel.regrow_mesh(probe=lambda d: False) is None
    assert 7 in parallel.failed_device_ids()


@pytest.mark.mesh
def test_mesh_min_devices_floor(healthy_devices):
    from jepsen_tpu import parallel
    assert parallel.mesh_min_devices(None) == 2
    assert parallel.mesh_min_devices(4) == 4
    assert parallel.mesh_min_devices("garbage") == 2  # tolerant
    mesh = parallel.auto_mesh(8)
    if mesh is None or int(mesh.devices.size) < 8:
        pytest.skip("needs the conftest-forced 8-device mesh")
    err = RuntimeError("UNAVAILABLE: device lost")
    assert parallel.shrink_mesh(mesh, exc=err, min_devices=8) is None


@pytest.mark.mesh
def test_device_failure_shrinks_mesh_bit_identical(monkeypatch,
                                                   metrics_registry,
                                                   healthy_devices):
    """The acceptance scenario: a per-device failure on the sharded
    rung shrinks the mesh 8→4 and the check COMPLETES SHARDED with a
    verdict bit-identical to single-device — no demotion to
    single-device."""
    import jax

    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest-forced 8-device mesh")

    history = block_history(1100, seed=3)
    real = jitlin.matrix_check

    def flaky_on_8(stream, *a, **kw):
        mesh = kw.get("mesh")
        if mesh is not None and int(mesh.devices.size) == 8:
            raise RuntimeError("UNAVAILABLE: device 7 lost in collective")
        return real(stream, *a, **kw)

    monkeypatch.setattr(jitlin, "matrix_check", flaky_on_8)
    chk = LinearizableChecker(accelerator="tpu")
    out = chk.check({}, history, {"checker_sharded": True})
    assert out["algorithm"] == "jitlin-tpu-matrix-sharded", \
        "the shrunken mesh must settle the check — not single-device"
    shrunk = metrics_registry.counter(
        "mesh_shrink_total", labels=("from", "to")).value(
        **{"from": "8", "to": "4"})
    assert shrunk == 1
    demoted = sum(
        r["value"] for r in metrics_registry.snapshot()
        if r.get("name") == "checker_backend_demotions_total"
        and r.get("labels", {}).get("backend") == "sharded-matrix")
    assert demoted == 0

    # bit-identity against the single-device path
    single = LinearizableChecker(accelerator="tpu").check(
        {}, history, {"checker_sharded": False})
    assert out["valid?"] == single["valid?"]


@pytest.mark.mesh
def test_oom_on_sharded_rung_never_poisons_device_health(monkeypatch,
                                                         metrics_registry,
                                                         healthy_devices):
    """A RESOURCE_EXHAUSTED whose message happens to name a device is
    an OOM, not a casualty: the cure is the element-budget halving
    (then an UNATTRIBUTED mesh shrink once the budget bottoms out) —
    the named device must stay healthy and available to future
    meshes."""
    import jax

    from jepsen_tpu import parallel
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin
    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest-forced 8-device mesh")

    # monkeypatch restores the adaptive budget the halvings mutate
    monkeypatch.setattr(jitlin, "MATRIX_MAX_ELEMS",
                        jitlin.MATRIX_MAX_ELEMS)
    history = block_history(1100, seed=4)
    real = jitlin.matrix_check

    def oom_on_8(stream, *a, **kw):
        mesh = kw.get("mesh")
        if mesh is not None and int(mesh.devices.size) == 8:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating buffer "
                "on device 3")
        return real(stream, *a, **kw)

    monkeypatch.setattr(jitlin, "matrix_check", oom_on_8)
    out = LinearizableChecker(accelerator="tpu").check(
        {}, history, {"checker_sharded": True})
    assert out["valid?"] is True
    assert 3 not in parallel.failed_device_ids(), \
        "an OOM must never mark a healthy device failed"


# ---------------------------------------------------------------------------
# Checker-level SIGKILL chaos (the acceptance scenario)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_sigkill_mid_check_resumes_bit_identical(tmp_path, monkeypatch,
                                                 metrics_registry):
    """SIGKILL a run-dir-backed segmented check between two durable
    persists; the next check auto-resumes from check.ckpt, re-runs only
    the remaining segments, settles a verdict bit-identical to an
    uninterrupted check, and clears the checkpoint."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "resume_worker.py")
    name, ts = "resume", "20260804T000000.000Z"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JEPSEN_TPU_MATRIX_SEGMENT_EVENTS"] = "2048"
    proc = subprocess.Popen(
        [sys.executable, worker, str(tmp_path), name, ts, "0.3"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    ckpt = tmp_path / name / ts / "check.ckpt"
    deadline = time.monotonic() + 180
    try:
        while time.monotonic() < deadline:
            if ckpt.exists():
                break
            if proc.poll() is not None:
                pytest.fail(f"worker exited before a checkpoint landed "
                            f"({proc.returncode}):\n"
                            f"{proc.stdout.read()[-4000:]}")
            time.sleep(0.05)
        assert ckpt.exists(), "no durable checkpoint ever appeared"
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)

    # the interrupted check's checkpoint is a forensic artifact
    from jepsen_tpu import store
    assert "check.ckpt" in store.forensic_artifacts(tmp_path / name / ts)

    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.ops import jitlin
    monkeypatch.setattr(jitlin, "MATRIX_SEGMENT_EVENTS", 2048)
    calls = _count_segments(monkeypatch)
    test = {"name": name, "start_time": ts, "store_dir": str(tmp_path),
            "checker_sharded": False}
    history = block_history(4096)
    n_cuts = len(jitlin.quiescent_cuts(
        np.asarray(_stream(4096).kind), 2048))
    out = LinearizableChecker(accelerator="tpu").check(test, history, {})
    assert out["valid?"] is True
    assert out["algorithm"] == "jitlin-tpu-matrix"
    assert _resume_count(metrics_registry, "ckpt") == 1
    assert 1 <= len(calls) < n_cuts, \
        f"resume re-ran {len(calls)}/{n_cuts} segments"
    assert not ckpt.exists(), "a completed check must clear check.ckpt"

    # bit-identical to an uninterrupted check (no checkpoint left, so
    # this second run is from scratch)
    calls.clear()
    scratch = LinearizableChecker(accelerator="tpu").check(
        test, history, {})
    assert len(calls) == n_cuts
    assert scratch["valid?"] == out["valid?"]
    assert scratch["algorithm"] == out["algorithm"]


# ---------------------------------------------------------------------------
# Restartable live daemon
# ---------------------------------------------------------------------------

def _live_history(n_pairs, seed=5):
    rng = np.random.default_rng(seed)
    ops = []
    for i in range(n_pairs):
        v = int(rng.integers(5))
        ops.append({"process": 0, "type": "invoke", "f": "write",
                    "value": v})
        ops.append({"process": 0, "type": "ok", "f": "write", "value": v})
        ops.append({"process": 1, "type": "invoke", "f": "read",
                    "value": None})
        ops.append({"process": 1, "type": "ok", "f": "read", "value": v})
    return ops


@pytest.mark.live
def test_daemon_restart_resumes_at_offset(tmp_path, monkeypatch,
                                          metrics_registry):
    from jepsen_tpu.live import daemon as live_daemon
    monkeypatch.setattr(live_daemon, "SNAPSHOT_MIN_INTERVAL_S", 0.0)
    ops = _live_history(100)
    half = len(ops) // 2
    run_dir = tmp_path / "r" / "20260804T000000.000Z"
    run_dir.mkdir(parents=True)
    wal = run_dir / "history.wal.jsonl"
    with open(wal, "w") as f:
        for op in ops[:half]:
            f.write(json.dumps(op) + "\n")

    d1 = live_daemon.LiveDaemon(store_root=str(tmp_path), poll_s=0.01,
                                accelerator="cpu",
                                registry=metrics_registry)
    d1.poll_once()
    tr1 = next(iter(d1.trackers.values()))
    off = tr1.tailer.offset
    assert off > 0 and tr1.ops_absorbed == half
    assert (run_dir / live_daemon.LIVE_CKPT_NAME).exists()
    d1.stop()

    # the run continues and completes while no daemon is watching
    with open(wal, "a") as f:
        for op in ops[half:]:
            f.write(json.dumps(op) + "\n")
    with open(run_dir / "history.jsonl", "w") as f:
        for op in ops:
            f.write(json.dumps(op) + "\n")

    d2 = live_daemon.LiveDaemon(store_root=str(tmp_path), poll_s=0.01,
                                accelerator="cpu",
                                registry=metrics_registry)
    d2.discover()
    tr2 = next(iter(d2.trackers.values()))
    assert tr2.resumed is True
    assert tr2.tailer.offset == off, \
        "restart must resume tailing at the snapshot's offset"
    assert tr2.ops_absorbed == half
    d2.run_until_idle(timeout_s=60)
    d2.stop()
    status = live_daemon.load_live_status(run_dir)
    assert status["state"] == "final"
    assert status["results"]["valid?"] is True
    assert status["ops_absorbed"] == len(ops)
    assert metrics_registry.counter(
        "live_session_resumes_total").value() == 1
    assert not (run_dir / live_daemon.LIVE_CKPT_NAME).exists(), \
        "a finalized run must clear its restart snapshot"


@pytest.mark.live
def test_daemon_restart_rejects_diverged_wal(tmp_path, monkeypatch,
                                             metrics_registry):
    """A rewritten WAL (different run reusing the dir) fails the
    prefix-hash check: the snapshot is rejected and the tracker
    re-ingests from zero — slower, never diverged."""
    from jepsen_tpu.live import daemon as live_daemon
    monkeypatch.setattr(live_daemon, "SNAPSHOT_MIN_INTERVAL_S", 0.0)
    ops = _live_history(60, seed=6)
    run_dir = tmp_path / "r" / "20260804T000000.000Z"
    run_dir.mkdir(parents=True)
    wal = run_dir / "history.wal.jsonl"
    with open(wal, "w") as f:
        for op in ops[:120]:
            f.write(json.dumps(op) + "\n")
    d1 = live_daemon.LiveDaemon(store_root=str(tmp_path), poll_s=0.01,
                                accelerator="cpu",
                                registry=metrics_registry)
    d1.poll_once()
    d1.stop()
    assert (run_dir / live_daemon.LIVE_CKPT_NAME).exists()

    # a different run reuses the dir: same length prefix, different ops
    other = _live_history(60, seed=7)
    with open(wal, "w") as f:
        for op in other:
            f.write(json.dumps(op) + "\n")
    with open(run_dir / "history.jsonl", "w") as f:
        for op in other:
            f.write(json.dumps(op) + "\n")

    d2 = live_daemon.LiveDaemon(store_root=str(tmp_path), poll_s=0.01,
                                accelerator="cpu",
                                registry=metrics_registry)
    d2.discover()
    tr = next(iter(d2.trackers.values()))
    assert tr.resumed is False
    assert tr.tailer.offset == 0
    d2.run_until_idle(timeout_s=60)
    d2.stop()
    status = live_daemon.load_live_status(run_dir)
    assert status["state"] == "final"
    assert status["ops_absorbed"] == len(other)
    assert metrics_registry.counter(
        "live_session_resume_rejected_total").value() == 1


@pytest.mark.live
def test_encoder_snapshot_roundtrip_differential():
    """LiveRegisterEncoder snapshot at a cut with OPEN ops: restore +
    absorb the rest → the identical encoded stream as one
    uninterrupted encoder."""
    from jepsen_tpu.history import Intern
    from jepsen_tpu.history_ir.builder import LiveRegisterEncoder
    ops = _live_history(40)
    # interleave an op pair so an invoke is open across the cut
    cut = len(ops) // 2 + 1
    full = LiveRegisterEncoder(Intern())
    for op in ops:
        full.add(op)
    full.finalize()

    enc = LiveRegisterEncoder(Intern())
    for op in ops[:cut]:
        enc.add(op)
    enc.encode_resolved()
    snap = enc.snapshot()
    assert snap is not None
    enc2 = LiveRegisterEncoder.restore(snap)
    assert enc2 is not None
    for op in ops[cut:]:
        enc2.add(op)
    enc2.finalize()
    for col in ("kind", "slot", "f", "a", "b", "op_index"):
        assert getattr(enc2.stream, col) == getattr(full.stream, col), col
    assert list(enc2.intern.table) == list(full.intern.table)


# ---------------------------------------------------------------------------
# Preflight knob coverage
# ---------------------------------------------------------------------------

def _pf(t):
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    return pf.preflight(core.prepare_test(t))


def _codes(diags):
    return {d.code for d in diags}


class TestResumeKnobs:
    def test_ckpt_interval_garbage(self):
        from jepsen_tpu import fakes
        diags = _pf(fakes.noop_test(check_ckpt_interval="banana"))
        assert any(d.code == "KNB001"
                   and d.path == "check_ckpt_interval" for d in diags)

    def test_ckpt_interval_numeric_clean(self):
        from jepsen_tpu import fakes
        diags = _pf(fakes.noop_test(check_ckpt_interval=2.5))
        assert "KNB001" not in _codes(diags)
        # negative disables — not a range error
        assert "KNB002" not in _codes(_pf(
            fakes.noop_test(check_ckpt_interval=-1)))

    def test_mesh_min_devices_rows(self):
        from jepsen_tpu import fakes
        assert any(d.code == "KNB001" and d.path == "mesh_min_devices"
                   for d in _pf(fakes.noop_test(mesh_min_devices="lots")))
        diags = _pf(fakes.noop_test(mesh_min_devices="4"))
        assert "KNB001" not in _codes(diags)
        assert "KNB006" in _codes(diags)  # stringly number: warn

    def test_resume_check_bool(self):
        from jepsen_tpu import fakes
        assert any(d.code == "KNB001" and d.path == "resume_check"
                   for d in _pf(fakes.noop_test(resume_check="maybe")))
        assert "KNB001" not in _codes(_pf(
            fakes.noop_test(resume_check=False)))

    def test_env_twins(self, monkeypatch):
        from jepsen_tpu import fakes
        monkeypatch.setenv("JEPSEN_TPU_CHECK_CKPT_INTERVAL", "banana")
        assert any(d.code == "KNB001"
                   and d.path == "JEPSEN_TPU_CHECK_CKPT_INTERVAL"
                   for d in _pf(fakes.noop_test()))
        monkeypatch.setenv("JEPSEN_TPU_CHECK_CKPT_INTERVAL", "7.5")
        monkeypatch.setenv("JEPSEN_TPU_RESUME_CHECK", "sometimes")
        diags = _pf(fakes.noop_test())
        assert any(d.code == "KNB007"
                   and d.path == "JEPSEN_TPU_RESUME_CHECK"
                   for d in diags)
        monkeypatch.setenv("JEPSEN_TPU_RESUME_CHECK", "0")
        monkeypatch.setenv("JEPSEN_TPU_MESH_MIN_DEVICES", "4")
        diags = _pf(fakes.noop_test())
        assert not any(d.path.startswith("JEPSEN_TPU_") for d in diags)


def test_ckpt_knob_coercion():
    from jepsen_tpu.checker import checkpoint as ckpt_mod
    assert ckpt_mod.ckpt_interval({}) == ckpt_mod.DEFAULT_CKPT_INTERVAL_S
    assert ckpt_mod.ckpt_interval({"check_ckpt_interval": 2}) == 2.0
    assert ckpt_mod.ckpt_interval({"check_ckpt_interval": 0}) is None
    assert ckpt_mod.ckpt_interval({"check_ckpt_interval": -3}) is None
    assert ckpt_mod.ckpt_interval({"check_ckpt_interval": "nope"}) \
        == ckpt_mod.DEFAULT_CKPT_INTERVAL_S
    assert ckpt_mod.resume_enabled({}) is True
    assert ckpt_mod.resume_enabled({"resume_check": False}) is False
    assert ckpt_mod.resume_enabled({"resume_check": "garbage"}) is True


def test_encode_array_roundtrip():
    from jepsen_tpu.checker.checkpoint import decode_array, encode_array
    rng = np.random.default_rng(0)
    bits = (rng.random((3, 17)) > 0.5).astype(np.float32)
    out = decode_array(encode_array(bits))
    assert out.shape == bits.shape and (out == bits).all()
    raw = rng.integers(0, 1 << 30, (5, 7)).astype(np.uint32)
    raw[0, 0] = 0xFFFFFFFF
    out = decode_array(encode_array(raw))
    assert out.dtype == np.uint32 and (out == raw).all()
