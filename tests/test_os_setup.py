"""OS automation unit tests over a scripted remote.

Covers the Debian apt path and the CentOS yum/rpm path (reference:
jepsen/src/jepsen/os/debian.clj, os/centos.clj) the way the wire-protocol
suites are covered: every shell command is captured and asserted, with
canned outputs for the query commands.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from jepsen_tpu import control
from jepsen_tpu.control.core import Remote, Result
from jepsen_tpu.os_setup import (
    CentOS, Debian, OS_REGISTRY, install_start_stop_daemon, os_by_name,
    patch_loopback_hostname, yum_install, yum_installed,
    yum_installed_version, yum_maybe_update, yum_uninstall,
)


@dataclass
class ScriptedRemote(Remote):
    """Records every command; answers from a substring-keyed script."""

    script: dict = field(default_factory=dict)  # substring -> (rc, out)
    log: list = field(default_factory=list)
    host: str | None = None
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def connect(self, conn_spec):
        return ScriptedRemote(script=self.script, log=self.log,
                              host=conn_spec.get("host"), _lock=self._lock)

    def execute(self, ctx, cmd):
        with self._lock:
            self.log.append((cmd, ctx.get("stdin")))
        for key, (rc, out) in self.script.items():
            if key in cmd:
                return Result(cmd=cmd, exit_status=rc, out=out, err="",
                              host=self.host)
        return Result(cmd=cmd, exit_status=0, out="", err="", host=self.host)

    def upload(self, ctx, local_paths, remote_path):
        pass

    def download(self, ctx, remote_paths, local_path):
        pass


def _run_on(remote, test, fn):
    test = dict(test)
    test.setdefault("ssh", {})
    test["remote"] = remote
    return control.on("n1", test, fn)


def _test_with(remote, nodes=("n1", "n2")):
    return {"ssh": {}, "remote": remote, "nodes": list(nodes)}


def test_debian_setup_installs_base_packages():
    remote = ScriptedRemote(script={"dpkg-query": (0, "sudo\ncurl\n")})
    Debian(extra_packages=["tcpdump"]).setup(_test_with(remote), "n1")
    cmds = [c for c, _ in remote.log]
    assert any("tee /etc/hosts" in c for c in cmds)
    install = next(c for c in cmds if "apt-get install" in c)
    assert "tcpdump" in install and "iptables" in install
    assert "curl" not in install.split()  # already installed per dpkg-query


def test_debian_hostfile_maps_all_nodes():
    remote = ScriptedRemote()
    Debian().setup(_test_with(remote, nodes=("n1", "n2", "n3")), "n1")
    stdin = next(s for c, s in remote.log if "tee /etc/hosts" in c)
    for n in ("n1", "n2", "n3"):
        assert f" {n}" in stdin


def test_centos_setup_full_path():
    remote = ScriptedRemote(script={
        "hostname": (0, "n1"),
        "cat /etc/hosts": (0, "127.0.0.1 localhost\n10.0.0.2 n2"),
        "rpm -q": (1, "curl\nwget\npackage gcc is not installed\n"),
        "test -x /usr/bin/start-stop-daemon": (1, ""),
    })
    CentOS().setup(_test_with(remote), "n1")
    cmds = [c for c, _ in remote.log]
    # loopback patch appended the hostname to the 127.0.0.1 line
    loop_stdin = [s for c, s in remote.log
                  if "tee /etc/hosts" in c and s and "127.0.0.1" in s]
    assert any("127.0.0.1 localhost n1" in s for s in loop_stdin)
    # yum update gated on the yum log's age
    assert any("/var/log/yum.log" in c and "yum -y update" in c
               for c in cmds)
    # build tools for the clock nemesis's on-node compiles are installed,
    # already-present packages are not
    install = next(c for c in cmds if "yum -y install" in c)
    assert "gcc" in install.split() and "gcc-c++" in install
    assert "curl" not in install.split()
    # start-stop-daemon was absent, so it gets built from the dpkg tarball
    assert any("start-stop-daemon" in c and "cp" in c for c in cmds)
    assert any("./configure" in c for c in cmds)


def test_centos_skips_ssd_build_when_present():
    remote = ScriptedRemote(script={
        "hostname": (0, "n1"),
        "cat /etc/hosts": (0, "127.0.0.1 localhost n1"),
        "rpm -q": (1, ""),
        "test -x /usr/bin/start-stop-daemon": (0, ""),
    })
    CentOS().setup(_test_with(remote), "n1")
    cmds = [c for c, _ in remote.log]
    assert not any("dpkg" in c for c in cmds)
    # loopback line already had the hostname: no hosts rewrite beyond the
    # cluster hostfile
    loop = [c for c, s in remote.log
            if "tee /etc/hosts" in c and s and "localhost n1 n1" in (s or "")]
    assert not loop


def test_yum_helpers():
    # rpm reports misses ON STDOUT ("package b is not installed") — the
    # installed-set parse must not count those lines as package names
    remote = ScriptedRemote(script={
        "VERSION": (0, "2.17"),
        "rpm -q": (1, "a\npackage b is not installed\nc\n"),
    })

    def go():
        assert yum_installed(["a", "b", "c"]) == {"a", "c"}
        yum_install(["a", "b", "c"])
        yum_uninstall(["a", "b"])
        yum_maybe_update()
        assert yum_installed_version("glibc") == "2.17"
        yum_install({"glibc": "2.17"})   # matching version: no install
        yum_install({"glibc": "2.18"})   # mismatch: pinned install
    _run_on(remote, {"ssh": {}}, go)
    cmds = [c for c, _ in remote.log]
    assert any(c.startswith("yum -y install b") for c in cmds)
    assert any("yum -y remove a" in c for c in cmds)
    assert any("glibc-2.18" in c for c in cmds)
    assert not any("glibc-2.17" in c for c in cmds)


def test_install_start_stop_daemon_builds_when_missing():
    remote = ScriptedRemote(script={"test -x": (1, "")})
    _run_on(remote, {"ssh": {}}, install_start_stop_daemon)
    cmds = [c for c, _ in remote.log]
    assert any("wget" in c and "dpkg" in c for c in cmds)
    assert any("make -C utils" in c for c in cmds)


def test_patch_loopback_noop_when_hostname_present():
    remote = ScriptedRemote(script={
        "hostname": (0, "n7"),
        "cat /etc/hosts": (0, "127.0.0.1 localhost n7"),
    })
    _run_on(remote, {"ssh": {}}, patch_loopback_hostname)
    assert not any("tee" in c for c, _ in remote.log)


def test_os_registry_and_suite_option():
    assert os_by_name("centos") is CentOS
    assert set(OS_REGISTRY) == {"debian", "ubuntu", "centos", "smartos",
                                "noop"}
    try:
        os_by_name("bsd")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_suite_os_override():
    """--os centos must override a suite's default Debian automation."""
    from jepsen_tpu.suites import etcd

    test = etcd.etcd_test({"os": "centos", "nodes": ["n1"],
                           "faults": set()})
    assert isinstance(test["os"], CentOS)


def test_smartos_setup_full_path():
    """SmartOS setup: loopback hostfile patch, age-gated pkgin update,
    installed-set-aware install, ipfilter via svcadm (smartos.clj)."""
    from jepsen_tpu.os_setup import SmartOS

    remote = ScriptedRemote(script={
        "hostname": (0, "n1"),
        "cat /etc/hosts": (0, "127.0.0.1\tlocalhost\n10.0.0.2 n2"),
        # curl + wget installed; vim/unzip/rsyslog/logrotate missing
        "pkgin -p list": (0, "curl-8.4.0;x;y\nwget-1.21.4;x;y\n"),
    })
    SmartOS().setup(_test_with(remote), "n1")
    cmds = [c for c, _ in remote.log]
    loop_stdin = [s for c, s in remote.log
                  if "tee /etc/hosts" in c and s and "127.0.0.1" in s]
    assert any("n1" in s.splitlines()[0] for s in loop_stdin)
    # update gated on pkgin's sql.log age
    assert any("/var/db/pkgin/sql.log" in c and "pkgin update" in c
               for c in cmds)
    install = next(c for c in cmds if "pkgin -y install" in c)
    assert "vim" in install.split() and "rsyslog" in install.split()
    assert "curl" not in install.split()  # already present per pkgin list
    assert any("svcadm enable -r ipfilter" in c for c in cmds)


def test_pkgin_helpers_parse_versions():
    from jepsen_tpu.os_setup import (pkgin_install, pkgin_installed,
                                     pkgin_installed_version,
                                     pkgin_uninstall)

    remote = ScriptedRemote(script={
        "pkgin -p list": (0, "gnu-coreutils-9.1;x\ncurl-8.4.0;x\n"),
    })

    def go():
        assert pkgin_installed(["curl", "vim"]) == {"curl"}
        assert pkgin_installed_version("gnu-coreutils") == "9.1"
        assert pkgin_installed_version("vim") is None
        # version pin: mismatched version reinstalls, matching doesn't
        pkgin_install({"curl": "8.5.0", "gnu-coreutils": "9.1"})
        pkgin_uninstall(["curl", "vim"])

    _run_on(remote, {"ssh": {}}, go)
    cmds = [c for c, _ in remote.log]
    assert any("pkgin -y install curl-8.5.0" in c for c in cmds)
    assert not any("install gnu-coreutils" in c for c in cmds)
    assert any("pkgin -y remove curl" in c for c in cmds)
