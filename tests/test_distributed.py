"""Two-process jax.distributed mesh test (VERDICT r2 item 7): proves the
multi-host claim by actually running it — two OS processes, 4 virtual
CPU devices each, one 8-device global mesh, the sharded trim's psum
crossing the process boundary and batch_check's verdicts allgathering.

The workers run tests/distributed_worker.py; each asserts its own view
(device/process counts, trim mask, batch verdicts) and prints DIST-OK.
"""
import os
import socket
import subprocess
import sys

import pytest

# slow lane: spawns two OS processes that each initialize a jax
# runtime — tens of seconds of real time, and dependent on the
# backend's multiprocess support
pytestmark = pytest.mark.slow


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Error signatures of a backend that simply lacks multiprocess
# collective support (vs a real regression in our sharding code). The
# stock CPU PJRT client raises the first one; the others cover older/
# newer jaxlib wordings and gloo-less builds.
_NO_COLLECTIVES_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "multiprocess computations aren't implemented",
    "cross-host collectives are not implemented",
    "CollectivesInterface",
    "distributed computation is not supported",
)


def _missing_collective_support(outs: list[str]) -> str | None:
    """The matched signature line when every failing worker failed for
    lack of backend collective support, else None (a real failure)."""
    for out in outs:
        for line in out.splitlines():
            if any(m.lower() in line.lower()
                   for m in _NO_COLLECTIVES_MARKERS):
                return line.strip()
    return None


def test_two_process_mesh_trim_and_batch_check():
    port = _free_port()
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "distributed_worker.py")
    env = dict(os.environ)
    # the XLA flag must be set before ANY jax import in the worker
    # (sitecustomize may import jax at interpreter start)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the distributed runtime must own backend init: drop the tunnel
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any(p.returncode != 0 for p in procs):
        # runtime capability detection: a backend without multiprocess
        # collectives (this container's CPU PJRT) can't run the test at
        # all — that's an environment limit, not a regression
        sig = _missing_collective_support(outs)
        if sig is not None:
            pytest.skip("backend lacks multiprocess collective support: "
                        + sig[:200])
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"DIST-OK {i}" in out, out[-4000:]
