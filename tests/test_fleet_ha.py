"""Fleet-HA tier: leased checking with fencing, receiver failover +
honest backpressure, ENOSPC park-and-retry, and the self-chaos harness
(doc/robustness.md "Fleet HA").

Covers the ISSUE-19 acceptance surface:

* lease protocol: claim / renew / TTL expiry / takeover, the read-back
  race electing exactly one claimant, and the stale-epoch regression
  pins (a fenced `RunTracker` status write and a fenced
  `CheckpointStore.save` both drop, never land);
* two live daemons over one store: one holder, one waiter, a takeover
  past the TTL, the deposed host fencing itself out;
* Journal / FaultRegistry / ingest ENOSPC: bounded in-memory park, a
  truncate rollback of partially-landed bytes, drain on the next
  append — ENOSPC is transient weather, any other OSError still
  permanently self-disables the journal;
* receiver shedding: 429 + Retry-After on disk headroom, the pool's
  aggregate-lag pressure hook, and an injected ENOSPC park;
* shipper HA: endpoint failover with resync counters, a 429's
  Retry-After obeyed with the un-absorbed bytes re-polled, the sealed
  path when the receiver already holds the final;
* finals race, both orders: exactly one digest-valid history.jsonl,
  the loser told with 409, the seal surviving a receiver restart;
* preflight KNB rows + env twins for the HA knobs, and the
  `fleet_receivers` URL-list validation;
* the fleet-chaos harness end to end (slow lane, `-m fleet_chaos`).
"""
from __future__ import annotations

import errno
import hashlib
import json
import random
import time
import urllib.error
import urllib.request

import pytest

pytestmark = pytest.mark.fleet


def _register_history(n, seed=7, n_procs=3):
    from __graft_entry__ import _register_history as gen
    return gen(n, n_procs=n_procs, seed=seed, n_values=5)


def _write_wal(run_dir, ops, complete=False):
    from jepsen_tpu.journal import Journal
    run_dir.mkdir(parents=True, exist_ok=True)
    j = Journal(run_dir / "history.wal.jsonl", fsync_interval_s=-1)
    for op in ops:
        j.append(op)
    j.close()
    if complete:
        with open(run_dir / "history.jsonl", "w") as f:
            for op in ops:
                f.write(json.dumps(op) + "\n")


def _ctr(reg, name, **labels):
    total = 0
    for row in reg.snapshot():
        if row.get("name") != name:
            continue
        got = row.get("labels", {})
        if any(got.get(k) != v for k, v in labels.items()):
            continue
        total += row.get("value", 0)
    return total


def _lease_store(root, host, clock, ttl=10.0):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.lease import LeaseStore
    return LeaseStore(root, host_id=host, ttl_s=ttl,
                      registry=telemetry.Registry(),
                      time_fn=lambda: clock[0])


# ---------------------------------------------------------------------------
# lease protocol: claim / renew / expiry / takeover / fencing
# ---------------------------------------------------------------------------

def test_lease_acquire_renew_release(tmp_path):
    clock = [1000.0]
    a = _lease_store(tmp_path, "a", clock)
    rd = tmp_path / "demo" / "t0"
    rd.mkdir(parents=True)
    epoch = a.acquire(rd)
    assert epoch == 1
    assert a.held == {str(rd): 1}
    doc = a.read(rd)
    assert doc["host"] == "a" and doc["epoch"] == 1
    assert _ctr(a.registry, "fleet_lease_acquired_total") == 1

    clock[0] += 5.0
    assert a.renew(rd, epoch)
    assert a.read(rd)["renewed_at"] == clock[0]
    # renewal is a heartbeat, never a takeover: the epoch is stable
    assert a.read(rd)["epoch"] == 1
    assert _ctr(a.registry, "fleet_lease_renewals_total") == 1
    assert a.guard(rd, epoch)
    assert _ctr(a.registry, "fleet_lease_fenced_writes_total") == 0

    a.release(rd, epoch)
    assert a.read(rd) is None
    assert a.held == {}


def test_lease_foreign_holder_blocks_until_ttl(tmp_path):
    """A live foreign lease blocks adoption; past the TTL the waiter
    takes over at epoch+1, and the deposed host's renew/guard both say
    no (fencing) with the loss counted."""
    clock = [1000.0]
    a = _lease_store(tmp_path, "a", clock, ttl=10.0)
    b = _lease_store(tmp_path, "b", clock, ttl=10.0)
    rd = tmp_path / "demo" / "t0"
    rd.mkdir(parents=True)
    assert a.acquire(rd) == 1
    assert b.acquire(rd) is None  # a is live: no takeover

    clock[0] += 10.1  # a's lease expires un-renewed
    assert b.acquire(rd) == 2  # takeover bumps the fencing epoch
    assert b.read(rd)["host"] == "b"

    assert not a.renew(rd, 1)
    assert _ctr(a.registry, "fleet_lease_lost_total") == 1
    assert not a.guard(rd, 1)
    assert _ctr(a.registry, "fleet_lease_fenced_writes_total") == 1
    # the deposed host must not unlink its successor's lease
    a.release(rd, 1)
    assert b.read(rd)["host"] == "b"
    assert b.guard(rd, 2)


def test_lease_read_back_race_elects_one_claimant(tmp_path):
    """Two hosts racing an expired lease both write; last-writer-wins
    plus the read-back verify elects exactly one, and the loser reports
    the claim failed (it never believes it holds the run)."""
    clock = [1000.0]
    a = _lease_store(tmp_path, "a", clock)
    b = _lease_store(tmp_path, "b", clock)
    rd = tmp_path / "demo" / "t0"
    rd.mkdir(parents=True)

    real_write = a._write

    def write_then_lose(run_dir, epoch, acquired_at):
        # a's write lands, then b — which read "free" at the same
        # instant — overwrites it before a's read-back; the on-disk
        # file is the only truth
        out = real_write(run_dir, epoch, acquired_at)
        b._write(run_dir, epoch, acquired_at)
        return out

    a._write = write_then_lose
    assert a.acquire(rd) is None
    assert str(rd) not in a.held
    assert b.read(rd)["host"] == "b"


def test_lease_garbled_file_is_adoptable(tmp_path):
    clock = [1000.0]
    a = _lease_store(tmp_path, "a", clock)
    rd = tmp_path / "demo" / "t0"
    rd.mkdir(parents=True)
    (rd / "check.lease").write_text("{torn garbage")
    assert a.acquire(rd) == 1  # a torn lease never wedges the run


# ---------------------------------------------------------------------------
# stale-epoch regression pins: fenced writes DROP
# ---------------------------------------------------------------------------

def test_tracker_status_write_fenced(tmp_path):
    """The regression pin for the double-publish bug leasing exists to
    prevent: a RunTracker whose fence says no must drop the status
    write entirely, not land a stale document."""
    from jepsen_tpu.live.daemon import RunTracker
    rd = tmp_path / "demo" / "t0"
    _write_wal(rd, _register_history(12))
    tr = RunTracker(rd, accelerator="cpu", fence=lambda: False,
                    lease={"host": "a", "epoch": 1})
    tr.write_status(tr.status(lag_budget_ops=1000.0))
    assert not (rd / "live-status.json").exists()
    assert tr.fenced


def test_tracker_snapshot_fenced(tmp_path):
    from jepsen_tpu.live.daemon import RunTracker
    rd = tmp_path / "demo" / "t0"
    _write_wal(rd, _register_history(12))
    tr = RunTracker(rd, accelerator="cpu", fence=lambda: False)
    tr.unsupported = True  # snapshotable without a session
    tr.ops_absorbed = 5
    tr._last_snapshot = -1e9
    assert not tr.maybe_snapshot()
    assert tr.fenced
    assert not tr._ckpt_path.exists()


def test_checkpoint_store_guard_fences(tmp_path):
    from jepsen_tpu.checker.checkpoint import CheckpointStore
    p = tmp_path / "check.ckpt"
    fenced = CheckpointStore(p, interval_s=0.0, guard=lambda: False)
    assert not fenced.save({"carry": 1})
    assert fenced.fenced and not p.exists()

    held = CheckpointStore(p, interval_s=0.0, guard=lambda: True)
    assert held.save({"carry": 1})
    assert p.exists() and not held.fenced


def test_two_daemons_one_store_takeover(tmp_path):
    """The leased-checking e2e: daemon A admits and leases a run;
    daemon B over the same store stays out while A's lease is live,
    adopts at epoch 2 past the TTL, and A's next poll fences itself
    out (lease lost, tracker dropped, no stale write)."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.live.daemon import LiveDaemon
    clock = [1000.0]
    ls_a = _lease_store(tmp_path, "a", clock, ttl=30.0)
    ls_b = _lease_store(tmp_path, "b", clock, ttl=30.0)
    rd = tmp_path / "demo" / "t0"
    _write_wal(rd, _register_history(24))  # no final: stays tracked

    da = LiveDaemon(store_root=tmp_path, accelerator="cpu",
                    registry=telemetry.Registry(), lease_store=ls_a)
    db = LiveDaemon(store_root=tmp_path, accelerator="cpu",
                    registry=telemetry.Registry(), lease_store=ls_b)
    try:
        da.poll_once()
        assert ls_a.read(rd)["host"] == "a"
        db.poll_once()
        assert not db.trackers  # leased elsewhere: not admitted
        assert ls_a.read(rd)["epoch"] == 1

        clock[0] += 31.0  # a stalls past its TTL (SIGSTOP, GC, NFS...)
        db.poll_once()
        doc = ls_b.read(rd)
        assert doc["host"] == "b" and doc["epoch"] == 2
        status = json.loads((rd / "live-status.json").read_text())
        assert status["lease"] == {"host": "b", "epoch": 2}

        da.poll_once()  # the deposed host discovers it was deposed
        assert not da.trackers
        assert _ctr(ls_a.registry, "fleet_lease_lost_total") == 1
        # b's status survived a's fenced poll untouched
        status = json.loads((rd / "live-status.json").read_text())
        assert status["lease"]["host"] == "b"
    finally:
        da.stop()
        db.stop()


def test_daemon_releases_lease_and_fires_on_final(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.live.daemon import LiveDaemon
    clock = [1000.0]
    ls = _lease_store(tmp_path, "a", clock, ttl=30.0)
    rd = tmp_path / "demo" / "t0"
    ops = _register_history(24)
    _write_wal(rd, ops)
    finals = []
    d = LiveDaemon(store_root=tmp_path, accelerator="cpu",
                   registry=telemetry.Registry(), lease_store=ls,
                   on_final=lambda tr, res: finals.append(
                       (tr.label, tr.lease, res.get("valid?"))))
    try:
        d.poll_once()  # admit + lease while the run is still live
        assert ls.read(rd)["host"] == "a"
        with open(rd / "history.jsonl", "w") as f:
            for op in ops:
                f.write(json.dumps(op) + "\n")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not finals:
            d.poll_once()
    finally:
        d.stop()
    assert finals == [("demo/t0", {"host": "a", "epoch": 1}, True)]
    assert ls.read(rd) is None  # released at finalize
    assert ls.held == {}


# ---------------------------------------------------------------------------
# ENOSPC: Journal park/drain + truncate rollback, FaultRegistry park
# ---------------------------------------------------------------------------

class _FailingFile:
    """A write handle that fails every write with ``err`` — optionally
    leaking ``partial`` bytes into the real file first, the way a real
    disk-full write can land a prefix before dying."""

    def __init__(self, err=errno.ENOSPC, leak_path=None):
        self.err = err
        self.leak_path = leak_path
        self.closed = False

    def write(self, data):
        if self.leak_path is not None:
            with open(self.leak_path, "ab") as f:
                f.write(data[: max(1, len(data) // 2)])
        raise OSError(self.err, "injected write failure")

    def flush(self):
        pass

    def close(self):
        self.closed = True


def test_journal_enospc_parks_then_drains(tmp_path):
    from jepsen_tpu.journal import Journal, read_jsonl_tolerant
    p = tmp_path / "history.wal.jsonl"
    j = Journal(p, fsync_interval_s=-1)
    j.append({"i": 0})
    good = p.read_bytes()
    real = j._f
    j._f = _FailingFile()  # the disk fills
    j.append({"i": 1})
    real.close()
    assert j.appended == 1  # parked, not counted as landed
    assert len(j.parked) == 1
    assert p.read_bytes() == good  # nothing half-landed
    j.append({"i": 2})  # next append re-probes: reopen + drain backlog
    assert j.appended == 3 and j.parked == []
    j.close()
    rows, truncated = read_jsonl_tolerant(p)
    assert [r["i"] for r in rows] == [0, 1, 2]
    assert not truncated


def test_journal_enospc_rolls_back_partial_bytes(tmp_path):
    """A failed write that LANDED a prefix is truncated back to the
    last known-good offset — a torn half-line must never sit in the
    WAL waiting to corrupt a resume token."""
    from jepsen_tpu.journal import Journal, read_jsonl_tolerant
    p = tmp_path / "history.wal.jsonl"
    j = Journal(p, fsync_interval_s=-1)
    j.append({"i": 0})
    good = p.read_bytes()
    real = j._f
    j._f = _FailingFile(leak_path=p)
    j.append({"i": 1})
    real.close()
    assert p.read_bytes() == good  # the leaked prefix was truncated
    j.append({"i": 2})
    j.close()
    rows, _ = read_jsonl_tolerant(p)
    assert [r["i"] for r in rows] == [0, 1, 2]
    assert p.read_bytes().startswith(good)


def test_journal_enospc_park_is_bounded(tmp_path, monkeypatch):
    from jepsen_tpu import journal as journal_mod
    monkeypatch.setattr(journal_mod, "ENOSPC_PARK_MAX_LINES", 3)
    j = journal_mod.Journal(tmp_path / "w.jsonl", fsync_interval_s=-1)
    for i in range(5):
        j._park([json.dumps({"i": i}).encode() + b"\n"])
    assert len(j.parked) == 3
    assert j.parked_dropped == 2
    # oldest dropped first: the tail of the run is the valuable part
    assert [json.loads(line)["i"] for line in j.parked] == [2, 3, 4]
    j.close()


def test_journal_non_enospc_still_self_disables(tmp_path):
    from jepsen_tpu.journal import Journal
    p = tmp_path / "w.jsonl"
    j = Journal(p, fsync_interval_s=-1)
    j.append({"i": 0})
    real = j._f
    j._f = _FailingFile(err=errno.EIO)
    j.append({"i": 1})  # unknown I/O fault: permanent self-disable
    real.close()
    assert j._f.closed and not j._parked_closed
    before = p.read_bytes()
    j.append({"i": 2})  # no-op: the journal is done
    assert j.appended == 1
    assert p.read_bytes() == before
    j.close()


def test_fault_registry_enospc_parks_then_drains(tmp_path):
    from jepsen_tpu.nemesis.faults import FaultRegistry, load_rows
    p = tmp_path / "faults.jsonl"
    reg = FaultRegistry(p)
    fid0 = reg.record("net", f="start-partition")
    real = reg._f
    reg._f = _FailingFile()
    fid1 = reg.record("clock", f="clock-skew")  # parked, id still minted
    assert len(reg._parked) == 1 and reg._dirty_tail
    reg._f = real
    fid2 = reg.record("net", f="start-partition")  # drains the backlog
    assert reg._parked == [] and not reg._dirty_tail
    reg.close()
    rows = load_rows(p)
    recorded = {r["id"] for r in rows if r.get("op") == "inject"}
    assert recorded == {fid0, fid1, fid2}


# ---------------------------------------------------------------------------
# receiver backpressure: 429 + Retry-After, ENOSPC park + rollback
# ---------------------------------------------------------------------------

def _post_chunk(port, key, body, offset=0, prefix_sha=None,
                chunk_sha=None):
    if prefix_sha is None:
        prefix_sha = hashlib.sha256().hexdigest()
    if chunk_sha is None:
        chunk_sha = hashlib.sha256(body).hexdigest()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/wal/{key}", data=body,
        headers={"X-Jepsen-Offset": str(offset),
                 "X-Jepsen-Prefix-Sha": prefix_sha,
                 "X-Jepsen-Chunk-Sha": chunk_sha}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers or {})


def test_receiver_sheds_on_disk_headroom(tmp_path, monkeypatch):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet import ingest as ingest_mod
    monkeypatch.setattr(ingest_mod, "disk_free_mb", lambda path: 1.0)
    reg = telemetry.Registry()
    srv = ingest_mod.IngestServer(tmp_path, port=0, registry=reg,
                                  disk_headroom_mb=64.0)
    srv.start()
    try:
        body = b'{"i": 0}\n'
        status, resp, headers = _post_chunk(srv.port, "demo/t0", body)
        assert status == 429
        assert float(headers["Retry-After"]) > 0
        verdict = json.loads(resp)
        assert verdict["shed"] == "headroom"
        assert not (tmp_path / "demo" / "t0"
                    / "history.wal.jsonl").exists()
        assert _ctr(reg, "fleet_ingest_shed_total",
                    reason="headroom") == 1
    finally:
        srv.stop()


def test_receiver_sheds_on_pressure_hook(tmp_path):
    """The pool's aggregate-lag hook: non-None = shed, and the wait it
    returns is the Retry-After the shipper is told verbatim."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    wait = {"s": 2.5}
    srv = IngestServer(tmp_path, port=0,
                       registry=telemetry.Registry(),
                       pressure=lambda: wait["s"])
    srv.start()
    try:
        status, resp, headers = _post_chunk(srv.port, "demo/t0",
                                            b'{"i": 0}\n')
        assert status == 429
        assert json.loads(resp) == {"shed": "lag", "retry_after": 2.5}
        assert abs(float(headers["Retry-After"]) - 2.5) < 1e-6

        wait["s"] = None  # pool caught up: chunks land again
        status, _, _ = _post_chunk(srv.port, "demo/t0", b'{"i": 0}\n')
        assert status == 204
    finally:
        srv.stop()


def test_receiver_enospc_parks_and_rolls_back(tmp_path, monkeypatch):
    """An append dying on ENOSPC sheds the chunk, truncates any
    partially-landed bytes back to the advertised cursor, and parks
    the run; the park lapses and the SAME bytes then land whole."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet import ingest as ingest_mod
    monkeypatch.setattr(ingest_mod, "ENOSPC_PARK_S", 0.05)
    reg = telemetry.Registry()
    fail = {"on": False}
    wal = tmp_path / "demo" / "t0" / "history.wal.jsonl"

    def fault_hook(key, body):
        if fail["on"]:
            # leak a partial prefix the way a real disk-full can
            wal.parent.mkdir(parents=True, exist_ok=True)
            with open(wal, "ab") as f:
                f.write(body[: len(body) // 2])
            raise OSError(errno.ENOSPC, "injected disk full")

    srv = ingest_mod.IngestServer(tmp_path, port=0, registry=reg,
                                  fault_hook=fault_hook)
    srv.start()
    try:
        first = b'{"i": 0}\n'
        assert _post_chunk(srv.port, "demo/t0", first)[0] == 204

        fail["on"] = True
        sha0 = hashlib.sha256(first).hexdigest()
        second = b'{"i": 1}\n'
        sha1 = hashlib.sha256(first + second).hexdigest()
        status, resp, _ = _post_chunk(srv.port, "demo/t0", second,
                                      offset=len(first),
                                      prefix_sha=sha0, chunk_sha=sha1)
        assert status == 429
        assert json.loads(resp)["shed"] == "enospc"
        assert wal.read_bytes() == first  # partial bytes rolled back
        # parked: an immediate retry bounces without touching the disk
        status, resp, _ = _post_chunk(srv.port, "demo/t0", second,
                                      offset=len(first),
                                      prefix_sha=sha0, chunk_sha=sha1)
        assert status == 429

        fail["on"] = False
        time.sleep(0.08)  # the park lapses; the next append re-probes
        status, _, _ = _post_chunk(srv.port, "demo/t0", second,
                                   offset=len(first),
                                   prefix_sha=sha0, chunk_sha=sha1)
        assert status == 204
        assert wal.read_bytes() == first + second
        assert _ctr(reg, "fleet_ingest_shed_total", reason="enospc") == 2
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# shipper HA: failover, Retry-After, sealed runs
# ---------------------------------------------------------------------------

def _dead_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_shipper_fails_over_and_ships_everything(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    from jepsen_tpu.fleet.ship import Shipper
    ops = _register_history(30)
    rd = tmp_path / "src" / "demo" / "t0"
    _write_wal(rd, ops, complete=True)
    store = tmp_path / "fleet"
    srv = IngestServer(store, port=0,
                       registry=telemetry.Registry())
    srv.start()
    try:
        reg = telemetry.Registry()
        sh = Shipper(rd, [f"http://127.0.0.1:{_dead_port()}",
                          f"http://127.0.0.1:{srv.port}"],
                     poll_s=0.02, registry=reg,
                     rng=random.Random(0))
        assert sh.run(timeout_s=60)
        assert sh.failovers >= 1
        assert _ctr(reg, "fleet_ship_resyncs_total",
                    reason="failover") >= 1
        assert ((store / "demo" / "t0" / "history.wal.jsonl")
                .read_bytes()
                == (rd / "history.wal.jsonl").read_bytes())
        assert ((store / "demo" / "t0" / "history.jsonl").read_bytes()
                == (rd / "history.jsonl").read_bytes())
    finally:
        srv.stop()


def test_shipper_obeys_retry_after_and_repolls(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    from jepsen_tpu.fleet.ship import Shipper
    rd = tmp_path / "src" / "demo" / "t0"
    _write_wal(rd, [{"i": 0}, {"i": 1}])
    wait = {"s": 0.08}
    store = tmp_path / "fleet"
    srv = IngestServer(store, port=0, registry=telemetry.Registry(),
                       pressure=lambda: wait["s"])
    srv.start()
    try:
        reg = telemetry.Registry()
        sh = Shipper(rd, f"http://127.0.0.1:{srv.port}", poll_s=0.01,
                     registry=reg, rng=random.Random(0))
        assert sh.sync()
        assert sh.step() == 0  # shed: nothing absorbed
        assert sh._retry_at > time.monotonic()
        assert sh.tailer.offset == 0  # the bytes were rewound
        assert _ctr(reg, "fleet_ship_resyncs_total", reason="shed") == 1
        assert sh.step() == 0  # still parked: not even a request

        wait["s"] = None
        time.sleep(0.1)
        assert sh.step() > 0  # the SAME bytes land after the park
        assert ((store / "demo" / "t0" / "history.wal.jsonl")
                .read_bytes()
                == (rd / "history.wal.jsonl").read_bytes())
    finally:
        srv.stop()


def test_shipper_seals_when_receiver_holds_final(tmp_path):
    """A shipper (re)starting against a run the receiver already
    finalized stops shipping instead of fighting the seal."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    from jepsen_tpu.fleet.ship import Shipper
    ops = _register_history(12)
    rd = tmp_path / "src" / "demo" / "t0"
    _write_wal(rd, ops, complete=True)
    final = (rd / "history.jsonl").read_bytes()
    store = tmp_path / "fleet"
    srv = IngestServer(store, port=0, registry=telemetry.Registry())
    srv.start()
    try:
        assert srv.finalize_run(
            "demo/t0", hashlib.sha256(final).hexdigest(), final) == "ok"
        sh = Shipper(rd, f"http://127.0.0.1:{srv.port}", poll_s=0.01,
                     registry=telemetry.Registry())
        assert sh.run(timeout_s=30)
        assert sh.sealed
        assert sh.bytes_sent == 0  # nothing shipped against the seal
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# finals race: one digest-valid history, 409 loser, both orders
# ---------------------------------------------------------------------------

def test_finals_race_final_then_late_chunk(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    reg = telemetry.Registry()
    srv = IngestServer(tmp_path, port=0, registry=reg)
    srv.start()
    try:
        chunk = b'{"i": 0}\n'
        assert _post_chunk(srv.port, "demo/t0", chunk)[0] == 204
        final = b'{"i": 0}\n{"i": 1}\n'
        sha = hashlib.sha256(final).hexdigest()
        assert srv.finalize_run("demo/t0", sha, final) == "ok"

        # the losing half of the race: a late WAL chunk after the seal
        late = b'{"i": 9}\n'
        status, resp, _ = _post_chunk(
            srv.port, "demo/t0", late, offset=len(chunk),
            prefix_sha=hashlib.sha256(chunk).hexdigest(),
            chunk_sha=hashlib.sha256(chunk + late).hexdigest())
        assert status == 409
        assert json.loads(resp)["reason"] == "finalized"
        wal = tmp_path / "demo" / "t0" / "history.wal.jsonl"
        assert wal.read_bytes() == chunk  # the WAL is sealed
        hist = (tmp_path / "demo" / "t0" / "history.jsonl").read_bytes()
        assert hashlib.sha256(hist).hexdigest() == sha
        assert _ctr(reg, "fleet_ingest_rejected_total",
                    reason="finalized") == 1

        # a DIFFERENT final is the race's other loser: 409, not a swap
        other = b'{"i": 7}\n'
        assert srv.finalize_run(
            "demo/t0", hashlib.sha256(other).hexdigest(),
            other) == "conflict"
        # the byte-identical final is an idempotent re-send
        assert srv.finalize_run("demo/t0", sha, final) == "ok"
        assert (tmp_path / "demo" / "t0"
                / "history.jsonl").read_bytes() == final
    finally:
        srv.stop()


def test_finals_race_chunk_then_final_and_restart_seal(tmp_path):
    """The other order: the chunk lands first, the final seals after —
    and the seal survives a receiver restart (the on-disk history IS
    the final), so a replaying shipper still gets its 409."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    chunk = b'{"i": 0}\n'
    final = b'{"i": 0}\n{"i": 1}\n'
    sha = hashlib.sha256(final).hexdigest()
    srv = IngestServer(tmp_path, port=0,
                       registry=telemetry.Registry())
    srv.start()
    try:
        assert _post_chunk(srv.port, "demo/t0", chunk)[0] == 204
        assert srv.finalize_run("demo/t0", sha, final) == "ok"
    finally:
        srv.stop()

    srv2 = IngestServer(tmp_path, port=0,
                        registry=telemetry.Registry())
    srv2.start()
    try:
        late = b'{"i": 9}\n'
        status, resp, _ = _post_chunk(
            srv2.port, "demo/t0", late, offset=len(chunk),
            prefix_sha=hashlib.sha256(chunk).hexdigest(),
            chunk_sha=hashlib.sha256(chunk + late).hexdigest())
        assert status == 409
        assert json.loads(resp)["reason"] == "finalized"
        assert srv2.finalize_run(
            "demo/t0", hashlib.sha256(late).hexdigest(),
            late) == "conflict"
        assert (tmp_path / "demo" / "t0"
                / "history.jsonl").read_bytes() == final
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# pool scheduler: HA status block, degraded mode, pressure wiring
# ---------------------------------------------------------------------------

def test_fleet_daemon_publishes_ha_block(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import FleetDaemon
    fd = FleetDaemon(tmp_path, port=0, poll_s=0.05,
                     accelerator="cpu", host_id="pool-a",
                     lease_ttl_s=5.0,
                     registry=telemetry.Registry())
    fd.ingest.start()
    try:
        payload = fd.poll_once()
        ha = payload["ha"]
        assert ha["host"] == "pool-a"
        assert ha["leasing"] and ha["lease_ttl_s"] == 5.0
        assert ha["leases_held"] == 0 and not ha["shedding"]
        for k in ("lease_acquired", "lease_lost", "fenced_writes",
                  "degraded_total"):
            assert ha[k] == 0
        assert payload["ingest"]["shed_total"] == 0
    finally:
        fd.stop()


def test_fleet_daemon_lease_ttl_zero_disables_leasing(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import FleetDaemon
    fd = FleetDaemon(tmp_path, port=0, lease_ttl_s=0,
                     accelerator="cpu",
                     registry=telemetry.Registry())
    fd.ingest.start()
    try:
        assert fd.lease_store is None
        assert not fd.poll_once()["ha"]["leasing"]
    finally:
        fd.stop()


def test_fleet_daemon_degrades_on_status_write_failure(tmp_path,
                                                       monkeypatch):
    """Degraded mode: a failing status write is counted and survived —
    poll_once still returns, because verdicts outrank dashboards."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import FleetDaemon

    def broken_write(path, text):
        raise OSError(errno.EIO, "injected status-plane failure")

    monkeypatch.setattr(telemetry, "_atomic_write", broken_write)
    reg = telemetry.Registry()
    fd = FleetDaemon(tmp_path, port=0, lease_ttl_s=0,
                     accelerator="cpu", registry=reg)
    fd.ingest.start()
    try:
        payload = fd.poll_once()
        assert payload.get("degraded_write")
        assert _ctr(reg, "fleet_degraded_total", surface="status") == 1
    finally:
        fd.stop()


def test_fleet_daemon_lag_pressure_sheds(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import LAG_SHED_BUDGETS, FleetDaemon
    fd = FleetDaemon(tmp_path, port=0, lease_ttl_s=0,
                     accelerator="cpu",
                     registry=telemetry.Registry())
    fd.ingest.start()
    try:
        over = fd.daemon.lag_budget_ops * LAG_SHED_BUDGETS + 1
        fd._update_pressure({"demo/t0": {"lag_ops": over}})
        assert fd._shed_wait is not None
        verdict = fd.ingest.overload()
        assert verdict and verdict["shed"] == "lag"
        fd._update_pressure({"demo/t0": {"lag_ops": 0}})
        assert fd._shed_wait is None and fd.ingest.overload() is None
    finally:
        fd.stop()


def test_web_ha_line_renders(tmp_path):
    from jepsen_tpu.web import Handler
    line = Handler._ha_line({
        "host": "pool-a", "leasing": True, "lease_ttl_s": 5.0,
        "leases_held": 3, "lease_acquired": 4, "lease_lost": 1,
        "fenced_writes": 2, "degraded_total": 1, "shedding": True})
    assert "pool-a" in line and "3 held" in line
    assert "4 takeovers" in line and "2 fenced writes" in line
    assert "shedding" in line and "degraded" in line
    assert Handler._ha_line({}) == ""


# ---------------------------------------------------------------------------
# knobs: preflight KNB rows, env twins, fleet_receivers validation
# ---------------------------------------------------------------------------

def test_preflight_validates_ha_knobs():
    from jepsen_tpu.analysis.preflight import preflight

    diags = preflight({"nodes": ["n1"], "fleet_lease_ttl_s": "junk"})
    assert any(d.code == "KNB001" and d.path == "fleet_lease_ttl_s"
               for d in diags)
    diags = preflight({"nodes": ["n1"], "fleet_lease_ttl_s": -1})
    assert any(d.code == "KNB002" for d in diags)
    diags = preflight({"nodes": ["n1"],
                       "fleet_disk_headroom_mb": "junk"})
    assert any(d.code == "KNB001"
               and d.path == "fleet_disk_headroom_mb" for d in diags)
    diags = preflight({"nodes": ["n1"], "fleet_lease_ttl_s": 2.0,
                       "fleet_disk_headroom_mb": 64})
    assert not [d for d in diags if d.path.startswith("fleet_")]


def test_preflight_validates_ha_env_twins(monkeypatch):
    from jepsen_tpu.analysis.preflight import preflight
    monkeypatch.setenv("JEPSEN_TPU_FLEET_LEASE_TTL_S", "junk")
    monkeypatch.setenv("JEPSEN_TPU_FLEET_DISK_HEADROOM_MB", "nope")
    diags = preflight({"nodes": ["n1"]})
    assert any(d.code == "KNB001"
               and d.path == "JEPSEN_TPU_FLEET_LEASE_TTL_S"
               for d in diags)
    assert any(d.code == "KNB001"
               and d.path == "JEPSEN_TPU_FLEET_DISK_HEADROOM_MB"
               for d in diags)


def test_preflight_validates_fleet_receivers():
    from jepsen_tpu.analysis.preflight import preflight

    diags = preflight({"nodes": ["n1"], "fleet_receivers": 42})
    assert any(d.code == "KNB001" and d.path == "fleet_receivers"
               for d in diags)
    diags = preflight({"nodes": ["n1"],
                       "fleet_receivers": ["ftp://pool:1"]})
    assert any(d.code == "KNB007" and d.path == "fleet_receivers"
               for d in diags)
    diags = preflight({"nodes": ["n1"],
                       "fleet_receivers": ["http://a:8091",
                                           "https://b:8091"]})
    assert not [d for d in diags if d.path == "fleet_receivers"]
    # the comma-separated string form validates entry by entry
    diags = preflight({"nodes": ["n1"],
                       "fleet_receivers": "http://a:8091, gopher://b"})
    assert any(d.code == "KNB007" for d in diags)


def test_preflight_validates_fleet_receivers_env_twin(monkeypatch):
    from jepsen_tpu.analysis.preflight import preflight
    monkeypatch.setenv("JEPSEN_TPU_FLEET_RECEIVERS", "not-a-url")
    diags = preflight({"nodes": ["n1"]})
    assert any(d.code == "KNB007"
               and d.path == "JEPSEN_TPU_FLEET_RECEIVERS"
               for d in diags)


def test_fleet_knob_env_twins(monkeypatch):
    from jepsen_tpu.fleet import fleet_knob, fleet_receivers
    monkeypatch.setenv("JEPSEN_TPU_FLEET_LEASE_TTL_S", "2.5")
    assert fleet_knob("fleet_lease_ttl_s", None, 10.0, 0.0) == 2.5
    monkeypatch.setenv("JEPSEN_TPU_FLEET_RECEIVERS",
                       "http://a:8091, http://b:8091/")
    assert fleet_receivers() == ["http://a:8091", "http://b:8091"]
    # explicit values win over the env; garbage tolerantly reads empty
    assert fleet_receivers(["http://c:1/"]) == ["http://c:1"]
    assert fleet_receivers("http://d:2,,") == ["http://d:2"]
    assert fleet_receivers(42) == []


# ---------------------------------------------------------------------------
# the self-chaos harness (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.fleet_chaos
def test_fleet_chaos_invariants_hold(tmp_path):
    """The whole HA story under its own nemesis: SIGKILL the receiver,
    SIGSTOP a pool host past its TTL, SIGKILL the other, torn TCP,
    injected ENOSPC — zero double-checked runs, zero lost/duplicated
    WAL bytes, verdicts bit-identical to local analyze."""
    from jepsen_tpu.fleet.chaos import REPORT_NAME, run_fleet_chaos
    report = run_fleet_chaos(tmp_path, runs=3, n_ops=100, seed=2,
                             lease_ttl_s=0.8, timeout_s=150.0)
    assert report["ok"], report
    assert report["double_checked"] == []
    assert report["wal_mismatch"] == []
    assert report["verdict_mismatch"] == []
    assert report["settled"] == report["runs"] == 3
    assert report["chaos"]["receiver_kills"] == 1
    assert report["chaos"]["pool_kills"] == 1
    on_disk = json.loads((tmp_path / REPORT_NAME).read_text())
    assert on_disk["ok"]
