"""FaunaDB / RobustIRC / LogCabin suite tests: FQL expression
composition, robustsession message parsing, TreeOps exec command
shapes and error mapping, plus fake-mode lifecycle runs."""
from jepsen_tpu import control
from jepsen_tpu.suites import faunadb, logcabin, robustirc

from conftest import run_fake  # noqa: E402
import pytest

NODES = ["n1", "n2", "n3", "n4", "n5"]


# ---------------------------------------------------------------------------
# faunadb: FQL JSON expression builders + client bodies
# ---------------------------------------------------------------------------

def test_fauna_fql_builders():
    r = faunadb.ref_("registers", 3)
    assert r == {"ref": {"@ref": "classes/registers/3"}}
    up = faunadb.upsert("registers", 3, {"v": 7})
    assert up["if"] == {"exists": {"@ref": "classes/registers/3"}}
    assert up["then"]["update"] == {"@ref": "classes/registers/3"}
    assert up["else"]["create"] == {"@ref": "classes/registers/3"}


def test_fauna_client_cas_expression():
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            return True

    c = TClient(node="n1")
    out = c.invoke({}, {"f": "cas", "type": "invoke",
                        "value": [1, (4, 5)]})
    assert out["type"] == "ok"
    expr = sent[0]
    # If(Equals(Select(..), 4), Do(Update(.., v=5), true), false)
    assert expr["if"]["equals"][1] == 4
    assert expr["then"]["do"][0]["update"] == {"@ref": "classes/registers/1"}
    assert expr["else"] is False


def test_fauna_client_not_found_read_is_nil():
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            raise faunadb.FaunaError([{"code": "instance not found"}])

    out = TClient(node="n1").invoke(
        {}, {"f": "read", "type": "invoke", "value": [2, None]})
    assert out["type"] == "ok" and out["value"] == [2, None]


@pytest.mark.slow
def test_fauna_fake_register_run():
    result = run_fake(faunadb.faunadb_test)
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_fauna_fake_bank_run():
    result = run_fake(faunadb.faunadb_test, workload="bank")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# robustirc
# ---------------------------------------------------------------------------

def test_robustirc_daemon_args():
    args = robustirc.base_args("n2")
    joined = " ".join(args)
    assert "-listen=n2:13001" in joined
    assert "-network_password=secret" in joined


def test_robustirc_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = robustirc.RobustIRCDB()
    try:
        control.on("n1", t, lambda: db.start(t, "n1"))
        control.on("n3", t, lambda: db.start(t, "n3"))
        joined = " ".join(str(x) for x in remote.log)
        assert "-singlenode" in joined          # primary bootstraps
        assert "-join=n1:13001" in joined        # others join it
    finally:
        control.disconnect_all(t)


@pytest.mark.slow
def test_robustirc_fake_set_run():
    result = run_fake(robustirc.robustirc_test)
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# logcabin
# ---------------------------------------------------------------------------

def test_logcabin_config():
    assert logcabin.server_id("n3") == "3"
    assert logcabin.server_addrs({"nodes": NODES}).startswith("n1:5254,")


def test_logcabin_client_exec_shapes():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    try:
        c = logcabin.LogCabinClient().open(t, "n2")
        out = c.invoke(t, {"f": "write", "type": "invoke",
                           "value": [1, 5]})
        assert out["type"] == "ok"
        joined = " ".join(str(x) for x in remote.log)
        assert "/root/TreeOps" in joined
        assert "write /jepsen-1" in joined
        out = c.invoke(t, {"f": "cas", "type": "invoke",
                           "value": [1, (5, 6)]})
        joined = " ".join(str(x) for x in remote.log)
        assert "-p /jepsen-1:5" in joined        # TreeOps CAS predicate
    finally:
        control.disconnect_all(t)


def test_logcabin_error_mapping():
    c = logcabin.LogCabinClient("n1")

    class R:
        exit_status = 1
        out = ""
        err = ("Exiting due to LogCabin::Client::Exception: Path "
               "'/jepsen-1' has value '3', not '4' as required")

    # a CAS precondition miss is a definite fail
    c._exec = lambda *a, **kw: R()
    out = c.invoke({}, {"f": "cas", "type": "invoke", "value": [1, (4, 5)]})
    assert out["type"] == "fail"

    class RTimeout(R):
        err = ("Exiting due to LogCabin::Client::Exception: "
               "Client-specified timeout elapsed")

    # a timed-out write is indeterminate (deviation from the reference,
    # which unsoundly fails all timed-out ops)
    c._exec = lambda *a, **kw: RTimeout()
    out = c.invoke({}, {"f": "write", "type": "invoke", "value": [1, 2]})
    assert out["type"] == "info"
    out = c.invoke({}, {"f": "read", "type": "invoke", "value": [1, None]})
    assert out["type"] == "fail"


@pytest.mark.slow
def test_logcabin_fake_register_run():
    result = run_fake(logcabin.logcabin_test)
    assert result["results"]["valid?"] is True, result["results"]


def test_suite_registry_is_complete():
    """Every reference L8 suite dir has a counterpart in the registry
    (SURVEY.md §1 L8; mongodb-* / postgres-rds map to mongodb/postgres,
    aerospike/rabbitmq/rethinkdb arrive with their own wire clients)."""
    from jepsen_tpu.suites import suite_registry
    reg = set(suite_registry())
    assert {"etcd", "zookeeper", "consul", "redis", "postgres", "mongodb",
            "elasticsearch", "crate", "dgraph", "ignite", "hazelcast",
            "chronos", "raftis", "disque", "galera", "percona",
            "mysql-cluster", "tidb", "cockroachdb", "stolon", "yugabyte",
            "faunadb", "robustirc", "logcabin"} <= reg


def test_fauna_bank_read_is_one_transaction():
    """All balances must come back from ONE query (one FaunaDB txn) —
    per-account queries would interleave with transfers and produce
    false wrong-total violations."""
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            return {"0": 10, "1": 13}

    out = TClient(node="n1").invoke(
        {"accounts": [0, 1]}, {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "ok" and out["value"] == {0: 10, 1: 13}
    assert len(sent) == 1 and "object" in sent[0]


def test_fauna_not_found_on_bank_read_is_typed_completion():
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            raise faunadb.FaunaError([{"code": "instance not found"}])

    out = TClient(node="n1").invoke(
        {"accounts": [0, 1]}, {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "fail"  # not a raised TypeError


def test_clock_scrambler_commands():
    from jepsen_tpu import nemesis as nem
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    try:
        scrambler = nem.clock_scrambler(60).setup(t)
        out = scrambler.invoke(t, {"f": "scramble-clock", "type": "info",
                                   "value": ["n1", "n2"]})
        assert out["type"] == "info"
        assert set(out["value"]) == {"n1", "n2"}
        assert all(-60 <= off <= 60 for off in out["value"].values())
        joined = " ".join(str(x) for x in remote.log)
        assert "date -s" in joined
        scrambler.teardown(t)
    finally:
        control.disconnect_all(t)


def test_mongodb_variants():
    from jepsen_tpu.suites import mongodb
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    try:
        db = mongodb.MongoDB("rocksdb")
        control.on("n1", t, lambda: db.start(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--storageEngine rocksdb" in joined
    finally:
        control.disconnect_all(t)
    tm = mongodb.mongodb_test({"fake": True})
    assert tm["generator"] is not None  # variants don't break fake mode


def test_fauna_client_set_and_adya_expressions():
    """set adds upsert keyed elements and whole-reads paginate the
    all-elements index; adya inserts predicate-read both pair cells in
    one If transaction (faunadb/set.clj, g2.clj shapes)."""
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            if "paginate" in expr:
                return {"data": [3, 1]}
            return True

    c = TClient(node="n1")
    assert c.invoke({}, {"f": "add", "type": "invoke",
                         "value": 7})["type"] == "ok"
    assert sent[0]["if"] == {"exists": {"@ref": "classes/elements/7"}}
    out = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "ok" and out["value"] == [1, 3]
    assert sent[1]["paginate"]["match"]["index"] == \
        {"@ref": "indexes/all_elements"}

    out = c.invoke({}, {"f": "insert", "type": "invoke",
                        "value": [4, 99, "a"]})
    assert out["type"] == "ok"
    g2 = sent[2]
    # the guard is a PREDICATE read: index match over the pair term
    assert g2["if"]["is_empty"]["paginate"]["match"]["index"] ==         {"@ref": "indexes/adya_by_pair"}
    assert g2["if"]["is_empty"]["paginate"]["terms"] == 4
    assert g2["then"]["do"][0]["create"] == {"@ref": "classes/adya/4-a"}
    assert g2["else"] is False

    class Occupied(faunadb.FaunaClient):
        def _query(self, expr):
            return False  # pair not empty: If takes the else branch

    out = Occupied(node="n1").invoke({}, {"f": "insert", "type": "invoke",
                                          "value": [4, 99, "b"]})
    assert out["type"] == "fail"


@pytest.mark.slow
def test_fauna_fake_set_and_adya_runs():
    for wl in ("set", "adya"):
        result = run_fake(faunadb.faunadb_test, workload=wl)
        assert result["results"]["valid?"] is True, (wl, result["results"])


def test_pages_checker_group_atomicity():
    """Reads must decompose into COMPLETE add-groups; a page boundary
    slicing a group is the anomaly (faunadb/pages.clj:93-145)."""
    from jepsen_tpu.workloads.pages import PagesChecker

    def h(adds, reads, failed=()):
        out = []
        for g in adds:
            out.append({"type": "invoke", "f": "add", "value": list(g)})
            out.append({"type": ("fail" if tuple(g) in failed else "ok"),
                        "f": "add", "value": list(g)})
        for r in reads:
            out.append({"type": "ok", "f": "read", "value": list(r)})
        return out

    ok = PagesChecker().check(
        {}, h([[1, 2], [3, 4, 5]], [[1, 2], [1, 2, 3, 4, 5], []]), {})
    assert ok["valid?"] is True and ok["ok-read-count"] == 3
    torn = PagesChecker().check(
        {}, h([[1, 2], [3, 4, 5]], [[1, 2, 3]]), {})
    assert torn["valid?"] is False
    assert torn["errors"][0]["op-errors"][0]["expected"] == [3, 4, 5]
    dup = PagesChecker().check({}, h([[1, 2]], [[1, 1, 2]]), {})
    assert dup["valid?"] is False
    # a definitely-failed group's elements are unexpected if read
    ghost = PagesChecker().check(
        {}, h([[1, 2]], [[1, 2]], failed={(1, 2)}), {})
    assert ghost["valid?"] is False


def test_fauna_pages_client_cursored_reads():
    """Group adds ride one Do-of-creates transaction; reads page the
    by-key index match with cursors across separate queries."""
    sent = []
    pages = [{"data": [1, 5], "after": ["c1"]},
             {"data": [9], "after": None}]

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            if "paginate" in expr:
                return pages[1 if "after" in expr else 0]
            return True

    c = TClient(node="n1")
    t = {"pages": True}
    out = c.invoke(t, {"f": "add", "type": "invoke",
                       "value": [7, [1, 5]]})
    assert out["type"] == "ok"
    do = sent[0]["do"]
    assert len(do) == 2
    assert do[0]["params"]["object"]["data"]["object"] == {"key": 7,
                                                           "value": 1}
    out = c.invoke(t, {"f": "read", "type": "invoke", "value": [7, None]})
    assert out["type"] == "ok" and out["value"] == [7, [1, 5, 9]]
    assert sent[1]["paginate"]["terms"] == 7
    assert sent[2]["after"] == ["c1"]  # the cursor chained


@pytest.mark.slow
def test_fauna_fake_pages_run():
    result = run_fake(faunadb.faunadb_test, workload="pages")
    assert result["results"]["valid?"] is True, result["results"]


def test_fauna_pages_read_not_found_fails():
    """A missing pages index must FAIL the read, not fabricate an
    ok-empty one (a trivially-valid verdict would mask anomalies)."""
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            raise faunadb.FaunaError([{"code": "instance not found"}])

    out = TClient(node="n1").invoke(
        {"pages": True}, {"f": "read", "type": "invoke", "value": [2, None]})
    assert out["type"] == "fail", out


# ---------------------------------------------------------------------------
# op tracing (dgraph/trace.clj analog, jepsen_tpu/tracing.py)
# ---------------------------------------------------------------------------

def test_tracer_spans_nest_and_export(tmp_path):
    import json

    from jepsen_tpu.tracing import Tracer

    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with tr.with_trace("outer"):
        outer_ctx = tr.context()
        tr.annotate("started")
        with tr.with_trace("inner"):
            inner_ctx = tr.context()
            tr.attribute("k", "v")
        assert tr.context()["span-id"] == outer_ctx["span-id"]
    tr.close()
    spans = [json.loads(line) for line in open(path)]
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"outer", "inner"}
    assert inner_ctx["trace-id"] == outer_ctx["trace-id"]
    assert by_name["inner"]["parent-id"] == outer_ctx["span-id"]
    assert by_name["inner"]["attributes"] == {"k": "v"}
    assert by_name["outer"]["annotations"][0]["message"] == "started"
    assert by_name["outer"]["end"] >= by_name["outer"]["start"]


def test_tracer_disabled_is_noop():
    from jepsen_tpu.tracing import Tracer

    tr = Tracer(None)
    with tr.with_trace("x"):
        tr.annotate("y")
        tr.attribute("a", "b")
    assert tr.context() == {"span-id": None, "trace-id": None}
    tr.close()   # nothing written, nothing raised


@pytest.mark.slow
def test_dgraph_trace_fake_run(tmp_path):
    import json

    from jepsen_tpu import core
    from jepsen_tpu.suites.dgraph import dgraph_test

    t = dgraph_test({"fake": True, "time_limit": 1.0, "no_perf": True,
                     "accelerator": "cpu", "trace": True,
                     "store_dir": str(tmp_path)})
    res = core.run(t)
    assert res["results"]["valid?"] is True
    # the shared telemetry wiring writes a PER-RUN trace.jsonl (and
    # core.run owns the tracer teardown — no manual close needed)
    from jepsen_tpu import store
    _, _, run_dir = store.latest(str(tmp_path))
    spans = [json.loads(line)
             for line in open(run_dir / "trace.jsonl")]
    assert spans, "client ops must produce spans"
    assert all(s["name"].startswith("invoke/") for s in spans)
    assert all(s["attributes"].get("type") in ("ok", "fail", "info")
               for s in spans)
