"""Ignite thin-client wire tests: the binary protocol client against an
in-process mock server (handshake, data objects, transactional cache
ops with real rollback semantics), the suite bank client's error
mapping, and the fake-mode bank lifecycle."""
from __future__ import annotations

import socket
import struct
import threading

import pytest

from jepsen_tpu.suites import _ignite as ig
from jepsen_tpu.suites._ignite import (IgniteError, ThinClient, java_hash,
                                       obj_long, obj_string, read_obj)
from jepsen_tpu.suites._wire import recv_exact


def test_java_hash_matches_jvm():
    # well-known java.lang.String#hashCode values
    assert java_hash("") == 0
    assert java_hash("a") == 97
    assert java_hash("abc") == 96354
    assert java_hash("hello") == 99162322
    assert java_hash("polygenelubricants") == -2147483648  # famous MIN_VALUE


def test_data_object_roundtrip():
    buf = obj_long(-7) + obj_string("héllo") + obj_string(None)
    v1, off = read_obj(buf, 0)
    v2, off = read_obj(buf, off)
    v3, off = read_obj(buf, off)
    assert (v1, v2, v3) == (-7, "héllo", None)
    assert off == len(buf)


class MockIgnite:
    """Thin-protocol server: handshake + GET/PUT/GET_ALL + client
    transactions with buffered writes (committed on TX_END(true),
    discarded on TX_END(false) or disconnect)."""

    def __init__(self, reject_handshake=False):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.lock = threading.Lock()
        self.caches: dict[int, dict] = {}
        self.tx_seq = 0
        self.reject_handshake = reject_handshake
        self.fail_next: str | None = None   # op name to fail once
        self.stop = False
        threading.Thread(target=self._serve, daemon=True).start()

    def close(self):
        self.stop = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self.stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        # per-connection ambient txn: id -> buffered writes
        open_tx: dict[int, dict] = {}
        try:
            n = struct.unpack("<i", recv_exact(conn, 4))[0]
            body = recv_exact(conn, n)
            assert body[0] == 1
            if self.reject_handshake:
                msg = obj_string("unsupported version")
                out = struct.pack("<bhhh", 0, 1, 6, 0) + msg
                conn.sendall(struct.pack("<i", len(out)) + out)
                return
            conn.sendall(struct.pack("<ib", 1, 1))
            while True:
                n = struct.unpack("<i", recv_exact(conn, 4))[0]
                body = recv_exact(conn, n)
                conn.sendall(self._dispatch(body, open_tx))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            open_tx.clear()   # disconnect rolls back open txns
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _resp(rid, payload=b"", status=0, msg=""):
        body = struct.pack("<qi", rid, status)
        if status != 0:
            body += obj_string(msg)
        else:
            body += payload
        return struct.pack("<i", len(body)) + body

    def _cache_view(self, cache_id, open_tx, tx_id):
        base = self.caches.setdefault(cache_id, {})
        if tx_id is not None and tx_id in open_tx:
            mine = {k: v for (cid, k), v in open_tx[tx_id].items()
                    if cid == cache_id}
            return {**base, **mine}
        return base

    def _dispatch(self, body, open_tx) -> bytes:
        op, rid = struct.unpack_from("<hq", body, 0)
        off = 10
        with self.lock:
            if self.fail_next:
                name, self.fail_next = self.fail_next, None
                if name == "any":
                    return self._resp(rid, status=1,
                                      msg="injected server error")
            if op == ig.OP_TX_START:
                self.tx_seq += 1
                open_tx[self.tx_seq] = {}
                return self._resp(rid, struct.pack("<i", self.tx_seq))
            if op == ig.OP_TX_END:
                tx_id, committed = struct.unpack_from("<ib", body, off)
                writes = open_tx.pop(tx_id, None)
                if writes is None:
                    return self._resp(rid, status=1, msg="unknown tx")
                if committed:
                    for (cid, k), v in writes.items():
                        self.caches.setdefault(cid, {})[k] = v
                return self._resp(rid)
            # cache ops: header = cache_id i32, flags byte [, tx i32]
            cid, flags = struct.unpack_from("<ib", body, off)
            off += 5
            tx_id = None
            if flags & ig.FLAG_TRANSACTIONAL:
                tx_id = struct.unpack_from("<i", body, off)[0]
                off += 4
                if tx_id not in open_tx:
                    return self._resp(rid, status=1, msg="stale tx")
            if op == ig.OP_CACHE_GET:
                k, off = read_obj(body, off)
                view = self._cache_view(cid, open_tx, tx_id)
                v = view.get(k)
                return self._resp(rid, obj_long(v) if v is not None
                                  else struct.pack("<b", ig.TYPE_NULL))
            if op == ig.OP_CACHE_PUT:
                k, off = read_obj(body, off)
                v, off = read_obj(body, off)
                if tx_id is not None:
                    open_tx[tx_id][(cid, k)] = v
                else:
                    self.caches.setdefault(cid, {})[k] = v
                return self._resp(rid)
            if op == ig.OP_CACHE_GET_ALL:
                count = struct.unpack_from("<i", body, off)[0]
                off += 4
                keys = []
                for _ in range(count):
                    k, off = read_obj(body, off)
                    keys.append(k)
                view = self._cache_view(cid, open_tx, tx_id)
                out = struct.pack("<i", len(keys))
                for k in keys:
                    v = view.get(k)
                    out += obj_long(k)
                    out += obj_long(v) if v is not None \
                        else struct.pack("<b", ig.TYPE_NULL)
                return self._resp(rid, out)
            return self._resp(rid, status=1, msg=f"unsupported op {op}")


@pytest.fixture()
def server():
    s = MockIgnite()
    yield s
    s.close()


def test_handshake_and_basic_ops(server):
    c = ThinClient("127.0.0.1", server.port).connect()
    c.cache_put("ACCOUNTS", 1, 100)
    assert c.cache_get("ACCOUNTS", 1) == 100
    assert c.cache_get("ACCOUNTS", 2) is None
    assert c.cache_get_all("ACCOUNTS", [1, 2]) == {1: 100, 2: None}
    c.close()


def test_handshake_rejection():
    s = MockIgnite(reject_handshake=True)
    try:
        with pytest.raises(IgniteError, match="handshake"):
            ThinClient("127.0.0.1", s.port).connect()
    finally:
        s.close()


def test_transaction_commit_and_rollback(server):
    c = ThinClient("127.0.0.1", server.port).connect()
    c.cache_put("ACCOUNTS", 0, 50)
    # rollback: writes invisible afterwards
    c.tx_start()
    c.cache_put("ACCOUNTS", 0, 7)
    assert c.cache_get("ACCOUNTS", 0) == 7      # own-write visible in tx
    c.tx_end(False)
    assert c.cache_get("ACCOUNTS", 0) == 50
    # commit: applied atomically
    c.tx_start()
    c.cache_put("ACCOUNTS", 0, 10)
    c.cache_put("ACCOUNTS", 1, 40)
    c.tx_end(True)
    assert c.cache_get_all("ACCOUNTS", [0, 1]) == {0: 10, 1: 40}
    c.close()


def test_server_error_raises(server):
    c = ThinClient("127.0.0.1", server.port).connect()
    server.fail_next = "any"
    with pytest.raises(IgniteError, match="injected"):
        c.cache_get("ACCOUNTS", 0)
    c.close()


def test_suite_bank_client_against_mock(server, monkeypatch):
    from jepsen_tpu.suites import ignite as suite

    monkeypatch.setattr(suite, "THIN_PORT", server.port)
    test = {"accounts": list(range(4)), "total-amount": 40}
    c = suite.IgniteBankClient().open(test, "127.0.0.1")
    c.setup(test)
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert out["type"] == "ok"
    assert sum(out["value"].values()) == 40
    ok = c.invoke(test, {"f": "transfer", "process": 0,
                         "value": {"from": 0, "to": 1, "amount": 5}})
    assert ok["type"] == "ok"
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert out["value"][0] == 5 and out["value"][1] == 15
    assert sum(out["value"].values()) == 40
    # overdraft fails cleanly and moves nothing
    bad = c.invoke(test, {"f": "transfer", "process": 0,
                          "value": {"from": 0, "to": 1, "amount": 99}})
    assert bad["type"] == "fail" and bad["error"][0] == "negative"
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert sum(out["value"].values()) == 40
    # injected server error pre-commit -> clean fail, txn rolled back
    server.fail_next = "any"
    err = c.invoke(test, {"f": "transfer", "process": 0,
                          "value": {"from": 1, "to": 0, "amount": 1}})
    assert err["type"] == "fail" and err["error"][0] == "ignite"
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert sum(out["value"].values()) == 40
    c.close(test)


def test_suite_bank_client_net_error_reconnects(server, monkeypatch):
    from jepsen_tpu.suites import ignite as suite

    monkeypatch.setattr(suite, "THIN_PORT", server.port)
    test = {"accounts": list(range(4)), "total-amount": 40}
    c = suite.IgniteBankClient().open(test, "127.0.0.1")
    c.setup(test)
    c.conn.sock.close()   # simulate a dropped connection
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert out["type"] == "fail" and out["error"][0] == "net"
    # next invoke reconnects transparently
    out = c.invoke(test, {"f": "read", "value": None, "process": 0})
    assert out["type"] == "ok" and sum(out["value"].values()) == 40
    c.close(test)


@pytest.mark.slow
def test_ignite_bank_fake_lifecycle():
    from conftest import run_fake
    from jepsen_tpu.suites.ignite import ignite_test

    res = run_fake(ignite_test, workload="bank", time_limit=2.0)
    r = res["results"]
    assert r["valid?"] is True, r
    assert r["workload"]["valid?"] is True
    assert r["stats"]["count"] > 0
