"""FaunaDB deep-suite probes: the monotonic / multimonotonic / internal
workloads (checker soundness on known-bad histories, client FQL
expression shapes, fake-mode lifecycles) and the topology membership
nemesis (reference: faunadb/src/jepsen/faunadb/{monotonic,
multimonotonic,internal,topology,nemesis}.clj)."""
import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import faunadb
from jepsen_tpu.workloads import (fauna_internal, fauna_monotonic,
                                  fauna_multimonotonic)

from conftest import run_fake  # noqa: E402

NODES = ["n1", "n2", "n3", "n4", "n5"]


def dummy_test(**over):
    t = {"nodes": list(NODES), "ssh": {"dummy": True}, "concurrency": 2}
    t.update(over)
    return t


@pytest.fixture()
def dummy():
    t = dummy_test()
    remote = control.default_remote(t)
    yield t, remote
    control.disconnect_all(t)


# ---------------------------------------------------------------------------
# monotonic: checkers
# ---------------------------------------------------------------------------

def _ok(f, value, process=0, index=0):
    return {"type": "ok", "f": f, "value": value, "process": process,
            "index": index}


def test_monotonic_per_process_catches_value_regression():
    history = [_ok("read", [1, 5], process=0),
               _ok("read", [2, 3], process=0)]  # value went backwards
    out = fauna_monotonic.PerProcessMonotonicChecker().check({}, history, {})
    assert out["valid?"] is False
    assert out["value-error-count"] == 1
    assert out["ts-error-count"] == 0


def test_monotonic_per_process_catches_ts_regression():
    history = [_ok("inc", ["2020-01-01T00:00:09", 1], process=1),
               _ok("read", ["2020-01-01T00:00:05", 2], process=1)]
    out = fauna_monotonic.PerProcessMonotonicChecker().check({}, history, {})
    assert out["valid?"] is False
    assert out["ts-error-count"] == 1


def test_monotonic_per_process_ignores_cross_process_order():
    history = [_ok("read", [5, 9], process=0),
               _ok("read", [6, 2], process=1)]  # different session: fine
    out = fauna_monotonic.PerProcessMonotonicChecker().check({}, history, {})
    assert out["valid?"] is True


def test_timestamp_value_checker_global_order():
    # read-at completions: higher timestamp must not show a lower value
    history = [_ok("read-at", [10, 4]),
               _ok("read-at", [20, 2]),
               _ok("inc", [30, 5])]
    out = fauna_monotonic.TimestampValueChecker().check({}, history, {})
    assert out["valid?"] is False and out["error-count"] == 1
    good = [_ok("read-at", [10, 1]), _ok("read-at", [20, 1]),
            _ok("inc", [30, 2])]
    assert fauna_monotonic.TimestampValueChecker().check(
        {}, good, {})["valid?"] is True


def test_not_found_checker():
    history = [{"type": "fail", "f": "read", "error": ["not-found"]},
               {"type": "invoke", "f": "read", "value": None}]
    out = fauna_monotonic.NotFoundChecker().check({}, history, {})
    assert out["valid?"] is False and out["error-count"] == 1


def test_merged_windows():
    assert fauna_monotonic.merged_windows(2, [5, 6, 20]) == [[3, 8], [18, 22]]
    assert fauna_monotonic.merged_windows(2, []) == []


@pytest.mark.slow
def test_timestamp_value_plotter_renders_windows(tmp_path):
    history = []
    for i in range(40):
        # process 0 sees a regression at ts 20
        v = 3 if i == 20 else i // 2
        history.append(_ok("read-at", [i, v], process=0, index=i))
    t = {"name": "plot-test", "store_dir": str(tmp_path),
         "start_time": "t"}
    out = fauna_monotonic.TimestampValuePlotter().check(t, history, {})
    assert out["valid?"] is True and out["spot-count"] >= 1
    pngs = list(tmp_path.rglob("sequential-*.png"))
    assert pngs, "expected a rendered window plot"


# ---------------------------------------------------------------------------
# multimonotonic: checkers
# ---------------------------------------------------------------------------

def _mread(ts, regs, index=0):
    return {"type": "ok", "f": "read", "index": index,
            "value": {"ts": ts,
                      "registers": {k: {"value": v, "ts": ts}
                                    for k, v in regs.items()}}}


def test_ts_order_checker_catches_backwards_read():
    history = [_mread(1, {"a": 5}, index=0),
               _mread(2, {"a": 3}, index=1)]  # a regressed at later ts
    out = fauna_multimonotonic.TsOrderChecker().check({}, history, {})
    assert out["valid?"] is False
    err = out["errors"][0]
    assert err["inferred"] == {"a": 5} and err["observed"] == {"a": 3}
    assert "a" in err["errors"]


def test_ts_order_checker_valid_on_monotonic():
    history = [_mread(1, {"a": 1, "b": 1}), _mread(2, {"a": 2}),
               _mread(3, {"a": 2, "b": 4})]
    assert fauna_multimonotonic.TsOrderChecker().check(
        {}, history, {})["valid?"] is True


def test_read_skew_checker_catches_skew():
    # r1: x=1,y=2; r2: x=2,y=1 — x orders r1<r2, y orders r2<r1
    history = [_mread(1, {"x": 1, "y": 2}, index=0),
               _mread(2, {"x": 2, "y": 1}, index=1)]
    out = fauna_multimonotonic.ReadSkewChecker().check({}, history, {})
    assert out["valid?"] is False
    assert out["skew-component-count"] == 1


def test_read_skew_checker_valid_on_compatible_orders():
    history = [_mread(1, {"x": 1, "y": 1}, index=0),
               _mread(2, {"x": 2, "y": 1}, index=1),
               _mread(3, {"x": 2, "y": 2}, index=2)]
    out = fauna_multimonotonic.ReadSkewChecker().check({}, history, {})
    assert out["valid?"] is True


# ---------------------------------------------------------------------------
# internal: checker
# ---------------------------------------------------------------------------

def test_internal_checker_create_errors():
    bad = [{"type": "ok", "f": "create-tabby-let",
            "value": {"tabbies-0": ["cat-1"], "tabby": "cat-1",
                      "tabbies-1": []}}]
    out = fauna_internal.InternalChecker().check({}, bad, {})
    assert out["valid?"] is False
    assert out["error-types"] == ["missing-after-create",
                                  "present-before-create"]


def test_internal_checker_change_type_errors():
    bad = [{"type": "ok", "f": "change-type",
            "value": ["cat-2", ["cat-2"], []]}]
    out = fauna_internal.InternalChecker().check({}, bad, {})
    assert out["valid?"] is False
    assert out["error-types"] == ["missing-after-change",
                                  "present-after-change"]


def test_internal_checker_valid():
    good = [
        {"type": "ok", "f": "create-tabby-obj",
         "value": {"tabbies-0": [], "tabby": "cat-0",
                   "tabbies-1": ["cat-0"]}},
        {"type": "ok", "f": "change-type",
         "value": ["cat-0", [], ["cat-0"]]},
        {"type": "ok", "f": "change-type", "value": [None, [], []]},
        {"type": "ok", "f": "reset", "value": None},
    ]
    assert fauna_internal.InternalChecker().check(
        {}, good, {})["valid?"] is True


# ---------------------------------------------------------------------------
# client FQL expression shapes (scripted _query doubles)
# ---------------------------------------------------------------------------

def test_monotonic_client_inc_expression():
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            return [{"@ts": "2020-01-01T00:00:01Z"}, 4]

    out = TClient(node="n1").invoke(
        {"fauna_monotonic": True},
        {"f": "inc", "type": "invoke", "value": None})
    assert out["type"] == "ok"
    assert out["value"] == ["2020-01-01T00:00:01", 4]  # Z stripped
    expr = sent[0]
    assert expr[0] == faunadb.TIME_NOW
    # the exists branch binds v then updates to v+1 and yields v
    then = expr[1]["then"]
    assert "let" in then
    add = then["in"]["do"][0]["update"]
    assert add == {"@ref": "classes/registers/0"}


def test_monotonic_client_read_at_jitters_now():
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            if expr == faunadb.TIME_NOW:
                return {"@ts": "2020-01-01T00:00:10Z"}
            return ["2020-01-01T00:00:09.5", 3]

    out = TClient(node="n1").invoke(
        {"fauna_monotonic": True},
        {"f": "read-at", "type": "invoke", "value": [None, None]})
    assert out["type"] == "ok" and out["value"][1] == 3
    # second query wraps the jittered (≤ now) timestamp in At, re-tagged
    # as a timestamp VALUE through Time(), not a bare string
    at = sent[1][1]
    assert "at" in at and "time" in at["at"]
    assert at["at"]["time"] <= "2020-01-01T00:00:10Z"
    assert at["at"]["time"].endswith("Z")


def test_multimonotonic_client_read_parses_instances():
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            return [{"@ts": "2020-01-01T00:00:02Z"},
                    [{"ts": 123, "data": {"value": 7}}, None]]

    out = TClient(node="n1").invoke(
        {"fauna_multimonotonic": True},
        {"f": "read", "type": "invoke", "value": [3, 9]})
    assert out["type"] == "ok"
    v = out["value"]
    assert v["ts"] == "2020-01-01T00:00:02"
    assert v["registers"] == {3: {"value": 7, "ts": 123}}  # 9 was absent


def test_internal_client_obj_form_permutes_keys():
    sent = []

    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            sent.append(expr)
            return {"c": {"data": []}, "a": "inst", "b": {"data": ["cat-5"]}}

    out = TClient(node="n1").invoke(
        {"fauna_internal": True},
        {"f": "create-tabby-obj", "type": "invoke", "value": 5})
    assert out["type"] == "ok"
    assert out["value"] == {"tabbies-0": [], "tabby": "cat-5",
                            "tabbies-1": ["cat-5"]}
    obj = sent[0]["object"]
    # declaration order c (before), a (create), b (after) — deliberately
    # not alphabetical (internal.clj:98-113)
    assert list(obj.keys()) == ["c", "a", "b"]
    assert obj["a"]["create"] == {"@ref": "classes/cats/5"}


def test_internal_client_change_type_value_shape():
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            return ["cat-1", {"data": []}, {"data": ["cat-1"]}]

    out = TClient(node="n1").invoke(
        {"fauna_internal": True},
        {"f": "change-type", "type": "invoke", "value": None})
    assert out["type"] == "ok"
    assert out["value"] == ["cat-1", [], ["cat-1"]]


def test_multimonotonic_not_found_read_fails_not_fabricates():
    """A not-found on a multimonotonic read (key-list value) must NOT
    take the register-workload's ok-empty recovery — with 2 keys the
    shapes collide and a fabricated [k, None] would silently pass."""
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            raise faunadb.FaunaError([{"code": "instance not found"}])

    out = TClient(node="n1").invoke(
        {"fauna_multimonotonic": True},
        {"f": "read", "type": "invoke", "value": [3, 9]})
    assert out["type"] == "fail"
    assert "not-found" in out["error"]


def test_not_found_error_is_tagged_for_checker():
    """The client's not-found failures carry the literal "not-found"
    element the NotFoundChecker matches on."""
    class TClient(faunadb.FaunaClient):
        def _query(self, expr):
            raise faunadb.FaunaError([{"code": "instance not found"}])

    out = TClient(node="n1").invoke(
        {"fauna_monotonic": True},
        {"f": "read-at", "type": "invoke", "value": [5, None]})
    assert out["type"] == "fail"  # temporal reads are idempotent: fail
    res = fauna_monotonic.NotFoundChecker().check({}, [out], {})
    assert res["valid?"] is False and res["error-count"] == 1


# ---------------------------------------------------------------------------
# fake-mode lifecycles
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fauna_fake_monotonic_run():
    result = run_fake(faunadb.faunadb_test, workload="monotonic")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_fauna_fake_multimonotonic_run():
    result = run_fake(faunadb.faunadb_test, workload="multimonotonic")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_fauna_fake_internal_run():
    result = run_fake(faunadb.faunadb_test, workload="internal")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# topology membership nemesis
# ---------------------------------------------------------------------------

def test_topology_initial_model_and_ops(dummy):
    t, _ = dummy
    topo = faunadb.FaunaTopology(replicas=3)
    topo._ensure_topo(t)
    assert topo.topo["replica_count"] == 3
    # 5 nodes over 3 replicas: two replicas have 2 members
    op = topo.op(t)
    assert op["f"] == "remove-node"  # nothing absent yet
    assert op["value"] in {"n1", "n2", "n4", "n5"}  # n3 alone in replica-2


def test_topology_invoke_remove_then_add(dummy):
    t, remote = dummy
    import random
    topo = faunadb.FaunaTopology(replicas=3, rng=random.Random(7))
    topo._ensure_topo(t)
    out = topo.invoke(t, {"f": "remove-node", "value": "n1"})
    assert out == ["removed", "n1"]
    assert all(n["node"] != "n1" for n in topo.topo["nodes"])
    cmds = [c for (kind, _h, c) in remote.log if kind == "exec"]
    assert any("faunadb-admin remove n1" in c for c in cmds)
    # n1 now absent → an add op becomes possible
    ops = {topo.op(t)["f"] for _ in range(30)}
    assert "add-node" in ops
    out = topo.invoke(t, {"f": "add-node",
                          "value": {"node": "n1", "join": "n2"}})
    assert out[0] == "added"
    assert any(n["node"] == "n1" for n in topo.topo["nodes"])
    cmds = [c for (kind, _h, c) in remote.log if kind == "exec"]
    assert any("faunadb-admin join" in c for c in cmds)


def test_topology_node_view_parses_status(dummy):
    t, _ = dummy
    topo = faunadb.FaunaTopology()

    class R:
        pass

    # scripted: feed a status table through a stand-in exec
    import jepsen_tpu.control as ctl
    real_on = ctl.on
    try:
        ctl.on = lambda node, test, fn: (
            "n1 replica-0 Active\nn2 replica-1 Active\njunk line")
        view = topo.node_view(t, "n1")
    finally:
        ctl.on = real_on
    assert view == [
        {"node": "n1", "replica": "replica-0", "state": "active"},
        {"node": "n2", "replica": "replica-1", "state": "active"}]


@pytest.mark.slow
def test_fauna_fake_run_with_topology_fault():
    result = run_fake(faunadb.faunadb_test, workload="register",
                      faults={"topology"}, nemesis_interval=0.2,
                      time_limit=1.5)
    assert result["results"]["valid?"] is True, result["results"]
    # the membership nemesis actually emitted topology transitions
    fs = {op.get("f") for op in result["history"]
          if not isinstance(op.get("process"), int)}
    assert fs & {"add-node", "remove-node"}, fs


# ---------------------------------------------------------------------------
# replica-aware partitions (nemesis.clj:29-55)
# ---------------------------------------------------------------------------

def test_replica_partition_ops_shapes(dummy):
    import random

    t, _ = dummy
    topo = faunadb.FaunaTopology(replicas=3)
    topo._ensure_topo(t)
    start = faunadb.replica_partition_ops(topo, rng=random.Random(3))
    seen = set()
    for _ in range(40):
        op = start(t, None)
        assert op["f"] == "start-partition-replica"
        v = op["value"]
        grudge, ptype = v["grudge"], v["partition-type"]
        seen.add(ptype[0])
        if ptype[0] == "intra-replica":
            # both sides live in ONE replica; other replicas untouched
            members = {n["node"]: n["replica"] for n in topo.topo["nodes"]}
            involved = set(grudge) | {x for xs in grudge.values()
                                      for x in xs}
            assert len({members[n] for n in involved}) == 1
            assert ptype[1].startswith("replica-")
        else:
            # inter-replica: whole replica groups land on one side
            members = {}
            for n in topo.topo["nodes"]:
                members.setdefault(n["replica"], set()).add(n["node"])
            for group in members.values():
                sides = {frozenset(grudge.get(n, [])) for n in group}
                assert len(sides) == 1, "a replica must not be split"
    assert seen == {"intra-replica", "inter-replica"}


@pytest.mark.slow
def test_replica_partition_fake_run_composes_with_topology():
    result = run_fake(faunadb.faunadb_test, workload="register",
                      time_limit=3.0, nemesis_interval=0.5,
                      faults={"topology", "partition-replica"})
    h = result["history"]
    starts = [op for op in h if op.get("f") == "start-partition-replica"
              and op.get("type") == "info"
              and isinstance(op.get("value"), list)]
    assert starts, "replica partitions must fire"
    assert any(op.get("f") in ("add-node", "remove-node") for op in h), \
        "topology nemesis must run alongside"
    assert result["results"]["valid?"] is True
