"""independent key-lifting tests (reference: independent_test.clj), incl.
the batched vmapped checker over the 8-device virtual CPU mesh."""
import random

import jepsen_tpu.generator as gen
from jepsen_tpu import independent as ind
from jepsen_tpu.checker.linearizable import LinearizableChecker
from jepsen_tpu.generator.simulate import invocations, perfect, quick


TEST = {"concurrency": 4}


def test_tuple_gen_wraps_values():
    h = quick(TEST, ind.tuple_gen("k1", gen.limit(2, gen.repeat({"f": "read"}))))
    assert all(op["value"][0] == "k1" for op in h)


def test_sequential_generator_orders_keys():
    g = ind.sequential_generator(
        ["a", "b"], lambda k: gen.limit(3, gen.repeat({"f": "w", "value": k})))
    h = quick(TEST, g)
    keys = [op["value"][0] for op in invocations(h)]
    assert keys == ["a"] * 3 + ["b"] * 3


def test_concurrent_generator_groups():
    g = ind.concurrent_generator(
        2, ["a", "b", "c", "d"],
        lambda k: gen.limit(4, gen.repeat({"f": "read"})))
    h = perfect(TEST, gen.clients(g))
    inv = invocations(h)
    assert len(inv) == 16  # 4 keys x 4 ops
    # group 0 = threads {0,1}, group 1 = threads {2,3}... with concurrency 4
    # each group claims keys in rotation; every key's ops stay in one group
    by_key = {}
    for op in inv:
        by_key.setdefault(op["value"][0], set()).add(op["process"] % 4 // 2)
    for k, groups in by_key.items():
        assert len(groups) == 1, (k, groups)


def test_history_keys_and_subhistory():
    h = [
        {"type": "invoke", "process": 0, "f": "w", "value": ["a", 1]},
        {"type": "ok", "process": 0, "f": "w", "value": ["a", 1]},
        {"type": "invoke", "process": 1, "f": "w", "value": ["b", 2]},
        {"type": "ok", "process": 1, "f": "w", "value": ["b", 2]},
    ]
    assert ind.history_keys(h) == ["a", "b"]
    sub = ind.subhistory("a", h)
    assert len(sub) == 2
    assert sub[0]["value"] == 1


def make_key_history(rng, corrupt=False):
    """A small linearizable register history (optionally corrupted)."""
    ops = []
    val = None
    for i in range(30):
        p = rng.randrange(3)
        if rng.random() < 0.5:
            v = rng.randrange(4)
            ops.append({"type": "invoke", "process": p, "f": "write", "value": v})
            ops.append({"type": "ok", "process": p, "f": "write", "value": v})
            val = v
        else:
            ops.append({"type": "invoke", "process": p, "f": "read", "value": None})
            ops.append({"type": "ok", "process": p, "f": "read", "value": val})
    if corrupt:
        for op in reversed(ops):
            if op["type"] == "ok" and op["f"] == "read":
                op["value"] = 77
                break
    return ops


def lift(k, ops):
    return [{**op, "value": [k, op["value"]]} for op in ops]


def test_independent_checker_cpu():
    rng = random.Random(3)
    h = []
    for k in range(6):
        h.extend(lift(f"k{k}", make_key_history(rng, corrupt=(k == 4))))
    chk = ind.checker(LinearizableChecker(accelerator="cpu"))
    r = chk.check({}, h, {})
    assert r["valid?"] is False
    assert r["failures"] == ["k4"]
    assert r["count"] == 6


def test_independent_checker_batched_device():
    """The vmapped/sharded fast path agrees with per-key CPU checking."""
    rng = random.Random(9)
    h = []
    bad_keys = {"k2", "k5"}
    for k in range(8):
        name = f"k{k}"
        h.extend(lift(name, make_key_history(rng, corrupt=name in bad_keys)))
    chk = ind.checker(LinearizableChecker(accelerator="tpu"))
    r = chk.check({}, h, {})
    assert r["valid?"] is False
    assert set(r["failures"]) == bad_keys
    # device kernel actually used
    assert any(v.get("algorithm", "").startswith("jitlin")
               for v in r["results"].values())


def test_batch_check_sharded_over_mesh():
    """batch_check shards keys over the 8-device virtual CPU mesh."""
    import jax
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.parallel import batch_check, get_mesh
    assert len(jax.devices()) == 8, "conftest should give 8 virtual devices"
    rng = random.Random(11)
    streams = [encode_register_ops(make_key_history(rng, corrupt=(i % 3 == 0)))
               for i in range(11)]  # deliberately not a multiple of 8
    mesh = get_mesh()
    out = batch_check(streams, capacity=64, mesh=mesh)
    assert len(out) == 11
    for i, (alive, died, ovf, peak) in enumerate(out):
        from jepsen_tpu.checker.linear_cpu import check_stream
        expected = check_stream(streams[i]).valid
        from jepsen_tpu.ops.jitlin import verdict
        assert verdict(alive, ovf) == expected, i


def test_batched_path_sees_through_compose(tmp_path):
    """The register workload composes linear+timeline per key; the
    batched kernel path must still engage for the linear sub-checker,
    and each key's timeline must land in its own independent/<k> dir."""
    import os

    from jepsen_tpu import checker as chk
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import CASRegister

    inner = chk.compose({"linear": linearizable(model=CASRegister()),
                         "timeline": chk.timeline_html()})
    c = ind.checker(inner)
    h = []
    for k in ("a", "b"):
        h += [
            {"type": "invoke", "process": 0, "f": "write", "value": [k, 1],
             "time": 1},
            {"type": "ok", "process": 0, "f": "write", "value": [k, 1],
             "time": 2},
            {"type": "invoke", "process": 1, "f": "read", "value": [k, None],
             "time": 3},
            {"type": "ok", "process": 1, "f": "read", "value": [k, 1],
             "time": 4},
        ]
    test = {"name": "ind-compose", "start_time": "t0",
            "store_dir": str(tmp_path)}
    out = c.check(test, h, {})
    assert out["valid?"] is True
    for k in ("a", "b"):
        sub = out["results"][k]
        assert sub["linear"]["algorithm"].startswith("jitlin"), sub
        assert sub["timeline"]["valid?"] is True
        assert os.path.exists(
            tmp_path / "ind-compose" / "t0" / "independent" / k
            / "timeline.html")


def test_batched_device_path_actually_engages():
    """Regression: the batched independent fast path must produce
    jitlin-tpu verdicts, not silently fall back per-key (a signature
    drift in the checker once made every batch raise and the broad
    fallback ate it)."""
    from jepsen_tpu import independent
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import CASRegister

    history = []
    for k in range(3):
        for i, v in enumerate([1, 2, 3]):
            history.append({"type": "invoke", "process": k, "f": "write",
                            "value": [k, v]})
            history.append({"type": "ok", "process": k, "f": "write",
                            "value": [k, v]})
    chk = independent.checker(linearizable(model=CASRegister(),
                                           accelerator="tpu"))
    out = chk.check({}, history, {})
    assert out["valid?"] is True
    per_key = list(out["results"].values())
    assert len(per_key) == 3, out
    assert all(r.get("algorithm", "").startswith("jitlin-tpu")
               for r in per_key), out


def test_batched_device_path_nonzero_init_state():
    """CASRegister(0) (single-key-acid) must thread its initial value
    through the batched encoding: a first read of 0 is valid."""
    from jepsen_tpu import independent
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import CASRegister

    history = []
    for k in range(2):
        history.append({"type": "invoke", "process": k, "f": "read",
                        "value": None})
        history.append({"type": "ok", "process": k, "f": "read",
                        "value": [k, 0]})
    chk = independent.checker(linearizable(model=CASRegister(0),
                                           accelerator="tpu"))
    out = chk.check({}, history, {})
    assert out["valid?"] is True, out
