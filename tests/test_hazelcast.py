"""Hazelcast CP-subsystem tests: the lock-model family (reference
models hazelcast.clj:516-650), the Open Binary Client Protocol wire
client against an in-process mock member, the suite's error mapping,
and the fake-mode lifecycle for every CP workload."""
from __future__ import annotations

import socket
import struct
import threading

import pytest

from jepsen_tpu.models import (AcquiredPermits, FencedMutex, OwnerMutex,
                               ReentrantFencedMutex, ReentrantMutex,
                               is_inconsistent)
from jepsen_tpu.suites import _hazelcast as hz
from jepsen_tpu.suites._hazelcast import (BEGIN_FRAME, END_FRAME, Frame,
                                          HzClient, HzError, MSG, NULL_FRAME,
                                          RESPONSE_HEADER, REQUEST_HEADER,
                                          decode_raft_group, encode_message,
                                          encode_uuid, read_message,
                                          str_frame)


def _op(f, process, value=None, **kw):
    return {"f": f, "process": process, "value": value, **kw}


# ---------------------------------------------------------------------------
# models
# ---------------------------------------------------------------------------

def test_owner_mutex_owner_checked():
    m = OwnerMutex()
    m = m.step(_op("acquire", 1))
    assert not is_inconsistent(m)
    assert is_inconsistent(m.step(_op("acquire", 2)))
    assert is_inconsistent(m.step(_op("release", 2)))
    m = m.step(_op("release", 1))
    assert not is_inconsistent(m) and m.owner is None


def test_reentrant_mutex_bounded_holds():
    m = ReentrantMutex(max_holds=2)
    m = m.step(_op("acquire", 1))
    m = m.step(_op("acquire", 1))          # re-acquire: ok
    assert not is_inconsistent(m)
    assert is_inconsistent(m.step(_op("acquire", 1)))   # third: over bound
    assert is_inconsistent(m.step(_op("acquire", 2)))   # other client
    m = m.step(_op("release", 1))
    assert m.owner == 1                     # still held once
    assert is_inconsistent(m.step(_op("release", 2)))
    m = m.step(_op("release", 1))
    assert m.owner is None and m.holds == 0


def test_fenced_mutex_fence_monotonicity():
    m = FencedMutex()
    m = m.step(_op("acquire", 1, 5))
    assert m.fence == 5
    m = m.step(_op("release", 1))
    # next fence must exceed 5; an equal or lower fence is inconsistent
    assert is_inconsistent(m.step(_op("acquire", 2, 5)))
    assert is_inconsistent(m.step(_op("acquire", 2, 4)))
    m2 = m.step(_op("acquire", 2, 6))
    assert m2.fence == 6
    # an acquire with no observed fence (crashed acquire) is always legal
    m3 = m.step(_op("acquire", 2, None))
    assert m3.owner == 2 and m3.fence == 5


def test_reentrant_fenced_mutex_same_fence_on_reacquire():
    m = ReentrantFencedMutex(max_holds=2)
    m = m.step(_op("acquire", 1, 7))
    m2 = m.step(_op("acquire", 1, 7))      # same fence: ok
    assert not is_inconsistent(m2)
    assert is_inconsistent(m.step(_op("acquire", 1, 8)))  # new fence held
    m2 = m2.step(_op("release", 1))
    m2 = m2.step(_op("release", 1))
    assert m2.owner is None
    assert is_inconsistent(m2.step(_op("acquire", 2, 7)))  # ≤ highest
    assert not is_inconsistent(m2.step(_op("acquire", 2, 8)))


def test_reentrant_fenced_mutex_unknown_fence_reveal():
    m = ReentrantFencedMutex(max_holds=2)
    m = m.step(_op("acquire", 1, None))    # crashed acquire, fence unknown
    assert m.fence == 0 and m.owner == 1
    m2 = m.step(_op("acquire", 1, 9))      # re-acquire reveals the fence
    assert m2.fence == 9 and m2.highest == 9
    # a revealed fence must still exceed every previously observed one
    stale = ReentrantFencedMutex(owner=1, holds=1, fence=0, highest=10)
    assert is_inconsistent(stale.step(_op("acquire", 1, 5)))


def test_acquired_permits_caps_and_ownership():
    m = AcquiredPermits(permits=2)
    m = m.step(_op("acquire", 1))
    m = m.step(_op("acquire", 2))
    assert is_inconsistent(m.step(_op("acquire", 3)))   # permits exhausted
    assert is_inconsistent(m.step(_op("release", 3)))   # holds nothing
    m = m.step(_op("release", 1))
    m = m.step(_op("acquire", 3))
    assert not is_inconsistent(m)


# ---------------------------------------------------------------------------
# mock member
# ---------------------------------------------------------------------------

class MockMember:
    """In-process Hazelcast member speaking the 2.x client protocol from
    the server side: auth, Raft-group resolution, CP sessions, an
    AtomicLong, a reentrant FencedLock, and a counting semaphore."""

    def __init__(self, max_holds=2, permits=2):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.lock = threading.Lock()
        self.along: dict[str, int] = {}
        self.sessions = 0
        self.threads = 0
        self.fences = 0
        self.locks: dict = {}   # name -> [holder(sid,tid)|None, holds, fence]
        self.sem: dict = {}     # name -> {holder: count}
        self.sem_permits: dict = {}
        self.maps: dict = {}    # map name -> {key blob: value blob}
        self.refs: dict = {}    # ref name -> Data blob | None
        self.flake = 0
        self.max_holds = max_holds
        self.permits = permits
        self.auths = 0
        self.stop = False
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def close(self):
        self.stop = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self.stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            proto = b""
            while len(proto) < 3:
                proto += conn.recv(3 - len(proto))
            assert proto == b"CP2", proto
            while True:
                frames = read_message(conn)
                conn.sendall(self._dispatch(frames))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- response builders --------------------------------------------------

    @staticmethod
    def _resp(req_type, corr, fixed=b"", var=None):
        initial = Frame(struct.pack("<IqB", req_type + 1, corr, 0) + fixed)
        return encode_message([initial] + (var or []))

    @staticmethod
    def _error(corr, code, class_name, message=""):
        initial = Frame(struct.pack("<IqB", hz.EXCEPTION_MSG_TYPE, corr, 0))
        frames = [initial, BEGIN_FRAME, BEGIN_FRAME,
                  Frame(struct.pack("<i", code)), str_frame(class_name),
                  str_frame(message) if message else NULL_FRAME,
                  BEGIN_FRAME, END_FRAME,   # empty stack trace list
                  END_FRAME, END_FRAME]
        return encode_message(frames)

    # -- request decode helpers --------------------------------------------

    @staticmethod
    def _group_and_name(frames):
        group, j = decode_raft_group(frames, 1)
        return group, frames[j].payload.decode()

    def _dispatch(self, frames) -> bytes:
        rtype, corr = struct.unpack_from("<Iq", frames[0].payload, 0)
        fixed = frames[0].payload[REQUEST_HEADER:]
        with self.lock:
            if rtype == MSG["client.authentication"]:
                self.auths += 1
                body = (b"\x00" + encode_uuid(b"\x11" * 16) + b"\x01"
                        + struct.pack("<i", 271) + encode_uuid(b"\x22" * 16)
                        + b"\x00")
                return self._resp(rtype, corr, body,
                                  [NULL_FRAME, str_frame("5.3.7")])
            if rtype == MSG["cpgroup.createcpgroup"]:
                name = frames[1].payload.decode()
                return self._resp(
                    rtype, corr, b"",
                    [BEGIN_FRAME, Frame(struct.pack("<qq", 0, 7)),
                     str_frame(name), END_FRAME])
            if rtype == MSG["cpsession.createsession"]:
                self.sessions += 1
                return self._resp(rtype, corr,
                                  struct.pack("<qqq", self.sessions,
                                              30_000, 5_000))
            if rtype == MSG["cpsession.heartbeatsession"]:
                sid = struct.unpack_from("<q", fixed, 0)[0]
                if sid > self.sessions:
                    return self._error(corr, 17,
                                       "com.hazelcast.cp.internal.session."
                                       "SessionExpiredException")
                return self._resp(rtype, corr)
            if rtype == MSG["cpsession.generatethreadid"]:
                self.threads += 1
                return self._resp(rtype, corr,
                                  struct.pack("<q", self.threads))
            if rtype == MSG["atomiclong.addandget"]:
                delta = struct.unpack_from("<q", fixed, 0)[0]
                _, name = self._group_and_name(frames)
                v = self.along.get(name, 0) + delta
                self.along[name] = v
                return self._resp(rtype, corr, struct.pack("<q", v))
            if rtype == MSG["atomiclong.get"]:
                _, name = self._group_and_name(frames)
                return self._resp(rtype, corr,
                                  struct.pack("<q", self.along.get(name, 0)))
            if rtype == MSG["atomiclong.compareandset"]:
                old, new = struct.unpack_from("<qq", fixed, 0)
                _, name = self._group_and_name(frames)
                ok = self.along.get(name, 0) == old
                if ok:
                    self.along[name] = new
                return self._resp(rtype, corr, struct.pack("<b", ok))
            if rtype == MSG["atomiclong.getandset"]:
                new = struct.unpack_from("<q", fixed, 0)[0]
                _, name = self._group_and_name(frames)
                v = self.along.get(name, 0)
                self.along[name] = new
                return self._resp(rtype, corr, struct.pack("<q", v))
            if rtype == MSG["fencedlock.trylock"]:
                sid, tid = struct.unpack_from("<qq", fixed, 0)
                _, name = self._group_and_name(frames)
                st = self.locks.setdefault(name, [None, 0, 0])
                if st[0] is None:
                    self.fences += 1
                    st[0], st[1], st[2] = (sid, tid), 1, self.fences
                    fence = st[2]
                elif st[0] == (sid, tid) and st[1] < self.max_holds:
                    st[1] += 1
                    fence = st[2]
                else:
                    fence = 0
                return self._resp(rtype, corr, struct.pack("<q", fence))
            if rtype == MSG["fencedlock.unlock"]:
                sid, tid = struct.unpack_from("<qq", fixed, 0)
                _, name = self._group_and_name(frames)
                st = self.locks.setdefault(name, [None, 0, 0])
                if st[0] != (sid, tid):
                    return self._error(
                        corr, 24, "java.lang.IllegalMonitorStateException",
                        "Current thread is not owner of the lock!")
                st[1] -= 1
                if st[1] == 0:
                    st[0] = None
                return self._resp(rtype, corr,
                                  struct.pack("<b", st[1] > 0))
            if rtype == MSG["semaphore.init"]:
                permits = struct.unpack_from("<i", fixed, 0)[0]
                _, name = self._group_and_name(frames)
                fresh = name not in self.sem_permits
                if fresh:
                    self.sem_permits[name] = permits
                    self.sem[name] = {}
                return self._resp(rtype, corr, struct.pack("<b", fresh))
            if rtype == MSG["semaphore.acquire"]:
                sid, tid = struct.unpack_from("<qq", fixed, 0)
                _, name = self._group_and_name(frames)
                held = self.sem.setdefault(name, {})
                cap = self.sem_permits.get(name, self.permits)
                ok = sum(held.values()) < cap
                if ok:
                    held[(sid, tid)] = held.get((sid, tid), 0) + 1
                return self._resp(rtype, corr, struct.pack("<b", ok))
            if rtype == MSG["semaphore.release"]:
                sid, tid = struct.unpack_from("<qq", fixed, 0)
                _, name = self._group_and_name(frames)
                held = self.sem.setdefault(name, {})
                if held.get((sid, tid), 0) <= 0:
                    return self._error(
                        corr, 25, "java.lang.IllegalArgumentException",
                        "not a permit holder")
                held[(sid, tid)] -= 1
                return self._resp(rtype, corr, struct.pack("<b", 1))
            if rtype == MSG["map.get"]:
                name = frames[1].payload.decode()
                got = self.maps.get(name, {}).get(bytes(frames[2].payload))
                return self._resp(rtype, corr, b"",
                                  [NULL_FRAME if got is None
                                   else Frame(got)])
            if rtype == MSG["map.put"]:
                name = frames[1].payload.decode()
                m = self.maps.setdefault(name, {})
                k = bytes(frames[2].payload)
                old = m.get(k)
                m[k] = bytes(frames[3].payload)
                return self._resp(rtype, corr, b"",
                                  [NULL_FRAME if old is None
                                   else Frame(old)])
            if rtype == MSG["map.putifabsent"]:
                name = frames[1].payload.decode()
                m = self.maps.setdefault(name, {})
                k = bytes(frames[2].payload)
                old = m.get(k)
                if old is None:
                    m[k] = bytes(frames[3].payload)
                return self._resp(rtype, corr, b"",
                                  [NULL_FRAME if old is None
                                   else Frame(old)])
            if rtype == MSG["map.replaceifsame"]:
                name = frames[1].payload.decode()
                m = self.maps.setdefault(name, {})
                k = bytes(frames[2].payload)
                ok = m.get(k) == bytes(frames[3].payload)
                if ok:
                    m[k] = bytes(frames[4].payload)
                return self._resp(rtype, corr, struct.pack("<b", ok))
            if rtype == MSG["atomicref.get"]:
                _, name = self._group_and_name(frames)
                got = self.refs.get(name)
                return self._resp(rtype, corr, b"",
                                  [NULL_FRAME if got is None
                                   else Frame(got)])
            if rtype == MSG["atomicref.set"]:
                g, j = hz.decode_raft_group(frames, 1)
                name = frames[j].payload.decode()
                vf = frames[j + 1]
                self.refs[name] = None if vf.is_null() \
                    else bytes(vf.payload)
                return self._resp(rtype, corr)
            if rtype == MSG["atomicref.compareandset"]:
                g, j = hz.decode_raft_group(frames, 1)
                name = frames[j].payload.decode()
                ef, vf = frames[j + 1], frames[j + 2]
                expected = None if ef.is_null() else bytes(ef.payload)
                ok = self.refs.get(name) == expected
                if ok:
                    self.refs[name] = None if vf.is_null() \
                        else bytes(vf.payload)
                return self._resp(rtype, corr, struct.pack("<b", ok))
            if rtype == MSG["flakeidgen.newidbatch"]:
                size = struct.unpack_from("<i", fixed, 0)[0]
                base = self.flake
                self.flake += size
                return self._resp(rtype, corr,
                                  struct.pack("<qqi", base, 1, size))
            return self._error(corr, -1, "java.lang."
                               "UnsupportedOperationException",
                               hex(rtype))


@pytest.fixture()
def member():
    m = MockMember()
    yield m
    m.close()


def _client(member) -> HzClient:
    return HzClient("127.0.0.1", member.port).connect()


# ---------------------------------------------------------------------------
# wire client vs mock member
# ---------------------------------------------------------------------------

def test_auth_handshake(member):
    c = _client(member)
    assert member.auths == 1
    c.close()


def test_atomic_long_ops(member):
    c = _client(member)
    assert c.atomic_add_and_get("jepsen.a", 1) == 1
    assert c.atomic_add_and_get("jepsen.a", 2) == 3
    assert c.atomic_get("jepsen.a") == 3
    assert c.atomic_compare_and_set("jepsen.a", 3, 9) is True
    assert c.atomic_compare_and_set("jepsen.a", 3, 5) is False
    assert c.atomic_get_and_set("jepsen.a", 0) == 9
    assert c.atomic_get("jepsen.a") == 0
    c.close()


def test_fenced_lock_fences_monotonic(member):
    c1, c2 = _client(member), _client(member)
    f1 = c1.lock_try_lock("jepsen.L")
    assert f1 > 0
    assert c2.lock_try_lock("jepsen.L") == 0       # busy -> invalid fence
    # reentrant acquire by the holder: same fence
    assert c1.lock_try_lock("jepsen.L") == f1
    c1.lock_unlock("jepsen.L")
    c1.lock_unlock("jepsen.L")
    f2 = c2.lock_try_lock("jepsen.L")
    assert f2 > f1                                  # fence grew
    c2.lock_unlock("jepsen.L")
    c1.close()
    c2.close()


def test_unlock_by_non_owner_raises(member):
    c1, c2 = _client(member), _client(member)
    assert c1.lock_try_lock("jepsen.L") > 0
    with pytest.raises(HzError) as ei:
        c2.lock_unlock("jepsen.L")
    assert "IllegalMonitorState" in ei.value.class_name
    c1.close()
    c2.close()


def test_semaphore_permits(member):
    c1, c2, c3 = (_client(member) for _ in range(3))
    assert c1.semaphore_init("jepsen.S", 2) is True
    assert c1.semaphore_acquire("jepsen.S") is True
    assert c2.semaphore_acquire("jepsen.S") is True
    assert c3.semaphore_acquire("jepsen.S") is False   # permits exhausted
    with pytest.raises(HzError):
        c3.semaphore_release("jepsen.S")
    assert c1.semaphore_release("jepsen.S")
    assert c3.semaphore_acquire("jepsen.S") is True
    for c in (c1, c2, c3):
        c.close()


def test_session_and_thread_id_reused(member):
    c = _client(member)
    c.lock_try_lock("jepsen.L")
    c.lock_unlock("jepsen.L")
    c.lock_try_lock("jepsen.L")
    # one session + one thread id for the whole connection
    assert member.sessions == 1
    assert member.threads == 1
    c.close()


def test_raft_group_codec_roundtrip():
    g = hz.RaftGroupId("default", 3, 12)
    frames = hz.raft_group_frames(g) + [str_frame("tail")]
    g2, j = decode_raft_group(frames, 0)
    assert (g2.name, g2.seed, g2.group_id) == ("default", 3, 12)
    assert frames[j].payload == b"tail"


# ---------------------------------------------------------------------------
# suite client error mapping (HzCPClient over the mock member)
# ---------------------------------------------------------------------------

def test_suite_lock_client_against_mock(member, monkeypatch):
    from jepsen_tpu.suites import hazelcast as suite

    monkeypatch.setattr(suite, "PORT", member.port)
    base = suite.HzCPClient("lock")
    c1 = base.open({}, "127.0.0.1")
    c2 = base.open({}, "127.0.0.1")
    op1 = c1.invoke({}, _op("acquire", 1))
    assert op1["type"] == "ok" and op1["value"] > 0
    assert c2.invoke({}, _op("acquire", 2))["type"] == "fail"
    # release by non-owner maps to a fail with the owner error
    bad = c2.invoke({}, _op("release", 2))
    assert bad["type"] == "fail" and bad["error"] == "not-lock-owner"
    assert c1.invoke({}, _op("release", 1))["type"] == "ok"
    got = c2.invoke({}, _op("acquire", 2))
    assert got["type"] == "ok" and got["value"] > op1["value"]
    c1.close({})
    c2.close({})


def test_suite_ids_and_cas_clients_against_mock(member, monkeypatch):
    from jepsen_tpu.suites import hazelcast as suite

    monkeypatch.setattr(suite, "PORT", member.port)
    ids = suite.HzCPClient("ids").open({}, "127.0.0.1")
    seen = {ids.invoke({}, _op("generate", 0))["value"] for _ in range(5)}
    assert len(seen) == 5
    cas = suite.HzCPClient("cas").open({}, "127.0.0.1")
    assert cas.invoke({}, _op("read", 0))["value"] == 0
    assert cas.invoke({}, _op("write", 0, 3))["type"] == "ok"
    assert cas.invoke({}, _op("cas", 0, [3, 4]))["type"] == "ok"
    out = cas.invoke({}, _op("cas", 0, [3, 4]))
    assert out["type"] == "fail" and out["error"] == "cas-failed"
    assert cas.invoke({}, _op("read", 0))["value"] == 4
    ids.close({})
    cas.close({})


def test_suite_net_error_mapping(monkeypatch):
    from jepsen_tpu.suites import hazelcast as suite

    # connect to a dead port: open fails; invoke on a closed conn -> info
    c = suite.HzCPClient("lock")
    c.conn = HzClient("127.0.0.1", 1)   # never connected
    out = c.invoke({}, _op("acquire", 1))
    assert out["type"] == "info" and out["error"][0] == "net"
    out = c.invoke({}, _op("read", 1))
    assert out["type"] == "fail"


# ---------------------------------------------------------------------------
# fake-mode lifecycle for every CP workload
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("wl", ["lock", "cp-lock", "reentrant-cp-lock",
                                "fenced-lock", "reentrant-fenced-lock",
                                "cp-semaphore", "atomic-long-ids",
                                "cp-cas-long"])
def test_hazelcast_cp_fake_lifecycle(wl):
    from conftest import run_fake
    from jepsen_tpu.suites.hazelcast import hazelcast_test

    res = run_fake(hazelcast_test, workload=wl, time_limit=2.0)
    r = res["results"]
    assert r["valid?"] is True, r
    assert r["workload"]["valid?"] is True
    assert r["stats"]["count"] > 0


def test_data_codec_roundtrip():
    from jepsen_tpu.suites._hazelcast import (data_long, data_long_array,
                                              data_string, decode_data)

    assert decode_data(data_long(-5)) == -5
    assert decode_data(data_string("héllo")) == "héllo"
    assert decode_data(data_long_array([3, 1, 2])) == [3, 1, 2]
    assert decode_data(data_long_array([])) == []


def test_map_cas_set_ops(member):
    from jepsen_tpu.suites._hazelcast import (data_long_array, data_string,
                                              decode_data)

    c1, c2 = _client(member), _client(member)
    key = data_string("hi")
    # first add wins via putIfAbsent
    assert c1.map_put_if_absent("jepsen.map", key,
                                data_long_array([1])) is None
    # losing putIfAbsent returns the existing value
    assert c2.map_put_if_absent("jepsen.map", key,
                                data_long_array([9])) == [1]
    # CAS grow: must hand back the exact stored blob
    cur = c1.map_get_raw("jepsen.map", key)
    assert decode_data(cur) == [1]
    assert c1.map_replace_if_same("jepsen.map", key, cur,
                                  data_long_array([1, 2])) is True
    # a stale CAS (old blob) is rejected
    assert c2.map_replace_if_same("jepsen.map", key, cur,
                                  data_long_array([1, 9])) is False
    assert c2.map_get("jepsen.map", key) == [1, 2]
    c1.close()
    c2.close()


def test_atomic_ref_and_flake_ids(member):
    c = _client(member)
    assert c.atomic_ref_get("jepsen.r") is None
    assert c.atomic_ref_compare_and_set("jepsen.r", None, 0) is True
    assert c.atomic_ref_compare_and_set("jepsen.r", None, 5) is False
    assert c.atomic_ref_compare_and_set("jepsen.r", 0, 7) is True
    assert c.atomic_ref_get("jepsen.r") == 7
    c.atomic_ref_set("jepsen.r", 9)
    assert c.atomic_ref_get("jepsen.r") == 9
    b0 = c.flake_id_batch("jepsen.g", 4)
    b1 = c.flake_id_batch("jepsen.g", 4)
    ids0 = {b0[0] + k * b0[1] for k in range(b0[2])}
    ids1 = {b1[0] + k * b1[1] for k in range(b1[2])}
    assert not ids0 & ids1, "batches must not overlap"
    c.close()


def test_suite_map_and_ref_clients_against_mock(member, monkeypatch):
    from jepsen_tpu.suites import hazelcast as suite

    monkeypatch.setattr(suite, "PORT", member.port)
    m1 = suite.HzCPClient("map").open({}, "127.0.0.1")
    m2 = suite.HzCPClient("map").open({}, "127.0.0.1")
    assert m1.invoke({}, _op("add", 0, 1))["type"] == "ok"
    assert m2.invoke({}, _op("add", 1, 2))["type"] == "ok"
    got = m1.invoke({}, _op("read", 0))
    assert got["type"] == "ok" and got["value"] == [1, 2]
    refs = suite.HzCPClient("ref-ids").open({}, "127.0.0.1")
    seen = {refs.invoke({}, _op("generate", 0))["value"]
            for _ in range(4)}
    assert seen == {1, 2, 3, 4}
    flake = suite.HzCPClient("flake-ids").open({}, "127.0.0.1")
    fl = [flake.invoke({}, _op("generate", 0))["value"] for _ in range(4)]
    assert len(set(fl)) == 4
    casr = suite.HzCPClient("cas-ref").open({}, "127.0.0.1")
    assert casr.invoke({}, _op("cas", 0, [0, 3]))["type"] in ("ok", "fail")
    for c in (m1, m2, refs, flake, casr):
        c.close({})


@pytest.mark.slow
@pytest.mark.parametrize("wl", ["map-set", "crdt-map", "atomic-ref-ids",
                                "id-gen-ids", "cp-id-gen-long",
                                "cp-cas-reference"])
def test_hazelcast_extended_fake_lifecycle(wl):
    from conftest import run_fake
    from jepsen_tpu.suites.hazelcast import hazelcast_test

    res = run_fake(hazelcast_test, workload=wl, time_limit=2.0)
    r = res["results"]
    assert r["valid?"] is True, r
    assert r["workload"]["valid?"] is True


def test_murmur3_known_vectors_and_partition_routing(member):
    """Murmur3_x86_32 against public vectors (seed-0 classics plus the
    hazelcast default seed), and the client routes map ops by key."""
    from jepsen_tpu.suites._hazelcast import hash_to_index, murmur3_x86_32

    # public reference vectors, seed 0
    def u(h):   # unsigned view for vector comparison
        return h & 0xFFFFFFFF

    assert u(murmur3_x86_32(b"", 0)) == 0
    assert u(murmur3_x86_32(b"a", 0)) == 0x3C2569B2
    assert u(murmur3_x86_32(b"abc", 0)) == 0xB3DD93FA
    assert u(murmur3_x86_32(b"Hello, world!", 0x9747B28C)) == 0x24884CBA
    assert hash_to_index(-(1 << 31), 271) == 0
    assert hash_to_index(-5, 271) == 5
    # client learned the partition count from the mock's auth response
    c = _client(member)
    assert c.partition_count == 271
    from jepsen_tpu.suites._hazelcast import data_string
    p = c._partition_of(data_string("hi"))
    assert 0 <= p < 271
    c.close()
