"""Chaos-test worker: a fake-mode run with a durable membership nemesis,
for the parent test to SIGKILL mid-`shrink` (tests/test_membership.py).

The FakeClusterState settles reconfigurations only after ``settle_s``
(600 s here — effectively never), so the shrink fires, lands in the
durable fault registry with its pre-op member set, shrinks the
members file, and then stays UNRESOLVED until the parent kills us:
exactly the stranded-reconfiguration crash the heal replay exists for.
Client ops grind meanwhile so the write-ahead journal accumulates lines
the parent can poll for. Usage:

    python membership_worker.py <store-dir> <members-json-path>
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu import core  # noqa: E402
from jepsen_tpu import generator as gen
from jepsen_tpu.fakes import AtomClient, AtomDB, FakeClusterState, noop_test
from jepsen_tpu.nemesis import combined

NODES = ["n1", "n2", "n3", "n4", "n5"]


class SlowAtomClient(AtomClient):
    """AtomClient with a per-op delay, so the run is killable mid-case
    instead of finishing before the parent can aim."""

    def invoke(self, test, op):
        time.sleep(0.01)
        return super().invoke(test, op)


def main() -> int:
    store_dir, members_path = sys.argv[1], sys.argv[2]
    db = AtomDB()
    state = FakeClusterState(members_path, nodes=NODES, settle_s=600.0)
    pkg = combined.nemesis_package({
        "db": None, "faults": {"membership"},
        "membership_state": state, "interval": 0.2,
        "membership_poll_interval": 0.05})
    ops = [{"type": "invoke", "f": "write", "value": 1},
           {"type": "invoke", "f": "read", "value": None},
           {"type": "invoke", "f": "cas", "value": [1, 2]},
           {"type": "invoke", "f": "write", "value": 3}]
    g = gen.any_gen(
        gen.clients(gen.limit(50_000, gen.cycle(gen.Seq(ops)))),
        gen.nemesis_gen(pkg["generator"]),
    )
    t = noop_test(db=db, client=SlowAtomClient(db),
                  nemesis=pkg["nemesis"],
                  generator=g, store_dir=store_dir,
                  nodes=list(NODES),
                  time_limit=600.0,
                  # fsync every append: the WAL the parent inspects
                  # after SIGKILL must be fully durable
                  wal_fsync_interval=0,
                  metrics_interval=0)
    core.run(t)
    return 0


if __name__ == "__main__":
    sys.exit(main())
