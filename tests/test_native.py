"""Native C++ WGL library: build, correctness on the unit cases, and
differential agreement with the Python search on random histories."""
import random
import shutil

import pytest

from jepsen_tpu.checker.linear_cpu import check_stream
from jepsen_tpu.checker.linear_encode import encode_register_ops
from jepsen_tpu import native

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ toolchain")


def op(typ, process, f, value=None):
    return {"type": typ, "process": process, "f": f, "value": value}


def test_native_builds():
    assert native.available()


CASES = [
    ([op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
      op("invoke", 1, "read"), op("ok", 1, "read", 1)], True),
    ([op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
      op("invoke", 1, "read"), op("ok", 1, "read", 99)], False),
    ([op("invoke", 0, "write", 1), op("invoke", 1, "read"),
      op("ok", 1, "read", 1), op("ok", 0, "write", 1)], True),
    ([op("invoke", 0, "write", 7), op("info", 0, "write", 7),
      op("invoke", 1, "read"), op("ok", 1, "read", 7)], True),
    ([op("invoke", 0, "write", 7), op("fail", 0, "write", 7),
      op("invoke", 1, "read"), op("ok", 1, "read", 7)], False),
    ([op("invoke", 1, "read"), op("ok", 1, "read", 7),
      op("invoke", 0, "write", 7), op("ok", 0, "write", 7)], False),
    ([op("invoke", 0, "cas", [None, 3]), op("ok", 0, "cas", [None, 3]),
      op("invoke", 1, "read"), op("ok", 1, "read", 3)], True),
]


@pytest.mark.parametrize("history,expected", CASES)
def test_native_unit_cases(history, expected):
    res = native.check_stream_native(encode_register_ops(history))
    assert res is not None
    assert res.valid is expected
    if expected is False:
        assert res.failed_op_index >= 0


def random_history(rng, n_ops=60, n_procs=4, valid=True):
    reg = None
    history = []
    pending = {}
    done = 0
    while done < n_ops or pending:
        free = [p for p in range(n_procs) if p not in pending]
        if done < n_ops and free and (not pending or rng.random() < 0.6):
            p = rng.choice(free)
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                v = None
            elif f == "write":
                v = rng.randrange(5)
            else:
                v = [reg if reg is not None and rng.random() < 0.6
                     else rng.randrange(5), rng.randrange(5)]
            o = {"type": "invoke", "process": p, "f": f, "value": v}
            history.append(o)
            pending[p] = o
            done += 1
        else:
            p = rng.choice(list(pending))
            inv = pending.pop(p)
            f, v = inv["f"], inv["value"]
            if f == "read":
                out = reg
                if not valid and rng.random() < 0.15:
                    out = 99
                history.append(op("ok", p, f, out))
            elif f == "write":
                reg = v
                history.append(op("ok", p, f, v))
            else:
                old, new = v
                if reg == old:
                    reg = new
                    history.append(op("ok", p, f, v))
                else:
                    history.append(op("fail", p, f, v))
    return history


def test_native_matches_python_on_random_histories():
    rng = random.Random(5)
    for trial in range(40):
        h = random_history(rng, n_ops=50, valid=(trial % 2 == 0))
        stream = encode_register_ops(h)
        py = check_stream(stream)
        nat = native.check_stream_native(stream)
        assert nat is not None
        assert nat.valid == py.valid, f"trial {trial}"
        if py.valid is False:
            assert nat.failed_event == py.failed_event
