"""Static-analysis tier: preflight diagnostics + the invariant linter.

Every preflight diagnostic and every lint rule gets a deliberately
broken fixture (true positive) AND its corrected twin (must stay
silent) — the "both directions" contract from doc/static-analysis.md.
The self-lint gate at the bottom runs the linter over ``jepsen_tpu/``
itself and fails on any non-baselined finding, which is what turns a
future concurrency/JAX invariant regression into a red build instead of
a review catch.
"""
from __future__ import annotations

import textwrap

import pytest

from jepsen_tpu import core, fakes
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.analysis import lint as lint_mod
from jepsen_tpu.analysis import preflight as pf
from jepsen_tpu.analysis.preflight import PreflightFailed

pytestmark = pytest.mark.lint


def _pf(test):
    return pf.preflight(core.prepare_test(test))


def _codes(diags):
    return [d.code for d in diags]


def _atom_test(**over):
    db = fakes.AtomDB()
    base = dict(db=db, client=fakes.AtomClient(db), ssh={"dummy": True})
    base.update(over)
    return fakes.noop_test(**base)


# ---------------------------------------------------------------------------
# Preflight: one broken fixture per diagnostic, plus the corrected twin
# ---------------------------------------------------------------------------

class TestPreflightDiagnostics:
    def test_gen001_unsupported_f(self):
        t = _atom_test(generator=gen.limit(5, {"f": "frobnicate"}))
        diags = _pf(t)
        assert "GEN001" in _codes(diags)
        assert any(d.severity == "error" for d in diags)

    def test_gen001_silent_on_supported_f(self):
        t = _atom_test(generator=gen.limit(5, {"f": "read"}))
        assert "GEN001" not in _codes(_pf(t))

    def test_gen002_empty_generator(self):
        t = _atom_test(generator=gen.limit(0, {"f": "read"}))
        assert "GEN002" in _codes(_pf(t))

    def test_gen003_truncated_enumeration(self):
        t = _atom_test(generator=gen.repeat({"f": "read"}),
                       preflight_ops=16)
        diags = _pf(t)
        assert "GEN003" in _codes(diags)
        # truncation is informational, never fatal
        assert all(d.severity != "error" for d in diags
                   if d.code == "GEN003")

    def test_gen005_stateful_generator_skipped(self):
        from jepsen_tpu.workloads import set_workload
        w = set_workload.workload()
        kv = fakes.KVStore()
        t = fakes.noop_test(db=kv, client=fakes.KVClient(kv),
                            generator=w["generator"])
        diags = _pf(t)
        assert _codes(diags) == ["GEN005"]

    def test_gen006_malformed_op(self):
        t = _atom_test(generator=gen.limit(2, {"f": "read",
                                               "type": "bogus"}))
        assert "GEN006" in _codes(_pf(t))

    def test_cli001_client_ops_without_client(self):
        t = fakes.noop_test(client=None,
                            generator=gen.limit(3, {"f": "read"}))
        assert "CLI001" in _codes(_pf(t))

    def test_nem001_nemesis_ops_without_nemesis(self):
        t = _atom_test(generator=gen.nemesis_gen(
            gen.limit(2, {"f": "start-partition"})))
        diags = _pf(t)
        assert "NEM001" in _codes(diags)
        assert all(d.severity != "error" for d in diags)  # warning only

    def test_nem002_unhealable_kind(self):
        t = _atom_test(
            nemesis=nem.TruncateFile("/tmp/x"),
            generator=gen.nemesis_gen(gen.limit(2, {"f": "truncate-file"})))
        diags = _pf(t)
        assert [d.code for d in diags if d.severity == "error"] \
            == ["NEM002"]

    def test_nem002_downgraded_by_allow_list(self):
        t = _atom_test(
            nemesis=nem.TruncateFile("/tmp/x"),
            generator=gen.nemesis_gen(gen.limit(2, {"f": "truncate-file"})),
            preflight_allow=["NEM002"])
        diags = _pf(t)
        assert all(d.severity != "error" for d in diags)
        assert "NEM002" in _codes(diags)

    def test_nem003_outside_nemesis_surface(self):
        t = _atom_test(
            nemesis=nem.partition_halves(),
            generator=gen.nemesis_gen(gen.limit(2, {"f": "scramble-clock"})))
        assert "NEM003" in _codes(_pf(t))

    def test_nem003_silent_on_matching_surface(self):
        t = _atom_test(
            nemesis=nem.partition_halves(),
            generator=gen.nemesis_gen(
                gen.limit(2, [{"f": "start-partition"},
                              {"f": "stop-partition"}])))
        diags = _pf(t)
        assert "NEM003" not in _codes(diags)
        assert "NEM002" not in _codes(diags)  # net faults heal fine

    def test_knb001_garbage_knob(self):
        t = _atom_test(op_timeout_s="banana")
        diags = _pf(t)
        assert "KNB001" in _codes(diags)

    def test_knb001_silent_on_numeric(self):
        t = _atom_test(op_timeout_s=30.0)
        assert "KNB001" not in _codes(_pf(t))

    def test_knb002_negative_timeout(self):
        t = _atom_test(drain_timeout_s=-5)
        assert "KNB002" in _codes(_pf(t))

    def test_knb003_bad_concurrency(self):
        t = fakes.noop_test(concurrency="wat")
        # prepare_test would choke on this, so check the raw map
        assert "KNB003" in _codes(pf.preflight(t))

    def test_knb004_nodes_without_workers(self):
        t = _atom_test(concurrency=2)  # 5 nodes
        diags = _pf(t)
        assert "KNB004" in _codes(diags)
        assert all(d.severity == "warning" for d in diags
                   if d.code == "KNB004")

    def test_knb007_matrix_variant_enum(self):
        t = _atom_test(matrix_variant="bf16")
        diags = _pf(t)
        assert "KNB007" in _codes(diags)
        assert "KNB007" not in _codes(_pf(_atom_test(
            matrix_variant="packed")))
        assert "KNB007" not in _codes(_pf(_atom_test(
            matrix_variant="auto")))

    def test_knb_combine_fused_bool(self):
        assert "KNB001" in _codes(_pf(_atom_test(combine_fused="maybe")))
        diags = _pf(_atom_test(combine_fused="yes"))
        assert "KNB001" not in _codes(diags)   # stringly bool: warn only
        assert "KNB006" in _codes(diags)
        assert "KNB001" not in _codes(_pf(_atom_test(combine_fused=True)))

    def test_knb007_env_routing_knobs(self, monkeypatch):
        monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "sometimes")
        assert "KNB007" in _codes(_pf(_atom_test()))
        monkeypatch.setenv("JEPSEN_TPU_PALLAS_PROBE", "skip")
        monkeypatch.setenv("JEPSEN_TPU_MATRIX_VARIANT", "int8")
        monkeypatch.setenv("JEPSEN_TPU_FUSE_COMBINE", "off")
        assert "KNB007" not in _codes(_pf(_atom_test()))
        monkeypatch.setenv("JEPSEN_TPU_FUSE_COMBINE", "fast")
        assert "KNB007" in _codes(_pf(_atom_test()))

    def test_knb005_deadline_exceeds_time_limit(self):
        t = _atom_test(op_timeout_s=600, time_limit=30)
        assert "KNB005" in _codes(_pf(t))

    def test_knb005_silent_when_defaults(self):
        t = _atom_test(time_limit=30)  # op timeout not explicitly set
        assert "KNB005" not in _codes(_pf(t))

    def test_chk001_model_mismatch(self):
        from jepsen_tpu.checker.linearizable import LinearizableChecker
        t = _atom_test(
            client=fakes.KVClient(fakes.KVStore()),
            checker=LinearizableChecker(),
            generator=gen.limit(4, {"f": "enqueue", "value": 1}))
        assert "CHK001" in _codes(_pf(t))

    def test_chk001_silent_on_matching_model(self):
        from jepsen_tpu.checker.linearizable import LinearizableChecker
        t = _atom_test(checker=LinearizableChecker(),
                       generator=gen.limit(4, {"f": "read"}))
        assert "CHK001" not in _codes(_pf(t))

    def test_clean_test_has_no_diagnostics(self):
        t = _atom_test(generator=gen.limit(5, {"f": "read"}))
        assert _pf(t) == []


class TestPreflightGate:
    """The core.run integration: reject before node contact, escape
    hatch restores old behavior."""

    def test_rejects_before_any_node_setup(self, tmp_path):
        db = fakes.AtomDB()
        t = fakes.noop_test(
            db=db, client=fakes.AtomClient(db),
            generator=gen.limit(5, {"f": "frobnicate"}),
            store_dir=str(tmp_path), name="pf-reject")
        with pytest.raises(PreflightFailed) as ei:
            core.run(t)
        assert [d.code for d in ei.value.errors] == ["GEN001"]
        # nothing lifecycle-shaped happened: no db setup, no client open
        assert db.log == []

    def test_no_preflight_escape_hatch(self, tmp_path):
        db = fakes.AtomDB()
        t = fakes.noop_test(
            db=db, client=fakes.AtomClient(db),
            generator=gen.limit(3, {"f": "frobnicate"}),
            store_dir=str(tmp_path), name="pf-skip", preflight=False)
        res = core.run(t)
        # the old behavior: the run happens, unknown fs fail per-op
        assert {op.get("f") for op in res["history"]} == {"frobnicate"}

    def test_clean_run_passes_gate(self, tmp_path):
        db = fakes.AtomDB()
        t = fakes.noop_test(
            db=db, client=fakes.AtomClient(db),
            generator=gen.limit(3, {"f": "read"}),
            store_dir=str(tmp_path), name="pf-clean")
        res = core.run(t)
        assert (res.get("results") or {}).get("valid?") is True

    def test_failure_counter_exported(self, tmp_path):
        from jepsen_tpu import telemetry
        db = fakes.AtomDB()
        t = fakes.noop_test(
            db=db, client=fakes.AtomClient(db),
            generator=gen.limit(5, {"f": "frobnicate"}),
            store_dir=str(tmp_path), name="pf-counter")
        with pytest.raises(PreflightFailed):
            core.run(t)
        # the registry was torn down with the run; check the export
        prom = (tmp_path / "pf-counter").glob("*/metrics.prom")
        text = "".join(p.read_text() for p in prom)
        assert 'preflight_failures_total{code="GEN001"} 1' in text

    def test_skip_counter(self):
        from jepsen_tpu import telemetry
        reg = telemetry.Registry()
        with telemetry.use(reg):
            core._preflight_gate({"preflight": False})
        assert reg.counter("preflight_skipped_total").value() == 1


class TestSimulateCaps:
    def test_seeded_enumeration_is_deterministic(self):
        from jepsen_tpu.generator import simulate as sim
        g = gen.mix([{"f": "a"}, {"f": "b"}, {"f": "c"}])
        t = {"concurrency": 3}
        runs = [sim.quick(t, gen.limit(30, gen.cycle(g)), seed=7)
                for _ in range(2)]
        assert runs[0] == runs[1]
        other = sim.quick(t, gen.limit(30, gen.cycle(g)), seed=8)
        assert [o["f"] for o in other] != [] \
            and isinstance(other, list)

    def test_op_cap_terminates_infinite_generator(self):
        from jepsen_tpu.generator import simulate as sim
        hist = sim.quick({"concurrency": 2},
                         gen.repeat({"f": "read"}), limit=50)
        assert 0 < len(hist) <= 100  # invokes + completions, bounded

    def test_wall_cap_terminates(self):
        from jepsen_tpu.generator import simulate as sim
        import time as _t

        def slow(test, ctx):
            _t.sleep(0.01)
            return {"f": "read"}

        t0 = _t.monotonic()
        sim.quick({"concurrency": 2}, gen.Fn(slow), max_wall_s=0.2)
        assert _t.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# Lint rules: one broken fixture + corrected twin per rule
# ---------------------------------------------------------------------------

def _lint_source(tmp_path, source, rules=None, name="fx.py"):
    d = tmp_path / "fixture_pkg"
    d.mkdir(exist_ok=True)
    (d / name).write_text(textwrap.dedent(source), encoding="utf-8")
    rep = lint_mod.lint_paths([str(d)], baseline=False, rules=rules)
    return rep.findings


class TestLintRules:
    def test_lock_guard_fires_and_corrected_silent(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def add(self, x):
                    with self._lock:
                        self.items.append(x)

                def racy(self, x):
                    self.items.append(x)
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-guard"])
        assert [f.rule for f in finds] == ["lock-guard"]
        good = bad.replace(
            "def racy(self, x):\n                    self.items.append(x)",
            "def racy(self, x):\n                    "
            "with self._lock:\n                        "
            "self.items.append(x)")
        assert _lint_source(tmp_path, good, rules=["lock-guard"]) == []

    def test_lock_guard_exempts_lock_held_helper(self, tmp_path):
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def add(self, x):
                    with self._lock:
                        self.items.append(x)

                def _wipe(self):
                    self.items.clear()

                def reset(self):
                    with self._lock:
                        self._wipe()
        """
        assert _lint_source(tmp_path, src, rules=["lock-guard"]) == []

    def test_thread_owner_reachability(self, tmp_path):
        bad = """
            def mutate():  # owner: scheduler
                pass

            def step():
                mutate()

            def worker_loop():  # owner: worker
                step()
        """
        finds = _lint_source(tmp_path, bad, rules=["thread-owner"])
        assert [f.rule for f in finds] == ["thread-owner"]
        assert "worker_loop" in finds[0].message
        good = bad.replace("# owner: scheduler", "# owner: any")
        assert _lint_source(tmp_path, good, rules=["thread-owner"]) == []

    def test_no_unbounded_block(self, tmp_path):
        bad = """
            def pump(q):  # owner: scheduler
                q.put_nowait(1)
                return q.get()
        """
        finds = _lint_source(tmp_path, bad, rules=["no-unbounded-block"])
        assert [f.rule for f in finds] == ["no-unbounded-block"]
        good = bad.replace("q.get()", "q.get(timeout=1.0)")
        assert _lint_source(tmp_path, good,
                            rules=["no-unbounded-block"]) == []

    def test_no_unbounded_block_ignores_dict_get(self, tmp_path):
        src = """
            def lookup(d):  # owner: scheduler
                return d.get("k")
        """
        assert _lint_source(tmp_path, src,
                            rules=["no-unbounded-block"]) == []

    def test_fsync_pairing(self, tmp_path):
        bad = """
            import os

            class Wal:  # durability: fsync
                def __init__(self, f):
                    self._f = f

                def append(self, line):
                    self._f.write(line)
                    self._f.flush()
        """
        finds = _lint_source(tmp_path, bad, rules=["fsync-pairing"])
        assert [f.rule for f in finds] == ["fsync-pairing"]
        good = bad.replace(
            "self._f.flush()",
            "self._f.flush()\n                    "
            "os.fsync(self._f.fileno())")
        assert _lint_source(tmp_path, good, rules=["fsync-pairing"]) == []

    def test_fsync_without_flush(self, tmp_path):
        bad = """
            import os

            def sync_only(f):
                os.fsync(f.fileno())
        """
        finds = _lint_source(tmp_path, bad, rules=["fsync-pairing"])
        assert [f.rule for f in finds] == ["fsync-pairing"]

    def test_no_host_effects_in_jit(self, tmp_path):
        bad = """
            import time
            import jax

            @jax.jit
            def traced(x):
                return x + time.time()
        """
        finds = _lint_source(tmp_path, bad,
                             rules=["no-host-effects-in-jit"])
        assert [f.rule for f in finds] == ["no-host-effects-in-jit"]
        good = """
            import jax

            @jax.jit
            def traced(x, now):
                return x + now
        """
        assert _lint_source(tmp_path, good,
                            rules=["no-host-effects-in-jit"]) == []

    def test_donation_reuse(self, tmp_path):
        bad = """
            import jax

            def _step(x):
                return x * 2

            fast = jax.jit(_step, donate_argnums=(0,))

            def dispatch(buf):
                y = fast(buf)
                return buf + y
        """
        finds = _lint_source(tmp_path, bad, rules=["donation-reuse"])
        assert [f.rule for f in finds] == ["donation-reuse"]
        good = bad.replace("return buf + y", "return y")
        assert _lint_source(tmp_path, good, rules=["donation-reuse"]) == []

    def test_donation_reuse_allows_rebind(self, tmp_path):
        src = """
            import jax

            def _step(x):
                return x * 2

            fast = jax.jit(_step, donate_argnums=(0,))

            def dispatch(buf):
                buf = fast(buf)
                return buf
        """
        assert _lint_source(tmp_path, src, rules=["donation-reuse"]) == []

    def test_threshold_dtype_fires_in_kernel_scope(self, tmp_path):
        bad = """
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                def bool_mm(x, y):
                    return (jnp.dot(x, y,
                                    preferred_element_type=jnp.float32)
                            > 0).astype(jnp.float32)
                o_ref[...] = bool_mm(x_ref[...], x_ref[...])
        """
        finds = _lint_source(tmp_path, bad, rules=["threshold-dtype"])
        assert [f.rule for f in finds] == ["threshold-dtype"]
        # the int8 form (the rework's replacement) is the fix
        good = bad.replace("jnp.float32)\n                            > 0",
                           "jnp.int32)\n                            > 0")
        assert _lint_source(tmp_path, good,
                            rules=["threshold-dtype"]) == []

    def test_threshold_dtype_waiver_and_jit_scope(self, tmp_path):
        waived = """
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                def bool_mm(x, y):
                    return (
                        jnp.dot(x, y,  # lint: ignore[threshold-dtype]
                                preferred_element_type=jnp.float32) > 0
                    ).astype(jnp.float32)
                o_ref[...] = bool_mm(x_ref[...], x_ref[...])
        """
        assert _lint_source(tmp_path, waived,
                            rules=["threshold-dtype"]) == []
        # jitted function in a non-pallas module is kernel scope too
        jit_bad = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def screen(a, b):
                return (jnp.dot(a, b,
                                preferred_element_type=jnp.float32) > 0)
        """
        finds = _lint_source(tmp_path, jit_bad, rules=["threshold-dtype"])
        assert [f.rule for f in finds] == ["threshold-dtype"]
        # an UN-jitted host function without pallas: not kernel scope
        host = jit_bad.replace("@jax.jit\n            ", "")
        assert _lint_source(tmp_path, host,
                            rules=["threshold-dtype"]) == []
        # a dot without the threshold (magnitude consumer): not flagged
        mag = jit_bad.replace(" > 0", "")
        assert _lint_source(tmp_path, mag,
                            rules=["threshold-dtype"]) == []

    def test_recompile_hazard_jit_in_loop(self, tmp_path):
        bad = """
            import jax

            def hot(xs):
                out = []
                for x in xs:
                    out.append(jax.jit(lambda v: v + 1)(x))
                return out
        """
        finds = _lint_source(tmp_path, bad, rules=["recompile-hazard"])
        assert [f.rule for f in finds] == ["recompile-hazard"]
        good = """
            import jax

            def hot(xs):
                f = jax.jit(lambda v: v + 1)
                return [f(x) for x in xs]
        """
        assert _lint_source(tmp_path, good,
                            rules=["recompile-hazard"]) == []

    def test_recompile_hazard_static_loop_var(self, tmp_path):
        bad = """
            import jax

            def _kernel(x, n):
                return x * n

            k = jax.jit(_kernel, static_argnums=(1,))

            def sweep(x):
                for n in range(100):
                    x = k(x, n)
                return x
        """
        finds = _lint_source(tmp_path, bad, rules=["recompile-hazard"])
        assert [f.rule for f in finds] == ["recompile-hazard"]

    def test_inline_waiver(self, tmp_path):
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n += 1  # lint: ignore[lock-guard]
        """
        assert _lint_source(tmp_path, src, rules=["lock-guard"]) == []

    def test_baseline_suppresses_and_reports_stale(self, tmp_path):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "fx.py").write_text(textwrap.dedent("""
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

                def racy(self):
                    self.n += 1
        """), encoding="utf-8")
        rep = lint_mod.lint_paths([str(d)], baseline=False)
        assert len(rep.findings) == 1
        bl = tmp_path / "baseline.txt"
        lint_mod.write_baseline(bl, rep.findings)
        rep2 = lint_mod.lint_paths([str(d)], baseline=str(bl))
        assert rep2.findings == [] and len(rep2.baselined) == 1
        bl.write_text(bl.read_text() + "pkg/gone.py::X.y::lock-guard\n",
                      encoding="utf-8")
        rep3 = lint_mod.lint_paths([str(d)], baseline=str(bl))
        assert rep3.stale_waivers == ["pkg/gone.py::X.y::lock-guard"]

    def test_findings_metrics_counter(self, tmp_path):
        from jepsen_tpu import telemetry
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "fx.py").write_text(textwrap.dedent("""
            def sched(q):  # owner: scheduler
                q.put_nowait(1)
                q.get()
        """), encoding="utf-8")
        reg = telemetry.Registry()
        with telemetry.use(reg):
            lint_mod.lint_paths([str(d)], baseline=False)
        assert reg.counter("lint_findings_total", labels=("rule",)).value(
            rule="no-unbounded-block") == 1


# ---------------------------------------------------------------------------
# The gate: jepsen_tpu/ itself lints clean (modulo the checked-in baseline)
# ---------------------------------------------------------------------------

class TestSelfLint:
    def test_package_lints_clean(self):
        import time as _t
        from pathlib import Path
        root = Path(__file__).resolve().parent.parent
        t0 = _t.monotonic()
        rep = lint_mod.lint_paths([str(root / "jepsen_tpu")],
                                  baseline=str(root / "lint-baseline.txt"),
                                  root=str(root))
        elapsed = _t.monotonic() - t0
        assert rep.findings == [], (
            "non-baselined lint findings in jepsen_tpu/ — fix them or "
            "add a documented waiver to lint-baseline.txt:\n"
            + "\n".join(f.render() for f in rep.findings))
        assert rep.stale_waivers == [], (
            "stale lint-baseline.txt entries: " + str(rep.stale_waivers))
        # tier-1 budget: the AST cache must keep this fast
        assert elapsed < 30.0, f"self-lint took {elapsed:.1f}s"

    def test_second_run_hits_ast_cache(self):
        import time as _t
        from pathlib import Path
        root = Path(__file__).resolve().parent.parent
        lint_mod.lint_paths([str(root / "jepsen_tpu")], baseline=False,
                            root=str(root))
        t0 = _t.monotonic()
        lint_mod.lint_paths([str(root / "jepsen_tpu")], baseline=False,
                            root=str(root))
        assert _t.monotonic() - t0 < 10.0

    def test_cli_lint_subcommand(self, capsys):
        from jepsen_tpu import cli
        import os
        cwd = os.getcwd()
        from pathlib import Path
        os.chdir(Path(__file__).resolve().parent.parent)
        try:
            rc = cli.noop_main(["lint", "jepsen_tpu"])
        finally:
            os.chdir(cwd)
        out = capsys.readouterr().out
        assert rc == 0 and "0 findings" in out

    def test_cli_preflight_subcommand(self, capsys):
        from jepsen_tpu import cli
        rc = cli.noop_main(["preflight", "--no-ssh"])
        assert rc == 0
        assert "preflight clean" in capsys.readouterr().out

    def test_cli_lint_json(self, capsys):
        import json
        import os
        from pathlib import Path
        from jepsen_tpu import cli
        cwd = os.getcwd()
        os.chdir(Path(__file__).resolve().parent.parent)
        try:
            rc = cli.noop_main(["lint", "jepsen_tpu", "--format=json"])
        finally:
            os.chdir(cwd)
        assert rc == 0
        lines = [json.loads(x) for x in
                 capsys.readouterr().out.strip().splitlines()]
        assert lines[-1]["summary"] is True
        assert lines[-1]["findings"] == 0
