"""Tests for reconnect, fs_cache, codec, report, repl."""
import threading

import pytest

from jepsen_tpu import codec, fs_cache, reconnect, report, repl, store


# ---------------------------------------------------------------------------
# reconnect
# ---------------------------------------------------------------------------

def test_reconnect_reopens_on_error():
    opens = []
    closes = []

    def open_conn():
        opens.append(1)
        return {"id": len(opens), "healthy": len(opens) > 1}

    w = reconnect.wrapper(open_conn, lambda c: closes.append(c["id"]),
                          name="db")
    w.open()
    assert w.conn()["id"] == 1

    def use(conn):
        if not conn["healthy"]:
            raise RuntimeError("conn dead")
        return "ok"

    with pytest.raises(RuntimeError):
        w.with_conn(use)
    # broken conn was closed and a fresh one opened
    assert closes == [1]
    assert w.conn()["id"] == 2
    assert w.with_conn(use) == "ok"
    w.close()
    assert closes == [1, 2]


def test_reconnect_concurrent_reads():
    w = reconnect.wrapper(lambda: {"v": 0}, name="x")
    w.open()
    results = []

    def reader():
        results.append(w.with_conn(lambda c: c["v"]))

    ts = [threading.Thread(target=reader) for _ in range(8)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert results == [0] * 8


# ---------------------------------------------------------------------------
# fs_cache
# ---------------------------------------------------------------------------

def test_fs_cache_roundtrips(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_CACHE_DIR", str(tmp_path / "cache"))
    key = ["builds", "etcd", "v3.5"]
    assert not fs_cache.exists(key)
    fs_cache.save_string(key, "hello")
    assert fs_cache.exists(key)
    assert fs_cache.load_string(key) == "hello"
    fs_cache.save_data(["meta"], {"a": [1, 2]})
    assert fs_cache.load_data(["meta"]) == {"a": [1, 2]}
    src = tmp_path / "artifact.bin"
    src.write_bytes(b"\x00\x01")
    p = fs_cache.save_file(["files", "artifact"], src)
    assert p.read_bytes() == b"\x00\x01"
    with fs_cache.lock(key):
        pass
    fs_cache.clear(key)
    assert not fs_cache.exists(key)
    fs_cache.clear()
    assert fs_cache.load_data(["meta"]) is None


def test_fs_cache_encodes_weird_keys(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_CACHE_DIR", str(tmp_path))
    p = fs_cache.cache_path(["a/b", "c:d e"])
    assert str(tmp_path) in str(p)
    assert "/b" not in str(p.relative_to(tmp_path))


# ---------------------------------------------------------------------------
# codec / report / repl
# ---------------------------------------------------------------------------

def test_codec_roundtrip():
    for v in (None, 0, "x", [1, {"k": [True, None]}], {"a": 1}):
        assert codec.decode(codec.encode(v)) == v
    assert codec.encode(None) == b""
    assert codec.decode(b"") is None


def test_report_and_repl(tmp_path):
    t = {"name": "rpt", "start_time": "20260729T010101",
         "store_dir": str(tmp_path)}
    with report.to(t, "analysis.txt"):
        print("all good")
    assert "all good" in (tmp_path / "rpt" / "20260729T010101" /
                          "analysis.txt").read_text()
    t["results"] = {"valid?": True}
    t["history"] = []
    store.save_1(t)
    store.save_2(t)
    out = repl.latest_test(str(tmp_path))
    assert out is not None
    assert out["results"]["valid?"] is True
