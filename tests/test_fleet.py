"""Fleet observability tier: network WAL ingest, pool scheduler,
fleet status plane (doc/observability.md "Fleet plane").

Covers the ISSUE-16 acceptance surface:

* `WalTailer.poll_bytes` / `seek` resume-token edge cases under
  shipping: torn final line held at the shipped boundary, a replayed
  chunk with a stale token rejected (nothing double-absorbed), a
  mid-file rewrite re-ingested from zero via hash-mismatch + explicit
  reset;
* ingest protocol: token GETs, divergence/gap rejection with reason
  counters, receiver-restart cursor rebuild, digest-checked finals;
* end-to-end over loopback HTTP: a producer-side fake run shipped
  while it is written, the pool daemon settling it with a verdict
  bit-identical to post-hoc analyze on the producer's own history;
* per-run series capping (top-K + `other`) and the unlabeled fleet
  rollup gauges; the discovery scan cache's mtime fast-path;
* preflight knob rows + tolerant coercion + env twins;
* the `/fleet` web dashboard; multi-producer e2e in the slow lane.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from types import SimpleNamespace

import pytest

pytestmark = pytest.mark.fleet


def _register_history(n, seed=7, planted_at=None, n_procs=4):
    from __graft_entry__ import _register_history as gen
    h = gen(n, n_procs=n_procs, seed=seed, n_values=5)
    planted = None
    if planted_at is not None:
        for i, op in enumerate(h):
            if i >= planted_at and op.get("type") == "ok" \
                    and op.get("f") == "read" \
                    and op.get("value") is not None:
                op["value"] = op["value"] + 10_000
                planted = i
                break
        assert planted is not None, "no read to corrupt"
    return h, planted


def _write_wal(run_dir, ops, complete=False):
    from jepsen_tpu.journal import Journal
    run_dir.mkdir(parents=True, exist_ok=True)
    j = Journal(run_dir / "history.wal.jsonl", fsync_interval_s=-1)
    for op in ops:
        j.append(op)
    j.close()
    if complete:
        with open(run_dir / "history.jsonl", "w") as f:
            for op in ops:
                f.write(json.dumps(op) + "\n")


@pytest.fixture()
def ingest(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    reg = telemetry.Registry()
    srv = IngestServer(tmp_path / "fleet", port=0, registry=reg)
    srv.start()
    yield srv
    srv.stop()


# ---------------------------------------------------------------------------
# WalTailer shipping seams: poll_bytes + seek resume tokens
# ---------------------------------------------------------------------------

def test_poll_bytes_holds_torn_final_line(tmp_path):
    """The shipped boundary is always a newline: an in-progress final
    line ships nothing (offset frozen), then ships whole once the
    writer completes it — so a receiver never holds a torn prefix."""
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    with open(p, "w") as f:
        f.write('{"i": 0}\n{"i": 1')  # torn in-progress tail
        f.flush()
        t = WalTailer(p)
        body = t.poll_bytes()
        assert body == b'{"i": 0}\n'
        assert t.poll_bytes() == b""  # torn tail: nothing ships
        off_before = t.offset
        f.write('}\n')
        f.flush()
        assert t.poll_bytes() == b'{"i": 1}\n'
        assert t.offset > off_before
    # the running digest equals the file prefix digest — the resume
    # token a shipper would present
    assert t.prefix_sha() == hashlib.sha256(
        p.read_bytes()).hexdigest()


def test_seek_rejects_rewritten_prefix(tmp_path):
    """The shipping resume seam: seek() adopts a token only when the
    file's first `offset` bytes hash to it; a rewritten WAL fails and
    leaves the tailer at 0 (re-ingest)."""
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    p.write_text('{"i": 0}\n{"i": 1}\n')
    t = WalTailer(p)
    t.poll_bytes()
    offset, sha = t.offset, t.prefix_sha()

    fresh = WalTailer(p)
    assert fresh.seek(offset, prefix_sha=sha)
    assert fresh.offset == offset

    p.write_text('{"i": 9}\n{"i": 1}\n')  # same length, new bytes
    diverged = WalTailer(p)
    assert not diverged.seek(offset, prefix_sha=sha)
    assert diverged.offset == 0  # re-ingest from zero

    # file shorter than the token: also rejected
    p.write_text('{"i"')
    short = WalTailer(p)
    assert not short.seek(offset, prefix_sha=sha)
    assert short.offset == 0


# ---------------------------------------------------------------------------
# ingest protocol: replay, divergence, gap, reset, restart
# ---------------------------------------------------------------------------

def _ship_all(run_dir, port):
    from jepsen_tpu.fleet.ship import Shipper
    sh = Shipper(run_dir, f"http://127.0.0.1:{port}")
    sh.sync()
    while sh.step():
        pass
    return sh


def test_replayed_chunk_with_stale_token_rejected(tmp_path, ingest):
    """A replayed shipment (process restart re-sending an already-
    absorbed chunk) bounces on its stale token and nothing is
    double-absorbed."""
    h, _ = _register_history(40, seed=1)
    rd = tmp_path / "src" / "reg" / "20260806T000001"
    _write_wal(rd, h)
    sh = _ship_all(rd, ingest.port)
    assert sh.chunks_sent >= 1

    wal = (rd / "history.wal.jsonl").read_bytes()
    # replay the whole WAL as one chunk at offset 0 with valid hashes:
    # exactly what a restarted, token-less shipper would try
    current = ingest.append_chunk(
        "reg/20260806T000001", 0, hashlib.sha256().hexdigest(),
        hashlib.sha256(wal).hexdigest(), wal)
    assert current is not None  # rejected, token returned
    assert current["offset"] == len(wal)
    got = ingest.registry.counter(
        "fleet_ingest_rejected_total", labels=("reason",)
        ).value(reason="stale-token")
    assert got == 1
    # nothing double-absorbed: receiver copy still byte-identical
    assert (ingest.store_root / "reg" / "20260806T000001"
            / "history.wal.jsonl").read_bytes() == wal

    # and a shipper recovering via the token re-syncs without resets
    sh2 = _ship_all(rd, ingest.port)
    assert sh2.resets == 0 and sh2.chunks_sent == 0


def test_diverged_and_gap_shipments_rejected(tmp_path, ingest):
    h, _ = _register_history(30, seed=2)
    rd = tmp_path / "src" / "reg" / "20260806T000002"
    _write_wal(rd, h)
    _ship_all(rd, ingest.port)
    key = "reg/20260806T000002"
    token = ingest.token(key)

    # same offset, wrong prefix hash -> diverged
    bad = ingest.append_chunk(key, token["offset"], "0" * 64,
                              hashlib.sha256(b"x").hexdigest(), b"x")
    assert bad is not None
    # offset beyond the receiver's -> gap
    gap = ingest.append_chunk(key, token["offset"] + 100,
                              token["prefix_sha"],
                              hashlib.sha256(b"x").hexdigest(), b"x")
    assert gap is not None and gap["offset"] == token["offset"]
    # corrupt body (chunk digest mismatch) -> bad-chunk, cursor frozen
    corrupt = ingest.append_chunk(key, token["offset"],
                                  token["prefix_sha"], "0" * 64, b"x")
    assert corrupt is not None
    reasons = {
        r: ingest.registry.counter(
            "fleet_ingest_rejected_total", labels=("reason",)
            ).value(reason=r)
        for r in ("diverged", "gap", "bad-chunk")}
    assert reasons == {"diverged": 1, "gap": 1, "bad-chunk": 1}


def test_midfile_rewrite_resets_and_reships(tmp_path, ingest):
    """The bottom rung of the recovery ladder: the producer's WAL was
    rewritten under the shipper, the local seek() fails against the
    receiver's token, and an explicit reset re-ingests from zero —
    ending byte-identical to the NEW file."""
    from jepsen_tpu.fleet.ship import Shipper
    h, _ = _register_history(30, seed=3)
    rd = tmp_path / "src" / "reg" / "20260806T000003"
    _write_wal(rd, h)
    _ship_all(rd, ingest.port)

    # rewrite the WAL wholesale (a new run reusing the dir)
    h2, _ = _register_history(20, seed=9)
    (rd / "history.wal.jsonl").unlink()
    _write_wal(rd, h2)

    sh = Shipper(rd, f"http://127.0.0.1:{ingest.port}")
    sh.sync()  # receiver token no longer hash-matches -> reset rung
    while sh.step():
        pass
    assert sh.resets == 1
    want = (rd / "history.wal.jsonl").read_bytes()
    got = (ingest.store_root / "reg" / "20260806T000003"
           / "history.wal.jsonl").read_bytes()
    assert got == want


def test_receiver_restart_rebuilds_cursor_from_disk(tmp_path, ingest):
    """A receiver restart must not force a re-ship: the cursor is
    rebuilt by hashing the WAL already on disk."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.ingest import IngestServer
    h, _ = _register_history(30, seed=4)
    rd = tmp_path / "src" / "reg" / "20260806T000004"
    _write_wal(rd, h)
    _ship_all(rd, ingest.port)
    wal = (rd / "history.wal.jsonl").read_bytes()

    srv2 = IngestServer(ingest.store_root, port=0,
                        registry=telemetry.Registry())
    srv2.start()
    try:
        sh = _ship_all(rd, srv2.port)
        assert sh.resets == 0 and sh.chunks_sent == 0  # nothing re-sent
        token = srv2.token("reg/20260806T000004")
        assert token["offset"] == len(wal)
        assert token["prefix_sha"] == hashlib.sha256(wal).hexdigest()
    finally:
        srv2.stop()


def test_final_install_is_digest_checked(tmp_path, ingest):
    body = b'{"i": 0}\n'
    assert ingest.finalize_run("reg/20260806T000005", "0" * 64,
                               body) == "bad"
    assert ingest.finalize_run(
        "reg/20260806T000005", hashlib.sha256(body).hexdigest(),
        body) == "ok"
    assert (ingest.store_root / "reg" / "20260806T000005"
            / "history.jsonl").read_bytes() == body


# ---------------------------------------------------------------------------
# end-to-end: ship while writing, pool settles, verdict parity
# ---------------------------------------------------------------------------

def _analyze_locally(history):
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    return LinearizableChecker(accelerator="cpu").check({}, history, {})


def test_fleet_end_to_end_verdict_parity(tmp_path):
    """A producer-side run shipped over loopback HTTP while it is
    written; the pool daemon settles it and the fleet verdict (valid
    AND invalid cases) matches the local post-hoc checker on the same
    history, bit for bit."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import FleetDaemon
    from jepsen_tpu.fleet.ship import Shipper
    from jepsen_tpu.live.daemon import load_live_status

    cases = {"ok": _register_history(300, seed=5),
             "bad": _register_history(300, seed=6, planted_at=200)}
    src = tmp_path / "src"
    fd = FleetDaemon(tmp_path / "fleet", port=0, poll_s=0.02,
                     accelerator="cpu",
                     registry=telemetry.Registry())
    fd.start()
    try:
        shippers = []
        for name, (h, _) in cases.items():
            rd = src / name / "20260806T000010"
            rd.mkdir(parents=True)

            def produce(rd=rd, h=h):
                from jepsen_tpu.journal import Journal
                j = Journal(rd / "history.wal.jsonl",
                            fsync_interval_s=-1)
                for op in h:
                    j.append(op)
                    time.sleep(0.0005)
                j.close()
                with open(rd / "history.jsonl", "w") as f:
                    for op in h:
                        f.write(json.dumps(op) + "\n")

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            sh = Shipper(rd, f"http://127.0.0.1:{fd.port}",
                         poll_s=0.01)
            st = threading.Thread(
                target=lambda sh=sh: sh.run(timeout_s=60),
                daemon=True)
            st.start()
            shippers.append((t, st, sh))
        for t, st, sh in shippers:
            t.join(60)
            st.join(60)
            assert sh.finalized, "shipper never finalized"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and fd.daemon.trackers:
            time.sleep(0.05)
        assert not fd.daemon.trackers, "pool never settled the runs"
    finally:
        fd.stop()

    for name, (h, planted) in cases.items():
        fleet_dir = tmp_path / "fleet" / name / "20260806T000010"
        # receiver copies byte-identical to the producer's artifacts
        assert (fleet_dir / "history.wal.jsonl").read_bytes() == \
            (src / name / "20260806T000010"
             / "history.wal.jsonl").read_bytes()
        status = load_live_status(fleet_dir)
        assert status["state"] == "final"
        local = _analyze_locally(h)
        assert status["valid_so_far"] is local["valid?"]
        if planted is not None:
            assert status["valid_so_far"] is False
            assert status["first_anomaly_op"] == planted

    # the status plane saw both runs through to final
    from jepsen_tpu.fleet.status import load_fleet_status
    payload = load_fleet_status(tmp_path / "fleet")
    assert payload["runs"]["final"] == 2
    assert payload["runs"]["invalid"] == 1
    assert payload["ingest"]["chunks_total"] >= 2
    assert (tmp_path / "fleet" / "fleet-metrics.prom").exists()


# ---------------------------------------------------------------------------
# series capping + fleet rollups
# ---------------------------------------------------------------------------

def test_run_series_capped_topk_plus_other(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.live.daemon import LiveDaemon

    reg = telemetry.Registry()
    d = LiveDaemon(store_root=tmp_path, registry=reg)
    d.run_series_topk = 3
    rows = []
    for i in range(8):
        tr = SimpleNamespace(label=f"reg/run{i}", broken=None)
        st = {"state": "tailing", "valid_so_far": (None if i == 0
                                                   else i != 5),
              "lag_ops": 100 * i, "lag_s": 0.1 * i,
              "first_anomaly_op": 42 if i == 5 else None}
        rows.append((tr, st))
    rows[1][0].broken = "boom"
    d._publish_run_series(rows)
    snap = reg.snapshot()

    lag = [s for s in snap if s["name"] == "live_checker_lag_ops"]
    runs = sorted(s["labels"]["run"] for s in lag)
    # top-3 by lag exactly, everything else folded into "other"
    assert runs == ["other", "reg/run5", "reg/run6", "reg/run7"]
    other_lag = next(s["value"] for s in lag
                     if s["labels"]["run"] == "other")
    assert other_lag == 400  # the worst folded run's lag
    # worst verdict in "other": run5 is in the exact set, so the fold
    # holds run0's unknown (None) and the valid rest -> -1
    verd = {s["labels"]["run"]: s["value"] for s in snap
            if s["name"] == "live_verdict"}
    assert verd["other"] == -1.0
    assert verd["reg/run5"] == 0.0
    # folded breaker count rides the "other" series as a count
    brk = {s["labels"]["run"]: s["value"] for s in snap
           if s["name"] == "live_run_breaker_open"}
    assert brk == {"other": 1.0}

    # unlabeled rollups stay exact regardless of the cap
    rollups = {s["name"]: s["value"] for s in snap
               if s["name"].startswith("fleet_")}
    assert rollups == {"fleet_runs_active": 8.0,
                       "fleet_worst_lag_ops": 700.0,
                       "fleet_invalid_runs": 1.0}

    # a smaller next poll clears stale series instead of leaving them
    d._publish_run_series(rows[:1])
    lag2 = [s for s in reg.snapshot()
            if s["name"] == "live_checker_lag_ops"]
    assert [s["labels"]["run"] for s in lag2] == ["reg/run0"]


def test_run_label_interning_bounded(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.live.daemon import LiveDaemon
    d = LiveDaemon(store_root=tmp_path,
                   registry=telemetry.Registry())
    d.run_series_topk = 2
    assert d._run_label("a") == "a"
    assert d._run_label("b") == "b"
    assert d._run_label("c") == "other"  # beyond the cap
    assert d._run_label("a") == "a"      # sticky for the lifetime
    assert len(d._run_labels) == 2       # "other" is never stored


# ---------------------------------------------------------------------------
# discovery scan cache
# ---------------------------------------------------------------------------

def test_discovery_scan_cache_and_invalidation(tmp_path):
    from jepsen_tpu import telemetry
    from jepsen_tpu.live.daemon import LiveDaemon

    h, _ = _register_history(20, seed=7)
    _write_wal(tmp_path / "reg" / "20260806T000020", h, complete=True)
    reg = telemetry.Registry()
    d = LiveDaemon(store_root=tmp_path, registry=reg, poll_s=0.01,
                   accelerator="cpu")
    d.poll_once()
    d.poll_once()
    d.poll_once()

    def hits():
        return sum(s["value"] for s in reg.snapshot()
                   if s["name"] == "live_scan_cache_hits_total")

    warm = hits()
    assert warm >= 1  # unchanged tree answered from the cache
    # settled candidates are skipped without re-parsing their status
    assert str(tmp_path / "reg" / "20260806T000020") in d._settled

    # a new run inside an existing name dir bumps its mtime: the cache
    # must miss once and the run must be discovered
    _write_wal(tmp_path / "reg" / "20260806T000021", h)
    d.poll_once()
    assert any(k.endswith("20260806T000021") for k in d.trackers)
    # a brand-new name dir is discovered the same way
    _write_wal(tmp_path / "cas" / "20260806T000022", h)
    d.poll_once()
    assert any("cas" in k for k in d.trackers)
    d.stop()


# ---------------------------------------------------------------------------
# knobs: preflight rows, tolerant coercion, env twins
# ---------------------------------------------------------------------------

def test_preflight_validates_fleet_knobs():
    from jepsen_tpu.analysis.preflight import preflight

    diags = preflight({"nodes": ["n1"], "fleet_port": "garbage"})
    assert any(d.code == "KNB001" and d.path == "fleet_port"
               for d in diags)
    diags = preflight({"nodes": ["n1"], "fleet_max_runs": 0})
    assert any(d.code == "KNB002" and d.path == "fleet_max_runs"
               for d in diags)
    diags = preflight({"nodes": ["n1"], "fleet_ingest_budget_s": -1})
    assert any(d.code == "KNB002" for d in diags)


def test_preflight_validates_fleet_env_twins(monkeypatch):
    from jepsen_tpu.analysis.preflight import preflight
    monkeypatch.setenv("JEPSEN_TPU_FLEET_PORT", "not-a-port")
    diags = preflight({"nodes": ["n1"]})
    assert any("JEPSEN_TPU_FLEET_PORT" in (d.path or "")
               for d in diags)


def test_fleet_knob_tolerant_coercion_and_env_twin(monkeypatch):
    from jepsen_tpu.fleet import fleet_knob
    assert fleet_knob("fleet_max_runs", "12", 64, 1.0) == 12.0
    assert fleet_knob("fleet_max_runs", "oops", 64, 1.0) == 64.0
    assert fleet_knob("fleet_max_runs", -5, 64, 1.0) == 1.0
    monkeypatch.setenv("JEPSEN_TPU_FLEET_MAX_RUNS", "7")
    assert fleet_knob("fleet_max_runs", None, 64, 1.0) == 7.0
    # an explicit value beats the twin
    assert fleet_knob("fleet_max_runs", 3, 64, 1.0) == 3.0


# ---------------------------------------------------------------------------
# /fleet dashboard + status endpoints
# ---------------------------------------------------------------------------

def _get(port, path, headers=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path, headers=headers or {})
    r = conn.getresponse()
    body = r.read()
    out = (r.status, dict(r.getheaders()), body)
    conn.close()
    return out


def test_web_fleet_dashboard(tmp_path):
    from jepsen_tpu.web import make_server

    payload = {
        "version": 1, "updated": time.time(), "polls": 7,
        "runs": {"tracked": 2, "active": 1, "invalid": 1, "final": 1,
                 "breaker_open": 0, "deferred_total": 3},
        "worst_lag_ops": 123, "worst_lag_run": "reg/20260806T000030",
        "mesh": {"width": 4, "failed_devices": [7], "shrinks": 1,
                 "regrows": 0},
        "ingest": {"bytes_total": 1000, "bytes_per_s": 42.0,
                   "chunks_total": 5, "rejected_total": 1, "runs": 2},
        "top_runs": [
            {"name": "reg", "timestamp": "20260806T000030",
             "state": "tailing", "valid_so_far": False,
             "lag_ops": 123, "lag_s": 0.2, "first_anomaly_op": 40,
             "breaker_open": False,
             "links": {"live-status.json":
                       "reg/20260806T000030/live-status.json"}}],
    }
    (tmp_path / "fleet-status.json").write_text(json.dumps(payload))
    server = make_server(store_dir=str(tmp_path))
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        code, _hdr, body = _get(port, "/fleet")
        assert code == 200
        assert b"worst lag" in body and b"123" in body
        assert b"reg/20260806T000030" in body
        assert b"live-status.json" in body  # first-anomaly artifact link
        assert b"http-equiv='refresh'" in body
        # the home page links to the dashboard when the aggregate exists
        code, _hdr, home = _get(port, "/")
        assert code == 200 and b"href='/fleet'" in home
    finally:
        server.shutdown()
        server.server_close()
    # no aggregate -> 404 with a hint, not a crash
    (tmp_path / "fleet-status.json").unlink()
    server = make_server(store_dir=str(tmp_path))
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        code, _hdr, body = _get(port, "/fleet")
        assert code == 404 and b"fleet daemon" in body
    finally:
        server.shutdown()
        server.server_close()


def test_ingest_status_and_metrics_endpoints(tmp_path, ingest):
    h, _ = _register_history(20, seed=8)
    rd = tmp_path / "src" / "reg" / "20260806T000040"
    _write_wal(rd, h)
    _ship_all(rd, ingest.port)
    code, _hdr, body = _get(ingest.port, "/metrics")
    assert code == 200
    assert b"fleet_ingest_bytes_total" in body
    # fleet-status.json served once the status plane writes it
    (ingest.store_root / "fleet-status.json").write_text("{}")
    code, _hdr, body = _get(ingest.port, "/fleet-status.json")
    assert code == 200 and body == b"{}"
    # path traversal is rejected at the segment gate
    code, _hdr, _body = _get(ingest.port, "/wal/../x")
    assert code in (400, 404)


# ---------------------------------------------------------------------------
# multi-producer e2e (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_multi_producer_e2e(tmp_path):
    """Eight producers shipping concurrently into one pool: every run
    settles, every verdict matches local analyze, and the aggregate
    counts them all."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.fleet.scheduler import FleetDaemon
    from jepsen_tpu.fleet.ship import Shipper
    from jepsen_tpu.fleet.status import load_fleet_status
    from jepsen_tpu.live.daemon import load_live_status

    n_runs = 8
    hs = {f"2026080{i:02d}T000000": _register_history(
        150, seed=i, planted_at=100 if i == 3 else None)[0]
        for i in range(n_runs)}
    src = tmp_path / "src"
    fd = FleetDaemon(tmp_path / "fleet", port=0, poll_s=0.02,
                     accelerator="cpu", max_runs=n_runs,
                     registry=telemetry.Registry())
    fd.start()
    try:
        threads = []
        for ts, h in hs.items():
            rd = src / "reg" / ts

            def one(rd=rd, h=h):
                # ship WHILE producing — a run that lands on the
                # receiver already complete is (correctly) post-hoc
                # territory, not the pool's
                from jepsen_tpu.journal import Journal
                rd.mkdir(parents=True)
                j = Journal(rd / "history.wal.jsonl",
                            fsync_interval_s=-1)
                j.append(h[0])
                sh = Shipper(rd, f"http://127.0.0.1:{fd.port}",
                             poll_s=0.01)
                shipped = []
                st = threading.Thread(
                    target=lambda: shipped.append(
                        sh.run(timeout_s=120)), daemon=True)
                st.start()
                for op in h[1:]:
                    j.append(op)
                    time.sleep(0.001)
                j.close()
                with open(rd / "history.jsonl", "w") as f:
                    for op in h:
                        f.write(json.dumps(op) + "\n")
                st.join(120)
                assert shipped == [True]

            t = threading.Thread(target=one, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(120)
            assert not t.is_alive()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and fd.daemon.trackers:
            time.sleep(0.05)
        assert not fd.daemon.trackers
    finally:
        fd.stop()

    invalid = 0
    for ts, h in hs.items():
        status = load_live_status(tmp_path / "fleet" / "reg" / ts)
        assert status["state"] == "final"
        local = _analyze_locally(h)
        assert status["valid_so_far"] is local["valid?"]
        invalid += status["valid_so_far"] is False
    assert invalid == 1
    payload = load_fleet_status(tmp_path / "fleet")
    assert payload["runs"]["final"] == n_runs
    assert payload["runs"]["invalid"] == 1
