"""Live checking tier: WAL tailing, incremental sessions, the daemon.

Covers the ISSUE-6 acceptance surface:

* torn-line hardening of the tolerant jsonl readers (torn-middle,
  torn-final, resume-past-torn-once-completed) under concurrent append;
* FrontierSession chunked absorb == one-shot check_stream, bit for bit;
* the incremental register encoder == encode_register_ops, bit for bit;
* end-to-end: a WAL-writing fake run, the daemon tailing it, the
  verdict flipping to invalid at the exact planted op, lag metrics in
  the Prometheus export, wedge-proof shutdown;
* differential: the live final verdict == post-hoc analyze across a
  register workload and an Elle list-append workload;
* web UI live panel + ETag/304; preflight knob validation.
"""
from __future__ import annotations

import json
import random
import threading
import time

import pytest

pytestmark = pytest.mark.live


def _register_history(n, seed=7, planted_at=None, n_procs=4):
    from __graft_entry__ import _register_history as gen
    h = gen(n, n_procs=n_procs, seed=seed, n_values=5)
    planted = None
    if planted_at is not None:
        for i, op in enumerate(h):
            if i >= planted_at and op.get("type") == "ok" \
                    and op.get("f") == "read" \
                    and op.get("value") is not None:
                op["value"] = op["value"] + 10_000  # value nobody wrote
                planted = i
                break
        assert planted is not None, "no read to corrupt"
    return h, planted


# ---------------------------------------------------------------------------
# torn-line hardening (journal readers)
# ---------------------------------------------------------------------------

def test_tolerant_reader_torn_middle_keeps_tail(tmp_path):
    """A torn line MID-file must not swallow the valid lines after it."""
    from jepsen_tpu.journal import read_jsonl_tolerant
    p = tmp_path / "w.jsonl"
    rows = [json.dumps({"i": i}) for i in range(6)]
    rows[2] = rows[2][:4]  # torn interior line (newline-terminated)
    p.write_text("\n".join(rows) + "\n")
    got, truncated = read_jsonl_tolerant(p)
    assert [r["i"] for r in got] == [0, 1, 3, 4, 5]
    assert truncated is False  # interior tear, not a torn tail


def test_tolerant_reader_torn_final(tmp_path):
    from jepsen_tpu.journal import read_jsonl_tolerant
    p = tmp_path / "w.jsonl"
    p.write_text(json.dumps({"i": 0}) + "\n" + '{"i": 1')  # no newline
    got, truncated = read_jsonl_tolerant(p)
    assert [r["i"] for r in got] == [0]
    assert truncated is True


def test_tailer_resumes_past_in_progress_line_once_completed(tmp_path):
    """An unterminated final line is an in-progress write: the tailer
    waits, then delivers it once the writer finishes the line."""
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"i": 0}) + "\n")
        f.write('{"i": 1')  # torn in-progress
        f.flush()
        t = WalTailer(p)
        assert [r["i"] for r in t.poll()] == [0]
        assert t.poll() == []  # still in progress; offset did not move
        f.write(', "x": 2}\n')  # writer completes the line
        f.flush()
        assert [r["i"] for r in t.poll()] == [1]
        assert t.torn_skipped == 0


def test_tailer_skips_torn_middle_and_counts(tmp_path):
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    p.write_text(json.dumps({"i": 0}) + "\n" + '{"torn\n'
                 + json.dumps({"i": 2}) + "\n")
    t = WalTailer(p)
    assert [r["i"] for r in t.poll()] == [0, 2]
    assert t.torn_skipped == 1


def test_tailer_finalize_drops_unterminated_tail(tmp_path):
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    p.write_text(json.dumps({"i": 0}) + "\n" + '{"i": 1')
    t = WalTailer(p)
    assert [r["i"] for r in t.finalize()] == [0]
    assert t.truncated_tail is True
    assert t.poll() == []  # offset advanced past the dropped tail


def test_tailer_under_concurrent_append(tmp_path):
    """Poll loop racing a writer thread: every op arrives exactly once,
    in order, torn lines notwithstanding."""
    from jepsen_tpu.journal import WalTailer
    p = tmp_path / "w.jsonl"
    n = 500
    stop = threading.Event()

    def writer():
        with open(p, "w") as f:
            for i in range(n):
                doc = json.dumps({"i": i})
                # split some writes mid-line to exercise the torn path
                if i % 7 == 0:
                    f.write(doc[:3])
                    f.flush()
                    time.sleep(0.0005)
                    f.write(doc[3:] + "\n")
                else:
                    f.write(doc + "\n")
                f.flush()
        stop.set()

    w = threading.Thread(target=writer)
    w.start()
    t = WalTailer(p)
    got: list = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        got.extend(t.poll())
        if stop.is_set() and len(got) >= n:
            break
        time.sleep(0.001)
    w.join(10)
    assert [r["i"] for r in got] == list(range(n))
    assert t.torn_skipped == 0


# ---------------------------------------------------------------------------
# FrontierSession + incremental encoder differentials
# ---------------------------------------------------------------------------

def test_frontier_session_chunked_equals_one_shot():
    from jepsen_tpu.checker.linear_cpu import (
        FrontierSession, check_stream,
    )
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    rng = random.Random(5)
    for seed in range(8):
        h, _ = _register_history(120, seed=seed,
                                 planted_at=60 if seed % 2 else None)
        stream = encode_register_ops(h)
        ref = check_stream(stream)
        s = FrontierSession()
        e = 0
        while e < len(stream):
            e2 = min(len(stream), e + rng.randint(1, 9))
            res = s.absorb(stream, start=e, end=e2)
            e = e2
            if res.valid is False:
                break
        res = s.result()
        assert res.valid == ref.valid
        assert res.failed_event == ref.failed_event
        assert res.failed_op_index == ref.failed_op_index
        assert res.configs_max == ref.configs_max
        assert res.final_configs == ref.final_configs


def test_live_register_encoder_bit_identical_to_batch():
    """Chunk-fed incremental encoding == encode_register_ops over the
    full history, including fail pairs, crashed reads, and slot reuse."""
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.live.sessions import LinearLiveSession
    rng = random.Random(11)
    for seed in range(10):
        h, _ = _register_history(150, seed=seed)
        # sprinkle crash/fail outcomes: rewrite some oks
        for op in h:
            if op.get("type") == "ok" and rng.random() < 0.1:
                op["type"] = rng.choice(["fail", "info"])
        batch = encode_register_ops(h)
        s = LinearLiveSession(accelerator="cpu")
        i = 0
        while i < len(h):
            j = min(len(h), i + rng.randint(1, 13))
            for op in h[i:j]:
                s.add(op)
            s.verdict()
            i = j
        s.finalize()
        st = s.encoder.stream
        assert list(batch.kind) == st.kind
        assert list(batch.slot) == st.slot
        assert list(batch.f) == st.f
        assert list(batch.a) == st.a
        assert list(batch.b) == st.b
        assert list(batch.op_index) == st.op_index
        assert batch.n_slots == st.n_slots
        assert batch.intern.table == st.intern.table


def test_linear_live_final_verdict_matches_post_hoc():
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.live.sessions import LinearLiveSession
    for planted in (None, 80):
        h, planted_i = _register_history(200, seed=3, planted_at=planted)
        s = LinearLiveSession(accelerator="cpu")
        for op in h:
            s.add(op)
        live = s.finalize()
        post = LinearizableChecker(accelerator="cpu").check(None, h, {})
        assert live["valid?"] == post["valid?"]
        if planted is not None:
            assert live["valid?"] is False
            assert h[live["failed-op-index"]] == post["failed-op"]
            assert live["failed-op-index"] == planted_i


def _append_history(n_txns, seed, n_keys=4, plant=False):
    """Concurrent list-append history with *executed* reads (real
    payloads, so anomalies are plantable), via test_elle's interleaved
    builder. ``plant`` duplicates an element inside the first non-empty
    committed read — a guaranteed ``duplicate-elements`` +
    ``incompatible-order`` anomaly. Returns (history, planted_op_i)."""
    from tests.test_elle import _interleaved_history
    h = _interleaved_history(random.Random(seed), n_txns=n_txns,
                             n_keys=n_keys)
    planted = None
    if plant:
        for i, op in enumerate(h):
            if op["type"] == "ok":
                for m in op["value"]:
                    if m[0] == "r" and m[2]:
                        m[2].append(m[2][0])
                        planted = i
                        break
                if planted is not None:
                    break
        assert planted is not None, "no non-empty committed read to corrupt"
    return h, planted


def test_elle_session_matches_batch_checker():
    """Incremental Elle == batch list_append.check across a clean and a
    planted-anomaly workload (the >= 2 workloads differential)."""
    from jepsen_tpu.elle import list_append
    from jepsen_tpu.live.sessions import ElleSession
    rng = random.Random(2)
    for seed, plant in ((0, False), (1, True), (2, True)):
        h, _ = _append_history(120, seed=seed, plant=plant)
        batch = list_append.check(h, accelerator="cpu")
        s = ElleSession(accelerator="cpu")
        for op in h:
            s.add(op)
            if rng.random() < 0.02:
                s.verdict()  # interim verdicts must not corrupt state
        live = s.finalize()
        assert live["valid?"] == batch["valid?"]
        assert live.get("anomaly-types") == batch.get("anomaly-types")
        assert live["txn-count"] == batch["txn-count"]
        if plant:
            assert live["valid?"] is False


def test_multikey_session_demuxes_independent_histories():
    from jepsen_tpu import independent
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.live.sessions import (
        MultiKeyLinearSession, session_for_ops,
    )
    h0, planted = _register_history(120, seed=9, planted_at=40)
    h1, _ = _register_history(120, seed=10)
    # disjoint process spaces per key, values lifted to [k, v], the two
    # keys' ops interleaved — the shape independent's generators emit
    lifted = [op for pair in zip(
        ({**op, "value": independent.tuple_value("k0", op.get("value"))}
         for op in h0),
        ({**op, "process": op["process"] + 100,
          "value": independent.tuple_value("k1", op.get("value"))}
         for op in h1)) for op in pair]
    s = session_for_ops(lifted)
    assert isinstance(s, MultiKeyLinearSession)
    for op in lifted:
        s.add(op)
    final = s.finalize()
    assert final["valid?"] is False
    assert final["failures"] == ["k0"]
    # k0's sub-verdict pins the same failed op as a post-hoc check
    post = LinearizableChecker(accelerator="cpu").check(None, h0, {})
    assert post["valid?"] is False
    sub = final["results"]["k0"]
    assert sub["valid?"] is False


def test_session_sniffing():
    from jepsen_tpu.live.sessions import (
        ElleSession, LinearLiveSession, MultiKeyLinearSession,
        UNSUPPORTED, session_for_ops,
    )
    reg = [{"type": "invoke", "process": 0, "f": "read", "value": None}]
    assert isinstance(session_for_ops(reg), LinearLiveSession)
    ind = [{"type": "invoke", "process": 0, "f": "read",
            "value": ["k", None]}]
    assert isinstance(session_for_ops(ind), MultiKeyLinearSession)
    app = [{"type": "invoke", "process": 0, "f": "txn",
            "value": [["append", 1, 2]]}]
    assert isinstance(session_for_ops(app), ElleSession)
    multi = [{"type": "invoke", "process": 0, "f": "txn",
              "value": [["w", 1, 2]]}]
    assert session_for_ops(multi) is UNSUPPORTED
    assert session_for_ops(
        [{"type": "invoke", "process": "nemesis", "f": "kill"}]) is None


# ---------------------------------------------------------------------------
# end-to-end: daemon tailing a WAL-writing run
# ---------------------------------------------------------------------------

def _write_run(run_dir, history, journal_chunks=40, delay_s=0.002,
               complete=True, pause_at=None, pause_until=None):
    """Fake run: appends history to the WAL in chunks from a thread,
    then persists history.jsonl and discards the WAL (core.run order).
    ``pause_at``/``pause_until``: before writing op index ``pause_at``
    the writer blocks on ``pause_until()`` — tests gate the interesting
    suffix (e.g. a planted anomaly) on the daemon having observably
    screened the prefix, instead of racing a fixed delay against
    machine load."""
    from jepsen_tpu.journal import Journal
    run_dir.mkdir(parents=True, exist_ok=True)
    j = Journal(run_dir / "history.wal.jsonl", fsync_interval_s=-1)

    def writer():
        for i, op in enumerate(history):
            if i == pause_at and pause_until is not None:
                pause_until()
            j.append(op)
            if i % journal_chunks == 0:
                time.sleep(delay_s)
        if complete:
            with open(run_dir / "history.jsonl", "w") as f:
                for op in history:
                    f.write(json.dumps(op) + "\n")
            j.close(discard=True)
        else:
            j.close()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    return t


def test_daemon_end_to_end_register(tmp_path):
    """The acceptance demo: daemon tails a WAL-writing run, reports
    valid-so-far, flips to first-anomaly-at-op-N at the exact planted
    op, exports live_* metrics, finalizes bit-compatible with post-hoc
    analyze, and shuts down wedge-proof."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.live.daemon import LiveDaemon, load_live_status

    h, planted = _register_history(600, seed=4, planted_at=400)
    run_dir = tmp_path / "reg" / "20260803T000000.000"
    # the anomalous suffix is gated on the TEST observing an interim
    # valid-so-far verdict (30 s escape hatch), so the interim-verdict
    # assertion can't lose a fixed-delay race against machine load —
    # under a busy suite the daemon's first screen was landing only
    # after the whole 120 ms write finished
    saw_valid_evt = threading.Event()
    writer = _write_run(run_dir, h, pause_at=planted,
                        pause_until=lambda: saw_valid_evt.wait(30))
    daemon = LiveDaemon(store_root=str(tmp_path), poll_s=0.02,
                        accelerator="cpu")
    daemon.start()
    saw_valid = False
    deadline = time.monotonic() + 60
    status = None
    while time.monotonic() < deadline:
        status = load_live_status(run_dir)
        if status and status.get("valid_so_far") is True \
                and status.get("checked_ops", 0) > 0:
            saw_valid = True
            saw_valid_evt.set()
        if status and status.get("state") == "final":
            break
        time.sleep(0.02)
    saw_valid_evt.set()  # never wedge the writer on a failing run
    writer.join(10)
    t0 = time.monotonic()
    daemon.stop()
    assert time.monotonic() - t0 < 30  # wedge-proof join
    assert status is not None and status["state"] == "final"
    assert saw_valid, "never observed a valid-so-far interim verdict"
    assert status["valid_so_far"] is False
    assert status["first_anomaly_op"] == planted
    assert status["workload"] == "register"
    assert status["ops_absorbed"] == len(h)
    # schema essentials
    for key in ("lag_ops", "lag_s", "backend", "checked_ops",
                "updated", "results"):
        assert key in status, key
    # final incremental verdict == post-hoc analyze
    post = LinearizableChecker(accelerator="cpu").check(None, h, {})
    assert status["results"]["valid?"] == post["valid?"] is False
    assert h[status["results"]["failed-op-index"]] == post["failed-op"]
    # lag metrics exported in Prometheus format
    prom = (tmp_path / "live-metrics.prom").read_text()
    for metric in ("live_checker_lag_ops", "live_checker_lag_s",
                   "live_verdict", "live_first_anomaly_op",
                   "live_runs_active", "live_poll_seconds"):
        assert metric in prom, metric
    assert (tmp_path / "live-metrics.json").exists()


def test_daemon_end_to_end_elle(tmp_path):
    """Same demo over an Elle list-append workload, differential against
    post-hoc list_append.check: a planted duplicate-element anomaly must
    flip the live verdict."""
    from jepsen_tpu.elle import list_append
    from jepsen_tpu.live.daemon import LiveDaemon, load_live_status

    h, planted = _append_history(150, seed=6, n_keys=3, plant=True)
    run_dir = tmp_path / "append" / "20260803T000000.000"
    writer = _write_run(run_dir, h)
    daemon = LiveDaemon(store_root=str(tmp_path), poll_s=0.02,
                        accelerator="cpu")
    statuses = daemon.run_until_idle(timeout_s=60)
    writer.join(10)
    daemon.stop()
    status = load_live_status(run_dir)
    assert status["state"] == "final"
    assert status["workload"] == "list-append"
    post = list_append.check(h, accelerator="cpu")
    assert post["valid?"] is False  # the plant is detectable post-hoc
    assert status["results"]["valid?"] == post["valid?"]
    assert status["results"].get("anomaly-types") == \
        post.get("anomaly-types")
    assert status["valid_so_far"] is False
    assert statuses  # run_until_idle surfaced at least one snapshot


def test_daemon_admission_defers_not_starves(tmp_path):
    """Two runs, a tiny admission budget: both still get verdicts, and
    the deferral counter shows the budget was exercised."""
    from jepsen_tpu.live.daemon import LiveDaemon
    from jepsen_tpu.parallel.pipeline import CostModel

    runs = []
    for k in range(2):
        h, _ = _register_history(300, seed=20 + k)
        run_dir = tmp_path / f"r{k}" / "20260803T000000.000"
        # chunk/delay sized so each writer spans MANY daemon polls: a
        # writer that finishes before the first poll finalizes both
        # runs immediately and no poll ever has two pending runs to
        # arbitrate (the flake this pins down)
        runs.append((run_dir, _write_run(run_dir, h, journal_chunks=5,
                                         delay_s=0.005)))
    daemon = LiveDaemon(
        store_root=str(tmp_path), poll_s=0.01, accelerator="cpu",
        check_budget_s=0.001,
        cost_model=CostModel(cpu_events_per_sec_=1000.0))
    daemon.run_until_idle(timeout_s=60)
    for _d, w in runs:
        w.join(10)
    daemon.stop()
    for run_dir, _w in runs:
        from jepsen_tpu.live.daemon import load_live_status
        s = load_live_status(run_dir)
        assert s["state"] == "final"
        assert s["results"]["valid?"] is True
    # with a ~1-op budget at least one poll deferred someone
    snap = {r["name"]: r for r in daemon.registry.snapshot()
            if r.get("name") == "live_admission_deferred_total"}
    assert snap, "admission budget never deferred a run"


def test_finalize_rebuilds_after_torn_wal_line(tmp_path):
    """A torn mid-WAL line misaligns the tailer's view of the history;
    finalize must rebuild from the authoritative history.jsonl instead
    of back-filling by count — else the planted anomaly inside the torn
    op is skipped, the tail op doubles, and a WRONG 'exact' final
    verdict would pass analyze's freshness check and get reused."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.live.daemon import RunTracker

    h, planted = _register_history(240, seed=12, planted_at=60)
    run_dir = tmp_path / "torn" / "20260803T000000.000"
    run_dir.mkdir(parents=True)
    with open(run_dir / "history.wal.jsonl", "w") as f:
        for i, op in enumerate(h):
            line = json.dumps(op)
            # tear the planted op's own line (newline-terminated)
            f.write(line[: len(line) // 2] + "\n" if i == planted
                    else line + "\n")
    with open(run_dir / "history.jsonl", "w") as f:
        for op in h:
            f.write(json.dumps(op) + "\n")
    tr = RunTracker(run_dir, accelerator="cpu")
    tr.tail()
    assert tr.tailer.torn_skipped == 1
    results = tr.finalize()
    post = LinearizableChecker(accelerator="cpu").check(None, h, {})
    assert post["valid?"] is False
    assert results["valid?"] is False
    assert results["failed-op-index"] == planted
    assert tr.ops_absorbed == len(h)  # rebuilt, not count-back-filled


def test_untracked_run_reports_unknown_not_valid(tmp_path):
    """A workload with no live checker must never read as 'valid':
    valid_so_far stays None (live_verdict -1) and --once maps it to
    EXIT_UNKNOWN, not EXIT_OK."""
    from jepsen_tpu.live.daemon import LiveDaemon, load_live_status

    h = [{"type": t, "process": 0, "f": "txn",
          "value": [["w", 1, i]], "time": i}
         for i in range(30) for t in ("invoke", "ok")]
    run_dir = tmp_path / "unsup" / "20260803T000000.000"
    _write_run(run_dir, h, complete=False).join(10)
    daemon = LiveDaemon(store_root=str(tmp_path), poll_s=0.01,
                        accelerator="cpu")
    daemon.poll_once()
    status = load_live_status(run_dir)
    assert status["state"] == "untracked"
    assert status["workload"] is None
    assert status["valid_so_far"] is None
    # run completes: finalizes with no results (there is no checker)
    with open(run_dir / "history.jsonl", "w") as f:
        for op in h:
            f.write(json.dumps(op) + "\n")
    daemon.poll_once()
    daemon.stop()
    status = load_live_status(run_dir)
    assert status["state"] == "final"
    assert status["valid_so_far"] is None
    assert "results" not in status


def test_daemon_breaker_opens_on_poisoned_session(tmp_path, monkeypatch):
    from jepsen_tpu.live import daemon as daemon_mod

    h, _ = _register_history(50, seed=1)
    run_dir = tmp_path / "bad" / "20260803T000000.000"
    w = _write_run(run_dir, h, complete=False)
    w.join(10)
    daemon = daemon_mod.LiveDaemon(store_root=str(tmp_path),
                                   poll_s=0.01, accelerator="cpu")
    daemon.poll_once()
    (tr,) = daemon.trackers.values()

    def boom():
        raise RuntimeError("kaboom")

    monkeypatch.setattr(tr.session, "verdict", boom)
    tr.last_verdict["checked_ops"] = 0  # force pending work
    for _ in range(daemon_mod.LIVE_BREAKER_THRESHOLD + 1):
        tr.check()
    assert tr.broken
    status = tr.status(daemon.lag_budget_ops)
    assert status["state"] == "error"
    daemon.stop()


def test_core_analyze_reuses_fresh_live_verdict(tmp_path):
    from jepsen_tpu import core
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    from jepsen_tpu.live.daemon import LIVE_STATUS_NAME

    h, _ = _register_history(60, seed=2)
    test = {"name": "reuse", "start_time": "TS", "store_dir": str(tmp_path),
            "history": list(h), "checker": LinearizableChecker(
                accelerator="cpu"), "live_reuse": True}
    run_dir = tmp_path / "reuse" / "TS"
    run_dir.mkdir(parents=True)
    status = {"state": "final", "workload": "register",
              "ops_absorbed": len(h),
              "results": {"valid?": True, "algorithm": "jitlin-cpu-live",
                          "configs-max": 7}}
    (run_dir / LIVE_STATUS_NAME).write_text(json.dumps(status))
    out = core.analyze(dict(test))
    assert out["results"]["live-reused"] is True
    assert out["results"]["algorithm"] == "jitlin-cpu-live"
    # stale op count: no reuse
    status["ops_absorbed"] = len(h) - 1
    (run_dir / LIVE_STATUS_NAME).write_text(json.dumps(status))
    out = core.analyze(dict(test))
    assert "live-reused" not in out["results"]
    # explicit opt-out: no reuse
    status["ops_absorbed"] = len(h)
    (run_dir / LIVE_STATUS_NAME).write_text(json.dumps(status))
    out = core.analyze({**test, "live_reuse": False})
    assert "live-reused" not in out["results"]


# ---------------------------------------------------------------------------
# web UI: live panel, home section, ETag
# ---------------------------------------------------------------------------

def _get(port, path, headers=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path, headers=headers or {})
    r = conn.getresponse()
    body = r.read()
    out = (r.status, dict(r.getheaders()), body)
    conn.close()
    return out


def test_web_live_panel_and_etag(tmp_path):
    from jepsen_tpu.web import make_server

    run_dir = tmp_path / "livetest" / "20260803T000000.000"
    run_dir.mkdir(parents=True)
    status = {"state": "tailing", "workload": "register",
              "valid_so_far": False, "first_anomaly_op": 42,
              "backend": "frontier-cpu", "ops_absorbed": 100,
              "checked_ops": 95, "lag_ops": 5, "lag_s": 0.1,
              "over_lag_budget": False, "torn_skipped": 0,
              "polls": 3, "updated": time.time()}
    (run_dir / "live-status.json").write_text(json.dumps(status))
    server = make_server(store_dir=str(tmp_path))
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        code, _hdr, body = _get(port, "/")
        assert code == 200
        assert b"live" in body and b"livetest" in body
        code, hdr, body = _get(port, "/livetest/20260803T000000.000/")
        assert code == 200
        assert b"first anomaly at op 42" in body
        assert b"http-equiv='refresh'" in body  # auto-refreshing panel
        # JSON served as application/json with a working ETag
        code, hdr, body = _get(
            port, "/livetest/20260803T000000.000/live-status.json")
        assert code == 200
        assert hdr["Content-Type"] == "application/json"
        etag = hdr["ETag"]
        code, hdr, body = _get(
            port, "/livetest/20260803T000000.000/live-status.json",
            headers={"If-None-Match": etag})
        assert code == 304
        assert body == b""
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# preflight knob coverage + tolerant coercion
# ---------------------------------------------------------------------------

def test_preflight_validates_live_knobs():
    from jepsen_tpu.analysis.preflight import preflight

    diags = preflight({"nodes": ["n1"], "live_poll_s": "garbage"})
    assert any(d.code == "KNB001" and d.path == "live_poll_s"
               for d in diags)
    diags = preflight({"nodes": ["n1"], "live_max_runs": 0})
    assert any(d.code == "KNB002" and d.path == "live_max_runs"
               for d in diags)
    diags = preflight({"nodes": ["n1"], "live_lag_budget_ops": -1})
    assert any(d.code == "KNB002" for d in diags)
    diags = preflight({"nodes": ["n1"], "live_poll_s": "2.5",
                       "live_check_budget_s": 0.25})
    assert any(d.code == "KNB006" for d in diags)  # stringly number
    assert not any(d.code in ("KNB001", "KNB002") for d in diags)


def test_daemon_knob_coercion_tolerant():
    from jepsen_tpu.live.daemon import LiveDaemon

    d = LiveDaemon(store_root=None, poll_s="0.25",
                   lag_budget_ops="oops", max_runs=-3,
                   check_budget_s=None)
    assert d.poll_s == 0.25
    assert d.lag_budget_ops == 50_000  # garbage -> default
    assert d.max_runs == 1             # clamped to the minimum
    assert d.check_budget_s == 0.5     # None -> default


def test_conftest_budget_guard_names_slowest(capsys):
    import io

    import conftest

    saved = dict(conftest._TEST_DURATIONS)
    try:
        conftest._TEST_DURATIONS.clear()
        for i in range(14):
            conftest._TEST_DURATIONS[f"tests/test_x.py::t{i}"] = float(i)
        buf = io.StringIO()
        conftest._dump_slowest(buf)
        out = buf.getvalue()
        assert "slowest 10 tests" in out
        assert "t13" in out and "t4" in out and "t3" not in out
    finally:
        conftest._TEST_DURATIONS.clear()
        conftest._TEST_DURATIONS.update(saved)


@pytest.mark.chaos
def test_sigkill_mid_append_native_python_identical(tmp_path):
    """SIGKILL a writer mid-append, then parse the surviving WAL through
    both sides of the host ingest spine: the native chunk scanner and
    the Python tolerant reader must deliver the identical op list and
    torn-tail verdict on whatever byte prefix the kill left behind."""
    import os
    import signal
    import subprocess
    import sys
    import time as _t
    from pathlib import Path
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py, read_jsonl_tolerant
    wal = tmp_path / "kill.wal.jsonl"
    code = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "from jepsen_tpu.journal import Journal\n"
        "j = Journal(%r, fsync_interval_s=0.0)\n"
        "i = 0\n"
        "while True:\n"
        "    j.append({'type': 'ok', 'f': 'write', 'value': i,\n"
        "              'process': i %% 5, 'time': i,\n"
        "              'pad': 'x' * (i %% 97)})\n"
        "    i += 1\n" % (str(Path(__file__).parent.parent), str(wal)))
    proc = subprocess.Popen([sys.executable, "-c", code])
    try:
        deadline = _t.monotonic() + 30.0
        while _t.monotonic() < deadline:
            if wal.exists() and wal.stat().st_size > 20_000:
                break
            _t.sleep(0.02)
        else:
            pytest.fail("writer produced no WAL bytes to kill over")
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    raw = wal.read_bytes()
    m = ingest.native_mod()
    if m is None:
        pytest.skip("native ingest extension unavailable")
    for final in (False, True):
        got = m.ingest_chunk(raw, final, ingest._line_fallback,
                             ingest._SKIP, ingest._TORN)
        want = parse_wal_chunk_py(raw, final=final)
        assert ingest._deep_eq(list(got[0]), list(want[0]))
        assert (got[1], got[2], bool(got[3])) == \
            (want[1], want[2], bool(want[3]))
    # and both agree with the tolerant whole-file reader's op list
    rows, _trunc = read_jsonl_tolerant(wal)
    assert ingest._deep_eq(list(got[0]), rows)
    assert [o["value"] for o in rows] == list(range(len(rows)))
