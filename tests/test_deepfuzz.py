"""Extended differential fuzzes — the long-running confidence harness
behind the fast CI fuzzes. Run with ``-m deepfuzz``; the default suite
excludes the marker via pyproject's addopts filter.

Three nets, each pinning a production fast path to its exact oracle on
hundreds of randomized histories:

* the Elle φ-cluster/columnar path vs the trim+Tarjan cpu pipeline,
  across three consistency-model configurations,
* segmented event-scan verification (frontier carry) vs monolithic runs
  at random cut sizes,
* the transfer-matrix operator-product chain vs monolithic matrix runs.
"""
from __future__ import annotations

import random
from dataclasses import replace

import numpy as np
import pytest

# also `slow`: a tier-1 `-m 'not slow'` invocation OVERRIDES pyproject's
# `-m 'not deepfuzz'` addopts filter (later -m wins), so without the
# second marker the quick lane would run these multi-minute fuzzes
pytestmark = [pytest.mark.deepfuzz, pytest.mark.slow]


def test_elle_production_vs_oracle_many():
    from tests.test_elle import _interleaved_history, _messy_history
    from jepsen_tpu.elle import list_append

    rng = random.Random(20260731)
    for i in range(300):
        if i % 2 == 0:
            h = _interleaved_history(rng, n_txns=rng.randrange(40, 200),
                                     n_keys=rng.randrange(2, 6),
                                     corrupt=rng.randrange(5))
        else:
            h = _messy_history(rng, n_txns=rng.randrange(30, 120))
        for models in (("strict-serializable",), ("serializable",),
                       ("snapshot-isolation",)):
            a = list_append.check(h, accelerator="auto",
                                  consistency_models=models)
            c = list_append.check(h, accelerator="cpu",
                                  consistency_models=models)
            assert (a["valid?"], a["anomaly-types"]) == \
                (c["valid?"], c["anomaly-types"]), (i, models)


def test_segmented_paths_vs_monolithic_many():
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import (JitLinKernel, _slice_stream,
                                       matrix_check, matrix_check_resume,
                                       quiescent_cuts, segmented_check)

    rng = random.Random(42)
    k = JitLinKernel()
    for trial in range(60):
        n = rng.randrange(100, 700)
        stream = encode_register_ops(_register_history(
            n, n_procs=rng.randrange(2, 6), seed=trial, n_values=5))
        if rng.random() < 0.5:
            a = np.asarray(stream.a).copy()
            reads = np.nonzero((np.asarray(stream.kind) == 0)
                               & (np.asarray(stream.f) == 0))[0]
            for r in rng.sample(list(reads), min(5, len(reads))):
                a[r] = rng.randrange(1, 6)
            stream = replace(stream, a=a)

        whole = k.check(stream)
        seg = segmented_check(
            stream, max_segment=rng.choice([32, 64, 128, 256]), kernel=k)
        assert bool(seg[0]) == bool(whole[0]), trial

        m_whole = matrix_check(stream, force=True)
        cuts = quiescent_cuts(np.asarray(stream.kind),
                              rng.choice([64, 128, 256]))
        tot, alive, base = None, True, 0
        for end in cuts:
            a2, ix, tot = matrix_check_resume(
                _slice_stream(stream, base, end), tot,
                n_slots=stream.n_slots)
            assert not bool(np.asarray(ix).any())
            alive = bool(np.asarray(a2).all())
            if not alive:
                break
            base = end
        assert alive == bool(m_whole[0]), trial


def test_pallas_chunk_product_vs_scan_many():
    """The pallas fused chunk product (interpret mode, forced through
    the production dispatch) vs the XLA scan across random valid and
    corrupted histories — the deep net behind the two-case CI test in
    tests/test_pallas_matrix.py."""
    from __graft_entry__ import _register_history
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check

    rng = random.Random(9)
    for trial in range(20):
        n = rng.randrange(80, 400)
        stream = encode_register_ops(_register_history(
            n, n_procs=rng.randrange(2, 6), seed=1000 + trial, n_values=5))
        if rng.random() < 0.5:
            a = np.asarray(stream.a).copy()
            reads = np.nonzero((np.asarray(stream.kind) == 0)
                               & (np.asarray(stream.f) == 0))[0]
            for r in rng.sample(list(reads), min(4, len(reads))):
                a[r] = rng.randrange(1, 6)
            stream = replace(stream, a=a)

        pm.FORCE_INTERPRET = False
        scan = matrix_check(stream, force=True)
        pm.FORCE_INTERPRET = True
        try:
            pal = matrix_check(stream, force=True)
        finally:
            pm.FORCE_INTERPRET = False
        assert pal is not None and scan is not None
        assert bool(pal[0]) == bool(scan[0]), trial
