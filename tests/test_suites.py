"""L8 suite tests: test-map construction, command shapes over the dummy
remote, and full fake-mode lifecycle runs (reference: per-suite test stubs
plus core_test.clj tier-2 strategy, SURVEY.md §4)."""
import tempfile

import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import (compose_test, consul, etcd, mongodb, postgres,
                               redis, suite_registry, workload_registry,
                               zookeeper)

NODES = ["n1", "n2", "n3", "n4", "n5"]


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------

def test_workload_registry_complete():
    reg = workload_registry()
    assert {"register", "set", "bank", "append", "wr", "long-fork",
            "causal-reverse", "adya"} <= set(reg)
    for name, ctor in reg.items():
        w = ctor({"concurrency": 4, "nodes": NODES})
        assert "generator" in w and "checker" in w, name


def test_etcd_test_map_shape():
    t = etcd.etcd_test({"fake": True, "time_limit": 5})
    assert t["name"] == "etcd-register"
    assert t["generator"] is not None
    assert t["checker"] is not None
    assert t.get("nemesis") is None  # fake mode: no faults by default
    assert t["ssh"]["dummy"]

    t2 = etcd.etcd_test({"fake": True, "faults": {"partition"}})
    assert t2["nemesis"] is not None
    fs = t2["nemesis"].fs()
    assert "start-partition" in fs and "stop-partition" in fs


def test_zookeeper_test_map_shape():
    t = zookeeper.zookeeper_test({"fake": True, "workload": "set"})
    assert t["name"] == "zookeeper-set"
    assert t["generator"] is not None and t["checker"] is not None


# ---------------------------------------------------------------------------
# DB automation command shapes (dummy remote)
# ---------------------------------------------------------------------------

def test_etcd_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = etcd.EtcdDB()
    try:
        control.on("n1", t, lambda: db.start(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--initial-cluster" in joined
        assert "n1=http://n1:2380" in joined
        assert "--enable-v2" in joined
        control.on("n1", t, lambda: db.kill(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "kill" in joined.lower()
    finally:
        control.disconnect_all(t)


def test_zookeeper_cfg_and_myid():
    t = {"nodes": NODES}
    cfg = zookeeper.zoo_cfg(t)
    assert "server.1=n1:2888:3888" in cfg
    assert "server.5=n5:2888:3888" in cfg
    assert "clientPort=2181" in cfg
    assert zookeeper.node_id(t, "n3") == 3


# ---------------------------------------------------------------------------
# fake-mode lifecycle
# ---------------------------------------------------------------------------

from conftest import run_fake  # noqa: E402


@pytest.mark.slow
def test_etcd_fake_register_run():
    result = run_fake(etcd.etcd_test)
    assert result["results"]["valid?"] is True, result["results"]
    assert result["results"]["workload"]["valid?"] is True
    assert len(result["history"]) > 0


@pytest.mark.slow
def test_etcd_fake_set_run():
    result = run_fake(etcd.etcd_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_zookeeper_fake_register_run():
    result = run_fake(zookeeper.zookeeper_test)
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_etcd_cli_fake_run():
    with tempfile.TemporaryDirectory() as tmp:
        code = etcd.main(["test", "--fake", "--no-ssh", "--time-limit", "1",
                          "--no-perf", "--accelerator", "cpu",
                          "--store-dir", tmp])
        assert code == 0


def test_etcd_cli_bad_args():
    assert etcd.main(["test", "--workload", "nonsense"]) == 254


# ---------------------------------------------------------------------------
# the wider suite registry
# ---------------------------------------------------------------------------

def test_suite_registry_constructs_fake_tests():
    for name, ctor in suite_registry().items():
        t = ctor({"fake": True, "time_limit": 1})
        assert t["generator"] is not None, name
        assert t["checker"] is not None, name
        assert t["ssh"]["dummy"], name


def test_consul_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = consul.ConsulDB()
    try:
        control.on("n1", t, lambda: db.start(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "-bootstrap-expect 5" in joined
        assert "-retry-join n1" in joined
    finally:
        control.disconnect_all(t)


def test_redis_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = redis.RedisDB()
    try:
        control.on("n2", t, lambda: db.start(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--replicaof n1 6379" in joined   # n2 follows the primary
        control.on("n1", t, lambda: db.start(t, "n1"))
        primary_cmds = [x for x in remote.log if "redis-server" in str(x)]
        assert any("--replicaof" not in str(c) for c in primary_cmds)
        assert db.primaries(t) == ["n1"]
    finally:
        control.disconnect_all(t)


def test_resp_protocol_roundtrip():
    """The from-scratch RESP client against a scripted socket server."""
    import socket
    import threading

    # canned replies: simple string, integer, bulk, nil bulk, array, error
    replies = [b"+OK\r\n", b":1\r\n", b"$3\r\n42x\r\n", b"$-1\r\n",
               b"*2\r\n$1\r\n1\r\n$1\r\n2\r\n", b"-ERR boom\r\n"]
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    received = []

    def serve():
        conn, _ = srv.accept()
        f = conn.makefile("rb")
        for r in replies:
            # each command: array header + 2 lines per bulk arg
            header = f.readline()
            n = int(header[1:].strip())
            args = []
            for _ in range(n):
                f.readline()
                args.append(f.readline().strip().decode())
            received.append(args)
            conn.sendall(r)
        conn.close()

    thr = threading.Thread(target=serve, daemon=True)
    thr.start()
    c = redis.RespConnection("127.0.0.1", port=port)
    assert c.command("SET", "k", 1) == "OK"
    assert c.command("EVAL", redis.CAS_LUA, 1, "k", 0, 1) == 1
    assert c.command("GET", "k") == "42x"
    assert c.command("GET", "missing") is None
    assert c.command("SMEMBERS", "s") == ["1", "2"]
    with pytest.raises(redis.RespError):
        c.command("BAD")
    c.close()
    thr.join(timeout=5)
    assert received[0] == ["SET", "k", "1"]


@pytest.mark.slow
def test_postgres_fake_append_run():
    """The Elle list-append workload end-to-end over the fake txn store."""
    result = run_fake(postgres.postgres_test, workload="append")
    assert result["results"]["valid?"] is True, result["results"]
    txns = [op for op in result["history"]
            if op.get("f") == "txn" and op["type"] == "ok"]
    assert txns, "no committed txns"


@pytest.mark.slow
def test_redis_fake_set_run():
    result = run_fake(redis.redis_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_mongodb_fake_register_run():
    result = run_fake(mongodb.mongodb_test, workload="register")
    assert result["results"]["valid?"] is True, result["results"]


def test_fake_forces_dummy_remote():
    """--fake without --no-ssh must still ride the dummy remote."""
    t = etcd.etcd_test({"fake": True,
                        "ssh": {"dummy": False, "username": "root"}})
    assert t["ssh"]["dummy"] is True
    t2 = zookeeper.zookeeper_test({"fake": True,
                                   "ssh": {"dummy": False}})
    assert t2["ssh"]["dummy"] is True


# ---------------------------------------------------------------------------
# raftis & disque (RESP family)
# ---------------------------------------------------------------------------

def test_raftis_db_commands():
    from jepsen_tpu.suites import raftis
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = raftis.RaftisDB()
    try:
        control.on("n2", t, lambda: db.start(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        # daemon argv: full cluster string, own node name, raft + client ports
        assert "n1:8901,n2:8901,n3:8901,n4:8901,n5:8901" in joined
        assert " n2 " in joined and "8901" in joined and "6379" in joined
    finally:
        control.disconnect_all(t)


@pytest.mark.slow
def test_raftis_fake_register_run():
    from jepsen_tpu.suites import raftis
    result = run_fake(raftis.raftis_test)
    assert result["results"]["valid?"] is True, result["results"]


def test_disque_db_join_commands():
    from jepsen_tpu.suites import disque
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = disque.DisqueDB()
    try:
        control.on("n3", t, lambda: db.join(t, "n3"))
        joined = " ".join(str(x) for x in remote.log)
        assert "cluster meet" in joined   # CLUSTER MEET to the primary
        before = len(remote.log)
        control.on("n1", t, lambda: db.join(t, "n1"))  # primary: no meet
        assert len(remote.log) == before
    finally:
        control.disconnect_all(t)


@pytest.mark.slow
def test_disque_fake_queue_run():
    from jepsen_tpu.suites import disque
    result = run_fake(disque.disque_test)
    assert result["results"]["valid?"] is True, result["results"]
    # final drain phase must have produced drain ops
    assert any(op.get("f") == "drain" for op in result["history"])


def test_disque_ack_lost_is_indeterminate_not_lost():
    """A dead connection between GETJOB and ACKJOB must not produce a
    definite bare 'fail' (which total-queue would count as job loss)."""
    from jepsen_tpu.suites import disque

    class FakeConn:
        def __init__(self):
            self.calls = 0

        def command(self, *args):
            if args[0] == "GETJOB":
                return [["jepsen", "D-id", "42"]]
            raise ConnectionError("dropped before ACKJOB reply")

    c = disque.DisqueClient()
    c.conn = FakeConn()
    out = c.invoke({}, {"f": "dequeue", "value": None, "type": "invoke"})
    assert out["type"] == "ok" and out["value"] == 42  # delivery happened

    c.conn = FakeConn()
    out = c.invoke({}, {"f": "drain", "value": None, "type": "invoke"})
    assert out["type"] == "info" and out["value"] == [42]


def test_resp_truncated_replies_raise():
    """A server killed mid-reply must surface as ConnectionError, never as
    a plausible-but-corrupt successful value."""
    import socket
    import threading

    from jepsen_tpu.suites._resp import RespConnection

    for payload in (b"$3\r\n12", b":1", b"+O"):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def serve(s=srv, p=payload):
            conn, _ = s.accept()
            conn.recv(4096)
            conn.sendall(p)
            conn.close()

        threading.Thread(target=serve, daemon=True).start()
        c = RespConnection("127.0.0.1", port)
        try:
            import pytest
            with pytest.raises((ConnectionError, OSError)):
                c.command("GET", "k")
        finally:
            c.close()
            srv.close()


@pytest.mark.slow
def test_fake_run_with_partition_nemesis_end_to_end():
    """Full lifecycle with the nemesis ACTIVE in fake mode: partition
    ops ride the nemesis thread concurrently with client ops, the final
    phase heals, and the history records the fault schedule."""
    from jepsen_tpu.suites import etcd
    result = run_fake(etcd.etcd_test, faults={"partition"},
                      nemesis_interval=0.2, time_limit=2.0)
    assert result["results"]["valid?"] is True, result["results"]
    nem_ops = [op for op in result["history"]
               if op.get("process") == "nemesis"]
    assert any(op.get("f") == "start-partition" for op in nem_ops)
    # the final phase heals: the LAST nemesis action must be a heal
    # (main-phase ops alternate, so any() alone wouldn't prove the
    # final-generator phase ran)
    completions = [op for op in nem_ops if op.get("type") != "invoke"]
    assert completions and completions[-1].get("f") == "stop-partition"


@pytest.mark.slow
def test_fake_run_with_kill_and_pause_nemesis():
    """Kill/pause fault packages now compose in fake mode (the in-memory
    DB implements Process/Pause as meta-logged no-ops), so the whole
    DBNemesis scheduling path runs end to end."""
    from jepsen_tpu.suites import etcd
    result = run_fake(etcd.etcd_test,
                      faults={"kill", "pause", "partition"},
                      nemesis_interval=0.2, time_limit=2.5)
    assert result["results"]["valid?"] is True, result["results"]
    nem_fs = {op.get("f") for op in result["history"]
              if op.get("process") == "nemesis"}
    # BOTH newly-enabled families must schedule — a >=2-of-3 threshold
    # would let a dropped Process/Pause mixin regress undetected
    assert "kill" in nem_fs, nem_fs
    assert "pause" in nem_fs, nem_fs
