"""L8 suite tests: test-map construction, command shapes over the dummy
remote, and full fake-mode lifecycle runs (reference: per-suite test stubs
plus core_test.clj tier-2 strategy, SURVEY.md §4)."""
import tempfile

import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import compose_test, etcd, workload_registry, zookeeper

NODES = ["n1", "n2", "n3", "n4", "n5"]


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------

def test_workload_registry_complete():
    reg = workload_registry()
    assert {"register", "set", "bank", "append", "wr", "long-fork",
            "causal-reverse", "adya"} <= set(reg)
    for name, ctor in reg.items():
        w = ctor({"concurrency": 4, "nodes": NODES})
        assert "generator" in w and "checker" in w, name


def test_etcd_test_map_shape():
    t = etcd.etcd_test({"fake": True, "time_limit": 5})
    assert t["name"] == "etcd-register"
    assert t["generator"] is not None
    assert t["checker"] is not None
    assert t.get("nemesis") is None  # fake mode: no faults by default
    assert t["ssh"]["dummy"]

    t2 = etcd.etcd_test({"fake": True, "faults": {"partition"}})
    assert t2["nemesis"] is not None
    fs = t2["nemesis"].fs()
    assert "start-partition" in fs and "stop-partition" in fs


def test_zookeeper_test_map_shape():
    t = zookeeper.zookeeper_test({"fake": True, "workload": "set"})
    assert t["name"] == "zookeeper-set"
    assert t["generator"] is not None and t["checker"] is not None


# ---------------------------------------------------------------------------
# DB automation command shapes (dummy remote)
# ---------------------------------------------------------------------------

def test_etcd_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = etcd.EtcdDB()
    try:
        control.on("n1", t, lambda: db.start(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "--initial-cluster" in joined
        assert "n1=http://n1:2380" in joined
        assert "--enable-v2" in joined
        control.on("n1", t, lambda: db.kill(t, "n1"))
        joined = " ".join(str(x) for x in remote.log)
        assert "kill" in joined.lower()
    finally:
        control.disconnect_all(t)


def test_zookeeper_cfg_and_myid():
    t = {"nodes": NODES}
    cfg = zookeeper.zoo_cfg(t)
    assert "server.1=n1:2888:3888" in cfg
    assert "server.5=n5:2888:3888" in cfg
    assert "clientPort=2181" in cfg
    assert zookeeper.node_id(t, "n3") == 3


# ---------------------------------------------------------------------------
# fake-mode lifecycle
# ---------------------------------------------------------------------------

def run_fake(suite_test_fn, **opts):
    with tempfile.TemporaryDirectory() as tmp:
        t = suite_test_fn({"fake": True, "time_limit": 1.0,
                           "store_dir": tmp, "no_perf": True,
                           "accelerator": "cpu", **opts})
        from jepsen_tpu import core
        return core.run(t)


def test_etcd_fake_register_run():
    result = run_fake(etcd.etcd_test)
    assert result["results"]["valid?"] is True, result["results"]
    assert result["results"]["workload"]["valid?"] is True
    assert len(result["history"]) > 0


def test_etcd_fake_set_run():
    result = run_fake(etcd.etcd_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]


def test_zookeeper_fake_register_run():
    result = run_fake(zookeeper.zookeeper_test)
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_etcd_cli_fake_run():
    with tempfile.TemporaryDirectory() as tmp:
        code = etcd.main(["test", "--fake", "--no-ssh", "--time-limit", "1",
                          "--no-perf", "--accelerator", "cpu",
                          "--store-dir", tmp])
        assert code == 0


def test_etcd_cli_bad_args():
    assert etcd.main(["test", "--workload", "nonsense"]) == 254


def test_fake_forces_dummy_remote():
    """--fake without --no-ssh must still ride the dummy remote."""
    t = etcd.etcd_test({"fake": True,
                        "ssh": {"dummy": False, "username": "root"}})
    assert t["ssh"]["dummy"] is True
    t2 = zookeeper.zookeeper_test({"fake": True,
                                   "ssh": {"dummy": False}})
    assert t2["ssh"]["dummy"] is True
