"""Sanitizer lanes: the ASan+UBSan build of the native ingest spine.

Slow-lane (``-m native_san``). The differential suites and a bounded
fuzz run execute in a CHILD process with the ASan runtime LD_PRELOADed
(``columnar_c.san_env()``) — GCC's libasan aborts on a late dlopen, so
the instrumented ``.so`` can never load into this test process
directly. Gate mirrors conftest's ``_native_ingest_build_guard``: no
toolchain → soft skip; toolchain present but the san build fails →
loud ``pytest.exit`` (a silently skipped sanitizer lane would report
green forever). doc/static-analysis.md "Native code" documents the
workflow.
"""
from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.native_san, pytest.mark.slow]

_REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def san_lane():
    """(env, so_path) for a sanitizer-capable child, or skip/exit."""
    from jepsen_tpu.native import columnar_c
    if shutil.which("g++") is None:
        pytest.skip("no g++: sanitizer lane unavailable")
    env = columnar_c.san_env()
    if env is None:
        pytest.skip("no libasan/libubsan runtime next to g++")
    try:
        so = columnar_c.build(san=True)
    except Exception as e:  # noqa: BLE001
        pytest.exit("sanitizer toolchain present but the ASan+UBSan "
                    f"build of columnar_ext.c failed: {e!r} — the san "
                    "lane must not silently skip", returncode=3)
    env["PYTHONPATH"] = str(_REPO)
    return env, so


def _run(cmd, env, timeout=600):
    return subprocess.run(cmd, env=env, cwd=str(_REPO),
                          capture_output=True, text=True,
                          timeout=timeout)


def _assert_no_sanitizer_report(proc):
    blob = proc.stdout + proc.stderr
    assert "ERROR: AddressSanitizer" not in blob, blob[-4000:]
    assert "runtime error:" not in blob, blob[-4000:]  # UBSan


def test_san_build_is_distinct_artifact(san_lane):
    from jepsen_tpu.native import columnar_c
    env, so = san_lane
    assert "_columnar_c_san-" in Path(so).name
    assert Path(so) != columnar_c._so_path(san=False)


def test_differential_suites_under_asan(san_lane):
    """The existing torn/unicode/bigint/resume differentials, re-run
    with the instrumented scanner doing the work."""
    env, _so = san_lane
    proc = _run([sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
                 "tests/test_history_ir.py",
                 "-k", "ingest_chunk or wal_tailer_resume"],
                env)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    _assert_no_sanitizer_report(proc)
    # the suite must have RUN the native cases, not skipped them
    assert "skipped" not in proc.stdout.lower() or " 0 skipped" in proc.stdout


def test_wgl_differentials_under_asan(san_lane):
    """The C++ WGL search's unit + random-history differential suite,
    re-run against the instrumented `_libwgl_san` build (the child's
    JEPSEN_TPU_NATIVE_SAN=1 routes `native.lib()` to it)."""
    from jepsen_tpu import native
    env, _so = san_lane
    try:
        native.build(san=True)
    except Exception as e:  # noqa: BLE001
        pytest.exit("sanitizer toolchain present but the ASan+UBSan "
                    f"build of wgl.cpp failed: {e!r}", returncode=3)
    proc = _run([sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
                 "tests/test_native.py"], env)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    _assert_no_sanitizer_report(proc)
    assert "skipped" not in proc.stdout.lower() or " 0 skipped" in proc.stdout


def test_bounded_fuzz_under_asan(san_lane, tmp_path):
    """A bounded fuzz-native run in the sanitized child: zero
    divergences AND zero sanitizer reports."""
    env, _so = san_lane
    proc = _run([sys.executable, "-m", "jepsen_tpu.cli", "fuzz-native",
                 "--execs", "2000", "--seed", "1",
                 "--store-dir", str(tmp_path)], env)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    assert "variant=san" in proc.stdout, proc.stdout[-2000:]
    assert "0 divergence(s)" in proc.stdout
    _assert_no_sanitizer_report(proc)


def test_san_unavailable_counts_distinct_fallback(monkeypatch):
    """In THIS (non-preloaded) process the san variant must refuse to
    load, and the ingest layer must fall back to the Python twins with
    the dedicated ``san-unavailable`` reason — never a silently
    uninstrumented native path."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.native import columnar_c

    monkeypatch.setattr(columnar_c, "_mod_san", None)
    monkeypatch.setattr(columnar_c, "_mod_san_failed", False)
    monkeypatch.setenv("JEPSEN_TPU_NATIVE_SAN", "1")
    ingest.reset()
    try:
        with telemetry.use(telemetry.Registry()) as reg:
            assert ingest.native_mod() is None
            # and the chunk parse still works, through the Python twin
            ops, consumed, torn, trunc = ingest.parse_wal_chunk(
                b'{"type":"ok","f":"read","value":1,"process":0,'
                b'"time":1}\n', final=True)
            assert len(ops) == 1 and not trunc
            cell = reg.counter("native_ingest_fallback_total",
                               labels=("reason",)).cell(
                                   reason="san-unavailable")
            assert cell[0] >= 1
    finally:
        ingest.reset()
