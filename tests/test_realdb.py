"""Real-daemon integration smoke (SURVEY.md §4 tier 3).

Starts an actual single-node DB as a local process and runs the full
suite lifecycle against it over the dummy remote: every remote command
(install, start-stop-daemon, teardown) no-ops, but the CLIENT speaks the
real wire protocol to the real daemon on 127.0.0.1, the interpreter
schedules real concurrent ops, and the checker judges the real history.
This is the layer the scripted wire-protocol tests can't cover: a
daemon's actual command semantics, framing quirks, and timing.

Gated behind ``-m realdb``: each test skips unless the daemon binary is
on PATH (or named by JEPSEN_<DB>_BIN). In the build image no daemons
exist, so these skip; on a workstation with redis/etcd installed they
run the real thing.
"""
from __future__ import annotations

import os
import shutil
import socket
import subprocess
import time

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _await_conn(factory, proc, timeout_s: float = 30.0, dt: float = 0.3):
    """Retries ``factory()`` until it connects; raises early when the
    daemon has already exited (a dead daemon must not spin the whole
    timeout and surface as a generic connection error). ``proc=None``
    means the server is externally managed (docker/realdb ADDR mode):
    only the timeout applies."""
    deadline = time.time() + timeout_s
    while True:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(f"daemon exited rc={proc.returncode}")
        try:
            return factory()
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(dt)


def _addr(env_var: str) -> tuple[str, int] | None:
    """host:port of an ALREADY-RUNNING server (the docker/realdb
    compose services), or None to spawn a scratch daemon from a local
    binary. Hosts must be reachable as plain TCP (the compose file maps
    every service onto 127.0.0.1)."""
    v = os.environ.get(env_var)
    if not v:
        return None
    host, _, port = v.rpartition(":")
    return host or "127.0.0.1", int(port)


def _await_port(port: int, proc, timeout_s: float = 20.0,
                host: str = "127.0.0.1") -> None:
    def probe():
        socket.create_connection((host, port), timeout=1).close()

    _await_conn(probe, proc, timeout_s=timeout_s, dt=0.2)


def _find(binary: str, env_var: str) -> str | None:
    return os.environ.get(env_var) or shutil.which(binary)


def _run_suite(suite_test, tmp_path, **opts):
    from jepsen_tpu import core

    test = suite_test({
        "nodes": ["127.0.0.1"],
        "concurrency": 3,
        "time_limit": opts.pop("time_limit", 6),
        "ssh": {"dummy": True},
        "faults": set(),
        "store_dir": str(tmp_path),
        "no_perf": True,
        **opts,
    })
    return core.run(test)


MINI_RESP_SERVER = r"""
import socketserver, sys, threading

SETS = {}
LOCK = threading.Lock()

class H(socketserver.StreamRequestHandler):
    def read_cmd(self):
        line = self.rfile.readline()
        if not line or not line.startswith(b"*"):
            return None
        n = int(line[1:])
        out = []
        for _ in range(n):
            ln = self.rfile.readline()      # $<len>
            size = int(ln[1:])
            out.append(self.rfile.read(size))
            self.rfile.read(2)              # trailing CRLF
        return out

    def handle(self):
        while True:
            cmd = self.read_cmd()
            if cmd is None:
                return
            op = cmd[0].upper()
            with LOCK:
                if op == b"SADD":
                    SETS.setdefault(cmd[1], set()).add(cmd[2])
                    self.wfile.write(b":1\r\n")
                elif op == b"SMEMBERS":
                    ms = sorted(SETS.get(cmd[1], set()))
                    self.wfile.write(b"*%d\r\n" % len(ms))
                    for m in ms:
                        self.wfile.write(b"$%d\r\n%s\r\n" % (len(m), m))
                else:
                    self.wfile.write(b"-ERR unknown\r\n")

class S(socketserver.ThreadingTCPServer):
    allow_reuse_address = True

S(("127.0.0.1", int(sys.argv[1])), H).serve_forever()
"""


@pytest.mark.slow
def test_realdb_harness_mechanics(tmp_path, monkeypatch):
    """Proves the realdb harness end-to-end without a redis binary: a
    SUBPROCESS mini-RESP daemon stands in for redis-server, and the full
    suite lifecycle (dummy remote, real TCP wire protocol, interpreter,
    checker, store) runs against it. Not marked realdb — this must pass
    everywhere, so the gated tests' plumbing can't rot unnoticed."""
    import sys

    from jepsen_tpu.suites import redis as redis_suite

    port = _free_port()
    proc = subprocess.Popen([sys.executable, "-c", MINI_RESP_SERVER,
                             str(port)])
    try:
        _await_port(port, proc)
        monkeypatch.setattr(redis_suite, "PORT", port)
        result = _run_suite(redis_suite.redis_test, tmp_path,
                            workload="set", time_limit=4)
        ops = [o for o in result["history"] if o.get("type") == "ok"
               and isinstance(o.get("process"), int)]
        assert len(ops) > 10, "daemon must have served real ops"
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.realdb
def test_redis_real_daemon_set(tmp_path, monkeypatch):
    binary = _find("redis-server", "JEPSEN_REDIS_BIN")
    if not binary:
        pytest.skip("no redis-server binary available")
    from jepsen_tpu.suites import redis as redis_suite

    port = _free_port()
    proc = subprocess.Popen(
        [binary, "--port", str(port), "--bind", "127.0.0.1",
         "--save", "", "--appendonly", "no"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(port, proc)
        monkeypatch.setattr(redis_suite, "PORT", port)
        result = _run_suite(redis_suite.redis_test, tmp_path,
                            workload="set")
        ops = [o for o in result["history"] if o.get("type") == "ok"
               and isinstance(o.get("process"), int)]
        assert len(ops) > 10, "real daemon must have served real ops"
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.realdb
def test_etcd_real_daemon_register(tmp_path, monkeypatch):
    binary = _find("etcd", "JEPSEN_ETCD_BIN")
    if not binary:
        pytest.skip("no etcd binary available")
    from jepsen_tpu.suites import etcd as etcd_suite

    port = _free_port()
    peer = _free_port()
    proc = subprocess.Popen(
        [binary, "--name", "n0", "--data-dir", str(tmp_path / "etcd"),
         "--listen-client-urls", f"http://127.0.0.1:{port}",
         "--advertise-client-urls", f"http://127.0.0.1:{port}",
         "--listen-peer-urls", f"http://127.0.0.1:{peer}",
         "--initial-advertise-peer-urls", f"http://127.0.0.1:{peer}",
         "--initial-cluster", f"n0=http://127.0.0.1:{peer}",
         "--enable-v2=true"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(port, proc)
        monkeypatch.setattr(etcd_suite, "CLIENT_PORT", port)
        result = _run_suite(etcd_suite.etcd_test, tmp_path,
                            workload="register")
        ops = [o for o in result["history"] if o.get("type") == "ok"
               and isinstance(o.get("process"), int)]
        assert len(ops) > 10, "real daemon must have served real ops"
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        proc.kill()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# PostgreSQL: the from-scratch v3 wire client against a real server
# (VERDICT r2 item 6 — SCRAM auth, simple query, serialization-failure
# retry, and the bank workload lifecycle)
# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_postgres_wire_client(tmp_path, monkeypatch):
    initdb = _find("initdb", "JEPSEN_INITDB_BIN")
    postgres_bin = _find("postgres", "JEPSEN_POSTGRES_BIN")
    if not (initdb and postgres_bin):
        pytest.skip("postgres/initdb not installed")

    from jepsen_tpu.suites import postgres as pg_suite
    from jepsen_tpu.suites._postgres import (PGConnection, PgError,
                                             SERIALIZATION_FAILURE)

    port = _free_port()
    data = tmp_path / "pgdata"
    pw = tmp_path / "pw"
    pw.write_text("superpw\n")
    subprocess.run(
        [initdb, "-D", str(data), "-U", "super", "--auth-host=scram-sha-256",
         "--auth-local=trust", f"--pwfile={pw}"],
        check=True, capture_output=True)
    proc = subprocess.Popen(
        [postgres_bin, "-D", str(data), "-p", str(port),
         "-c", "listen_addresses=127.0.0.1",
         "-c", f"unix_socket_directories={tmp_path}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(port, proc)

        # SCRAM-SHA-256 auth + simple query over our own wire code
        conn = _await_conn(
            lambda: PGConnection("127.0.0.1", port=port, user="super",
                                 password="superpw", database="postgres"),
            proc, timeout_s=20)
        rows, _ = conn.query("select 1 + 1")
        assert rows[0][0] in ("2", 2)

        conn.query("create role jepsen with login password 'jepsenpw'")
        conn.query("create database jepsen owner jepsen")

        # serialization-failure retry: two serializable txns racing on
        # one row; the loser surfaces SQLSTATE 40001 through PgError and
        # a fresh attempt succeeds
        a = PGConnection("127.0.0.1", port=port, user="super",
                         password="superpw", database="postgres")
        b = PGConnection("127.0.0.1", port=port, user="super",
                         password="superpw", database="postgres")
        conn.query("create table sf (k int primary key, v int)")
        conn.query("insert into sf values (1, 0)")
        for c in (a, b):
            c.query("begin isolation level serializable")
            c.query("select v from sf where k = 1")
        a.query("update sf set v = 1 where k = 1")
        a.query("commit")
        failed = False
        try:
            b.query("update sf set v = 2 where k = 1")
            b.query("commit")
        except PgError as e:
            failed = True
            assert e.sqlstate == SERIALIZATION_FAILURE, e.sqlstate
            try:
                b.query("rollback")
            except Exception:
                pass
        assert failed, "concurrent serializable update must conflict"
        b.query("begin isolation level serializable")
        b.query("update sf set v = 2 where k = 1")
        b.query("commit")

        # bank workload end-to-end through the suite lifecycle: the
        # dummy remote no-ops node automation while the client speaks
        # the real wire protocol to the real server
        monkeypatch.setattr(pg_suite, "PORT", port)
        monkeypatch.setattr(pg_suite.PostgresClient, "PORT", port)
        result = _run_suite(pg_suite.postgres_test, tmp_path,
                            workload="bank", time_limit=5)
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_mysql_wire_client(tmp_path, monkeypatch):
    """Scratch mysqld/mariadbd + the from-scratch MySQL wire client:
    native-password auth, CRUD, the serializable bank workload through
    the full suite lifecycle (VERDICT r3 item 6 — the PG template at
    test_realdb_postgres_wire_client, one protocol over)."""
    addr = _addr("JEPSEN_MYSQL_ADDR")
    mysqld = install = None
    if addr is None:
        mysqld = _find("mariadbd", "JEPSEN_MYSQLD_BIN") \
            or _find("mysqld", "JEPSEN_MYSQLD_BIN")
        if not mysqld:
            pytest.skip("mysqld/mariadbd not installed and no "
                        "JEPSEN_MYSQL_ADDR")
        install = _find("mariadb-install-db", "JEPSEN_MYSQL_INSTALL_BIN") \
            or _find("mysql_install_db", "JEPSEN_MYSQL_INSTALL_BIN")

    from jepsen_tpu.suites import galera as galera_suite
    from jepsen_tpu.suites._mysql import MySQLConnection, MySQLError

    if addr is not None:
        # docker mode: server already up with a password-less root
        # (MYSQL_ALLOW_EMPTY_PASSWORD=yes in docker/realdb)
        host, port = addr
        _mysql_body(None, host, port, galera_suite, MySQLConnection,
                    MySQLError, tmp_path, monkeypatch)
        return

    host = "127.0.0.1"
    port = _free_port()
    data = tmp_path / "mysqldata"
    sock = tmp_path / "mysql.sock"
    base_args = [mysqld, f"--datadir={data}", f"--socket={sock}",
                 f"--port={port}", "--bind-address=127.0.0.1",
                 "--skip-name-resolve",
                 f"--pid-file={tmp_path}/mysqld.pid",
                 f"--log-error={tmp_path}/mysqld.err"]
    if install:  # mariadb: normal auth gives root a password-less login
        subprocess.run(
            [install, f"--datadir={data}",
             "--auth-root-authentication-method=normal"],
            check=True, capture_output=True)
    else:        # oracle mysqld: self-initializing, root with empty pw
        subprocess.run(
            [mysqld, f"--datadir={data}", "--initialize-insecure",
             f"--log-error={tmp_path}/init.err"],
            check=True, capture_output=True)
        base_args.append(
            "--default-authentication-plugin=mysql_native_password")
    proc = subprocess.Popen(base_args, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        _mysql_body(proc, host, port, galera_suite, MySQLConnection,
                    MySQLError, tmp_path, monkeypatch)
    finally:
        proc.kill()
        proc.wait()


def _mysql_body(proc, host, port, galera_suite, MySQLConnection,
                MySQLError, tmp_path, monkeypatch):
    """Auth + CRUD + bank lifecycle, shared by the scratch-daemon and
    ADDR (docker) modes. The workload table is dropped first so a
    reused server stays rerun-safe."""
    _await_port(port, proc, host=host)

    # native-password auth (empty root pw) + CRUD over our own wire
    conn = _await_conn(
        lambda: MySQLConnection(host, port=port, user="root",
                                password="", database="mysql"), proc)
    rows = conn.query("SELECT 1 + 1")
    assert int(rows[0][0]) == 2

    conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
    conn.query("CREATE USER IF NOT EXISTS 'jepsen'@'%' IDENTIFIED "
               "WITH mysql_native_password BY 'jepsen'")
    conn.query("GRANT ALL PRIVILEGES ON jepsen.* TO 'jepsen'@'%'")
    conn.query("FLUSH PRIVILEGES")

    # authenticated CRUD as the workload user (non-empty password
    # exercises the scramble path)
    c2 = MySQLConnection(host, port=port, user="jepsen",
                         password="jepsen", database="jepsen")
    c2.query("DROP TABLE IF EXISTS t")
    c2.query("CREATE TABLE t (k INT PRIMARY KEY, v INT)")
    c2.query("INSERT INTO t VALUES (1, 10)")
    c2.query("UPDATE t SET v = 11 WHERE k = 1")
    rows = c2.query("SELECT v FROM t WHERE k = 1")
    assert int(rows[0][0]) == 11
    with pytest.raises(MySQLError):
        c2.query("INSERT INTO t VALUES (1, 12)")  # duplicate key
    c2.query("DROP TABLE IF EXISTS accounts")   # bank kit rerun-safety

    # bank workload end-to-end: dummy remote no-ops the node
    # automation, the client speaks the real protocol to the daemon
    monkeypatch.setattr(galera_suite, "PORT", port)
    result = _run_suite(galera_suite.galera_test, tmp_path / "store",
                        workload="bank", time_limit=5, nodes=[host])
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_rethinkdb_wire_client(tmp_path, monkeypatch):
    """Scratch single-node rethinkdb + the bundled ReQL driver: V0_4
    handshake, DDL, CRUD terms, then the register and set workloads
    through the suite lifecycle."""
    addr = _addr("JEPSEN_RETHINKDB_ADDR")
    rethinkdb_bin = None
    if addr is None:
        rethinkdb_bin = _find("rethinkdb", "JEPSEN_RETHINKDB_BIN")
        if not rethinkdb_bin:
            pytest.skip("rethinkdb not installed and no "
                        "JEPSEN_RETHINKDB_ADDR")

    from jepsen_tpu.suites import rethinkdb as r_suite
    from jepsen_tpu.suites import _reql as r
    from jepsen_tpu.suites._reql import ReqlConnection

    proc = None
    if addr is not None:
        host, driver_port = addr
    else:
        host = "127.0.0.1"
        driver_port = _free_port()
        cluster_port = _free_port()
        proc = subprocess.Popen(
            [rethinkdb_bin, "--directory", str(tmp_path / "rdb"),
             "--bind", "127.0.0.1", "--driver-port", str(driver_port),
             "--cluster-port", str(cluster_port), "--no-http-admin",
             "--no-update-check"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _await_port(driver_port, proc, timeout_s=60, host=host)
        conn = _await_conn(
            lambda: ReqlConnection(host, driver_port), proc)
        db = f"smoke_{os.urandom(4).hex()}"   # rerun-safe database
        conn.run(r.db_create(db))
        conn.run(r.table_create(r.db(db), "t"))
        conn.run(r.insert(r.table(r.db(db), "t"), {"id": 1, "v": 5}))
        out = conn.run(r.get_field(r.get(r.table(r.db(db), "t"), 1),
                                   "v"))
        assert out == 5

        monkeypatch.setattr(r_suite, "DRIVER_PORT", driver_port)
        for workload in ("register", "set"):
            result = _run_suite(r_suite.rethinkdb_test,
                                tmp_path / f"store-{workload}",
                                workload=workload, time_limit=5,
                                nodes=[host])
            assert result["results"]["valid?"] is True, result["results"]
    finally:
        if proc is not None:
            proc.kill()
            proc.wait()


# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_rabbitmq_wire_client(tmp_path, monkeypatch):
    """Scratch rabbitmq-server + the bundled AMQP 0-9-1 client:
    handshake, declare/publish/get/ack, then the queue workload through
    the suite lifecycle."""
    addr = _addr("JEPSEN_RABBITMQ_ADDR")
    server = None
    if addr is None:
        server = _find("rabbitmq-server", "JEPSEN_RABBITMQ_BIN")
        if not server:
            pytest.skip("rabbitmq-server not installed and no "
                        "JEPSEN_RABBITMQ_ADDR")

    from jepsen_tpu.suites import rabbitmq as mq_suite
    from jepsen_tpu.suites._amqp import AmqpConnection

    proc = None
    if addr is not None:
        host, port = addr
    else:
        host = "127.0.0.1"
        port = _free_port()
        env = dict(os.environ,
                   RABBITMQ_NODENAME=f"jepsen{port}@localhost",
                   RABBITMQ_NODE_PORT=str(port),
                   RABBITMQ_NODE_IP_ADDRESS="127.0.0.1",
                   RABBITMQ_DIST_PORT=str(_free_port()),
                   RABBITMQ_MNESIA_BASE=str(tmp_path / "mnesia"),
                   RABBITMQ_LOG_BASE=str(tmp_path / "log"),
                   RABBITMQ_PID_FILE=str(tmp_path / "pid"),
                   RABBITMQ_ENABLED_PLUGINS_FILE=str(tmp_path / "plugins"))
        proc = subprocess.Popen([server], env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    try:
        _await_port(port, proc, timeout_s=90, host=host)
        conn = _await_conn(lambda: AmqpConnection(host, port),
                           proc, timeout_s=60, dt=0.5)
        q = f"smoke_{os.urandom(4).hex()}"   # rerun-safe queue
        conn.confirm_select()
        conn.queue_declare(q)
        conn.publish(q, b"42")
        tag, body = conn.get(q)
        assert body == b"42"
        conn.ack(tag)

        monkeypatch.setattr(mq_suite, "PORT", port)
        result = _run_suite(mq_suite.rabbitmq_test, tmp_path / "store",
                            workload="queue", time_limit=5, nodes=[host])
        assert result["results"]["valid?"] is True, result["results"]
    finally:
        if proc is not None:
            proc.kill()
            proc.wait()


# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_cassandra_cql_wire_client(tmp_path):
    """Scratch single-node Cassandra + the from-scratch CQL v4 client:
    STARTUP, DDL, typed Rows decode, counters, and LWT — the protocol
    surface the YCQL suite rides, against a real CQL server (the
    scripted-server tests' semantics check). JEPSEN_CASSANDRA_ADDR
    targets an already-running server (docker/realdb) instead of
    spawning one."""
    addr = _addr("JEPSEN_CASSANDRA_ADDR")
    cassandra_bin = None
    if addr is None:
        cassandra_bin = _find("cassandra", "JEPSEN_CASSANDRA_BIN")
        if not cassandra_bin:
            pytest.skip("cassandra not installed and no "
                        "JEPSEN_CASSANDRA_ADDR")

    from jepsen_tpu.suites._cql_client import CQLConnection

    if addr is not None:
        host, port = addr
        ks = f"smoke_{os.urandom(4).hex()}"   # rerun-safe keyspace
        conn = _await_conn(lambda: CQLConnection(host, port), None,
                           timeout_s=60, dt=0.5)
        try:
            conn.query(f"CREATE KEYSPACE {ks} WITH replication = "
                       "{'class': 'SimpleStrategy', "
                       "'replication_factor': 1}")
            conn.query(f"CREATE TABLE {ks}.t (k INT PRIMARY KEY, v INT)")
            conn.query(f"INSERT INTO {ks}.t (k, v) VALUES (1, 10)")
            rows = conn.query(f"SELECT k, v FROM {ks}.t WHERE k = 1")
            assert rows == [{"k": 1, "v": 10}]
            rows = conn.query(
                f"UPDATE {ks}.t SET v = 11 WHERE k = 1 IF v = 10")
            assert rows and rows[0].get("[applied]") is True
            rows = conn.query(
                f"UPDATE {ks}.t SET v = 12 WHERE k = 1 IF v = 99")
            assert rows and rows[0].get("[applied]") is False
            conn.query(f"CREATE TABLE {ks}.c (id INT PRIMARY KEY, "
                       "n COUNTER)")
            conn.query(f"UPDATE {ks}.c SET n = n + 5 WHERE id = 0")
            rows = conn.query(f"SELECT n FROM {ks}.c WHERE id = 0")
            assert rows[0]["n"] == 5
        finally:
            try:
                conn.query(f"DROP KEYSPACE {ks}")
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
        return

    port = _free_port()
    storage_port = _free_port()
    conf = tmp_path / "conf"
    conf.mkdir()
    # partitioner + commitlog_sync are REQUIRED directives; the
    # host:port seed form needs Cassandra 4.0+
    (conf / "cassandra.yaml").write_text(f"""
cluster_name: jepsen-smoke
num_tokens: 16
partitioner: org.apache.cassandra.dht.Murmur3Partitioner
commitlog_sync: periodic
commitlog_sync_period_in_ms: 10000
commitlog_directory: {tmp_path}/commitlog
data_file_directories: [{tmp_path}/data]
saved_caches_directory: {tmp_path}/caches
hints_directory: {tmp_path}/hints
listen_address: 127.0.0.1
rpc_address: 127.0.0.1
native_transport_port: {port}
storage_port: {storage_port}
start_native_transport: true
endpoint_snitch: SimpleSnitch
seed_provider:
  - class_name: org.apache.cassandra.locator.SimpleSeedProvider
    parameters:
      - seeds: "127.0.0.1:{storage_port}"
""")
    env = dict(os.environ, CASSANDRA_CONF=str(conf))
    proc = subprocess.Popen([cassandra_bin, "-f"], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        _await_port(port, proc, timeout_s=180)
        conn = _await_conn(lambda: CQLConnection("127.0.0.1", port),
                           proc, timeout_s=60, dt=0.5)
        conn.query("CREATE KEYSPACE smoke WITH replication = "
                   "{'class': 'SimpleStrategy', 'replication_factor': 1}")
        conn.query("CREATE TABLE smoke.t (k INT PRIMARY KEY, v INT)")
        conn.query("INSERT INTO smoke.t (k, v) VALUES (1, 10)")
        rows = conn.query("SELECT k, v FROM smoke.t WHERE k = 1")
        assert rows == [{"k": 1, "v": 10}]
        # LWT: applied and not-applied both decode
        rows = conn.query("UPDATE smoke.t SET v = 11 WHERE k = 1 IF v = 10")
        assert rows and rows[0].get("[applied]") is True
        rows = conn.query("UPDATE smoke.t SET v = 12 WHERE k = 1 IF v = 99")
        assert rows and rows[0].get("[applied]") is False
        # counter column decode
        conn.query("CREATE TABLE smoke.c (id INT PRIMARY KEY, n COUNTER)")
        conn.query("UPDATE smoke.c SET n = n + 5 WHERE id = 0")
        rows = conn.query("SELECT n FROM smoke.c WHERE id = 0")
        assert rows[0]["n"] == 5
    finally:
        proc.kill()
        proc.wait()


# ---------------------------------------------------------------------------

@pytest.mark.realdb
def test_realdb_aerospike_wire_client(tmp_path, monkeypatch):
    """Scratch single-node asd + the from-scratch binary protocol
    client: info, put/get, generation CAS, string append, then the
    register workload through the suite lifecycle."""
    addr = _addr("JEPSEN_AEROSPIKE_ADDR")
    asd = None
    if addr is None:
        asd = _find("asd", "JEPSEN_ASD_BIN")
        if not asd:
            pytest.skip("asd (aerospike) not installed and no "
                        "JEPSEN_AEROSPIKE_ADDR")

    from jepsen_tpu.suites import aerospike as as_suite
    from jepsen_tpu.suites._aerospike import AerospikeConnection

    if addr is not None:
        host, port = addr
        # docker images ship namespace "test"; scratch daemons use the
        # suite's "jepsen"
        ns = os.environ.get("JEPSEN_AEROSPIKE_NS", "test")
        _aerospike_body(None, host, port, ns, as_suite,
                        AerospikeConnection, tmp_path, monkeypatch)
        return

    port = _free_port()
    conf = tmp_path / "asd.conf"
    conf.write_text(f"""
service {{
    work-directory {tmp_path}
    pidfile {tmp_path}/asd.pid
    proto-fd-max 1024
}}
logging {{
    file {tmp_path}/asd.log {{ context any info }}
}}
network {{
    service {{ address 127.0.0.1
               port {port} }}
    heartbeat {{ mode mesh
                 address 127.0.0.1
                 port {_free_port()}
                 interval 150
                 timeout 10 }}
    fabric {{ port {_free_port()} }}
    info {{ port {_free_port()} }}
}}
namespace jepsen {{
    replication-factor 1
    storage-engine memory {{ data-size 128M }}
}}
""")
    proc = subprocess.Popen([asd, "--foreground", "--config-file",
                             str(conf)],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        _aerospike_body(proc, "127.0.0.1", port, "jepsen", as_suite,
                        AerospikeConnection, tmp_path, monkeypatch)
    finally:
        proc.kill()
        proc.wait()


def _aerospike_body(proc, host, port, ns, as_suite, AerospikeConnection,
                    tmp_path, monkeypatch):
    """Protocol assertions + suite lifecycle, shared by the scratch-asd
    and ADDR (docker) modes. Keys are randomized so a reused server
    (docker) stays rerun-safe."""
    import random

    _await_port(port, proc, timeout_s=60, host=host)
    k1, k2, k3 = random.sample(range(1 << 30), 3)

    def first_contact():
        c = AerospikeConnection(host, port, namespace=ns,
                                set_name="registers")
        c.put(k1, 10)  # retried too: partitions settle after the port
        return c

    conn = _await_conn(first_contact, proc)
    value, gen = conn.get(k1)
    assert value == 10
    applied = conn.put(k1, 11, generation=gen)
    assert applied
    stale = conn.put(k1, 12, generation=gen)  # gen moved on: rejected
    assert not stale
    conn.append(k2, " 7")
    conn.append(k2, " 9")
    assert conn.get_string(k2).split() == ["7", "9"]
    conn.incr(k3, 4)
    value, _ = conn.get(k3)
    assert value == 4

    monkeypatch.setattr(as_suite, "PORT", port)
    monkeypatch.setattr(as_suite, "NAMESPACE", ns)
    result = _run_suite(as_suite.aerospike_test, tmp_path / "store",
                        workload="register", time_limit=5, nodes=[host])
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.realdb
def test_hazelcast_real_member_cp_lock(tmp_path, monkeypatch):
    """A real 3-member Hazelcast cluster (hz-start from a local
    distribution; the CP subsystem needs >= 3 CP members) served the CP
    lock workload through the from-scratch binary protocol client.
    Needs JEPSEN_HAZELCAST_HOME pointing at an unpacked hazelcast-5.x
    distribution (or hz-start on PATH) and a JVM."""
    import glob

    from jepsen_tpu.suites import hazelcast as hz_suite

    addr = _addr("JEPSEN_HAZELCAST_ADDR")
    if addr is not None:
        # docker/realdb mode: a CP-enabled cluster is already up
        host, port = addr
        monkeypatch.setattr(hz_suite, "PORT", port)

        def factory():
            c = hz_suite.HzCPClient("lock").open({}, host)
            out = c.invoke({}, {"f": "acquire", "process": 0,
                                "value": None})
            assert out["type"] == "ok" and out["value"] > 0, out
            assert c.invoke({}, {"f": "release", "process": 0,
                                 "value": None})["type"] == "ok"
            c.close({})
            return True

        assert _await_conn(factory, None, timeout_s=180.0)
        return

    home = os.environ.get("JEPSEN_HAZELCAST_HOME")
    binary = (glob.glob(os.path.join(home, "bin", "hz-start"))[0]
              if home and glob.glob(os.path.join(home, "bin", "hz-start"))
              else shutil.which("hz-start"))
    if not binary:
        pytest.skip("no hazelcast distribution available and no "
                    "JEPSEN_HAZELCAST_ADDR")

    ports = [_free_port() for _ in range(3)]
    members = ", ".join(f"127.0.0.1:{p}" for p in ports)
    procs = []
    try:
        for i, port in enumerate(ports):
            cfg = tmp_path / f"hazelcast-{i}.yaml"
            cfg.write_text(hz_suite.CONFIG_YAML % {
                "port": port, "members": members,
                "queue": hz_suite.QUEUE, "cp_members": 3})
            env = dict(os.environ, HAZELCAST_CONFIG=str(cfg))
            procs.append(subprocess.Popen(
                [binary], env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        for port, proc in zip(ports, procs):
            _await_port(port, proc, timeout_s=180.0)
        monkeypatch.setattr(hz_suite, "PORT", ports[0])

        def factory():
            # CP discovery completes asynchronously after boot: retried
            # by _await_conn until the lock round-trips
            c = hz_suite.HzCPClient("lock").open({}, "127.0.0.1")
            out = c.invoke({}, {"f": "acquire", "process": 0,
                                "value": None})
            assert out["type"] == "ok" and out["value"] > 0, out
            assert c.invoke({}, {"f": "release", "process": 0,
                                 "value": None})["type"] == "ok"
            c.close({})
            return True

        assert _await_conn(factory, procs[0], timeout_s=180.0)
    finally:
        for proc in procs:
            proc.kill()
        for proc in procs:
            proc.wait(timeout=10)
