"""Generator semantics tests — exact-output assertions against the
deterministic simulator, mirroring the reference's strategy
(jepsen/test/jepsen/generator_test.clj)."""
import jepsen_tpu.generator as gen
from jepsen_tpu.generator import NEMESIS, PENDING
from jepsen_tpu.generator.simulate import (
    default_context, invocations, perfect, perfect_info, quick,
)
from jepsen_tpu.utils import secs_to_nanos

TEST = {"concurrency": 2}


def ops_of(history, keys=("f", "value", "type")):
    return [tuple(op.get(k) for k in keys) for op in history]


def test_dict_emits_exactly_one_op():
    h = quick(TEST, {"f": "read"})
    assert ops_of(h) == [("read", None, "invoke"), ("read", None, "ok")]


def test_list_emits_in_order():
    h = quick(TEST, [{"f": "a"}, {"f": "b"}, {"f": "c"}])
    fs = [op["f"] for op in invocations(h)]
    assert fs == ["a", "b", "c"]


def test_fn_generator_repeats_until_none():
    # fns must be (speculation-tolerant) functions of test/ctx: combinators
    # may probe them and discard results (generator.clj:575-599)
    def g(test, ctx):
        return {"f": "w", "value": "x"}

    h = quick(TEST, gen.limit(3, g))
    assert [op["value"] for op in invocations(h)] == ["x", "x", "x"]


def test_fn_generator_exhausts_on_none():
    def g(test, ctx):
        if ctx.time >= secs_to_nanos(2.0):
            return None
        return {"f": "w"}

    # fn is consulted at ctx.time (before delay re-stamps op time), so ops
    # scheduled for t=0,1,2s emit; the t>=2s consult returns None.
    h = quick(TEST, gen.delay(1.0, g))
    assert len(invocations(h)) == 3


def test_limit_and_once():
    h = quick(TEST, gen.limit(2, gen.repeat({"f": "read"})))
    assert len(invocations(h)) == 2
    h = quick(TEST, gen.once(gen.repeat({"f": "read"})))
    assert len(invocations(h)) == 1


def test_repeat_infinite_with_limit():
    h = quick(TEST, gen.limit(5, gen.repeat({"f": "read"})))
    assert len(invocations(h)) == 5
    assert all(op["f"] == "read" for op in invocations(h))


def test_repeat_n():
    h = quick(TEST, gen.repeat(3, {"f": "read"}))
    assert len(invocations(h)) == 3


def test_cycle():
    h = quick(TEST, gen.cycle([{"f": "a"}, {"f": "b"}], times=2))
    assert [op["f"] for op in invocations(h)] == ["a", "b", "a", "b"]


def test_map_transforms_ops():
    h = quick(TEST, gen.gen_map(lambda op: {**op, "f": "X"}, [{"f": "a"}, {"f": "b"}]))
    assert [op["f"] for op in invocations(h)] == ["X", "X"]


def test_filter():
    g = gen.gen_filter(lambda op: op["value"] % 2 == 0,
                       [{"f": "w", "value": v} for v in range(6)])
    h = quick(TEST, g)
    assert [op["value"] for op in invocations(h)] == [0, 2, 4]


def test_mix_draws_from_all():
    g = gen.mix([gen.repeat({"f": "a"}), gen.repeat({"f": "b"})])
    h = quick(TEST, gen.limit(100, g))
    fs = {op["f"] for op in invocations(h)}
    assert fs == {"a", "b"}


def test_clients_excludes_nemesis():
    h = quick(TEST, gen.clients(gen.limit(10, gen.repeat({"f": "read"}))))
    assert all(op["process"] != NEMESIS for op in h)


def test_nemesis_gen_only_nemesis():
    h = quick(TEST, gen.nemesis_gen(gen.limit(3, gen.repeat({"f": "start"}))))
    assert all(op["process"] == NEMESIS for op in h)


def test_each_thread_runs_once_per_thread():
    h = quick(TEST, gen.each_thread({"f": "hi"}))
    procs = sorted((op["process"] for op in invocations(h)), key=str)
    # 2 client threads + nemesis
    assert len(procs) == 3
    assert NEMESIS in procs or "nemesis" in procs


def test_reserve_partitions_threads():
    g = gen.reserve(1, gen.limit(5, gen.repeat({"f": "a"})),
                    gen.limit(5, gen.repeat({"f": "b"})))
    h = perfect(TEST, gen.clients(g))
    for op in invocations(h):
        if op["f"] == "a":
            assert op["process"] == 0
        else:
            assert op["process"] == 1


def test_stagger_spaces_ops_out():
    g = gen.stagger(1.0, gen.limit(10, gen.repeat({"f": "read"})))
    h = quick(TEST, g)
    times = [op["time"] for op in invocations(h)]
    assert times == sorted(times)
    # mean gap should be roughly 1s (uniform [0, 2s)); loose bound
    gaps = [b - a for a, b in zip(times, times[1:])]
    assert 0 < sum(gaps) / len(gaps) < secs_to_nanos(2)


def test_delay_enforces_interval():
    g = gen.delay(1.0, gen.limit(4, gen.repeat({"f": "read"})))
    h = quick(TEST, g)
    times = [op["time"] for op in invocations(h)]
    gaps = [b - a for a, b in zip(times, times[1:])]
    assert all(g >= secs_to_nanos(1.0) for g in gaps)


def test_time_limit_cuts_off():
    g = gen.time_limit(5.0, gen.delay(1.0, gen.repeat({"f": "read"})))
    h = quick(TEST, g)
    n = len(invocations(h))
    assert 4 <= n <= 6


def test_phases_synchronize():
    g = gen.phases(gen.limit(4, gen.repeat({"f": "a"})),
                   gen.limit(2, gen.repeat({"f": "b"})))
    h = perfect(TEST, g)
    inv = invocations(h)
    # all a-invocations precede all b-invocations
    last_a = max(i for i, op in enumerate(inv) if op["f"] == "a")
    first_b = min(i for i, op in enumerate(inv) if op["f"] == "b")
    assert last_a < first_b
    # and the first b starts only after every a completed
    a_completions = [op["time"] for op in h if op["f"] == "a" and op["type"] == "ok"]
    b_invokes = [op["time"] for op in h if op["f"] == "b" and op["type"] == "invoke"]
    assert max(a_completions) <= min(b_invokes)


def test_then_orders():
    g = gen.then(gen.once(gen.repeat({"f": "b"})), gen.once(gen.repeat({"f": "a"})))
    h = perfect(TEST, g)
    assert [op["f"] for op in invocations(h)] == ["a", "b"]


def test_until_ok_stops_after_first_ok():
    g = gen.until_ok(gen.repeat({"f": "read"}))
    h = perfect(TEST, g)
    # stops quickly: at most a handful of invokes (those already in flight)
    assert 1 <= len(invocations(h)) <= 3


def test_flip_flop_alternates():
    g = gen.limit(6, gen.flip_flop(gen.repeat({"f": "start"}), gen.repeat({"f": "stop"})))
    h = quick(TEST, g)
    assert [op["f"] for op in invocations(h)] == ["start", "stop"] * 3


def test_process_limit():
    # perfect_info crashes every op, so each op consumes a fresh process
    g = gen.process_limit(4, gen.clients(gen.repeat({"f": "read"})))
    h = perfect_info(TEST, g)
    procs = {op["process"] for op in invocations(h)}
    assert len(procs) <= 4


def test_crashed_process_renumbering():
    h = perfect_info(TEST, gen.clients(gen.limit(6, gen.repeat({"f": "read"}))))
    procs = [op["process"] for op in invocations(h)]
    # processes never repeat after a crash; fresh ids = old + concurrency
    assert len(set(procs)) == len(procs)
    assert all(p % 2 in (0, 1) for p in procs)


def test_validate_accepts_good_gen():
    h = quick(TEST, gen.validate(gen.limit(3, gen.repeat({"f": "read"}))))
    assert len(invocations(h)) == 3


def test_any_picks_soonest():
    g = gen.any_gen(gen.repeat({"f": "slow", "time": secs_to_nanos(10)}),
                    gen.limit(3, gen.repeat({"f": "fast"})))
    h = quick(TEST, gen.limit(3, g))
    assert [op["f"] for op in invocations(h)] == ["fast", "fast", "fast"]


def test_context_free_threads():
    ctx = default_context()
    assert ctx.free_threads == frozenset([0, 1, NEMESIS])
    ctx2 = ctx.busy_thread(0)
    assert ctx2.free_threads == frozenset([1, NEMESIS])
    assert ctx.free_threads == frozenset([0, 1, NEMESIS])  # immutable


def test_next_process():
    ctx = default_context()
    assert gen.next_process(ctx, 0) == 2
    assert gen.next_process(ctx, NEMESIS) == NEMESIS


def test_generator_throughput():
    """The pure scheduler must stay cheap (reference: >20k ops/sec,
    generator.clj:67-70). We assert a sane floor for the Python build."""
    import time
    g = gen.limit(20_000, gen.repeat({"f": "read"}))
    t0 = time.monotonic()
    h = quick({"concurrency": 10}, g)
    dt = time.monotonic() - t0
    assert len(invocations(h)) == 20_000
    assert dt < 20.0, f"generator too slow: {20_000/dt:.0f} ops/sec"
