"""Anomaly forensics: localization differentials, witness shrink,
artifacts, and surfaces (doc/observability.md "Anomaly forensics").

The acceptance bar: on a planted-anomaly history, every matrix-family
backend — single-device, segmented, sharded-mesh, live screen — reports
the SAME exact ``first_anomaly_op`` as the exact CPU frontier, writes
``anomaly.json`` + a witness timeline, and the web run page links both.
"""
import json

import numpy as np
import pytest

pytestmark = pytest.mark.explain

N_PROCS, N_VALUES = 3, 5


def _history(n_blocks, plant_anomaly_at=None, seed=3, with_times=False):
    """Write/read blocks over a rand-int-5 register domain; planting an
    anomaly makes one read observe a value that was NOT the concurrent
    or previous write (non-linearizable at that read's return)."""
    rng = np.random.default_rng(seed)
    ops = []
    t = 0
    for b in range(n_blocks):
        p = int(rng.integers(N_PROCS))
        v = int(rng.integers(N_VALUES))
        p2 = int(rng.integers(N_PROCS))
        rv = (v + 1) % N_VALUES if b == plant_anomaly_at else v
        block = [
            {"process": p, "type": "invoke", "f": "write", "value": v},
            {"process": p, "type": "ok", "f": "write", "value": v},
            {"process": p2, "type": "invoke", "f": "read", "value": None},
            {"process": p2, "type": "ok", "f": "read", "value": rv},
        ]
        for op in block:
            if with_times:
                op["time"] = t * 1_000_000
                t += 1
            ops.append(op)
    return ops


def _stream(history):
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    return encode_register_ops(history)


def _cpu(history):
    from jepsen_tpu.checker.linear_cpu import check_stream
    return check_stream(_stream(history))


# ---------------------------------------------------------------------------
# localization differentials (the acceptance bar's bit-identity half)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("plant", [0, 1, 700, 1500, 2047])
def test_matrix_localize_matches_frontier(plant):
    """Single-device: the device bisection's failed event/op must be
    bit-identical to the exact CPU frontier's first rejection."""
    from jepsen_tpu.ops.jitlin import matrix_localize

    h = _history(2048, plant_anomaly_at=plant)
    cpu = _cpu(h)
    assert cpu.valid is False
    loc = matrix_localize(_stream(h))
    assert loc is not None
    assert loc.failed_event == cpu.failed_event
    assert loc.failed_op_index == cpu.failed_op_index
    assert loc.bisect_steps >= 1


def test_matrix_localize_valid_returns_none():
    from jepsen_tpu.ops.jitlin import matrix_localize

    h = _history(2048)
    assert _cpu(h).valid is True
    assert matrix_localize(_stream(h)) is None


def test_matrix_localize_segmented_chain():
    """Segmented backend: a failing segment localizes against the
    carried operator product (tot0) and reports the same absolute op as
    the CPU frontier over the whole chain — no chain re-scan."""
    from jepsen_tpu.ops import jitlin
    from jepsen_tpu.ops.jitlin import _slice_stream

    h = _history(4096, plant_anomaly_at=3000)
    s = _stream(h)
    cpu = _cpu(h)
    cuts = jitlin.quiescent_cuts(np.asarray(s.kind), 1 << 13)
    assert len(cuts) >= 2, "chain must span several segments"
    tot, base, found = None, 0, None
    for end in cuts:
        seg = _slice_stream(s, base, end)
        alive, inexact, tot2 = jitlin.matrix_check_resume(
            seg, tot, n_slots=s.n_slots, num_states=len(s.intern))
        assert not bool(np.asarray(inexact).any())
        if not bool(np.asarray(alive).all()):
            loc = jitlin.matrix_localize(seg, tot0=tot,
                                         num_states=len(s.intern),
                                         n_slots=s.n_slots)
            assert loc is not None
            found = (base + loc.failed_event, loc.failed_op_index)
            break
        tot, base = tot2, end
    assert found == (cpu.failed_event, cpu.failed_op_index)


def test_matrix_localize_sharded_mesh_checker():
    """Sharded-mesh backend: a checker forced onto the mesh rung
    settles the planted anomaly at the matrix rung with the exact CPU
    op — no demotion to the scan just to find it."""
    import jax

    from jepsen_tpu.checker.linearizable import LinearizableChecker

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 virtual)")
    h = _history(2048, plant_anomaly_at=1500)
    cpu = _cpu(h)
    res = LinearizableChecker(accelerator="tpu").check(
        {}, h, {"checker_sharded": True})
    assert res["valid?"] is False
    assert res["algorithm"] == "jitlin-tpu-matrix-sharded", res["algorithm"]
    assert res["explain"]["first-anomaly-op"] == cpu.failed_op_index


def test_ladder_settles_invalid_at_matrix_rung():
    """The single-device matrix rung attaches localization to an
    invalid verdict instead of demoting: algorithm stays matrix, the
    failed op is the frontier's, and the telemetry backend counter
    names the matrix rung as the settler."""
    from jepsen_tpu import telemetry
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        h = _history(2048, plant_anomaly_at=700)
        cpu = _cpu(h)
        res = LinearizableChecker(accelerator="tpu").check(
            {}, h, {"checker_sharded": False})
        assert res["valid?"] is False
        assert res["algorithm"] == "jitlin-tpu-matrix", res["algorithm"]
        assert res["failed-op"] == h[cpu.failed_op_index]
        assert res["explain"]["first-anomaly-op"] == cpu.failed_op_index
        snap = {(r["name"], tuple(sorted((r.get("labels") or {}).items())))
                for r in reg.snapshot()}
        assert ("checker_backend_total",
                (("backend", "jitlin-tpu-matrix"),)) in snap
        names = {r["name"] for r in reg.snapshot()}
        assert {"explain_bisect_steps", "explain_latency_seconds",
                "witness_ops"} <= names
    finally:
        telemetry.install(prev)


def test_explain_off_restores_demotion_path():
    """``explain: False`` restores the old behavior: the matrix rung
    demotes on invalid and the frontier scan settles with the same
    exact op — the knob changes cost, never the verdict."""
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    h = _history(2048, plant_anomaly_at=700)
    cpu = _cpu(h)
    res = LinearizableChecker(accelerator="tpu").check(
        {"explain": False}, h, {"checker_sharded": False})
    assert res["valid?"] is False
    assert res["algorithm"] != "jitlin-tpu-matrix"
    assert "explain" not in res
    assert res["failed-op"] == h[cpu.failed_op_index]


def test_live_screen_reports_exact_first_anomaly():
    """Live-screen backend: the daemon's matrix screen reports the
    exact first_anomaly_op itself (no deferral to the CPU frontier
    rung), matching the frontier bit-for-bit."""
    from jepsen_tpu.live.sessions import LinearLiveSession

    h = _history(2048, plant_anomaly_at=1800)
    cpu = _cpu(h)
    sess = LinearLiveSession(accelerator="tpu")
    for op in h:
        sess.add(op)
    v = sess.verdict()
    assert v["valid_so_far"] is False
    assert v["backend"] == "pallas-matrix", v
    assert v["first_anomaly_op"] == cpu.failed_op_index
    # the latch answers later polls without re-screening, and finalize's
    # exact frontier pass agrees with the screen's localization
    v2 = sess.verdict()
    assert v2["first_anomaly_op"] == cpu.failed_op_index
    final = sess.finalize()
    assert final["valid?"] is False
    assert final["failed-op-index"] == cpu.failed_op_index


def test_localize_keys_distributed_single_process():
    """The multi-host forensics surface, exercised single-process (the
    allgather degenerates): invalid keys localize, valid keys don't
    appear, and the events match the CPU frontier."""
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.parallel.distributed import localize_keys_distributed

    streams = [
        _stream(_history(700, plant_anomaly_at=600, seed=10)),
        _stream(_history(700, seed=11)),
        _stream(_history(700, plant_anomaly_at=33, seed=12)),
    ]
    out = localize_keys_distributed(streams, [0, 2])
    assert set(out) == {0, 2}
    for i in (0, 2):
        cpu = check_stream(streams[i])
        assert out[i] == (cpu.failed_event, cpu.failed_op_index)


# ---------------------------------------------------------------------------
# witness shrink
# ---------------------------------------------------------------------------

def test_witness_shrink_is_bounded_and_keeps_fatal():
    from jepsen_tpu.checker.explain import explain_stream
    from jepsen_tpu.checker.linear_cpu import check_stream

    h = _history(8192, plant_anomaly_at=2000)
    s = _stream(h)
    cpu = check_stream(s)
    f = explain_stream(s, max_witness_ops=2, shrink_budget=64)
    assert f is not None
    assert f["backend"] == "matrix-bisect"
    assert f["first_anomaly"]["op_index"] == cpu.failed_op_index
    wit = f["witness"]
    # the fatal op's invoke is always part of the witness
    assert cpu.failed_op_index - 1 in wit["op_indices"]
    assert wit["candidates"] <= 64
    assert len(wit["op_indices"]) <= wit["window_op_count"]
    # the planted anomaly needs only a handful of ops to reproduce...
    assert len(wit["op_indices"]) < wit["window_op_count"]
    # ...but "minimal" is a PROOF: a shrink stopped early by the
    # max_witness_ops floor was never verified irreducible
    assert wit["minimal"] is False


def test_explain_stream_cpu_fallback():
    """Out of the matrix regime (short history) the forensics fall back
    to the exact CPU frontier: same first anomaly, frontier-derived
    witness, no device bisection."""
    from jepsen_tpu.checker.explain import explain_stream

    h = _history(40, plant_anomaly_at=35)
    s = _stream(h)
    cpu = _cpu(h)
    f = explain_stream(s)
    assert f is not None
    assert f["backend"] == "frontier-cpu"
    assert f["first_anomaly"]["op_index"] == cpu.failed_op_index
    assert cpu.failed_op_index in f["witness"]["op_indices"]


def test_explain_stream_valid_returns_none():
    from jepsen_tpu.checker.explain import explain_stream

    assert explain_stream(_stream(_history(40))) is None


# ---------------------------------------------------------------------------
# artifacts + surfaces
# ---------------------------------------------------------------------------

def _run_checker(tmp_path, h, name="explain-run", ts="20260803T000000"):
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    test = {"name": name, "start_time": ts, "store_dir": str(tmp_path)}
    res = LinearizableChecker(accelerator="tpu").check(test, h, {})
    return test, res, tmp_path / name / ts


def test_invalid_check_writes_anomaly_artifacts(tmp_path):
    h = _history(2048, plant_anomaly_at=1337, with_times=True)
    cpu = _cpu(h)
    test, res, run_dir = _run_checker(tmp_path, h)
    assert res["valid?"] is False
    a = json.loads((run_dir / "anomaly.json").read_text())
    assert a["first_anomaly"]["op_index"] == cpu.failed_op_index
    assert a["first_anomaly"]["f"] == "read"
    # the fatal op_index is the RETURN's index — its detail must still
    # resolve the full invoke+completion pair (schema promise)
    assert a["first_anomaly"]["completion_type"] == "ok"
    assert a["first_anomaly"]["latency_ns"] == 1_000_000
    assert a["witness"]["ops"], "per-op detail must be present"
    assert "fault_windows" in a
    html = (run_dir / "witness-timeline.html").read_text()
    assert "fatal" in html and "witness" in html
    assert sorted(res["explain"]["artifacts"]) == [
        "anomaly.json", "witness-timeline.html"]


def test_web_run_page_links_explain(tmp_path):
    import threading
    import urllib.request

    from jepsen_tpu import web

    h = _history(2048, plant_anomaly_at=1337, with_times=True)
    test, res, run_dir = _run_checker(tmp_path, h)
    server = web.make_server(store_dir=str(tmp_path))
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        host, port = server.server_address
        base = f"http://{host}:{port}"
        page = urllib.request.urlopen(
            f"{base}/{test['name']}/{test['start_time']}/",
            timeout=10).read().decode()
        assert "anomaly.json" in page
        assert "witness-timeline.html" in page
        assert "first anomaly" in page           # the Explain panel
        home = urllib.request.urlopen(base, timeout=10).read().decode()
        assert "anomaly.json" in home            # artifact links column
        # the rendered timeline serves as html (clickable, not a blob)
        r = urllib.request.urlopen(
            f"{base}/{test['name']}/{test['start_time']}/"
            "witness-timeline.html", timeout=10)
        assert r.headers.get("Content-Type", "").startswith("text/html")
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)


def test_cli_explain_rederives_offline(tmp_path, capsys):
    from jepsen_tpu import cli, store

    h = _history(2048, plant_anomaly_at=900, with_times=True)
    cpu = _cpu(h)
    test = {"name": "explain-cli", "start_time": "20260803T000001",
            "store_dir": str(tmp_path), "history": h}
    store.save_1(test)
    run_dir = tmp_path / "explain-cli" / "20260803T000001"
    rc = cli.noop_main(["explain", str(run_dir)])
    out = capsys.readouterr().out
    # validity_exit_code convention: an invalid run exits EXIT_INVALID
    assert rc == cli.EXIT_INVALID, out
    assert f"first anomaly at op {cpu.failed_op_index}" in out
    a = json.loads((run_dir / "anomaly.json").read_text())
    assert a["first_anomaly"]["op_index"] == cpu.failed_op_index
    assert (run_dir / "witness-timeline.html").exists()


def test_cli_explain_valid_history(tmp_path, capsys):
    from jepsen_tpu import cli, store

    test = {"name": "explain-ok", "start_time": "20260803T000002",
            "store_dir": str(tmp_path), "history": _history(40)}
    store.save_1(test)
    rc = cli.noop_main(
        ["explain", str(tmp_path / "explain-ok" / "20260803T000002")])
    assert rc == cli.EXIT_OK
    assert "nothing to explain" in capsys.readouterr().out


def test_cli_explain_wr_run_routes_to_rw_register(tmp_path, capsys):
    """A stored rw-register (wr) run also carries f='txn' — the offline
    route must sniff the mop dialect like the live daemon and feed the
    rw_register checker, not crash in list-append."""
    from jepsen_tpu import cli, store

    h = [
        {"process": 0, "type": "invoke", "f": "txn",
         "value": [["w", "x", 1]], "time": 0},
        {"process": 0, "type": "ok", "f": "txn",
         "value": [["w", "x", 1]], "time": 1},
        {"process": 1, "type": "invoke", "f": "txn",
         "value": [["r", "x", None]], "time": 2},
        {"process": 1, "type": "ok", "f": "txn",
         "value": [["r", "x", 1]], "time": 3},
    ]
    test = {"name": "explain-wr", "start_time": "20260803T000006",
            "store_dir": str(tmp_path), "history": h}
    store.save_1(test)
    rc = cli.noop_main(
        ["explain", str(tmp_path / "explain-wr" / "20260803T000006")])
    out = capsys.readouterr().out
    assert rc == cli.EXIT_OK, out
    assert "nothing to explain" in out


def test_elle_artifacts_witness_timeline(tmp_path):
    """Elle cycle explanations gain the same witness-window timeline."""
    from jepsen_tpu.elle import artifacts

    history = [
        {"index": 0, "type": "invoke", "process": 0, "f": "txn",
         "value": [["append", 1, 10]], "time": 0},
        {"index": 1, "type": "ok", "process": 0, "f": "txn",
         "value": [["append", 1, 10]], "time": 1},
        {"index": 2, "type": "invoke", "process": 1, "f": "txn",
         "value": [["r", 1, None]], "time": 2},
        {"index": 3, "type": "ok", "process": 1, "f": "txn",
         "value": [["r", 1, [10]]], "time": 3},
    ]
    result = {
        "valid?": False,
        "anomalies": {"G1c": [[
            {"from": [["append", 1, 10]], "type": "wr",
             "to": [["r", 1, [10]]]},
            {"from": [["r", 1, [10]]], "type": "rw",
             "to": [["append", 1, 10]]},
        ]]},
    }
    test = {"name": "elle-wit", "start_time": "20260803T000003",
            "store_dir": str(tmp_path)}
    artifacts.write_for_test(test, result, history=history)
    d = tmp_path / "elle-wit" / "20260803T000003" / "elle"
    assert (d / "G1c.txt").exists()
    html = (d / "witness-timeline.html").read_text()
    assert "witness" in html
    assert "witness-timeline.html" in (d / "index.txt").read_text()


# ---------------------------------------------------------------------------
# satellites: timeline truncation, fault shading, knobs
# ---------------------------------------------------------------------------

def test_timeline_windowed_truncation_banner():
    from jepsen_tpu.checker import timeline

    h = _history(200, with_times=True)
    total = len(timeline.pairs(h))
    html = timeline.render({"name": "t"}, h, max_ops=50)
    assert "truncated — showing" in html
    assert f"of {total} ops" in html
    # windowed, not clipped: the LAST block's ops still render
    assert "whole run windowed" in html
    small = timeline.render({"name": "t"}, _history(5, with_times=True))
    assert "truncated" not in small


def test_batched_independent_writes_per_key_forensics(tmp_path):
    """The batched device lane (the default independent path) attaches
    per-key forensics and writes artifacts under independent/<k>,
    matching the per-key lane's lift."""
    from jepsen_tpu import independent as ind
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    h = []
    for k in range(4):
        plant = 80 if k == 2 else None
        for i, op in enumerate(_history(128, plant_anomaly_at=plant,
                                        seed=20 + k, with_times=True)):
            op = dict(op)
            if op.get("value") is not None or op["f"] == "read":
                op["value"] = [f"k{k}", op.get("value")]
            h.append(op)
    test = {"name": "ind-explain", "start_time": "20260803T000005",
            "store_dir": str(tmp_path)}
    chk = ind.checker(LinearizableChecker(accelerator="tpu"))
    r = chk.check(test, h, {})
    assert r["valid?"] is False
    assert set(r["failures"]) == {"k2"}
    bad = r["results"]["k2"]
    # the BATCHED lane settled this key (per-key fallback results carry
    # the full _finish surface instead of the bare batch verdict)
    assert "configs-max" in bad, bad
    assert "explain" in bad, bad
    key_dir = (tmp_path / "ind-explain" / "20260803T000005"
               / "independent" / "k2")
    assert (key_dir / "anomaly.json").exists()
    assert (key_dir / "witness-timeline.html").exists()
    # valid keys got no forensics dirs
    assert not (tmp_path / "ind-explain" / "20260803T000005"
                / "independent" / "k0" / "anomaly.json").exists()


def test_render_witness_omits_out_of_span_open_fault():
    """An open (end_time=None) fault window starting AFTER the witness
    span is omitted like a healed one — it must not stretch the page."""
    from jepsen_tpu.checker import timeline

    h = _history(20, plant_anomaly_at=15, with_times=True)
    span_end = max(op["time"] for op in h)
    payload = {
        "first_anomaly": {"op_index": 61},
        "witness": {"op_indices": [59, 61], "context_op_indices": []},
        "fault_windows": [
            {"kind": "net", "f": "start-partition", "healed": False,
             "start_time": span_end + 10**12, "end_time": None},
            {"kind": "clock", "f": "bump", "healed": True,
             "start_time": 0, "end_time": span_end + 10**12},
        ],
    }
    html = timeline.render_witness({"name": "t"}, h, payload)
    assert "start-partition" not in html      # out of span: omitted
    assert "clock" in html                    # overlapping: drawn


def test_faults_history_windows_pairing(tmp_path):
    from jepsen_tpu.nemesis import faults as faults_mod

    reg_path = tmp_path / "faults.jsonl"
    reg = faults_mod.FaultRegistry(reg_path)
    i1 = reg.record("net", f="start-partition", value=["n1", "n2"])
    reg.record("clock", f="bump", value=500)
    reg.mark_healed(i1, via="nemesis")
    # the clock fault is healed OUTSIDE the history (crash-path replay)
    reg.mark_healed(kind="clock", via="replay")
    reg.close()
    history = [
        {"process": "nemesis", "type": "info", "f": "start-partition",
         "value": ["n1", "n2"], "time": 10 * 10**9},
        {"process": 0, "type": "invoke", "f": "read", "value": None,
         "time": 11 * 10**9},
        {"process": 0, "type": "ok", "f": "read", "value": None,
         "time": 12 * 10**9},
        {"process": "nemesis", "type": "info", "f": "stop-partition",
         "value": None, "time": 20 * 10**9},
        {"process": "nemesis", "type": "info", "f": "bump",
         "value": 500, "time": 30 * 10**9},
    ]
    rows = faults_mod.load_rows(reg_path)
    wins = faults_mod.history_windows(history, rows)
    assert len(wins) == 2
    net = next(w for w in wins if w["kind"] == "net")
    assert net["start_time"] == 10 * 10**9
    assert net["end_time"] == 20 * 10**9
    assert net["healed"] is True
    clock = next(w for w in wins if w["kind"] == "clock")
    assert clock["end_time"] is None          # no closing op in history
    assert clock["healed"] is True            # ...but the registry knows
    assert clock["via"] == "replay"


def test_perf_plots_shade_registry_windows(tmp_path):
    from jepsen_tpu import store
    from jepsen_tpu.checker import perf_plots
    from jepsen_tpu.nemesis import faults as faults_mod

    test = {"name": "shade", "start_time": "20260803T000004",
            "store_dir": str(tmp_path)}
    reg = faults_mod.FaultRegistry(
        store.path_mk(test, faults_mod.FAULTS_NAME))
    reg.record("net", f="start-partition")
    reg.mark_healed(kind="net", via="teardown")
    reg.close()
    history = [
        {"process": "nemesis", "type": "info", "f": "start-partition",
         "value": None, "time": 1 * 10**9},
        {"process": 0, "type": "invoke", "f": "read", "value": None,
         "time": 2 * 10**9},
        {"process": 0, "type": "ok", "f": "read", "value": None,
         "time": 3 * 10**9},
    ]
    wins = perf_plots.registry_fault_windows(test, history)
    assert len(wins) == 1 and wins[0]["kind"] == "net"
    out = store.path_mk(test, "latency-raw.png")
    perf_plots.point_graph(test, history, out)   # shading must not crash
    assert out.exists()


def test_explain_knob_coercion_and_preflight():
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.checker import explain as explain_mod

    # tolerant runtime coercion: garbage warns and reads as default
    assert explain_mod.enabled({"explain": "garbage"}) is True
    assert explain_mod.enabled({"explain": False}) is False
    assert explain_mod.enabled({"explain": "no"}) is False
    assert explain_mod.enabled({}) is True
    assert explain_mod.shrink_budget({"explain_shrink_budget": "64"}) == 64
    assert explain_mod.shrink_budget(
        {"explain_shrink_budget": "junk"}) == explain_mod.DEFAULT_SHRINK_BUDGET
    assert explain_mod.max_witness_ops(
        {"explain_max_witness_ops": 0}) == 1   # clamped to the floor

    # preflight is where garbage becomes an error (KNB house style)
    diags = pf._check_knobs({"explain": "garbage"})
    assert any(d.code == "KNB001" and d.path == "explain" for d in diags)
    diags = pf._check_knobs({"explain_shrink_budget": -1})
    assert any(d.code == "KNB002" for d in diags)
    diags = pf._check_knobs({"explain_max_witness_ops": "junk"})
    assert any(d.code == "KNB001" for d in diags)
    assert not pf._check_knobs({"explain": True,
                                "explain_shrink_budget": 64,
                                "explain_max_witness_ops": 8})
