"""Per-op deadlines, zombie-worker reaping, and wedge-proof shutdown
(doc/robustness.md).

The hang-injection tests carry the ``chaos`` marker and assert tight
absolute wall-clock bounds: a regression in the deadline layer must fail
fast here, not eat the tier-1 budget by actually wedging."""
import json
import threading
import time

import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.client import Client
from jepsen_tpu.utils import with_relative_time


@pytest.fixture
def metrics_registry():
    """A live telemetry registry installed for the test's duration."""
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


class HangingClient(Client):
    """Blocks in invoke (a DB behind a partition with no socket timeout)
    on selected op values, until ``release`` is set — or forever."""

    reusable = False

    def __init__(self, hang_values=(), release=None, on_invoke=None):
        self.hang_values = set(hang_values)
        self.release = release if release is not None else threading.Event()
        self.on_invoke = on_invoke
        self.log: list = []
        self._lock = threading.Lock()

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        if self.on_invoke is not None:
            self.on_invoke(op)
        if op.get("value") in self.hang_values:
            self.release.wait()
            return {**op, "type": "ok"}
        with self._lock:
            self.log.append(op.get("value"))
        return {**op, "type": "ok"}

    def close(self, test):
        with self._lock:
            self.log.append("close")


def _run(test):
    from jepsen_tpu.generator import interpreter
    with with_relative_time():
        return interpreter.run(test)


def _writes(values):
    return [{"f": "write", "value": v} for v in values]


# ---------------------------------------------------------------------------
# Knob resolution + combinator + forensic log (quick lane, no hangs)
# ---------------------------------------------------------------------------

def test_knob_resolution(monkeypatch):
    from jepsen_tpu.generator import interpreter as interp

    env = "JEPSEN_TPU_OP_TIMEOUT_S"
    monkeypatch.delenv(env, raising=False)
    # default when nothing is set
    assert interp._knob({}, "op_timeout_s", env, 600.0) == 600.0
    # environment beats the default
    monkeypatch.setenv(env, "12.5")
    assert interp._knob({}, "op_timeout_s", env, 600.0) == 12.5
    # env 0 disables
    monkeypatch.setenv(env, "0")
    assert interp._knob({}, "op_timeout_s", env, 600.0) is None
    # the test map beats the environment; explicit None/0 disable
    monkeypatch.setenv(env, "12.5")
    assert interp._knob({"op_timeout_s": 3}, "op_timeout_s", env, 600.0) == 3.0
    assert interp._knob({"op_timeout_s": None}, "op_timeout_s", env,
                        600.0) is None
    assert interp._knob({"op_timeout_s": 0}, "op_timeout_s", env,
                        600.0) is None
    # garbage in the environment OR the test map degrades to the
    # default, never raises — a bad knob must not kill the run
    monkeypatch.setenv(env, "soon")
    assert interp._knob({}, "op_timeout_s", env, 600.0) == 600.0
    assert interp._knob({"op_timeout_s": "1m"}, "op_timeout_s", env,
                        600.0) == 600.0
    assert interp._knob({"op_timeout_s": "2.5"}, "op_timeout_s", env,
                        600.0) == 2.5


def test_garbage_per_op_timeout_does_not_kill_run():
    """A generator stamping a bad timeout_s must degrade to the test
    default (warn), and a string "0" disables — never a scheduler
    crash."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.fakes import AtomClient, AtomDB

    db = AtomDB()
    ops = [{"f": "write", "value": 0, "timeout_s": "1m"},
           {"f": "write", "value": 1, "timeout_s": "0"},
           {"f": "write", "value": 2}]
    test = {"concurrency": 1, "nodes": ["n1"], "client": AtomClient(db),
            "generator": gen.clients(gen.Seq(ops)),
            "op_timeout_s": 30.0, "drain_timeout_s": 5.0, "stall_s": 0}
    history = _run(test)
    assert [op["type"] for op in history
            if op.get("type") != "invoke"] == ["ok", "ok", "ok"]


def test_op_timeout_combinator_stamps_ops():
    import jepsen_tpu.generator as gen

    g = gen.as_gen(gen.op_timeout(1.5, gen.Seq(_writes([0]))))
    ctx = gen.context({"concurrency": 1})
    op, _g2 = g.op({}, ctx)
    assert op["timeout_s"] == 1.5
    assert op["f"] == "write"


def test_forensic_log_lazy_create_and_roundtrip(tmp_path):
    from jepsen_tpu.journal import ForensicLog, read_jsonl_tolerant

    p = tmp_path / "sub" / "late.jsonl"
    log = ForensicLog(p)
    assert not p.exists()  # lazily created: clean runs leave no artifact
    log.append({"f": "write", "value": 1, "late": True})
    log.append({"f": "write", "value": object()})  # unserializable-ish
    log.close()
    log.close()  # idempotent
    rows, truncated = read_jsonl_tolerant(p)
    assert truncated is False
    assert [r["value"] for r in rows][0] == 1
    assert all(r.get("late") or isinstance(r.get("value"), str)
               for r in rows)


def test_cli_op_timeout_flag():
    import argparse

    from jepsen_tpu import cli

    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    opts = p.parse_args(["--op-timeout", "2.5", "--no-ssh"])
    test = cli.test_opts_to_test(opts, {"name": "t"})
    assert test["op_timeout_s"] == 2.5
    opts = p.parse_args(["--no-ssh"])
    test = cli.test_opts_to_test(opts, {"name": "t"})
    assert "op_timeout_s" not in test  # flag omitted: env/default applies


# ---------------------------------------------------------------------------
# Differential: deadlines enabled-but-untriggered == disabled
# ---------------------------------------------------------------------------

def _sequential_history(**knobs):
    import jepsen_tpu.generator as gen
    from jepsen_tpu.fakes import AtomClient, AtomDB

    db = AtomDB()
    ops = []
    for i in range(10):
        ops.append({"f": "write", "value": i})
        ops.append({"f": "read", "value": None})
    test = {"concurrency": 1, "nodes": ["n1"], "client": AtomClient(db),
            "generator": gen.clients(gen.Seq(ops)), "stall_s": 0, **knobs}
    return _run(test)


def test_histories_identical_deadlines_on_vs_off():
    """The deadline layer must be invisible until it fires: the same
    sequential workload produces the same history (modulo wall-clock
    stamps) with deadlines armed-but-untriggered and disabled."""
    armed = _sequential_history(op_timeout_s=30.0, drain_timeout_s=30.0)
    off = _sequential_history(op_timeout_s=0, drain_timeout_s=0)
    strip = [[{k: v for k, v in op.items() if k != "time"} for op in h]
             for h in (armed, off)]
    assert strip[0] == strip[1]
    assert len(armed) == 40  # 20 invocations + 20 completions


# ---------------------------------------------------------------------------
# Chaos: hang injection
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_hung_op_times_out_and_worker_replaced(metrics_registry):
    """One hung invoke becomes a bounded :info — op-timeout error,
    process renumbered — and a replacement worker (bumped generation)
    serves the rest of the schedule."""
    import jepsen_tpu.generator as gen

    client = HangingClient(hang_values={1})
    test = {"concurrency": 1, "nodes": ["n1"], "client": client,
            "generator": gen.clients(gen.Seq(_writes([0, 1, 2, 3]))),
            "op_timeout_s": 0.4, "drain_timeout_s": 2.0, "stall_s": 0}
    t0 = time.monotonic()
    history = _run(test)
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, f"run took {elapsed:.1f}s — deadline didn't fire"

    # the hung op is exactly one indeterminate :info with the op-timeout
    # error; it never completed ok
    done_1 = [op for op in history
              if op.get("value") == 1 and op.get("type") != "invoke"]
    assert [op["type"] for op in done_1] == ["info"]
    assert done_1[0]["error"] == ["op-timeout", 0.4]
    # the replacement worker served the remaining ops under a renumbered
    # process (crash semantics, interpreter.clj:142-157)
    ok_after = [op for op in history
                if op.get("type") == "ok" and op.get("value") in (2, 3)]
    assert len(ok_after) == 2
    assert all(op["process"] == 1 for op in ok_after)
    assert client.log[:1] == [0] and set(client.log) >= {0, 2, 3}
    reg = metrics_registry
    assert reg.counter("interpreter_op_timeouts_total",
                       labels=("f",)).value(f="write") == 1
    # the zombie never returned: still on the books at run end
    assert reg.gauge("interpreter_zombie_workers").value() == 1.0
    assert reg.counter("interpreter_late_completions_total").value() == 0


@pytest.mark.chaos
def test_late_completion_quarantined(tmp_path, metrics_registry):
    """A zombie's eventual completion is quarantined to late.jsonl —
    counted, never appended to history — and the zombie retires."""
    import jepsen_tpu.generator as gen

    release = threading.Event()

    def on_invoke(op):
        if op.get("value") == 2:
            release.set()  # wake the zombie while the run is still live

    client = HangingClient(hang_values={1}, release=release,
                           on_invoke=on_invoke)
    ops = _writes([0, 1, 2]) + [{"type": "sleep", "value": 0.4}] \
        + _writes([3])
    test = {"concurrency": 1, "nodes": ["n1"], "client": client,
            "generator": gen.clients(gen.Seq(ops)),
            "op_timeout_s": 0.4, "drain_timeout_s": 2.0, "stall_s": 0,
            "name": "late", "start_time": "20260803T000000.000",
            "store_dir": str(tmp_path)}
    t0 = time.monotonic()
    history = _run(test)
    assert time.monotonic() - t0 < 6.0

    # history holds exactly the synthesized :info for the hung op —
    # the late ok is NOT there
    done_1 = [op for op in history
              if op.get("value") == 1 and op.get("type") != "invoke"]
    assert [op["type"] for op in done_1] == ["info"]
    late_file = tmp_path / "late" / "20260803T000000.000" / "late.jsonl"
    assert late_file.exists()
    rows = [json.loads(line) for line in
            late_file.read_text().splitlines()]
    assert len(rows) == 1
    assert rows[0]["value"] == 1 and rows[0]["type"] == "ok"
    assert rows[0]["late"] is True
    reg = metrics_registry
    assert reg.counter("interpreter_late_completions_total").value() == 1
    # the zombie delivered its one op and retired: gauge back to zero
    assert reg.gauge("interpreter_zombie_workers").value() == 0.0


@pytest.mark.chaos
def test_per_op_timeout_overrides_test_default(metrics_registry):
    """An op-level timeout_s (gen.op_timeout) beats the generous test
    default — and the per-op deadline also fires inside the drain."""
    import jepsen_tpu.generator as gen

    client = HangingClient(hang_values={0})
    test = {"concurrency": 1, "nodes": ["n1"], "client": client,
            "generator": gen.clients(
                gen.op_timeout(0.3, gen.Seq(_writes([0])))),
            "op_timeout_s": 60.0, "drain_timeout_s": 5.0, "stall_s": 0}
    t0 = time.monotonic()
    history = _run(test)
    assert time.monotonic() - t0 < 4.0
    infos = [op for op in history if op.get("type") == "info"]
    assert len(infos) == 1
    assert infos[0]["error"] == ["op-timeout", 0.3]


@pytest.mark.chaos
def test_drain_deadline_abandons_stuck_op(metrics_registry):
    """With per-op deadlines disabled, the drain deadline alone unwedges
    shutdown: the stuck op gets a drain-deadline :info and the worker is
    abandoned explicitly."""
    import jepsen_tpu.generator as gen

    client = HangingClient(hang_values={1})
    test = {"concurrency": 1, "nodes": ["n1"], "client": client,
            "generator": gen.clients(gen.Seq(_writes([0, 1]))),
            "op_timeout_s": 0, "drain_timeout_s": 0.5, "stall_s": 0}
    t0 = time.monotonic()
    history = _run(test)
    assert time.monotonic() - t0 < 5.0
    done_1 = [op for op in history
              if op.get("value") == 1 and op.get("type") != "invoke"]
    assert [op["type"] for op in done_1] == ["info"]
    assert done_1[0]["error"] == ["op-timeout", "drain-deadline"]
    reg = metrics_registry
    assert reg.counter("interpreter_abandoned_workers_total").value() >= 1


@pytest.mark.chaos
def test_stall_detector_dumps_thread_stacks(tmp_path, metrics_registry):
    """No dispatch and no completion for stall_s: the watchdog emits a
    telemetry event and dumps every thread's stack into the store dir."""
    import jepsen_tpu.generator as gen

    client = HangingClient(hang_values={1})
    test = {"concurrency": 1, "nodes": ["n1"], "client": client,
            "generator": gen.clients(gen.Seq(_writes([0, 1]))),
            "op_timeout_s": 0, "drain_timeout_s": 1.5, "stall_s": 0.25,
            "name": "stall", "start_time": "20260803T000001.000",
            "store_dir": str(tmp_path)}
    t0 = time.monotonic()
    _run(test)
    assert time.monotonic() - t0 < 6.0
    dump = tmp_path / "stall" / "20260803T000001.000" / "stall-threads.txt"
    assert dump.exists()
    text = dump.read_text()
    assert "thread stacks @" in text
    # the hung worker's stack is in the dump: it's parked in this file's
    # HangingClient.invoke (faulthandler prints files, not thread names)
    assert "test_deadline.py" in text
    reg = metrics_registry
    assert reg.counter("interpreter_stalls_total").value() >= 1
    events = [r for r in reg.snapshot() if r.get("type") == "event"
              and r.get("name") == "interpreter-stall"]
    assert events


@pytest.mark.chaos
def test_timed_out_fault_closing_op_stays_unhealed(tmp_path,
                                                   metrics_registry):
    """A fault-closing nemesis op that outlives its deadline must NOT
    mark the fault healed — not when reaped, and not when the hung heal
    eventually returns — so the idempotent replay can restore the
    network."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.net import NoopNet
    from jepsen_tpu.nemesis.faults import FaultRegistry, replay_unhealed

    release = threading.Event()

    class HangingHealNemesis:
        def invoke(self, test, op):
            if op.get("f") == "stop-partition":
                release.wait()
            return {**op, "type": "info"}

    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"concurrency": 1, "nodes": ["n1"], "client": None,
            "nemesis": HangingHealNemesis(), "_faults": registry,
            "generator": gen.nemesis_gen(gen.Seq([
                {"type": "info", "f": "start-partition", "value": None},
                {"type": "info", "f": "stop-partition", "value": None},
            ])),
            "op_timeout_s": 0.4, "drain_timeout_s": 2.0, "stall_s": 0}
    t0 = time.monotonic()
    history = _run(test)
    assert time.monotonic() - t0 < 5.0
    timeouts = [op for op in history
                if (op.get("error") or [None])[0] == "op-timeout"]
    assert [op["f"] for op in timeouts] == ["stop-partition"]
    assert [r["kind"] for r in registry.unhealed()] == ["net"]

    # the hung heal completes LATE: the zombied NemesisWorker must still
    # refuse to mark it healed
    release.set()
    time.sleep(0.3)
    assert [r["kind"] for r in registry.unhealed()] == ["net"]

    # ... which is exactly what the crash-path / cli-heal replay is for
    heal_test = {"nodes": ["n1", "n2"], "ssh": {"dummy": True},
                 "net": NoopNet()}
    out = replay_unhealed(heal_test, registry)
    assert len(out["healed"]) == 1 and heal_test["_net_log"] == [("heal",)]
    assert registry.unhealed() == []
    registry.close()


@pytest.mark.chaos
def test_late_fault_opening_injection_rerecorded(tmp_path,
                                                 metrics_registry):
    """A fault-*opening* op whose injection lands after its deadline is
    re-recorded: a same-kind closing op may have marked the pre-recorded
    entry healed in the meantime, and the late injection must not leave
    the cluster faulted with a clean-looking registry."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.nemesis.faults import FaultRegistry

    release = threading.Event()

    class HangingInjectNemesis:
        def invoke(self, test, op):
            if op.get("f") == "start-partition":
                release.wait()  # the injection is stuck mid-SSH
            return {**op, "type": "info"}

    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"concurrency": 1, "nodes": ["n1"], "client": None,
            "nemesis": HangingInjectNemesis(), "_faults": registry,
            "generator": gen.nemesis_gen(gen.Seq([
                {"type": "info", "f": "start-partition", "value": None},
                {"type": "info", "f": "stop-partition", "value": None},
            ])),
            "op_timeout_s": 0.4, "drain_timeout_s": 2.0, "stall_s": 0}
    t0 = time.monotonic()
    _run(test)
    assert time.monotonic() - t0 < 5.0
    # the replacement worker's stop-partition marked the pre-recorded
    # injection healed — at this point the registry looks clean
    assert registry.unhealed() == []
    # the run ends and closes the registry (as core.run's finally does)
    # BEFORE the hung injection actually fires: the late record must
    # still reach the durable log — it is the only evidence the
    # cluster is dirty
    registry.close()
    release.set()
    time.sleep(0.3)
    reopened = FaultRegistry(tmp_path / "faults.jsonl")
    assert [r["kind"] for r in reopened.unhealed()] == ["net"]
    reopened.close()


# ---------------------------------------------------------------------------
# Chaos: the acceptance scenario end to end through core.run
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_full_run_with_forever_hung_client_finishes(tmp_path):
    """A run whose client hangs forever on one op still finishes end to
    end — history checked, nemesis fault healed, store written — within
    op_timeout + drain deadline of the hang, with the op recorded as
    :info [op-timeout ...] and the timeout/zombie metrics exported."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu import core
    from jepsen_tpu import nemesis as nem
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.fakes import noop_test
    from jepsen_tpu.nemesis.faults import FaultRegistry

    client = HangingClient(hang_values={3})
    g = gen.Seq([
        gen.nemesis_gen(gen.Seq([
            {"type": "info", "f": "start-partition", "value": None},
            {"type": "info", "f": "stop-partition", "value": None},
        ])),
        gen.clients(gen.Seq(_writes([0, 1, 2, 3, 4, 5]))),
    ])
    t = noop_test(client=client, nemesis=nem.partitioner(), generator=g,
                  checker=linearizable(accelerator="cpu"),
                  store_dir=str(tmp_path), op_timeout_s=1.0,
                  drain_timeout_s=2.0, stall_s=0, time_limit=30.0)
    t0 = time.monotonic()
    result = core.run(t)
    elapsed = time.monotonic() - t0
    assert elapsed < 30.0, f"run took {elapsed:.1f}s — wedged?"

    assert result["results"]["valid?"] is True
    run_dirs = list(tmp_path.glob("noop/2*"))
    assert len(run_dirs) == 1
    run_dir = run_dirs[0]
    assert (run_dir / "results.json").exists()
    history = [json.loads(line) for line in
               (run_dir / "history.jsonl").read_text().splitlines()]
    timeouts = [op for op in history
                if (op.get("error") or [None])[0] == "op-timeout"]
    assert len(timeouts) == 1 and timeouts[0]["value"] == 3
    assert timeouts[0]["type"] == "info"
    # the nemesis window closed cleanly: nothing left for a replay
    freg = FaultRegistry(run_dir / "faults.jsonl")
    assert freg.unhealed() == []
    freg.close()
    # the run's exported metrics reflect the reap
    rows = [json.loads(line) for line in
            (run_dir / "metrics.json").read_text().splitlines()]
    by_name = {}
    for r in rows:
        if r.get("type") in ("counter", "gauge"):
            by_name[r["name"]] = by_name.get(r["name"], 0) + r["value"]
    assert by_name.get("interpreter_op_timeouts_total") == 1
    assert by_name.get("interpreter_zombie_workers") == 1
