"""Suite-specific fault machinery: dgraph's tablet-mover, aerospike's
kill/revive/recluster vocabulary, rethinkdb's reconfigure nemesis, plus
the rethinkdb set/counter workloads those faults exercise (references:
dgraph/src/jepsen/dgraph/nemesis.clj:51-99,
aerospike/src/aerospike/nemesis.clj:17-128,
rethinkdb/src/jepsen/rethinkdb.clj:180-232)."""
import random

import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import _reql as r
from jepsen_tpu.suites import aerospike, dgraph, rethinkdb
from jepsen_tpu.suites._reql import ReqlError

from conftest import run_fake  # noqa: E402

NODES = ["n1", "n2", "n3", "n4", "n5"]


def dummy_test(**over):
    t = {"nodes": list(NODES), "ssh": {"dummy": True}, "concurrency": 2}
    t.update(over)
    return t


@pytest.fixture()
def dummy():
    t = dummy_test()
    remote = control.default_remote(t)
    yield t, remote
    control.disconnect_all(t)


# ---------------------------------------------------------------------------
# dgraph tablet-mover
# ---------------------------------------------------------------------------

ZERO_STATE = {
    "zeros": {"1": {"addr": "n2:5080", "leader": True},
              "2": {"addr": "n1:5080"}},
    "groups": {
        "1": {"tablets": {"key": {"predicate": "key", "groupId": 1}}},
        "2": {"tablets": {"el": {"predicate": "el", "groupId": 2}}}},
}


def test_zero_leader_parse():
    assert dgraph.zero_leader(ZERO_STATE) == "n2"
    assert dgraph.zero_leader({"zeros": {}}) is None


def test_tablet_mover_moves_through_leader(monkeypatch):
    urls = []

    def fake_http(url, body=None, **kw):
        urls.append(url)
        if url.endswith("/state"):
            return ZERO_STATE
        return ""

    monkeypatch.setattr(dgraph, "http_json", fake_http)
    mover = dgraph.TabletMover(rng=random.Random(3))
    out = mover.invoke({"nodes": NODES},
                       {"type": "info", "f": "move-tablet", "value": None})
    assert out["type"] == "info"
    moves = out["value"]
    assert isinstance(moves, dict) and moves, moves
    # every move went to the zero LEADER's admin endpoint with both params
    move_urls = [u for u in urls if "/moveTablet" in u]
    assert move_urls and all(u.startswith("http://n2:6080/") for u in move_urls)
    assert all("tablet=" in u and "group=" in u for u in move_urls)
    # recorded as {predicate: [from, to]} with from != to
    for pred, (frm, to) in moves.items():
        assert frm != to


def test_tablet_mover_timeout_value(monkeypatch):
    monkeypatch.setattr(dgraph, "http_json",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("down")))
    mover = dgraph.TabletMover()
    out = mover.invoke({"nodes": NODES},
                       {"type": "info", "f": "move-tablet", "value": None})
    assert out["value"] == "timeout"


@pytest.mark.slow
def test_dgraph_fake_run_with_move_tablet_fault():
    result = run_fake(dgraph.dgraph_test, workload="register",
                      faults={"move-tablet"}, nemesis_interval=0.3)
    assert result["results"]["valid?"] is True, result["results"]
    fs = {op.get("f") for op in result["history"]
          if not isinstance(op.get("process"), int)}
    assert "move-tablet" in fs


# ---------------------------------------------------------------------------
# aerospike killer
# ---------------------------------------------------------------------------

def test_killer_kill_respects_max_dead(dummy):
    t, remote = dummy
    n = aerospike.KillerNemesis(max_dead=2, rng=random.Random(1))
    out = n.invoke(t, {"type": "info", "f": "kill",
                       "value": ["n1", "n2", "n3"]})
    vals = out["value"]
    assert sorted(vals) == ["n1", "n2", "n3"]
    assert sorted(v for v in vals.values()) == [
        "killed", "killed", "still-alive"]
    assert len(n.dead) == 2
    cmds = [c for (k, _h, c) in remote.log if k == "exec"]
    assert sum("killall -9 asd" in c for c in cmds) == 2


def test_killer_kill_cap_holds_under_concurrency(dummy):
    """The cap check-then-add must be atomic: _on_nodes runs per-node
    closures on real threads, and with SSH-like latency every thread
    would otherwise see the dead set empty (nemesis.clj:11-15's atomic
    capped-conj)."""
    import time

    t, remote = dummy
    real_execute = type(remote).execute

    def slow_execute(self, ctx, cmd):
        time.sleep(0.05)
        return real_execute(self, ctx, cmd)

    n = aerospike.KillerNemesis(max_dead=2)
    try:
        type(remote).execute = slow_execute
        out = n.invoke(t, {"type": "info", "f": "kill", "value": NODES})
    finally:
        type(remote).execute = real_execute
    assert sorted(out["value"].values()).count("killed") == 2
    assert len(n.dead) == 2


def test_tablet_mover_marks_refusals(monkeypatch):
    import urllib.error

    def fake_http(url, body=None, **kw):
        if url.endswith("/state"):
            return ZERO_STATE
        raise urllib.error.HTTPError(
            url, 500, "err", {}, __import__("io").BytesIO(
                b"Unable to move reserved predicate"))

    monkeypatch.setattr(dgraph, "http_json", fake_http)
    mover = dgraph.TabletMover(rng=random.Random(3))
    out = mover.invoke({"nodes": NODES},
                       {"type": "info", "f": "move-tablet", "value": None})
    assert out["value"], out
    for entry in out["value"].values():
        assert entry[0] == "refused" and len(entry) == 3


def test_killer_restart_revive_recluster(dummy):
    t, remote = dummy
    n = aerospike.KillerNemesis(max_dead=2)
    n.dead = {"n1", "n2"}
    out = n.invoke(t, {"type": "info", "f": "restart",
                       "value": ["n1", "n2"]})
    assert all(v == "started" for v in out["value"].values())
    assert not n.dead
    n.invoke(t, {"type": "info", "f": "revive", "value": None})
    n.invoke(t, {"type": "info", "f": "recluster", "value": None})
    cmds = [c for (k, _h, c) in remote.log if k == "exec"]
    assert any("asinfo -v revive:namespace=jepsen" in c for c in cmds)
    assert any("asinfo -v recluster:" in c for c in cmds)
    # revive/recluster with no explicit subset hit EVERY node
    revive_hosts = {h for (k, h, c) in remote.log
                    if k == "exec" and "revive:" in c}
    assert revive_hosts == set(NODES)


def test_killer_gen_patterns():
    from jepsen_tpu import generator as gen
    g = gen.time_limit(5.0, gen.nemesis_gen(aerospike.killer_gen()))
    t = dummy_test()
    ctx = gen.context(t)
    seen = set()
    for _ in range(60):
        res = g.op(t, ctx)
        if res is None:
            break
        op, g = res
        if op is gen.PENDING or op.get("f") is None:
            break
        seen.add(op.get("f"))
        if op.get("f") in ("kill", "restart"):
            assert op.get("value"), "kill/restart must carry a node subset"
        g = g.update(t, ctx, {**op, "type": "info"})
    assert {"kill", "restart", "revive", "recluster"} <= seen


def test_aerospike_fake_run_with_killer_fault():
    result = run_fake(aerospike.aerospike_test, workload="register",
                      faults={"killer"}, nemesis_interval=0.3)
    assert result["results"]["valid?"] is True, result["results"]
    fs = {op.get("f") for op in result["history"]
          if not isinstance(op.get("process"), int)}
    assert fs & {"kill", "restart", "revive", "recluster"}, fs


# ---------------------------------------------------------------------------
# rethinkdb reconfigure
# ---------------------------------------------------------------------------

class FakeConn:
    def __init__(self, script):
        self.script = script  # list of results or exceptions
        self.terms = []

    def run(self, term):
        self.terms.append(term)
        out = self.script.pop(0)
        if isinstance(out, Exception):
            raise out
        return out

    def close(self):
        pass


def scripted_reconfigurer(script, rng=None):
    conn = FakeConn(script)

    class TNemesis(rethinkdb.ReconfigureNemesis):
        def _connect(self, primary):
            conn.primary = primary
            return conn

    return TNemesis(rng=rng or random.Random(5)), conn


def test_reconfigure_term_shape():
    n, conn = scripted_reconfigurer([{"reconfigured": 1}])
    t = dummy_test(name="rethinkdb-register")
    out = n.invoke(t, {"type": "info", "f": "reconfigure", "value": None})
    v = out["value"]
    assert v["primary"] in v["replicas"]
    term = conn.terms[0]
    assert term[0] == r.RECONFIGURE
    opts = term[2]
    assert opts["shards"] == 1
    assert opts["primary_replica_tag"] == v["primary"]
    assert set(opts["replicas"]) == set(v["replicas"])
    assert all(x == 1 for x in opts["replicas"].values())
    # the connection went to the new primary itself
    assert conn.primary == v["primary"]


def test_reconfigure_retries_tag_errors():
    err = ReqlError(18, ["Could not find any servers with server tag n3"])
    n, conn = scripted_reconfigurer([err, err, {"reconfigured": 1}])
    out = n.invoke(dummy_test(), {"type": "info", "f": "reconfigure",
                                  "value": None})
    assert isinstance(out["value"], dict)
    assert len(conn.terms) == 3


def test_reconfigure_gives_up_on_other_errors():
    err = ReqlError(18, ["Table `jepsen.cas` does not exist"])
    n, conn = scripted_reconfigurer([err])
    out = n.invoke(dummy_test(), {"type": "info", "f": "reconfigure",
                                  "value": None})
    assert out["value"][0] == "error"
    assert len(conn.terms) == 1


# ---------------------------------------------------------------------------
# rethinkdb set / counter workloads
# ---------------------------------------------------------------------------

def scripted_client(results):
    conn = FakeConn(list(results))
    c = rethinkdb.RethinkDBClient()
    c.conn = conn
    return c, conn


def test_rethinkdb_set_client_ops():
    c, conn = scripted_client([{"inserted": 1}, [3, 1, 2]])
    out = c.invoke({}, {"f": "add", "type": "invoke", "value": 3})
    assert out["type"] == "ok"
    ins = conn.terms[0]
    assert ins[0] == r.INSERT and ins[1][1] == {"id": 3}
    out = c.invoke({}, {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "ok" and out["value"] == [1, 2, 3]
    read = conn.terms[1]
    assert read[0] == r.COERCE_TO and read[1][1] == "array"


def test_rethinkdb_counter_client_ops():
    t = {"counter": True}
    c, conn = scripted_client([{"replaced": 1, "errors": 0}, 7])
    out = c.invoke(t, {"f": "add", "type": "invoke", "value": 2})
    assert out["type"] == "ok"
    upd = conn.terms[0]
    assert upd[0] == r.UPDATE
    out = c.invoke(t, {"f": "read", "type": "invoke", "value": None})
    assert out["type"] == "ok" and out["value"] == 7


@pytest.mark.slow
def test_rethinkdb_fake_set_and_counter_runs():
    result = run_fake(rethinkdb.rethinkdb_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]
    result = run_fake(rethinkdb.rethinkdb_test, workload="counter")
    assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# aerospike pause-to-lose-writes (pause.clj)
# ---------------------------------------------------------------------------

def test_pause_client_gen_paused_to_wait_on_ok_add():
    from jepsen_tpu import generator as gen
    from jepsen_tpu.workloads.pause_workload import (MachineState,
                                                     PauseClientGen)
    t = dummy_test(concurrency=4)
    # clients-restricted context, as compose_test wraps it in production
    # (a bare context would let some_free_process pick the nemesis)
    ctx = gen.context(t).restrict(frozenset(range(4)))
    state = MachineState(rng=random.Random(1))
    g = PauseClientGen(state)
    op, g = g.op(t, ctx)
    assert op is not gen.PENDING and op["f"] == "add"
    state.phase = "paused"
    g = g.update(t, ctx, {**op, "type": "ok"})
    assert state.phase == "wait"
    # wait phase: clients stop cold
    assert g.op(t, ctx)[0] is gen.PENDING


def test_pause_nemesis_gen_cycle():
    from jepsen_tpu import generator as gen
    from jepsen_tpu.workloads.pause_workload import (MachineState,
                                                     PauseNemesisGen)
    t = dummy_test(concurrency=4)
    t["pause-healthy-delay"] = 0.001
    t["pause-delay"] = 0.001
    ctx = gen.context(t)
    state = MachineState(rng=random.Random(1))
    g = PauseNemesisGen(state)
    op, g = g.op(t, ctx)
    assert op["f"] == "pause" and op["value"] == state.masters
    # op() is PURE: a discarded poll must not transition the machine
    assert state.phase == "healthy"
    op2, g = g.op(t, ctx)
    assert op2["f"] == "pause"  # re-polled, same phase, same op
    g = g.update(t, ctx, {**op, "type": "info"})  # dispatched invocation
    assert state.phase == "paused"
    assert g.op(t, ctx)[0] is gen.PENDING  # waits for the client flip
    state.phase = "wait"
    first_keys = list(state.keys)
    op, g = g.op(t, ctx)
    assert op["f"] == "resume"
    assert state.phase == "wait"  # still pure at emission
    g = g.update(t, ctx, {**op, "type": "info"})
    assert state.phase == "healthy"
    assert state.keys != first_keys  # fresh key block (pause.clj:29-38)


def test_pause_nemesis_process_mode(dummy):
    t, remote = dummy
    n = aerospike.PauseNemesis(mode="process")
    n.invoke(t, {"type": "info", "f": "pause", "value": ["n2"]})
    n.invoke(t, {"type": "info", "f": "resume", "value": ["n2"]})
    cmds = [c for (k, h, c) in remote.log if k == "exec" and h == "n2"]
    # grepkill emits pkill -STOP/-CONT with a bracketed pattern
    assert any("-STOP" in c and "sd'" in c for c in cmds), cmds
    assert any("-CONT" in c and "sd'" in c for c in cmds), cmds


def test_pause_client_bodies():
    sent = []

    class TConn:
        def append(self, key, text):
            sent.append(("append", key, text))

        def get_string(self, key):
            sent.append(("get", key))
            return " 3 1"

    c = aerospike.AerospikeClient(node="n1")
    c.conn = TConn()
    t = {"pause-workload": True}
    out = c.invoke(t, {"f": "add", "type": "invoke", "value": [7, 3]})
    assert out["type"] == "ok" and sent[0] == ("append", 7, " 3")
    out = c.invoke(t, {"f": "read", "type": "invoke", "value": [7, None]})
    assert out["type"] == "ok" and out["value"] == [7, [1, 3]]


@pytest.mark.slow
def test_aerospike_fake_pause_run():
    result = run_fake(aerospike.aerospike_test, workload="pause",
                      faults={"pause-writes"}, time_limit=2.0,
                      healthy_delay=0.1, pause_delay=0.1, concurrency=4)
    assert result["results"]["valid?"] is True, result["results"]
    fs = {op.get("f") for op in result["history"]}
    assert {"pause", "resume", "add", "read"} <= fs, fs
