"""The stdlib CQL wire client against a scripted in-process server.

Covers the protocol surface the YCQL suite depends on (STARTUP/READY,
PLAIN SASL auth, QUERY → Rows decode with typed columns and the LWT
``[applied]`` column, ERROR frames) the way test_postgres_wire.py covers
the Postgres family."""
from __future__ import annotations

import socket
import struct
import threading

import pytest

from jepsen_tpu.suites._cql_client import (CQLConnection, CqlError,
                                           T_BOOLEAN, T_COUNTER, T_INT,
                                           T_VARCHAR, YCQLSuiteClient)


def _frame(opcode: int, body: bytes, stream: int = 0) -> bytes:
    return struct.pack("!BBhBI", 0x84, 0, stream, opcode, len(body)) + body


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _rows(cols, rows) -> bytes:
    """RESULT/Rows with a global table spec; cols = [(name, type_id)]."""
    body = struct.pack("!I", 0x0002)           # kind = Rows
    body += struct.pack("!II", 0x0001, len(cols))  # global spec flag
    body += _string("ks") + _string("tbl")
    for name, tid in cols:
        body += _string(name) + struct.pack("!H", tid)
    body += struct.pack("!I", len(rows))
    for row in rows:
        for cell in row:
            if cell is None:
                body += struct.pack("!i", -1)
            else:
                body += struct.pack("!i", len(cell)) + cell
    return _frame(0x08, body)


def _void() -> bytes:
    return _frame(0x08, struct.pack("!I", 0x0001))


def _error(code: int, msg: str) -> bytes:
    return _frame(0x00, struct.pack("!I", code) + _string(msg))


class MockCQLServer:
    """One-connection scripted server: responds READY to STARTUP (or the
    AUTHENTICATE dance when ``auth``), then pops canned responses per
    QUERY; records the query strings."""

    def __init__(self, responses, auth: bool = False):
        self.responses = list(responses)
        self.auth = auth
        self.queries: list[str] = []
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _recv_frame(self, conn):
        header = b""
        while len(header) < 9:
            chunk = conn.recv(9 - len(header))
            if not chunk:
                return None, None
            header += chunk
        _v, _f, _s, opcode, length = struct.unpack("!BBhBI", header)
        body = b""
        while len(body) < length:
            body += conn.recv(length - len(body))
        return opcode, body

    def _serve(self):
        conn, _ = self.sock.accept()
        with conn:
            opcode, _body = self._recv_frame(conn)
            assert opcode == 0x01  # STARTUP
            if self.auth:
                conn.sendall(_frame(0x03, _string("PasswordAuthenticator")))
                opcode, body = self._recv_frame(conn)
                assert opcode == 0x0F  # AUTH_RESPONSE
                tlen = struct.unpack("!I", body[:4])[0]
                self.token = body[4:4 + tlen]
                conn.sendall(_frame(0x10, struct.pack("!i", -1)))
            else:
                conn.sendall(_frame(0x02, b""))
            while self.responses:
                opcode, body = self._recv_frame(conn)
                if opcode is None:
                    return
                assert opcode == 0x07  # QUERY
                qlen = struct.unpack("!I", body[:4])[0]
                self.queries.append(body[4:4 + qlen].decode())
                conn.sendall(self.responses.pop(0))


def test_startup_query_and_typed_rows():
    srv = MockCQLServer([
        _rows([("val", T_INT), ("count", T_COUNTER), ("name", T_VARCHAR)],
              [[struct.pack("!i", 7), struct.pack("!q", 3), b"x"],
               [struct.pack("!i", 9), None, b"y"]]),
        _void(),
    ])
    c = CQLConnection("127.0.0.1", port=srv.port)
    rows = c.query("SELECT val, count, name FROM t")
    assert rows == [{"val": 7, "count": 3, "name": "x"},
                    {"val": 9, "count": None, "name": "y"}]
    assert c.query("CREATE TABLE t (x INT PRIMARY KEY)") == []
    assert srv.queries[0].startswith("SELECT")
    c.close()


def test_plain_sasl_auth():
    srv = MockCQLServer([_void()], auth=True)
    c = CQLConnection("127.0.0.1", port=srv.port, user="cassandra",
                      password="pw")
    c.query("SELECT 1")
    assert srv.token == b"\x00cassandra\x00pw"
    c.close()


def test_error_frame_raises_cql_error():
    srv = MockCQLServer([_error(0x2200, "Invalid query")])
    c = CQLConnection("127.0.0.1", port=srv.port)
    with pytest.raises(CqlError) as ei:
        c.query("SELECT nonsense")
    assert ei.value.code == 0x2200
    assert "Invalid query" in ei.value.message
    c.close()


def _client_with(srv) -> YCQLSuiteClient:
    cl = YCQLSuiteClient(port=srv.port, node="127.0.0.1")
    cl._connect({"nodes": ["127.0.0.1"]})
    return cl


def test_ycql_client_cas_applied_column():
    """LWT cas maps the [applied] column to ok/fail
    (ycql/single_key_acid.clj:33-39)."""
    srv = MockCQLServer([
        _rows([("[applied]", T_BOOLEAN)], [[b"\x01"]]),
        _rows([("[applied]", T_BOOLEAN)], [[b"\x00"]]),
    ])
    cl = _client_with(srv)
    ok = cl.invoke({}, {"f": "cas", "value": [3, [1, 2]]})
    assert ok["type"] == "ok"
    fail = cl.invoke({}, {"f": "cas", "value": [3, [4, 2]]})
    assert fail["type"] == "fail"
    assert "IF val = 1" in srv.queries[0]
    cl.close({})


def test_ycql_client_multi_key_txn_string():
    """Write txns compose one BEGIN/END TRANSACTION statement
    (ycql/multi_key_acid.clj:49-60); reads fill mops from the group's
    rows."""
    srv = MockCQLServer([
        _void(),
        _rows([("ik", T_INT), ("val", T_INT)],
              [[struct.pack("!i", 0), struct.pack("!i", 4)]]),
    ])
    cl = _client_with(srv)
    w = cl.invoke({"txn-mode": "multi"},
                  {"f": "txn", "value": [7, [["w", 0, 4], ["w", 2, 1]]]})
    assert w["type"] == "ok"
    q = srv.queries[0]
    assert q.startswith("BEGIN TRANSACTION") and q.rstrip().endswith(
        "END TRANSACTION;")
    assert q.count("INSERT INTO") == 2
    r = cl.invoke({"txn-mode": "multi"},
                  {"f": "txn", "value": [7, [["r", 0, None], ["r", 2, None]]]})
    assert r["type"] == "ok"
    assert r["value"] == [7, [["r", 0, 4], ["r", 2, None]]]
    cl.close({})


def test_ycql_client_bank_transfer_guard():
    """Transfers read the source balance first and refuse overdrafts
    without issuing the transaction (ycql/bank.clj:40-60)."""
    srv = MockCQLServer([
        _rows([("balance", T_COUNTER)], [[struct.pack("!q", 3)]]),
    ])
    cl = _client_with(srv)
    out = cl.invoke({}, {"f": "transfer",
                         "value": {"from": 0, "to": 1, "amount": 5}})
    assert out["type"] == "fail"
    assert len(srv.queries) == 1  # no txn was sent
    cl.close({})


def test_ycql_client_error_discipline():
    """CqlError: reads fail, writes go indeterminate, and the connection
    is rebuilt before the next op."""
    srv = MockCQLServer([_error(0x1000, "unavailable")])
    cl = _client_with(srv)
    out = cl.invoke({}, {"f": "write", "value": [1, 2]})
    assert out["type"] == "info"
    assert cl._broken
    cl.close({})


def test_yugabyte_ycql_fake_mode_lifecycle():
    """--api ycql composes the YCQL workload list end to end in fake
    mode (yugabyte/core.clj:74-85)."""
    from conftest import run_fake
    from jepsen_tpu.suites.yugabyte import yugabyte_test

    for wl in ("set-index", "multi-key-acid"):
        t = run_fake(yugabyte_test, api="ycql", workload=wl,
                     time_limit=0.5)
        assert t["results"]["valid?"] in (True, "unknown"), (
            wl, t["results"])
