"""Elle-equivalent txn checker tests: classic Adya anomaly constructions +
serializable histories + device/CPU trim agreement."""
import random

import numpy as np
import pytest

from jepsen_tpu.elle import Graph, RW, WR, WW, check_cycles, list_append, rw_register
from jepsen_tpu.ops.scc import has_cycle, tarjan_scc, trim_to_cycles


def ok(process, txn):
    return {"type": "ok", "process": process, "f": "txn", "value": txn}


def fail(process, txn):
    return {"type": "fail", "process": process, "f": "txn", "value": txn}


# ---------------------------------------------------------------------------
# graph machinery
# ---------------------------------------------------------------------------

def test_trim_finds_cycle():
    # 0->1->2->0 plus a tail 3->0
    src = np.array([0, 1, 2, 3], dtype=np.int32)
    dst = np.array([1, 2, 0, 0], dtype=np.int32)
    mask = trim_to_cycles(4, src, dst)
    assert mask.tolist() == [True, True, True, False]


def test_trim_acyclic_empty():
    src = np.array([0, 1, 2], dtype=np.int32)
    dst = np.array([1, 2, 3], dtype=np.int32)
    assert not trim_to_cycles(4, src, dst).any()
    assert not has_cycle(4, src, dst)


def test_tarjan():
    edges = [(0, 1), (1, 2), (2, 0), (3, 4)]
    sccs = tarjan_scc(5, edges)
    assert sorted(sccs[0]) == [0, 1, 2]
    assert len(sccs) == 1


def test_check_cycles_classification():
    g = Graph(2)
    g.add(0, 1, WW)
    g.add(1, 0, WW)
    r = check_cycles(g)
    assert "G0" in r

    g = Graph(2)
    g.add(0, 1, WR)
    g.add(1, 0, WW)
    r = check_cycles(g)
    assert "G1c" in r

    g = Graph(2)
    g.add(0, 1, WR)
    g.add(1, 0, RW)
    r = check_cycles(g)
    assert "G-single" in r
    assert "G2" not in r

    g = Graph(2)
    g.add(0, 1, RW)
    g.add(1, 0, RW)
    r = check_cycles(g)
    assert "G2" in r


# ---------------------------------------------------------------------------
# list-append anomalies
# ---------------------------------------------------------------------------

def test_append_serializable_ok():
    h = [
        ok(0, [["append", "x", 1]]),
        ok(1, [["r", "x", [1]], ["append", "x", 2]]),
        ok(0, [["r", "x", [1, 2]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is True
    assert r["anomaly-types"] == []


def test_append_g0():
    h = [
        ok(0, [["append", "x", 1], ["append", "y", 1]]),
        ok(1, [["append", "x", 2], ["append", "y", 2]]),
        ok(2, [["r", "x", [1, 2]], ["r", "y", [2, 1]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G0" in r["anomaly-types"]


def test_append_g1c():
    h = [
        ok(0, [["append", "x", 1], ["r", "y", [1]]]),
        ok(1, [["append", "y", 1], ["r", "x", [1]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"]


def test_append_g_single():
    h = [
        ok(0, [["append", "x", 1], ["append", "y", 1]]),
        ok(1, [["r", "x", [1]], ["r", "y", []]]),
        ok(2, [["r", "y", [1]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G-single" in r["anomaly-types"]


def test_append_g2_write_skew():
    h = [
        ok(0, [["r", "x", []], ["append", "y", 1]]),
        ok(1, [["r", "y", []], ["append", "x", 1]]),
        ok(2, [["r", "x", [1]], ["r", "y", [1]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G2" in r["anomaly-types"]


def test_append_g1a_aborted_read():
    h = [
        fail(0, [["append", "x", 9]]),
        ok(1, [["r", "x", [9]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G1a" in r["anomaly-types"]


def test_append_g1b_intermediate_read():
    h = [
        ok(0, [["append", "x", 1], ["append", "x", 2]]),
        ok(1, [["r", "x", [1]]]),
        ok(2, [["r", "x", [1, 2]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G1b" in r["anomaly-types"]


def test_append_internal():
    h = [ok(0, [["append", "x", 1], ["r", "x", []]])]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "internal" in r["anomaly-types"]


def test_append_incompatible_order():
    h = [
        ok(0, [["append", "x", 1]]),
        ok(1, [["append", "x", 2]]),
        ok(2, [["r", "x", [1, 2]]]),
        ok(3, [["r", "x", [2]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "incompatible-order" in r["anomaly-types"]


def serializable_append_history(rng, n_txns=300, n_keys=5, n_procs=5):
    """Executes random append txns sequentially against real lists: the
    resulting history is serializable by construction."""
    state = {k: [] for k in range(n_keys)}
    h = []
    counter = {k: 0 for k in range(n_keys)}
    for i in range(n_txns):
        txn = []
        for _ in range(rng.randint(1, 4)):
            k = rng.randrange(n_keys)
            if rng.random() < 0.5:
                txn.append(["r", k, list(state[k])])
            else:
                counter[k] += 1
                state[k].append(counter[k])
                txn.append(["append", k, counter[k]])
        h.append(ok(i % n_procs, txn))
    # final reads pin down version orders
    for k in range(n_keys):
        h.append(ok(0, [["r", k, list(state[k])]]))
    return h


def test_append_random_serializable():
    rng = random.Random(42)
    h = serializable_append_history(rng)
    r = list_append.check(h)
    assert r["valid?"] is True, r["anomaly-types"]
    assert r["txn-count"] == len(h)


def test_append_cpu_and_device_agree():
    rng = random.Random(1)
    good = serializable_append_history(rng, n_txns=100)
    bad = [
        ok(0, [["append", "x", 1], ["append", "y", 1]]),
        ok(1, [["append", "x", 2], ["append", "y", 2]]),
        ok(2, [["r", "x", [1, 2]], ["r", "y", [2, 1]]]),
    ]
    for h in (good, bad):
        r_dev = list_append.check(h, accelerator="auto")
        r_cpu = list_append.check(h, accelerator="cpu")
        assert r_dev["valid?"] == r_cpu["valid?"]
        assert r_dev["anomaly-types"] == r_cpu["anomaly-types"]


# ---------------------------------------------------------------------------
# rw-register
# ---------------------------------------------------------------------------

def test_wr_register_serializable():
    h = [
        ok(0, [["w", "x", 1]]),
        ok(1, [["r", "x", 1], ["w", "x", 2]]),
        ok(0, [["r", "x", 2]]),
    ]
    r = rw_register.check(h)
    assert r["valid?"] is True


def test_wr_register_g1a():
    h = [
        fail(0, [["w", "x", 9]]),
        ok(1, [["r", "x", 9]]),
    ]
    r = rw_register.check(h)
    assert r["valid?"] is False
    assert "G1a" in r["anomaly-types"]


def test_wr_register_internal():
    h = [ok(0, [["w", "x", 1], ["r", "x", 5]])]
    r = rw_register.check(h)
    assert r["valid?"] is False
    assert "internal" in r["anomaly-types"]


def test_wr_register_wr_cycle():
    # T0 reads T1's write, T1 reads T0's write: wr cycle (G1c)
    h = [
        ok(0, [["w", "x", 1], ["r", "y", 1]]),
        ok(1, [["w", "y", 1], ["r", "x", 1]]),
    ]
    r = rw_register.check(h)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"]


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------

def test_append_gen_produces_txns():
    from jepsen_tpu.generator.simulate import default_context, invocations, quick
    import jepsen_tpu.generator as gen
    g = gen.limit(20, list_append.gen(key_count=3))
    h = quick({"concurrency": 2}, g)
    inv = invocations(h)
    assert len(inv) == 20
    for op in inv:
        assert op["f"] == "txn"
        for m in op["value"]:
            assert m[0] in ("r", "append")


def test_append_g1b_partial_observation_mid_read():
    # T3 observes T1's append of 1 without its 2, with T2's 3 after it —
    # an intermediate state even though the read's last element is final.
    h = [
        ok(0, [["append", "x", 1], ["append", "x", 2]]),
        ok(1, [["append", "x", 3]]),
        ok(2, [["r", "x", [1, 3]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "G1b" in r["anomaly-types"]


def test_append_txn_elements_out_of_order():
    # read observes a txn's own appends in the wrong order
    h = [
        ok(0, [["append", "x", 1], ["append", "x", 2]]),
        ok(1, [["r", "x", [2, 1]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is False
    assert "incompatible-order" in r["anomaly-types"]


def test_append_full_observation_not_g1b():
    h = [
        ok(0, [["append", "x", 1], ["append", "x", 2]]),
        ok(1, [["append", "x", 3]]),
        ok(2, [["r", "x", [1, 2, 3]]]),
    ]
    r = list_append.check(h)
    assert r["valid?"] is True


def test_wr_register_g1b_intermediate_read():
    # T0 writes x=1 then overwrites with x=2; T1 reads the intermediate 1
    h = [
        ok(0, [["w", "x", 1], ["w", "x", 2]]),
        ok(1, [["r", "x", 1]]),
    ]
    r = rw_register.check(h)
    assert r["valid?"] is False
    assert "G1b" in r["anomaly-types"]


def test_wr_register_consistency_models_forwarded():
    # A single-rw-edge cycle (G-single): T2 reads x before T1's overwrite
    # (rw T2->T1) but also observes T1's write to y (wr T1->T2). Blocked
    # under strict-serializable, allowed under read-committed.
    h = [
        ok(0, [["w", "x", 1]]),
        ok(1, [["r", "x", 1], ["w", "x", 2], ["w", "y", 2]]),
        ok(2, [["r", "y", 2], ["r", "x", 1]]),
    ]
    strict = rw_register.check(h)
    assert strict["valid?"] is False
    assert "G-single" in strict["anomaly-types"]
    rc = rw_register.check(h, consistency_models=("read-committed",))
    assert rc["valid?"] is True


def test_txn_utils():
    from jepsen_tpu.txn import (ext_reads, ext_writes, int_write_mops,
                                is_read, is_write, reduce_mops)
    txn = [["r", "x", 1], ["w", "x", 2], ["w", "x", 3], ["r", "y", None],
           ["w", "y", 9]]
    assert ext_reads(txn) == {"x": 1, "y": None}
    assert ext_writes(txn) == {"x": 3, "y": 9}
    assert int_write_mops(txn) == [["w", "x", 2]]
    assert is_read(["r", "x", None]) and is_write(["append", "x", 1])
    n = reduce_mops(lambda acc, op, m: acc + 1, 0,
                    [ok(0, txn), ok(1, [["r", "z", None]])])
    assert n == 6
    # appends never overwrite within a txn
    assert int_write_mops([["append", "x", 1], ["append", "x", 2]]) == []


def test_long_chain_no_false_cycle():
    """A serial history longer than the trim's iteration cap must not be
    reported cyclic: the capped peel leaves an acyclic residue and the
    exact pass must overrule it (regression: 35k-txn fake-mode append
    runs were flagged G1c with zero witness cycles)."""
    n = 2000
    g = Graph(n)
    for i in range(n - 1):
        g.add(i, i + 1, WW if i % 2 else WR)
    # trim with a cap far below the chain length: residue stays non-empty
    src, dst = g.arrays(None)
    mask = trim_to_cycles(n, src, dst, max_iters=16)
    assert mask.any()
    anoms = check_cycles(g)
    assert anoms == {}

    # same chain plus one real 3-cycle deep inside: found and classified
    g.add(500, 400, WR)  # 400..500 chain back-edge => ww+wr cycle
    anoms = check_cycles(g)
    assert "G1c" in anoms and anoms["G1c"]


def test_result_map_drops_empty_anomaly_lists():
    from jepsen_tpu.elle import result_map
    r = result_map({"G1c": []}, [], {})
    assert r["valid?"] is True and r["anomaly-types"] == []


# ---------------------------------------------------------------------------
# soundness differential vs a brute-force serializability oracle
# ---------------------------------------------------------------------------

def _brute_force_serializable(txns) -> bool:
    """Tries every ordering of the committed txns; serializable iff some
    order replays with every read seeing the exact current list state."""
    from itertools import permutations

    for perm in permutations(txns):
        lists: dict = {}
        ok = True
        for txn in perm:
            for f, k, v in txn:
                if f == "r":
                    if list(lists.get(k, [])) != list(v or []):
                        ok = False
                        break
                else:
                    lists.setdefault(k, []).append(v)
            if not ok:
                break
        if ok:
            return True
    return False


@pytest.mark.parametrize("accelerator,seed", [("cpu", 99), ("auto", 131)])
def test_append_checker_soundness_vs_brute_force(accelerator, seed):
    """Whenever the cycle checker CONVICTS a history (valid? False), a
    brute-force search over all serializations must agree no valid
    order exists — the checker must never accuse a serializable
    history, on the cpu oracle NOR the production columnar/φ-cluster
    path. Histories are tiny (<= 6 txns) so permutations are cheap;
    reads are randomly corrupted to produce both verdicts."""
    import random

    from jepsen_tpu.elle import list_append

    rng = random.Random(seed)
    convictions = acquittals = 0
    for trial in range(120):
        # build a sequentially-applied (serializable) history over 2 keys
        lists: dict = {}
        history = []
        txns = []
        for i in range(rng.randrange(3, 7)):
            ops = []
            k = rng.randrange(2)
            if rng.random() < 0.6:
                ops.append(["r", k, list(lists.get(k, []))])
            lists.setdefault(k, []).append(i)
            ops.append(["append", k, i])
            txns.append(ops)
            history.append({"type": "invoke", "f": "txn", "process": i % 3,
                            "value": [[f, kk, None if f == "r" else vv]
                                      for f, kk, vv in ops], "index": 2 * i})
            history.append({"type": "ok", "f": "txn", "process": i % 3,
                            "value": ops, "index": 2 * i + 1})
        if rng.random() < 0.6:
            # corrupt one read to a random (often impossible) state
            reads = [(ti, oi) for ti, t in enumerate(txns)
                     for oi, (f, _, _) in enumerate(t) if f == "r"]
            if reads:
                ti, oi = reads[rng.randrange(len(reads))]
                k = txns[ti][oi][1]
                # the ok op's value aliases txns[ti], so this mutates
                # the history entry too
                txns[ti][oi] = ["r", k, [rng.randrange(10)]]
        out = list_append.check(history, accelerator=accelerator,
                                consistency_models=("serializable",))
        if out.get("valid?") is False:
            convictions += 1
            assert not _brute_force_serializable(txns), (
                f"trial {trial}: checker convicted a serializable history "
                f"{txns}\nanomalies: {out.get('anomaly-types')}")
        else:
            acquittals += 1
    # the fuzz must have exercised both verdicts to mean anything
    assert convictions >= 10 and acquittals >= 10, (convictions, acquittals)


def test_wr_checker_soundness_vs_brute_force():
    """rw-register twin of the append soundness fuzz: a conviction must
    mean NO serialization replays with every read seeing the latest
    write (writes are unique ints, so version attribution is exact)."""
    import random
    from itertools import permutations

    from jepsen_tpu.elle import rw_register

    def brute_force_serializable(txns) -> bool:
        for perm in permutations(txns):
            regs: dict = {}
            ok = True
            for txn in perm:
                for f, k, v in txn:
                    if f == "r":
                        if regs.get(k) != v:
                            ok = False
                            break
                    else:
                        regs[k] = v
                if not ok:
                    break
            if ok:
                return True
        return False

    rng = random.Random(41)
    convictions = acquittals = 0
    for trial in range(150):
        regs: dict = {}
        versions: dict = {0: [None], 1: [None]}  # per-key version order
        history = []
        txns = []
        for i in range(rng.randrange(3, 7)):
            # mix same-key read-then-write txns (they trace version
            # successions, powering rw-edge inference) with cross-key ones
            ops = []
            k = rng.randrange(2)
            wk = k if rng.random() < 0.5 else 1 - k
            if rng.random() < 0.8:
                ops.append(["r", k, regs.get(k)])
            regs[wk] = i  # unique write values
            versions[wk].append(i)
            ops.append(["w", wk, i])
            txns.append(ops)
            history.append({"type": "invoke", "f": "txn", "process": i % 3,
                            "value": [[f, kk, None if f == "r" else vv]
                                      for f, kk, vv in ops], "index": 2 * i})
            history.append({"type": "ok", "f": "txn", "process": i % 3,
                            "value": ops, "index": 2 * i + 1})
        if rng.random() < 0.7:
            # corrupt one read to a STALE version of its key (a value the
            # key really held earlier, or the initial None) — phantom
            # values would be unattributable and prove nothing
            reads = [(ti, oi) for ti, t in enumerate(txns)
                     for oi, (f, _, _) in enumerate(t) if f == "r"]
            if reads:
                ti, oi = reads[rng.randrange(len(reads))]
                k = txns[ti][oi][1]
                cur = txns[ti][oi][2]
                older = [v for v in versions[k] if v != cur]
                if older:
                    # the ok op's value aliases txns[ti]
                    txns[ti][oi] = ["r", k, rng.choice(older)]
        out = rw_register.check(history, accelerator="cpu",
                                consistency_models=("serializable",))
        if out.get("valid?") is False:
            convictions += 1
            assert not brute_force_serializable(txns), (
                f"trial {trial}: convicted a serializable history {txns}\n"
                f"anomalies: {out.get('anomaly-types')}")
        else:
            acquittals += 1
    assert convictions >= 10 and acquittals >= 10, (convictions, acquittals)


def test_wr_written_none_is_not_the_initial_state():
    """A txn can WRITE a literal None; reading it must not be conflated
    with reading the initial state (which would fabricate rw edges and
    convict a serializable history)."""
    from jepsen_tpu.elle import rw_register

    txns = [[["w", 0, None], ["w", 1, 1]],
            [["r", 1, 1], ["r", 0, None]]]
    h = []
    for i, ops in enumerate(txns):
        h.append({"type": "invoke", "f": "txn", "process": i,
                  "value": [[f, k, None if f == "r" else v]
                            for f, k, v in ops], "index": 2 * i})
        h.append({"type": "ok", "f": "txn", "process": i, "value": ops,
                  "index": 2 * i + 1})
    out = rw_register.check(h, accelerator="cpu",
                            consistency_models=("serializable",))
    assert out["valid?"] is True, out  # T1;T2 replays fine


# ---------------------------------------------------------------------------
# realtime / process precedence (strict-serializable surface)
# ---------------------------------------------------------------------------

def inv(process, txn):
    return {"type": "invoke", "process": process, "f": "txn",
            "value": [[f, k, None if f in ("r",) else v] for f, k, v in txn]}


def test_append_realtime_cycle_stale_read():
    # T1 appends 1 and completes; T2, invoked strictly after, still reads
    # the empty list. Serializable (order T2 < T1) but not strictly so.
    h = [
        inv(0, [["append", "x", 1]]),
        ok(0, [["append", "x", 1]]),
        inv(1, [["r", "x", []]]),
        ok(1, [["r", "x", []]]),
        inv(2, [["r", "x", [1]]]),
        ok(2, [["r", "x", [1]]]),
    ]
    strict = list_append.check(h, accelerator="cpu")
    assert strict["valid?"] is False
    assert "realtime-cycle" in strict["anomaly-types"]
    serial = list_append.check(h, accelerator="cpu",
                               consistency_models=("serializable",))
    assert serial["valid?"] is True


def test_append_process_cycle_completion_only_history():
    # Same stale read by ONE process, with no invocation events at all:
    # the per-process succession still orders T1 < T2.
    h = [
        ok(0, [["append", "x", 1]]),
        ok(0, [["r", "x", []]]),
        ok(1, [["r", "x", [1]]]),
    ]
    strict = list_append.check(h, accelerator="cpu")
    assert strict["valid?"] is False
    assert "process-cycle" in strict["anomaly-types"]
    seq = list_append.check(h, accelerator="cpu",
                            consistency_models=("sequential",))
    assert seq["valid?"] is False
    serial = list_append.check(h, accelerator="cpu",
                               consistency_models=("serializable",))
    assert serial["valid?"] is True


def test_wr_register_realtime_cycle_stale_read():
    # rw-register twin: T1 writes x=1 and completes, then T2 reads the
    # initial state. The init-successor inference yields rw T2 -> T1;
    # realtime yields T1 -> T2.
    h = [
        inv(0, [["w", "x", 1]]),
        ok(0, [["w", "x", 1]]),
        inv(1, [["r", "x", None]]),
        ok(1, [["r", "x", None]]),
    ]
    strict = rw_register.check(h, accelerator="cpu")
    assert strict["valid?"] is False
    assert "realtime-cycle" in strict["anomaly-types"]
    serial = rw_register.check(h, accelerator="cpu",
                               consistency_models=("serializable",))
    assert serial["valid?"] is True


def test_concurrent_txns_no_false_realtime_cycle():
    # Overlapping intervals: T1 and T2 both in flight; T2 reads [] while
    # T1's append lands after. Strictly serializable -> no anomaly.
    h = [
        inv(0, [["append", "x", 1]]),
        inv(1, [["r", "x", []]]),
        ok(0, [["append", "x", 1]]),
        ok(1, [["r", "x", []]]),
        inv(2, [["r", "x", [1]]]),
        ok(2, [["r", "x", [1]]]),
    ]
    strict = list_append.check(h, accelerator="cpu")
    assert strict["valid?"] is True, strict


def test_realtime_soundness_fuzz_linearized_store():
    """Histories generated by applying each txn atomically at a random
    point inside its [invoke, complete] interval are strictly
    serializable by construction; the checker must never convict one."""
    rng = random.Random(4242)
    for trial in range(60):
        n_txns = rng.randrange(6, 14)
        concurrency = rng.randrange(2, 5)
        # build txn intents
        intents = []
        ctr = 0
        for _ in range(n_txns):
            txn = []
            for _ in range(rng.randrange(1, 4)):
                k = rng.randrange(2)
                if rng.random() < 0.5:
                    txn.append(["r", k, None])
                else:
                    ctr += 1
                    txn.append(["append", k, ctr])
            intents.append(txn)
        # schedule: each txn has invoke < apply < complete events; at most
        # `concurrency` txns in flight; apply executes against the store
        lists: dict = {}
        history = []
        in_flight: list = []  # (txn_idx, applied?)
        next_txn = 0
        done = 0
        state: dict = {}
        while done < n_txns:
            choices = []
            if next_txn < n_txns and len(in_flight) < concurrency:
                choices.append("invoke")
            for idx, (ti, applied) in enumerate(in_flight):
                choices.append(("apply", idx) if not applied
                               else ("complete", idx))
            ev = choices[rng.randrange(len(choices))]
            if ev == "invoke":
                p = next_txn  # fresh process per txn keeps pairing simple
                history.append({"type": "invoke", "process": p, "f": "txn",
                                "value": [[f, k, None if f == "r" else v]
                                          for f, k, v in intents[next_txn]]})
                in_flight.append((next_txn, False))
                next_txn += 1
            elif ev[0] == "apply":
                ti, _ = in_flight[ev[1]]
                executed = []
                for f, k, v in intents[ti]:
                    if f == "r":
                        executed.append(["r", k, list(lists.get(k, []))])
                    else:
                        lists.setdefault(k, []).append(v)
                        executed.append(["append", k, v])
                state[ti] = executed
                in_flight[ev[1]] = (ti, True)
            else:
                ti, _ = in_flight.pop(ev[1])
                history.append({"type": "ok", "process": ti, "f": "txn",
                                "value": state[ti]})
                done += 1
        out = list_append.check(history, accelerator="cpu")
        assert out["valid?"] is True, (
            f"trial {trial}: convicted a linearized history: "
            f"{out['anomaly-types']}\n{history}")


def test_strict_soundness_fuzz_sequential_histories():
    """For a fully sequential history (each txn completes before the next
    invokes) the ONLY realtime-respecting serialization is history order;
    a strict-serializable conviction must mean that order fails replay."""
    rng = random.Random(777)

    def replays_in_order(txns):
        lists: dict = {}
        for txn in txns:
            for f, k, v in txn:
                if f == "r":
                    if list(lists.get(k, [])) != list(v or []):
                        return False
                else:
                    lists.setdefault(k, []).append(v)
        return True

    convictions = acquittals = 0
    for trial in range(120):
        lists = {}
        history = []
        txns = []
        for i in range(rng.randrange(3, 7)):
            ops = []
            k = rng.randrange(2)
            if rng.random() < 0.6:
                ops.append(["r", k, list(lists.get(k, []))])
            lists.setdefault(k, []).append(i)
            ops.append(["append", k, i])
            txns.append(ops)
            history.append(inv(i % 3, ops))
            history.append(ok(i % 3, ops))
        if rng.random() < 0.7:
            reads = [(ti, oi) for ti, t in enumerate(txns)
                     for oi, (f, _, _) in enumerate(t) if f == "r"]
            if reads:
                ti, oi = reads[rng.randrange(len(reads))]
                k = txns[ti][oi][1]
                corrupt = rng.choice([[], [rng.randrange(8)]])
                txns[ti][oi] = ["r", k, corrupt]
        out = list_append.check(history, accelerator="cpu")
        if out["valid?"] is False:
            convictions += 1
            assert not replays_in_order(txns), (
                f"trial {trial}: strict conviction of a history that "
                f"replays in realtime order {txns}\n{out['anomaly-types']}")
        else:
            acquittals += 1
    assert convictions >= 10 and acquittals >= 10, (convictions, acquittals)


def test_mixed_process_and_realtime_cycle_detected():
    """A strict-serializability violation whose cycle needs BOTH a
    process edge (between completion-only txns) and a realtime edge:
    A ->process B ->wr C ->realtime D ->rw A. Neither order alone closes
    the cycle, so the realtime search must walk process edges too."""
    h = [
        ok(0, [["append", "x", 1]]),            # A (no invoke events)
        ok(0, [["append", "y", 1]]),            # B: process A -> B
        inv(1, [["r", "y", [1]]]),
        ok(1, [["r", "y", [1]]]),               # C: wr B -> C
        inv(2, [["r", "x", []]]),               # invoked after C completed
        ok(2, [["r", "x", []]]),                # D: realtime C -> D, rw D -> A
        inv(3, [["r", "x", [1]]]),
        ok(3, [["r", "x", [1]]]),               # E: establishes x order [1]
    ]
    strict = list_append.check(h, accelerator="cpu")
    assert strict["valid?"] is False
    assert "realtime-cycle" in strict["anomaly-types"], strict["anomaly-types"]
    serial = list_append.check(h, accelerator="cpu",
                               consistency_models=("serializable",))
    assert serial["valid?"] is True, serial


# ---------------------------------------------------------------------------
# richer rw-register version-order inference (round-2 strengthening)
# ---------------------------------------------------------------------------

def _rw_history(txns, procs=3):
    h = []
    for i, ops in enumerate(txns):
        h.append({"type": "invoke", "f": "txn", "process": i % procs,
                  "value": [[f, k, None if f == "r" else v]
                            for f, k, v in ops], "index": 2 * i})
        h.append({"type": "ok", "f": "txn", "process": i % procs,
                  "value": ops, "index": 2 * i + 1})
    return h


def test_wr_init_read_orders_before_all_writers():
    """G-single the old single-writer-only init inference missed: key 1
    has TWO writers, yet a None read of key 1 still proves the reader
    precedes both."""
    txns = [
        [["w", 0, 10], ["w", 1, 100]],            # W1: writes both keys
        [["w", 1, 101]],                          # W2: second writer of 1
        [["r", 0, 10], ["r", 1, None]],           # T: saw W1's key-0 write
    ]
    out = rw_register.check(_rw_history(txns), accelerator="cpu",
                            consistency_models=("serializable",))
    # wr edge W1->T (read 10); rw edge T->W1 (init read of key 1): cycle
    assert out["valid?"] is False
    assert "G-single" in out["anomaly-types"]


def test_wr_init_read_two_writers_acquits_consistent():
    """Same shape but consistent: T read key 0's initial state too, so T
    precedes everything — acyclic, serializable."""
    txns = [
        [["w", 0, 10], ["w", 1, 100]],
        [["w", 1, 101]],
        [["r", 0, None], ["r", 1, None]],
    ]
    out = rw_register.check(_rw_history(txns), accelerator="cpu",
                            consistency_models=("serializable",))
    assert out["valid?"] is True


def test_wr_cyclic_versions_detected():
    """Two txns whose traces order each other's writes both ways: the
    version graph 1->2->1 can't come from any register execution."""
    txns = [
        [["r", 0, 1], ["w", 0, 2]],   # traces 1 -> 2
        [["r", 0, 2], ["w", 0, 1]],   # traces 2 -> 1
    ]
    out = rw_register.check(_rw_history(txns), accelerator="cpu",
                            consistency_models=("read-uncommitted",))
    assert out["valid?"] is False
    assert "cyclic-versions" in out["anomaly-types"]
    (anom,) = out["anomalies"]["cyclic-versions"]
    assert anom["key"] == 0 and set(anom["versions"]) == {1, 2}


def test_wr_version_chain_composes_g_single():
    """Write-follows-read chains compose: T read v1; v1's successor chain
    v1->v2->v3 gives T rw-> writer(v2) ww-> writer(v3); if writer(v3)'s
    write was read by a txn T depends on, the cycle closes."""
    txns = [
        [["w", 0, 1]],                 # A
        [["r", 0, 1], ["w", 0, 2]],    # B traces 1->2
        [["r", 0, 2], ["w", 0, 3], ["w", 1, 30]],  # C traces 2->3, writes k1
        [["r", 1, 30], ["r", 0, 1]],   # T: depends on C (wr), but read STALE 1
    ]
    out = rw_register.check(_rw_history(txns), accelerator="cpu",
                            consistency_models=("serializable",))
    # T rw-> B (succ of 1) ww-> C wr-> T
    assert out["valid?"] is False
    assert "G-single" in out["anomaly-types"] or "G2" in out["anomaly-types"]


def test_list_append_fast_scan_matches_python_twin(monkeypatch):
    """The columnar per-key read scan and the pure-Python twin must emit
    identical anomalies across random histories seeded with every
    anomaly class it classifies (G1a, G1b, duplicates, incompatible
    orders, unobserved writers)."""
    import json
    import random as rnd

    def run(history, force_py):
        if force_py:
            with_mp = monkeypatch.context()
            with with_mp as m:
                m.setattr(list_append, "_scan_reads_fast",
                          lambda *a, **kw: False)
                return list_append.check(history, accelerator="cpu")
        return list_append.check(history, accelerator="cpu")

    rng = rnd.Random(97)
    for trial in range(40):
        n_keys = rng.randint(1, 3)
        vals = {k: [] for k in range(n_keys)}
        txns = []
        for i in range(rng.randint(3, 8)):
            ops = []
            k = rng.randrange(n_keys)
            n_app = rng.choice([1, 1, 1, 2])  # sometimes multi-append
            for _ in range(n_app):
                v = len(vals[k]) + 1000 * k
                vals[k].append(v)
                ops.append(["append", k, v])
            if rng.random() < 0.8:
                rk = rng.randrange(n_keys)
                ops.append(["r", rk, list(vals[rk])])
            txns.append(ops)
        history = []
        for i, ops in enumerate(txns):
            history.append({"type": "invoke", "process": i % 3, "f": "txn",
                            "value": [[f, k, None if f == "r" else v]
                                      for f, k, v in ops]})
            history.append({"type": "ok", "process": i % 3, "f": "txn",
                            "value": ops})
        # corruptions: drop a mid element (G1b/incompatible), duplicate an
        # element, insert a phantom, read a failed write
        c = rng.random()
        reads = [(ti, oi) for ti, t in enumerate(txns)
                 for oi, m in enumerate(t) if m[0] == "r" and len(m[2]) >= 2]
        if c < 0.5 and reads:
            ti, oi = reads[rng.randrange(len(reads))]
            r = list(txns[ti][oi][2])
            kind = rng.random()
            if kind < 0.3:
                del r[rng.randrange(len(r) - 1)]       # lose a mid element
            elif kind < 0.6:
                r.append(r[rng.randrange(len(r))])     # duplicate
            elif kind < 0.8:
                r.append(999_999)                      # phantom value
            else:
                r[0], r[1] = r[1], r[0]                # reorder
            txns[ti][oi][2] = r
        if c >= 0.5 and c < 0.6:
            history.append({"type": "fail", "process": 9, "f": "txn",
                            "value": [["append", 0, 777]]})
            if reads:
                ti, oi = reads[rng.randrange(len(reads))]
                txns[ti][oi][2] = list(txns[ti][oi][2]) + [777]

        fast = run(history, force_py=False)
        slow = run(history, force_py=True)
        assert fast["valid?"] == slow["valid?"], trial
        assert fast["anomaly-types"] == slow["anomaly-types"], (
            trial, fast["anomaly-types"], slow["anomaly-types"])
        for typ in fast["anomalies"]:
            f_recs = fast["anomalies"][typ]
            s_recs = slow["anomalies"][typ]
            if typ in ("G1c", "realtime-cycle", "process-cycle"):
                continue  # cycle exemplars may legitimately differ
            norm = lambda rs: sorted(  # noqa: E731
                json.dumps(x, sort_keys=True, default=repr) for x in rs)
            assert norm(f_recs) == norm(s_recs), (trial, typ)


def test_list_append_fast_scan_trailing_empty_read():
    """Regression: a trailing empty read must not steal the final element
    of its neighbour's segment (reduceat-clipping bug)."""
    txns = [
        [["append", 0, 1], ["append", 0, 2], ["append", 0, 3]],
        [["r", 0, [1, 2, 3]]],
        [["r", 0, [1, 9]]],   # stale/invented tail: incompatible-order
        [["r", 0, []]],
    ]
    history = []
    for i, ops in enumerate(txns):
        history.append({"type": "invoke", "process": i % 3, "f": "txn",
                        "value": ops})
        history.append({"type": "ok", "process": i % 3, "f": "txn",
                        "value": ops})
    out = list_append.check(history, accelerator="cpu",
                            consistency_models=("read-committed",))
    assert "incompatible-order" in out["anomaly-types"]


def test_list_append_fast_scan_rejects_float_domain():
    """Regression: float values must fall back to the Python twin, not
    truncate (2.7 -> 2 fabricated a G1a against a failed write)."""
    history = [
        {"type": "ok", "process": 0, "f": "txn",
         "value": [["append", 0, 2.1]]},
        {"type": "fail", "process": 1, "f": "txn",
         "value": [["append", 0, 2.7]]},
        {"type": "ok", "process": 2, "f": "txn",
         "value": [["r", 0, [2.1]]]},
    ]
    out = list_append.check(history, accelerator="cpu",
                            consistency_models=("serializable",))
    assert out["valid?"] is True, out["anomaly-types"]


def test_list_append_fast_scan_big_int_fallback():
    """Values at/above 2^53 can't be float-verified: the fast path must
    fall back to the Python twin rather than silently rounding them."""
    big = (1 << 53) + 1
    history = [
        {"type": "ok", "process": 0, "f": "txn",
         "value": [["append", 0, big]]},
        {"type": "ok", "process": 1, "f": "txn",
         "value": [["r", 0, [big]]]},
    ]
    out = list_append.check(history, accelerator="cpu",
                            consistency_models=("serializable",))
    assert out["valid?"] is True, out["anomaly-types"]
    assert out["read-scan-keys"]["python"] == 1


# ---------------------------------------------------------------------------
# φ-interval cluster path (production check_cycles) vs the cpu oracle
# ---------------------------------------------------------------------------

def test_phi_clusters_merge_intervals():
    from jepsen_tpu.elle import _phi_clusters
    import numpy as np

    # back edges (src_phi, dst_phi): [2,7], [5,9] overlap; [20,21] apart
    src_phi = np.asarray([7, 9, 21])
    dst_phi = np.asarray([2, 5, 20])
    assert _phi_clusters(src_phi, dst_phi) == [(2, 9), (20, 21)]
    # self-loop (equal phi) is its own point interval
    assert _phi_clusters(np.asarray([4]), np.asarray([4])) == [(4, 4)]


def test_batch_cluster_screen_exact():
    from jepsen_tpu.ops.scc import batch_cluster_screen
    import numpy as np

    # cluster 0: 3-cycle; cluster 1: acyclic chain; cluster 2: self-loop
    cid = np.asarray([0, 0, 0, 1, 1, 2], np.int32)
    src = np.asarray([0, 1, 2, 0, 1, 0], np.int32)
    dst = np.asarray([1, 2, 0, 1, 2, 0], np.int32)
    flags = batch_cluster_screen(cid, src, dst, 3, 3)
    assert flags.tolist() == [True, False, True]
    # empty edge set: nothing flagged
    z = np.zeros(0, np.int32)
    assert batch_cluster_screen(z, z, z, 2, 4).tolist() == [False, False]


def _interleaved_history(rng, n_txns=60, n_keys=3, corrupt=0):
    """Concurrent-process append history with real invoke/ok intervals
    (so φ exists), optionally corrupting reads to inject anomalies."""
    lists: dict = {}
    history = []
    open_ops: dict = {}
    procs = list(range(4))
    i = 0
    while i < n_txns or open_ops:
        p = rng.choice(procs)
        if p in open_ops:
            mops = open_ops.pop(p)
            applied = []
            for f, k, v in mops:
                if f == "append":
                    lists.setdefault(k, []).append(v)
                    applied.append(["append", k, v])
                else:
                    applied.append(["r", k, list(lists.get(k, []))])
            history.append({"type": "ok", "process": p, "f": "txn",
                            "value": applied})
        elif i < n_txns:
            mops = []
            for _ in range(rng.randrange(1, 3)):
                k = rng.randrange(n_keys)
                if rng.random() < 0.5:
                    mops.append(["r", k, None])
                else:
                    mops.append(["append", k, 1000 * (i + 1) + len(mops)])
            history.append({"type": "invoke", "process": p, "f": "txn",
                            "value": mops})
            open_ops[p] = mops
            i += 1
    for _ in range(corrupt):
        oks = [op for op in history if op["type"] == "ok"]
        op = rng.choice(oks)
        reads = [m for m in op["value"] if m[0] == "r"]
        if reads:
            m = rng.choice(reads)
            m[2] = list(m[2][:-1]) if m[2] else [rng.randrange(5)]
    return history


def test_phi_path_parity_fuzz_vs_cpu_oracle():
    """The φ-cluster production path must reach the same verdict and
    anomaly-type set as the trim+Tarjan cpu oracle on fuzzed concurrent
    histories, clean and corrupted alike."""
    rng = random.Random(7)
    saw_invalid = saw_valid = 0
    for trial in range(40):
        h = _interleaved_history(rng, corrupt=rng.randrange(3))
        r_fast = list_append.check(h, accelerator="auto")
        r_cpu = list_append.check(h, accelerator="cpu")
        assert r_fast["valid?"] == r_cpu["valid?"], (trial, r_fast, r_cpu)
        assert r_fast["anomaly-types"] == r_cpu["anomaly-types"], (
            trial, r_fast["anomaly-types"], r_cpu["anomaly-types"])
        if r_cpu["valid?"]:
            saw_valid += 1
        else:
            saw_invalid += 1
    assert saw_valid >= 5 and saw_invalid >= 5, (saw_valid, saw_invalid)


def test_phi_path_device_screen_parity():
    """Force the device (virtual-cpu jax here) batched screen and check it
    agrees with the oracle on a history with injected wr cycles."""
    rng = random.Random(11)
    h = _interleaved_history(rng, corrupt=2)
    r_dev = list_append.check(h, accelerator="tpu")
    r_cpu = list_append.check(h, accelerator="cpu")
    assert r_dev["valid?"] == r_cpu["valid?"]
    assert r_dev["anomaly-types"] == r_cpu["anomaly-types"]


def test_phi_path_timing_cycles_parity():
    """Realtime/process cycles must survive the cluster decomposition:
    a stale read closed by realtime order is found by both paths."""
    h = [
        {"type": "invoke", "process": 0, "f": "txn",
         "value": [["append", "x", 1]]},
        {"type": "ok", "process": 0, "f": "txn",
         "value": [["append", "x", 1]]},
        {"type": "invoke", "process": 1, "f": "txn",
         "value": [["append", "x", 2], ["r", "x", None]]},
        {"type": "ok", "process": 1, "f": "txn",
         "value": [["append", "x", 2], ["r", "x", [1, 2]]]},
        # realtime-after both, but reads the pre-2 state: stale
        {"type": "invoke", "process": 2, "f": "txn",
         "value": [["r", "x", None]]},
        {"type": "ok", "process": 2, "f": "txn",
         "value": [["r", "x", [1]]]},
    ]
    r_fast = list_append.check(h, accelerator="auto")
    r_cpu = list_append.check(h, accelerator="cpu")
    assert r_fast["valid?"] is False and r_cpu["valid?"] is False
    assert r_fast["anomaly-types"] == r_cpu["anomaly-types"]


def test_phi_path_oversized_cluster_falls_back(monkeypatch):
    """Clusters beyond MATRIX_CLUSTER_MAX must still be classified
    exactly (straight to the host pass, no matrix)."""
    import jepsen_tpu.elle as elle_mod

    monkeypatch.setattr(elle_mod, "MATRIX_CLUSTER_MAX", 2)
    rng = random.Random(13)
    h = _interleaved_history(rng, corrupt=2)
    r_fast = list_append.check(h, accelerator="auto")
    r_cpu = list_append.check(h, accelerator="cpu")
    assert r_fast["valid?"] == r_cpu["valid?"]
    assert r_fast["anomaly-types"] == r_cpu["anomaly-types"]


# ---------------------------------------------------------------------------
# columnar builder vs Python-builder oracle
# ---------------------------------------------------------------------------

def _messy_history(rng, n_txns=50):
    """History exercising every columnar corner: multi-appends, failed
    writes, info txns, empty reads, then random corruptions (dropped
    elements, duplicated elements, failed-value reads, phantom values)."""
    lists: dict = {}
    history = []
    vc = [0]

    def nv():
        vc[0] += 1
        return vc[0]

    for i in range(n_txns):
        p = i % 5
        k = rng.randrange(3)
        kind = rng.random()
        if kind < 0.15:
            # failed multi-append
            vals = [nv() for _ in range(rng.randrange(1, 3))]
            mops = [["append", k, v] for v in vals]
            history.append({"type": "invoke", "process": p, "f": "txn",
                            "value": [[f, kk, vv] for f, kk, vv in mops]})
            history.append({"type": "fail", "process": p, "f": "txn",
                            "value": mops})
            continue
        mops = []
        for _ in range(rng.randrange(1, 4)):
            if rng.random() < 0.5:
                mops.append(["r", k, None])
            else:
                v = nv()
                lists.setdefault(k, []).append(v)
                mops.append(["append", k, v])
        applied = [
            ["r", m[1], list(lists.get(m[1], []))] if m[0] == "r" else m
            for m in mops]
        history.append({"type": "invoke", "process": p, "f": "txn",
                        "value": mops})
        t = "info" if kind < 0.22 else "ok"
        history.append({"type": t, "process": p, "f": "txn",
                        "value": applied if t == "ok" else mops})
    # corruptions
    for _ in range(rng.randrange(4)):
        oks = [op for op in history if op["type"] == "ok"]
        op = rng.choice(oks)
        reads = [m for m in op["value"] if m[0] == "r"]
        if not reads:
            continue
        m = rng.choice(reads)
        roll = rng.random()
        if roll < 0.3 and m[2]:
            m[2] = list(m[2][:-1])          # dropped tail element
        elif roll < 0.5 and m[2]:
            m[2] = list(m[2]) + [m[2][0]]   # duplicated element
        elif roll < 0.75:
            m[2] = list(m[2]) + [vc[0] + rng.randrange(1, 9)]  # phantom
        else:
            m[2] = [rng.randrange(1, vc[0] + 1)]  # arbitrary single value
    return history


def test_columnar_builder_parity_fuzz():
    """The columnar builder must reach the oracle's verdict and
    anomaly-type set on messy histories (multi-appends, fails, infos,
    corrupted reads)."""
    rng = random.Random(23)
    invalid = 0
    for trial in range(60):
        h = _messy_history(rng)
        r_col = list_append.check(h, accelerator="auto")
        r_cpu = list_append.check(h, accelerator="cpu")
        assert r_col.get("builder") == "columnar", "fast path must engage"
        assert r_col["valid?"] == r_cpu["valid?"], (trial, r_col, r_cpu)
        assert r_col["anomaly-types"] == r_cpu["anomaly-types"], (
            trial, r_col["anomaly-types"], r_cpu["anomaly-types"])
        assert r_col["edge-count"] == r_cpu["edge-count"], trial
        invalid += 0 if r_cpu["valid?"] else 1
    assert invalid >= 15, invalid


def test_columnar_falls_back_on_non_int_domains():
    for bad_val in ("s", 2.5, True, (1 << 53) + 1):
        h = [
            {"type": "ok", "process": 0, "f": "txn",
             "value": [["append", 0, bad_val]]},
            {"type": "ok", "process": 1, "f": "txn",
             "value": [["r", 0, [bad_val]]]},
        ]
        r = list_append.check(h, accelerator="auto")
        assert "builder" not in r, bad_val  # python builder took over


def test_columnar_out_of_range_read_value_no_writer_collision():
    """Regression: a corrupt read ending in a value >= 2^32 must not
    alias another key's writer through the 32-bit composite join."""
    h = [
        {"type": "ok", "process": 0, "f": "txn",
         "value": [["append", 0, 7]]},
        {"type": "ok", "process": 1, "f": "txn",
         "value": [["append", 1, 1]]},
        # key-1 read whose last element is (1<<32)|7 — with kid=1 the
        # composite equals key-0's append of 7 if unmasked
        {"type": "ok", "process": 2, "f": "txn",
         "value": [["r", 1, [(1 << 32) | 7]]]},
    ]
    r_col = list_append.check(h, accelerator="auto")
    r_cpu = list_append.check(h, accelerator="cpu")
    assert r_col["edge-count"] == r_cpu["edge-count"]
    assert r_col["anomaly-types"] == r_cpu["anomaly-types"]


def test_columnar_spine_tie_break_matches_oracle():
    """Regression: on equal-length conflicting reads the spine must be
    the FIRST longest read (the oracle's max(key=len) semantics)."""
    h = [
        {"type": "ok", "process": 0, "f": "txn",
         "value": [["append", 0, 1]]},
        {"type": "ok", "process": 1, "f": "txn",
         "value": [["append", 0, 2]]},
        {"type": "ok", "process": 2, "f": "txn",
         "value": [["append", 0, 3]]},
        {"type": "ok", "process": 3, "f": "txn",
         "value": [["r", 0, [1, 2]]]},
        {"type": "ok", "process": 4, "f": "txn",
         "value": [["r", 0, [1, 3]]]},
    ]
    r_col = list_append.check(h, accelerator="auto")
    r_cpu = list_append.check(h, accelerator="cpu")
    assert r_col["anomaly-types"] == r_cpu["anomaly-types"]
    assert r_col["edge-count"] == r_cpu["edge-count"]


def test_batch_cluster_screen_chunks_over_budget(monkeypatch):
    """Batches beyond the element budget split along the cluster axis
    without changing verdicts."""
    from jepsen_tpu.ops import scc as scc_mod
    import numpy as np

    monkeypatch.setattr(scc_mod, "SCREEN_MAX_ELEMS", 8 * 8 * 2)  # 2/chunk
    cid = np.asarray([0, 0, 1, 2, 2, 2, 4], np.int32)
    src = np.asarray([0, 1, 0, 0, 1, 2, 0], np.int32)
    dst = np.asarray([1, 0, 1, 1, 2, 0, 0], np.int32)
    flags = scc_mod.batch_cluster_screen(cid, src, dst, 5, 3)
    assert flags.tolist() == [True, False, True, False, True]


def test_columnar_fast_flatten_fallbacks():
    """The vectorized pass B declines regimes the general loop handles —
    huge int keys (beyond int64), non-int keys, bool append values —
    and _build still produces a columnar result for them."""
    from jepsen_tpu.elle import columnar

    def h(key, val=1):
        return [
            {"type": "invoke", "process": 0,
             "value": [["append", key, val]]},
            {"type": "ok", "process": 0, "value": [["append", key, val]]},
            {"type": "invoke", "process": 0, "value": [["r", key, None]]},
            {"type": "ok", "process": 0, "value": [["r", key, [val]]]},
        ]

    for key in (1 << 63, -(1 << 63) - 1, "k"):
        types = [op.get("type") for op in h(key)]
        txns = [op for op, t in zip(h(key), types) if t == "ok"]
        assert columnar._flatten_mops_fast(txns) is None, key
        parts = columnar._build(h(key))   # general loop still builds
        assert parts is not None, key
        graph, txns_out, extras, n_keys = parts
        assert n_keys == 1 and len(txns_out) == 2

    # bool append value: BOTH paths decline (python builder territory)
    txns = [op for op in h(0, True) if op["type"] == "ok"]
    assert columnar._flatten_mops_fast(txns) is None
    assert columnar._build(h(0, True)) is None


def test_c_front_vs_python_front_parity_fuzz(monkeypatch):
    """The native C parser front (native/columnar_ext.c) and the
    numpy Python front must produce identical results — verdict,
    anomaly types, edge counts, extras — on messy histories. When the
    C extension is unavailable this reduces to a self-check."""
    from jepsen_tpu.elle import columnar
    from jepsen_tpu.native import columnar_c

    if not columnar_c.available():
        pytest.skip("C toolchain unavailable")

    rng = random.Random(71)
    engaged = 0
    for trial in range(40):
        h = _messy_history(rng)
        r_c = list_append.check(h, accelerator="auto")
        with monkeypatch.context() as mp:
            mp.setattr(columnar, "_cmod", lambda: None)
            r_py = list_append.check(h, accelerator="auto")
        if r_c.get("builder") != "columnar":
            assert r_py.get("builder") != "columnar", trial
            continue
        engaged += 1
        assert r_c["valid?"] == r_py["valid?"], (trial, r_c, r_py)
        assert r_c["anomaly-types"] == r_py["anomaly-types"], trial
        assert r_c["edge-count"] == r_py["edge-count"], trial
        assert r_c["txn-count"] == r_py["txn-count"], trial
        assert r_c["anomalies"] == r_py["anomalies"], trial
    assert engaged >= 30, engaged


def test_c_front_bails_match_python_front(monkeypatch):
    """Inputs the C parser declines must still produce the same final
    result through whichever builder takes over."""
    from jepsen_tpu.native import columnar_c

    if not columnar_c.available():
        pytest.skip("C toolchain unavailable")
    cases = [
        # non-int key (general loop path)
        [{"type": "ok", "process": 0, "value": [["append", "k", 1]]},
         {"type": "ok", "process": 1, "value": [["r", "k", [1]]]}],
        # bool append value (python builder path)
        [{"type": "ok", "process": 0, "value": [["append", 0, True]]}],
        # tuple micro-op container and tuple payload
        [{"type": "ok", "process": 0, "value": (("append", 0, 1),)},
         {"type": "ok", "process": 1, "value": [("r", 0, (1,))]}],
        # out-of-range append value
        [{"type": "ok", "process": 0, "value": [["append", 0, 1 << 33]]}],
        # huge int key: C path interns objects, numpy front declines
        [{"type": "ok", "process": 0, "value": [["append", 1 << 70, 1]]},
         {"type": "ok", "process": 1, "value": [["r", 1 << 70, [1]]]}],
        # non-string process on an ok op (dropped from txn set)
        [{"type": "ok", "process": "nemesis", "value": [["append", 0, 1]]},
         {"type": "ok", "process": 0, "value": [["append", 0, 2]]},
         {"type": "ok", "process": 1, "value": [["r", 0, [2]]]}],
    ]
    from jepsen_tpu.elle import columnar
    for i, h in enumerate(cases):
        r_c = list_append.check(h, accelerator="auto")
        with monkeypatch.context() as mp:
            mp.setattr(columnar, "_cmod", lambda: None)
            r_py = list_append.check(h, accelerator="auto")
        assert r_c["valid?"] == r_py["valid?"], (i, r_c, r_py)
        assert r_c["anomaly-types"] == r_py["anomaly-types"], i


def test_stored_columns_roundtrip_clean(tmp_path):
    """parse_columns -> npz save/load -> check_columns must equal the
    object-path check on a clean history, with no object access."""
    import numpy as np

    from jepsen_tpu.elle import columnar

    h = []
    t = 0
    for i in range(400):
        k = i % 7
        seen = list(range(k, i + 1, 7))
        h.append({"type": "invoke", "process": i % 5,
                  "value": [["append", k, i], ["r", k, None]], "time": t})
        h.append({"type": "ok", "process": i % 5,
                  "value": [["append", k, i], ["r", k, seen]],
                  "time": t + 1})
        t += 2
    cols = columnar.parse_columns(h)
    if cols is None:
        pytest.skip("C parser unavailable")
    p = tmp_path / "cols.npz"
    np.savez_compressed(p, **cols)
    with np.load(p) as z:
        loaded = {k: z[k] for k in z.files}
    r = columnar.check_columns(loaded, accelerator="auto")
    r0 = list_append.check(h, accelerator="auto")
    for key in ("valid?", "anomaly-types", "edge-count", "txn-count"):
        assert r[key] == r0[key], key
    assert r["builder"] == "columnar-store"


def test_stored_columns_anomalous_needs_objects():
    """Findings that cite txn objects must raise NeedsObjects instead
    of fabricating citations."""
    from jepsen_tpu.elle import columnar

    h = [
        {"type": "ok", "process": 0, "value": [["append", 0, 1]]},
        {"type": "ok", "process": 1,
         "value": [["r", 0, [1, 99]]]},   # phantom + order trouble
        {"type": "fail", "process": 2, "value": [["append", 0, 99]]},
    ]
    cols = columnar.parse_columns(h)
    if cols is None:
        pytest.skip("C parser unavailable")
    with pytest.raises(columnar.NeedsObjects):
        columnar.check_columns(cols)


def test_stored_columns_non_txn_extras_complete():
    """Extras that never cite txns (duplicate appends) complete from
    columns alone."""
    from jepsen_tpu.elle import columnar

    h = [
        {"type": "ok", "process": 0, "value": [["append", 0, 1]]},
        {"type": "ok", "process": 1, "value": [["append", 0, 1]]},  # dup
        {"type": "ok", "process": 2, "value": [["r", 0, [1]]]},
    ]
    cols = columnar.parse_columns(h)
    if cols is None:
        pytest.skip("C parser unavailable")
    r = columnar.check_columns(cols)
    r0 = list_append.check(h, accelerator="auto")
    assert r["anomaly-types"] == r0["anomaly-types"]
    assert "duplicate-appends" in r["anomalies"]


def test_check_stored_prefers_sidecar(tmp_path):
    """An append-workload run saved through the store re-checks from
    the elle_* sidecar columns (and matches a fresh object check)."""
    from jepsen_tpu import store
    from jepsen_tpu.elle import columnar, list_append as la

    h = []
    for i in range(50):
        k = i % 3
        seen = list(range(k, i + 1, 3))
        h.append({"type": "invoke", "process": i % 5,
                  "value": [["append", k, i]], "time": 2 * i})
        h.append({"type": "ok", "process": i % 5,
                  "value": [["append", k, i], ["r", k, seen]],
                  "time": 2 * i + 1})
    test = {"name": "elle-store-t", "start_time": "20260731T000000",
            "store_dir": str(tmp_path), "history": h}
    store.write_history(test)
    store.write_columnar(test)
    cols = store.load_elle_columns("elle-store-t", "20260731T000000",
                                   str(tmp_path))
    if cols is None:
        pytest.skip("C parser unavailable")
    r = la.check_stored("elle-store-t", "20260731T000000", str(tmp_path),
                        accelerator="auto")
    assert r["builder"] == "columnar-store"
    assert r["valid?"] == la.check(h)["valid?"] is True


def test_stored_columns_parity_fuzz():
    """On messy histories, the stored-column check must either agree
    with the object path in full or raise NeedsObjects exactly when the
    object path's findings cite txn values."""
    from jepsen_tpu.elle import columnar

    rng = random.Random(97)
    compared = deferred = 0
    for trial in range(40):
        h = _messy_history(rng)
        cols = columnar.parse_columns(h)
        if cols is None:
            continue
        r0 = list_append.check(h, accelerator="auto")
        try:
            r = columnar.check_columns(cols, accelerator="auto")
        except columnar.NeedsObjects:
            deferred += 1
            # the object path must indeed have txn-citing output:
            # a cycle, or a G1a/G1b style extra carrying txn values
            citing = bool(r0.get("anomalies")) and any(
                k in r0["anomalies"]
                for k in ("G1a", "G1b", "G0", "G1c", "G-single", "G2",
                          "G2-item", "realtime", "process"))
            assert citing or not r0["valid?"], (trial, r0)
            continue
        compared += 1
        assert r["valid?"] == r0["valid?"], (trial, r, r0)
        assert r["anomaly-types"] == r0["anomaly-types"], trial
        assert r["edge-count"] == r0["edge-count"], trial
    assert compared >= 5 and deferred >= 5, (compared, deferred)
