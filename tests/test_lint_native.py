"""Native-code correctness plane: JTN lint rules + fuzz determinism.

One broken/fixed C fixture pair per JTN diagnostic (the
``_lint_source`` pattern from test_analysis.py, over ``.c`` files),
the C-side waiver grammar, the parse cache, glob rule selection, and
the fuzz harness's seeded-determinism contract
(doc/static-analysis.md "Native code").
"""
from __future__ import annotations

import textwrap

import pytest

from jepsen_tpu.analysis import lint as lint_mod
from jepsen_tpu.analysis.lint import csrc

pytestmark = pytest.mark.lint


def _lint_c(tmp_path, source, rules=None, name="fx.c"):
    d = tmp_path / "cfix"
    d.mkdir(exist_ok=True)
    (d / name).write_text(textwrap.dedent(source), encoding="utf-8")
    rep = lint_mod.lint_paths([str(d)], baseline=False, rules=rules)
    return rep.findings


class TestJTNRules:
    def test_alloc_check_deref_fires_and_checked_silent(self, tmp_path):
        bad = """
            static int use(void) {
                char *p;
                p = malloc(16);
                p[0] = 'x';
                return 0;
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-alloc-check"])
        assert [f.code for f in finds] == ["JTN001"]
        assert finds[0].qualname == "use"
        good = bad.replace("p[0] = 'x';",
                           "if (!p) return -1;\n    p[0] = 'x';")
        assert _lint_c(tmp_path, good, rules=["jtn-alloc-check"]) == []

    def test_alloc_check_pyarg_discarded(self, tmp_path):
        bad = """
            static PyObject *meth(PyObject *self, PyObject *args) {
                long v;
                PyArg_ParseTuple(args, "l", &v);
                return PyLong_FromLong(v);
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-alloc-check"])
        assert [f.code for f in finds] == ["JTN001"]
        good = bad.replace(
            'PyArg_ParseTuple(args, "l", &v);',
            'if (!PyArg_ParseTuple(args, "l", &v)) return NULL;')
        assert _lint_c(tmp_path, good, rules=["jtn-alloc-check"]) == []

    def test_cleanup_return_bypass_fires_and_goto_silent(self, tmp_path):
        bad = """
            static PyObject *mk(PyObject *o) {
                PyObject *d = PyDict_New();
                if (!d) goto fail;
                if (PyDict_SetItem(d, o, o) < 0) return NULL;
                return d;
            fail:
                Py_XDECREF(d);
                return NULL;
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-cleanup-return"])
        assert [f.code for f in finds] == ["JTN002"]
        good = bad.replace("< 0) return NULL;", "< 0) goto fail;")
        assert _lint_c(tmp_path, good, rules=["jtn-cleanup-return"]) == []

    def test_errcheck_fires_and_pyerr_occurred_silent(self, tmp_path):
        bad = """
            static long gx(PyObject *o) {
                long v = PyLong_AsLong(o);
                return v + 1;
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-errcheck"])
        assert [f.code for f in finds] == ["JTN003"]
        good = bad.replace(
            "return v + 1;",
            "if (v == -1 && PyErr_Occurred()) return -1;\n"
            "    return v + 1;")
        assert _lint_c(tmp_path, good, rules=["jtn-errcheck"]) == []

    def test_gil_call_fires_and_blocked_silent(self, tmp_path):
        bad = """
            static void work(PyObject *o, char *buf, int n) {
                Py_BEGIN_ALLOW_THREADS
                scan(buf, n);
                PyList_Append(o, o);
                Py_END_ALLOW_THREADS
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-gil-call"])
        assert [f.code for f in finds] == ["JTN004"]
        # re-acquiring with Py_BLOCK_THREADS makes the call legal
        good = bad.replace(
            "PyList_Append(o, o);",
            "Py_BLOCK_THREADS\n    PyList_Append(o, o);\n"
            "    Py_UNBLOCK_THREADS")
        assert _lint_c(tmp_path, good, rules=["jtn-gil-call"]) == []

    def test_bounds_guard_fires_and_masked_or_compared_silent(
            self, tmp_path):
        bad = """
            static void fill(char *buf, int n) {
                int i = n + 2;
                buf[i] = 'x';
            }
        """
        finds = _lint_c(tmp_path, bad, rules=["jtn-bounds-guard"])
        assert [f.code for f in finds] == ["JTN005"]
        compared = bad.replace("buf[i] = 'x';",
                               "if (i < n) buf[i] = 'x';")
        assert _lint_c(tmp_path, compared,
                       rules=["jtn-bounds-guard"]) == []
        # the open-addressing probe idiom: a mask assignment IS the bound
        masked = bad.replace("int i = n + 2;", "int i = n & (16 - 1);")
        assert _lint_c(tmp_path, masked, rules=["jtn-bounds-guard"]) == []


class TestCWaivers:
    BAD = """
        static void fill(char *buf, int n) {
            int i = n + 2;
            buf[i] = 'x';
        }
    """

    def test_trailing_waiver(self, tmp_path):
        src = self.BAD.replace(
            "buf[i] = 'x';",
            "buf[i] = 'x'; /* lint: ignore[jtn-bounds-guard] */")
        assert _lint_c(tmp_path, src, rules=["jtn-bounds-guard"]) == []

    def test_line_above_waiver(self, tmp_path):
        src = self.BAD.replace(
            "buf[i] = 'x';",
            "/* i is caller-bounded: lint: ignore[jtn-bounds-guard] */\n"
            "    buf[i] = 'x';")
        assert _lint_c(tmp_path, src, rules=["jtn-bounds-guard"]) == []

    def test_function_level_boxed_waiver(self, tmp_path):
        # a multi-line boxed why-comment directly above the signature
        # waives the whole function (the csrc comment-map carries the
        # marker to the comment's END line)
        src = ("/* every index here is bounded by the caller's\n"
               " * contract — lint: ignore[jtn-bounds-guard] */\n"
               + textwrap.dedent(self.BAD).lstrip("\n"))
        d = tmp_path / "cfix"
        d.mkdir(exist_ok=True)
        (d / "fx.c").write_text(src, encoding="utf-8")
        rep = lint_mod.lint_paths([str(d)], baseline=False,
                                  rules=["jtn-bounds-guard"])
        assert rep.findings == []

    def test_skip_file(self, tmp_path):
        src = "/* lint: skip-file */\n" + textwrap.dedent(self.BAD)
        assert _lint_c(tmp_path, src, rules=["jtn-bounds-guard"]) == []

    def test_unwaived_still_fires(self, tmp_path):
        assert len(_lint_c(tmp_path, self.BAD,
                           rules=["jtn-bounds-guard"])) == 1


class TestDriverIntegration:
    def test_glob_rule_selection(self, tmp_path):
        # 'jtn-*' expands to exactly the C rule family
        assert lint_mod.resolve_rules(["jtn-*"]) == {
            name for name, _fn in lint_mod.C_RULES}
        with pytest.raises(ValueError):
            lint_mod.resolve_rules(["jtn-nope*"])

    def test_c_files_collected_by_default(self, tmp_path):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "a.py").write_text("x = 1\n", encoding="utf-8")
        (d / "b.c").write_text(
            "static void f(char *b, int n) { int i = n; b[i] = 1; }\n",
            encoding="utf-8")
        rep = lint_mod.lint_paths([str(d)], baseline=False)
        assert rep.files == 2
        assert any(f.code == "JTN005" for f in rep.findings)

    def test_parse_cache_stamp(self, tmp_path):
        p = tmp_path / "c.c"
        p.write_text("static int f(void) { return 0; }\n",
                     encoding="utf-8")
        m1 = csrc.parse_c_module(p)
        m2 = csrc.parse_c_module(p)
        assert m1 is m2  # unchanged stamp -> cache hit
        p.write_text("static int g(void) { return 1; }\n",
                     encoding="utf-8")
        m3 = csrc.parse_c_module(p)
        assert m3 is not m1 and "g" in m3.functions

    def test_real_native_sources_lint_clean(self):
        # the acceptance gate: zero non-baselined JTN findings over the
        # shipped C sources (safe idioms carry inline waivers, not
        # baseline entries)
        from pathlib import Path
        import jepsen_tpu
        native = Path(jepsen_tpu.__file__).parent / "native"
        srcs = sorted(str(p) for p in native.glob("*.c*"))
        assert srcs, "native sources moved?"
        rep = lint_mod.lint_paths(srcs, baseline=False, rules=["jtn-*"])
        assert rep.findings == [], \
            "\n".join(f.render() for f in rep.findings)


class TestFuzzDeterminism:
    def test_mutant_stream_is_seed_deterministic(self):
        from jepsen_tpu.fuzz import native as fn
        a = [(i, bytes(d), s, tuple(o))
             for i, d, s, o in fn.mutant_stream(1234, 300)]
        b = [(i, bytes(d), s, tuple(o))
             for i, d, s, o in fn.mutant_stream(1234, 300)]
        assert a == b  # same seed => byte-identical mutant stream
        c = [d for _i, d, _s, _o in fn.mutant_stream(1235, 300)]
        assert [d for _i, d, _s, _o in a] != c

    def test_exec_rng_is_per_exec_independent(self):
        # exec i's mutant does not depend on how many execs ran before
        # it — artifacts replay by (seed, exec) alone
        from jepsen_tpu.fuzz import native as fn
        solo = fn.mutant(fn.exec_rng(7, 250))
        stream = list(fn.mutant_stream(7, 251))[-1]
        assert stream[1] == solo[0] and stream[2] == solo[1]

    def test_corpus_seeds_cover_the_nasty_shapes(self):
        from jepsen_tpu.fuzz import native as fn
        names = {n for n, _ in fn.SEEDS}
        assert {"happy", "torn-final", "torn-interior", "unicode",
                "numbers", "fleet-chunk"} <= names
        # every seed must itself survive the Python tolerant parser
        from jepsen_tpu.journal import parse_wal_chunk_py
        for _name, data in fn.SEEDS:
            ops, consumed, torn, truncated = parse_wal_chunk_py(
                data, final=True)
            assert consumed == len(data)
