"""Membership + clock-rate nemesis tier (doc/robustness.md "Membership
and clock-rate faults"): the modeled reconfiguration state machine, its
durable fault records and exactly-once rejoin heal, deadline interplay,
preflight NEM diagnostics, and the faketime clock-rate package.

The SIGKILL chaos scenario rides the slow lane (``-m 'membership and
slow'``); everything else is quick."""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error

import pytest

from jepsen_tpu import telemetry
from jepsen_tpu.fakes import FakeClusterState
from jepsen_tpu.nemesis import membership
from jepsen_tpu.nemesis.faults import FaultRegistry, replay_unhealed
from jepsen_tpu.utils import with_relative_time

pytestmark = pytest.mark.membership

NODES = ["n1", "n2", "n3", "n4", "n5"]


@pytest.fixture
def metrics_registry():
    reg = telemetry.Registry()
    prev = telemetry.install(reg)
    try:
        yield reg
    finally:
        telemetry.install(prev)


def _run(test):
    from jepsen_tpu.generator import interpreter
    with with_relative_time():
        return interpreter.run(test)


# ---------------------------------------------------------------------------
# FakeClusterState: the durable fake cluster
# ---------------------------------------------------------------------------

def test_fake_cluster_state_durable_roundtrip(tmp_path):
    p = tmp_path / "members.json"
    st = FakeClusterState(p, nodes=NODES)
    assert st.members() == set(NODES)
    assert json.loads(p.read_text()) == sorted(NODES)
    out = st.invoke({}, {"f": "shrink", "value": "n5"})
    assert out["action"] == "shrink"
    assert json.loads(p.read_text()) == ["n1", "n2", "n3", "n4"]
    # a NEW state over the same file sees the shrunken set: the file IS
    # the cluster, so reconfigurations survive a control-process crash
    st2 = FakeClusterState(p, nodes=NODES)
    assert st2.members() == {"n1", "n2", "n3", "n4"}
    # op() proposes growing the missing node back
    op = st2.op({"nodes": NODES})
    assert (op["f"], op["value"]) == ("grow", "n5")


def test_fake_cluster_state_settle_window(tmp_path):
    st = FakeClusterState(tmp_path / "m.json", nodes=NODES, settle_s=30.0)
    val = st.invoke({}, {"f": "shrink", "value": "n5"})
    # in flight: unresolved, and no second op proposed
    assert st.resolve_op({}, ({"f": "shrink"}, val)) is None
    assert st.op({"nodes": NODES}) == "pending"
    fast = FakeClusterState(tmp_path / "m2.json", nodes=NODES, settle_s=0.0)
    val = fast.invoke({}, {"f": "shrink", "value": "n5"})
    assert fast.resolve_op({}, ({"f": "shrink"}, val)) is fast


def test_restore_members_file_idempotent(tmp_path):
    p = tmp_path / "members.json"
    st = FakeClusterState(p, nodes=NODES)
    st.invoke({}, {"f": "shrink", "value": "n5"})
    row = {"id": 0, "kind": "membership",
           "value": {"pre_members": sorted(NODES),
                     "heal": st.heal_spec({})}}
    membership.heal_record({}, row)
    assert json.loads(p.read_text()) == sorted(NODES)
    membership.heal_record({}, row)  # idempotent
    assert json.loads(p.read_text()) == sorted(NODES)


def test_heal_record_rejects_missing_spec():
    from jepsen_tpu.nemesis.faults import Unhealable
    with pytest.raises(Unhealable, match="no heal spec"):
        membership.heal_record({}, {"id": 1, "value": {"pre_members": []}})
    with pytest.raises(Unhealable, match="unknown membership heal"):
        membership.heal_record({}, {"id": 1, "value": {
            "pre_members": [], "heal": {"mechanism": "telepathy"}}})
    with pytest.raises(Unhealable, match="not importable"):
        membership.heal_record({}, {"id": 1, "value": {
            "pre_members": [], "heal": {"mechanism": "import",
                                        "module": "no.such.module",
                                        "fn": "nope"}}})


# ---------------------------------------------------------------------------
# MembershipNemesis: records, resolution heal, thread safety, bounds
# ---------------------------------------------------------------------------

def test_invoke_records_pre_op_set_and_heals_on_resolve(tmp_path,
                                                        metrics_registry):
    st = FakeClusterState(tmp_path / "m.json", nodes=NODES, settle_s=0.0)
    n = membership.MembershipNemesis(st, poll_interval=0.05)
    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"nodes": NODES, "_faults": registry}
    out = n.invoke(test, {"type": "info", "f": "shrink", "value": "n5"})
    assert out["type"] == "info"
    rows = [json.loads(line)
            for line in (tmp_path / "faults.jsonl").read_text().splitlines()]
    injects = [r for r in rows if r["op"] == "inject"]
    heals = [r for r in rows if r["op"] == "heal"]
    assert len(injects) == 1 and injects[0]["kind"] == "membership"
    assert injects[0]["value"]["pre_members"] == sorted(NODES)
    assert injects[0]["value"]["heal"]["mechanism"] == "file"
    # settle_s=0: the trailing resolve pass already marked it healed
    assert heals and heals[0]["via"] == "resolve"
    assert registry.unhealed() == []
    registry.close()
    reg = metrics_registry
    assert reg.counter("nemesis_membership_ops_total",
                       labels=("f",)).value(f="shrink") == 1
    assert reg.counter("nemesis_membership_resolves_total",
                       labels=("f",)).value(f="shrink") == 1


def test_unresolved_op_stays_unhealed_and_replays(tmp_path):
    """A reconfig that never resolves (settle window) leaves its entry
    on the books; replay_unhealed restores the recorded pre-op set
    exactly once."""
    p = tmp_path / "m.json"
    st = FakeClusterState(p, nodes=NODES, settle_s=600.0)
    n = membership.MembershipNemesis(st, poll_interval=0.05)
    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"nodes": NODES, "_faults": registry}
    n.invoke(test, {"type": "info", "f": "shrink", "value": "n5"})
    assert json.loads(p.read_text()) == ["n1", "n2", "n3", "n4"]
    assert [r["kind"] for r in registry.unhealed()] == ["membership"]
    out = replay_unhealed({"nodes": NODES}, registry)
    assert len(out["healed"]) == 1
    assert json.loads(p.read_text()) == sorted(NODES)  # pre-op set back
    # exactly once: a second replay is a no-op even if the file moved on
    p.write_text(json.dumps(["sentinel"]))
    out2 = replay_unhealed({"nodes": NODES}, registry)
    assert out2 == {"healed": [], "unhealable": [], "failed": []}
    assert json.loads(p.read_text()) == ["sentinel"]
    registry.close()


def test_newest_first_replay_restores_oldest_pre_op_set(tmp_path):
    """Two stranded reconfigs: the replay must end on the OLDEST
    record's pre-op set — the cluster as it was before the first
    stranded op."""
    p = tmp_path / "m.json"
    st = FakeClusterState(p, nodes=NODES, settle_s=600.0)
    n = membership.MembershipNemesis(st, poll_interval=0.05)
    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"nodes": NODES, "_faults": registry}
    n.invoke(test, {"type": "info", "f": "shrink", "value": "n5"})
    n.invoke(test, {"type": "info", "f": "shrink", "value": "n4"})
    assert json.loads(p.read_text()) == ["n1", "n2", "n3"]
    replay_unhealed({"nodes": NODES}, registry)
    assert json.loads(p.read_text()) == sorted(NODES)
    registry.close()


def test_resolve_fixed_point_bounded(metrics_registry):
    """A State that resolves at most one op per pass cannot spin the
    fixed point past max_resolve_iters; the cap is counted."""

    class OnePerPass(membership.State):
        _budget = 0

        def fs(self):
            return {"tick"}

        def merge_views(self, test, views):
            return self

        def resolve(self, test):
            self._budget = 1  # one resolution per fixed-point pass
            return self

        def resolve_op(self, test, pair):
            if self._budget > 0:
                self._budget -= 1
                return self
            return None

    st = OnePerPass()
    n = membership.MembershipNemesis(st, max_resolve_iters=2)
    with n._lock:
        n._pending = [membership._Pending({"f": "tick", "value": i},
                                          {}, None, False)
                      for i in range(5)]
    n._resolve({})
    # 2 iterations resolved ops 0 and 1; the bound stopped the rest
    assert n.pending_count() == 3
    reg = metrics_registry
    assert reg.counter(
        "nemesis_membership_resolve_capped_total").value() == 1


def test_concurrent_invoke_and_generator_resolve(tmp_path):
    """The PR-9 race fix: membership_gen's next_op (interpreter thread)
    and invoke (nemesis worker) hammer _resolve/state/_pending
    concurrently without corruption — every applied op leaves the
    members file parseable and the pending list empty once settled."""
    st = FakeClusterState(tmp_path / "m.json", nodes=NODES, settle_s=0.0)
    n = membership.MembershipNemesis(st, poll_interval=0.01)
    test = {"nodes": NODES}
    gen_fn = membership.membership_gen(n)
    errors: list = []
    stop = threading.Event()

    def churn_gen():
        from jepsen_tpu.generator.simulate import default_context
        ctx = default_context({"concurrency": 2})
        while not stop.is_set():
            try:
                gen_fn(test, ctx)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    def churn_invoke(f, node):
        for _ in range(100):
            try:
                n.invoke(test, {"type": "info", "f": f, "value": node})
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=churn_gen, daemon=True)
               for _ in range(2)]
    threads += [threading.Thread(target=churn_invoke,
                                 args=("shrink", "n5"), daemon=True),
                threading.Thread(target=churn_invoke,
                                 args=("grow", "n5"), daemon=True)]
    for t in threads:
        t.start()
    for t in threads[2:]:
        t.join(timeout=30)
    stop.set()
    for t in threads[:2]:
        t.join(timeout=5)
    assert not errors
    n._resolve(test)
    assert n.pending_count() == 0
    members = json.loads((tmp_path / "m.json").read_text())
    assert set(members) <= set(NODES) and "n1" in members


def test_teardown_abandons_stuck_poll_thread(metrics_registry):
    """A node_view hung in remote I/O must not wedge teardown: the join
    is bounded, the thread abandoned, the abandonment counted."""
    release = threading.Event()

    class StuckView(membership.State):
        def fs(self):
            return {"noop"}

        def node_view(self, test, node):
            release.wait()
            return []

        def merge_views(self, test, views):
            return self

    n = membership.MembershipNemesis(StuckView(), poll_interval=0.01,
                                     teardown_join_s=0.3)
    n.setup({"nodes": ["n1"]})
    time.sleep(0.1)  # the poll thread is now stuck inside node_view
    t0 = time.monotonic()
    n.teardown({"nodes": ["n1"]})
    assert time.monotonic() - t0 < 3.0
    reg = metrics_registry
    assert reg.counter(
        "nemesis_membership_poll_abandoned_total").value() == 1
    release.set()


# ---------------------------------------------------------------------------
# Deadline interplay (the PR-4 late-heal rule for reconfigurations)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_hung_invoke_zombifies_and_entry_stays_unhealed(tmp_path,
                                                        metrics_registry):
    """The acceptance pin: a hung membership invoke cannot wedge a run —
    the op times out, the worker zombifies, and the registry entry
    remains unhealed for replay EVEN IF the hung invoke later returns
    and the op resolves."""
    import jepsen_tpu.generator as gen

    release = threading.Event()

    class HangingState(membership.State):
        def fs(self):
            return {"shrink"}

        def merge_views(self, test, views):
            return self

        def members(self):
            return set(NODES)

        def heal_spec(self, test):
            return {"mechanism": "file", "path": "/dev/null"}

        def invoke(self, test, op):
            release.wait()  # stuck mid-reconfig (SSH to a dead node)
            return {"applied": op.get("f")}

        def resolve_op(self, test, pair):
            return self  # resolves instantly once invoked

    n = membership.MembershipNemesis(HangingState(), poll_interval=0.05)
    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"concurrency": 1, "nodes": ["n1"], "client": None,
            "nemesis": n, "_faults": registry,
            "generator": gen.nemesis_gen(gen.Seq([
                {"type": "info", "f": "shrink", "value": "n1"}])),
            "op_timeout_s": 0.4, "drain_timeout_s": 2.0, "stall_s": 0}
    t0 = time.monotonic()
    history = _run(test)
    assert time.monotonic() - t0 < 10.0  # reaped, not wedged
    timeouts = [op for op in history
                if (op.get("error") or [None])[0] == "op-timeout"]
    assert [op["f"] for op in timeouts] == ["shrink"]
    # recorded before firing; unresolved at reap time
    assert [r["kind"] for r in registry.unhealed()] == ["membership"]
    reg = metrics_registry
    assert reg.counter("interpreter_op_timeouts_total",
                       labels=("f",)).value(f="shrink") == 1

    # the hung invoke returns LATE on the zombie thread and the op then
    # resolves — the entry must STILL stay on the books (the run already
    # published an indeterminate :info for it; only the replay may heal)
    release.set()
    time.sleep(0.5)
    n._resolve(test)
    assert [r["kind"] for r in registry.unhealed()] == ["membership"]
    registry.close()


# ---------------------------------------------------------------------------
# Generator integration + preflight
# ---------------------------------------------------------------------------

def test_polling_gen_pending_not_exhausted():
    from jepsen_tpu.generator.simulate import default_context
    box = {"ops": [None, None, {"type": "info", "f": "shrink",
                                "value": "n5"}]}

    def fn(test, ctx):
        return box["ops"].pop(0) if box["ops"] else None

    g = membership.PollingGen(fn)
    ctx = default_context({"concurrency": 1})
    from jepsen_tpu import generator as gen_mod
    op, g2 = g.op({}, ctx)
    assert op is gen_mod.PENDING and g2 is g  # None = pending, NOT done
    op, g2 = g.op({}, ctx)
    assert op is gen_mod.PENDING
    op, g2 = g.op({}, ctx)
    assert op["f"] == "shrink" and g2 is g


def test_membership_package_skipped_with_gen005(tmp_path):
    """Preflight must SKIP the membership package's generator (GEN005) —
    enumerating it would consume live nemesis state — and the skip must
    leave the State untouched."""
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.nemesis import combined

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES, settle_s=0.0)
    pkg = combined.nemesis_package({
        "db": None, "faults": {"membership"}, "membership_state": st,
        "interval": 0.1})
    db = AtomDB()
    t = core.prepare_test(noop_test(
        db=db, client=AtomClient(db), nemesis=pkg["nemesis"],
        generator=pkg["generator"]))
    diags = pf.preflight(t)
    assert [d.code for d in diags] == ["GEN005"]
    assert st.members() == set(NODES)  # nothing consumed
    assert (tmp_path / "m.json").exists()


def test_preflight_rejects_f_outside_state_surface(tmp_path):
    """Acceptance pin: a membership package whose (data) generator emits
    an :f outside State.fs() fails preflight with NEM003."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES)
    n = membership.MembershipNemesis(st)
    db = AtomDB()
    t = core.prepare_test(noop_test(
        db=db, client=AtomClient(db), nemesis=n,
        generator=gen.nemesis_gen(gen.limit(
            2, {"type": "info", "f": "frobnicate", "value": None}))))
    diags = pf.preflight(t)
    errors = {d.code for d in diags if d.severity == "error"}
    assert "NEM003" in errors
    with pytest.raises(pf.PreflightFailed):
        pf.check(t)


def test_preflight_rejects_unhealable_membership_state():
    """Acceptance pin: a membership package whose kind is unhealable (no
    heal spec) fails preflight with NEM005 — downgradeable via
    preflight_allow."""
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test

    class NoHeal(membership.State):
        def fs(self):
            return {"shrink"}

    n = membership.MembershipNemesis(NoHeal())
    db = AtomDB()
    t = core.prepare_test(noop_test(db=db, client=AtomClient(db),
                                    nemesis=n, generator=None))
    diags = pf.preflight(t)
    assert [(d.code, d.severity) for d in diags] == [("NEM005", "error")]
    t["preflight_allow"] = ["NEM005"]
    diags = pf.preflight(t)
    assert [(d.code, d.severity) for d in diags] == [("NEM005", "warning")]
    pf.check(t)  # downgraded: the run may proceed


def test_preflight_validates_package_knobs(tmp_path):
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES)
    n = membership.MembershipNemesis(st, poll_interval="soon")
    db = AtomDB()
    t = core.prepare_test(noop_test(db=db, client=AtomClient(db),
                                    nemesis=n, generator=None))
    codes = {d.code for d in pf.preflight(t) if d.severity == "error"}
    assert "NEM004" in codes


def test_preflight_faketime_missing_lib(monkeypatch, tmp_path):
    """The faketime.install failure path surfaces as a structured NEM006
    diagnostic at preflight (downgradeable), not a RemoteError
    mid-run."""
    from jepsen_tpu import core, faketime
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.nemesis.time import ClockRateNemesis

    monkeypatch.setattr(faketime, "LIB_PATHS", ("/nonexistent/libfake.so",))
    db = AtomDB()
    t = core.prepare_test(noop_test(db=db, client=AtomClient(db),
                                    nemesis=ClockRateNemesis("/opt/db/db"),
                                    generator=None))
    diags = pf.preflight(t)
    assert [(d.code, d.severity) for d in diags] == [("NEM006", "error")]
    with pytest.raises(pf.PreflightFailed):
        pf.check(t)
    t["preflight_allow"] = ["NEM006"]
    pf.check(t)  # deliberate: the run may try an on-node install
    # a present library (or an explicit lib=) passes clean
    monkeypatch.setattr(faketime, "LIB_PATHS", (sys.executable,))
    assert pf.preflight(core.prepare_test(noop_test(
        db=db, client=AtomClient(db),
        nemesis=ClockRateNemesis("/opt/db/db"), generator=None))) == []


# ---------------------------------------------------------------------------
# Clock-rate: records + offline heal
# ---------------------------------------------------------------------------

@pytest.fixture()
def dummy():
    from jepsen_tpu import control
    t = {"nodes": list(NODES), "ssh": {"dummy": True}, "concurrency": 2}
    remote = control.default_remote(t)
    yield t, remote
    control.disconnect_all(t)


def test_clock_rate_classify_and_teardown_heals():
    from jepsen_tpu.nemesis.faults import (
        KINDS, TEARDOWN_HEALS, UNHEALABLE_KINDS, classify,
    )
    assert classify("start-clock-rate") == ("begin", "clock-rate")
    assert classify("stop-clock-rate") == ("end", "clock-rate")
    assert "clock-rate" in KINDS and "membership" in KINDS
    assert "clock-rate" in TEARDOWN_HEALS
    # membership is NOT teardown-healed: State.teardown does not restore
    # the member set, so unresolved reconfigs must survive to replay
    assert "membership" not in TEARDOWN_HEALS
    assert "membership" not in UNHEALABLE_KINDS


def test_clock_rate_nemesis_wraps_and_heals_offline(dummy, tmp_path):
    from jepsen_tpu.nemesis.time import ClockRateNemesis

    t, remote = dummy
    n = ClockRateNemesis("/opt/db/bin/db", restart=False)
    out = n.invoke(t, {"type": "info", "f": "start-clock-rate",
                       "value": {"binary": "/opt/db/bin/db",
                                 "rates": {"n1": 1.01, "n2": 0.99}}})
    assert out["value"]["rates"] == {"n1": 1.01, "n2": 0.99}
    joined = " ".join(str(x) for x in remote.log)
    assert "/opt/db/bin/db.real" in joined  # wrapper installed
    # offline heal: a stranded clock-rate record unwraps via the
    # binary path serialized in the record value
    registry = FaultRegistry(tmp_path / "faults.jsonl")
    registry.record("clock-rate", f="start-clock-rate",
                    value={"binary": "/opt/db/bin/db",
                           "rates": {"n1": 1.01}})
    out = replay_unhealed(t, registry)
    assert len(out["healed"]) == 1
    joined = " ".join(str(x) for x in remote.log)
    assert "mv /opt/db/bin/db.real /opt/db/bin/db" in joined
    registry.close()


def test_clock_rate_package_generator_enumerable(tmp_path):
    """The clock-rate package is data+pure-fn: preflight enumerates it
    (no GEN005) and sees the begin/end window fs."""
    from jepsen_tpu import core
    from jepsen_tpu.analysis import preflight as pf
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.nemesis import combined

    pkg = combined.nemesis_package({
        "db": None, "faults": {"clock-rate"},
        "clock_rate_binary": "/opt/db/db",
        "clock_rate_lib": "/usr/lib/faketime/libfaketime.so.1",
        "interval": 0.1})
    db = AtomDB()
    t = core.prepare_test(noop_test(
        db=db, client=AtomClient(db), nemesis=pkg["nemesis"],
        generator=pkg["generator"], preflight_ops=16))
    diags = pf.preflight(t)
    assert not [d for d in diags if d.severity == "error"], diags
    assert "GEN005" not in {d.code for d in diags}
    # the enumerated schedule alternates begin/end windows (a bare Fn
    # in the cycle would pin it on start ops forever)
    from jepsen_tpu.analysis.preflight import _enumerate
    invocations, _ = _enumerate(t)
    fs = [op.get("f") for op in invocations]
    assert "start-clock-rate" in fs and "stop-clock-rate" in fs
    first_stop = fs.index("stop-clock-rate")
    assert fs[first_stop - 1] == "start-clock-rate"


# ---------------------------------------------------------------------------
# Combined compositions: model-aware fault windows during reconfig
# ---------------------------------------------------------------------------

def test_partition_during_reconfig_window_follows_pending(tmp_path):
    from jepsen_tpu.generator.simulate import default_context
    from jepsen_tpu.nemesis import combined

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES, settle_s=600.0)
    pkg = combined.partition_during_reconfig_package({
        "db": None, "faults": {"partition-during-reconfig"},
        "membership_state": st, "interval": 0.05})
    assert pkg is not None
    fs = pkg["nemesis"].fs()
    assert {"grow", "shrink", "start-partition", "stop-partition"} <= fs
    # find the membership nemesis inside the composition
    from jepsen_tpu.analysis.preflight import _walk_nemeses
    nems: list = []
    _walk_nemeses(pkg["nemesis"], nems)
    mn = next(x for x in nems
              if isinstance(x, membership.MembershipNemesis))
    # the window generator is the second composed generator; drive the
    # package generator and watch the partition edges track pending
    # both composed children are PollingGens now; the window generator
    # is the unpaced one (the membership gen carries the interval)
    window_gen = [g for g in pkg["generator"].gens
                  if isinstance(g, membership.PollingGen)
                  and not g.interval_nanos]
    assert window_gen, "combo lost its window generator"
    wg = window_gen[0]
    ctx = default_context({"concurrency": 1})
    t = {"nodes": NODES}
    from jepsen_tpu import generator as gen_mod
    op, _ = wg.op(t, ctx)
    assert op is gen_mod.PENDING  # nothing pending: window stays shut
    mn.invoke(t, {"type": "info", "f": "shrink", "value": "n5"})
    assert mn.pending_count() == 1
    op, _ = wg.op(t, ctx)
    assert op["f"] == "start-partition"  # reconfig in flight: open
    # an OFFERED edge is not a DISPATCHED edge: until the interpreter's
    # update confirms the dispatch, the edge must keep being offered —
    # a busy nemesis thread / lost scheduling tie must not drop it
    op2, _ = wg.op(t, ctx)
    assert op2["f"] == "start-partition"
    wg.update(t, ctx, dict(op))  # the edge dispatched
    op3, _ = wg.op(t, ctx)
    assert op3 is gen_mod.PENDING  # window open now
    with mn._lock:
        mn._pending.clear()  # the reconfig resolves
    op4, _ = wg.op(t, ctx)
    assert op4["f"] == "stop-partition"  # converged: close
    op5, _ = wg.op(t, ctx)
    assert op5["f"] == "stop-partition"  # still offered until dispatched
    wg.update(t, ctx, dict(op4))
    op6, _ = wg.op(t, ctx)
    assert op6 is gen_mod.PENDING  # closed and idle


def test_polling_gen_paces_after_dispatch_even_on_fast_resolve():
    """A State that resolves before the next scheduler poll must not
    bypass the interval: pacing is armed by the dispatch UPDATE, not by
    guessing from the next fn answer."""
    from jepsen_tpu.generator.simulate import default_context

    def always_propose(test, ctx):
        return {"type": "info", "f": "shrink", "value": "n5"}

    g = membership.PollingGen(always_propose, interval=10.0)
    ctx = default_context({"concurrency": 1})
    from jepsen_tpu import generator as gen_mod
    op, _ = g.op({}, ctx)
    assert op["f"] == "shrink"
    g.update({}, ctx, dict(op))  # dispatched; op resolved instantly
    op2, _ = g.op({}, ctx)  # fn STILL proposes, but the pacing gates it
    assert op2 is gen_mod.PENDING
    assert g._not_before is not None and g._not_before > ctx.time


def test_plain_nemesis_membership_fs_not_generically_recorded(tmp_path):
    """Pre-existing suites (faunadb topology, rethinkdb reconfigure) use
    membership-flavored :f names with PLAIN nemeses that keep no model:
    the interpreter's generic snapshot must not book permanently-
    unhealed membership rows for them (SELF_RECORDED_ONLY)."""
    import jepsen_tpu.generator as gen

    class PlainReconfigurer:
        def fs(self):
            return {"reconfigure", "add-node"}

        def invoke(self, test, op):
            return {**op, "type": "info", "value": "done"}

    registry = FaultRegistry(tmp_path / "faults.jsonl")
    test = {"concurrency": 1, "nodes": ["n1"], "client": None,
            "nemesis": PlainReconfigurer(), "_faults": registry,
            "generator": gen.nemesis_gen(gen.Seq([
                {"type": "info", "f": "reconfigure", "value": None},
                {"type": "info", "f": "add-node", "value": "n9"}])),
            "stall_s": 0}
    _run(test)
    assert registry.unhealed() == []
    assert (tmp_path / "faults.jsonl").read_text() == ""
    registry.close()


def test_etcd_remove_node_resolves_despite_stale_dead_view(monkeypatch):
    """The removed node's poll only fails after its process is killed,
    so the nemesis keeps its last good view — which still lists the
    node. Resolution must count only the survivors' views."""
    from jepsen_tpu.suites import etcd

    api = FakeMembersAPI(["n1", "n2", "n3"])
    monkeypatch.setattr(etcd, "_members_request", api)
    st = etcd.EtcdMembershipState()
    t = {"nodes": ["n1", "n2", "n3"]}
    full = st.node_view(t, "n1")
    st.merge_views(t, {n: full for n in ["n1", "n2", "n3"]})
    op = {"type": "info", "f": "remove-node", "value": "n3"}
    val = st.invoke(t, op)
    # survivors converge; n3's view is STALE (still the full set)
    survivor_view = sorted(api.members)
    st.merge_views(t, {"n1": survivor_view, "n2": survivor_view,
                       "n3": full})
    assert st.resolve_op(t, (op, val)) is st


def test_polling_gen_ignores_prior_completion():
    """Nemesis events arrive twice per op (dispatch with the op's value,
    completion with a rewritten one): a previous dispatch's completion
    must not pass for a dispatch of the CURRENT offer and burn a
    pacing window."""
    from jepsen_tpu.generator.simulate import default_context

    def always_propose(test, ctx):
        return {"type": "info", "f": "shrink", "value": "n5"}

    g = membership.PollingGen(always_propose, interval=10.0)
    ctx = default_context({"concurrency": 1})
    op, _ = g.op({}, ctx)
    assert op["f"] == "shrink"
    # the PREVIOUS op's completion: same f, rewritten value
    g.update({}, ctx, {**op, "value": {"action": "shrink", "at": 1.0}})
    assert g._offered is not None  # still awaiting OUR dispatch
    assert g._not_before is None   # no pacing burned
    g.update({}, ctx, dict(op))    # the real dispatch event
    assert g._offered is None and g._not_before is not None


def test_both_during_reconfig_combos_rejected(tmp_path):
    from jepsen_tpu.nemesis import combined

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES)
    with pytest.raises(ValueError, match="cannot be combined"):
        combined.nemesis_package({
            "db": None, "membership_state": st,
            "clock_rate_binary": "/opt/db/db",
            "faults": {"partition-during-reconfig",
                       "clock-rate-during-reconfig"}})


def test_preflight_inert_closure_types_still_enumerable(tmp_path):
    """Closures over immutable value objects (Path, datetime, ...) must
    keep full enumeration coverage — only live-state instances trigger
    the GEN005 skip."""
    import datetime
    from pathlib import Path

    from jepsen_tpu.analysis.preflight import _stateful_reason

    p, d = Path("/tmp/x"), datetime.date(2026, 1, 1)

    def data_gen(test, ctx):
        return {"f": "write", "value": f"{p}-{d}"}

    import jepsen_tpu.generator as gen
    assert _stateful_reason(gen.Fn(data_gen)) is None


def test_partition_combo_subsumes_standalone_partition(tmp_path):
    """faults={'partition','partition-during-reconfig'} must build ONE
    PartitionNemesis: a second one's staggered stop-partition would
    heal mid-reconfig and its start events would flip the combo's
    window state."""
    from jepsen_tpu.analysis.preflight import _walk_nemeses
    from jepsen_tpu.nemesis import combined
    from jepsen_tpu.nemesis.combined import PartitionNemesis

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES)
    pkg = combined.nemesis_package({
        "db": None, "membership_state": st,
        "faults": {"partition", "partition-during-reconfig"}})
    nems: list = []
    _walk_nemeses(pkg["nemesis"], nems)
    partitions = [n for n in nems if isinstance(n, PartitionNemesis)]
    assert len(partitions) == 1


def test_current_op_reaped_propagates_through_timeout_helper():
    """A Timeout nemesis wrapper runs the inner invoke on a helper
    thread; current_op_reaped() must answer for the logical op, not
    the physical thread."""
    from jepsen_tpu.generator import interpreter
    from jepsen_tpu.utils import timeout as timeout_fn

    ev = threading.Event()
    interpreter._worker_tls.zombied = ev
    try:
        assert timeout_fn(1000, None,
                          interpreter.current_op_reaped) is False
        ev.set()
        assert timeout_fn(1000, None,
                          interpreter.current_op_reaped) is True
    finally:
        del interpreter._worker_tls.zombied


def test_preflight_nested_and_builtin_closures_enumerable():
    """Nested immutable containers, module builtins, and partials over
    pure fns stay enumerable; instance-bound builtins (random.random is
    a bound method of the hidden Random) stay stateful."""
    import functools
    import math
    import random

    import jepsen_tpu.generator as gen
    from jepsen_tpu.analysis.preflight import _stateful_reason

    pairs = (("w", 1), ("r", None))
    sqrt = math.sqrt
    half = functools.partial(round, ndigits=2)

    def data_gen(test, ctx):
        return {"f": pairs[0][0], "value": half(sqrt(4.0))}

    assert _stateful_reason(gen.Fn(data_gen)) is None

    rand = random.random

    def rng_gen(test, ctx):
        return {"f": "write", "value": rand()}

    assert "bound to a Random" in _stateful_reason(gen.Fn(rng_gen))


def test_requested_but_unwired_fault_raises():
    """A fault the user NAMED must never silently no-op: membership /
    clock-rate / combo names without their wiring fail loudly at
    package-build time (cli maps ValueError to bad-args)."""
    from jepsen_tpu.nemesis import combined

    for faults in ({"membership"}, {"clock-rate"},
                   {"partition-during-reconfig"},
                   {"clock-rate-during-reconfig"}):
        with pytest.raises(ValueError, match="requested"):
            combined.nemesis_package({"db": None, "faults": faults})


def test_clock_rate_during_reconfig_package_builds(tmp_path):
    from jepsen_tpu.nemesis import combined

    st = FakeClusterState(tmp_path / "m.json", nodes=NODES)
    pkg = combined.nemesis_package({
        "db": None, "faults": {"clock-rate-during-reconfig"},
        "membership_state": st, "clock_rate_binary": "/opt/db/db",
        "interval": 0.05})
    fs = pkg["nemesis"].fs()
    assert {"grow", "shrink", "start-clock-rate", "stop-clock-rate"} <= fs


# ---------------------------------------------------------------------------
# Etcd membership state (stubbed members API)
# ---------------------------------------------------------------------------

class FakeMembersAPI:
    """A v2 /members transport double over a dict cluster."""

    def __init__(self, names):
        self.members = {n: f"id-{n}" for n in names}
        self.calls: list = []

    def __call__(self, node, method="GET", body=None, member_id=None,
                 timeout_s=5.0):
        self.calls.append((node, method, body, member_id))
        if method == "GET":
            return {"members": [{"id": i, "name": n,
                                 "peerURLs": [f"http://{n}:2380"]}
                                for n, i in sorted(self.members.items())]}
        if method == "POST":
            name = (body or {}).get("name")
            if name in self.members:
                raise urllib.error.HTTPError("u", 409, "conflict", {}, None)
            self.members[name] = f"id-{name}"
            return {}
        if method == "DELETE":
            name = next((n for n, i in self.members.items()
                         if i == member_id), None)
            if name is None:
                raise urllib.error.HTTPError("u", 404, "gone", {}, None)
            del self.members[name]
            return {}
        raise AssertionError(method)


def test_etcd_membership_state_cycle(monkeypatch):
    from jepsen_tpu.suites import etcd

    api = FakeMembersAPI(["n1", "n2", "n3", "n4", "n5"])
    monkeypatch.setattr(etcd, "_members_request", api)
    st = etcd.EtcdMembershipState()
    t = {"nodes": NODES}
    view = st.node_view(t, "n1")
    assert view == sorted(NODES)
    st.merge_views(t, {n: view for n in NODES})
    assert st.members() == set(NODES)
    op = st.op(t)
    assert op["f"] == "remove-node" and op["value"] == "n5"
    val = st.invoke(t, op)
    assert val["expect_present"] is False
    assert "n5" not in api.members
    # unresolved until the polled views agree the member is gone
    assert st.resolve_op(t, (op, val)) is None
    new_view = sorted(api.members)
    st.merge_views(t, {n: new_view for n in new_view})
    assert st.resolve_op(t, (op, val)) is st
    # with a member missing, the model proposes re-adding it
    op2 = st.op(t)
    assert (op2["f"], op2["value"]) == ("add-node", "n5")
    st.invoke(t, op2)
    assert "n5" in api.members


def test_etcd_restore_members_diffs_both_ways(monkeypatch):
    from jepsen_tpu.suites import etcd

    # n3 was removed (stranded shrink) and n9 half-added
    api = FakeMembersAPI(["n1", "n2", "n9"])
    monkeypatch.setattr(etcd, "_members_request", api)
    row = {"id": 7, "kind": "membership",
           "value": {"pre_members": ["n1", "n2", "n3"],
                     "heal": {"mechanism": "import",
                              "module": "jepsen_tpu.suites.etcd",
                              "fn": "restore_members"}}}
    membership.heal_record({"nodes": NODES}, row)
    assert sorted(api.members) == ["n1", "n2", "n3"]
    membership.heal_record({"nodes": NODES}, row)  # idempotent
    assert sorted(api.members) == ["n1", "n2", "n3"]


@pytest.mark.slow
def test_etcd_fake_mode_membership_end_to_end():
    """--fault membership runs the full fake suite lifecycle: the
    durable fake cluster reconfigures, every op lands (and heals) in
    the registry, and the run ends clean."""
    from jepsen_tpu.suites.etcd import etcd_test
    from tests.conftest import run_fake

    res = run_fake(etcd_test, faults={"membership"}, nemesis_interval=0.2,
                   membership_settle_s=0.0, time_limit=2.0)
    hist = res.get("history") or []
    fs = {op.get("f") for op in hist if op.get("process") == "nemesis"}
    assert fs & {"shrink", "grow"}, "membership nemesis never fired"
    assert (res.get("results") or {}).get("valid?") is True


# ---------------------------------------------------------------------------
# join_noisy bounded mode
# ---------------------------------------------------------------------------

def test_join_noisy_bounded_abandons():
    from jepsen_tpu.utils import join_noisy

    release = threading.Event()
    t = threading.Thread(target=release.wait, daemon=True)
    t.start()
    t0 = time.monotonic()
    assert join_noisy(t, "stuck thread", heartbeat_s=0.1,
                      max_wait_s=0.3) is False
    assert time.monotonic() - t0 < 2.0
    release.set()
    assert join_noisy(t, "released thread", heartbeat_s=0.1,
                      max_wait_s=5.0) is True


# ---------------------------------------------------------------------------
# Chaos: SIGKILL mid-shrink -> analyze --recover -> cli heal (slow lane)
# ---------------------------------------------------------------------------

def _cli_main():
    from jepsen_tpu import cli
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.fakes import noop_test

    def build(opts):
        return cli.test_opts_to_test(
            opts, noop_test(checker=linearizable(accelerator="cpu")))

    return cli.single_test_cmd(build)


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_mid_shrink_recover_and_heal(tmp_path):
    """The tentpole acceptance scenario end to end: SIGKILL lands while
    a shrink is unresolved; the durable record holds the pre-op member
    set; ``analyze --recover`` yields a valid-incomplete verdict with
    the membership fault window visible in the registry-derived fault
    bands; ``cli heal`` restores the recorded member set exactly once
    (a second heal is a no-op)."""
    members_path = tmp_path / "cluster-members.json"
    store = tmp_path / "store"
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "membership_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, worker, str(store), str(members_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    deadline = time.monotonic() + 120
    run_dir = None
    try:
        while time.monotonic() < deadline:
            regs = list(store.glob("noop/*/faults.jsonl"))
            wals = list(store.glob("noop/*/history.wal.jsonl"))
            if regs and wals and "shrink" in regs[0].read_text() \
                    and wals[0].read_text().count("\n") >= 20:
                run_dir = regs[0].parent
                break
            if proc.poll() is not None:
                out = proc.stdout.read()
                pytest.fail(f"worker exited early ({proc.returncode}):\n"
                            f"{out[-4000:]}")
            time.sleep(0.05)
        assert run_dir is not None, "shrink never recorded"
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=30)

    # the shrink applied (members file shrunk) but never resolved: the
    # registry holds the unhealed membership record with the pre-op set
    assert json.loads(members_path.read_text()) == ["n1", "n2", "n3", "n4"]
    freg = FaultRegistry(run_dir / "faults.jsonl")
    unhealed = freg.unhealed()
    freg.close()
    assert [r["kind"] for r in unhealed] == ["membership"]
    assert unhealed[0]["value"]["pre_members"] == sorted(NODES)

    # analyze --recover: valid-but-incomplete verdict over the WAL
    main = _cli_main()
    rc = main(["analyze", "--recover", "--store-dir", str(store),
               "--no-ssh", "--accelerator", "cpu"])
    assert rc == 0
    results = json.loads((run_dir / "results.json").read_text())
    assert results["valid?"] is True and results["incomplete"] is True

    # the unhealed membership row is visible in the registry-derived
    # fault bands (the source the explain timeline + perf-plot shading
    # draw from): an open window, in-registry, not yet healed
    from jepsen_tpu import store as store_mod
    from jepsen_tpu.checker.perf_plots import registry_fault_windows
    name, ts = "noop", run_dir.name
    stored = store_mod.load_test(name, ts, str(store))
    stored["store_dir"] = str(store)
    history = store_mod.load_history(name, ts, str(store))
    windows = [w for w in registry_fault_windows(stored, history)
               if w["kind"] == "membership"]
    assert windows and windows[0]["in_registry"] is True
    assert windows[0]["healed"] is False
    assert windows[0]["end_time"] is None  # never closed in-history

    # cli heal: restores the recorded pre-op member set, exactly once
    rc = main(["heal", str(run_dir)])
    assert rc == 0
    assert json.loads(members_path.read_text()) == sorted(NODES)
    freg = FaultRegistry(run_dir / "faults.jsonl")
    assert freg.unhealed() == []
    freg.close()
    # after the heal, the fault band flips to healed-via-replay
    windows = [w for w in registry_fault_windows(stored, history)
               if w["kind"] == "membership"]
    assert windows and windows[0]["healed"] is True
    assert windows[0]["via"] == "replay"
    # exactly once: a second heal is a no-op even if the cluster moved on
    members_path.write_text(json.dumps(["sentinel"]))
    rc = main(["heal", str(run_dir)])
    assert rc == 0
    assert json.loads(members_path.read_text()) == ["sentinel"]
