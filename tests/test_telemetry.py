"""Unified telemetry tests: registry semantics, histogram buckets and
quantiles, Prometheus rendering, disabled-mode no-ops, flusher lifecycle,
tracer teardown ownership, and the fake-mode end-to-end export
(metrics.prom / metrics.json landing in the store dir, rendered by the
web UI). See doc/observability.md."""
import json
import threading

import pytest

from jepsen_tpu import telemetry


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    r = telemetry.Registry()
    c = r.counter("ops_total", "ops", labels=("f",))
    c.inc(f="read")
    c.inc(2, f="read")
    c.inc(f="write")
    assert c.value(f="read") == 3
    assert c.value(f="write") == 1
    with pytest.raises(ValueError):
        c.inc(-1, f="read")  # counters only go up

    g = r.gauge("depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4
    g.set_max(2)
    assert g.value() == 4  # high-water keeps the max
    g.set_max(9)
    assert g.value() == 9


def test_registry_get_or_create_and_type_conflicts():
    r = telemetry.Registry()
    a = r.counter("x_total", "first help", labels=("f",))
    b = r.counter("x_total", labels=("f",))
    assert a is b
    assert a.help == "first help"  # first registration wins
    with pytest.raises(ValueError):
        r.gauge("x_total")  # same name, different kind
    with pytest.raises(ValueError):
        r.counter("x_total", labels=("g",))  # same name, different labels


def test_registry_is_thread_safe():
    r = telemetry.Registry()
    c = r.counter("n_total", labels=("w",))

    def work(wid):
        for _ in range(1000):
            c.inc(w=str(wid % 2))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(w="0") + c.value(w="1") == 8000


# ---------------------------------------------------------------------------
# histograms: log buckets, boundaries, quantiles
# ---------------------------------------------------------------------------

def test_log_bucket_boundaries():
    bounds = telemetry.log_buckets(1e-3, 10.0, 4)
    assert bounds == pytest.approx((1e-3, 1e-2, 1e-1, 1.0))
    with pytest.raises(ValueError):
        telemetry.log_buckets(0, 10, 4)
    # default buckets are log-spaced x4 from 1 µs
    d = telemetry.DEFAULT_BUCKETS
    assert d[0] == pytest.approx(1e-6)
    assert all(b2 / b1 == pytest.approx(4.0) for b1, b2 in zip(d, d[1:]))


def test_histogram_bucketing_and_overflow():
    r = telemetry.Registry()
    h = r.histogram("h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):
        h.observe(v)
    child = h._child({})
    # bucket counts: <=0.1, <=1.0, <=10.0, +Inf
    assert child.counts == [2, 1, 1, 1]
    assert child.count == 5
    assert child.min == 0.05 and child.max == 100.0
    assert child.sum == pytest.approx(102.65)


def test_histogram_quantiles_interpolate_within_bucket():
    r = telemetry.Registry()
    h = r.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.6, 3.0):
        h.observe(v)
    assert h.quantile(0.0) <= h.quantile(0.5) <= h.quantile(1.0)
    # p50 (rank 2) lands in the (1, 2] bucket
    assert 1.0 <= h.quantile(0.5) <= 2.0
    # p100 caps at the observed max
    assert h.quantile(1.0) <= 4.0
    assert r.histogram("empty", buckets=(1.0,)).quantile(0.5) is None


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_text_rendering():
    r = telemetry.Registry()
    r.counter("req_total", "requests served", labels=("f",)).inc(f='a"b\n')
    r.gauge("temp").set(3.5)
    h = r.histogram("lat", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(5.0)
    text = r.render_prom()
    assert "# HELP req_total requests served" in text
    assert "# TYPE req_total counter" in text
    assert 'req_total{f="a\\"b\\n"} 1' in text  # label escaping
    assert "temp 3.5" in text
    # histogram buckets are CUMULATIVE and end at +Inf
    assert 'lat_bucket{le="1"} 1' in text
    assert 'lat_bucket{le="2"} 1' in text
    assert 'lat_bucket{le="+Inf"} 2' in text
    assert "lat_sum 5.5" in text
    assert "lat_count 2" in text


def test_snapshot_and_export(tmp_path):
    r = telemetry.Registry()
    r.counter("c_total").inc(7)
    r.histogram("h").observe(0.25)
    r.event("nemesis-fault", f="kill", phase="begin")
    r.export(tmp_path)
    rows = [json.loads(line)
            for line in (tmp_path / "metrics.json").read_text().splitlines()]
    by = {(row.get("name"), row.get("type")): row for row in rows}
    assert by[("c_total", "counter")]["value"] == 7
    hist = by[("h", "histogram")]
    assert hist["count"] == 1 and hist["min"] == 0.25
    ev = by[("nemesis-fault", "event")]
    assert ev["fields"] == {"f": "kill", "phase": "begin"}
    assert (tmp_path / "metrics.prom").read_text().startswith("#")


def test_metrics_summary_report_block():
    from jepsen_tpu import report
    r = telemetry.Registry()
    r.counter("c_total", labels=("f",)).inc(3, f="read")
    r.gauge("g").set(2)
    r.histogram("h").observe(1.0)
    r.event("nemesis-fault", f="kill", phase="begin")
    text = report.metrics_summary(r.snapshot())
    assert "c_total{f=read} = 3" in text
    assert "g = 2" in text
    assert "count=1" in text
    assert "nemesis-fault" in text


# ---------------------------------------------------------------------------
# disabled mode
# ---------------------------------------------------------------------------

def test_default_registry_is_null_and_noop():
    reg = telemetry.get_registry()
    assert reg.enabled is False
    c = reg.counter("whatever")
    c.inc()
    c.inc(5, f="x")
    assert c.value() == 0.0
    with reg.timer("t"):
        pass
    reg.event("e")
    assert reg.snapshot() == []
    assert reg.render_prom() == ""
    # the same shared instrument backs every name: no per-call allocation
    assert reg.counter("a") is reg.gauge("b") is reg.histogram("c")


def test_install_and_restore():
    live = telemetry.Registry()
    prev = telemetry.install(live)
    try:
        assert telemetry.get_registry() is live
        with telemetry.use(telemetry.NULL):
            assert telemetry.get_registry().enabled is False
        assert telemetry.get_registry() is live
    finally:
        telemetry.install(prev)
    assert telemetry.get_registry() is prev


# ---------------------------------------------------------------------------
# fault-window classification
# ---------------------------------------------------------------------------

def test_fault_phase_heuristic():
    assert telemetry.fault_phase("start_partition") == "begin"
    assert telemetry.fault_phase("stop_partition") == "end"
    assert telemetry.fault_phase("kill") == "begin"
    assert telemetry.fault_phase("start") == "end"  # heal of a kill
    assert telemetry.fault_phase("pause") == "begin"
    assert telemetry.fault_phase("resume") == "end"
    assert telemetry.fault_phase("read") is None
    assert telemetry.fault_phase(None) is None


# ---------------------------------------------------------------------------
# flusher lifecycle
# ---------------------------------------------------------------------------

def _telemetry_threads():
    return [t for t in threading.enumerate()
            if "telemetry" in (t.name or "")]


def test_flusher_periodic_and_final_export(tmp_path):
    r = telemetry.Registry()
    r.counter("c_total").inc()
    fl = telemetry.Flusher(r, tmp_path, interval_s=0.02).start()
    try:
        import time
        deadline = time.time() + 5
        while not (tmp_path / "metrics.prom").exists():
            assert time.time() < deadline, "flusher never exported"
            time.sleep(0.01)
    finally:
        fl.stop()
    assert not _telemetry_threads()
    assert (tmp_path / "metrics.json").exists()


def test_flusher_zero_interval_still_final_exports(tmp_path):
    r = telemetry.Registry()
    r.counter("c_total").inc()
    fl = telemetry.Flusher(r, tmp_path, interval_s=0).start()
    assert not _telemetry_threads()  # no thread spawned
    fl.stop()
    assert (tmp_path / "metrics.prom").exists()


# ---------------------------------------------------------------------------
# tracer lifecycle (the shared-tracer teardown fix)
# ---------------------------------------------------------------------------

def test_tracer_close_is_idempotent(tmp_path):
    from jepsen_tpu.tracing import Tracer
    path = tmp_path / "trace.jsonl"
    tr = Tracer(str(path))
    with tr.with_trace("a"):
        pass
    tr.close()
    tr.close()  # second close: no error, no duplicate spans
    spans = [json.loads(line) for line in path.read_text().splitlines()]
    assert [s["name"] for s in spans] == ["a"]


def test_traced_client_close_leaves_shared_tracer_usable(tmp_path):
    from jepsen_tpu.fakes import AtomClient, AtomDB
    from jepsen_tpu.tracing import TracedClient, Tracer
    path = tmp_path / "trace.jsonl"
    tracer = Tracer(str(path))
    db = AtomDB()
    test = {"db": db}
    c1 = TracedClient(AtomClient(db), tracer).open(test, "n1")
    c2 = TracedClient(AtomClient(db), tracer).open(test, "n2")
    c1.invoke(test, {"f": "write", "value": 1, "process": 0,
                     "type": "invoke"})
    c1.close(test)  # must NOT tear down the tracer c2 still holds
    out = c2.invoke(test, {"f": "read", "value": None, "process": 1,
                           "type": "invoke"})
    assert out["type"] == "ok"
    c2.close(test)
    tracer.close()  # owner teardown
    spans = [json.loads(line) for line in path.read_text().splitlines()]
    assert {s["name"] for s in spans} == {"invoke/write", "invoke/read"}


# ---------------------------------------------------------------------------
# cli opt threading
# ---------------------------------------------------------------------------

def test_cli_threads_telemetry_opts_into_test_map():
    import argparse
    from jepsen_tpu import cli
    from jepsen_tpu.fakes import noop_test
    p = argparse.ArgumentParser()
    cli.add_test_opts(p)
    opts = p.parse_args(["--no-ssh", "--trace", "--profile",
                         "--metrics-interval", "2.5"])
    t = cli.test_opts_to_test(opts, noop_test())
    assert t["trace"] is True
    assert t["profile"] is True
    assert t["metrics_interval"] == 2.5
    # negative interval means metrics off entirely
    opts = p.parse_args(["--no-ssh", "--metrics-interval", "-1"])
    t = cli.test_opts_to_test(opts, noop_test())
    assert t["metrics"] is False


# ---------------------------------------------------------------------------
# end to end: fake-mode run -> store dir artifacts -> web UI
# ---------------------------------------------------------------------------

def _run_fake_cas(tmp, **overrides):
    import jepsen_tpu.generator as gen
    from jepsen_tpu import core
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test
    from jepsen_tpu.models import CASRegister

    db = AtomDB()
    ops = gen.Fn(lambda: {"f": "write", "value": 1})
    t = noop_test(
        db=db, client=AtomClient(db),
        generator=gen.limit(40, ops),
        checker=linearizable(model=CASRegister()),
        accelerator="cpu", concurrency=2, nodes=["n1", "n2"],
        store_dir=str(tmp), **overrides)
    return core.run(t)


def test_e2e_fake_run_exports_metrics(tmp_path):
    res = _run_fake_cas(tmp_path)
    assert res["results"]["valid?"] is True
    from jepsen_tpu import store
    name, ts, run_dir = store.latest(str(tmp_path))
    prom = (run_dir / "metrics.prom").read_text()
    rows = [json.loads(line) for line in
            (run_dir / "metrics.json").read_text().splitlines()]
    names = {r.get("name") for r in rows}
    # interpreter instrumentation saw the 40 writes
    ops = [r for r in rows if r.get("name") == "interpreter_ops_total"]
    assert sum(r["value"] for r in ops) == 40
    assert "interpreter_op_latency_seconds" in names
    # checker instrumentation recorded the backend dispatch
    assert any(r.get("name") == "checker_backend_total" for r in rows)
    assert "interpreter_ops_total" in prom
    assert "checker_backend_total" in prom
    assert (run_dir / "metrics-summary.txt").exists()
    # registry was restored and the flusher thread is gone
    assert telemetry.get_registry().enabled is False
    assert not _telemetry_threads()


def test_e2e_metrics_disabled_writes_nothing(tmp_path):
    res = _run_fake_cas(tmp_path, metrics=False)
    assert res["results"]["valid?"] is True
    from jepsen_tpu import store
    _, _, run_dir = store.latest(str(tmp_path))
    assert not (run_dir / "metrics.prom").exists()
    assert not (run_dir / "metrics.json").exists()
    assert not _telemetry_threads()


def test_e2e_trace_flag_wires_traced_client(tmp_path):
    res = _run_fake_cas(tmp_path, trace=True)
    assert res["results"]["valid?"] is True
    from jepsen_tpu import store
    _, _, run_dir = store.latest(str(tmp_path))
    spans = [json.loads(line) for line in
             (run_dir / "trace.jsonl").read_text().splitlines()]
    assert spans and all(s["name"].startswith("invoke/") for s in spans)


def test_web_renders_metrics_table_and_links(tmp_path):
    import urllib.request
    from jepsen_tpu import store
    from jepsen_tpu.web import make_server

    _run_fake_cas(tmp_path)
    name, ts, run_dir = store.latest(str(tmp_path))
    srv = make_server(str(tmp_path), "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        # run listing links the exported telemetry artifacts
        assert f"/{name}/{ts}/metrics.json" in home
        assert f"/{name}/{ts}/metrics.prom" in home
        run_page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{name}/{ts}/",
            timeout=10).read().decode()
        assert "<h2>metrics</h2>" in run_page
        assert "interpreter_ops_total" in run_page
        prom = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{name}/{ts}/metrics.prom",
            timeout=10).read().decode()
        assert "# TYPE" in prom
    finally:
        srv.shutdown()


def test_reanalysis_preserves_run_metrics(tmp_path):
    """Standalone analyze exports under metrics-analyze.* — the live
    run's interpreter measurements survive any number of re-checks."""
    from jepsen_tpu import core, store
    _run_fake_cas(tmp_path)
    name, ts, run_dir = store.latest(str(tmp_path))
    original = (run_dir / "metrics.json").read_text()
    assert "interpreter_ops_total" in original
    stored = store.load_test(name, ts, str(tmp_path))
    from jepsen_tpu.checker.linearizable import linearizable
    stored["checker"] = linearizable()
    stored["store_dir"] = str(tmp_path)
    core.analyze(stored)
    assert (run_dir / "metrics.json").read_text() == original
    reanalysis = (run_dir / "metrics-analyze.json").read_text()
    assert "checker_backend_total" in reanalysis
    assert "interpreter_ops_total" not in reanalysis


def test_store_telemetry_artifacts_listing(tmp_path):
    from jepsen_tpu import store
    (tmp_path / "metrics.prom").write_text("")
    (tmp_path / "profile").mkdir()
    arts = store.telemetry_artifacts(tmp_path)
    assert set(arts) == {"metrics.prom", "profile"}


def test_nemesis_fault_events_recorded():
    """A kill/heal nemesis schedule lands fault-window events + the
    active-window gauge returning to zero."""
    import jepsen_tpu.generator as gen
    from jepsen_tpu.generator import interpreter
    from jepsen_tpu.nemesis import Nemesis
    from jepsen_tpu.utils import with_relative_time

    class NoteNemesis(Nemesis):
        def invoke(self, test, op):
            return {**op, "type": "info"}

    reg = telemetry.Registry()
    with telemetry.use(reg):
        test = {"concurrency": 1, "nodes": ["n1"],
                "nemesis": NoteNemesis(), "client": None,
                "generator": gen.nemesis_gen([{"f": "kill", "value": None},
                                              {"f": "start", "value": None}])}
        with with_relative_time():
            interpreter.run(test)
    events = [row for row in reg.snapshot() if row.get("type") == "event"]
    phases = [e["fields"]["phase"] for e in events]
    assert phases == ["begin", "end"]
    assert reg.gauge("nemesis_fault_active").value() == 0
    assert reg.counter("nemesis_ops_total",
                       labels=("f",)).value(f="kill") == 1
