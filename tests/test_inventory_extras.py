"""Tests for the tcpdump capture DB, the sudo-aware SCP transfer
decorator, and the charybdefs filesystem-fault wrapper (reference:
db.clj:49-115, control/scp.clj, charybdefs/src/jepsen/charybdefs.clj) —
all command-shape tests over stub/dummy remotes (SURVEY.md §4 tier 2)."""
import pytest

from jepsen_tpu import charybdefs, control
from jepsen_tpu.control.core import Remote, RemoteError, Result
from jepsen_tpu.control.scp import SCPRemote
from jepsen_tpu.db import TcpdumpDB

NODES = ["n1", "n2", "n3"]


def dummy_test(**over):
    t = {"nodes": list(NODES), "ssh": {"dummy": True}, "concurrency": 2}
    t.update(over)
    return t


@pytest.fixture()
def dummy():
    t = dummy_test()
    remote = control.default_remote(t)
    yield t, remote
    control.disconnect_all(t)


# ---------------------------------------------------------------------------
# tcpdump DB
# ---------------------------------------------------------------------------

def test_tcpdump_setup_teardown_commands(dummy):
    t, remote = dummy
    db = TcpdumpDB(ports=[2379, 2380], filter="host 10.0.0.9")
    control.on("n1", t, lambda: db.setup(t, "n1"))
    joined = " ".join(str(x) for x in remote.log)
    assert "tcpdump" in joined
    assert "(port 2379 or port 2380)" in joined
    assert "host 10.0.0.9" in joined
    assert "-U" in joined  # unbuffered capture (db.clj:88-93)
    control.on("n1", t, lambda: db.teardown(t, "n1"))
    joined = " ".join(str(x) for x in remote.log)
    assert "rm -rf /tmp/jepsen/tcpdump" in joined
    assert db.log_files(t, "n1") == ["/tmp/jepsen/tcpdump/log",
                                     "/tmp/jepsen/tcpdump/tcpdump"]


def test_tcpdump_clients_only_filter():
    db = TcpdumpDB(ports=[5432], clients_only=True)
    f = db._filter_str("n1")
    assert f.startswith("(port 5432) and host ")


# ---------------------------------------------------------------------------
# SCP decorator
# ---------------------------------------------------------------------------

class StubRemote(Remote):
    """Logs transfers; lets tests script per-command failures."""

    def __init__(self, fail_cmds=()):
        self.calls = []
        self.fail_cmds = tuple(fail_cmds)

    def connect(self, conn_spec):
        return self

    def execute(self, ctx, cmd):
        self.calls.append(("exec", cmd))
        for frag in self.fail_cmds:
            if frag in cmd:
                return Result(cmd=cmd, exit_status=1, out="", err="nope",
                              host="n1")
        return Result(cmd=cmd, exit_status=0, out="", err="", host="n1")

    def upload(self, ctx, local_paths, remote_path):
        self.calls.append(("upload", local_paths, remote_path))

    def download(self, ctx, remote_paths, local_path):
        self.calls.append(("download", remote_paths, local_path))


def test_scp_no_sudo_passthrough():
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "admin"})
    scp.upload({}, "/local/a", "/remote/a")
    assert stub.calls == [("upload", "/local/a", "/remote/a")]
    scp.download({}, "/remote/b", "/local/b")
    assert stub.calls[-1] == ("download", "/remote/b", "/local/b")


def test_scp_sudo_upload_dance():
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "admin"})
    scp.upload({"sudo": True}, "/local/a", "/etc/secret")
    kinds = [c[0] for c in stub.calls]
    # tmp dir prepared, upload to tmp, chown+mv as root, tmp cleaned
    assert "upload" in kinds
    up = next(c for c in stub.calls if c[0] == "upload")
    assert up[2].startswith("/tmp/jepsen/scp/")
    joined = " ".join(c[1] for c in stub.calls if c[0] == "exec")
    assert "chown root" in joined
    assert "mv /tmp/jepsen/scp/" in joined and "/etc/secret" in joined


def test_scp_sudo_download_unreadable_copies_via_tmp():
    # head fails -> must copy via tmp as root
    stub = StubRemote(fail_cmds=("head",))
    scp = SCPRemote(stub, {"username": "admin"})
    scp.download({"sudo": True}, "/var/log/secret.log", "/local/")
    joined = " ".join(c[1] for c in stub.calls if c[0] == "exec")
    assert "ln -L /var/log/secret.log" in joined
    dl = next(c for c in stub.calls if c[0] == "download")
    assert dl[1].startswith("/tmp/jepsen/scp/")


def test_scp_sudo_download_readable_direct():
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "admin"})
    scp.download({"sudo": True}, "/var/log/ok.log", "/local/")
    dl = next(c for c in stub.calls if c[0] == "download")
    assert dl[1] == "/var/log/ok.log"  # direct, no tmp dance


def test_scp_same_user_sudo_is_direct():
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "root"})
    scp.upload({"sudo": "root"}, "/a", "/b")
    assert stub.calls == [("upload", "/a", "/b")]


def test_scp_sudo_true_as_root_login_is_direct():
    """sudo=True with a root login user needs no impersonation dance."""
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "root"})
    scp.upload({"sudo": True}, "/a", "/b")
    assert stub.calls == [("upload", "/a", "/b")]


def test_scp_sudo_upload_multi_file_keeps_basenames():
    stub = StubRemote()
    scp = SCPRemote(stub, {"username": "admin"})
    scp.upload({"sudo": True}, ["/l/a.conf", "/l/b.conf"], "/etc/app/")
    joined = " ".join(c[1] for c in stub.calls
                      if c[0] == "exec" and "mv " in c[1])
    assert "/etc/app/a.conf" in joined
    assert "/etc/app/b.conf" in joined


def test_etcd_client_5xx_is_indeterminate():
    import io
    import urllib.error
    from jepsen_tpu.suites.etcd import EtcdClient
    c = EtcdClient(node="n1")

    def boom(url, data=None, method="GET"):
        raise urllib.error.HTTPError(url, 500, "election", {}, io.BytesIO(b""))

    c._request = boom
    out = c.invoke({}, {"f": "write", "value": [1, 2]})
    assert out["type"] == "info"  # mutation during election: indeterminate
    out = c.invoke({}, {"f": "read", "value": [1, None]})
    assert out["type"] == "fail"  # reads fail safely


def test_grepkill_brackets_pattern(dummy):
    """pkill -f must not match the wrapper shells running the command
    itself — the first alnum char gets bracketed."""
    from jepsen_tpu.control import util as cu
    t, remote = dummy
    control.on("n1", t, lambda: cu.grepkill("etcd", sig="STOP"))
    joined = " ".join(str(x) for x in remote.log)
    assert "[e]tcd" in joined


# ---------------------------------------------------------------------------
# charybdefs
# ---------------------------------------------------------------------------

def test_charybdefs_install_commands(dummy):
    t, remote = dummy
    control.on("n1", t, lambda: charybdefs.install())
    joined = " ".join(str(x) for x in remote.log)
    # dummy remote reports thrift/charybdefs binaries already present, so
    # only the mount phase runs
    assert "modprobe fuse" in joined
    assert "umount /faulty" in joined
    assert "subdir=/real" in joined


def test_charybdefs_nemesis_ops(dummy):
    t, remote = dummy
    n = charybdefs.FSFaultNemesis()
    assert n.fs() == {"break-fs", "heal-fs"}
    n.setup(t)
    out = n.invoke(t, {"type": "info", "f": "break-fs",
                       "value": {"nodes": ["n2"], "mode": "all"}})
    assert out["type"] == "info"
    assert out["value"]["nodes"] == ["n2"]
    joined = " ".join(str(x) for x in remote.log)
    assert "--io-error" in joined
    out = n.invoke(t, {"type": "info", "f": "heal-fs"})
    assert out["value"]["f"] == "heal-fs"
    joined = " ".join(str(x) for x in remote.log)
    assert "--clear" in joined
    n.teardown(t)


@pytest.mark.slow
def test_suite_test_all_sweeps_fake(tmp_path):
    """The shared test-all runner (suites.standard_test_all) sweeps
    every supported workload of a suite in fake mode (cli.clj:429-515;
    yugabyte has its own bespoke sweep, tested in test_pg_suites)."""
    from jepsen_tpu.suites import mongodb, rethinkdb

    for suite in (rethinkdb, mongodb):
        code = suite.main_all(["--no-ssh", "--time-limit", "1",
                               "--accelerator", "cpu",
                               "--store-dir", str(tmp_path)])
        assert code == 0, suite.__name__


@pytest.mark.slow
def test_faunadb_test_all_sweep_fake(tmp_path):
    """FaunaDB's sweep covers all eight workloads incl. the
    timestamp-monotonicity family."""
    from jepsen_tpu.suites import faunadb

    code = faunadb.main_all(["--no-ssh", "--time-limit", "1",
                             "--accelerator", "cpu",
                             "--store-dir", str(tmp_path)])
    assert code == 0
