"""Real-TPU parity tier (VERDICT r3 item 5): device-vs-CPU verdict
parity for the hot kernels on ONE real chip. The CPU-backend fuzz
cannot catch backend-specific breakage (layout, bf16, tunneled-dispatch
semantics) — this tier runs the same checks on the actual device.

Opt-in: ``JEPSEN_TPU_TESTS=1 python -m pytest -m tpu tests/`` on a host
with the axon tunnel up (conftest leaves the platform list alone when
the env var is set). Without the env var every test here skips
instantly and the normal suite never touches the tunnel.

First compiles are slow (~20-40s each) — the module warms shared
shape-buckets so later tests reuse compiled kernels.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

if not os.environ.get("JEPSEN_TPU_TESTS"):
    pytest.skip("JEPSEN_TPU_TESTS not set (real-chip tier is opt-in)",
                allow_module_level=True)


@pytest.fixture(scope="module")
def tpu_device():
    import jax
    devices = [d for d in jax.devices() if d.platform != "cpu"]
    if not devices:
        pytest.skip("no non-CPU jax device present")
    return devices[0]


def _histories():
    from __graft_entry__ import _register_history
    good = _register_history(2_000, n_procs=5, seed=7, n_values=5)
    bad = [dict(op) for op in good]
    # corrupt one mid-history read completion to a value NOBODY ever
    # writes (outside the 5-value domain) — unconditionally
    # non-linearizable regardless of concurrency structure
    for i in reversed(range(len(bad) // 2, len(bad))):
        op = bad[i]
        if op["type"] == "ok" and op["f"] == "read":
            bad[i] = {**op, "value": 97}
            break
    return good, bad


@pytest.fixture(scope="module")
def streams():
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    good, bad = _histories()
    return encode_register_ops(good), encode_register_ops(bad)


def test_matrix_kernel_verdict_parity(tpu_device, streams):
    """Block-composed transfer-matrix kernel vs the CPU WGL oracle."""
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.ops.jitlin import matrix_check

    good, bad = streams
    # force=True skips the min-size gate (the differential-test seam) so
    # the tier stays fast; the kernel itself is the production one
    m = matrix_check(good, force=True)
    assert m is not None and bool(m[0]) and not bool(m[2])
    assert check_stream(good).valid is True
    mb = matrix_check(bad, force=True)
    assert mb is not None and not bool(mb[0])
    assert check_stream(bad).valid is False


def test_event_scan_verdict_parity(tpu_device, streams):
    """Dense-table event-scan kernel vs the CPU oracle, both verdicts."""
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import JitLinKernel, _bucket, verdict

    good, bad = streams
    for stream, want in ((good, True), (bad, False)):
        batch = pad_streams([stream], length=_bucket(len(stream)))
        run = JitLinKernel()._get(stream.n_slots, 256, batched=False,
                                  num_states=len(stream.intern))
        import jax.numpy as jnp
        args = tuple(jnp.asarray(batch[k][0])
                     for k in ("kind", "slot", "f", "a", "b"))
        alive, died, ovf, _peak = [np.asarray(x) for x in run(*args)]
        assert verdict(bool(alive), bool(ovf)) is want
        assert check_stream(stream).valid is want


def test_batch_check_multikey_parity(tpu_device):
    """The vmapped multi-key dispatch agrees with the CPU oracle
    per key, including a planted failure."""
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.parallel import batch_check

    good, bad = _histories()
    streams = [encode_register_ops(
        _register_history(500, n_procs=5, seed=100 + k, n_values=5))
        for k in range(7)] + [encode_register_ops(bad)]
    results = batch_check(streams, capacity=256)
    cpu = [check_stream(s).valid for s in streams]
    dev = [bool(r[0]) and not bool(r[2]) for r in results]
    assert dev == cpu
    assert dev[-1] is False and all(dev[:-1])


def test_set_full_membership_parity(tpu_device):
    """Device membership-matrix set-full path vs the CPU walk."""
    from jepsen_tpu.checker import SetFullChecker

    history, present = [], []
    t = 0
    for v in range(800):
        history.append({"type": "invoke", "process": v % 5, "f": "add",
                        "value": v, "time": t})
        history.append({"type": "ok", "process": v % 5, "f": "add",
                        "value": v, "time": t + 1})
        present.append(v)
        t += 2
        if (v + 1) % 40 == 0:
            history.append({"type": "invoke", "process": 5, "f": "read",
                            "value": None, "time": t})
            history.append({"type": "ok", "process": 5, "f": "read",
                            "value": list(present), "time": t + 1})
            t += 2
    # plant a LOST element: 100 is visible in early reads (known), then
    # vanishes from every read past element 400 — known-then-absent is
    # the set-full "lost" verdict regardless of add acknowledgment
    lost_history = [dict(op) for op in history]
    for op in lost_history:
        if op.get("f") == "read" and op.get("type") == "ok" \
                and max(op["value"]) >= 400:
            op["value"] = [x for x in op["value"] if x != 100]
    for h, want in ((history, True), (lost_history, False)):
        r_dev = SetFullChecker(accelerator="tpu").check({}, h, {})
        r_cpu = SetFullChecker(accelerator="cpu").check({}, h, {})
        assert bool(r_dev["valid?"]) is want, r_dev
        assert r_dev["valid?"] == r_cpu["valid?"]
        assert r_dev["stable-count"] == r_cpu["stable-count"]
        assert r_dev.get("lost-count") == r_cpu.get("lost-count")


def test_scc_screen_parity(tpu_device):
    """Device SCC trim vs CPU Tarjan on cyclic and acyclic graphs."""
    from jepsen_tpu.ops.scc import has_cycle, tarjan_scc

    rng = np.random.default_rng(3)
    n = 500
    # random DAG: edges only forward
    src = rng.integers(0, n - 1, 2000)
    off = rng.integers(1, 50, 2000)
    dst = np.minimum(src + off, n - 1)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    assert has_cycle(n, src, dst) is False
    assert all(len(c) == 1 for c in tarjan_scc(
        n, list(zip(src.tolist(), dst.tolist()))))
    # close one long cycle
    src2 = np.concatenate([src, [n - 1]])
    dst2 = np.concatenate([dst, [0]])
    dev = has_cycle(n, src2, dst2)
    cpu_sccs = tarjan_scc(n, list(zip(src2.tolist(), dst2.tolist())))
    assert dev is (max(len(c) for c in cpu_sccs) > 1)


def test_elle_device_parity(tpu_device):
    """The list-append check's device screen agrees with the CPU path on
    a valid and an anomalous history."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _elle_history
    from jepsen_tpu.elle import list_append

    good = _elle_history(2_000)
    bad = _elle_history(2_000, crossed_pairs=10)
    for h, want in ((good, True), (bad, False)):
        r_dev = list_append.check(h, accelerator="tpu")
        r_cpu = list_append.check(h, accelerator="cpu")
        assert r_dev["valid?"] is want and r_cpu["valid?"] is want
        if not want:
            assert set(r_dev["anomaly-types"]) == set(r_cpu["anomaly-types"])


def test_pallas_chunk_product_parity(tpu_device, streams):
    """The pallas fused chunk product (ops/pallas_matrix.py) against
    the XLA scan path on the REAL chip, both verdict polarities. Also
    asserts the self-verifying probe actually admitted the pallas path
    on this backend (if Mosaic regressed, the probe must say so rather
    than this test silently exercising the fallback twice)."""
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.ops.jitlin import matrix_check

    good, bad = streams
    if not pm.enabled(5, 8):
        pytest.fail("pallas probe rejected the kernel on the real chip "
                    "(lowering failure or miscompile — see the log); "
                    f"_DISABLED={pm._DISABLED} _PROBED={pm._PROBED}")
    for stream, expect in ((good, True), (bad, False)):
        pal = matrix_check(stream, force=True)
        os.environ["JEPSEN_TPU_NO_PALLAS"] = "1"
        try:
            scan = matrix_check(stream, force=True)
        finally:
            del os.environ["JEPSEN_TPU_NO_PALLAS"]
        assert pal is not None and scan is not None
        assert bool(pal[0]) == bool(scan[0]) == expect
