"""CLI and web UI tests (reference: cli.clj exit-code contract
:129-139, web.clj table/zip)."""
import json
import tempfile
import threading
import urllib.request

from jepsen_tpu import cli, store


def test_noop_cli_run_and_exit_code():
    with tempfile.TemporaryDirectory() as tmp:
        code = cli.noop_main(["test", "--no-ssh", "--store-dir", tmp,
                              "--concurrency", "2"])
        assert code == cli.EXIT_OK
        # a store dir was created with test.json
        found = store.latest(tmp)
        assert found is not None
        name, ts, p = found
        assert (p / "test.json").exists()


def test_cli_analyze_stored_history():
    with tempfile.TemporaryDirectory() as tmp:
        assert cli.noop_main(["test", "--no-ssh", "--store-dir", tmp]) == 0
        code = cli.noop_main(["analyze", "--store-dir", tmp])
        assert code == cli.EXIT_OK


def test_parse_concurrency():
    assert cli.parse_concurrency("30", 5) == 30
    assert cli.parse_concurrency("3n", 5) == 15
    assert cli.parse_concurrency("n", 5) == 5


def test_web_ui_serves_table_and_files():
    from jepsen_tpu.web import make_server
    with tempfile.TemporaryDirectory() as tmp:
        assert cli.noop_main(["test", "--no-ssh", "--store-dir", tmp]) == 0
        srv = make_server(tmp, "127.0.0.1", 0)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            home = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10).read().decode()
            assert "noop" in home
            assert "valid-true" in home
            name, ts, _ = store.latest(tmp)
            res = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/{name}/{ts}/results.json",
                timeout=10).read().decode()
            assert json.loads(res)["valid?"] is True
            z = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/zip/{name}/{ts}", timeout=10).read()
            assert z[:2] == b"PK"
        finally:
            srv.shutdown()


def test_columnar_sidecar_round_trip(tmp_path):
    """history.npz reloads as a ColumnarHistory with the f table intact
    (the re-entrant-analysis restart format, SURVEY.md §5.4)."""
    from jepsen_tpu import store
    from jepsen_tpu.history import ColumnarHistory

    history = [
        {"type": "invoke", "f": "write", "value": 1, "process": 0,
         "time": 1000, "index": 0},
        {"type": "ok", "f": "write", "value": 1, "process": 0,
         "time": 2000, "index": 1},
        {"type": "invoke", "f": "read", "value": None, "process": 1,
         "time": 1500, "index": 2},
        {"type": "ok", "f": "read", "value": 1, "process": 1,
         "time": 2500, "index": 3},
    ]
    test = {"name": "colstore", "start_time": "20260101T000000",
            "store_dir": str(tmp_path), "history": history}
    store.write_columnar(test)
    col = store.load_columnar("colstore", "20260101T000000",
                              store_dir=str(tmp_path))
    ref = ColumnarHistory.from_ops(history)
    import numpy as np
    assert np.array_equal(col.types, ref.types)
    assert np.array_equal(col.completion_of, ref.completion_of)
    assert col.f_table == ref.f_table
    # f codes decode back to op names through the table
    assert col.f_table[int(col.fs[0])] == "write"
    assert col.f_table[int(col.fs[2])] == "read"


def test_web_validity_cache_invalidates_on_mtime(tmp_path):
    import os
    from jepsen_tpu.web import _validity, _VALIDITY_CACHE

    run = tmp_path / "t" / "ts"
    run.mkdir(parents=True)
    f = run / "results.json"
    f.write_text('{"valid?": true}')
    assert _validity(run) == (True, False)
    assert _validity(run) == (True, False)  # served from cache
    assert str(f) in _VALIDITY_CACHE
    f.write_text('{"valid?": false, "incomplete": true}')
    os.utime(f, ns=(1, 1))  # force a distinct mtime
    # mtime change invalidated the entry; incomplete badge surfaces
    assert _validity(run) == (False, True)
