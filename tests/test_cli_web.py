"""CLI and web UI tests (reference: cli.clj exit-code contract
:129-139, web.clj table/zip)."""
import json
import tempfile
import threading
import urllib.request

from jepsen_tpu import cli, store


def test_noop_cli_run_and_exit_code():
    with tempfile.TemporaryDirectory() as tmp:
        code = cli.noop_main(["test", "--no-ssh", "--store-dir", tmp,
                              "--concurrency", "2"])
        assert code == cli.EXIT_OK
        # a store dir was created with test.json
        found = store.latest(tmp)
        assert found is not None
        name, ts, p = found
        assert (p / "test.json").exists()


def test_cli_analyze_stored_history():
    with tempfile.TemporaryDirectory() as tmp:
        assert cli.noop_main(["test", "--no-ssh", "--store-dir", tmp]) == 0
        code = cli.noop_main(["analyze", "--store-dir", tmp])
        assert code == cli.EXIT_OK


def test_parse_concurrency():
    assert cli.parse_concurrency("30", 5) == 30
    assert cli.parse_concurrency("3n", 5) == 15
    assert cli.parse_concurrency("n", 5) == 5


def test_web_ui_serves_table_and_files():
    from jepsen_tpu.web import make_server
    with tempfile.TemporaryDirectory() as tmp:
        assert cli.noop_main(["test", "--no-ssh", "--store-dir", tmp]) == 0
        srv = make_server(tmp, "127.0.0.1", 0)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            home = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10).read().decode()
            assert "noop" in home
            assert "valid-true" in home
            name, ts, _ = store.latest(tmp)
            res = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/{name}/{ts}/results.json",
                timeout=10).read().decode()
            assert json.loads(res)["valid?"] is True
            z = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/zip/{name}/{ts}", timeout=10).read()
            assert z[:2] == b"PK"
        finally:
            srv.shutdown()
