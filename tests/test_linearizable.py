"""Linearizability checker tests: unit cases + differential testing of the
WGL oracle, the int-encoded CPU search, and the JAX kernel (on the virtual
CPU mesh). Mirrors the reference's knossos-as-oracle strategy
(SURVEY.md §4, BASELINE north_star)."""
import random

import pytest

from jepsen_tpu.checker.linear_cpu import check_stream, wgl
from jepsen_tpu.checker.linear_encode import encode_register_ops
from jepsen_tpu.checker.linearizable import LinearizableChecker
from jepsen_tpu.models import CASRegister


def op(typ, process, f, value=None):
    return {"type": typ, "process": process, "f": f, "value": value}


GOOD_SEQ = [
    op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
    op("invoke", 1, "read"), op("ok", 1, "read", 1),
    op("invoke", 0, "cas", [1, 2]), op("ok", 0, "cas", [1, 2]),
    op("invoke", 1, "read"), op("ok", 1, "read", 2),
]

BAD_READ = [
    op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
    op("invoke", 1, "read"), op("ok", 1, "read", 99),
]

# write(1) and read run concurrently: read may see None or 1
CONCURRENT_OK = [
    op("invoke", 0, "write", 1),
    op("invoke", 1, "read"),
    op("ok", 1, "read", 1),
    op("ok", 0, "write", 1),
]

# crashed write may have taken effect
CRASHED_WRITE_SEEN = [
    op("invoke", 0, "write", 7), op("info", 0, "write", 7),
    op("invoke", 1, "read"), op("ok", 1, "read", 7),
]

# failed write must NOT be visible
FAILED_WRITE_SEEN = [
    op("invoke", 0, "write", 7), op("fail", 0, "write", 7),
    op("invoke", 1, "read"), op("ok", 1, "read", 7),
]

# read completed before the write was invoked: must not see it
REAL_TIME_VIOLATION = [
    op("invoke", 1, "read"), op("ok", 1, "read", 7),
    op("invoke", 0, "write", 7), op("ok", 0, "write", 7),
]


CASES = [
    (GOOD_SEQ, True),
    (BAD_READ, False),
    (CONCURRENT_OK, True),
    (CRASHED_WRITE_SEEN, True),
    (FAILED_WRITE_SEEN, False),
    (REAL_TIME_VIOLATION, False),
    ([], True),
]


@pytest.mark.parametrize("history,expected", CASES)
def test_wgl_cases(history, expected):
    assert wgl(history, CASRegister()).valid is expected


@pytest.mark.parametrize("history,expected", CASES)
def test_jitlin_cpu_cases(history, expected):
    assert check_stream(encode_register_ops(history)).valid is expected


@pytest.mark.parametrize("history,expected", CASES)
def test_jitlin_device_cases(history, expected):
    from jepsen_tpu.ops.jitlin import JitLinKernel, verdict
    if not history:
        return
    stream = encode_register_ops(history)
    alive, died, ovf, peak = JitLinKernel().check(stream, capacity=64)
    assert verdict(alive, ovf) is expected


def test_checker_interface():
    chk = LinearizableChecker(accelerator="cpu")
    r = chk.check({}, GOOD_SEQ, {})
    assert r["valid?"] is True
    r = chk.check({}, BAD_READ, {})
    assert r["valid?"] is False
    assert r["failed-op"] is not None


def gen_history(rng: random.Random, n_procs=4, n_ops=40, values=4, corrupt=False):
    """Generates a register history by simulating a real register with
    random overlap; optionally corrupts one read to force non-linearizable
    (usually)."""
    reg = None
    history = []
    pending = {}  # process -> op
    procs = list(range(n_procs))
    ops_left = n_ops
    while ops_left > 0 or pending:
        p = rng.choice(procs)
        if p in pending:
            # complete p's op: apply it now (linearization point at completion)
            o = pending.pop(p)
            f, v = o["f"], o["value"]
            outcome = rng.random()
            if f == "read":
                o2 = op("ok", p, "read", reg)
            elif outcome < 0.1:
                o2 = op("info", p, f, v)   # indeterminate: maybe applied
                if rng.random() < 0.5:
                    reg = v if f == "write" else (v[1] if reg == v[0] else reg)
            elif outcome < 0.2 and f == "cas":
                o2 = op("fail", p, f, v)   # definitely not applied
            else:
                if f == "write":
                    reg = v
                    o2 = op("ok", p, f, v)
                else:  # cas
                    if reg == v[0]:
                        reg = v[1]
                        o2 = op("ok", p, f, v)
                    else:
                        o2 = op("fail", p, f, v)
            history.append(o2)
        elif ops_left > 0:
            ops_left -= 1
            r = rng.random()
            if r < 0.4:
                o = op("invoke", p, "read")
            elif r < 0.7:
                o = op("invoke", p, "write", rng.randrange(values))
            else:
                o = op("invoke", p, "cas", [rng.randrange(values), rng.randrange(values)])
            pending[p] = o
            history.append(o)
    if corrupt:
        reads = [i for i, o in enumerate(history)
                 if o["type"] == "ok" and o["f"] == "read"]
        if reads:
            i = rng.choice(reads)
            history[i] = dict(history[i], value=(history[i]["value"] or 0) + 100)
    return history


@pytest.mark.slow
def test_differential_random_histories():
    """wgl == jitlin-cpu == jax kernel across random valid/corrupted
    histories."""
    from jepsen_tpu.ops.jitlin import JitLinKernel, verdict
    kernel = JitLinKernel()
    rng = random.Random(7)
    n_disagreements = []
    for trial in range(60):
        corrupt = trial % 3 == 0
        h = gen_history(rng, n_procs=4, n_ops=30, corrupt=corrupt)
        r_wgl = wgl(h, CASRegister()).valid
        stream = encode_register_ops(h)
        r_jit = check_stream(stream).valid
        alive, _, ovf, _ = kernel.check(stream, capacity=128)
        r_dev = verdict(alive, ovf)
        assert r_wgl == r_jit, f"trial {trial}: wgl={r_wgl} jit={r_jit}\n{h}"
        assert r_jit == r_dev, f"trial {trial}: jit={r_jit} dev={r_dev}\n{h}"
        if not corrupt:
            assert r_wgl is True, f"trial {trial}: valid history judged {r_wgl}\n{h}"
        n_disagreements.append((r_wgl, corrupt))
    # corrupted histories should usually be invalid (sanity that the test
    # exercises both verdicts)
    assert any(v is False for v, _ in n_disagreements)
    assert any(v is True for v, _ in n_disagreements)


def test_wgl_handles_uncompleted_ops():
    h = [
        op("invoke", 0, "write", 1),   # never completes
        op("invoke", 1, "read"), op("ok", 1, "read", 1),
    ]
    assert wgl(h, CASRegister()).valid is True
    assert check_stream(encode_register_ops(h)).valid is True


def test_nemesis_ops_ignored():
    h = [
        {"type": "info", "process": "nemesis", "f": "start", "value": None},
        op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
        {"type": "info", "process": "nemesis", "f": "stop", "value": None},
    ]
    assert wgl(h, CASRegister()).valid is True
    assert check_stream(encode_register_ops(h)).valid is True


def test_dense_and_sparse_kernels_agree():
    """The exact dense-table kernel (small 2^S x V config spaces) and the
    capacity-K sort-based frontier must return identical verdicts; the
    batch path auto-selects dense, so pin each explicitly here."""
    import jax
    from jepsen_tpu.ops.jitlin import (JitLinKernel, _bucket, verdict)
    from jepsen_tpu.checker.linear_encode import pad_streams

    kernel = JitLinKernel()
    rng = random.Random(13)
    for trial in range(20):
        h = gen_history(rng, n_procs=3, n_ops=24, corrupt=trial % 3 == 0)
        if not h:
            continue
        stream = encode_register_ops(h)
        batch = pad_streams([stream], length=_bucket(len(stream)))
        S = max(1, batch["n_slots"])
        args = tuple(batch[k][0] for k in ("kind", "slot", "f", "a", "b"))
        dense = kernel._get(S, 128, batched=False,
                            num_states=len(stream.intern))
        sparse = kernel._get(S, 128, batched=False, num_states=None)
        da, _, dovf, _ = map(jax.device_get, dense(*args))
        sa, _, sovf, _ = map(jax.device_get, sparse(*args))
        assert not bool(dovf)  # dense is exact, never overflows
        assert verdict(bool(da), bool(dovf)) == verdict(bool(sa), bool(sovf)), \
            f"trial {trial}: dense={bool(da)} sparse={bool(sa)}\n{h}"


# ---------------------------------------------------------------------------
# block-composed transfer-matrix kernel (ops/jitlin.matrix_check)
# ---------------------------------------------------------------------------

def _scan_alive(history):
    """The event-scan kernel's aliveness for differential comparison."""
    import jax
    from jepsen_tpu.checker.linear_encode import (encode_register_ops,
                                                  pad_streams)
    from jepsen_tpu.ops.jitlin import JitLinKernel, _bucket
    stream = encode_register_ops(history)
    batch = pad_streams([stream], length=_bucket(len(stream)))
    run = JitLinKernel()._get(max(1, batch["n_slots"]), 256, batched=False,
                              num_states=len(stream.intern))
    args = tuple(jax.numpy.asarray(batch[k][0])
                 for k in ("kind", "slot", "f", "a", "b"))
    alive, _, _, _ = run(*args)
    return bool(alive)


@pytest.mark.slow
def test_matrix_kernel_differential_valid():
    from __graft_entry__ import _register_history  # conftest adds the root
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check
    for n, seed in ((60, 0), (60, 1), (300, 2), (300, 3)):
        h = _register_history(n, n_procs=4, seed=seed)
        m = matrix_check(encode_register_ops(h), force=True)
        assert m is not None
        assert m[0] == _scan_alive(h) is True, (n, seed)


@pytest.mark.slow
def test_matrix_kernel_differential_invalid():
    import random
    from __graft_entry__ import _register_history  # conftest adds the root
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check
    for seed in range(4):
        h = _register_history(200, n_procs=4, seed=100 + seed)
        rng = random.Random(seed)
        reads = [op for op in h
                 if op.get("f") == "read" and op.get("type") == "ok"]
        for op in rng.sample(reads, min(2, len(reads))):
            op["value"] = 999  # a value never written
        m = matrix_check(encode_register_ops(h), force=True)
        assert m is not None
        assert m[0] == _scan_alive(h) is False, seed


def test_matrix_kernel_gating():
    """The matrix path must decline outside its regime: large value
    domains (quadratic blowup) and short histories."""
    from jepsen_tpu.ops.jitlin import matrix_ok
    assert matrix_ok(5, 8, 5000)
    assert not matrix_ok(5, 101, 5000)   # 10k-op bench history: 101 values
    assert not matrix_ok(5, 8, 100)      # short history: scan is cheaper
    assert not matrix_ok(12, 8, 5000)    # too many slots


def test_matrix_kernel_shape_bucketing():
    """Nearby return counts must map to the same (T, G) chunk shape so
    the compiled program is reused, and G stays within the element cap."""
    from jepsen_tpu.ops.jitlin import (MATRIX_MAX_ELEMS, _bucket)
    import numpy as np
    shapes = set()
    for R in (2000, 2040, 2500, 3000):
        MV = 32 * 8
        rb = _bucket(R, floor=64)
        G = int(np.clip(rb // 120, 8, 256))
        G = max(1, min(G, MATRIX_MAX_ELEMS // (MV * MV)))
        T = -(-rb // G)
        shapes.add((T, G))
    assert len(shapes) <= 2  # 2048 and 4096 buckets
    # the memory cap engages for big MV
    MV = 4096
    G = max(1, min(256, MATRIX_MAX_ELEMS // (MV * MV)))
    assert G * MV * MV <= MATRIX_MAX_ELEMS


def test_returns_prepass_vectorized_differential():
    """The vectorized matrix-kernel prepass must agree event-for-event
    with the straightforward per-event walk it replaced."""
    import numpy as np
    from jepsen_tpu.ops.jitlin import EV_INVOKE, EV_RETURN, _returns_prepass

    def walk(kind, slot, f, a, b):
        fabs = np.stack([f, a, b], axis=1)
        S = int(slot.max(initial=0)) + 1
        cur = np.zeros((S, 3), np.int64)
        pend = np.zeros((S,), bool)
        r_slot, r_pend, r_ops = [], [], []
        for i in range(kind.shape[0]):
            k, s = int(kind[i]), int(slot[i])
            if k == EV_INVOKE:
                cur[s] = fabs[i]
                pend[s] = True
            elif k == EV_RETURN:
                r_slot.append(s)
                r_pend.append(pend.copy())
                r_ops.append(cur.copy())
                pend[s] = False
        if not r_slot:
            return (np.zeros((0,), np.int32), np.zeros((0, S), bool),
                    np.zeros((0, S, 3), np.int64), S)
        return (np.asarray(r_slot, np.int32), np.stack(r_pend),
                np.stack(r_ops), S)

    rng = np.random.default_rng(7)
    for trial in range(100):
        E, S = int(rng.integers(1, 80)), int(rng.integers(1, 6))
        kind, slot, pend = [], [], set()
        for _ in range(E):
            r = rng.random()
            if (r < 0.25 and pend) or (r < 0.85 and len(pend) == S):
                s = int(rng.choice(sorted(pend)))
                pend.discard(s)
                kind.append(EV_RETURN)
            elif r < 0.85:
                s = int(rng.choice([x for x in range(S) if x not in pend]))
                pend.add(s)
                kind.append(EV_INVOKE)
            else:
                s = 0
                kind.append(2)  # noop
            slot.append(s)
        kind, slot = np.array(kind), np.array(slot)
        f = rng.integers(0, 3, E)
        a = rng.integers(0, 9, E)
        b = rng.integers(0, 9, E)
        got = _returns_prepass(kind, slot, f, a, b)
        want = walk(kind, slot, f, a, b)
        assert got[3] == want[3], trial
        for g, w in zip(got[:3], want[:3]):
            assert np.array_equal(g, w), trial


def test_matrix_check_batch_differential_and_dispatch(monkeypatch):
    """batch_check must route in-regime batches through the key-batched
    transfer-matrix kernel and still agree per-key with the CPU oracle —
    including invalid keys, which fall back to the event scan for
    diagnostics."""
    import jepsen_tpu.ops.jitlin as jitlin
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.parallel import batch_check

    histories = []
    for k in range(8):
        h = _register_history(500, n_procs=4, seed=500 + k, n_values=5)
        if k % 3 == 2:  # corrupt: read a value never written
            reads = [op for op in h
                     if op.get("f") == "read" and op.get("type") == "ok"]
            reads[len(reads) // 2]["value"] = 999
        histories.append(h)
    streams = [encode_register_ops(h) for h in histories]

    calls = []
    real = jitlin.matrix_check_batch

    def spy(*a, **kw):
        calls.append(len(a[0]))
        return real(*a, **kw)

    monkeypatch.setattr(jitlin, "matrix_check_batch", spy)
    results = batch_check(streams, capacity=256)
    assert calls == [8], "in-regime batch must take the matrix path"
    for i, (s, r) in enumerate(zip(streams, results)):
        want = check_stream(s).valid
        assert (r[0] and not r[2]) == (want is True), (i, r, want)


def test_linearizable_checker_selects_matrix_path():
    """The device dispatch must pick the transfer-matrix kernel for long
    small-value-domain histories (its home regime)."""
    import jax
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    if not jax.devices():
        return
    h = _register_history(3000, n_procs=4, seed=11, n_values=5)
    res = LinearizableChecker(accelerator="tpu").check({}, h, {})
    assert res["valid?"] is True
    assert res["algorithm"] == "jitlin-tpu-matrix", res["algorithm"]


# ---------------------------------------------------------------------------
# failure rendering (reference: linear.svg, checker.clj:205-212)
# ---------------------------------------------------------------------------

def _failing_history():
    return [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "invoke", "process": 1, "f": "write", "value": 2},
        {"type": "ok", "process": 1, "f": "write", "value": 2},
        {"type": "invoke", "process": 0, "f": "read", "value": None},
        {"type": "ok", "process": 0, "f": "read", "value": 1},  # stale!
    ]


def test_check_stream_captures_final_configs():
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops

    res = check_stream(encode_register_ops(_failing_history()))
    assert res.valid is False
    assert res.final_configs, "dying frontier must be captured"
    for c in res.final_configs:
        assert set(c) == {"state", "linearized", "pending"}
    # just before the fatal read returns, the register held 2
    assert any(c["state"] == 2 for c in res.final_configs)


def test_linear_png_written_on_failure(tmp_path):
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    test = {"name": "lin-fail", "start_time": "20260730T000000",
            "store_dir": str(tmp_path)}
    out = LinearizableChecker(accelerator="cpu").check(
        test, _failing_history(), {})
    assert out["valid?"] is False
    assert out["final-configs"]
    plot = out.get("plot")
    assert plot and plot.endswith("linear.png")
    import os
    assert os.path.getsize(plot) > 0


def test_linear_png_device_path_recovers_configs(tmp_path):
    """A device verdict has no frontier detail; the report path re-runs
    the CPU twin to recover the dying configurations."""
    import jax
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linearizable import LinearizableChecker

    if not jax.devices():
        return
    h = _register_history(800, n_procs=4, seed=77, n_values=5)
    reads = [op for op in h
             if op.get("f") == "read" and op.get("type") == "ok"]
    reads[-1]["value"] = 999  # a value never written
    test = {"name": "lin-fail-tpu", "start_time": "20260730T000001",
            "store_dir": str(tmp_path)}
    out = LinearizableChecker(accelerator="tpu").check(test, h, {})
    assert out["valid?"] is False
    assert out["final-configs"]
    assert out.get("plot", "").endswith("linear.png")


def test_matrix_batch_mesh_divisible_chunks():
    """Odd key counts on a mesh must still shard: the chunk heuristic
    rounds G = B*C to a device-count multiple."""
    import jax
    from jax.sharding import Mesh
    import numpy as np
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check_batch

    devs = jax.devices()
    if len(devs) < 2:
        return
    mesh = Mesh(np.array(devs), ("keys",))
    # B=3: 256//3 = 85, 3*85 = 255 not divisible by common device counts
    streams = [encode_register_ops(
        _register_history(800, n_procs=4, seed=900 + k, n_values=5))
        for k in range(3)]
    results = matrix_check_batch(streams, mesh=mesh)
    for s, r in zip(streams, results):
        want = check_stream(s).valid
        assert (r[0] and not r[2]) == (want is True)


# ---------------------------------------------------------------------------
# segmented (resumable-frontier) verification
# ---------------------------------------------------------------------------

def test_quiescent_cuts_never_split_pending_ops():
    from jepsen_tpu.ops.jitlin import EV_INVOKE, EV_NOOP, EV_RETURN, quiescent_cuts
    import numpy as np

    # invoke,invoke,return,return | invoke,return | noop
    kind = np.asarray([EV_INVOKE, EV_INVOKE, EV_RETURN, EV_RETURN,
                       EV_INVOKE, EV_RETURN, EV_NOOP])
    cuts = quiescent_cuts(kind, max_segment=2)
    # window of 2 has no quiescent point at 2 (one op pending): must
    # extend to 4, then 6, then end
    assert cuts[0] == 4
    assert cuts[-1] == len(kind)
    # verify every cut is genuinely quiescent (or the end)
    delta = np.where(kind == EV_INVOKE, 1,
                     np.where(kind == EV_RETURN, -1, 0))
    pending = np.cumsum(delta)
    for c in cuts[:-1]:
        assert pending[c - 1] == 0


def _seg_stream(n_ops, seed=0, n_values=5):
    from __graft_entry__ import _register_history
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    return encode_register_ops(
        _register_history(n_ops, n_procs=4, seed=seed, n_values=n_values))


@pytest.mark.parametrize("max_segment", [64, 256])
def test_segmented_check_matches_whole_run_valid(max_segment):
    from jepsen_tpu.ops.jitlin import JitLinKernel, segmented_check

    stream = _seg_stream(600, seed=7)
    k = JitLinKernel()
    whole = k.check(stream)
    seg = segmented_check(stream, max_segment=max_segment, kernel=k)
    assert seg[0] == whole[0] is True
    assert seg[2] == whole[2]


def test_segmented_check_matches_whole_run_invalid():
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import JitLinKernel, segmented_check

    # a read that observes a never-written value after a quiescent point
    h = []
    for i, v in enumerate([1, 2, 3]):
        h.append({"type": "invoke", "process": 0, "f": "write", "value": v})
        h.append({"type": "ok", "process": 0, "f": "write", "value": v})
    h.append({"type": "invoke", "process": 1, "f": "read", "value": None})
    h.append({"type": "ok", "process": 1, "f": "read", "value": 99})
    stream = encode_register_ops(h)
    k = JitLinKernel()
    whole = k.check(stream)
    seg = segmented_check(stream, max_segment=4, kernel=k)
    assert whole[0] is False or whole[0] == False  # noqa: E712
    assert bool(seg[0]) is False
    assert seg[1] >= 0  # died index reported (global)


@pytest.mark.slow
def test_segmented_check_sparse_kernel_path():
    """Force the sparse (capacity-K) kernel by exceeding the dense
    state-count regime, exercising the mask/state resume carry."""
    from jepsen_tpu.ops.jitlin import JitLinKernel, segmented_check

    stream = _seg_stream(400, seed=3, n_values=800)  # V too big for dense
    k = JitLinKernel()
    whole = k.check(stream)
    seg = segmented_check(stream, max_segment=128, kernel=k,
                          num_states=801)
    assert bool(seg[0]) == bool(whole[0])


@pytest.mark.slow
def test_matrix_resume_matches_monolithic():
    """Chaining segment operator products equals one monolithic matrix
    run (block composition is associative), valid and invalid alike."""
    import numpy as np

    from jepsen_tpu.ops.jitlin import (JitLinKernel, _slice_stream,
                                       matrix_check, matrix_check_resume,
                                       quiescent_cuts)

    for seed, corrupt in ((11, False), (12, True)):
        stream = _seg_stream(800, seed=seed, n_values=5)
        if corrupt:
            from dataclasses import replace
            a_bad = np.asarray(stream.a).copy()
            reads = np.nonzero((np.asarray(stream.kind) == 0)
                               & (np.asarray(stream.f) == 0))[0]
            # scramble several mid-stream reads so at least one is
            # genuinely impossible (asserted below, deterministic seed)
            for i, r in enumerate(reads[40:55]):
                a_bad[r] = (a_bad[r] % 5) + 1 if i % 2 else 5
            stream = replace(stream, a=a_bad)
        whole = matrix_check(stream, force=True)
        assert bool(whole[0]) == (not corrupt), (seed, corrupt, whole)
        cuts = quiescent_cuts(np.asarray(stream.kind), 256)
        tot = None
        alive = True
        base = 0
        S = stream.n_slots
        for end in cuts:
            seg = _slice_stream(stream, base, end)
            a, inexact, tot = matrix_check_resume(seg, tot, n_slots=S)
            assert not bool(np.asarray(inexact).any())
            alive = bool(np.asarray(a).all())
            if not alive:
                break
            base = end
        assert alive == bool(whole[0]), (seed, corrupt, alive, whole)


# ---------------------------------------------------------------------------
# stored-column re-check (lin_* sidecar)
# ---------------------------------------------------------------------------

def test_stream_columns_roundtrip():
    import numpy as np

    from jepsen_tpu.checker.linear_encode import (
        encode_register_ops, stream_from_columns, stream_to_columns)

    h = []
    for i in range(30):
        p = i % 3
        h.append({"type": "invoke", "process": p, "f": "write", "value": i})
        h.append({"type": "ok", "process": p, "f": "write", "value": i})
        h.append({"type": "invoke", "process": p, "f": "read",
                  "value": None})
        h.append({"type": "ok", "process": p, "f": "read", "value": i})
    s0 = encode_register_ops(h)
    cols = stream_to_columns(s0)
    assert cols is not None
    s1 = stream_from_columns(cols)
    assert np.array_equal(s0.kind, s1.kind)
    assert np.array_equal(s0.f, s1.f)
    assert np.array_equal(s0.a, s1.a)
    assert s0.n_slots == s1.n_slots
    assert list(s0.intern.table) == list(s1.intern.table)


def test_stream_columns_reject_non_int_values():
    from jepsen_tpu.checker.linear_encode import (
        encode_register_ops, stream_to_columns)

    h = [{"type": "invoke", "process": 0, "f": "write", "value": "x"},
         {"type": "ok", "process": 0, "f": "write", "value": "x"}]
    assert stream_to_columns(encode_register_ops(h)) is None


def test_linear_check_stored_roundtrip(tmp_path):
    from jepsen_tpu import store
    from jepsen_tpu.checker import linearizable as lin_mod

    h = []
    for i in range(40):
        p = i % 3
        h.append({"type": "invoke", "process": p, "f": "write",
                  "value": i % 5, "time": 2 * i})
        h.append({"type": "ok", "process": p, "f": "write",
                  "value": i % 5, "time": 2 * i + 1})
    test = {"name": "lin-store-t", "start_time": "20260731T000001",
            "store_dir": str(tmp_path), "history": h}
    store.write_history(test)
    store.write_columnar(test)
    cols = store.load_linear_columns("lin-store-t", "20260731T000001",
                                     str(tmp_path))
    assert cols is not None, "register run must persist lin_* columns"
    out = lin_mod.check_stored("lin-store-t", "20260731T000001",
                               str(tmp_path), accelerator="cpu")
    assert out["valid?"] is True
    assert out["algorithm"].endswith("(stored)")


def test_linear_check_stored_invalid_falls_back(tmp_path):
    """An invalid verdict needs op context: the stored lane must defer
    to the jsonl path, which renders the full failure report."""
    from jepsen_tpu import store
    from jepsen_tpu.checker import linearizable as lin_mod

    h = [
        {"type": "invoke", "process": 0, "f": "write", "value": 1},
        {"type": "ok", "process": 0, "f": "write", "value": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": None},
        {"type": "ok", "process": 1, "f": "read", "value": 2},  # impossible
    ]
    test = {"name": "lin-store-bad", "start_time": "20260731T000002",
            "store_dir": str(tmp_path), "history": h}
    store.write_history(test)
    store.write_columnar(test)
    out = lin_mod.check_stored("lin-store-bad", "20260731T000002",
                               str(tmp_path), accelerator="cpu")
    assert out["valid?"] is False
    assert not out["algorithm"].endswith("(stored)")
    assert out.get("failed-op") is not None     # full object report


def test_lin_sidecar_survives_leading_nemesis_op(tmp_path):
    """A nemesis op before the first client op must not mask a register
    run from the lin_* sidecar probe."""
    from jepsen_tpu import store

    h = [{"type": "info", "process": "nemesis", "f": "start-partition",
          "value": None}]
    for i in range(10):
        h.append({"type": "invoke", "process": 0, "f": "write",
                  "value": i})
        h.append({"type": "ok", "process": 0, "f": "write", "value": i})
    test = {"name": "lin-nem-t", "start_time": "20260801T000003",
            "store_dir": str(tmp_path), "history": h}
    store.write_history(test)
    store.write_columnar(test)
    assert store.load_linear_columns(
        "lin-nem-t", "20260801T000003", str(tmp_path)) is not None
