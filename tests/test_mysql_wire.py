"""The stdlib MySQL wire client against a scripted in-process server.

Covers the protocol surface the MySQL-family suites depend on
(handshake + mysql_native_password, OK/ERR/resultset parsing,
auth-switch), the way the reference unit-tests its transports against
local endpoints (control_test.clj pattern, SURVEY.md §4)."""
from __future__ import annotations

import hashlib
import socket
import struct
import threading

import pytest

from jepsen_tpu.suites._mysql import (MySQLConnection, MySQLError,
                                      native_password_scramble)

NONCE = b"abcdefgh" + b"ijklmnopqrst"  # 8 + 12 bytes
PASSWORD = "jepsenpw"


def _packet(seq: int, payload: bytes) -> bytes:
    return len(payload).to_bytes(3, "little") + bytes([seq]) + payload


def _greeting() -> bytes:
    return (b"\x0a" + b"8.0.0-fake\x00"
            + struct.pack("<I", 42)          # thread id
            + NONCE[:8] + b"\x00"            # auth data part 1 + filler
            + struct.pack("<H", 0xFFFF)      # caps low (incl SECURE_CONN)
            + b"\x21"                        # charset
            + struct.pack("<H", 0x0002)      # status
            + struct.pack("<H", 0x000F)      # caps high (incl PLUGIN_AUTH)
            + bytes([len(NONCE) + 1])        # auth data len
            + b"\x00" * 10
            + NONCE[8:] + b"\x00"            # part 2, null-terminated
            + b"mysql_native_password\x00")


def _eof() -> bytes:
    return b"\xfe\x00\x00\x02\x00"


def _lenenc_str(s: str) -> bytes:
    raw = s.encode()
    assert len(raw) < 0xFB
    return bytes([len(raw)]) + raw


class FakeServer:
    """Accepts one connection, validates auth, answers scripted queries."""

    def __init__(self, auth_switch: bool = False):
        self.auth_switch = auth_switch
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.errors: list[str] = []
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _recv_packet(self, conn) -> bytes:
        header = b""
        while len(header) < 4:
            chunk = conn.recv(4 - len(header))
            if not chunk:
                return b""
            header += chunk
        n = int.from_bytes(header[:3], "little")
        payload = b""
        while len(payload) < n:
            payload += conn.recv(n - len(payload))
        return payload

    def _serve(self):
        conn, _ = self.sock.accept()
        try:
            conn.sendall(_packet(0, _greeting()))
            resp = self._recv_packet(conn)
            caps, _maxp, _cs = struct.unpack_from("<IIB", resp, 0)
            pos = 32
            end = resp.index(b"\x00", pos)
            user = resp[pos:end].decode()
            pos = end + 1
            alen = resp[pos]
            auth = resp[pos + 1:pos + 1 + alen]
            if user != "jepsen":
                self.errors.append(f"bad user {user!r}")
            if self.auth_switch:
                new_nonce = b"ZYXWVUTSRQPONMLKJIHG"
                conn.sendall(_packet(2, b"\xfemysql_native_password\x00"
                                     + new_nonce + b"\x00"))
                auth = self._recv_packet(conn)
                expect = native_password_scramble(PASSWORD, new_nonce)
            else:
                expect = native_password_scramble(PASSWORD, NONCE[:20])
            if auth != expect:
                self.errors.append("bad scramble")
            conn.sendall(_packet(4 if self.auth_switch else 2,
                                 b"\x00\x00\x00\x02\x00\x00\x00"))
            while True:
                q = self._recv_packet(conn)
                if not q or q[0] == 0x01:  # COM_QUIT / close
                    return
                sql = q[1:].decode()
                if sql.startswith("SELECT"):
                    conn.sendall(_packet(1, b"\x02"))          # 2 columns
                    coldef = _lenenc_str("def") * 7 + b"\x0c" + b"\x00" * 10
                    conn.sendall(_packet(2, coldef))
                    conn.sendall(_packet(3, coldef))
                    conn.sendall(_packet(4, _eof()))
                    conn.sendall(_packet(5, _lenenc_str("5")
                                         + _lenenc_str("hello")))
                    conn.sendall(_packet(6, b"\xfb" + _lenenc_str("x")))
                    conn.sendall(_packet(7, _eof()))
                elif sql.startswith("BOOM"):
                    conn.sendall(_packet(1, b"\xff" + struct.pack("<H", 1062)
                                         + b"#23000duplicate key"))
                else:
                    conn.sendall(_packet(
                        1, b"\x00\x03\x07\x02\x00\x00\x00"))  # 3 rows, id 7
        finally:
            conn.close()
            self.sock.close()


def test_scramble_matches_reference_algorithm():
    h1 = hashlib.sha1(b"pw").digest()
    h2 = hashlib.sha1(h1).digest()
    expect = bytes(a ^ b for a, b in zip(
        h1, hashlib.sha1(b"n" * 20 + h2).digest()))
    assert native_password_scramble("pw", b"n" * 20) == expect
    assert native_password_scramble("", b"n" * 20) == b""


def test_query_roundtrip():
    srv = FakeServer()
    conn = MySQLConnection("127.0.0.1", srv.port, user="jepsen",
                           password=PASSWORD, timeout_s=5)
    assert conn.server_version == "8.0.0-fake"
    rows = conn.query("SELECT v FROM t")
    assert rows == [("5", "hello"), (None, "x")]
    affected, last_id = conn.query("INSERT INTO t VALUES (1)")
    assert (affected, last_id) == (3, 7)
    with pytest.raises(MySQLError) as err:
        conn.query("BOOM")
    assert err.value.code == 1062 and err.value.sqlstate == "23000"
    conn.close()
    srv.thread.join(timeout=5)
    assert srv.errors == []


def test_auth_switch():
    srv = FakeServer(auth_switch=True)
    conn = MySQLConnection("127.0.0.1", srv.port, user="jepsen",
                           password=PASSWORD, timeout_s=5)
    assert conn.query("UPDATE t SET x=1")[0] == 3
    conn.close()
    srv.thread.join(timeout=5)
    assert srv.errors == []
