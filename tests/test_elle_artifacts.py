"""Elle anomaly artifacts: per-anomaly-type explanation files in the
store on invalid txn checks, linked from the web UI run page (the
reference's elle output directory, append.clj:17-22)."""
import tempfile
import threading
import urllib.request
from pathlib import Path

from jepsen_tpu.elle import artifacts


def _anomalous_history():
    """Two mutually-observing append txns: a wr cycle (G1c)."""
    h = []
    t = 0

    def txn(proc, mops):
        nonlocal t
        h.append({"type": "invoke", "process": proc,
                  "value": [[m[0], m[1], None if m[0] == "r" else m[2]]
                            for m in mops], "time": t})
        h.append({"type": "ok", "process": proc, "value": mops,
                  "time": t + 1})
        t += 2

    txn(0, [["append", 0, 1], ["r", 1, [2]]])
    txn(1, [["append", 1, 2], ["r", 0, [1]]])
    return h


def test_write_artifacts_renders_cycles(tmp_path):
    result = {
        "valid?": False,
        "anomalies": {
            "G1c": [[{"from": [["append", 0, 1], ["r", 1, [2]]],
                      "type": "wr",
                      "to": [["append", 1, 2], ["r", 0, [1]]]},
                     {"from": [["append", 1, 2], ["r", 0, [1]]],
                      "type": "wr",
                      "to": [["append", 0, 1], ["r", 1, [2]]]}]],
            "G1a": [{"key": 3, "value": 9}],
        },
    }
    written = artifacts.write_artifacts(tmp_path, result)
    assert set(written) == {"G1c.txt", "G1a.txt", "index.txt"}
    g1c = (tmp_path / "G1c.txt").read_text()
    # human-readable: the gloss, the op terms, and the edge arrows
    assert "Cyclic information flow" in g1c
    assert "append 0 1" in g1c
    assert "--wr-->" in g1c
    idx = (tmp_path / "index.txt").read_text()
    assert "G1c.txt" in idx and "valid?: False" in idx


def test_write_artifacts_empty_result(tmp_path):
    assert artifacts.write_artifacts(tmp_path, {"valid?": True}) == []
    assert not (tmp_path / "index.txt").exists()


def test_append_checker_writes_store_artifacts():
    """End to end: an invalid list-append check through the workload
    checker leaves readable elle/ files in the test's store dir."""
    from jepsen_tpu.workloads import append as append_wl

    with tempfile.TemporaryDirectory() as tmp:
        test = {"name": "elle-art", "start_time": "20260803T000000",
                "store_dir": tmp}
        chk = append_wl.checker(accelerator="cpu")
        res = chk.check(test, _anomalous_history(), {})
        assert res["valid?"] is False
        d = Path(tmp) / "elle-art" / "20260803T000000" / "elle"
        assert (d / "index.txt").exists()
        files = sorted(p.name for p in d.iterdir())
        assert any(f.startswith("G") for f in files)
        # every artifact is plain readable text mentioning the ops
        body = "".join((d / f).read_text() for f in files)
        assert "append" in body


def test_valid_check_writes_nothing():
    from jepsen_tpu.workloads import append as append_wl

    history = [
        {"type": "invoke", "process": 0, "value": [["append", 0, 1]],
         "time": 0},
        {"type": "ok", "process": 0, "value": [["append", 0, 1]],
         "time": 1},
    ]
    with tempfile.TemporaryDirectory() as tmp:
        test = {"name": "elle-ok", "start_time": "20260803T000000",
                "store_dir": tmp}
        res = append_wl.checker(accelerator="cpu").check(test, history, {})
        assert res["valid?"] is True
        assert not (Path(tmp) / "elle-ok" / "20260803T000000"
                    / "elle").exists()


def test_web_run_page_links_elle_artifacts():
    from jepsen_tpu.web import make_server
    from jepsen_tpu.workloads import append as append_wl

    with tempfile.TemporaryDirectory() as tmp:
        test = {"name": "elle-web", "start_time": "20260803T000000",
                "store_dir": tmp}
        append_wl.checker(accelerator="cpu").check(
            test, _anomalous_history(), {})
        # the run page needs a dir; the checker created it
        srv = make_server(tmp, "127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/elle-web/20260803T000000/",
                timeout=10).read().decode()
            assert "anomalies (elle)" in page
            assert "index.txt" in page
            art = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/elle-web/20260803T000000/"
                f"elle/index.txt", timeout=10).read().decode()
            assert "Elle anomaly artifacts" in art
        finally:
            srv.shutdown()
