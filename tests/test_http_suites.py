"""HTTP-API suites (elasticsearch, crate, dgraph, ignite, hazelcast,
chronos): client wire behavior against scripted in-process HTTP
servers, DB-automation command shapes over the dummy remote, and full
fake-mode lifecycle runs (reference tier-1/2 strategy, SURVEY.md §4)."""
from __future__ import annotations

import json
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from jepsen_tpu import control
from jepsen_tpu.suites import (chronos, crate, dgraph, elasticsearch,
                               hazelcast, ignite)

NODES = ["n1", "n2", "n3", "n4", "n5"]


class ScriptedHTTP:
    """Serves responses from a handler fn(method, path, body) ->
    (status, payload); records every request."""

    def __init__(self, fn):
        self.fn = fn
        self.requests: list[tuple[str, str, bytes]] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _go(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                outer.requests.append((self.command, self.path, body))
                status, payload = outer.fn(self.command, self.path, body)
                raw = (json.dumps(payload).encode()
                       if not isinstance(payload, (bytes, str))
                       else (payload.encode() if isinstance(payload, str)
                             else payload))
                self.send_response(status)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            do_GET = do_POST = do_PUT = do_DELETE = _go

            def log_message(self, *a):
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


def hostport(port):
    return f"127.0.0.1:{port}"


# ---------------------------------------------------------------------------
# ignite: REST cas protocol
# ---------------------------------------------------------------------------

def test_ignite_client_cas_protocol():
    def fn(method, path, body):
        if "cmd=get" in path:
            return 200, {"successStatus": 0, "response": "7"}
        if "cmd=cas" in path:
            ok = "val2=7" in path
            return 200, {"successStatus": 0, "response": ok}
        if "cmd=put" in path:
            return 200, {"successStatus": 0, "response": True}
        return 200, {"successStatus": 1, "error": "bad cmd"}

    srv = ScriptedHTTP(fn)
    try:
        c = ignite.IgniteClient(node="127.0.0.1")
        # patch port by pointing REST_PORT-based URL at the fake server
        c._cmd_orig = c._cmd
        import urllib.parse

        def _cmd(**params):
            qs = urllib.parse.urlencode({"cacheName": ignite.CACHE, **params})
            from jepsen_tpu.suites._http import http_json
            doc = http_json(f"http://127.0.0.1:{srv.port}/ignite?{qs}")
            if doc.get("successStatus") != 0:
                raise ignite.IgniteError(doc.get("error") or str(doc))
            return doc.get("response")
        c._cmd = _cmd

        op = {"type": "invoke", "process": 0, "f": "read", "value": [3, None]}
        assert c.invoke({}, op)["value"] == [3, 7]
        cas = {"type": "invoke", "process": 0, "f": "cas", "value": [3, [7, 9]]}
        assert c.invoke({}, cas)["type"] == "ok"
        cas_bad = {"type": "invoke", "process": 0, "f": "cas",
                   "value": [3, [6, 9]]}
        assert c.invoke({}, cas_bad)["type"] == "fail"
        w = {"type": "invoke", "process": 0, "f": "write", "value": [3, 5]}
        assert c.invoke({}, w)["type"] == "ok"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# crate: _sql endpoint
# ---------------------------------------------------------------------------

def test_crate_client_sql_protocol():
    state = {"val": 4}

    def fn(method, path, body):
        doc = json.loads(body) if body else {}
        stmt = doc.get("stmt", "")
        if stmt.startswith("UPDATE registers SET val"):
            new, k, old = doc["args"]
            if state["val"] == old:
                state["val"] = new
                return 200, {"rowcount": 1, "rows": []}
            return 200, {"rowcount": 0, "rows": []}
        if stmt.startswith("SELECT val"):
            return 200, {"rows": [[state["val"]]]}
        if stmt.startswith("SELECT id"):
            return 200, {"rows": [[1], [2]]}
        return 200, {"rowcount": 1, "rows": []}

    srv = ScriptedHTTP(fn)
    try:
        c = crate.CrateClient(node="127.0.0.1")
        real_sql = c._sql

        def _sql(stmt, args=None):
            from jepsen_tpu.suites._http import http_json
            return http_json(f"http://127.0.0.1:{srv.port}/_sql",
                             {"stmt": stmt, "args": args or []})
        c._sql = _sql

        r = c.invoke({}, {"type": "invoke", "f": "read", "value": [9, None]})
        assert r["type"] == "ok" and r["value"] == [9, 4]
        good = c.invoke({}, {"type": "invoke", "f": "cas", "value": [9, [4, 5]]})
        assert good["type"] == "ok" and state["val"] == 5
        bad = c.invoke({}, {"type": "invoke", "f": "cas", "value": [9, [4, 6]]})
        assert bad["type"] == "fail" and state["val"] == 5
        s = c.invoke({}, {"type": "invoke", "f": "read", "value": None})
        assert s["value"] == [1, 2]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# dgraph: txn cas protocol (query@ts -> mutate@ts -> commit)
# ---------------------------------------------------------------------------

def test_dgraph_client_txn_cas():
    committed = {"n": 0}

    def fn(method, path, body):
        if path.startswith("/query"):
            return 200, {"data": {"q": [{"uid": "0x1", "val": 3}]},
                         "extensions": {"txn": {"start_ts": 42}}}
        if path.startswith("/mutate"):
            assert "startTs=42" in path
            return 200, {"data": {},
                         "extensions": {"txn": {"start_ts": 42,
                                                "keys": ["k1"],
                                                "preds": ["1-val"]}}}
        if path.startswith("/commit"):
            committed["n"] += 1
            return 200, {"data": {"code": "Success"}}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.dgraph as dg
        c = dg.DgraphClient(node="127.0.0.1")
        old_port = dg.ALPHA_HTTP_PORT
        dg.ALPHA_HTTP_PORT = srv.port
        try:
            ok = c.invoke({}, {"type": "invoke", "f": "cas",
                               "value": [7, [3, 8]]})
            assert ok["type"] == "ok" and committed["n"] == 1
            stale = c.invoke({}, {"type": "invoke", "f": "cas",
                                  "value": [7, [5, 8]]})
            assert stale["type"] == "fail" and committed["n"] == 1
        finally:
            dg.ALPHA_HTTP_PORT = old_port
    finally:
        srv.stop()


def test_dgraph_db_commands():
    t = {"nodes": NODES, "ssh": {"dummy": True}}
    remote = control.default_remote(t)
    db = dgraph.DgraphDB()
    try:
        control.on("n2", t, lambda: db.start(t, "n2"))
        joined = " ".join(str(x) for x in remote.log)
        assert "alpha" in joined and "--zero n1:5080" in joined
    finally:
        control.disconnect_all(t)


# ---------------------------------------------------------------------------
# hazelcast: queue REST mapping
# ---------------------------------------------------------------------------

def test_hazelcast_client_queue_protocol():
    # offer = POST with the value as request body; poll = DELETE with a
    # timeout path segment (the Hazelcast REST queue API shape)
    q: list[str] = ["10", "11"]

    def fn(method, path, body):
        if method == "POST":
            q.append(body.decode())
            return 200, ""
        assert method == "DELETE" and path.endswith("/1")
        return 200, (q.pop(0) if q else "")

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.hazelcast as hz
        c = hz.HazelcastClient(node="127.0.0.1")
        old_port = hz.PORT
        hz.PORT = srv.port
        try:
            e = c.invoke({}, {"type": "invoke", "f": "enqueue", "value": 12})
            assert e["type"] == "ok"
            d = c.invoke({}, {"type": "invoke", "f": "dequeue"})
            assert d["type"] == "ok" and d["value"] == 10
            dr = c.invoke({}, {"type": "invoke", "f": "drain"})
            assert dr["type"] == "ok" and dr["value"] == [11, 12]
            empty = c.invoke({}, {"type": "invoke", "f": "dequeue"})
            assert empty["type"] == "fail"
        finally:
            hz.PORT = old_port
    finally:
        srv.stop()


def test_hazelcast_drain_crash_keeps_partial_elements():
    """A network error mid-drain must not lose already-polled elements."""
    from jepsen_tpu import checker as chk
    polls = {"n": 0}

    def fn(method, path, body):
        if method == "POST":
            return 200, ""
        polls["n"] += 1
        if polls["n"] >= 3:
            raise BrokenPipeError("boom")  # kills the connection
        return 200, str(polls["n"])

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.hazelcast as hz
        c = hz.HazelcastClient(node="127.0.0.1", timeout_s=2)
        old_port = hz.PORT
        hz.PORT = srv.port
        try:
            dr = c.invoke({}, {"type": "invoke", "f": "drain"})
            assert dr["type"] == "info"
            assert dr["value"] == [1, 2]
        finally:
            hz.PORT = old_port
        # the expansion turns the partial info drain into real dequeues
        h = [{"type": "invoke", "process": 0, "f": "enqueue", "value": 1},
             {"type": "ok", "process": 0, "f": "enqueue", "value": 1},
             {"type": "invoke", "process": 0, "f": "enqueue", "value": 2},
             {"type": "ok", "process": 0, "f": "enqueue", "value": 2},
             {"type": "invoke", "process": 1, "f": "drain"},
             {**dr, "process": 1}]
        res = chk.total_queue().check({}, h, {})
        assert res["valid?"] is True and res["lost-count"] == 0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# elasticsearch: seq_no CAS mapping
# ---------------------------------------------------------------------------

def test_elasticsearch_client_cas_protocol():
    doc = {"v": 1, "seq": 5, "term": 1}

    def fn(method, path, body):
        if method == "GET" and "/_doc/" in path:
            return 200, {"_source": {"v": doc["v"]}, "_seq_no": doc["seq"],
                         "_primary_term": doc["term"]}
        if method == "PUT" and "if_seq_no=" in path:
            want = int(path.split("if_seq_no=")[1].split("&")[0])
            if want != doc["seq"]:
                return 409, {"error": "version_conflict"}
            doc["v"] = json.loads(body)["v"]
            doc["seq"] += 1
            return 200, {"result": "updated"}
        if method == "PUT":
            doc["v"] = json.loads(body)["v"]
            doc["seq"] += 1
            return 200, {"result": "updated"}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.elasticsearch as es
        c = es.ElasticsearchClient(node="127.0.0.1")
        old_port = es.PORT
        es.PORT = srv.port
        try:
            ok = c.invoke({}, {"type": "invoke", "f": "cas",
                               "value": [0, [1, 2]]})
            assert ok["type"] == "ok" and doc["v"] == 2
            stale = c.invoke({}, {"type": "invoke", "f": "cas",
                                  "value": [0, [1, 3]]})
            assert stale["type"] == "fail"
            # race: doc moves between read and conditional put -> 409 -> fail
            doc["v"] = 3
            real_get = c._get_doc

            def racy_get(k):
                v, s, t = real_get(k)
                doc["seq"] += 1  # someone else writes in the window
                return v, s, t
            c._get_doc = racy_get
            raced = c.invoke({}, {"type": "invoke", "f": "cas",
                                  "value": [0, [3, 4]]})
            assert raced["type"] == "fail"
        finally:
            es.PORT = old_port
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# chronos: targets + matching + full fake run
# ---------------------------------------------------------------------------

def test_chronos_targets_and_matching():
    job = {"name": 1, "start": 100, "interval": 60, "count": 3,
           "epsilon": 10, "duration": 5}
    # read at 400: all 3 targets due (last begins 220, finish cutoff 385)
    targets = chronos.job_targets(400, job)
    assert [t[0] for t in targets] == [100, 160, 220]
    assert targets[0][1] == 100 + 10 + chronos.EPSILON_FORGIVENESS
    # read at 170: only the first two targets are due
    assert [t[0] for t in chronos.job_targets(230, job)] == [100, 160]

    matched, unmatched = chronos.match_targets(targets, [101, 162, 221])
    assert not unmatched and len(matched) == 3
    # one run can't satisfy two targets
    matched, unmatched = chronos.match_targets(targets, [101])
    assert len(matched) == 1 and len(unmatched) == 2
    # greedy must leave the early run for the early window
    two = chronos.job_targets(230, job)
    matched, unmatched = chronos.match_targets(two, [114, 115])
    assert len(unmatched) == 1  # 115 fits window-1 only; 160s window empty


def test_chronos_checker_verdicts():
    ck = chronos.ChronosChecker()
    job = {"name": 1, "start": 100, "interval": 60, "count": 2,
           "epsilon": 10, "duration": 0}
    h = [
        {"type": "invoke", "process": 0, "f": "add-job", "value": job},
        {"type": "ok", "process": 0, "f": "add-job", "value": job},
        {"type": "invoke", "process": 1, "f": "read"},
        {"type": "ok", "process": 1, "f": "read",
         "value": {"read-time": 400, "runs": {"1": [100, 161]}}},
    ]
    assert ck.check({}, h, {})["valid?"] is True
    h[-1]["value"]["runs"]["1"] = [100]
    res = ck.check({}, h, {})
    assert res["valid?"] is False
    assert res["jobs"]["1"]["unmatched"] == [[160, 175]]


def test_chronos_fake_run():
    with tempfile.TemporaryDirectory() as tmp:
        t = chronos.chronos_test({"fake": True, "time_limit": 1.0,
                                  "store_dir": tmp, "no_perf": True,
                                  "accelerator": "cpu"})
        from jepsen_tpu import core
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]


# ---------------------------------------------------------------------------
# fake-mode lifecycle for the other new suites
# ---------------------------------------------------------------------------

def run_fake(suite_test_fn, **opts):
    with tempfile.TemporaryDirectory() as tmp:
        t = suite_test_fn({"fake": True, "time_limit": 1.0,
                           "store_dir": tmp, "no_perf": True,
                           "accelerator": "cpu", **opts})
        from jepsen_tpu import core
        return core.run(t)


@pytest.mark.slow
def test_hazelcast_fake_queue_run():
    result = run_fake(hazelcast.hazelcast_test, workload="queue")
    r = result["results"]
    assert r["valid?"] is True, r
    assert r["workload"]["attempt-count"] > 0


@pytest.mark.slow
def test_elasticsearch_fake_set_run():
    result = run_fake(elasticsearch.elasticsearch_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_crate_fake_register_run():
    result = run_fake(crate.crate_test, workload="register")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_ignite_fake_register_run():
    result = run_fake(ignite.ignite_test, workload="register")
    assert result["results"]["valid?"] is True, result["results"]


@pytest.mark.slow
def test_dgraph_fake_set_run():
    result = run_fake(dgraph.dgraph_test, workload="set")
    assert result["results"]["valid?"] is True, result["results"]


def test_dgraph_client_bank_and_wr_txn():
    """bank transfers and rw-register txns run as real dgraph txns:
    snapshot query at start_ts, mutate at the same ts, commit
    (dgraph/bank.clj, wr.clj shapes)."""
    calls = {"commits": 0, "mutates": []}

    def fn(method, path, body):
        if path.startswith("/query"):
            q = body.decode()
            if "has(acct)" in q:
                return 200, {"data": {"q": [
                    {"acct": 0, "balance": 7},
                    {"acct": 1, "balance": 3}]}}
            if "acct" in q:
                return 200, {"data": {
                    "a": [{"uid": "0xa", "balance": 7}],
                    "b": [{"uid": "0xb", "balance": 3}]},
                    "extensions": {"txn": {"start_ts": 9}}}
            return 200, {"data": {"k1": [{"uid": "0x1", "val": 5}],
                                  "k2": []},
                         "extensions": {"txn": {"start_ts": 9}}}
        if path.startswith("/mutate"):
            assert "startTs=9" in path
            calls["mutates"].append(json.loads(body.decode()))
            return 200, {"data": {},
                         "extensions": {"txn": {"start_ts": 9,
                                                "keys": ["x"],
                                                "preds": ["p"]}}}
        if path.startswith("/commit"):
            calls["commits"] += 1
            return 200, {"data": {"code": "Success"}}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.dgraph as dg
        c = dg.DgraphClient(node="127.0.0.1")
        old_port = dg.ALPHA_HTTP_PORT
        dg.ALPHA_HTTP_PORT = srv.port
        try:
            # bank whole-read must return balances, not the element set
            # (regression: the set read branch used to shadow it)
            out = c.invoke({"accounts": [0, 1]},
                           {"type": "invoke", "f": "read", "value": None})
            assert out["type"] == "ok" and out["value"] == {0: 7, 1: 3}

            out = c.invoke({}, {"type": "invoke", "f": "transfer",
                                "value": {"from": 0, "to": 1, "amount": 5}})
            assert out["type"] == "ok" and calls["commits"] == 1
            sets = calls["mutates"][0]["set"]
            assert {"uid": "0xa", "balance": 2} in sets
            assert {"uid": "0xb", "balance": 8} in sets
            # overdraft refused before any mutate
            out = c.invoke({}, {"type": "invoke", "f": "transfer",
                                "value": {"from": 0, "to": 1, "amount": 9}})
            assert out["type"] == "fail" and out["error"][0] == "negative"
            assert calls["commits"] == 1

            out = c.invoke({}, {"type": "invoke", "f": "txn",
                                "value": [["r", 1, None], ["w", 2, 4],
                                          ["r", 2, None]]})
            assert out["type"] == "ok"
            assert out["value"][0] == ["r", 1, 5]
            assert out["value"][2] == ["r", 2, 4]  # sees own write
            mut = calls["mutates"][1]
            # writes ride an upsert block: uid bound by query var, so a
            # fresh key creates exactly once under the @upsert index
            assert "w2(func: eq(key, 2)) { u2 as uid }" in mut["query"]
            assert {"uid": "uid(u2)", "key": 2, "val": 4} in mut["set"]
            assert calls["commits"] == 2
        finally:
            dg.ALPHA_HTTP_PORT = old_port
    finally:
        srv.stop()


def test_dgraph_client_upsert_conditional():
    """Upserts are single conditional blocks gated on key absence
    (dgraph/upsert.clj)."""
    posted = []

    def fn(method, path, body):
        if path.startswith("/mutate"):
            posted.append(json.loads(body.decode()))
            return 200, {"data": {}}
        if path.startswith("/query"):
            return 200, {"data": {"q": [{"uid": "0x1"}, {"uid": "0x2"}]},
                         "extensions": {"txn": {"start_ts": 1}}}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.dgraph as dg
        c = dg.DgraphClient(node="127.0.0.1")
        old_port = dg.ALPHA_HTTP_PORT
        dg.ALPHA_HTTP_PORT = srv.port
        try:
            out = c.invoke({}, {"type": "invoke", "f": "upsert",
                                "value": [3, 17]})
            assert out["type"] == "ok"
            assert posted[0]["cond"] == "@if(eq(len(u), 0))"
            assert posted[0]["set"] == [{"ukey": 3, "uval": 17}]
            # duplicate detection surface: read-uids returns every record
            out = c.invoke({}, {"type": "invoke", "f": "read-uids",
                                "value": [3, None]})
            assert out["type"] == "ok" and out["value"] == [3, ["0x1", "0x2"]]
        finally:
            dg.ALPHA_HTTP_PORT = old_port
    finally:
        srv.stop()


@pytest.mark.slow
def test_upsert_checker_and_dgraph_fake_runs():
    from jepsen_tpu.workloads.upsert import UpsertChecker
    from conftest import run_fake

    bad = [{"type": "ok", "f": "read-uids", "value": [2, ["0x1", "0x2"]]}]
    out = UpsertChecker().check({}, bad, {})
    assert out["valid?"] is False and out["duplicate-count"] == 1
    assert UpsertChecker().check({}, [], {})["valid?"] is True

    for wl in ("bank", "wr", "long-fork", "upsert"):
        result = run_fake(dgraph.dgraph_test, workload=wl)
        assert result["results"]["valid?"] is True, (wl, result["results"])


def test_crate_lost_updates_rmw_versions():
    """The lost-updates client RMWs under crate's _version guard:
    insert when absent, guarded update when present, definite fail when
    retries exhaust (crate/lost_updates.clj)."""
    state = {"rows": [], "version": 1, "updates": 0, "conflict": False}

    def fn(method, path, body):
        req = json.loads(body.decode())
        stmt = req["stmt"]
        if stmt.startswith("REFRESH"):
            return 200, {"rows": []}
        if stmt.startswith("SELECT elements, _version"):
            if not state["rows"]:
                return 200, {"rows": []}
            return 200, {"rows": [[list(state["rows"]),
                                   state["version"]]]}
        if stmt.startswith("INSERT INTO lu"):
            state["rows"] = list(req["args"][1])
            return 200, {"rowcount": 1}
        if stmt.startswith("UPDATE lu"):
            if state["conflict"]:
                return 200, {"rowcount": 0}  # stale _version
            assert req["args"][2] == state["version"]
            state["rows"] = list(req["args"][0])
            state["version"] += 1
            state["updates"] += 1
            return 200, {"rowcount": 1}
        if stmt.startswith("SELECT elements FROM lu"):
            return 200, {"rows": [[sorted(state["rows"])]]}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.crate as cr
        old_port = cr.PORT
        cr.PORT = srv.port
        try:
            c = cr.CrateClient(node="127.0.0.1")
            t = {"lost-updates": True}
            assert c.invoke(t, {"type": "invoke", "f": "add",
                                "value": [0, 5]})["type"] == "ok"
            assert c.invoke(t, {"type": "invoke", "f": "add",
                                "value": [0, 9]})["type"] == "ok"
            assert state["updates"] == 1  # first add inserted
            out = c.invoke(t, {"type": "invoke", "f": "read",
                               "value": [0, None]})
            assert out["value"] == [0, [5, 9]]
            # persistent version conflicts must FAIL, not silently drop
            state["conflict"] = True
            out = c.invoke(t, {"type": "invoke", "f": "add",
                               "value": [0, 11]})
            assert out["type"] == "fail"
            assert out["error"][0] == "version-conflict"
        finally:
            cr.PORT = old_port
    finally:
        srv.stop()


@pytest.mark.slow
def test_crate_fake_lost_updates_run():
    from conftest import run_fake
    from jepsen_tpu.suites.crate import crate_test

    result = run_fake(crate_test, workload="lost-updates")
    # the time limit can cut the last key's group before its read phase,
    # leaving that key honestly unknown — what the lifecycle must prove
    # is that no key LOST an acked element and most keys fully verified
    wl = result["results"]["workload"]
    per_key = wl["results"]
    assert not any(v.get("valid?") is False for v in per_key.values()), wl
    proven = sum(1 for v in per_key.values() if v.get("valid?") is True)
    assert proven >= 3, wl


def test_version_divergence_checker_and_crate_bodies():
    """A version mapping to two distinct values is divergence
    (crate/version_divergence.clj:97-108); the crate client reads
    val+_version pairs and blind-upserts writes."""
    from jepsen_tpu.workloads.version_divergence import (
        VersionDivergenceChecker)

    ok = [{"type": "ok", "f": "read", "value": [7, 3]},
          {"type": "ok", "f": "read", "value": [7, 3]},
          {"type": "ok", "f": "read", "value": [9, 4]},
          {"type": "ok", "f": "read", "value": [None, None]}]
    out = VersionDivergenceChecker().check({}, ok, {})
    assert out["valid?"] is True and out["read-count"] == 3
    bad = ok + [{"type": "ok", "f": "read", "value": [8, 3]}]
    out = VersionDivergenceChecker().check({}, bad, {})
    assert out["valid?"] is False and out["divergent-count"] == 1
    assert out["multis"][3] == [7, 8]

    def fn(method, path, body):
        req = json.loads(body.decode())
        if req["stmt"].startswith("SELECT val, _version"):
            return 200, {"rows": [[5, 12]]}
        if req["stmt"].startswith("INSERT INTO registers"):
            return 200, {"rowcount": 1}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.crate as cr
        old_port = cr.PORT
        cr.PORT = srv.port
        try:
            c = cr.CrateClient(node="127.0.0.1")
            t = {"version-divergence": True}
            out = c.invoke(t, {"type": "invoke", "f": "read",
                               "value": [2, None]})
            assert out["type"] == "ok" and out["value"] == [2, [5, 12]]
            out = c.invoke(t, {"type": "invoke", "f": "write",
                               "value": [2, 44]})
            assert out["type"] == "ok"
        finally:
            cr.PORT = old_port
    finally:
        srv.stop()


@pytest.mark.slow
def test_crate_fake_version_divergence_run():
    from conftest import run_fake
    from jepsen_tpu.suites.crate import crate_test

    result = run_fake(crate_test, workload="version-divergence")
    assert result["results"]["valid?"] is True, result["results"]


def test_dirty_read_checker_semantics():
    """dirty = point-read ids absent from every strong read; lost =
    acked writes absent; node disagreement is reported but does not
    invalidate (elasticsearch/dirty_read.clj:106-150 semantics with
    benign visibility skew tolerated)."""
    from jepsen_tpu.workloads.dirty_read import DirtyReadChecker

    def h(reads, writes, strongs):
        out = []
        for w in writes:
            out.append({"type": "ok", "f": "write", "value": w})
        for r in reads:
            out.append({"type": "ok", "f": "read", "value": r})
        for s in strongs:
            out.append({"type": "ok", "f": "strong-read", "value": s})
        return out

    ok = DirtyReadChecker().check(
        {}, h([1, 2], [1, 2, 3], [[1, 2, 3], [1, 2, 3]]), {})
    assert ok["valid?"] is True
    dirty = DirtyReadChecker().check(
        {}, h([9], [1], [[1], [1]]), {})
    assert dirty["valid?"] is False and dirty["dirty"] == [9]
    lost = DirtyReadChecker().check(
        {}, h([], [1, 2], [[1], [1]]), {})
    assert lost["valid?"] is False and lost["lost"] == [2]
    # node disagreement is reported but not a validity condition (an
    # indeterminate write landing between strong reads is benign skew)
    split = DirtyReadChecker().check(
        {}, h([], [1, 2], [[1, 2], [1]]), {})
    assert split["valid?"] is True and split["nodes-agree?"] is False
    assert split["not-on-all-count"] == 1
    none = DirtyReadChecker().check({}, h([1], [1], []), {})
    assert none["valid?"] == "unknown"


def test_elasticsearch_dirty_read_client_bodies():
    docs = {}

    def fn(method, path, body):
        if "_doc/" in path and method == "PUT":
            docs[int(path.rsplit("/", 1)[1])] = True
            return 200, {"result": "created"}
        if "_doc/" in path and method == "GET":
            v = int(path.rsplit("/", 1)[1])
            if v in docs:
                return 200, {"found": True, "_source": {"v": v}}
            return 404, {"found": False}
        if path.endswith("_refresh"):
            return 200, {}
        if path.endswith("_search"):
            hits = [{"_source": {"v": v}, "sort": [v]}
                    for v in sorted(docs)]
            return 200, {"hits": {"hits": hits}}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.elasticsearch as es
        c = es.ElasticsearchClient(node="127.0.0.1")
        old = es.PORT
        es.PORT = srv.port
        try:
            t = {"dirty-read": True}
            assert c.invoke(t, {"type": "invoke", "f": "write",
                                "value": 3})["type"] == "ok"
            assert c.invoke(t, {"type": "invoke", "f": "read",
                                "value": 3})["type"] == "ok"
            out = c.invoke(t, {"type": "invoke", "f": "read", "value": 9})
            assert out["type"] == "fail" and out["error"] == ["not-found"]
            assert c.invoke(t, {"type": "invoke", "f": "refresh",
                                "value": None})["type"] == "ok"
            out = c.invoke(t, {"type": "invoke", "f": "strong-read",
                               "value": None})
            assert out["type"] == "ok" and out["value"] == [3]
        finally:
            es.PORT = old
    finally:
        srv.stop()


@pytest.mark.slow
def test_elasticsearch_fake_dirty_read_run():
    from conftest import run_fake
    from jepsen_tpu.suites.elasticsearch import elasticsearch_test

    result = run_fake(elasticsearch_test, workload="dirty-read")
    assert result["results"]["workload"]["valid?"] is True, (
        result["results"])


def test_hazelcast_map_workload_rw_register():
    """The map workload runs the r/w register subset over the REST map
    endpoint (no CAS on that surface)."""
    store = {}

    def fn(method, path, body):
        k = path.rsplit("/", 1)[1]
        if method == "POST":
            store[k] = body.decode()
            return 200, ""
        if method == "GET":
            if k in store:
                return 200, store[k]
            return 204, ""
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.hazelcast as hz
        c = hz.HazelcastClient(node="127.0.0.1")
        old = hz.PORT
        hz.PORT = srv.port
        try:
            out = c.invoke({}, {"type": "invoke", "f": "read",
                                "value": [3, None]})
            assert out["type"] == "ok" and out["value"] == [3, None]
            assert c.invoke({}, {"type": "invoke", "f": "write",
                                 "value": [3, 7]})["type"] == "ok"
            out = c.invoke({}, {"type": "invoke", "f": "read",
                                "value": [3, None]})
            assert out["value"] == [3, 7]
        finally:
            hz.PORT = old
    finally:
        srv.stop()


@pytest.mark.slow
def test_hazelcast_fake_map_run():
    from conftest import run_fake
    from jepsen_tpu.suites.hazelcast import hazelcast_test

    result = run_fake(hazelcast_test, workload="map")
    assert result["results"]["valid?"] is True, result["results"]
    # the r/w subset must never emit cas
    assert not any(op.get("f") == "cas" for op in result["history"])


def test_crate_dirty_read_rw_gen():
    """rw-gen (crate/dirty_read.clj:197-226): writer threads insert
    fresh ids recording each as their node's in-flight write; reader
    threads point-read the id most recently in flight on their OWN
    node; discarded polls never burn a value (pure state threading)."""
    from jepsen_tpu import generator as gen
    from jepsen_tpu.workloads.crate_dirty_read import RWGen

    test = {"nodes": ["n1", "n2", "n3"], "concurrency": 6}
    ctx = gen.context(test)
    g = RWGen(writers=2)

    c0 = ctx.restrict(frozenset({0}))       # thread 0 = writer, node 0
    op, g = g.op(test, c0)
    assert op["f"] == "write" and op["value"] == 0 and op["process"] == 0
    op, g = g.op(test, c0)
    assert op["f"] == "write" and op["value"] == 1

    c3 = ctx.restrict(frozenset({3}))       # thread 3 = reader, node 0
    op, g2 = g.op(test, c3)
    assert op["f"] == "read" and op["value"] == 1

    c4 = ctx.restrict(frozenset({4}))       # thread 4 = reader, node 1
    op, _ = g2.op(test, c4)
    assert op["f"] == "read" and op["value"] == 0

    # a poll whose op gets discarded must not advance the counter
    op_a, _ = g.op(test, c0)
    op_b, _ = g.op(test, c0)
    assert op_a["value"] == op_b["value"] == 2


def test_crate_dirty_read_checker_semantics():
    """Unlike the elasticsearch probe, node disagreement IS a validity
    condition here (crate/dirty_read.clj:178-180); dirty and lost
    elements convict; a strong-read count short of concurrency degrades
    to unknown instead of the reference's assert."""
    from jepsen_tpu.workloads.crate_dirty_read import CrateDirtyReadChecker

    def h(reads, writes, strongs):
        out = []
        for w in writes:
            out.append({"type": "ok", "f": "write", "value": w})
        for r in reads:
            out.append({"type": "ok", "f": "read", "value": r})
        for s in strongs:
            out.append({"type": "ok", "f": "strong-read", "value": s})
        return out

    t = {"concurrency": 2}
    ok = CrateDirtyReadChecker().check(
        t, h([1, 2], [1, 2, 3], [[1, 2, 3], [1, 2, 3]]), {})
    assert ok["valid?"] is True and ok["unchecked-count"] == 1

    dirty = CrateDirtyReadChecker().check(
        t, h([9], [1], [[1], [1]]), {})
    assert dirty["valid?"] is False and dirty["dirty"] == [9]

    lost = CrateDirtyReadChecker().check(
        t, h([], [1, 2], [[1], [1]]), {})
    assert lost["valid?"] is False and lost["lost"] == [2]

    # node disagreement alone convicts (the crate probe's distinction)
    split = CrateDirtyReadChecker().check(
        t, h([], [1, 2], [[1, 2], [1]]), {})
    assert split["valid?"] is False and split["nodes-agree?"] is False
    assert split["some-lost-count"] == 1

    short = CrateDirtyReadChecker().check(
        {"concurrency": 5}, h([], [1], [[1], [1]]), {})
    assert short["valid?"] == "unknown"

    none = CrateDirtyReadChecker().check(t, h([1], [1], []), {})
    assert none["valid?"] == "unknown"


def test_crate_dirty_read_client_bodies():
    """SQL bodies (insert / point read / refresh / LIMIT scan) and the
    --es-ops routing through the embedded ES API
    (crate/dirty_read.clj:54-141)."""
    rows = set()

    def fn(method, path, body):
        if path.endswith("/_sql"):
            req = json.loads(body)
            stmt, args = req["stmt"], req.get("args") or []
            if stmt.startswith("INSERT INTO dirty_read"):
                rows.add(int(args[0]))
                return 200, {"rowcount": 1}
            if "WHERE id" in stmt:
                hit = int(args[0]) in rows
                return 200, {"rows": [[int(args[0])]] if hit else []}
            if stmt.startswith("REFRESH"):
                return 200, {"rowcount": 0}
            if stmt.startswith("SELECT id FROM dirty_read"):
                return 200, {"rows": [[i] for i in sorted(rows)]}
            return 200, {"rows": [], "rowcount": 0}
        if "/dirty_read/default/" in path:
            v = int(path.rsplit("/", 1)[1])
            if method == "PUT":
                rows.add(v)
                return 200, {"result": "created"}
            if v in rows:
                return 200, {"found": True, "_source": {"id": v}}
            return 404, {"found": False}
        if path.endswith("/_search"):
            hits = [{"_source": {"id": v}} for v in sorted(rows)]
            return 200, {"hits": {"hits": hits}}
        return 404, {}

    srv = ScriptedHTTP(fn)
    try:
        import jepsen_tpu.suites.crate as cr
        old = cr.PORT
        cr.PORT = srv.port
        try:
            t = {"dirty-read": True}
            c = cr.CrateClient(node="127.0.0.1")
            assert c.invoke(t, {"type": "invoke", "f": "write",
                                "value": 3})["type"] == "ok"
            assert c.invoke(t, {"type": "invoke", "f": "read",
                                "value": 3})["type"] == "ok"
            assert c.invoke(t, {"type": "invoke", "f": "read",
                                "value": 9})["type"] == "fail"
            assert c.invoke(t, {"type": "invoke", "f": "refresh",
                                "value": None})["type"] == "ok"
            out = c.invoke(t, {"type": "invoke", "f": "strong-read",
                               "value": None})
            assert out["type"] == "ok" and out["value"] == [3]

            es = cr.CrateClient(node="127.0.0.1",
                                es_ops={"read", "write", "strong-read"})
            assert es.invoke(t, {"type": "invoke", "f": "write",
                                 "value": 7})["type"] == "ok"
            assert es.invoke(t, {"type": "invoke", "f": "read",
                                 "value": 7})["type"] == "ok"
            assert es.invoke(t, {"type": "invoke", "f": "read",
                                 "value": 99})["type"] == "fail"
            out = es.invoke(t, {"type": "invoke", "f": "strong-read",
                                "value": None})
            assert out["type"] == "ok" and out["value"] == [3, 7]
            # refresh rides SQL even under es-ops routing
            assert es.invoke(t, {"type": "invoke", "f": "refresh",
                                 "value": None})["type"] == "ok"
        finally:
            cr.PORT = old
    finally:
        srv.stop()


@pytest.mark.slow
def test_crate_fake_dirty_read_run():
    from conftest import run_fake
    from jepsen_tpu.suites.crate import crate_test

    result = run_fake(crate_test, workload="dirty-read",
                      dirty_read_quiesce=0.2)
    assert result["results"]["workload"]["valid?"] is True, (
        result["results"])
    # the final phase is deterministic; write/read emission is pinned
    # by test_crate_dirty_read_rw_gen (the 1 s main phase schedules so
    # few ops that demanding a writer-thread pick would flake)
    fs = {op.get("f") for op in result["history"]}
    assert {"refresh", "strong-read"} <= fs
