"""Chaos-test worker for the causal trace (tests/test_trace.py).

A fake-mode ``--trace`` run whose client HANGS after a prefix of fast
ops: the interpreter's stall watchdog (armed down to 1 s here) fires
and dumps the flight recorder, and the streaming trace.json keeps
accumulating — then the parent SIGKILLs the process mid-run and
asserts both artifacts survived as loadable prefixes. Usage:

    python trace_worker.py <store-dir>
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jepsen_tpu import core  # noqa: E402
from jepsen_tpu import generator as gen
from jepsen_tpu.fakes import AtomClient, AtomDB, noop_test


class HangingAtomClient(AtomClient):
    """Fast for the first ops, then blocks forever — the wedge the
    stall watchdog (and its flight-recorder dump) exists for."""

    invocations = 0
    _count_lock = threading.Lock()

    def invoke(self, test, op):
        with HangingAtomClient._count_lock:
            HangingAtomClient.invocations += 1
            n = HangingAtomClient.invocations
        if n > 20:
            time.sleep(3600)
        return super().invoke(test, op)


def main() -> int:
    store_dir = sys.argv[1]
    db = AtomDB()
    t = noop_test(
        db=db, client=HangingAtomClient(db),
        generator=gen.clients(gen.limit(
            50_000, gen.cycle(gen.Seq([
                {"type": "invoke", "f": "write", "value": 1},
                {"type": "invoke", "f": "read", "value": None},
            ])))),
        store_dir=store_dir, time_limit=600.0,
        trace=True,
        # a 1 s stall threshold so the hung client trips the watchdog
        # (and its flight dump) quickly; generous op deadlines so the
        # reaper never beats the watchdog to the wedge
        stall_s=1.0, op_timeout_s=300.0,
        wal_fsync_interval=0, metrics_interval=0)
    core.run(t)
    return 0


if __name__ == "__main__":
    sys.exit(main())
