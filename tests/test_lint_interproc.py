"""Interprocedural lint tier: thread-spawn edges, lock-order, cond-wait,
durability-protocol, telemetry-name, and the AST-cache rewrite fix.

PR-5 style: every new rule/diagnostic gets a deliberately broken
fixture (true positive) AND its corrected twin (must stay silent).
The thread-edge tests additionally run the same fixture against a
spawn-edge-stripped graph — the PR-5 "thread targets are not edges"
semantics — proving each finding is *previously invisible*: it is
reachable only through a thread-spawn edge.
"""
from __future__ import annotations

import os
import textwrap
from pathlib import Path

import pytest

from jepsen_tpu.analysis import lint as lint_mod
from jepsen_tpu.analysis.lint import astcache, callgraph
from jepsen_tpu.analysis.lint import rules_concurrency as rc

pytestmark = pytest.mark.lint


def _lint_source(tmp_path, source, rules=None, name="fx.py"):
    d = tmp_path / "fixture_pkg"
    d.mkdir(exist_ok=True)
    (d / name).write_text(textwrap.dedent(source), encoding="utf-8")
    rep = lint_mod.lint_paths([str(d)], baseline=False, rules=rules)
    return rep.findings


def _graphs(tmp_path, source, name="fx.py"):
    """(new graph, spawn-edge-stripped old-semantics graph)."""
    d = tmp_path / "fixture_pkg"
    d.mkdir(exist_ok=True)
    f = d / name
    f.write_text(textwrap.dedent(source), encoding="utf-8")
    mod = astcache.parse_module(f, root=str(tmp_path))
    g = callgraph.build([mod], root=str(tmp_path))
    stripped = callgraph.CallGraph(
        edges={n: [(c, ln, k) for c, ln, k in es if k == callgraph.CALL]
               for n, es in g.edges.items()},
        functions=g.functions, modules=g.modules, spawn_targets={},
        root=g.root)
    return g, stripped


# ---------------------------------------------------------------------------
# Thread-spawn edges: the PR-5 known limit, closed
# ---------------------------------------------------------------------------

THREAD_ESCAPE = """
    import threading

    def mutate_schedule():  # owner: scheduler
        pass

    def step():
        mutate_schedule()

    def worker_loop():
        step()

    def launch():  # owner: scheduler
        t = threading.Thread(target=worker_loop, daemon=True)
        t.start()
"""


class TestThreadEdges:
    def test_thread_target_owner_escape_fires(self, tmp_path):
        """The PR-4 incident shape: an UNANNOTATED Thread target reaches
        a scheduler-only mutator. Only the spawn edge's owner transition
        makes worker_loop a worker root at all."""
        finds = _lint_source(tmp_path, THREAD_ESCAPE,
                             rules=["thread-owner"])
        assert [f.rule for f in finds] == ["thread-owner"]
        assert "worker_loop" in finds[0].message

    def test_previously_invisible_without_spawn_edges(self, tmp_path):
        """The same fixture against the old single-thread graph
        (spawn edges stripped, no spawn targets): silent. This is the
        documented PR-5 blind spot the rework closes."""
        g, stripped = _graphs(tmp_path, THREAD_ESCAPE)
        assert callgraph.SPAWN in {k for _n, es in g.edges.items()
                                   for _c, _ln, k in es}
        assert rc.thread_owner(g) != []
        assert rc.thread_owner(stripped) == []

    def test_corrected_twin_silent(self, tmp_path):
        good = THREAD_ESCAPE.replace("# owner: scheduler\n        pass",
                                     "# owner: any\n        pass", 1)
        assert _lint_source(tmp_path, good, rules=["thread-owner"]) == []

    def test_timer_and_submit_targets_resolve(self, tmp_path):
        src = """
            import threading

            def tick():
                touch()

            def touch():  # owner: scheduler
                pass

            def arm():  # owner: scheduler
                threading.Timer(5.0, tick).start()

            def offload(pool):  # owner: scheduler
                pool.submit(tick)
        """
        finds = _lint_source(tmp_path, src, rules=["thread-owner"])
        assert len(finds) == 1 and finds[0].rule == "thread-owner"
        assert "tick" in finds[0].message

    def test_sync_spawn_helper_blocks_scheduler(self, tmp_path):
        """A # thread-helper: sync-spawn(arg=0) helper (utils.real_pmap's
        shape): the caller WAITS, so an unbounded block in the spawned
        fn is the scheduler's block — visible only through the edge."""
        src = """
            import threading

            def pmap(fn, coll):  # thread-helper: sync-spawn(arg=0)
                ts = [threading.Thread(target=fn, args=(x,))
                      for x in coll]
                for t in ts:
                    t.start()

            def drain(q):
                q.put_nowait(None)
                return q.get()

            def teardown(queues):  # owner: scheduler
                pmap(drain, queues)
        """
        finds = _lint_source(tmp_path, src, rules=["no-unbounded-block"])
        assert [f.rule for f in finds] == ["no-unbounded-block"]
        assert "teardown" in finds[0].message
        good = src.replace("q.get()", "q.get(timeout=5.0)")
        assert _lint_source(tmp_path, good,
                            rules=["no-unbounded-block"]) == []

    def test_detached_spawn_not_a_scheduler_block(self, tmp_path):
        """A worker parked on its own queue (the interpreter's in_q
        pattern) must NOT flag: detached spawn edges are not traversed
        by no-unbounded-block."""
        src = """
            import threading

            def loop(q):
                q.put_nowait(None)
                while True:
                    q.get()

            def launch(q):  # owner: scheduler
                threading.Thread(target=loop, args=(q,), daemon=True).start()
        """
        assert _lint_source(tmp_path, src,
                            rules=["no-unbounded-block"]) == []

    def test_lock_guard_sees_through_spawn_reference(self, tmp_path):
        """A helper provably called only under the lock used to inherit
        the guard — but a Thread(target=self._wipe) reference runs it
        on a fresh thread with NO lock. The thread-edge closure defeats
        the exemption."""
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []

                def add(self, x):
                    with self._lock:
                        self.items.append(x)

                def reset(self):
                    with self._lock:
                        self._wipe()

                def _wipe(self):
                    self.items.clear()

                def reset_bg(self):
                    threading.Thread(target=self._wipe).start()
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-guard"])
        assert [f.rule for f in finds] == ["lock-guard"]
        assert "_wipe" in finds[0].qualname
        # corrected: spawn a locked wrapper instead of the bare helper
        good = bad.replace("threading.Thread(target=self._wipe).start()",
                           "threading.Thread(target=self.reset).start()")
        assert _lint_source(tmp_path, good, rules=["lock-guard"]) == []

    def test_differential_single_thread_graph_identical(self, tmp_path):
        """On a module with NO thread idioms, the enlarged graph must be
        finding-identical to the old call-only graph for every
        pre-existing global rule."""
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

            def mutate():  # owner: scheduler
                pass

            def step():
                mutate()

            def worker_loop():  # owner: worker
                step()

            def pump(q):  # owner: scheduler
                q.put_nowait(1)
                return q.get()
        """
        g, stripped = _graphs(tmp_path, src)
        assert g.spawn_targets == {}
        for rule in (rc.thread_owner, rc.no_unbounded_block):
            new = [f.render() for f in rule(g)]
            old = [f.render() for f in rule(stripped)]
            assert new == old and new  # identical AND non-empty


    def test_via_sync_upgrade_not_order_dependent(self, tmp_path):
        """Review pin: a node reached FIRST by a plain-call path (which
        stops at worker-annotated leaves) and also via sync-spawn must
        still be scanned — first-visit-wins dropped the finding
        depending on statement order."""
        src = """
            import threading

            def pmap(fn, coll):  # thread-helper: sync-spawn(arg=0)
                ts = [threading.Thread(target=fn, args=(x,))
                      for x in coll]
                for t in ts:
                    t.start()

            def drain(q):  # owner: worker
                q.put_nowait(None)
                return q.get()

            def teardown(queues):  # owner: scheduler
                drain(queues[0])
                pmap(drain, queues)
        """
        finds = _lint_source(tmp_path, src, rules=["no-unbounded-block"])
        assert [f.rule for f in finds] == ["no-unbounded-block"]
        # and with the statements swapped (sync-spawn seen first)
        swapped = src.replace(
            "drain(queues[0])\n                pmap(drain, queues)",
            "pmap(drain, queues)\n                drain(queues[0])")
        finds2 = _lint_source(tmp_path, swapped,
                              rules=["no-unbounded-block"])
        assert [f.rule for f in finds2] == ["no-unbounded-block"]


# ---------------------------------------------------------------------------
# lock-order (JTL005)
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_ab_ba_cycle(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert [f.rule for f in finds] == ["lock-order"]
        assert "cycle" in finds[0].message
        good = bad.replace(
            "with self._b:\n                        with self._a:",
            "with self._a:\n                        with self._b:")
        assert _lint_source(tmp_path, good, rules=["lock-order"]) == []

    def test_interprocedural_self_deadlock(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def _bump(self):
                    with self._lock:
                        self.n += 1

                def bump_twice(self):
                    with self._lock:
                        self._bump()
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert [f.rule for f in finds] == ["lock-order"]
        assert "re-acquire" in finds[0].message
        assert finds[0].qualname == "Box.bump_twice"
        good = bad.replace("threading.Lock()", "threading.RLock()")
        assert _lint_source(tmp_path, good, rules=["lock-order"]) == []

    def test_cross_function_cycle_through_calls(self, tmp_path):
        """The interprocedural case: each function nests only via a
        call, so only the transitive acquisition analysis sees it."""
        bad = """
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def take_b():
                with _b:
                    pass

            def take_a():
                with _a:
                    pass

            def ab():
                with _a:
                    take_b()

            def ba():
                with _b:
                    take_a()
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert len(finds) == 1 and "cycle" in finds[0].message

    def test_blocking_annotation_under_lock(self, tmp_path):
        bad = """
            import threading

            def fetch():  # blocking: rpc
                pass

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        fetch()
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert [f.rule for f in finds] == ["lock-order"]
        assert "blocking" in finds[0].message
        good = bad.replace(
            "with self._lock:\n                        fetch()",
            "fetch()\n                    with self._lock:\n"
            "                        pass")
        assert _lint_source(tmp_path, good, rules=["lock-order"]) == []

    def test_unbounded_primitive_under_lock(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def pump(self, q):
                    q.put_nowait(1)
                    with self._lock:
                        return q.get()
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert [f.rule for f in finds] == ["lock-order"]
        assert "while holding" in finds[0].message
        good = bad.replace("q.get()", "q.get(timeout=1.0)")
        assert _lint_source(tmp_path, good, rules=["lock-order"]) == []

    def test_multi_item_with_orders_its_own_items(self, tmp_path):
        """Review pin: `with self._a, self._b:` is sugar for nested
        withs and must contribute the same a->b edge — the combined
        form was a blind spot."""
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a, self._b:
                        pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """
        finds = _lint_source(tmp_path, bad, rules=["lock-order"])
        assert len(finds) == 1 and "cycle" in finds[0].message
        # and `with a, a:` on a plain Lock is a direct self-deadlock
        dup = """
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()

                def oops(self):
                    with self._a, self._a:
                        pass
        """
        finds = _lint_source(tmp_path, dup, rules=["lock-order"])
        assert len(finds) == 1 and "self-deadlock" in finds[0].message

    def test_cycle_respects_inline_waiver(self, tmp_path):
        """Review pin: `# lint: ignore[lock-order]` on an acquisition
        site must suppress the cycles that edge participates in, like
        every other diagnostic of the rule."""
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:  # lint: ignore[lock-order]
                            pass
        """
        assert _lint_source(tmp_path, src, rules=["lock-order"]) == []

    def test_condition_wait_releases_its_lock(self, tmp_path):
        """The reconnect.py _RWLock shape: cv.wait() under `with cv`
        RELEASES the lock — textbook, must stay silent (regression pin
        for the false positive the first lock-order draft produced)."""
        src = """
            import threading

            class RW:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._writer = False

                def acquire_read(self):
                    with self._cond:
                        while self._writer:
                            self._cond.wait()
        """
        assert _lint_source(tmp_path, src, rules=["lock-order"]) == []


# ---------------------------------------------------------------------------
# cond-wait (JTL006)
# ---------------------------------------------------------------------------

class TestCondWait:
    def test_naked_wait_not_in_while(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def block(self):
                    with self._cond:
                        if not self.ready:
                            self._cond.wait(1.0)
        """
        finds = _lint_source(tmp_path, bad, rules=["cond-wait"])
        assert [f.rule for f in finds] == ["cond-wait"]
        assert "while" in finds[0].message
        good = bad.replace("if not self.ready:", "while not self.ready:")
        assert _lint_source(tmp_path, good, rules=["cond-wait"]) == []

    def test_wait_outside_lock(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def block(self):
                    while not self.ready:
                        self._cond.wait(1.0)
        """
        finds = _lint_source(tmp_path, bad, rules=["cond-wait"])
        assert [f.rule for f in finds] == ["cond-wait"]
        assert "outside" in finds[0].message

    def test_notify_outside_lock(self, tmp_path):
        bad = """
            import threading

            class Box:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def wake(self):
                    self.ready = True
                    self._cond.notify_all()
        """
        finds = _lint_source(tmp_path, bad, rules=["cond-wait"])
        assert [f.rule for f in finds] == ["cond-wait"]
        good = bad.replace(
            "self.ready = True\n                    "
            "self._cond.notify_all()",
            "with self._cond:\n                        "
            "self.ready = True\n                        "
            "self._cond.notify_all()")
        assert _lint_source(tmp_path, good, rules=["cond-wait"]) == []

    def test_timeoutless_wait_escalates_on_scheduler_path(self, tmp_path):
        sched = """
            import threading

            class Box:
                def __init__(self):
                    self._cond = threading.Condition()
                    self.ready = False

                def block(self):  # owner: scheduler
                    with self._cond:
                        while not self.ready:
                            self._cond.wait()
        """
        finds = _lint_source(tmp_path, sched, rules=["cond-wait"])
        assert [f.rule for f in finds] == ["cond-wait"]
        assert "scheduler" in finds[0].message
        # same discipline off the scheduler path: no escalation
        off = sched.replace("  # owner: scheduler", "")
        assert _lint_source(tmp_path, off, rules=["cond-wait"]) == []
        # bounded wait on the scheduler path: fine
        bounded = sched.replace("self._cond.wait()",
                                "self._cond.wait(1.0)")
        assert _lint_source(tmp_path, bounded, rules=["cond-wait"]) == []

    def test_condition_with_explicit_lock_identity(self, tmp_path):
        """Condition(self._lock): waiting under `with self._lock` IS
        under the condition's lock."""
        src = """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)
                    self.ready = False

                def block(self):
                    with self._lock:
                        while not self.ready:
                            self._cond.wait(0.5)
        """
        assert _lint_source(tmp_path, src, rules=["cond-wait"]) == []


# ---------------------------------------------------------------------------
# durability-protocol (JTD001)
# ---------------------------------------------------------------------------

class TestDurabilityProtocol:
    def test_missing_fsync_before_rename(self, tmp_path):
        bad = """
            import os

            def publish(path, tmp, doc):
                with open(tmp, "w") as f:
                    f.write(doc)
                    f.flush()
                os.replace(tmp, path)
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert [f.rule for f in finds] == ["durability-protocol"]
        assert "fsync" in finds[0].message
        good = bad.replace(
            "f.flush()",
            "f.flush()\n                    os.fsync(f.fileno())")
        assert _lint_source(tmp_path, good,
                            rules=["durability-protocol"]) == []

    def test_fsync_of_earlier_publish_does_not_vouch(self, tmp_path):
        """Review pin: a function that atomically publishes file A and
        then renames an unfsynced file B must still flag B — any-fsync-
        before-any-rename let A's fsync vouch for B."""
        bad = """
            import os

            def publish_two(a_tmp, a, b_tmp, b, doc):
                with open(a_tmp, "w") as f:
                    f.write(doc)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(a_tmp, a)
                with open(b_tmp, "w") as g:
                    g.write(doc)
                    g.flush()
                os.replace(b_tmp, b)
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert len(finds) == 1 and finds[0].line > 10
        good = bad.replace(
            "g.flush()",
            "g.flush()\n                    os.fsync(g.fileno())")
        assert _lint_source(tmp_path, good,
                            rules=["durability-protocol"]) == []

    def test_rename_elsewhere_does_not_exempt_overwrite(self, tmp_path):
        """Review pin: an atomic publish of one artifact must not exempt
        a direct in-place overwrite of a SECOND durable artifact in the
        same method (the per-method has_rename shortcut did)."""
        bad = """
            import os

            class Reg:  # durability: fsync
                def __init__(self, path, ckpt):
                    self.path = path
                    self.ckpt = ckpt

                def publish(self, tmp, doc):
                    with open(tmp, "w") as f:
                        f.write(doc)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, self.path)
                    with open(self.ckpt, "w") as g:
                        g.write(doc)
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert len(finds) == 1 and "overwrites" in finds[0].message
        # open(self.<tmp attr>) FOLLOWED by a rename stays exempt
        good = """
            import os

            class Reg:  # durability: fsync
                def __init__(self, path, tmp):
                    self.path = path
                    self.tmp = tmp

                def publish(self, doc):
                    with open(self.tmp, "w") as f:
                        f.write(doc)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(self.tmp, self.path)
        """
        assert _lint_source(tmp_path, good,
                            rules=["durability-protocol"]) == []

    def test_pure_rename_not_flagged(self, tmp_path):
        src = """
            import os

            def rotate(a, b):
                os.replace(a, b)
        """
        assert _lint_source(tmp_path, src,
                            rules=["durability-protocol"]) == []

    def test_durable_overwrite_in_annotated_class(self, tmp_path):
        bad = """
            class Registry:  # durability: fsync
                def __init__(self, path):
                    self.path = path

                def rewrite(self, doc):
                    with open(self.path, "w") as f:
                        f.write(doc)
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert [f.rule for f in finds] == ["durability-protocol"]
        assert "overwrites" in finds[0].message
        # corrected twin: append-only (the WAL protocol)
        good = bad.replace('open(self.path, "w")', 'open(self.path, "a")')
        assert _lint_source(tmp_path, good,
                            rules=["durability-protocol"]) == []

    def test_init_fresh_file_exempt(self, tmp_path):
        src = """
            class Wal:  # durability: fsync
                def __init__(self, path):
                    self.path = path
                    self._f = open(self.path, "w")
        """
        assert _lint_source(tmp_path, src,
                            rules=["durability-protocol"]) == []

    def test_record_after_act(self, tmp_path):
        bad = """
            class Nem:
                # durability: record-before-act
                def invoke(self, registry, nemesis, op):
                    res = nemesis.invoke(op)
                    registry.record("net", op)
                    return res
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert [f.rule for f in finds] == ["durability-protocol"]
        assert "record" in finds[0].message
        good = bad.replace(
            'res = nemesis.invoke(op)\n                    '
            'registry.record("net", op)',
            'registry.record("net", op)\n                    '
            'res = nemesis.invoke(op)')
        assert _lint_source(tmp_path, good,
                            rules=["durability-protocol"]) == []

    def test_late_re_record_allowed(self, tmp_path):
        """NemesisWorker.invoke's shape: a record precedes the act, and
        a deliberate LATE re-record follows it — allowed (there exists
        an earlier record)."""
        src = """
            class Nem:
                # durability: record-before-act
                def invoke(self, registry, nemesis, op, reaped):
                    registry.record("net", op)
                    res = nemesis.invoke(op)
                    if reaped:
                        registry.record("net", op)
                    return res
        """
        assert _lint_source(tmp_path, src,
                            rules=["durability-protocol"]) == []

    def test_act_without_any_record(self, tmp_path):
        bad = """
            class Nem:
                # durability: record-before-act
                def invoke(self, nemesis, op):
                    return nemesis.invoke(op)
        """
        finds = _lint_source(tmp_path, bad, rules=["durability-protocol"])
        assert len(finds) == 1 and "no durable record" in finds[0].message


# ---------------------------------------------------------------------------
# telemetry-name (JTM001)
# ---------------------------------------------------------------------------

class TestTelemetryName:
    def test_suffix_and_case_conventions(self, tmp_path):
        bad = """
            def setup(reg):
                reg.counter("opsDone")
                reg.counter("ops_count")
                reg.histogram("op_latency")
        """
        finds = _lint_source(tmp_path, bad, rules=["telemetry-name"])
        msgs = "\n".join(f.message for f in finds)
        assert len(finds) == 3
        assert "snake_case" in msgs and "_total" in msgs \
            and "unit suffix" in msgs
        good = """
            def setup(reg):
                reg.counter("ops_done_total")
                reg.counter("ops_total")
                reg.histogram("op_latency_seconds")
                reg.gauge("queue_depth")
        """
        assert _lint_source(tmp_path, good, rules=["telemetry-name"]) == []

    def test_kind_conflict(self, tmp_path):
        bad = """
            def a(reg):
                reg.counter("x_total")

            def b(reg):
                reg.gauge("x_total")
        """
        finds = _lint_source(tmp_path, bad, rules=["telemetry-name"])
        assert len(finds) == 1 and "counter and gauge" in finds[0].message

    def test_label_conflict(self, tmp_path):
        bad = """
            def a(reg):
                reg.counter("y_total", "h", labels=("f",))

            def b(reg):
                reg.counter("y_total", "h", labels=("g",))
        """
        finds = _lint_source(tmp_path, bad, rules=["telemetry-name"])
        assert len(finds) == 1 and "label sets" in finds[0].message

    def test_trace_name_conventions(self, tmp_path):
        """Trace track/span literals must be kebab-case — the causal
        trace's query-key hygiene (doc/observability.md)."""
        bad = """
            def emit(tracer):
                tracer.instant("Bad_Track", "op-timeout")
                tracer.complete("checkpoint", "Ckpt_Write", 0, 1)
                with tracer.span("checker-ladder", "Rung Attempt"):
                    pass
        """
        finds = _lint_source(tmp_path, bad, rules=["telemetry-name"])
        assert len(finds) == 3
        msgs = "\n".join(f.message for f in finds)
        assert "Bad_Track" in msgs and "Ckpt_Write" in msgs \
            and "Rung Attempt" in msgs
        assert all("kebab-case" in f.message for f in finds)
        good = """
            def emit(tracer, track):
                tracer.instant("scheduler", "op-timeout")
                tracer.complete("checkpoint", "ckpt-write", 0, 1)
                tracer.window_begin("nemesis", "net", wid="fault-0")
                # dynamic names (worker tracks) are not literals: skipped
                tracer.instant(f"worker-{track}", "late-completion")
                tracer.instant(track, "stall")
        """
        assert _lint_source(tmp_path, good, rules=["telemetry-name"]) == []

    def test_trace_name_waivable(self, tmp_path):
        waived = """
            def emit(tracer):
                tracer.instant("Legacy_Track", "x")  # lint: ignore[telemetry-name]
        """
        assert _lint_source(tmp_path, waived,
                            rules=["telemetry-name"]) == []

    def test_doc_drift(self, tmp_path):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "m.py").write_text(textwrap.dedent("""
            def setup(reg):
                reg.counter("real_total", labels=("f",))
        """), encoding="utf-8")
        doc = tmp_path / "doc"
        doc.mkdir()
        (doc / "observability.md").write_text(
            "counts `real_total{f}` and the renamed-away "
            "`gone_total` plus knob `live_poll_s`.\n",
            encoding="utf-8")
        rep = lint_mod.lint_paths([str(d)], baseline=False,
                                  root=str(tmp_path),
                                  rules=["telemetry-name"])
        assert [f.qualname for f in rep.findings] == ["<doc>"]
        assert "gone_total" in rep.findings[0].message


# ---------------------------------------------------------------------------
# astcache: same-mtime same-size rewrite invalidation
# ---------------------------------------------------------------------------

class TestAstCacheRewrite:
    def test_same_tick_same_size_rewrite_invalidates(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def aa(): pass\n", encoding="utf-8")
        m1 = astcache.parse_module(p)
        assert "aa" in m1.functions
        st = p.stat()
        p.write_text("def bb(): pass\n", encoding="utf-8")  # same size
        # force the SAME mtime: a coarse-timestamp filesystem (or a
        # fast test harness) rewriting inside one tick
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
        st2 = p.stat()
        assert (st2.st_mtime_ns, st2.st_size) \
            == (st.st_mtime_ns, st.st_size)
        m2 = astcache.parse_module(p)
        assert "bb" in m2.functions and "aa" not in m2.functions

    def test_unchanged_file_hits_cache(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def aa(): pass\n", encoding="utf-8")
        m1 = astcache.parse_module(p)
        assert astcache.parse_module(p) is m1


# ---------------------------------------------------------------------------
# Regression pins for the true positives the new analysis surfaced
# ---------------------------------------------------------------------------

class TestDurabilityFixes:
    """durability-protocol flagged two real write+rename publishers with
    no fsync — a power cut could publish a torn/empty artifact under a
    durable name (live-status.json is REUSED by analyze; a corrupt
    fs_cache entry feeds every later run). Pinned here; the lint gate
    keeps them fixed."""

    def _trace(self, monkeypatch):
        events = []
        real_fsync, real_replace = os.fsync, os.replace
        monkeypatch.setattr(
            os, "fsync",
            lambda fd: (events.append("fsync"), real_fsync(fd))[1])
        monkeypatch.setattr(
            os, "replace",
            lambda a, b: (events.append("replace"),
                          real_replace(a, b))[1])
        return events

    def test_telemetry_atomic_write_fsyncs_before_rename(
            self, tmp_path, monkeypatch):
        from jepsen_tpu import telemetry
        events = self._trace(monkeypatch)
        telemetry._atomic_write(tmp_path / "metrics.json", "{}\n")
        assert "fsync" in events
        assert events.index("fsync") < events.index("replace")
        assert (tmp_path / "metrics.json").read_text() == "{}\n"

    def test_fs_cache_atomic_write_fsyncs_before_rename(
            self, tmp_path, monkeypatch):
        from jepsen_tpu import fs_cache
        monkeypatch.setattr(fs_cache, "cache_root",
                            lambda: tmp_path / "cache", raising=False)
        events = self._trace(monkeypatch)
        fs_cache._atomic_write(tmp_path / "entry",
                               lambda f: f.write(b"payload"))
        assert "fsync" in events
        assert events.index("fsync") < events.index("replace")
        assert (tmp_path / "entry").read_bytes() == b"payload"
