"""Workload-kit tests: literal-history checker cases (reference tier-1
style, SURVEY.md §4) plus simulated-generator smoke runs."""
import pytest

from jepsen_tpu.generator.simulate import perfect, quick, invocations
from jepsen_tpu.workloads import (adya, append, bank, causal, causal_reverse,
                                  long_fork, register, set_workload, wr)


def op(typ, process, f, value=None):
    return {"type": typ, "process": process, "f": f, "value": value}


# ---------------------------------------------------------------------------
# bank
# ---------------------------------------------------------------------------

def bank_test():
    w = bank.workload()
    return {**w, "accounts": [0, 1], "total-amount": 20}


def test_bank_valid():
    t = bank_test()
    h = [
        op("invoke", 0, "read"), op("ok", 0, "read", {0: 10, 1: 10}),
        op("invoke", 1, "transfer", {"from": 0, "to": 1, "amount": 5}),
        op("ok", 1, "transfer", {"from": 0, "to": 1, "amount": 5}),
        op("invoke", 0, "read"), op("ok", 0, "read", {0: 5, 1: 15}),
    ]
    assert bank.checker().check(t, h, {})["valid?"] is True


def test_bank_wrong_total():
    t = bank_test()
    h = [op("invoke", 0, "read"), op("ok", 0, "read", {0: 10, 1: 11})]
    r = bank.checker().check(t, h, {})
    assert r["valid?"] is False
    assert r["first-error"]["errors"][0]["error"] == "wrong-total"


def test_bank_negative_balance():
    t = bank_test()
    h = [op("invoke", 0, "read"), op("ok", 0, "read", {0: -5, 1: 25})]
    assert bank.checker().check(t, h, {})["valid?"] is False
    assert bank.checker(negative_balances=True).check(t, h, {})["valid?"] is True


def test_bank_generator_shapes():
    t = bank_test()
    h = quick(t, __import__("jepsen_tpu.generator", fromlist=["g"]).limit(
        50, bank.generator()))
    assert len(invocations(h)) == 50
    for iv in invocations(h):
        assert iv["f"] in ("read", "transfer")
        if iv["f"] == "transfer":
            v = iv["value"]
            assert v["from"] in t["accounts"] and v["to"] in t["accounts"]
            assert v["from"] != v["to"] and v["amount"] >= 1


# ---------------------------------------------------------------------------
# long fork
# ---------------------------------------------------------------------------

def test_long_fork_detects_fork():
    # keys 0,1 in group 0 (group_size 2); two incomparable reads
    c = long_fork.checker(group_size=2)
    h = [
        op("ok", 0, "txn", [["w", 0, 1]]),
        op("ok", 1, "txn", [["w", 1, 1]]),
        op("ok", 2, "txn", [["r", 0, 1], ["r", 1, None]]),
        op("ok", 3, "txn", [["r", 0, None], ["r", 1, 1]]),
    ]
    r = c.check({}, h, {})
    assert r["valid?"] is False and r["fork-count"] == 1


def test_long_fork_comparable_ok():
    c = long_fork.checker(group_size=2)
    h = [
        op("ok", 0, "txn", [["w", 0, 1]]),
        op("ok", 2, "txn", [["r", 0, 1], ["r", 1, None]]),
        op("ok", 3, "txn", [["r", 0, 1], ["r", 1, 1]]),
        op("ok", 1, "txn", [["w", 1, 1]]),
    ]
    assert c.check({}, h, {})["valid?"] is True


def test_long_fork_generator_simulates():
    h = quick({"concurrency": 4},
              __import__("jepsen_tpu.generator", fromlist=["g"]).limit(
                  60, long_fork.generator(group_size=2)))
    ivs = invocations(h)
    assert len(ivs) == 60
    writes = [m for iv in ivs for m in iv["value"] if m[0] == "w"]
    # each key written at most once
    keys = [m[1] for m in writes]
    assert len(keys) == len(set(keys))


# ---------------------------------------------------------------------------
# causal / causal-reverse
# ---------------------------------------------------------------------------

def test_causal_model():
    m = causal.CausalRegister()
    m2 = m.step({"f": "write", "value": 1})
    assert m2.value == 1
    assert m2.step({"f": "write", "value": 3}).is_inconsistent()
    assert m2.step({"f": "read", "value": 1}) is m2


def test_causal_workload_checks():
    w = causal.workload(n_writes=3)
    h = [
        op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
        op("invoke", 1, "read"), op("ok", 1, "read", 1),
        op("invoke", 0, "write", 2), op("ok", 0, "write", 2),
    ]
    assert w["checker"].check({}, h, {"accelerator": "cpu"})["valid?"] is True


def test_causal_reverse_detects_reorder():
    c = causal_reverse.checker()
    h = [
        op("invoke", 0, "write", 1), op("ok", 0, "write", 1),
        op("invoke", 0, "write", 2), op("ok", 0, "write", 2),
        # read sees 2 but not 1, though write 1 completed before write 2 began
        op("invoke", 1, "read"), op("ok", 1, "read", [2]),
    ]
    r = c.check({}, h, {})
    assert r["valid?"] is False
    assert r["errors"][0]["missing"] == 1


def test_causal_reverse_concurrent_ok():
    c = causal_reverse.checker()
    h = [
        op("invoke", 0, "write", 1),
        op("invoke", 2, "write", 2), op("ok", 2, "write", 2),
        op("ok", 0, "write", 1),
        # 1 and 2 were concurrent: seeing only 2 is fine
        op("invoke", 1, "read"), op("ok", 1, "read", [2]),
    ]
    assert c.check({}, h, {})["valid?"] is True


# ---------------------------------------------------------------------------
# adya g2
# ---------------------------------------------------------------------------

def test_adya_write_skew():
    c = adya.checker()
    h = [
        op("ok", 0, "insert", [7, 1, "a"]),
        op("ok", 1, "insert", [7, 2, "b"]),
        op("ok", 2, "insert", [8, 3, "a"]),
    ]
    r = c.check({}, h, {})
    assert r["valid?"] is False and r["g2-count"] == 1


def test_adya_generator_pairs():
    h = quick({"concurrency": 4},
              __import__("jepsen_tpu.generator", fromlist=["g"]).limit(
                  40, adya.generator()))
    ivs = invocations(h)
    uids = [iv["value"][1] for iv in ivs]
    assert len(set(uids)) == len(uids)
    by_pair = {}
    for iv in ivs:
        pair, _uid, cell = iv["value"]
        by_pair.setdefault(pair, []).append(cell)
    for cells in by_pair.values():
        assert len(cells) <= 2 and len(set(cells)) == len(cells)


# ---------------------------------------------------------------------------
# register / set / elle wrappers: end-to-end smoke via simulation
# ---------------------------------------------------------------------------

def test_register_workload_end_to_end():
    import jepsen_tpu.generator as g
    w = register.workload({"concurrency": 4}, per_key_limit=8)
    t = {"concurrency": 4}
    h = perfect(t, g.limit(200, w["generator"]))
    # simulate returns uniform ok completions mirroring the invoke — i.e.
    # every read sees its own placeholder; build a trivially valid register
    # history instead: reads return None (unknown) are not valid ops, so
    # just verify the generator emits well-formed tuple values
    for iv in invocations(h):
        k, v = iv["value"]
        assert iv["f"] in ("read", "write", "cas")


def test_set_workload_checker():
    w = set_workload.workload()
    h = [
        op("invoke", 0, "add", 0), op("ok", 0, "add", 0),
        op("invoke", 1, "add", 1), op("ok", 1, "add", 1),
        op("invoke", 0, "read"), op("ok", 0, "read", [0, 1]),
    ]
    assert w["checker"].check({}, h, {})["valid?"] is True
    h_lost = h[:-1] + [op("ok", 0, "read", [1])]
    assert w["checker"].check({}, h_lost, {})["valid?"] is False


def test_append_wr_workloads():
    aw = append.workload()
    h = [op("ok", 0, "txn", [["append", "x", 1], ["r", "x", [1]]])]
    assert aw["checker"].check({}, h, {"accelerator": "cpu"})["valid?"] is True
    ww = wr.workload()
    h2 = [op("ok", 0, "txn", [["w", "x", 1], ["r", "x", 1]])]
    assert ww["checker"].check({}, h2, {"accelerator": "cpu"})["valid?"] is True


def test_append_generator_via_workload():
    import jepsen_tpu.generator as g
    w = append.workload()
    h = quick({"concurrency": 2}, g.limit(30, w["generator"]))
    assert len(invocations(h)) == 30
    for iv in invocations(h):
        for m in iv["value"]:
            assert m[0] in ("r", "append")


# ---------------------------------------------------------------------------
# queue (enqueue/dequeue/drain -> total-queue)
# ---------------------------------------------------------------------------

def test_queue_workload_drain_expansion_and_verdicts():
    from jepsen_tpu.workloads import queue_workload
    w = queue_workload.workload()
    ok = [
        op("invoke", 0, "enqueue", 1), op("ok", 0, "enqueue", 1),
        op("invoke", 1, "enqueue", 2), op("ok", 1, "enqueue", 2),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", 1),
        op("invoke", 1, "drain"), op("ok", 1, "drain", [2]),
    ]
    res = w["checker"].check({}, ok, {})
    assert res["valid?"] is True and res["lost-count"] == 0
    lost = ok[:-2] + [op("invoke", 1, "drain"), op("ok", 1, "drain", [])]
    res = w["checker"].check({}, lost, {})
    assert res["valid?"] is False and res["lost"] == [2]
    # unacked enqueue that surfaces later is recovered, not unexpected
    rec = [
        op("invoke", 0, "enqueue", 9), op("info", 0, "enqueue", 9),
        op("invoke", 1, "drain"), op("ok", 1, "drain", [9]),
    ]
    res = w["checker"].check({}, rec, {})
    assert res["valid?"] is True and res["recovered-count"] == 1


def test_queue_workload_generator_simulates():
    from jepsen_tpu.workloads import queue_workload
    import jepsen_tpu.generator as g
    w = queue_workload.workload()
    h = quick({"concurrency": 2}, g.limit(20, w["generator"]))
    fs = {iv["f"] for iv in invocations(h)}
    assert fs <= {"enqueue", "dequeue"} and "enqueue" in fs


def test_queue_duplicate_delivery_is_not_unexpected():
    # redelivery of an attempted value: duplicated, still valid
    # (checker.clj:663-666 — duplicates alone don't invalidate)
    from jepsen_tpu.workloads import queue_workload
    w = queue_workload.workload()
    h = [
        op("invoke", 0, "enqueue", 1), op("ok", 0, "enqueue", 1),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", 1),
        op("invoke", 0, "dequeue"), op("ok", 0, "dequeue", 1),
    ]
    res = w["checker"].check({}, h, {})
    assert res["valid?"] is True
    assert res["duplicated-count"] == 1 and res["duplicated"] == [1]
    assert res["unexpected-count"] == 0
    # a value from nowhere is unexpected with full multiplicity
    h2 = h + [op("invoke", 1, "dequeue"), op("ok", 1, "dequeue", 99)]
    res2 = w["checker"].check({}, h2, {})
    assert res2["valid?"] is False and res2["unexpected"] == [99]


def test_bank_plotter_writes_png(tmp_path):
    t = {**bank_test(), "name": "bank-plot", "start_time": "t0",
         "store_dir": str(tmp_path), "nodes": ["n1", "n2"]}
    h = [
        op("invoke", 0, "read"), op("ok", 0, "read", {0: 10, 1: 10}),
        op("invoke", 1, "read"), op("ok", 1, "read", {0: 10, 1: 10}),
    ]
    for i, o in enumerate(h):
        o["time"] = i * 10**9
    r = bank.plotter().check(t, h, {})
    assert r["valid?"] is True
    import os
    assert r["plot"].endswith("bank.png") and os.path.getsize(r["plot"]) > 0
    # the workload's composed checker runs SI + plot together
    rc = t["checker"].check(t, h, {})
    assert rc["valid?"] is True and "plot" in rc


def test_long_fork_read_accounting():
    from jepsen_tpu.workloads import long_fork
    chk = long_fork.checker(group_size=2)
    h = [
        # early: nothing written yet
        op("ok", 0, "txn", [["r", 0, None], ["r", 1, None]]),
        # partial: witnesses the intermediate state
        op("ok", 1, "txn", [["r", 0, 1], ["r", 1, None]]),
        # late: everything written
        op("ok", 0, "txn", [["r", 0, 1], ["r", 1, 1]]),
    ]
    r = chk.check({}, h, {})
    assert r["valid?"] is True
    assert r["reads-count"] == 3
    assert r["early-read-count"] == 1
    assert r["late-read-count"] == 1


def test_generic_cycle_checker_custom_analyzer():
    """tests/cycle.clj parity: a checker built from a custom analyzer fn
    classifies cycles in whatever dependency graph the analyzer derives."""
    from jepsen_tpu.elle import Graph, WW, WR
    from jepsen_tpu.workloads import cycle

    def analyzer(history):
        # toy analyzer: "observed" field names the txn each op depends on
        oks = [o for o in history if o["type"] == "ok"]
        g = Graph(len(oks))
        for i, o in enumerate(oks):
            dep = o.get("observed")
            if dep is not None:
                g.add(dep, i, WR)
            if i > 0 and o.get("overwrites") is not None:
                g.add(i, o["overwrites"], WW)
        return g, oks

    acyclic = [
        {"type": "ok", "process": 0, "value": 1},
        {"type": "ok", "process": 1, "value": 2, "observed": 0},
    ]
    out = cycle.checker(analyzer).check({}, acyclic, {})
    assert out["valid?"] is True

    cyclic = [
        {"type": "ok", "process": 0, "value": 1},
        {"type": "ok", "process": 1, "value": 2, "observed": 0,
         "overwrites": 0},
    ]
    out = cycle.checker(analyzer).check({}, cyclic, {})
    assert out["valid?"] is False
    assert out["anomaly-types"], out


def test_register_workload_composes_timeline(tmp_path):
    from jepsen_tpu.workloads import register

    w = register.workload({"concurrency": 2})
    t = {"name": "reg", "start_time": "t0", "store_dir": str(tmp_path),
         "concurrency": 2}
    h = [
        {"type": "invoke", "process": 0, "f": "write", "value": [1, 3],
         "time": 0},
        {"type": "ok", "process": 0, "f": "write", "value": [1, 3],
         "time": 1},
        {"type": "invoke", "process": 1, "f": "read", "value": [1, None],
         "time": 2},
        {"type": "ok", "process": 1, "f": "read", "value": [1, 3],
         "time": 3},
    ]
    out = w["checker"].check(t, h, {})
    assert out["valid?"] is True


# ---------------------------------------------------------------------------
# deep-suite workloads: multi-key-acid, single-key-acid, default-value,
# comments (VERDICT r2 item 5)
# ---------------------------------------------------------------------------

def test_multi_register_model_semantics():
    from jepsen_tpu.models import MultiRegister, is_inconsistent

    m = MultiRegister()
    m = m.step({"f": "txn", "value": [["w", 0, 3], ["w", 2, 1]]})
    assert m.get(0) == 3 and m.get(2) == 1 and m.get(1) is None
    # read None always legal; read of wrong value inconsistent
    assert not is_inconsistent(
        m.step({"f": "txn", "value": [["r", 1, None], ["r", 0, 3]]}))
    assert is_inconsistent(m.step({"f": "txn", "value": [["r", 0, 4]]}))
    assert is_inconsistent(m.step({"f": "txn", "value": [["r", 1, 0]]}))


def test_multi_register_spec_matches_py_twin():
    """Device spec vs python twin vs object model on random txn batches."""
    import random

    import jax
    import jax.numpy as jnp
    import numpy as np

    from jepsen_tpu.checker.linear_cpu import multi_register_step_py
    from jepsen_tpu.checker.linear_encode import encode_multi_register_ops
    from jepsen_tpu.models import multi_register_spec

    K, V = 3, 5
    spec = multi_register_spec(K, V)
    py = multi_register_step_py(K, V)
    rng = random.Random(17)
    step_j = jax.jit(spec.step_ids)
    for _ in range(200):
        state = rng.randrange((V + 1) ** K)
        # random packed action
        a = 0
        for k in range(K):
            a = a * (2 * V + 2) + rng.randrange(2 * V + 2)
        s_py, ok_py = py(state, 0, a, 0)
        s_j, ok_j = step_j(jnp.int32(state), jnp.int32(0), jnp.int32(a),
                           jnp.int32(0))
        assert bool(ok_j) == bool(ok_py)
        if ok_py:
            assert int(s_j) == s_py


def _mr_history(txns):
    h = []
    for i, mops in enumerate(txns):
        h.append({"type": "invoke", "process": i % 3, "f": "txn",
                  "value": [[f, k, None if f == "r" else v]
                            for f, k, v in mops]})
        h.append({"type": "ok", "process": i % 3, "f": "txn", "value": mops})
    return h


def test_multi_key_acid_checker_verdicts():
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import MultiRegister

    chk_lin = linearizable(model=MultiRegister(), accelerator="cpu")
    good = _mr_history([
        [["w", 0, 1], ["w", 1, 2]],
        [["r", 0, 1], ["r", 1, 2]],
        [["w", 0, 4]],
        [["r", 0, 4], ["r", 2, None]],
    ])
    assert chk_lin.check({}, good, {})["valid?"] is True
    # a read that observes a value nobody wrote: not linearizable
    bad = _mr_history([
        [["w", 0, 1]],
        [["r", 0, 2]],
    ])
    out = chk_lin.check({}, bad, {})
    assert out["valid?"] is False


def test_multi_key_acid_device_stream_parity():
    """The int-encoded stream path (auto) agrees with the wgl object
    search on sequential multi-register histories."""
    import random

    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import MultiRegister

    rng = random.Random(5)
    state = {}
    txns = []
    for i in range(40):
        keys = sorted(rng.sample(range(3), rng.randint(1, 3)))
        if rng.random() < 0.5:
            mops = [["w", k, rng.randrange(5)] for k in keys]
            for f, k, v in mops:
                state[k] = v
        else:
            mops = [["r", k, state.get(k)] for k in keys]
        txns.append(mops)
    h = _mr_history(txns)
    a = linearizable(model=MultiRegister(), algorithm="jitlin",
                     accelerator="cpu").check({}, h, {})
    b = linearizable(model=MultiRegister(), algorithm="wgl").check({}, h, {})
    assert a["valid?"] == b["valid?"] is True


def test_single_key_acid_fake_mode_lifecycle():
    from jepsen_tpu.suites.yugabyte import yugabyte_test
    from conftest import run_fake

    t = run_fake(yugabyte_test, workload="single-key-acid", time_limit=0.5)
    assert t["results"]["valid?"] in (True, "unknown"), t["results"]


def test_multi_key_acid_fake_mode_lifecycle():
    from jepsen_tpu.suites.yugabyte import yugabyte_test
    from conftest import run_fake

    t = run_fake(yugabyte_test, workload="multi-key-acid", time_limit=0.5)
    assert t["results"]["valid?"] in (True, "unknown"), t["results"]


def test_default_value_fake_mode_lifecycle():
    from jepsen_tpu.suites.yugabyte import yugabyte_test
    from conftest import run_fake

    t = run_fake(yugabyte_test, workload="default-value", time_limit=0.5)
    # DDL churn legitimately fails ops while the table is dropped, so a
    # short run can leave some op class with zero oks and trip the
    # generic stats checker — the WORKLOAD verdict (no null-column rows)
    # and the exceptions checker are what this lifecycle test pins
    assert t["results"]["workload"]["valid?"] is True, t["results"]
    assert t["results"]["exceptions"]["valid?"] is True, t["results"]
    oks = [op for op in t["history"] if op.get("type") == "ok"]
    assert oks, "the DDL-churn run must complete some ops"


def test_comments_fake_mode_lifecycle():
    from jepsen_tpu.suites.cockroachdb import cockroachdb_test
    from conftest import run_fake

    t = run_fake(cockroachdb_test, workload="comments", time_limit=0.5)
    assert t["results"]["valid?"] in (True, "unknown"), t["results"]


def test_default_value_checker_flags_null_rows():
    from jepsen_tpu.workloads.default_value import DefaultValueChecker

    h = [
        {"type": "ok", "f": "read", "process": 0,
         "value": [{"id": 0, "v": 0}]},
        {"type": "ok", "f": "read", "process": 1,
         "value": [{"id": 1, "v": None}]},
    ]
    out = DefaultValueChecker().check({}, h, {})
    assert out["valid?"] is False and out["bad-read-count"] == 1
    ok = DefaultValueChecker().check({}, h[:1], {})
    assert ok["valid?"] is True


def test_comments_checker_finds_visibility_hole():
    from jepsen_tpu.workloads.comments import CommentsChecker

    # w0 completes before w1 invokes; a read sees w1 but not w0
    h = [
        {"type": "invoke", "f": "write", "process": 0, "value": 0},
        {"type": "ok", "f": "write", "process": 0, "value": 0},
        {"type": "invoke", "f": "write", "process": 1, "value": 1},
        {"type": "ok", "f": "write", "process": 1, "value": 1},
        {"type": "invoke", "f": "read", "process": 2, "value": None},
        {"type": "ok", "f": "read", "process": 2, "value": [1]},
    ]
    out = CommentsChecker().check({}, h, {})
    assert out["valid?"] is False
    assert out["errors"][0]["missing"] == [0]
    # seeing both (or only w0) is fine
    h[-1] = {"type": "ok", "f": "read", "process": 2, "value": [0, 1]}
    assert CommentsChecker().check({}, h, {})["valid?"] is True


def test_table_workload_checker_and_fake_lifecycle():
    """tidb's table-creation visibility probe (tidb/table.clj): inserts
    into acknowledged tables must never fail with doesnt-exist."""
    from jepsen_tpu.suites.tidb import tidb_test
    from jepsen_tpu.workloads.table_workload import TableChecker
    from conftest import run_fake

    bad = [{"type": "fail", "f": "insert", "process": 0,
            "value": [1, 0], "error": ["doesnt-exist", 1]}]
    out = TableChecker().check({}, bad, {})
    assert out["valid?"] is False and out["missing-table-count"] == 1
    assert TableChecker().check({}, [], {})["valid?"] is True

    t = run_fake(tidb_test, workload="table", time_limit=0.5)
    assert t["results"]["valid?"] is True, t["results"]
    creates = [op for op in t["history"]
               if op.get("f") == "create-table" and op.get("type") == "ok"]
    inserts = [op for op in t["history"]
               if op.get("f") == "insert" and op.get("type") == "ok"]
    assert creates and inserts
