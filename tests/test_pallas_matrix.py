"""Pallas transfer-matrix chunk-product kernel (ops/pallas_matrix.py).

CPU tier: the kernel runs in pallas interpret mode, differentially
pinned against (a) an independent numpy oracle of the factored math and
(b) the XLA scan path through the PRODUCTION matrix_check dispatch.
Real-chip verdict parity lives in tests/test_tpu_parity.py (-m tpu).
"""
from __future__ import annotations

import numpy as np
import pytest


def _oracle(S, V, pend, ids, mtT, slots, valid):
    """The shared numpy replay (also the enabled() probe's reference)."""
    from jepsen_tpu.ops.pallas_matrix import _oracle_product

    return _oracle_product(S, V, pend, ids, mtT, slots, valid)


def test_static_tables_express_kron_and_kill():
    """Rexp * tile(X) == R (kron) X^T, and Kexp @ B == the row
    gather+mask the XLA path performs — the two identities the
    factored kernel rests on."""
    from jepsen_tpu.ops.pallas_matrix import _static_tables

    S, V = 3, 4
    M = 1 << S
    MV = M * V
    Rexp, Kexp, U1, U2 = _static_tables(S, V)
    rng = np.random.default_rng(7)
    X = (rng.random((V, V)) < 0.4).astype(np.float32)
    rows = np.arange(MV)
    a, w = rows // V, rows % V
    for s in range(S):
        R = np.zeros((M, M), np.float32)
        src = np.arange(M)[((np.arange(M) >> s) & 1) == 0]
        R[src | (1 << s), src] = 1.0
        kron = R[a][:, a] * X.T[w][:, w]  # [(a,w),(b,v)] = R[a,b] X[v,w]
        got = Rexp[s] * (U1 @ X.T @ U2)
        assert np.array_equal(kron, got), s

    B = (rng.random((MV, MV)) < 0.3).astype(np.float32)
    for s in range(S):
        ok = ((a >> s) & 1) == 0
        kill_idx = np.where(ok, ((a | (1 << s)) * V + w), 0)
        ref = B[kill_idx] * ok[:, None]
        assert np.array_equal((Kexp[s] @ B > 0) * 1.0, (ref > 0) * 1.0), s


def test_kernel_matches_numpy_oracle_interpret():
    from jepsen_tpu.ops.pallas_matrix import _build

    S, V, T, U, G = 3, 8, 5, 16, 4
    rng = np.random.default_rng(0)
    pend = (rng.random((T, G, S)) < 0.5).astype(np.float32)
    ids = rng.integers(0, U, (T, G, S)).astype(np.int32)
    mtT = (rng.random((U, V, V)) < 0.3).astype(np.float32)
    slots = rng.integers(0, S, (T, G)).astype(np.int32)
    valid = (rng.random((T, G)) < 0.8).astype(np.float32)

    ref = _oracle(S, V, pend, ids, mtT, slots, valid)
    fn = _build(S, V, T, U, interpret=True)
    got = np.asarray(fn(pend, ids, mtT, slots, valid)).astype(np.float32)
    assert np.array_equal(ref, got)


def test_pretile_variant_matches_oracle_interpret():
    """The pre-tiled L-build (uop tiles computed once in XLA, gathered
    in the kernel) is bit-identical to the in-kernel tiling dots and
    the numpy oracle — the variant production picks when the [U, MV,
    MV] table fits the VMEM budget."""
    from jepsen_tpu.ops.pallas_matrix import _build, _pretile_ok

    S, V, T, U, G = 3, 8, 5, 16, 4
    assert _pretile_ok(S, V, U)  # this shape IS the pretile regime
    rng = np.random.default_rng(3)
    pend = (rng.random((T, G, S)) < 0.5).astype(np.float32)
    ids = rng.integers(0, U, (T, G, S)).astype(np.int32)
    mtT = (rng.random((U, V, V)) < 0.3).astype(np.float32)
    slots = rng.integers(0, S, (T, G)).astype(np.int32)
    valid = (rng.random((T, G)) < 0.8).astype(np.float32)

    ref = _oracle(S, V, pend, ids, mtT, slots, valid)
    for pretile in (False, True):
        fn = _build(S, V, T, U, interpret=True, pretile=pretile)
        got = np.asarray(fn(pend, ids, mtT, slots, valid)
                         ).astype(np.float32)
        assert np.array_equal(ref, got), f"pretile={pretile}"


@pytest.mark.slow
def test_production_dispatch_verdict_parity(monkeypatch):
    """matrix_check through the pallas path (interpret mode, forced)
    agrees with the XLA scan path on valid AND corrupted histories —
    the same cross-check the chip parity tier runs for real."""
    from __graft_entry__ import _register_history  # conftest adds the root
    import jepsen_tpu.ops.pallas_matrix as pm
    from jepsen_tpu.checker.linear_encode import encode_register_ops
    from jepsen_tpu.ops.jitlin import matrix_check

    def verdicts(h):
        monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
        scan = matrix_check(encode_register_ops(h), force=True)
        monkeypatch.setattr(pm, "FORCE_INTERPRET", True)
        try:
            pallas = matrix_check(encode_register_ops(h), force=True)
        finally:
            monkeypatch.setattr(pm, "FORCE_INTERPRET", False)
        return scan, pallas

    h = _register_history(120, n_procs=4, seed=5)
    scan, pallas = verdicts(h)
    assert scan is not None and pallas is not None
    assert pallas[0] == scan[0] is True

    import random
    h = _register_history(120, n_procs=4, seed=6)
    reads = [op for op in h
             if op.get("f") == "read" and op.get("type") == "ok"]
    for op in random.Random(0).sample(reads, min(2, len(reads))):
        op["value"] = 999
    scan, pallas = verdicts(h)
    assert pallas[0] == scan[0] is False


def test_gates(monkeypatch):
    import jepsen_tpu.ops.pallas_matrix as pm

    # VMEM caps: decline huge operator dimensions
    assert pm.chunk_product(9, 8, 4, 16) is None        # S over cap
    assert pm.chunk_product(8, 16, 4, 16) is None       # MV = 4096 over cap
    # env kill-switch (monkeypatch restores any externally-set value)
    monkeypatch.setenv("JEPSEN_TPU_NO_PALLAS", "1")
    assert not pm.available()
    assert not pm.enabled(3, 8)
    assert pm.chunk_product(3, 8, 4, 16) is None
    monkeypatch.delenv("JEPSEN_TPU_NO_PALLAS")
    assert pm.available()
